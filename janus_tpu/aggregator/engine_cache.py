"""Jitted device-step cache with HBM-aware batch-size bucketing.

One compiled executable serves many request sizes: batches are padded
up to the next power-of-two bucket (padding lanes carry mask=False and
are sliced off), so each (task VDAF, step kind) compiles O(log max
batch) times total. This is the TPU answer to the reference's
per-report loop — XLA sees static shapes, reports ride the batch axis.

Bucketing is no longer blind (ISSUE r6): at construction each
EngineCache asks the HBM feasibility model (vdaf.feasibility) for the
largest bucket the device budget supports given the circuit geometry
and the streamed-query tile, and batches beyond that cap are chunked
into serial cap-sized dispatches instead of padded into one doomed
one. When the model is still optimistic and the device raises
RESOURCE_EXHAUSTED anyway, the engine halves its cap and retries; at
the bucket floor it falls back to the scalar HostEngineCache —
permanently for a definite RESOURCE_EXHAUSTED, with a timed device
re-probe when only the ambiguous tunnel-500 marker was seen — so a
serving aggregation job degrades to host speed instead of dying
(previously only bench.py survived an OOM).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from collections import OrderedDict, deque

import jax
import jax.numpy as jnp
import numpy as np

from .. import failpoints
from ..core.deadline import current_deadline
from . import aot_cache, shape_manifest
from ..vdaf.engine import STREAM_MIN_INPUT_LEN, stream_plan
from ..vdaf.feasibility import device_memory_budget, feasible_bucket
from ..vdaf.reference import SparseSumVec
from ..vdaf.registry import VdafInstance, prio3_batched
from . import device_watchdog
from .device_watchdog import DeviceHangError  # noqa: F401 - re-export: the
# job drivers catch it at the step boundary (step_back, not job failure)

log = logging.getLogger(__name__)

MIN_BUCKET = 32


def bucket_size(n: int, cap: int | None = None) -> int:
    """Power-of-two jit bucket for n rows, floored at MIN_BUCKET.

    `cap` (the engine's HBM feasibility bound) clamps the result; a
    capped bucket may be smaller than n, in which case the caller is
    responsible for chunking the batch into cap-sized dispatches
    (EngineCache does)."""
    b = MIN_BUCKET
    while b < n:
        b *= 2
    if cap is not None and cap < b:
        b = cap
    return b


# Substrings identifying a device memory exhaustion across the ways it
# surfaces (XlaRuntimeError RESOURCE_EXHAUSTED, allocator messages).
_OOM_DEFINITE_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "OOM",
    "Allocation failure",
)
# Errors that MAY be an HBM overflow but can equally be a transient
# infra failure: the axon tunnel answers remote_compile with an opaque
# 500 both when the program doesn't fit AND when the tunnel server
# itself hiccups. These still get the halve-and-retry ladder, but a
# host fallback reached through them is timed (re-probed), never
# permanent — see EngineCache._host.
_OOM_AMBIGUOUS_MARKERS = ("remote_compile: HTTP 500",)


def is_oom_error(e: BaseException) -> bool:
    s = str(e)
    return any(m in s for m in _OOM_DEFINITE_MARKERS + _OOM_AMBIGUOUS_MARKERS)


def _is_definite_oom(e: BaseException) -> bool:
    s = str(e)
    return any(m in s for m in _OOM_DEFINITE_MARKERS)


def _annotate_dispatch_bucket(e: BaseException, b: int, fixed: bool = False) -> None:
    """Record the bucket of the DISPATCH that raised. OOM recovery must
    halve from the failed dispatch size — a coalesced round dispatches
    many submitters' rows at once, and halving from one submitter's own
    (much smaller) n would collapse the cap far below what actually
    overflowed. `fixed` marks dispatches whose bucket cannot follow a
    halved cap (aggregates over an already-resident device buffer), so
    the handler knows retrying cannot make progress. Best-effort:
    extension exception types without a __dict__ simply keep the
    caller-n fallback."""
    try:
        if not hasattr(e, "_janus_dispatch_bucket"):
            e._janus_dispatch_bucket = b
            e._janus_fixed_bucket = fixed
    except Exception:
        pass


def _cut_rows(a, s: int, e: int):
    """Row-slice an arg that may be None, bytes, a field limb tuple, or
    a plain array (the per-call arg vocabulary of pad_args)."""
    if a is None or isinstance(a, (bytes, int)):
        return a
    if isinstance(a, tuple):
        return tuple(x[s:e] for x in a)
    return a[s:e]


def _pad(arr, b: int):
    if arr is None:
        return None
    pad = b - arr.shape[0]
    if pad == 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(np.asarray(arr), widths)


def _tree_nbytes(tree) -> int:
    """Host bytes of an arg pytree (arrays / field-limb tuples / None /
    scalars) — the h2d/d2h accounting unit of janus_engine_hd_bytes_total."""
    if tree is None or isinstance(tree, (bytes, int, float, bool)):
        return 0
    if isinstance(tree, (tuple, list)):
        return sum(_tree_nbytes(x) for x in tree)
    nb = getattr(tree, "nbytes", None)
    return int(nb) if nb is not None else 0


def count_h2d(tree_or_bytes) -> None:
    """Account host->device bytes (staged uploads, masks, bucket ids)."""
    from .. import metrics

    n = tree_or_bytes if isinstance(tree_or_bytes, int) else _tree_nbytes(tree_or_bytes)
    if n:
        metrics.engine_hd_bytes_total.add(n, direction="h2d")


def count_d2h(tree_or_bytes) -> None:
    """Account device->host bytes (fetches of masks, seeds, aggregates)."""
    from .. import metrics

    n = tree_or_bytes if isinstance(tree_or_bytes, int) else _tree_nbytes(tree_or_bytes)
    if n:
        metrics.engine_hd_bytes_total.add(n, direction="d2h")


def put_args(args, block: bool = False, shardings=None):
    """Explicitly dispatch every staged host array to the device, all
    puts in flight at once (async), before invoking the jit — one slow
    serialized arg upload must not gate the whole call.

    block=True waits for the transfers to land before returning:
    measured on the tunnel backend, dispatching an execute against
    still-pending input buffers degrades the transfer ~1.5-2x versus
    letting the puts finish first.

    shardings: optional pytree (matching args) of NamedShardings so
    multi-device placement happens in the transfer itself instead of a
    resharding copy at dispatch."""
    count_h2d(args)
    if shardings is not None:
        out = jax.device_put(args, shardings)
    else:
        out = jax.device_put(args)  # maps over the arg pytree, puts async
    if block:
        jax.block_until_ready(out)
    return out


def pad_args(b: int, *args):
    out = []
    for a in args:
        if a is None or isinstance(a, (bytes, int)):
            out.append(a)
        elif isinstance(a, tuple):  # field value limbs
            out.append(tuple(_pad(x, b) for x in a))
        else:
            out.append(_pad(a, b))
    return tuple(out)


class DeviceRows:
    """Out-share field value living ON DEVICE, padded to its bucket.

    The serving path used to fetch out shares to numpy after init and
    re-upload them for the masked aggregate — ~2x the out-share bytes
    across the host<->device link per job for nothing. Callers that
    truly need host rows (multi-round park paths) go through
    `to_numpy()`; `EngineCache.aggregate` consumes the device value
    directly.

    `offset` supports coalesced dispatches: several jobs' rows share
    one device buffer, each job holding a [offset, offset+n) view."""

    __slots__ = ("value", "n", "offset")

    def __init__(self, value, n: int, offset: int = 0):
        self.value = value  # tuple of [bucket, len] device limb arrays
        self.n = n  # true batch size (rows beyond n are padding)
        self.offset = offset

    def to_numpy(self):
        rows = tuple(
            np.asarray(x)[self.offset : self.offset + self.n] for x in self.value
        )
        count_d2h(rows)
        return rows


class DeviceRowsChunks:
    """Out shares of a pipelined (chunked) leader init: an ordered list
    of DeviceRows covering consecutive row ranges. Quacks like
    DeviceRows for the two consumers (to_numpy; EngineCache.aggregate
    special-cases it)."""

    __slots__ = ("chunks",)

    def __init__(self, chunks: list[DeviceRows]):
        self.chunks = chunks

    @property
    def n(self) -> int:
        return sum(c.n for c in self.chunks)

    def to_numpy(self):
        parts = [c.to_numpy() for c in self.chunks]
        return tuple(np.concatenate([p[i] for p in parts]) for i in range(len(parts[0])))


class PrestagedInit:
    """Async-uploaded leader-init columns (double-buffered staging,
    ISSUE 12): pad_args + device_put issued while the device lane runs
    the PREVIOUS job's dispatch, consumed by leader_init when the
    direct path applies at the same bucket. Holds only the device
    pytree — discard() drops the references so a fallback (coalesced
    multi-job round, bucket cap moved under OOM recovery, host
    fallback) frees the transfer's buffers immediately."""

    __slots__ = ("b", "_staged", "meshed")

    def __init__(self, b: int, staged, meshed: bool):
        self.b = b
        self._staged = staged
        self.meshed = meshed

    def usable(self, b: int, meshed: bool) -> bool:
        return self._staged is not None and self.b == b and self.meshed == meshed

    def take(self):
        staged, self._staged = self._staged, None
        return staged

    def discard(self) -> None:
        self._staged = None


class ResidentMergeError(RuntimeError):
    """resident_merge died partway through its entry loop. `merged`
    holds the keys whose delta DID land in a resident slot before the
    failure — those contributions are safe on device and flush with the
    slot; the caller must directly flush only the REMAINING entries'
    delta rows (re-flushing a merged one double-counts it)."""

    def __init__(self, merged: frozenset, cause: BaseException):
        super().__init__(
            f"resident merge failed after {len(merged)} bucket(s): {cause!r}"
        )
        self.merged = merged


class ResidentSlot:
    """One per-(task, batch bucket) aggregate buffer living in device
    memory across job steps: `value` is a [output_len] field limb tuple
    of device arrays. Host-side metadata rides along so a flush can
    write through the existing batch-aggregation path (the interval is
    the union of every merged contribution's; counts/checksums are
    already durable — the per-job write tx records them at commit time,
    only the share bytes live here)."""

    __slots__ = ("key", "value", "interval", "rows", "nbytes", "last_used")

    def __init__(self, key: tuple, value, interval, rows: int, nbytes: int):
        self.key = key  # (task_id bytes, agg_param bytes, batch_identifier bytes)
        self.value = value
        self.interval = interval
        self.rows = rows
        self.nbytes = nbytes
        self.last_used = time.monotonic()


class PendingDeltas:
    """Per-bucket masked sums of ONE job step, still on device
    ([k, output_len] field limb tuple): computed by aggregate_pending
    on the device lane, merged into resident slots only AFTER the job's
    write transaction committed (resident_merge). A failed commit just
    drops the object — no rollback, no double-merge on the re-step."""

    __slots__ = ("value", "k", "row_nbytes")

    def __init__(self, value, k: int, row_nbytes: int):
        self.value = value
        self.k = k
        self.row_nbytes = row_nbytes

    def row(self, j: int):
        """Row j as a device field value (lazy jnp slice — no fetch)."""
        return tuple(x[j] for x in self.value)


class SparsePendingDeltas:
    """Sparse-job pending state (ISSUE 17). Unlike PendingDeltas the
    per-bucket reduction CANNOT run at dispatch time: two reports of
    the same batch bucket carry different block indices, so a
    compact-width pre-sum would add values living at unrelated logical
    coordinates. Instead the job's raw out shares (device rows) ride to
    merge time together with each report's flat scatter indices, and
    resident_merge scatter-adds report blocks straight into the dense
    logical slot — HBM holds ONE [logical_len] accumulator per slot
    while per-report device work stays O(nonzero lanes). Same commit
    discipline as PendingDeltas: dropped uncommitted, merged after.

    flat_idx: [n, compact_len] host int32 scatter targets, sentinel =
    logical_len for padding lanes (the scatter drops them);
    bucket_idx: [n] host int32 bucket per report, -1 = rejected.
    row_nbytes is the DENSE logical row size (what a slot occupies)."""

    __slots__ = ("out_shares", "flat_idx", "bucket_idx", "k", "row_nbytes", "logical_len")

    def __init__(self, out_shares, flat_idx, bucket_idx, k: int, row_nbytes: int, logical_len: int):
        self.out_shares = out_shares
        self.flat_idx = flat_idx
        self.bucket_idx = bucket_idx
        self.k = k
        self.row_nbytes = row_nbytes
        self.logical_len = logical_len


# process-wide resident accounting (the HBM the resident layer holds
# across every engine; the eviction cap reads the byte total). The
# per-kind buffer counts live here too: several engines share a vdaf
# kind (one per task verify key), so a per-engine gauge set would have
# them overwrite each other's value instead of summing.
_resident_bytes_lock = threading.Lock()
_resident_bytes_total = 0
_resident_buffer_counts: dict[str, int] = {}


def _resident_bytes_add(delta: int, kind: str, nbuf: int) -> int:
    """Account one slot insert/remove: `delta` device bytes and `nbuf`
    (+1/-1) buffers of vdaf `kind`. Publishes both gauges."""
    global _resident_bytes_total
    from .. import metrics

    with _resident_bytes_lock:
        _resident_bytes_total += delta
        total = _resident_bytes_total
        n = _resident_buffer_counts.get(kind, 0) + nbuf
        _resident_buffer_counts[kind] = n
    metrics.engine_resident_bytes.set(float(total))
    metrics.engine_resident_buffers.set(float(n), vdaf=kind)
    return total


def resident_bytes_total() -> int:
    with _resident_bytes_lock:
        return _resident_bytes_total


class _Coalescer:
    """Round-based dispatch coalescing across concurrent callers.

    The driver steps jobs concurrently but each job used to dispatch
    its own device call: a 10k-report Count job got 86,813 r/s from a
    chip that does 287,619 at batch 32768 (BASELINE.md matrix,
    VERDICT r4 weak #7) — the dispatch floor cannot amortize. Here
    concurrent calls to the same engine step merge into one padded
    device call: an arrival with no dispatch in flight goes out
    immediately (zero added latency when unloaded); arrivals during an
    in-flight dispatch queue and ride the next round together. The
    reference's analog is rayon parallelism inside one job
    (aggregation_job_driver.rs:329) — it has no cross-job batching at
    all.

    Lease/abandon semantics are untouched: coalescing sits strictly
    below the job layer (one device call serving several jobs' rows;
    each job still writes and releases its own lease).
    """

    __slots__ = ("_run", "_max_rows", "_lock", "_cv", "_queue", "_active", "rounds")

    def __init__(self, run, max_rows: int):
        import collections

        self._run = run  # ([args...], [n...]) -> [per-call results]
        self._max_rows = max_rows
        self._lock = threading.Lock()
        # signaled when the dispatcher role frees up with work queued
        self._cv = threading.Condition(self._lock)
        self._queue: list[list] = []  # entries: [args, n, Event, result, error]
        self._active = False
        # calls per dispatched round, recent window only (stats/tests;
        # unbounded growth would be a slow RSS leak on long-lived
        # aggregators)
        self.rounds = collections.deque(maxlen=1024)

    def submit(self, args, n: int):
        ent = [args, n, threading.Event(), None, None]
        with self._lock:
            self._queue.append(ent)
            dispatcher = not self._active
            if dispatcher:
                self._active = True
        if dispatcher:
            self._dispatch_until_done(ent)
        else:
            while not ent[2].is_set():
                # the previous dispatcher may exit with entries still
                # queued (its own round finished first): a waiter is
                # notified via the condition and adopts the role (the
                # short timeout is only a lost-wakeup backstop)
                with self._lock:
                    adopt = not self._active and not ent[2].is_set() and bool(self._queue)
                    if adopt:
                        self._active = True
                    elif not ent[2].is_set():
                        self._cv.wait(0.05)
                        continue
                if adopt:
                    self._dispatch_until_done(ent)
                    break
        if ent[4] is not None:
            raise ent[4]
        return ent[3]

    def _dispatch_until_done(self, own):
        """Dispatch rounds until our own entry completes AND the queue
        is drained or another thread adopts the role."""
        try:
            while True:
                with self._lock:
                    batch: list[list] = []
                    rows = 0
                    while self._queue and (
                        not batch or rows + self._queue[0][1] <= self._max_rows
                    ):
                        e = self._queue.pop(0)
                        batch.append(e)
                        rows += e[1]
                    if not batch:
                        return
                self.rounds.append(len(batch))
                try:
                    results = self._run([e[0] for e in batch], [e[1] for e in batch])
                    for e, r in zip(batch, results):
                        e[3] = r
                except BaseException as ex:  # noqa: BLE001 - even
                    # KeyboardInterrupt/SystemExit must release the
                    # co-batched waiters (their entries were already
                    # popped; nobody else will ever set their events)
                    for e in batch:
                        e[4] = ex
                    if not isinstance(ex, Exception):
                        for e in batch:
                            e[2].set()
                        with self._lock:
                            self._cv.notify_all()
                        raise
                for e in batch:
                    e[2].set()
                # wake cv-parked waiters so completed entries return
                # immediately instead of on the 50 ms backstop
                with self._lock:
                    self._cv.notify_all()
                if own[2].is_set():
                    # our caller has work to do with its result; hand
                    # the role to a waiter (notified in finally)
                    return
        finally:
            with self._lock:
                self._active = False
                if self._queue:
                    self._cv.notify()


def _concat_args(args_list):
    """Concatenate per-call arg tuples along the batch axis. None args
    must be None in every call (same engine => same schedule)."""
    out = []
    for parts in zip(*args_list):
        if parts[0] is None:
            assert all(p is None for p in parts)
            out.append(None)
        elif isinstance(parts[0], tuple):  # field limbs
            out.append(
                tuple(
                    np.concatenate([np.asarray(p[k]) for p in parts])
                    for k in range(len(parts[0]))
                )
            )
        else:
            assert all(p is not None for p in parts)
            out.append(np.concatenate([np.asarray(p) for p in parts]))
    return tuple(out)


def _split_rows(value, offsets):
    """Slice a host array / field tuple / None back into per-call rows."""
    if value is None:
        return [None] * (len(offsets) - 1)
    if isinstance(value, tuple):
        return [
            tuple(x[s:e] for x in value) for s, e in zip(offsets, offsets[1:])
        ]
    return [value[s:e] for s, e in zip(offsets, offsets[1:])]


# ---------------------------------------------------------------------------
# Cross-TASK dispatch coalescing (ISSUE 12). The PR 7 coalescer merged
# concurrent small jobs of ONE engine (one task's vdaf+verify_key) into
# shared dispatches; here engines of the SAME VdafInstance — identical
# circuit geometry, identical compiled steps, differing only in the
# 16-byte verify key — share one round-based coalescer per (inst,
# side), and a mixed round dispatches ONE device call whose verify key
# is a per-LANE input (the XOF already consumes per-lane seed segments,
# so the kernel change is just the key's segment becoming an array).
# The cross-job mask-leak invariant is unchanged by construction: each
# job still holds an [offset, offset+n) view of the shared buffer and
# aggregates under its own mask (re-pinned cross-task in
# tests/test_engine_coalesce.py).
# ---------------------------------------------------------------------------

# default ON: single-engine rounds take byte-identical code paths (the
# scalar-key jit), so behavior only changes when two tasks' small jobs
# genuinely overlap — exactly the fleet shape ROADMAP item 2 adds.
XTASK_COALESCE = os.environ.get("JANUS_XTASK_COALESCE", "1") != "0"

class _MeshDispatch:
    """One queued mesh enqueue: the wrapped jit, its args, and the
    rendezvous the submitting thread blocks on."""

    __slots__ = (
        "fn", "args", "kwargs", "vdaf", "program", "t_submit",
        "done", "result", "error",
    )

    def __init__(self, fn, args, kwargs, vdaf, program):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.vdaf = vdaf
        self.program = program
        self.t_submit = time.monotonic()
        self.done = threading.Event()
        self.result = None
        self.error = None


class MeshDispatchQueue:
    """Single-controller dispatch lane for EVERY mesh program in the
    process (replaces the PR 14 process-global _MESH_DISPATCH_LOCK).

    Single-controller multi-device programs deadlock when two threads
    interleave their per-device enqueues: each device ends up parked on
    the other program's collective. That happens between ANY two mesh
    programs sharing the process's devices — two different tasks'
    engines dispatching concurrently (the cross-task fleet/coalesce
    shape) deadlocked exactly like two threads on one engine did
    (observed as a rare tier-1 stall in
    test_cross_task_coalesced_round_matches_solo_...). The lock fixed
    correctness but became the throughput ceiling: it woke waiters in
    arbitrary order (starvation under contention) and hid the
    cross-engine serialization cost inside each caller's dispatch wall
    time, invisible to the cost ledger.

    The queue keeps the invariant — exactly ONE thread (the
    "mesh-dispatch" lane, profiled under the device_lane role) performs
    every mesh enqueue — and adds what a lock cannot: FIFO fairness,
    janus_mesh_dispatch_* queue-depth/wait-time metrics, and a
    cost-ledger row per mesh program. Only the ENQUEUE is serialized;
    execution stays async on the devices, so concurrent jobs keep
    coalescing and pipelining safely. Exceptions (OOM recovery depends
    on them) re-raise in the submitting thread, original object intact
    — _handle_engine_error's type checks and the _janus_oom_handled
    dedup marker keep working."""

    def __init__(self):
        self._q: "queue.SimpleQueue[_MeshDispatch]" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._pid: int | None = None
        self._depth = 0
        self._seen: set[tuple[str, str]] = set()
        self._stats = {
            "submitted": 0,
            "completed": 0,
            "errors": 0,
            "max_depth": 0,
            "max_wait_s": 0.0,
            "busy_s": 0.0,
        }

    def submit(self, fn, args, kwargs, vdaf: str = "", program: str = ""):
        """Run fn(*args, **kwargs) on the dispatch lane; block until the
        enqueue returns; re-raise its exception in the caller."""
        from .. import metrics

        self._ensure_thread()
        item = _MeshDispatch(fn, args, kwargs, vdaf, program)
        with self._lock:
            self._depth += 1
            depth = self._depth
            self._stats["submitted"] += 1
            if depth > self._stats["max_depth"]:
                self._stats["max_depth"] = depth
        metrics.mesh_dispatch_queue_depth.set(float(depth))
        self._q.put(item)
        item.done.wait()
        if item.error is not None:
            raise item.error
        return item.result

    def _ensure_thread(self) -> None:
        pid = os.getpid()
        t = self._thread
        if t is not None and t.is_alive() and self._pid == pid:
            return
        with self._lock:
            t = self._thread
            if t is not None and t.is_alive() and self._pid == pid:
                return
            if self._pid is not None and self._pid != pid:
                # forked child: the parent's lane thread didn't survive
                # the fork and its queue may hold the parent's items —
                # start clean (submitters in the child re-enqueue)
                self._q = queue.SimpleQueue()
                self._depth = 0
            self._pid = pid
            q = self._q
            t = threading.Thread(
                target=self._run, args=(q,), name="mesh-dispatch", daemon=True
            )
            self._thread = t
            t.start()

    def _run(self, q) -> None:
        from .. import metrics
        from ..profiler import DEVICE_COST

        while True:
            item = q.get()
            wait = time.monotonic() - item.t_submit
            with self._lock:
                self._depth -= 1
                depth = self._depth
                if wait > self._stats["max_wait_s"]:
                    self._stats["max_wait_s"] = wait
                first = (item.vdaf, item.program) not in self._seen
                if first:
                    self._seen.add((item.vdaf, item.program))
            metrics.mesh_dispatch_queue_depth.set(float(depth))
            metrics.mesh_dispatch_wait_seconds.observe(wait)
            t0 = time.monotonic()
            try:
                item.result = item.fn(*item.args, **item.kwargs)
            except BaseException as e:  # noqa: BLE001 - belongs to the caller
                item.error = e
            finally:
                dt = time.monotonic() - t0
                with self._lock:
                    self._stats["busy_s"] += dt
                    self._stats["completed"] += 1
                    if item.error is not None:
                        self._stats["errors"] += 1
                metrics.mesh_dispatch_busy_seconds.add(dt)
                metrics.mesh_dispatch_total.add(program=item.program or "unknown")
                if item.vdaf:
                    # per-mesh-program ledger row: the lane's enqueue
                    # wall (first call of a program = trace+compile or
                    # AOT deserialize; distinct from the engine's own
                    # per-specialization rows, which include queue wait)
                    DEVICE_COST.record(
                        item.vdaf,
                        f"mesh:{item.program}",
                        0,
                        "compile" if first else "execute",
                        dt,
                        dispatches=1,
                    )
                item.done.set()

    def status(self) -> dict:
        with self._lock:
            t = self._thread
            return {
                "depth": self._depth,
                "lane_alive": bool(t is not None and t.is_alive()),
                "programs": len(self._seen),
                **dict(self._stats),
            }

    def reset_for_tests(self) -> None:
        """Zero counters between test modules; the lane thread (if any)
        keeps running — it is stateless outside these counters."""
        with self._lock:
            self._seen.clear()
            self._stats.update(
                submitted=0, completed=0, errors=0,
                max_depth=0, max_wait_s=0.0, busy_s=0.0,
            )


# the process-wide lane: one queue for every engine's mesh programs,
# mirroring the lock it replaced (the interleaved-enqueue deadlock is a
# process-level hazard, not a per-engine one)
_MESH_QUEUE = MeshDispatchQueue()

_xtask_lock = threading.Lock()
_xtask_coalescers: dict[tuple, "_Coalescer"] = {}


def _shared_coalescer(inst, side: str, max_rows: int) -> "_Coalescer":
    key = (inst, side)
    with _xtask_lock:
        co = _xtask_coalescers.get(key)
        if co is None:
            run = _run_leader_round if side == "leader" else _run_helper_round
            co = _Coalescer(run, max_rows)
            _xtask_coalescers[key] = co
        return co


def _clear_shared_coalescers() -> None:
    with _xtask_lock:
        _xtask_coalescers.clear()


def _verify_key_lanes(engines, ns) -> np.ndarray:
    """[sum(ns), 2] u64 lane array carrying each entry's task verify
    key across its rows (the per-lane key input of a cross-task round)."""
    rows = [
        np.broadcast_to(
            np.frombuffer(e.verify_key, dtype="<u8").astype(np.uint64), (n, 2)
        )
        for e, n in zip(engines, ns)
    ]
    return np.ascontiguousarray(np.concatenate(rows, axis=0))


def _round_prestage_fallback(prestaged_list) -> None:
    from .. import metrics

    for p in prestaged_list:
        if p is not None:
            p.discard()  # a merged round re-stages from host columns
            metrics.engine_prestage_total.add(outcome="fallback")


def _run_leader_round(args_list, ns):
    """Coalescer round callback (leader init). Entries carry their
    submitting engine: a single-engine round is exactly the PR 7 path
    (scalar verify key, same jit); a mixed round merges across tasks
    with per-lane verify keys, executed by the first entry's engine
    (same VdafInstance => same Prio3Batched object => same geometry)."""
    engines = [a[0] for a in args_list]
    if len(args_list) == 1:
        eng, prestaged, *rest = args_list[0]
        return [eng._leader_init_inner(*rest, prestaged=prestaged)]
    from .. import metrics

    exec_eng = engines[0]
    cross = any(e is not exec_eng for e in engines)
    offsets = list(np.cumsum([0] + ns))
    metrics.engine_coalesced_rounds_total.add()
    metrics.engine_coalesced_rows_total.add(int(sum(ns)))
    _round_prestage_fallback([a[1] for a in args_list])
    merged = _concat_args([a[2:] for a in args_list])
    vk = _verify_key_lanes(engines, ns) if cross else None
    # one padded dispatch for the whole round (no intra-call
    # pipelining: round-to-round overlap already covers H2D)
    out0, seed0, ver0, part0 = exec_eng._leader_init_inner(
        *merged, coalesced=len(ns), allow_pipeline=False, vk_lanes=vk
    )
    if isinstance(out0, DeviceRowsChunks):
        # cap halved mid-round (concurrent OOM recovery): split on
        # host rows instead of device-buffer views
        rows = out0.to_numpy()
        outs = [
            tuple(x[s:e] for x in rows) for s, e in zip(offsets, offsets[1:])
        ]
    else:
        outs = [
            DeviceRows(out0.value, e - s, offset=s)
            for s, e in zip(offsets, offsets[1:])
        ]
    seeds = _split_rows(seed0, offsets)
    vers = _split_rows(ver0, offsets)
    parts = _split_rows(part0, offsets)
    return list(zip(outs, seeds, vers, parts))


def _run_helper_round(args_list, ns):
    """Coalescer round callback (helper init); see _run_leader_round."""
    engines = [a[0] for a in args_list]
    offsets = list(np.cumsum([0] + ns))
    if len(args_list) == 1:
        eng, *rest = args_list[0]
        out1, mask, prep_msg = eng._helper_init_inner(*rest)
        return [(out1, mask, prep_msg)]
    from .. import metrics

    exec_eng = engines[0]
    cross = any(e is not exec_eng for e in engines)
    metrics.engine_coalesced_rounds_total.add()
    metrics.engine_coalesced_rows_total.add(int(sum(ns)))
    merged = _concat_args([a[1:] for a in args_list])
    vk = _verify_key_lanes(engines, ns) if cross else None
    out1, mask, prep_msg = exec_eng._helper_init_inner(
        *merged, coalesced=len(ns), vk_lanes=vk
    )
    if isinstance(out1, DeviceRowsChunks):
        # the bucket cap halved between round admission and dispatch
        # (concurrent OOM recovery) and the merged round chunked:
        # split on host rows — plain limb tuples are valid out-share
        # currency (HostEngineCache returns them)
        rows = out1.to_numpy()
        return [
            (tuple(x[s:e] for x in rows), mask[s:e], prep_msg[s:e])
            for s, e in zip(offsets, offsets[1:])
        ]
    return [
        (DeviceRows(out1.value, e - s, offset=s), mask[s:e], prep_msg[s:e])
        for s, e in zip(offsets, offsets[1:])
    ]


def _engine_dispatch_failpoint() -> None:
    """`engine.dispatch` failpoint INSIDE every watchdog-supervised
    device region: the oom action raises a RESOURCE_EXHAUSTED-shaped
    error so the injected fault rides the REAL recovery path
    (_handle_engine_error's halved-bucket retry / host fallback),
    exactly like a device OOM; the hang action parks the supervised
    worker exactly like a wedged XLA dispatch, so the watchdog's
    abandon/quarantine path is what recovers it."""
    failpoints.hit(
        "engine.dispatch",
        error_factory=lambda: RuntimeError(
            "RESOURCE_EXHAUSTED: injected failpoint engine.dispatch"
        ),
    )


class EngineCache:
    """Per (vdaf, verify_key) jitted steps, keyed by batch bucket.

    Multi-device serving: when the process sees more than one JAX
    device, every jitted step is bound to a dp (report-batch) mesh over
    the largest power-of-two device count, so helper init and the
    leader driver — the production traffic paths, not just bench.py —
    shard across chips (SURVEY §2.10 P2/P4; the reference scales the
    same work with DB replicas + rayon). Single-device behavior is
    unchanged."""

    # input_len at which the vector axis gets a slice of the mesh (sp):
    # the streamed-query activation point — the lengths where per-report
    # tensors, not report count, dominate
    SP_MIN_INPUT_LEN = STREAM_MIN_INPUT_LEN

    # mesh geometry overrides (the `engine: mesh: {dp, sp}` config
    # stanza; None = auto-select from device count + circuit shape).
    # Class attributes so janus_main applies the YAML once; the
    # JANUS_MESH_DP / JANUS_MESH_SP env vars win over both (operator
    # override, read per-engine so subprocess benches can force shapes).
    MESH_DP: int | None = None
    MESH_SP: int | None = None

    @classmethod
    def _configured_geometry(cls) -> tuple[int | None, int | None]:
        def pick(env: str, fallback: int | None) -> int | None:
            v = os.environ.get(env)
            if v is None or not v.strip():
                return fallback
            try:
                return int(v)
            except ValueError:
                log.warning("ignoring non-integer %s=%r", env, v)
                return fallback

        return pick("JANUS_MESH_DP", cls.MESH_DP), pick("JANUS_MESH_SP", cls.MESH_SP)

    def __init__(self, inst: VdafInstance, verify_key: bytes):
        self.inst = inst
        self.verify_key = verify_key
        self.p3 = prio3_batched(inst)
        self._jits: dict[str, object] = {}
        ndev = len(jax.devices())
        self._ndev = ndev
        # geometry: auto-selected from device count and circuit shape
        # (dp = report batch axis, sp = measurement/out-share column
        # axis for long-vector tasks — SURVEY §2.10 P4 / §5
        # long-context analog), or pinned by the `engine: mesh:` config
        # stanza / JANUS_MESH_DP/SP overrides. One device (or an
        # override pinning 1x1) = the single-device path, no mesh.
        from ..parallel.api import choose_mesh_geometry, make_mesh

        cfg_dp, cfg_sp = self._configured_geometry()
        circ = self.p3.circ
        dp, sp = choose_mesh_geometry(
            ndev,
            getattr(circ, "input_len", 0),
            getattr(circ, "output_len", 0),
            self.SP_MIN_INPUT_LEN,
            MIN_BUCKET,  # every bucket must divide by dp
            dp=cfg_dp,
            sp=cfg_sp,
        )
        # block-sparse tasks (ISSUE 17) force the single-device path:
        # the scatter-merge kernel writes one donated logical
        # accumulator per slot, and sharding its write axis over 'sp'
        # is future work. The reason is explicit in /statusz mesh.
        self.sparse = isinstance(self.p3.circ, SparseSumVec)
        self.mesh_fallback_reason: str | None = None
        if self.sparse and dp * sp > 1:
            dp, sp = 1, 1
            self.mesh_fallback_reason = "sparse_scatter_single_device"
        self.mesh = make_mesh(dp, sp) if dp * sp > 1 else None
        self.dp = dp
        self.sp = sp
        # HBM feasibility bound (ISSUE r6): the largest power-of-two
        # bucket the device budget supports for this circuit, from the
        # bytes model in vdaf.feasibility (staged share + proofs +
        # outputs + the streamed-query tile working set). None =
        # unknown budget (CPU backend, tunnel without memory_stats) =
        # uncapped, preserving legacy behavior there. JANUS_BUCKET_CAP
        # overrides for tests/tuning ("0" = explicitly uncapped).
        circ = self.p3.circ
        plan = stream_plan(self.p3.bc)
        self.tile_elems = plan.group if plan is not None else None
        env_cap = os.environ.get("JANUS_BUCKET_CAP")
        if env_cap is not None:
            cap = int(env_cap)
            # buckets are powers of two (bucket_size) and mesh shards
            # need dp | bucket — round a stray override down so e.g.
            # "20" can't produce a 20-row axis dp can't partition
            self.bucket_cap = (1 << (cap.bit_length() - 1)) if cap > 0 else None
        else:
            self.bucket_cap = feasible_bucket(
                circ,
                device_memory_budget(),
                tile_elems=self.tile_elems,
                draft=inst.xof_mode != "fast",
            )
        if self.bucket_cap is not None:
            # mesh dispatches shard the report axis over dp devices;
            # every bucket (hence the cap) must stay divisible by dp
            self.bucket_cap = max(self.bucket_cap, self.dp)
        # runtime OOM recovery state: halve-the-bucket retries mutate
        # bucket_cap under the lock; at the floor the engine installs a
        # HostEngineCache and serves from it — permanently for a
        # definite RESOURCE_EXHAUSTED, with a timed device re-probe
        # (_host) when only the ambiguous tunnel-500 marker was seen.
        self._oom_lock = threading.Lock()
        self._host_fallback: "HostEngineCache | None" = None
        self._host_fallback_until: float | None = None
        self._initial_bucket_cap = self.bucket_cap
        # multi-device program dispatch rides the process-wide
        # single-controller lane (_MESH_QUEUE — see MeshDispatchQueue
        # for the interleaved-enqueue deadlock it prevents and the
        # queue-depth/wait metrics it adds over the lock it replaced)
        # cross-job dispatch coalescing (VERDICT r4 item 3): calls at or
        # below COALESCE_MAX_JOB rows ride shared device dispatches;
        # bigger jobs fill a dispatch on their own and go direct. The
        # per-round row cap scales inversely with the instance's
        # per-row size: a global 32768 tuned on Count would merge
        # concurrent SumVec jobs past the measured single-dispatch HBM
        # limit (len=1000 OOMs at batch 4096, BASELINE.md matrix) and
        # fail every co-batched job at once — and never past the HBM
        # feasibility cap.
        self._coalesce = os.environ.get("JANUS_COALESCE", "1") != "0"
        in_len = max(1, getattr(self.p3.circ, "input_len", 1))
        round_rows = max(
            MIN_BUCKET, min(self.COALESCE_ROUND_ROWS, self.COALESCE_ROUND_ELEMS // in_len)
        )
        if self.bucket_cap is not None:
            round_rows = min(round_rows, self.bucket_cap)
        self._initial_round_rows = round_rows
        # round-based coalescers. With cross-task coalescing (the
        # default) engines of the same VdafInstance SHARE one coalescer
        # per side, so small jobs of different tasks ride one dispatch
        # (per-lane verify keys); disabled, each engine keeps its own
        # (the PR 7 shape). Entries always carry their engine.
        if XTASK_COALESCE:
            self._co_leader = _shared_coalescer(inst, "leader", round_rows)
            self._co_helper = _shared_coalescer(inst, "helper", round_rows)
        else:
            self._co_leader = _Coalescer(_run_leader_round, round_rows)
            self._co_helper = _Coalescer(_run_helper_round, round_rows)
        # device-resident aggregate state (ISSUE 12): per-(task, batch
        # bucket) accumulator buffers living in device memory across job
        # steps. The ENGINE owns the buffers and the device ops
        # (delta/merge/fetch); the DRIVER owns the flush policy (the
        # write-tx path) — see aggregation_job_driver.ResidentConfig.
        self._resident: "OrderedDict[tuple, ResidentSlot]" = OrderedDict()
        self._resident_lock = threading.Lock()
        self._resident_stats = {
            "merged_rows": 0,
            "merges": 0,
            "evictions": 0,
            "eviction_deferred": 0,
            "takes": 0,
        }
        # sparse scatter accounting (ISSUE 17): total reports scattered
        # into dense logical accumulators + the last dispatch's mean
        # block occupancy — surfaced on the statusz `sparse` line and
        # the janus_engine_scatter_rows_total / _sparse_block_occupancy
        # metrics
        self._scatter_rows = 0
        self._sparse_last_occupancy: float | None = None
        # device-circuit quarantine (ISSUE 8; docs/ROBUSTNESS.md "Device
        # hangs & deadlines"): a watchdog-abandoned dispatch opens the
        # circuit — serving moves to the host engine immediately (the
        # interim work must land), and a background canary thread
        # recompiles + probe-dispatches until the device answers again,
        # then restores the device path with the initial caps. Unlike
        # the OOM timed_fallback this is EVENT-driven (proof the device
        # responds), not timer-driven hope.
        self._quarantined = False
        # set by stop_canary() (process teardown): wakes the canary's
        # cool-down wait so the loop exits instead of launching a probe
        # whose native device work would race interpreter finalization
        self._canary_wakeup = threading.Event()
        self._canary_stop = False
        self._canary_thread: threading.Thread | None = None
        # observability (docs/OBSERVABILITY.md "Engine metrics"): first
        # dispatch per (op, bucket) is the compile; OOM events feed the
        # /statusz engine-cache section
        self._dispatched_buckets: set[tuple[str, int]] = set()
        # finer first-dispatch tracking for the device cost ledger:
        # keyed by the jit specialization (variant name + bucket) the
        # call site reports, so a classic-aggregate compile after the
        # resident path warmed the same row bucket — or a new
        # agg_buckets_{kk} program at an already-seen bucket — still
        # books as phase="compile" in ITS ledger row
        self._ledger_dispatched: set[tuple] = set()
        self._dispatch_track_lock = threading.Lock()
        self.oom_history: deque = deque(maxlen=16)
        self._publish_state()

    # every state the janus_engine_backend gauge manages; exactly one
    # is 1 per VDAF kind at any time (docs/OBSERVABILITY.md)
    BACKEND_STATES = ("device", "host_fallback", "timed_fallback", "quarantined", "host")

    def _backend_state(self) -> str:
        if self._quarantined:
            return "quarantined"
        if self._host_fallback is None:
            return "device"
        return "host_fallback" if self._host_fallback_until is None else "timed_fallback"

    def _publish_state(self) -> None:
        """Refresh the janus_engine_backend / janus_engine_bucket_cap
        gauges for this engine's VDAF kind (callers hold _oom_lock when
        mutating fallback state; the gauges take their own locks).
        All states are managed — including "host", which only
        _build_engine sets to 1 — so exactly one state is 1 per kind
        and a draft-mode host engine followed by a fast-mode device
        engine of the same kind can't leave both at 1. Same-kind
        engines (different params) share the label and last-writer
        wins; the gauge is per VDAF kind, not per task."""
        from ..metrics import engine_backend_state, engine_bucket_cap

        state = self._backend_state()
        for s in self.BACKEND_STATES:
            engine_backend_state.set(1.0 if s == state else 0.0, vdaf=self.inst.kind, state=s)
        engine_bucket_cap.set(float(self.bucket_cap or 0), vdaf=self.inst.kind)

    def _record_dispatch(
        self,
        op: str,
        n: int,
        b: int,
        elapsed_s: float,
        ledger_op: str | None = None,
        compile_key: tuple | None = None,
    ) -> None:
        """Per-dispatch accounting: throughput counters, padding-waste
        gauge, and the first-call-per-(op, bucket) compile histogram —
        jax.jit compiles synchronously on the first call of a shape
        bucket, so that call's wall time IS the cold-start cost
        OBSERVABILITY.md used to describe only in prose."""
        from .. import metrics

        metrics.engine_dispatches_total.add(op=op)
        metrics.engine_rows_total.add(n, op=op)
        if b > 0:
            metrics.engine_batch_fill_ratio.set(n / b, op=op)
        lkey = compile_key if compile_key is not None else (ledger_op or op, b)
        if self.mesh is not None:
            # mesh specializations are keyed by geometry too: the shape
            # manifest must never hand a (dp, sp) program to a boot with
            # a different device topology (prewarm checks this suffix),
            # and the AOT digest carries the same triple
            lkey = tuple(lkey) + ("mesh", self.dp, self.sp, self._ndev)
        with self._dispatch_track_lock:
            first = (op, b) not in self._dispatched_buckets
            if first:
                self._dispatched_buckets.add((op, b))
            ledger_first = lkey not in self._ledger_dispatched
            if ledger_first:
                self._ledger_dispatched.add(lkey)
        if first:
            metrics.engine_compile_seconds.observe(elapsed_s, op=op, bucket=str(b))
        # per-dispatch device cost ledger (ISSUE 13): the first call of
        # a jit specialization IS the trace+compile, later calls are
        # execute; rows ride along so the µs/report attribution has a
        # denominator. `ledger_op` splits ledger rows finer than the
        # engine counters (the resident aggregate_pending path shares
        # op="aggregate" in janus_engine_dispatches_total but one
        # dispatch covers k buckets) and `compile_key` carries the
        # variant name the call site jitted, so compile-vs-execute
        # classification tracks the real specialization, not the
        # engine-metric (op, bucket) approximation.
        from ..profiler import DEVICE_COST

        DEVICE_COST.record(
            self.inst.kind,
            ledger_op or op,
            b,
            "compile" if ledger_first else "execute",
            elapsed_s,
            rows=n,
            dispatches=1,
        )
        if ledger_first:
            # persisted shape manifest (ISSUE 14): the first dispatch
            # of a specialization IS the cold-start cost a restarted
            # process would pay again — record it so the boot prewarm
            # can compile exactly this set before /readyz flips ready
            shape_manifest.record_dispatch(
                self.inst, ledger_op or op, b, lkey, elapsed_s, rows=n
            )

    # Per-call row cap for joining a shared round; absolute round row
    # cap; and the rows x input_len budget one coalesced round may
    # stage (2^25 elements = half the len=1000 OOM point at 4096 rows).
    COALESCE_MAX_JOB = 4096
    COALESCE_ROUND_ROWS = 32768
    COALESCE_ROUND_ELEMS = 1 << 25

    def _shard(self, *batch_ndims):
        """NamedShardings splitting the leading (report) axis over 'dp';
        one entry per arg, each an int ndim or a tuple (field limbs) or
        None (absent arg). The string marker "vec2" is a 2-d field limb
        whose trailing (vector) axis additionally shards over 'sp'."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        def one(nd):
            if nd is None:
                return None
            if nd == "vec2":
                return NamedSharding(self.mesh, P("dp", "sp"))
            if isinstance(nd, tuple):
                return tuple(one(x) for x in nd)
            return NamedSharding(self.mesh, P(*(("dp",) + (None,) * (nd - 1))))

        return tuple(one(nd) for nd in batch_ndims)

    def _jit(self, name: str, fn, in_shardings=None, out_shardings=None):
        if name not in self._jits:
            kwargs = {}
            if self.mesh is not None:
                if in_shardings is not None:
                    kwargs["in_shardings"] = in_shardings
                if out_shardings is not None:
                    kwargs["out_shardings"] = out_shardings
            jitted = jax.jit(fn, **kwargs)
            # every program — single-device AND mesh — rides the
            # serialized-executable AOT cache (aot_cache.py): a
            # restarted process, or a canary rebuild that just dropped
            # _jits, deserializes the compiled executable instead of
            # re-tracing. Mesh digests carry (dp, sp, device count) so
            # a blob only ever loads on its own topology; a passthrough
            # while the cache is disarmed.
            wrapped = aot_cache.wrap(
                jitted,
                aot_cache.engine_base(
                    self.inst.to_dict(),
                    self.verify_key,
                    name,
                    mesh=(self.dp, self.sp, self._ndev)
                    if self.mesh is not None
                    else None,
                ),
            )
            if self.mesh is not None:
                # multi-device enqueues are owned by the process-wide
                # single-controller lane (MeshDispatchQueue): submit
                # blocks this thread until the lane ran the enqueue,
                # execution stays async on the devices
                vdaf = self.inst.kind

                def queued(*a, _fn=wrapped, _name=name, _vdaf=vdaf, **k):
                    return _MESH_QUEUE.submit(_fn, a, k, vdaf=_vdaf, program=_name)

                self._jits[name] = queued
            else:
                self._jits[name] = wrapped
        return self._jits[name]

    # --- OOM recovery (shared by every public step) ---
    def _handle_engine_error(self, e: BaseException, n: int) -> None:
        """Called from an except block. Re-raises non-OOM errors;
        otherwise halves the bucket cap (so the caller's retry chunks
        smaller) and, at the bucket floor, installs the permanent
        HostEngineCache fallback. Never lets the OOM escape — the
        aggregation job driver sees a slow success, not a dead job."""
        if not is_oom_error(e):
            raise
        with self._oom_lock:
            if self._host_fallback is not None:
                return
            # A coalesced round hands the SAME exception object to every
            # co-batched submitter's retry loop; only the first may act,
            # or one transient OOM would halve once per submitter and
            # walk the cap straight to the host-fallback floor.
            if getattr(e, "_janus_oom_handled", False):
                return
            try:
                e._janus_oom_handled = True
            except Exception:
                pass
            floor = max(1, self.dp)
            observed = getattr(e, "_janus_dispatch_bucket", None)
            if observed is None:
                observed = bucket_size(n, self.bucket_cap)
            # halving only helps dispatches whose bucket tracks the cap.
            # An aggregate over an ALREADY-RESIDENT device buffer re-runs
            # at the buffer's fixed bucket no matter the cap, so a
            # persistent OOM there would loop forever at new_cap ==
            # bucket_cap — treat "no progress possible" as the floor.
            stuck = (
                getattr(e, "_janus_fixed_bucket", False)
                and self.bucket_cap is not None
                and observed // 2 >= self.bucket_cap
            )
            if observed <= floor or stuck:
                definite = _is_definite_oom(e)
                log.warning(
                    "device OOM at bucket floor %d for %s; falling back to "
                    "the host engine %s: %s",
                    floor,
                    self.inst.kind,
                    "permanently" if definite
                    else f"for {self.HOST_FALLBACK_RETRY_SECS:.0f}s (ambiguous tunnel error)",
                    e,
                )
                from ..metrics import engine_host_fallback_counter

                engine_host_fallback_counter.add()
                self._host_fallback = HostEngineCache(self.inst, self.verify_key)
                # A genuine HBM overflow at bucket 1 can never fit, so
                # the fallback is final. The tunnel's opaque 500 could
                # equally be a restart/outage — re-probe the device
                # path after a cool-down instead of pinning a recovered
                # tunnel to the ~100x slower host loop forever.
                self._host_fallback_until = (
                    None if definite else time.monotonic() + self.HOST_FALLBACK_RETRY_SECS
                )
                self.oom_history.append(
                    {
                        "at": time.time(),
                        "bucket": observed,
                        "action": "host_fallback" if definite else "timed_fallback",
                        "error": str(e)[:200],
                    }
                )
                self._publish_state()
                return
            new_cap = observed // 2
            self.bucket_cap = new_cap if self.bucket_cap is None else min(self.bucket_cap, new_cap)
            self._co_leader._max_rows = min(self._co_leader._max_rows, self.bucket_cap)
            self._co_helper._max_rows = min(self._co_helper._max_rows, self.bucket_cap)
            log.warning(
                "device OOM at bucket %d for %s; retrying with bucket cap %d: %s",
                observed, self.inst.kind, self.bucket_cap, e,
            )
            from ..metrics import engine_oom_retry_counter

            engine_oom_retry_counter.add()
            self.oom_history.append(
                {
                    "at": time.time(),
                    "bucket": observed,
                    "action": f"halved_to_{self.bucket_cap}",
                    "error": str(e)[:200],
                }
            )
            self._publish_state()

    # Cool-down before a host fallback reached through an AMBIGUOUS
    # error (tunnel 500) re-probes the device path.
    HOST_FALLBACK_RETRY_SECS = 60.0

    def _host(self) -> "HostEngineCache | None":
        """Active host fallback, honoring the ambiguous-OOM expiry: a
        definite RESOURCE_EXHAUSTED at the bucket floor pins the host
        engine for the process lifetime (until=None); a tunnel-500
        fallback expires after HOST_FALLBACK_RETRY_SECS, restoring the
        initial feasibility caps so a recovered tunnel serves at full
        device speed again (a still-broken one just re-walks the
        halving ladder once per cool-down).

        Two further sources outrank the timer: process-wide HOST-ONLY
        mode (the watchdog's abandoned-thread cap tripped — the device
        has eaten too many threads to trust again this process) and the
        per-engine hang QUARANTINE, whose exit is the canary probe, not
        a clock."""
        if device_watchdog.WATCHDOG.host_only():
            host = self._host_fallback
            if host is None:
                with self._oom_lock:
                    if self._host_fallback is None:
                        self._host_fallback = HostEngineCache(self.inst, self.verify_key)
                        self._host_fallback_until = None
                        self._publish_state()
                    host = self._host_fallback
            return host
        if self._quarantined:
            # canary-driven: serve host until the probe proves the
            # device answers again (_canary_loop clears the state)
            return self._host_fallback
        host = self._host_fallback
        if host is None:
            return None
        until = self._host_fallback_until
        if until is None or time.monotonic() < until:
            return host
        with self._oom_lock:
            if self._host_fallback is host and self._host_fallback_until == until:
                log.warning(
                    "re-probing device engine for %s after ambiguous-OOM host fallback",
                    self.inst.kind,
                )
                self._host_fallback = None
                self._host_fallback_until = None
                self.bucket_cap = self._initial_bucket_cap
                self._co_leader._max_rows = self._initial_round_rows
                self._co_helper._max_rows = self._initial_round_rows
                self._publish_state()
            return self._host_fallback

    # --- hang quarantine + canary rebuild (ISSUE 8) ---
    # Env defaults let harnesses (chaos_run device_hang) shrink the
    # cycle; janus_main applies the YAML `device_watchdog:` values to
    # these class attributes at boot.
    QUARANTINE_CANARY_DELAY_SECS = float(os.environ.get("JANUS_CANARY_DELAY_S", "5.0"))
    QUARANTINE_CANARY_TIMEOUT_SECS = float(os.environ.get("JANUS_CANARY_TIMEOUT_S", "30.0"))
    QUARANTINE_CANARY_MAX_DELAY_SECS = 60.0

    # Supervised regions whose wall time the device cost ledger
    # attributes as a whole (no finer-grained span/dispatch accounting
    # inside them): the resident fetches are pure d2h waits. The init/
    # aggregate labels are deliberately absent — their phases are split
    # inside the closure (_record_dispatch + the put/fetch span hooks).
    _LEDGER_SUPERVISED_PHASES = {
        "fetch_resident": "d2h",
        "resident_fetch": "d2h",
        "resident_delta_fetch": "d2h",
    }

    def _supervised(self, label: str, fn):
        """Route a device-touching closure through the process dispatch
        watchdog under the AMBIENT deadline (job drivers: lease bound;
        helper handlers: propagated request budget — core/deadline.py).
        No ambient deadline = direct call: one contextvar read, the
        bench --dry-run `watchdog_overhead` record keeps it honest."""
        phase = self._LEDGER_SUPERVISED_PHASES.get(label)
        t0 = time.monotonic() if phase is not None else 0.0
        try:
            return device_watchdog.WATCHDOG.run(
                fn,
                deadline=current_deadline(),
                label=label,
                vdaf=self.inst.kind,
                on_hang=self._quarantine_on_hang,
            )
        finally:
            if phase is not None:
                from ..profiler import DEVICE_COST

                DEVICE_COST.record(
                    self.inst.kind, label, 0, phase, time.monotonic() - t0
                )

    def _quarantine_on_hang(self, label: str) -> None:
        """Watchdog hang hook: open the device circuit. Serving moves
        to the host engine NOW (the step that hung steps back; its
        retry and every other job must land through host fallback), and
        the canary thread owns the way back."""
        from .. import metrics

        with self._oom_lock:
            if self._quarantined:
                return
            # order matters for the lock-free readers in _host(): the
            # fallback must exist BEFORE the flag flips, or a racing
            # caller sees quarantined-with-no-host and dispatches to
            # the known-wedged device
            if self._host_fallback is None:
                self._host_fallback = HostEngineCache(self.inst, self.verify_key)
            self._host_fallback_until = None
            self._quarantined = True
            self.oom_history.append(
                {
                    "at": time.time(),
                    "bucket": None,
                    "action": "quarantined",
                    "error": f"hung dispatch {label}",
                }
            )
            self._publish_state()
            start_canary = not device_watchdog.WATCHDOG.host_only()
        metrics.engine_quarantines_total.add(vdaf=self.inst.kind, event="open")
        log.error(
            "engine %s QUARANTINED after hung %s dispatch; serving from the host "
            "engine while the canary probes the device",
            self.inst.kind,
            label,
        )
        if start_canary:
            t = threading.Thread(
                target=self._canary_loop,
                name=f"engine-canary-{self.inst.kind}",
                daemon=True,
            )
            self._canary_thread = t
            t.start()

    def _canary_loop(self) -> None:
        """Background canary: after a cool-down, recompile + probe the
        device; on success restore the device path with the initial
        caps, on failure back off and try again (a still-wedged device
        keeps quarantine open; repeated hung probes walk the abandoned
        cap toward host-only mode, which ends the loop)."""
        from .. import metrics

        delay = self.QUARANTINE_CANARY_DELAY_SECS
        while True:
            self._canary_wakeup.wait(delay)
            self._canary_wakeup.clear()
            if (
                self._canary_stop
                or not self._quarantined
                or device_watchdog.WATCHDOG.host_only()
            ):
                return
            metrics.engine_quarantines_total.add(vdaf=self.inst.kind, event="canary_probe")
            try:
                self._canary_probe()
            except BaseException as e:  # noqa: BLE001 - incl. DeviceHangError
                metrics.engine_quarantines_total.add(
                    vdaf=self.inst.kind, event="canary_failed"
                )
                log.warning(
                    "canary probe for %s failed (%s: %s); next probe in %.1fs",
                    self.inst.kind, type(e).__name__, e, delay,
                )
                delay = min(delay * 2, self.QUARANTINE_CANARY_MAX_DELAY_SECS)
                continue
            with self._oom_lock:
                self._quarantined = False
                self._host_fallback = None
                self._host_fallback_until = None
                self.bucket_cap = self._initial_bucket_cap
                self._co_leader._max_rows = self._initial_round_rows
                self._co_helper._max_rows = self._initial_round_rows
                self.oom_history.append(
                    {"at": time.time(), "bucket": None, "action": "restored", "error": ""}
                )
                self._publish_state()
            metrics.engine_quarantines_total.add(vdaf=self.inst.kind, event="restored")
            log.warning(
                "engine %s restored to the device path (canary probe succeeded)",
                self.inst.kind,
            )
            # warm canary restore (ISSUE 14): the probe dropped every
            # compiled executable, so re-warm this engine's recorded
            # specializations from the shape manifest HERE, in the
            # canary thread — with the persistent compile cache these
            # are disk loads, and the serving path never pays a
            # post-restore re-trace. Best-effort: serving is already
            # restored; a failed warm just means lazier compiles.
            if not self._canary_stop:
                try:
                    from .prewarm import warm_engine_from_manifest

                    # stop-aware between entries: stop_canary's bounded
                    # join must not leave this loop dispatching native
                    # work into interpreter finalization
                    warmed = warm_engine_from_manifest(
                        self, should_stop=lambda: self._canary_stop
                    )
                    if warmed:
                        log.info(
                            "canary re-warmed %d recorded specialization(s) for %s",
                            warmed, self.inst.kind,
                        )
                except Exception:
                    log.warning("post-restore manifest warm failed", exc_info=True)
            return

    def stop_canary(self, timeout_s: float = 2.0) -> None:
        """Process-teardown hook (shutdown_engines): stop the canary
        loop and give an in-flight probe a bounded window to finish —
        a daemon worker mid-probe re-entering native device code while
        the interpreter finalizes crashes the runtime (the same hazard
        as woken hang workers; ROBUSTNESS.md)."""
        self._canary_stop = True
        self._canary_wakeup.set()
        t = self._canary_thread
        if t is not None and t.is_alive():
            t.join(timeout_s)

    def _canary_probe(self) -> None:
        """Recompile + probe dispatch: drop the cached executables (the
        hung program may be wedged inside the runtime) and run a small
        REAL masked aggregate — device put, fresh trace+compile,
        dispatch, fetch — under the watchdog with its own bounded
        deadline. Success means the device answers end to end. The
        `engine.canary` failpoint lets tests hold the quarantine open."""
        p3 = self.p3
        self._jits = {}  # atomic swap; abandoned threads keep old refs
        b = max(MIN_BUCKET, self.dp)
        value = tuple(
            np.zeros((b, p3.circ.output_len), dtype=np.uint64)
            for _ in range(p3.jf.LIMBS)
        )
        mask = np.zeros(b, dtype=bool)

        def step(v, m):
            return p3.aggregate(v, m)

        fn = self._jit("aggregate", step)
        deadline = time.monotonic() + self.QUARANTINE_CANARY_TIMEOUT_SECS

        def probe():
            failpoints.hit("engine.canary")
            staged = put_args((value, mask), block=True)
            agg = fn(*staged)
            return [int(x) for x in p3.jf.to_ints(agg)]

        result = device_watchdog.WATCHDOG.run(
            probe, deadline=deadline, label="canary", vdaf=self.inst.kind
        )
        if any(result):
            raise RuntimeError(f"canary probe returned garbage: {result[:4]}")

    # --- helper side: init + combine + decide in one traced step ---
    def helper_init(self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
        """Returns (out1 field value, accept mask, prep_msg lanes) sliced
        to the true batch size. Small batches coalesce with concurrent
        callers into one device dispatch (_Coalescer). Device OOM is
        absorbed: halved-bucket retry, then host fallback."""
        args = (nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask)
        while True:
            host = self._host()
            if host is not None:
                return host.helper_init(*args)
            try:
                return self._helper_init_entry(*args)
            except Exception as e:  # noqa: BLE001 - OOM filter inside
                self._handle_engine_error(e, nonce_lanes.shape[0])

    def _helper_init_entry(self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
        n = nonce_lanes.shape[0]
        cap = self.bucket_cap
        if self._coalesce and n <= self.COALESCE_MAX_JOB and (cap is None or n <= cap):
            return self._co_helper.submit(
                (self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask),
                n,
            )
        if cap is not None and n > cap:
            return self._helper_init_chunked(
                nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask, cap
            )
        return self._helper_init_inner(
            nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask
        )

    def _helper_init_chunked(
        self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask, cap: int,
        vk_lanes=None,
    ):
        """Serial cap-sized dispatches for a batch past the HBM bound —
        each chunk's working set fits the budget; out shares stay
        device-resident as DeviceRowsChunks."""
        n = nonce_lanes.shape[0]
        outs, masks, preps = [], [], []
        for s in range(0, n, cap):
            e = min(s + cap, n)
            out1, mask, prep = self._helper_init_inner(
                _cut_rows(nonce_lanes, s, e),
                _cut_rows(public_parts, s, e),
                _cut_rows(helper_seeds, s, e),
                _cut_rows(blinds, s, e),
                _cut_rows(ver0, s, e),
                _cut_rows(part0, s, e),
                _cut_rows(ok_mask, s, e),
                vk_lanes=_cut_rows(vk_lanes, s, e),
            )
            outs.append(out1)
            masks.append(mask)
            preps.append(prep)
        return DeviceRowsChunks(outs), np.concatenate(masks), np.concatenate(preps)

    def _helper_init_inner(
        self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask,
        coalesced: int = 0, vk_lanes=None,
    ):
        p3 = self.p3
        n = nonce_lanes.shape[0]
        cap = self.bucket_cap  # read once — concurrent OOM recovery may
        # halve it between the entry/coalescer gate and here; a stale
        # smaller cap with n > cap must chunk, never pad negative
        if cap is not None and n > cap:
            return self._helper_init_chunked(
                nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask, cap,
                vk_lanes=vk_lanes,
            )
        b = bucket_size(n, cap)

        def step_body(vkey, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
            out1, seed1, ver1, part1 = p3.prepare_init_helper(
                vkey, nonce_lanes, public_parts, helper_seeds, blinds
            )
            mask, prep_msg = p3.prep_shares_to_prep(ver0, ver1, part0, part1)
            mask = p3.prepare_finish(seed1, prep_msg, mask)
            mask = mask & ok_mask
            if prep_msg is None:
                prep_msg = jnp.zeros((nonce_lanes.shape[0], 2), dtype=jnp.uint64)
            return out1, mask, prep_msg

        from ..trace import span

        L = len(ver0)
        arg_nds = (
            2,
            None if public_parts is None else 3,
            2,
            None if blinds is None else 2,
            (2,) * L,
            2,
            1,
        )
        if vk_lanes is None:
            # single-task round: the verify key stays a trace constant —
            # byte-identical compiled steps to the pre-cross-task engine
            def step(*a):
                return step_body(self.verify_key, *a)

            name = "helper_init"
        else:
            # cross-task round: the key is a per-lane input
            def step(vk, *a):
                return step_body(vk, *a)

            name = "helper_init_vk"
            arg_nds = (2,) + arg_nds
        shardings = None
        if self.mesh is not None:
            shardings = self._shard(*arg_nds)
        fn = self._jit(name, step, in_shardings=shardings)
        raw_args = (nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask)
        if vk_lanes is not None:
            raw_args = (vk_lanes,) + raw_args
        args = pad_args(b, *raw_args)

        # the np.asarray conversions block on device execution — they
        # must sit inside the span or it measures only async dispatch.
        # out1 stays ON DEVICE (DeviceRows): the aggregate step reads it
        # there; only the small mask/prep_msg come back. The whole
        # device-touching region (put/dispatch/fetch — every point a
        # wedged device can park the thread, failpoint included so the
        # hang action models exactly that) runs under the dispatch
        # watchdog (_supervised).
        def device_call():
            _engine_dispatch_failpoint()
            with span(
                "engine.helper_init",
                vdaf=self.inst.kind,
                batch=n,
                bucket=b,
                coalesced=coalesced,
            ):
                with span("engine.helper_init.put", vdaf=self.inst.kind, bucket=b):
                    staged = put_args(args, block=True, shardings=shardings)
                t_disp = time.monotonic()
                with span("engine.helper_init.dispatch", vdaf=self.inst.kind):
                    out1, mask, prep_msg = fn(*staged)
                self._record_dispatch(
                    "helper_init", n, b, time.monotonic() - t_disp,
                    compile_key=(name, b),
                )
                with span("engine.helper_init.fetch", vdaf=self.inst.kind, bucket=b):
                    mask = np.asarray(mask)[:n]
                    prep_msg = np.asarray(prep_msg)[:n]
                    count_d2h((mask, prep_msg))
            return out1, mask, prep_msg

        try:
            out1, mask, prep_msg = self._supervised("helper_init", device_call)
        except Exception as e:
            _annotate_dispatch_bucket(e, b)
            raise
        return DeviceRows(out1, n), mask, prep_msg

    # Pipelined leader init: jobs past 2x this size split into chunks
    # whose host->device transfers are ALL issued up front; each chunk's
    # dispatch then overlaps the later chunks' transfers (VERDICT r3
    # item 8 — the driver used to stage-then-dispatch serially, leaving
    # the device idle for the whole staging transfer).
    PIPELINE_CHUNK = 256

    # --- leader side: init only (network round trip follows) ---
    def leader_init(self, nonce_lanes, public_parts, meas, proof, blind0, ok=None, prestaged=None):
        # ok is accepted for interface parity with HostEngineCache; the
        # batched device step costs nothing extra for failed lanes
        # (their rows are zeroed and masked downstream).
        while True:
            host = self._host()
            if host is not None:
                if prestaged is not None:
                    prestaged.discard()
                    prestaged = None
                return host.leader_init(nonce_lanes, public_parts, meas, proof, blind0, ok)
            try:
                return self._leader_init_entry(
                    nonce_lanes, public_parts, meas, proof, blind0, prestaged
                )
            except Exception as e:  # noqa: BLE001 - OOM filter inside
                if prestaged is not None:
                    prestaged.discard()
                    prestaged = None  # the retry re-stages from host
                self._handle_engine_error(e, nonce_lanes.shape[0])

    def _leader_init_entry(self, nonce_lanes, public_parts, meas, proof, blind0, prestaged=None):
        n = nonce_lanes.shape[0]
        cap = self.bucket_cap
        if self._coalesce and n <= self.COALESCE_MAX_JOB and (cap is None or n <= cap):
            return self._co_leader.submit(
                (self, prestaged, nonce_lanes, public_parts, meas, proof, blind0), n
            )
        return self._leader_init_inner(
            nonce_lanes, public_parts, meas, proof, blind0, prestaged=prestaged
        )

    def _leader_init_inner(
        self,
        nonce_lanes,
        public_parts,
        meas,
        proof,
        blind0,
        coalesced: int = 0,
        allow_pipeline: bool = True,
        vk_lanes=None,
        prestaged=None,
    ):
        p3 = self.p3
        n = nonce_lanes.shape[0]
        cap = self.bucket_cap
        if cap is not None and n > cap:
            # past the HBM bound: serial cap-sized dispatches (staging
            # everything up front, as the pipelined path does, would
            # resident-stage exactly the bytes the cap exists to avoid)
            if prestaged is not None:
                prestaged.discard()
            return self._leader_init_chunked(
                nonce_lanes, public_parts, meas, proof, blind0, cap, vk_lanes=vk_lanes
            )
        if (
            allow_pipeline
            and vk_lanes is None
            and self.mesh is None
            and n >= 2 * self.PIPELINE_CHUNK
        ):
            if prestaged is not None:
                prestaged.discard()
            return self._leader_init_pipelined(
                nonce_lanes, public_parts, meas, proof, blind0
            )
        b = bucket_size(n, cap)

        from ..trace import span

        L = len(meas)
        meas_nd = "vec2" if self.sp > 1 else 2
        arg_nds = (
            2,
            None if public_parts is None else 3,
            (meas_nd,) * L,
            (2,) * L,
            None if blind0 is None else 2,
        )
        if vk_lanes is None:

            def step(*a):
                return p3.prepare_init_leader(self.verify_key, *a)

            name = "leader_init"
        else:
            # cross-task round: per-lane verify keys ride the dispatch
            def step(vk, *a):
                return p3.prepare_init_leader(vk, *a)

            name = "leader_init_vk"
            arg_nds = (2,) + arg_nds
        shardings = None
        if self.mesh is not None:
            shardings = self._shard(*arg_nds)
        fn = self._jit(name, step, in_shardings=shardings)
        # double-buffered staging (ISSUE 12): a usable prestaged column
        # set (same bucket, issued while the PREVIOUS job occupied the
        # device lane) skips the host put entirely — its transfers are
        # already in flight or landed
        use_prestaged = (
            prestaged is not None
            and vk_lanes is None
            and prestaged.usable(b, self.mesh is not None)
        )
        if prestaged is not None:
            from .. import metrics

            metrics.engine_prestage_total.add(
                outcome="hit" if use_prestaged else "fallback"
            )
            if not use_prestaged:
                prestaged.discard()
        if not use_prestaged:
            raw_args = (nonce_lanes, public_parts, meas, proof, blind0)
            if vk_lanes is not None:
                raw_args = (vk_lanes,) + raw_args
            args = pad_args(b, *raw_args)

        # conversions block on device execution — keep inside the span.
        # out0 stays ON DEVICE (DeviceRows) for the later aggregate;
        # seed0/ver0/part0 are needed host-side for the wire round trip.
        # Whole device region watchdog-supervised (see _helper_init_inner).
        def device_call():
            _engine_dispatch_failpoint()
            with span(
                "engine.leader_init",
                vdaf=self.inst.kind,
                batch=n,
                bucket=b,
                coalesced=coalesced,
                prestaged=bool(use_prestaged),
            ):
                with span("engine.leader_init.put", vdaf=self.inst.kind, bucket=b):
                    if use_prestaged:
                        staged = prestaged.take()  # transfers already in flight
                        jax.block_until_ready(staged)
                    else:
                        staged = put_args(args, block=True, shardings=shardings)
                t_disp = time.monotonic()
                with span("engine.leader_init.dispatch", vdaf=self.inst.kind):
                    out0, seed0, ver0, part0 = fn(*staged)
                self._record_dispatch(
                    "leader_init", n, b, time.monotonic() - t_disp,
                    compile_key=(name, b),
                )
                with span("engine.leader_init.fetch_seed", vdaf=self.inst.kind, bucket=b):
                    seed0 = np.asarray(seed0)[:n] if seed0 is not None else None
                with span("engine.leader_init.fetch_ver", vdaf=self.inst.kind, bucket=b):
                    ver0 = tuple(np.asarray(x)[:n] for x in ver0)
                with span("engine.leader_init.fetch_part", vdaf=self.inst.kind, bucket=b):
                    part0 = np.asarray(part0)[:n] if part0 is not None else None
                count_d2h((seed0, ver0, part0))
            return out0, seed0, ver0, part0

        try:
            out0, seed0, ver0, part0 = self._supervised("leader_init", device_call)
        except Exception as e:
            _annotate_dispatch_bucket(e, b)
            raise
        return DeviceRows(out0, n), seed0, ver0, part0

    def prestage_leader(self, nonce_lanes, public_parts, meas, proof, blind0):
        """Double-buffered host->device staging: issue the padded column
        uploads ASYNC now (typically from the pipeline's read stage,
        while the device lane runs the previous job's dispatch) and hand
        back a PrestagedInit for leader_init to consume. Returns None
        when the direct-dispatch path can't use it (host fallback /
        quarantine, chunked past the HBM cap, or the big-batch pipelined
        path which stages its own chunks)."""
        if self._host() is not None:
            return None
        n = nonce_lanes.shape[0]
        cap = self.bucket_cap
        if cap is not None and n > cap:
            return None
        if self.mesh is None and n >= 2 * self.PIPELINE_CHUNK:
            return None
        b = bucket_size(n, cap)
        L = len(meas)
        shardings = None
        if self.mesh is not None:
            meas_nd = "vec2" if self.sp > 1 else 2
            shardings = self._shard(
                2,
                None if public_parts is None else 3,
                (meas_nd,) * L,
                (2,) * L,
                None if blind0 is None else 2,
            )
        args = pad_args(b, nonce_lanes, public_parts, meas, proof, blind0)
        staged = put_args(args, block=False, shardings=shardings)
        return PrestagedInit(b, staged, self.mesh is not None)

    def _leader_init_chunked(
        self, nonce_lanes, public_parts, meas, proof, blind0, cap: int, vk_lanes=None
    ):
        """Serial cap-sized leader inits for a batch past the HBM bound.
        Unlike _leader_init_pipelined, chunk k+1's transfer is NOT
        staged while chunk k computes — bounding resident bytes is the
        whole point. Outputs merge exactly like the pipelined path."""
        n = nonce_lanes.shape[0]
        outs, seeds, vers, parts = [], [], [], []
        for s in range(0, n, cap):
            e = min(s + cap, n)
            out0, seed0, ver0, part0 = self._leader_init_inner(
                _cut_rows(nonce_lanes, s, e),
                _cut_rows(public_parts, s, e),
                _cut_rows(meas, s, e),
                _cut_rows(proof, s, e),
                _cut_rows(blind0, s, e),
                allow_pipeline=False,
                vk_lanes=_cut_rows(vk_lanes, s, e),
            )
            outs.append(out0)
            seeds.append(seed0)
            vers.append(ver0)
            parts.append(part0)
        seed = np.concatenate(seeds) if seeds[0] is not None else None
        ver = tuple(
            np.concatenate([v[i] for v in vers]) for i in range(len(vers[0]))
        )
        part = np.concatenate(parts) if parts[0] is not None else None
        return DeviceRowsChunks(outs), seed, ver, part

    def _leader_init_pipelined(self, nonce_lanes, public_parts, meas, proof, blind0):
        """Chunked leader init: every chunk's device transfer is issued
        immediately (async, all in flight), then chunks dispatch in
        order — chunk k's compute overlaps chunk k+1..'s H2D. Outputs
        are host-concatenated; out shares stay device-resident as
        DeviceRowsChunks."""
        import jax

        from ..trace import span

        p3 = self.p3
        n = nonce_lanes.shape[0]
        C = self.PIPELINE_CHUNK

        def step(nonce_lanes, public_parts, meas, proof, blind0):
            return p3.prepare_init_leader(
                self.verify_key, nonce_lanes, public_parts, meas, proof, blind0
            )

        fn = self._jit("leader_init", step)

        spans_ = [(s, min(s + C, n)) for s in range(0, n, C)]

        # one supervised region for the whole pipeline: every chunk's
        # block_until_ready/dispatch/fetch can park on a wedged device
        # the dominant chunk bucket keys the cost ledger's per-bucket
        # row for the whole pipelined pass (the tail chunk may pad to a
        # smaller bucket; its share of the one put/fetch span can't be
        # split out)
        chunk_b = bucket_size(min(n, C))

        def device_call():
            _engine_dispatch_failpoint()
            with span("engine.leader_init", vdaf=self.inst.kind, batch=n, pipelined=len(spans_)):
                staged = []
                with span(
                    "engine.leader_init.put_all_async", vdaf=self.inst.kind, bucket=chunk_b
                ):
                    for s, e in spans_:
                        args = pad_args(
                            bucket_size(e - s),
                            _cut_rows(nonce_lanes, s, e),
                            _cut_rows(public_parts, s, e),
                            _cut_rows(meas, s, e),
                            _cut_rows(proof, s, e),
                            _cut_rows(blind0, s, e),
                        )
                        staged.append(put_args(args, block=False))
                outs = []
                for k, ((s, e), args) in enumerate(zip(spans_, staged)):
                    with span("engine.leader_init.chunk", k=k, rows=e - s, vdaf=self.inst.kind):
                        jax.block_until_ready(args)  # this chunk's H2D only
                        t_disp = time.monotonic()
                        outs.append(fn(*args))
                        self._record_dispatch(
                            "leader_init", e - s, bucket_size(e - s),
                            time.monotonic() - t_disp,
                        )
                with span("engine.leader_init.fetch", vdaf=self.inst.kind, bucket=chunk_b):
                    out_chunks = [
                        DeviceRows(o[0], e - s) for (s, e), o in zip(spans_, outs)
                    ]
                    seed0 = (
                        np.concatenate(
                            [np.asarray(o[1])[: e - s] for (s, e), o in zip(spans_, outs)]
                        )
                        if outs[0][1] is not None
                        else None
                    )
                    L = len(outs[0][2])
                    ver0 = tuple(
                        np.concatenate(
                            [np.asarray(o[2][i])[: e - s] for (s, e), o in zip(spans_, outs)]
                        )
                        for i in range(L)
                    )
                    part0 = (
                        np.concatenate(
                            [np.asarray(o[3])[: e - s] for (s, e), o in zip(spans_, outs)]
                        )
                        if outs[0][3] is not None
                        else None
                    )
            return DeviceRowsChunks(out_chunks), seed0, ver0, part0

        try:
            return self._supervised("leader_init", device_call)
        except Exception as exc:
            _annotate_dispatch_bucket(exc, bucket_size(min(n, C)))
            raise

    # --- masked aggregate over the batch axis ---
    def aggregate(self, out_shares, mask):
        """Masked aggregate with the same OOM recovery as the init
        steps. After a host fallback, rows produced by the host engine
        (plain limb tuples) aggregate on host; device-resident rows
        from before the fallback are fetched and aggregated on host."""
        while True:
            host = self._host()
            if host is not None:
                if isinstance(out_shares, (DeviceRows, DeviceRowsChunks)):
                    # fetching a buffer resident on a possibly-wedged
                    # device is itself a device wait: supervise it, so
                    # a hung fetch steps the job back instead of
                    # parking the host path unbounded
                    rows = self._supervised("fetch_resident", out_shares.to_numpy)
                    return host.aggregate(rows, np.asarray(mask))
                return host.aggregate(out_shares, mask)
            try:
                return self._aggregate_inner(out_shares, mask)
            except Exception as e:  # noqa: BLE001 - OOM filter inside
                if (
                    is_oom_error(e)
                    and getattr(e, "_janus_fixed_bucket", False)
                    and isinstance(out_shares, (DeviceRows, DeviceRowsChunks))
                ):
                    # A resident buffer re-dispatches at its own fixed
                    # bucket no matter the cap, so halving can't help —
                    # fetch and reduce THIS buffer on host instead of
                    # abandoning the device path engine-wide for an OOM
                    # specific to one oversized buffer.
                    log.warning(
                        "device OOM aggregating a fixed-bucket resident "
                        "buffer for %s; reducing it on host: %s",
                        self.inst.kind, e,
                    )
                    host = HostEngineCache(self.inst, self.verify_key)
                    return host.aggregate(out_shares.to_numpy(), np.asarray(mask))
                n = getattr(out_shares, "n", None) or np.asarray(mask).shape[0]
                self._handle_engine_error(e, n)

    def _aggregate_inner(self, out_shares, mask):
        p3 = self.p3

        if isinstance(out_shares, DeviceRowsChunks):
            # chunked out shares: per-chunk masked reduce, host merge
            p = p3.jf.MODULUS
            total = None
            off = 0
            for chunk in out_shares.chunks:
                part = self._aggregate_inner(chunk, np.asarray(mask)[off : off + chunk.n])
                off += chunk.n
                total = part if total is None else [
                    (a + b) % p for a, b in zip(total, part)
                ]
            return total

        def step(out_shares, mask):
            return p3.aggregate(out_shares, mask)

        fn = self._jit("aggregate", step)
        if isinstance(out_shares, DeviceRows):
            # device-resident path: the out shares are already on device
            # padded to their bucket — only the (tiny) mask moves.
            n = out_shares.n
            value = out_shares.value
            b = value[0].shape[0]
            vb = bucket_size(n)
            s = out_shares.offset
            if (s or vb < b) and s + vb <= b:
                # coalesced view: one jitted dynamic-slice + masked
                # reduce over the job's own bucket — reducing the whole
                # merged buffer once per co-batched job would multiply
                # the aggregate work by the round size. (Views whose
                # bucket would run past the buffer keep the full-width
                # mask path below: dynamic_slice clamps out-of-bounds
                # starts, which would silently shift rows.)
                def step_view(value, start, mask, _vb=vb):
                    v = tuple(
                        jax.lax.dynamic_slice_in_dim(x, start, _vb, axis=0)
                        for x in value
                    )
                    return p3.aggregate(v, mask)

                jit_name = f"aggregate_view_{vb}"
                fnv = self._jit(jit_name, step_view)
                mask_vb = np.zeros(vb, dtype=bool)
                mask_vb[:n] = np.asarray(mask, dtype=bool)
                count_h2d(int(mask_vb.nbytes))
                dispatch_b, dispatch_fixed = vb, True
                dispatch = lambda: fnv(value, np.int32(s), mask_vb)  # noqa: E731
            else:
                jit_name = "aggregate"
                full = np.zeros(b, dtype=bool)
                full[s : s + n] = np.asarray(mask, dtype=bool)
                count_h2d(int(full.nbytes))
                dispatch_b, dispatch_fixed = b, True
                dispatch = lambda: fn(value, full)  # noqa: E731
        else:
            n = mask.shape[0]
            cap = self.bucket_cap
            if cap is not None and n > cap:
                # host-staged rows past the HBM cap: cap-sized partial
                # reduces merged mod p on host
                p = p3.jf.MODULUS
                total = None
                for s in range(0, n, cap):
                    e = min(s + cap, n)
                    part = self._aggregate_inner(
                        _cut_rows(out_shares, s, e), np.asarray(mask)[s:e]
                    )
                    total = part if total is None else [
                        (a + b) % p for a, b in zip(total, part)
                    ]
                return total
            b = bucket_size(n, cap)
            jit_name = "aggregate"
            dispatch_b, dispatch_fixed = b, False
            host_args = pad_args(b, out_shares, mask)
            count_h2d(host_args)
            dispatch = lambda: fn(*host_args)  # noqa: E731
        from ..trace import span

        # PJRT raises allocation failures synchronously from the
        # dispatch; other device errors realize async at the fetch.
        # Both need the bucket annotation, so both live in this try.
        # to_ints forces the fetch, so the span bounds true device
        # wall time, not async dispatch. Watchdog-supervised: the fetch
        # is exactly where a wedged device parks the thread.
        def device_call():
            _engine_dispatch_failpoint()
            t_disp = time.monotonic()
            with span(
                "engine.aggregate.dispatch",
                vdaf=self.inst.kind,
                batch=n,
                bucket=dispatch_b,
            ):
                agg = dispatch()
                result = [int(x) for x in p3.jf.to_ints(agg)]
                count_d2h(len(result) * p3.jf.LIMBS * 8)
            self._record_dispatch(
                "aggregate", n, dispatch_b, time.monotonic() - t_disp,
                compile_key=(jit_name, dispatch_b),
            )
            return result

        try:
            return self._supervised("aggregate", device_call)
        except Exception as e:
            _annotate_dispatch_bucket(e, dispatch_b, fixed=dispatch_fixed)
            raise

    def aggregate_sparse(self, out_shares, mask, flat_idx):
        """Masked sparse aggregate: scatter-add every accepted report's
        blocks into a dense logical accumulator and fetch it — the
        classic-path analogue of the resident scatter-merge (helper
        accumulate and the resident-disabled leader land here). An OOM
        degrades to a host scatter over fetched rows instead of failing
        the job; other errors propagate like aggregate's."""
        from .. import metrics

        host = self._host()
        if host is not None:
            if isinstance(out_shares, (DeviceRows, DeviceRowsChunks)):
                rows = self._supervised("fetch_resident", out_shares.to_numpy)
                return host.aggregate_sparse(rows, np.asarray(mask), flat_idx)
            return host.aggregate_sparse(out_shares, mask, flat_idx)
        p3 = self.p3
        L = p3.circ.agg_output_len
        accept = np.asarray(mask, bool)
        idx = np.where(
            accept[:, None], np.asarray(flat_idx, np.int32), np.int32(L)
        ).astype(np.int32)
        n = idx.shape[0]
        n_rows = int(accept.sum())
        live = int((idx < L).sum())

        def device_call():
            _engine_dispatch_failpoint()
            t_disp = time.monotonic()
            acc = self._scatter_dispatch(self._zeros_row(L), out_shares, idx)
            result = [int(x) for x in p3.jf.to_ints(acc)]
            count_d2h(len(result) * p3.jf.LIMBS * 8)
            self._record_dispatch(
                "aggregate",
                n,
                bucket_size(n),
                time.monotonic() - t_disp,
                ledger_op="scatter_merge",
                compile_key=("scatter_merge", bucket_size(n)),
            )
            metrics.engine_scatter_rows_total.add(n_rows, vdaf=self.inst.kind)
            self._scatter_rows += n_rows
            if n_rows:
                occ = live / (n_rows * idx.shape[1])
                self._sparse_last_occupancy = occ
                metrics.engine_sparse_block_occupancy.set(occ, vdaf=self.inst.kind)
            return result

        try:
            return self._supervised("aggregate_sparse", device_call)
        except Exception as e:
            if not is_oom_error(e):
                _annotate_dispatch_bucket(e, bucket_size(n), fixed=True)
                raise
            log.warning(
                "sparse aggregate OOM at bucket %d; scattering on host",
                bucket_size(n),
                exc_info=True,
            )
            rows = (
                self._supervised("fetch_resident", out_shares.to_numpy)
                if isinstance(out_shares, (DeviceRows, DeviceRowsChunks))
                else out_shares
            )
            return _host_scatter_rows(p3.jf, rows, idx, L)

    # --- device-resident aggregate state (ISSUE 12; docs/ARCHITECTURE.md
    # "Resident aggregate state"). The engine owns the per-(task, batch
    # bucket) buffers and the device ops; the DRIVER owns flush policy
    # (interval / eviction / quarantine / drain all go through its
    # write-tx path — aggregation_job_driver.flush_resident_state). ---

    # process-wide device-byte bound on resident buffers; overflow
    # evicts this engine's LRU slots through the flush path. Env is the
    # operator override; janus_main applies the YAML `engine:` value.
    RESIDENT_MAX_BYTES = int(os.environ.get("JANUS_RESIDENT_MAX_BYTES", str(256 << 20)))

    def resident_ready(self) -> bool:
        """True while the device path serves. Resident accumulation is
        a device feature: under host fallback / quarantine the driver
        uses the classic per-job flush, so interim work is durable
        immediately (the quarantine-mid-job contract)."""
        return self._host() is None

    def _delta_shardings(self, ndim: int = 2):
        """out_shardings for pending-delta values ([kk, output_len], or
        [output_len] rows when ndim=1): the out-share COLUMNS shard over
        'sp' when the engine has a vector axis, so the resident
        accumulator lives sharded per device — scatter merges stay
        sharded and the gather happens only at the flush/take fetch
        (the parallel/api.py design note, now on the serving path).
        Engines without a vector axis keep the delta replicated; None on
        the single-device path."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        out_len = getattr(self.p3.circ, "output_len", 0)
        col = "sp" if (self.sp > 1 and out_len % self.sp == 0) else None
        spec = P(*((None,) * (ndim - 1) + (col,)))
        sh = NamedSharding(self.mesh, spec)
        return tuple(sh for _ in range(self.p3.jf.LIMBS))

    def aggregate_pending(self, out_shares, bucket_idx, k: int, flat_idx=None):
        """Per-bucket masked sums of one job's out shares as a DEVICE
        [k, output_len] value — ONE dispatch, one [n] int32 upload,
        nothing fetched (the classic path uploads a full n-bool mask
        and fetches the aggregate per bucket). k pads to the next power
        of two so the traced program specializes O(log k) times.
        Errors propagate: the driver falls back to the classic
        accumulate for OOM-class failures and steps back on hangs.

        `flat_idx` ([n, compact_len] int32 scatter targets) marks a
        block-sparse job: no device work happens here — the per-bucket
        scatter into the dense logical accumulator runs at merge time
        (SparsePendingDeltas explains why a compact pre-sum is wrong)."""
        p3 = self.p3
        if flat_idx is not None:
            L = p3.circ.agg_output_len
            return SparsePendingDeltas(
                out_shares,
                np.asarray(flat_idx, np.int32),
                np.asarray(bucket_idx, np.int32),
                k,
                L * p3.jf.LIMBS * 8,
                L,
            )
        kk = 1 << max(0, int(k - 1).bit_length())
        row_nbytes = p3.circ.output_len * p3.jf.LIMBS * 8

        n_rows = len(bucket_idx)

        def device_call():
            _engine_dispatch_failpoint()
            t_disp = time.monotonic()
            value = self._pending_dispatch(out_shares, np.asarray(bucket_idx, np.int32), kk)
            self._record_dispatch(
                "aggregate",
                n_rows,
                bucket_size(n_rows),
                time.monotonic() - t_disp,
                ledger_op="aggregate_pending",
                # the traced program specializes on the padded bucket
                # COUNT kk (agg_buckets_{kk}), not just the row bucket
                compile_key=("aggregate_pending", kk, bucket_size(n_rows)),
            )
            return value

        try:
            value = self._supervised("aggregate_pending", device_call)
        except Exception as e:
            _annotate_dispatch_bucket(e, kk, fixed=True)
            raise
        return PendingDeltas(value, k, row_nbytes)

    def _pending_dispatch(self, out_shares, bucket_idx, kk: int):
        p3 = self.p3
        if isinstance(out_shares, DeviceRowsChunks):
            total = None
            off = 0
            for chunk in out_shares.chunks:
                part = self._pending_dispatch(
                    chunk, bucket_idx[off : off + chunk.n], kk
                )
                off += chunk.n
                total = part if total is None else p3.jf.add(total, part)
            return total
        if isinstance(out_shares, DeviceRows):
            n = out_shares.n
            value = out_shares.value
            b = value[0].shape[0]
            vb = bucket_size(n)
            s = out_shares.offset
            if (s or vb < b) and s + vb <= b:
                # coalesced view: dynamic-slice the job's own bucket
                # (same window discipline as the aggregate view path)
                idx = np.full(vb, -1, np.int32)
                idx[:n] = bucket_idx

                def step_view(value, start, idx, _vb=vb, _kk=kk):
                    v = tuple(
                        jax.lax.dynamic_slice_in_dim(x, start, _vb, axis=0)
                        for x in value
                    )
                    return p3.aggregate_buckets(v, idx, _kk)

                fn = self._jit(
                    f"agg_buckets_view_{kk}_{vb}",
                    step_view,
                    out_shardings=self._delta_shardings(),
                )
                count_h2d(int(idx.nbytes))
                return fn(value, np.int32(s), idx)
            idx = np.full(b, -1, np.int32)
            idx[s : s + n] = bucket_idx

            def step_full(value, idx, _kk=kk):
                return p3.aggregate_buckets(value, idx, _kk)

            fn = self._jit(
                f"agg_buckets_{kk}", step_full, out_shardings=self._delta_shardings()
            )
            count_h2d(int(idx.nbytes))
            return fn(value, idx)
        # host limb rows (a round that degraded to host currency):
        # stage them — rare, and still one dispatch for all buckets
        n = bucket_idx.shape[0]
        bb = bucket_size(n)
        idx = np.full(bb, -1, np.int32)
        idx[:n] = bucket_idx
        (padded,) = pad_args(bb, out_shares)
        count_h2d((padded, idx))

        def step_host(value, idx, _kk=kk):
            return p3.aggregate_buckets(value, idx, _kk)

        fn = self._jit(
            f"agg_buckets_{kk}", step_host, out_shardings=self._delta_shardings()
        )
        return fn(padded, idx)

    def _resident_add(self, acc, row):
        """acc + row on device. Single-device: the accumulator buffer
        is DONATED so the merge is in place (no HBM growth per merge);
        CPU ignores donation, mesh dispatches ride the single-controller
        lane via _jit and keep the slot's column sharding — the merged
        accumulator never gathers until flush."""
        if self.mesh is not None:
            fn = self._jit(
                "resident_add",
                lambda a, r: self.p3.jf.add(a, r),
                out_shardings=self._delta_shardings(ndim=1),
            )
            return fn(acc, row)
        name = "resident_add"
        if name not in self._jits:
            p3 = self.p3
            donate = (0,) if jax.default_backend() != "cpu" else ()
            self._jits[name] = jax.jit(
                lambda a, r: p3.jf.add(a, r), donate_argnums=donate
            )
        return self._jits[name](acc, row)

    # --- block-sparse scatter-merge (ISSUE 17; docs/ARCHITECTURE.md
    # "Block-sparse aggregation"): verified reports' compact blocks
    # scatter-add into the dense logical accumulator by their PUBLIC
    # block indices. Sparse engines are single-device (see __init__). ---

    def _zeros_row(self, length: int):
        """Fresh dense logical accumulator: a zero field row on device."""
        return tuple(
            jnp.zeros(length, dtype=jnp.uint64) for _ in range(self.p3.jf.LIMBS)
        )

    def _scatter_fn(self):
        """Jitted scatter-add of per-report compact blocks into a dense
        [logical_len] accumulator (the ISSUE 17 headline kernel —
        vdaf.prio3_jax.scatter_rows). The accumulator is DONATED on
        real devices so repeated merges into one slot stay in place;
        jax.jit respecializes per (bucket, compact_len, logical_len)
        shape on its own."""
        name = "scatter_merge"
        if name not in self._jits:
            p3 = self.p3
            donate = (0,) if jax.default_backend() != "cpu" else ()
            self._jits[name] = jax.jit(
                lambda acc, values, idx: p3.scatter_rows(acc, values, idx),
                donate_argnums=donate,
            )
        return self._jits[name]

    def _scatter_dispatch(self, acc, out_shares, idx):
        """Scatter-add every report row of `out_shares` whose idx row is
        live into acc. idx: [n, compact_len] host int32, sentinel =
        logical_len drops a lane. Handles the three out-share
        currencies like _pending_dispatch; padding rows inside a device
        bucket get all-sentinel idx rows so their garbage never lands."""
        L = acc[0].shape[0]
        fn = self._scatter_fn()
        if isinstance(out_shares, DeviceRowsChunks):
            off = 0
            for chunk in out_shares.chunks:
                acc = self._scatter_dispatch(acc, chunk, idx[off : off + chunk.n])
                off += chunk.n
            return acc
        if isinstance(out_shares, DeviceRows):
            n = out_shares.n
            value = out_shares.value
            b = value[0].shape[0]
            s = out_shares.offset
            full = np.full((b, idx.shape[1]), np.int32(L), np.int32)
            full[s : s + n] = idx
            count_h2d(int(full.nbytes))
            return fn(acc, value, full)
        # host limb rows (a round that degraded to host currency)
        n = idx.shape[0]
        bb = bucket_size(n)
        (padded,) = pad_args(bb, out_shares)
        full = np.full((bb, idx.shape[1]), np.int32(L), np.int32)
        full[:n] = idx
        count_h2d((padded, full))
        return fn(acc, padded, full)

    def _sparse_slot_value(self, slot, deltas: "SparsePendingDeltas", j: int):
        """Scatter-add bucket j's report blocks into the slot's dense
        logical accumulator (zeros for a fresh slot / a raw delta
        fetch). One device dispatch, booked as a scatter_merge cost-
        ledger row; feeds the scatter metrics."""
        from .. import metrics

        L = deltas.logical_len
        sel = deltas.bucket_idx == j
        idx = np.where(sel[:, None], deltas.flat_idx, np.int32(L)).astype(np.int32)
        acc = self._zeros_row(L) if slot is None else slot.value
        n_rows = int(sel.sum())
        live = int((idx < L).sum())
        t_disp = time.monotonic()
        value = self._scatter_dispatch(acc, deltas.out_shares, idx)
        self._record_dispatch(
            "aggregate",
            n_rows,
            bucket_size(len(sel)),
            time.monotonic() - t_disp,
            ledger_op="scatter_merge",
            compile_key=("scatter_merge", bucket_size(len(sel))),
        )
        metrics.engine_scatter_rows_total.add(n_rows, vdaf=self.inst.kind)
        self._scatter_rows += n_rows
        if n_rows:
            occ = live / (n_rows * deltas.flat_idx.shape[1])
            self._sparse_last_occupancy = occ
            metrics.engine_sparse_block_occupancy.set(occ, vdaf=self.inst.kind)
        return value

    def resident_merge(self, entries, deltas: PendingDeltas) -> list[dict]:
        """Merge one job's committed deltas into the resident slots.

        entries: [(key, delta_row, report_count, interval)] — call only
        AFTER the job's write transaction committed (the post-commit
        discipline that makes a failed/retried step unable to
        double-merge: an uncommitted PendingDeltas is simply dropped).
        Returns flush records for slots LRU-evicted past
        RESIDENT_MAX_BYTES — already fetched and removed from device
        state; the caller MUST persist them through the write-tx path.
        """
        from ..messages import Interval

        sparse = isinstance(deltas, SparsePendingDeltas)
        evicted: list[ResidentSlot] = []
        merged: set = set()
        with self._resident_lock:
            try:
                for key, j, rows, interval in entries:
                    slot = self._resident.get(key)
                    if sparse:
                        # scatter-merge: blocks land straight in the
                        # (fresh or existing) dense logical accumulator
                        value = self._sparse_slot_value(slot, deltas, j)
                    if slot is None:
                        slot = ResidentSlot(
                            key,
                            value if sparse else deltas.row(j),
                            interval,
                            rows,
                            deltas.row_nbytes,
                        )
                        self._resident[key] = slot
                        _resident_bytes_add(slot.nbytes, self.inst.kind, +1)
                    else:
                        slot.value = (
                            value
                            if sparse
                            else self._resident_add(slot.value, deltas.row(j))
                        )
                        slot.interval = Interval.merged(slot.interval, interval)
                        slot.rows += rows
                        self._resident.move_to_end(key)
                    slot.last_used = time.monotonic()
                    self._resident_stats["merged_rows"] += rows
                    merged.add(key)
            except BaseException as e:
                # a mid-loop failure leaves a merged PREFIX on device —
                # report exactly which keys landed so the caller flushes
                # only the remainder (re-flushing a merged entry's delta
                # would double-count it when the slot later flushes)
                raise ResidentMergeError(frozenset(merged), e) from e
            self._resident_stats["merges"] += 1
            while resident_bytes_total() > self.RESIDENT_MAX_BYTES and self._resident:
                _, slot = self._resident.popitem(last=False)
                _resident_bytes_add(-slot.nbytes, self.inst.kind, -1)
                evicted.append(slot)
                self._resident_stats["evictions"] += 1
            if not evicted:
                return []
            try:
                return self._fetch_slots_locked(evicted)
            except BaseException:
                for slot in evicted:  # restore: eviction must not LOSE state
                    self._resident[slot.key] = slot
                    _resident_bytes_add(slot.nbytes, self.inst.kind, +1)
                # the DELTAS all merged — raising here would make the
                # caller's merge-failed recovery re-flush them (double
                # count). The eviction is merely DEFERRED: bytes stay
                # over the cap, the next merge/flusher pass retries.
                self._resident_stats["eviction_deferred"] += 1
                log.warning(
                    "resident eviction fetch failed for %s; eviction deferred "
                    "(state restored, retried next pass)",
                    self.inst.kind,
                    exc_info=True,
                )
                return []

    def resident_take(self, keys=None) -> list[dict]:
        """Pop (all, or `keys`) resident slots and fetch their encoded
        shares for a flush. On a fetch failure every popped slot is
        RESTORED and the error propagates — resident state is never
        dropped because the device was slow once; the flusher retries
        after the canary restores the path."""
        with self._resident_lock:
            take = (
                list(self._resident.keys())
                if keys is None
                else [k for k in keys if k in self._resident]
            )
            slots = [self._resident.pop(k) for k in take]
            for slot in slots:
                _resident_bytes_add(-slot.nbytes, self.inst.kind, -1)
            if not slots:
                return []
            try:
                recs = self._fetch_slots_locked(slots)
            except BaseException:
                for slot in slots:
                    self._resident[slot.key] = slot
                    _resident_bytes_add(slot.nbytes, self.inst.kind, +1)
                raise
            self._resident_stats["takes"] += len(slots)
            return recs

    def fetch_delta_records(self, entries, deltas) -> list[dict]:
        """Supervised d2h fetch of a job's raw delta rows — the driver's
        merge-failed recovery path. Bounded like every other resident
        fetch: a raw to_ints() here would park the commit worker in
        native code forever on exactly the wedged device that likely
        just failed the merge. Sparse deltas scatter into a zero dense
        logical row first (the flush currency is always dense)."""
        p3 = self.p3
        sparse = isinstance(deltas, SparsePendingDeltas)

        def fetch():
            out = []
            for key, j, rows, interval in entries:
                value = (
                    self._sparse_slot_value(None, deltas, j)
                    if sparse
                    else deltas.row(j)
                )
                out.append(
                    {
                        "key": key,
                        "share": [int(x) for x in p3.jf.to_ints(value)],
                        "rows": rows,
                        "interval": interval,
                    }
                )
            return out

        recs = self._supervised("resident_delta_fetch", fetch)
        count_d2h(deltas.row_nbytes * len(entries))
        return recs

    def _fetch_slots_locked(self, slots: list) -> list[dict]:
        """Supervised d2h fetch of popped slots (callers hold
        _resident_lock; a watchdog-abandoned fetch raises back to them
        with the lock released by their unwind)."""
        p3 = self.p3

        def fetch():
            out = []
            for slot in slots:
                out.append(
                    {
                        "key": slot.key,
                        "share": [int(x) for x in p3.jf.to_ints(slot.value)],
                        "rows": slot.rows,
                        "interval": slot.interval,
                    }
                )
            return out

        recs = self._supervised("resident_fetch", fetch)
        count_d2h(sum(slot.nbytes for slot in slots))
        return recs

    def has_resident(self) -> bool:
        """True while unflushed resident slots live on this engine —
        the process engine-cache LRU must not evict such an engine (the
        flusher only walks CACHED engines; dropping one silently loses
        the share bytes and leaks the resident-bytes ledger)."""
        with self._resident_lock:
            return bool(self._resident)

    def would_coalesce(self, n: int) -> bool:
        """True when a leader init of n rows would enter a coalesced
        round (the _leader_init_entry routing predicate). A prestage
        for such a job is wasted whenever the round MERGES — the merged
        round re-stages from concatenated host columns — so a parallel
        device lane declines prestaging exactly these jobs."""
        cap = self.bucket_cap
        return bool(
            self._coalesce
            and n <= self.COALESCE_MAX_JOB
            and (cap is None or n <= cap)
        )

    def resident_status(self) -> dict:
        with self._resident_lock:
            out = {
                "vdaf": self.inst.kind,
                "buffers": len(self._resident),
                "bytes": sum(s.nbytes for s in self._resident.values()),
                **dict(self._resident_stats),
            }
            if self.sparse:
                circ = self.p3.circ
                out["sparse"] = {
                    "logical_length": circ.logical_length,
                    "block_size": circ.block_size,
                    "max_blocks": circ.max_blocks,
                    "scatter_rows": self._scatter_rows,
                    "block_occupancy": self._sparse_last_occupancy,
                }
            return out


def _host_scatter_rows(jf, rows, idx, L: int) -> list[int]:
    """Host scatter-add over fetched [n, compact_len] limb rows — the
    OOM degrade for EngineCache.aggregate_sparse. idx carries the same
    sentinel convention as the device kernel (>= L drops the lane)."""
    vals = jf.to_ints(tuple(np.asarray(r) for r in rows))
    p = jf.MODULUS
    agg = [0] * L
    n, cm = idx.shape
    for i in range(n):
        for c in range(cm):
            fx = int(idx[i, c])
            if 0 <= fx < L:
                agg[fx] = (agg[fx] + int(vals[i, c])) % p
    return agg


class _HostP3:
    """Duck-typed `.p3` for HostEngineCache (callers use engine.p3.jf
    for the columnar codecs)."""

    def __init__(self, jf):
        self.jf = jf


class HostEngineCache:
    """Per-report host engine for draft-mode (spec-framing) tasks.

    Same surface as EngineCache but loops reports through the scalar
    host Prio3 — mirroring the reference's own per-report CPU loop
    (aggregation_job_driver.rs:329-402, aggregator.rs:1775-1826). The
    TPU engine only implements the fast framing; conformant tasks trade
    throughput for cross-implementation compatibility.
    """

    def __init__(self, inst: VdafInstance, verify_key: bytes):
        from ..vdaf.engine import jf_for
        from ..vdaf.registry import circuit_for, prio3_host

        self.inst = inst
        self.verify_key = verify_key
        self.host = prio3_host(inst)
        self.circ = circuit_for(inst)
        self.jf = jf_for(self.circ)
        self.p3 = _HostP3(self.jf)

    # --- lane <-> host-int conversions ---
    def _row_ints(self, limbs, i) -> list[int]:
        if len(limbs) == 1:
            return [int(x) for x in np.asarray(limbs[0])[i]]
        lo = np.asarray(limbs[0])[i]
        hi = np.asarray(limbs[1])[i]
        return [int(l) | (int(h) << 64) for l, h in zip(lo, hi)]

    def _ints_to_limbs(self, rows: list[list[int] | None], n: int):
        batch = len(rows)
        out = tuple(np.zeros((batch, n), dtype=np.uint64) for _ in range(self.jf.LIMBS))
        for i, r in enumerate(rows):
            if r is None:
                continue
            for j, v in enumerate(r):
                out[0][i, j] = np.uint64(v & 0xFFFFFFFFFFFFFFFF)
                if self.jf.LIMBS == 2:
                    out[1][i, j] = np.uint64(v >> 64)
        return out

    @staticmethod
    def _row_bytes(lanes, i) -> bytes:
        return np.asarray(lanes, dtype="<u8")[i].tobytes()

    def helper_init(self, nonce_lanes, public_parts, helper_seeds, blinds, ver0, part0, ok_mask):
        from ..vdaf.reference import HelperShare, PrepShare, VdafError

        n = nonce_lanes.shape[0]
        uses_jr = self.host.uses_joint_rand
        out_rows: list[list[int] | None] = [None] * n
        accept = np.zeros(n, dtype=bool)
        prep_msg = np.zeros((n, 2), dtype=np.uint64)
        for i in range(n):
            if not ok_mask[i]:
                continue
            nonce = self._row_bytes(nonce_lanes, i)
            share = HelperShare(
                self._row_bytes(helper_seeds, i),
                self._row_bytes(blinds, i) if uses_jr else None,
            )
            parts = (
                [self._row_bytes(public_parts[:, 0], i), self._row_bytes(public_parts[:, 1], i)]
                if uses_jr
                else []
            )
            try:
                state1, ps1 = self.host.prepare_init(
                    self.verify_key, 1, nonce, parts, share
                )
                ps0 = PrepShare(
                    self._row_ints(ver0, i),
                    self._row_bytes(part0, i) if uses_jr else None,
                )
                msg = self.host.prepare_shares_to_prep([ps0, ps1])
                self.host.prepare_next(state1, msg)
            except VdafError:
                continue
            out_rows[i] = state1.out_share
            accept[i] = True
            if uses_jr:
                prep_msg[i] = np.frombuffer(msg, dtype="<u8")
        out1 = self._ints_to_limbs(out_rows, self.circ.output_len)
        return out1, accept, prep_msg

    def leader_init(
        self, nonce_lanes, public_parts, meas, proof, blind0, ok=None, prestaged=None
    ):
        from ..vdaf.reference import LeaderShare

        if prestaged is not None:
            # signature parity with EngineCache: the pipeline's
            # device_init passes prestaged= unconditionally; a host
            # engine has no device path, so free the transfer's buffers
            prestaged.discard()

        n = nonce_lanes.shape[0]
        uses_jr = self.host.uses_joint_rand
        out_rows: list[list[int] | None] = [None] * n
        ver_rows: list[list[int] | None] = [None] * n
        seed0 = np.zeros((n, 2), dtype=np.uint64) if uses_jr else None
        part0 = np.zeros((n, 2), dtype=np.uint64) if uses_jr else None
        for i in range(n):
            if ok is not None and not ok[i]:
                continue  # don't pay scalar FLP prepare for failed lanes
            nonce = self._row_bytes(nonce_lanes, i)
            share = LeaderShare(
                self._row_ints(meas, i),
                self._row_ints(proof, i),
                self._row_bytes(blind0, i) if uses_jr else None,
            )
            parts = (
                [self._row_bytes(public_parts[:, 0], i), self._row_bytes(public_parts[:, 1], i)]
                if uses_jr
                else []
            )
            state, ps = self.host.prepare_init(self.verify_key, 0, nonce, parts, share)
            out_rows[i] = state.out_share
            ver_rows[i] = ps.verifier_share
            if uses_jr:
                seed0[i] = np.frombuffer(state.corrected_joint_rand_seed, dtype="<u8")
                part0[i] = np.frombuffer(ps.joint_rand_part, dtype="<u8")
        out0 = self._ints_to_limbs(out_rows, self.circ.output_len)
        ver0 = self._ints_to_limbs(ver_rows, self.circ.verifier_len)
        return out0, seed0, ver0, part0

    def aggregate(self, out_shares, mask):
        p = self.circ.FIELD.MODULUS
        agg = [0] * self.circ.output_len
        for i in range(mask.shape[0]):
            if not mask[i]:
                continue
            row = self._row_ints(out_shares, i)
            agg = [(a + b) % p for a, b in zip(agg, row)]
        return agg

    def aggregate_sparse(self, out_shares, mask, flat_idx):
        """Host scatter-add of accepted reports' compact rows into a
        dense logical aggregate (same contract as the device
        EngineCache.aggregate_sparse)."""
        p = self.circ.FIELD.MODULUS
        L = getattr(self.circ, "agg_output_len", self.circ.output_len)
        agg = [0] * L
        idx = np.asarray(flat_idx)
        for i in range(mask.shape[0]):
            if not mask[i]:
                continue
            row = self._row_ints(out_shares, i)
            for v, fx in zip(row, idx[i]):
                fx = int(fx)
                if 0 <= fx < L:
                    agg[fx] = (agg[fx] + int(v)) % p
        return agg


def _build_engine(inst: VdafInstance, verify_key: bytes):
    if inst.xof_mode != "fast":
        # draft (VDAF-07) framing: device engine for every circuit
        # whose sponge streams fit vdaf.draft_jax MAX_STREAM_BLOCKS
        # (160k since r5 — covers the north-star len=100k; the r4
        # "latency knee" was a flat-scan pathology, BASELINE.md "Draft
        # mode"); truly huge streams keep the scalar host loop
        try:
            prio3_batched(inst)
        except ValueError:
            from ..metrics import engine_backend_state

            for s in EngineCache.BACKEND_STATES:
                engine_backend_state.set(
                    1.0 if s == "host" else 0.0, vdaf=inst.kind, state=s
                )
            return HostEngineCache(inst, verify_key)
    return EngineCache(inst, verify_key)


# LRU over live engines. Formerly a bare functools.lru_cache; the
# hand-rolled variant exists so hit/miss/entry counts export as
# metrics and /statusz can walk the live engines (bucket caps, backend
# state, OOM history) — lru_cache hides its table.
_ENGINE_CACHE_MAX = 256
_engine_cache_lock = threading.Lock()
_engine_cache: "OrderedDict[tuple, object]" = OrderedDict()


def engine_cache(inst: VdafInstance, verify_key: bytes):
    from .. import metrics

    key = (inst, verify_key)
    with _engine_cache_lock:
        eng = _engine_cache.get(key)
        if eng is not None:
            _engine_cache.move_to_end(key)
            metrics.engine_cache_hits.add()
            return eng
    metrics.engine_cache_misses.add()
    # build outside the lock: construction touches jax (mesh setup) and
    # must not serialize against lookups; a concurrent double-build
    # resolves first-insert-wins below
    eng = _build_engine(inst, verify_key)
    with _engine_cache_lock:
        cur = _engine_cache.get(key)
        if cur is not None:
            return cur
        _engine_cache[key] = eng
        while len(_engine_cache) > _ENGINE_CACHE_MAX:
            # evict the oldest entry that holds NO resident aggregate
            # state: the flusher only walks cached engines, so dropping
            # one with live slots silently loses the share bytes and
            # leaks its bytes in the resident ledger forever
            victim = None
            for k, e in _engine_cache.items():
                if not (isinstance(e, EngineCache) and e.has_resident()):
                    victim = k
                    break
            if victim is None:
                # every entry holds unflushed state (bounded by
                # RESIDENT_MAX_BYTES): keep them all until a flush
                # pass drains one, then the next insert evicts
                break
            _engine_cache.pop(victim)
        metrics.engine_cache_entries.set(float(len(_engine_cache)))
    return eng


def _engine_cache_clear() -> None:
    from .. import metrics

    global _resident_bytes_total
    with _engine_cache_lock:
        _engine_cache.clear()
    # shared cross-task coalescers, the mesh dispatch lane's counters
    # and the resident byte ledger follow the cache lifetime (tests
    # clear between modules for isolation)
    _clear_shared_coalescers()
    _MESH_QUEUE.reset_for_tests()
    with _resident_bytes_lock:
        _resident_bytes_total = 0
        kinds = list(_resident_buffer_counts)
        _resident_buffer_counts.clear()
    metrics.engine_resident_bytes.set(0.0)
    for kind in kinds:
        metrics.engine_resident_buffers.set(0.0, vdaf=kind)
    metrics.engine_cache_entries.set(0.0)


def live_engines() -> list["EngineCache"]:
    """Live DEVICE engines in the process cache (host engines hold no
    resident state) — the resident flusher/drain walk this."""
    with _engine_cache_lock:
        return [e for e in _engine_cache.values() if isinstance(e, EngineCache)]


def shutdown_engines(timeout_s: float = 2.0) -> None:
    """Process-teardown: stop every live engine's canary loop (bounded)
    so no probe's native device work races interpreter finalization.
    Called from janus_main's finally, before the watchdog drain."""
    with _engine_cache_lock:
        engines = list(_engine_cache.values())
    for eng in engines:
        stop = getattr(eng, "stop_canary", None)
        if stop is not None:
            try:
                stop(timeout_s)
            except Exception:
                log.exception("stopping canary for %s failed", eng.inst.kind)


# lru_cache-compatible surface (tests/conftest.py calls cache_clear
# between modules to drop compiled callables)
engine_cache.cache_clear = _engine_cache_clear


def engine_cache_status() -> dict:
    """Live engine-cache snapshot for /statusz: per-engine bucket cap,
    backend state, geometry, and recent OOM history."""
    with _engine_cache_lock:
        engines = list(_engine_cache.values())
    out = []
    for eng in engines:
        if isinstance(eng, HostEngineCache):
            out.append(
                {
                    "vdaf": eng.inst.kind,
                    "xof_mode": eng.inst.xof_mode,
                    "backend": "host",
                }
            )
            continue
        ent = {
            "vdaf": eng.inst.kind,
            "xof_mode": eng.inst.xof_mode,
            "backend": eng._backend_state(),
            "quarantined": eng._quarantined,
            "bucket_cap": eng.bucket_cap,
            "initial_bucket_cap": eng._initial_bucket_cap,
            "dp": eng.dp,
            "sp": eng.sp,
            "tile_elems": eng.tile_elems,
            "coalesce_round_rows": eng._co_leader._max_rows,
            "cross_task_coalesce": XTASK_COALESCE,
            "resident": eng.resident_status(),
            "oom_history": list(eng.oom_history),
        }
        try:
            from ..vdaf.engine import describe_engine_geometry

            ent["geometry"] = describe_engine_geometry(eng.p3.bc)
        except Exception:
            pass
        out.append(ent)
    return {"entries": len(engines), "max_entries": _ENGINE_CACHE_MAX, "engines": out}


def resident_accumulators_status() -> dict:
    """/statusz `resident_accumulators` section: process-wide resident
    aggregate state (bytes, per-engine buffer counts, merge/eviction/
    flush-take counters)."""
    with _engine_cache_lock:
        engines = list(_engine_cache.values())
    device_engines = [e for e in engines if not isinstance(e, HostEngineCache)]
    return {
        "total_bytes": resident_bytes_total(),
        "max_bytes": EngineCache.RESIDENT_MAX_BYTES,
        "cross_task_coalesce": XTASK_COALESCE,
        # block-sparse rollup (ISSUE 17): scatter-merge activity across
        # every sparse engine — scrape_check pins this line's presence
        "sparse": {
            "engines": sum(1 for e in device_engines if getattr(e, "sparse", False)),
            "scatter_rows": sum(getattr(e, "_scatter_rows", 0) for e in device_engines),
        },
        "engines": [eng.resident_status() for eng in device_engines],
    }


def mesh_status() -> dict:
    """/statusz `mesh` section: device topology, per-engine (dp, sp)
    geometry, and the single-controller dispatch lane's live counters
    (scripts/scrape_check.py pins the shape)."""
    devices = None
    try:
        from jax._src import xla_bridge

        # report the topology only if some engine already initialized
        # the backend — a bare statusz probe must not pay (or trigger)
        # device discovery
        if getattr(xla_bridge, "_backends", None):
            devices = len(jax.devices())
    except Exception:
        devices = None
    with _engine_cache_lock:
        engines = [e for e in _engine_cache.values() if isinstance(e, EngineCache)]
    return {
        "devices": devices,
        "queue": _MESH_QUEUE.status(),
        "engines": [
            {
                "vdaf": e.inst.kind,
                "dp": e.dp,
                "sp": e.sp,
                "mesh": e.mesh is not None,
                "sharded_resident": e.sp > 1,
                "fallback_reason": e.mesh_fallback_reason,
            }
            for e in engines
        ],
    }


from ..statusz import register_status_provider as _register_status_provider

_register_status_provider("engine_cache", engine_cache_status)
_register_status_provider("resident_accumulators", resident_accumulators_status)
_register_status_provider("mesh", mesh_status)
