"""Aggregation job creator (leader only).

Equivalent of reference aggregator/src/aggregator/aggregation_job_creator.rs:
44-705: periodically sweep every leader task, pack unaggregated client
reports into aggregation jobs of [min, max] size, and create the job +
report-aggregation rows. Fixed-size tasks additionally assign reports
to outstanding batches (BatchCreator, batch_creator.rs:32).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..datastore.models import (
    AggregationJobModel,
    AggregationJobState,
    OutstandingBatch,
    ReportAggregationModel,
    ReportAggregationState,
)
from ..datastore.store import Datastore
from ..messages import (
    AggregationJobId,
    BatchId,
    Duration,
    Interval,
    PartialBatchSelector,
    Role,
    Time,
    TimeInterval,
)
from ..task import Task
from ..trace import current_traceparent, span


@dataclass
class AggregationJobCreatorConfig:
    """reference aggregation_job_creator.rs:65-80."""

    min_aggregation_job_size: int = 1
    max_aggregation_job_size: int = 1024
    # worker threads for the per-task sweep (the reference runs a tokio
    # task per DAP task, aggregation_job_creator.rs:210); 1 = serial
    max_concurrent_tasks: int = 8


class AggregationJobCreator:
    def __init__(self, ds: Datastore, cfg: AggregationJobCreatorConfig | None = None):
        self.ds = ds
        self.cfg = cfg or AggregationJobCreatorConfig()

    def run_once(self) -> int:
        """Sweep all leader tasks once; returns number of jobs created.

        Tasks sweep concurrently in a thread pool (the reference spawns
        one worker per task, aggregation_job_creator.rs:210); each
        task's claim/write transactions are independent, so cross-task
        serialization would bound many-task deployments by the slowest
        task."""
        tasks = self.ds.run_tx(lambda tx: tx.get_tasks(), "creator_tasks")
        eligible = [
            t
            for t in tasks
            if t.role == Role.LEADER
            # parameterized VDAFs (Poplar1): reports aggregate once PER
            # collection parameter; jobs are created by the collection
            # job driver when the parameter is known
            and not t.vdaf.has_aggregation_parameter
        ]
        if len(eligible) <= 1 or self.cfg.max_concurrent_tasks <= 1:
            return sum(self.create_jobs_for_task(t) for t in eligible)
        from concurrent.futures import ThreadPoolExecutor

        workers = min(self.cfg.max_concurrent_tasks, len(eligible))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            return sum(pool.map(self.create_jobs_for_task, eligible))

    def create_jobs_for_task(self, task: Task) -> int:
        if task.query_type.code == TimeInterval.CODE:
            return self._create_time_interval_jobs(task)
        return self._create_fixed_size_jobs(task)

    def _claim(self, task: Task):
        return self.ds.run_tx(
            lambda tx: tx.get_unaggregated_client_reports_for_task(
                task.task_id, self.cfg.max_aggregation_job_size
            ),
            "creator_claim",
        )

    def _create_time_interval_jobs(self, task: Task) -> int:
        """reference create_aggregation_jobs_for_time_interval_task_no_param
        (:511)."""
        created = 0
        while True:
            claimed = self._claim(task)
            if len(claimed) < max(1, self.cfg.min_aggregation_job_size):
                # too few: release claim, try next sweep (reference keeps
                # sub-min reports unaggregated)
                if claimed:
                    self.ds.run_tx(
                        lambda tx: tx.mark_reports_unaggregated(
                            task.task_id, [r for r, _ in claimed]
                        ),
                        "creator_release",
                    )
                return created
            self._write_job(task, claimed, PartialBatchSelector.time_interval())
            created += 1
            if len(claimed) < self.cfg.max_aggregation_job_size:
                return created

    def _create_fixed_size_jobs(self, task: Task) -> int:
        """Batch packing toward max_batch_size (reference
        batch_creator.rs:140-330): claimed reports top up the fullest
        unfilled outstanding batch of their time bucket first, spill
        into new batches, and batches are marked filled exactly when
        their assigned size reaches max_batch_size."""
        created = 0
        max_bs = task.query_type.max_batch_size or self.cfg.max_aggregation_job_size
        while True:
            claimed = self._claim(task)
            if len(claimed) < max(1, self.cfg.min_aggregation_job_size):
                if claimed:
                    self.ds.run_tx(
                        lambda tx: tx.mark_reports_unaggregated(
                            task.task_id, [r for r, _ in claimed]
                        ),
                        "creator_release",
                    )
                return created

            window = task.query_type.batch_time_window_size
            by_bucket: dict = {}
            for rid, t in claimed:
                bucket = t.to_batch_interval_start(window) if window else None
                by_bucket.setdefault(bucket, []).append((rid, t))

            min_job = max(1, self.cfg.min_aggregation_job_size)

            def assign_and_write(tx):
                """One transaction: batch accounting AND job rows commit
                together (a crash between them would otherwise corrupt
                outstanding-batch sizes and orphan claimed reports)."""
                n_jobs = 0
                for bucket, group in by_bucket.items():
                    remaining = list(group)
                    obs = tx.get_outstanding_batches(task.task_id, bucket)
                    while remaining:
                        if obs:
                            ob = obs.pop(0)
                            bid, size = ob.batch_id, ob.size
                        else:
                            bid, size = None, 0  # a new batch, created lazily
                        take = min(max_bs - size, len(remaining))
                        if take <= 0:
                            tx.mark_outstanding_batch_filled(task.task_id, bid)
                            continue
                        if take < min_job and size + take < max_bs:
                            # too small for a job and doesn't complete the
                            # batch: leave these reports for a later pass
                            tx.mark_reports_unaggregated(
                                task.task_id, [r for r, _ in remaining]
                            )
                            break
                        if bid is None:
                            bid = BatchId(secrets.token_bytes(32))
                            tx.put_outstanding_batch(
                                OutstandingBatch(task.task_id, bid, bucket)
                            )
                        chunk, remaining = remaining[:take], remaining[take:]
                        new_size = tx.add_to_outstanding_batch(task.task_id, bid, take)
                        if new_size >= max_bs:
                            tx.mark_outstanding_batch_filled(task.task_id, bid)
                        self._write_job_in_tx(
                            tx, task, chunk, PartialBatchSelector.fixed_size(bid)
                        )
                        n_jobs += 1
                return n_jobs

            n_jobs = self.ds.run_tx(assign_and_write, "creator_fixed_assign")
            created += n_jobs
            if n_jobs == 0:
                # every bucket deferred (sub-min chunks): the same reports
                # would be re-claimed forever — stop this pass
                return created
            if len(claimed) < self.cfg.max_aggregation_job_size:
                return created

    def _write_job(self, task: Task, claimed, pbs: PartialBatchSelector) -> None:
        self.ds.run_tx(
            lambda tx: self._write_job_in_tx(tx, task, claimed, pbs), "creator_write_job"
        )

    def _write_job_in_tx(self, tx, task: Task, claimed, pbs: PartialBatchSelector) -> None:
        job_id = AggregationJobId(secrets.token_bytes(16))
        times = [t.seconds for _, t in claimed]
        # the job's trace is rooted HERE: the creating span's context is
        # persisted in the row, and both the leader driver (every step,
        # every restart) and the helper (via the propagated traceparent)
        # attach their spans under it — one trace id per job, across
        # processes and time (docs/OBSERVABILITY.md)
        with span("creator.create_job", reports=len(claimed)):
            job = AggregationJobModel(
                task.task_id,
                job_id,
                b"",
                pbs.to_bytes(),
                Interval(Time(min(times)), Duration(max(times) - min(times) + 1)),
                AggregationJobState.IN_PROGRESS,
                0,
                trace_context=current_traceparent(),
            )
            tx.put_aggregation_job(job)
            for i, (rid, t) in enumerate(claimed):
                tx.put_report_aggregation(
                    ReportAggregationModel(
                        task.task_id, job_id, rid, t, i, ReportAggregationState.START
                    )
                )
