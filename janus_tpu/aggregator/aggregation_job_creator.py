"""Aggregation job creator (leader only).

Equivalent of reference aggregator/src/aggregator/aggregation_job_creator.rs:
44-705: periodically sweep every leader task, pack unaggregated client
reports into aggregation jobs of [min, max] size, and create the job +
report-aggregation rows. Fixed-size tasks additionally assign reports
to outstanding batches (BatchCreator, batch_creator.rs:32).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..datastore.models import (
    AggregationJobModel,
    AggregationJobState,
    OutstandingBatch,
    ReportAggregationModel,
    ReportAggregationState,
)
from ..datastore.store import Datastore
from ..messages import (
    AggregationJobId,
    BatchId,
    Duration,
    Interval,
    PartialBatchSelector,
    Role,
    Time,
    TimeInterval,
)
from ..task import Task


@dataclass
class AggregationJobCreatorConfig:
    """reference aggregation_job_creator.rs:65-80."""

    min_aggregation_job_size: int = 1
    max_aggregation_job_size: int = 1024


class AggregationJobCreator:
    def __init__(self, ds: Datastore, cfg: AggregationJobCreatorConfig | None = None):
        self.ds = ds
        self.cfg = cfg or AggregationJobCreatorConfig()

    def run_once(self) -> int:
        """Sweep all leader tasks once; returns number of jobs created."""
        tasks = self.ds.run_tx(lambda tx: tx.get_tasks(), "creator_tasks")
        created = 0
        for task in tasks:
            if task.role != Role.LEADER:
                continue
            created += self.create_jobs_for_task(task)
        return created

    def create_jobs_for_task(self, task: Task) -> int:
        if task.query_type.code == TimeInterval.CODE:
            return self._create_time_interval_jobs(task)
        return self._create_fixed_size_jobs(task)

    def _claim(self, task: Task):
        return self.ds.run_tx(
            lambda tx: tx.get_unaggregated_client_reports_for_task(
                task.task_id, self.cfg.max_aggregation_job_size
            ),
            "creator_claim",
        )

    def _create_time_interval_jobs(self, task: Task) -> int:
        """reference create_aggregation_jobs_for_time_interval_task_no_param
        (:511)."""
        created = 0
        while True:
            claimed = self._claim(task)
            if len(claimed) < max(1, self.cfg.min_aggregation_job_size):
                # too few: release claim, try next sweep (reference keeps
                # sub-min reports unaggregated)
                if claimed:
                    self.ds.run_tx(
                        lambda tx: tx.mark_reports_unaggregated(
                            task.task_id, [r for r, _ in claimed]
                        ),
                        "creator_release",
                    )
                return created
            self._write_job(task, claimed, PartialBatchSelector.time_interval())
            created += 1
            if len(claimed) < self.cfg.max_aggregation_job_size:
                return created

    def _create_fixed_size_jobs(self, task: Task) -> int:
        """Greedy batch packing toward max_batch_size (reference
        batch_creator.rs:140-330, simplified: one outstanding batch per
        time bucket)."""
        created = 0
        max_bs = task.query_type.max_batch_size or self.cfg.max_aggregation_job_size
        while True:
            claimed = self._claim(task)
            if len(claimed) < max(1, self.cfg.min_aggregation_job_size):
                if claimed:
                    self.ds.run_tx(
                        lambda tx: tx.mark_reports_unaggregated(
                            task.task_id, [r for r, _ in claimed]
                        ),
                        "creator_release",
                    )
                return created

            def assign(tx):
                window = task.query_type.batch_time_window_size
                bucket = (
                    claimed[0][1].to_batch_interval_start(window) if window else None
                )
                obs = tx.get_outstanding_batches(task.task_id, bucket)
                if obs:
                    return obs[0].batch_id
                bid = BatchId(secrets.token_bytes(32))
                tx.put_outstanding_batch(OutstandingBatch(task.task_id, bid, bucket))
                return bid

            batch_id = self.ds.run_tx(assign, "creator_fixed_assign")
            self._write_job(task, claimed, PartialBatchSelector.fixed_size(batch_id))
            created += 1
            if len(claimed) >= max_bs:
                self.ds.run_tx(
                    lambda tx: tx.mark_outstanding_batch_filled(task.task_id, batch_id),
                    "creator_fixed_fill",
                )
            if len(claimed) < self.cfg.max_aggregation_job_size:
                return created

    def _write_job(self, task: Task, claimed, pbs: PartialBatchSelector) -> None:
        job_id = AggregationJobId(secrets.token_bytes(16))
        times = [t.seconds for _, t in claimed]
        job = AggregationJobModel(
            task.task_id,
            job_id,
            b"",
            pbs.to_bytes(),
            Interval(Time(min(times)), Duration(max(times) - min(times) + 1)),
            AggregationJobState.IN_PROGRESS,
            0,
        )
        ras = [
            ReportAggregationModel(
                task.task_id, job_id, rid, t, i, ReportAggregationState.START
            )
            for i, (rid, t) in enumerate(claimed)
        ]

        def write(tx):
            tx.put_aggregation_job(job)
            for ra in ras:
                tx.put_report_aggregation(ra)

        self.ds.run_tx(write, "creator_write_job")
