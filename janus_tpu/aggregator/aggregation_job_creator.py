"""Aggregation job creator (leader only).

Equivalent of reference aggregator/src/aggregator/aggregation_job_creator.rs:
44-705: periodically sweep every leader task, pack unaggregated client
reports into aggregation jobs of [min, max] size, and create the job +
report-aggregation rows. Fixed-size tasks additionally assign reports
to outstanding batches (BatchCreator, batch_creator.rs:32).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..datastore.models import (
    AggregationJobModel,
    AggregationJobState,
    OutstandingBatch,
    ReportAggregationModel,
    ReportAggregationState,
)
from ..datastore.store import Datastore
from ..messages import (
    AggregationJobId,
    BatchId,
    Duration,
    Interval,
    PartialBatchSelector,
    Role,
    Time,
    TimeInterval,
)
from ..task import Task
from ..trace import current_traceparent, span


@dataclass
class AggregationJobCreatorConfig:
    """reference aggregation_job_creator.rs:65-80."""

    min_aggregation_job_size: int = 1
    max_aggregation_job_size: int = 1024
    # worker threads for the per-task sweep (the reference runs a tokio
    # task per DAP task, aggregation_job_creator.rs:210); 1 = serial
    max_concurrent_tasks: int = 8


class AggregationJobCreator:
    def __init__(
        self,
        ds: Datastore,
        cfg: AggregationJobCreatorConfig | None = None,
        fleet=None,
    ):
        self.ds = ds
        self.cfg = cfg or AggregationJobCreatorConfig()
        # fleet shard preference (config.FleetConfig; docs/
        # ARCHITECTURE.md "Running a fleet"): a creator replica sweeps
        # its own shard's tasks every pass, and a FOREIGN shard's task
        # only once its unaggregated backlog has sat NONEMPTY for
        # steal_after_secs — so creator replicas stay off each other's
        # tasks while a dead replica's tasks still get jobs created.
        # Report claims are atomic either way; sharding is a
        # contention/efficiency predicate, never a correctness one.
        self.fleet = fleet
        # foreign-task steal timers: task_id -> (clock seconds when
        # THIS replica started the no-progress window, the task's
        # aggregated-report count at that moment, last probe time —
        # the progress probe runs at steal_after cadence). The stored
        # client_time is truncated to the task's time_precision
        # (hours, typically), so a report's own timestamp can NOT
        # measure how long work has been waiting — a replica-local
        # observation clock can (the health sampler's lease-age
        # idiom). The timer resets whenever the backlog empties OR the
        # owner demonstrably makes PROGRESS (the aggregated count
        # moved): under steady traffic the backlog is never observed
        # empty, and a gate keyed on nonemptiness alone would steal
        # every live owner's task forever.
        self._foreign_backlog_first_seen: dict[bytes, tuple[int, int, int]] = {}
        # the foreign-backlog lag scan itself also runs at steal_after
        # cadence (not per sweep): the steal gate cannot fire sooner,
        # and a healthy sharded fleet must not pay an extra index scan
        # per replica per second just to conclude "nothing to steal"
        self._next_lag_scan = 0.0
        # tasks this replica is ACTIVELY stealing: once the no-progress
        # gate fires, the task stays swept until its backlog drains —
        # without stickiness, the STEALER's own job creation would read
        # as "owner progress" at the next scan and restart the window,
        # halving a dead owner's effective job-creation rate
        self._stealing: set[bytes] = set()

    def _shard_filter(self, tasks: list[Task]) -> list[Task]:
        from ..datastore.store import job_shard_key

        fleet = self.fleet
        if fleet is None or fleet.shard_count <= 1 or not tasks:
            return tasks
        count = int(fleet.shard_count)
        index = int(fleet.shard_index) % count
        own, foreign = [], []
        for t in tasks:
            (own if job_shard_key(t.task_id.data, b"") % count == index else foreign).append(t)
        if not foreign:
            return own
        # steal signal: the foreign task has had unaggregated reports
        # continuously for steal_after_secs WITH NO OWNER PROGRESS (its
        # aggregated-report count static over the whole window) — a
        # live owner claims reports every sweep and keeps resetting the
        # window even under sustained uploads; one that cannot (dead,
        # or genuinely wedged) gets help. The progress probe (a
        # COUNT/SUM scan of the task's client_reports) runs at
        # steal_after cadence per task, NOT per sweep — the gate cannot
        # fire sooner than steal_after anyway, and a per-sweep scan
        # would be steady-state O(reports) load on the shared store
        # (worst-case steal detection latency: 2x steal_after).
        now = self.ds.clock.now().seconds
        steal_after = max(0.0, float(fleet.steal_after_secs))
        # sticky steals sweep EVERY pass (a dead owner's task gets full
        # cadence, not once-per-window); membership is re-evaluated at
        # scan cadence below
        own.extend(t for t in foreign if t.task_id.data in self._stealing)
        if now < self._next_lag_scan:
            return own
        self._next_lag_scan = now + steal_after
        try:
            backlog_tasks = {
                task_id
                for task_id, _ in self.ds.run_tx(
                    lambda tx: tx.min_unaggregated_report_time_by_task(),
                    "creator_lag_scan",
                )
            }
        except Exception:
            return own
        candidates = [t for t in foreign if t.task_id.data in backlog_tasks]
        due = [
            t
            for t in candidates
            if t.task_id.data not in self._foreign_backlog_first_seen
            or now - self._foreign_backlog_first_seen[t.task_id.data][2]
            >= steal_after
        ]
        try:
            aggregated = (
                self.ds.run_tx(
                    lambda tx: {
                        t.task_id.data: tx.count_client_reports_for_task(t.task_id)[1]
                        for t in due
                    },
                    "creator_progress_scan",
                )
                if due
                else {}
            )
        except Exception:
            return own
        live: set[bytes] = set()
        for t in candidates:
            key = t.task_id.data
            live.add(key)
            if key in self._stealing:
                continue  # already swept above, every pass
            if key not in aggregated:
                continue  # probe not due: the window verdict waits
            agg = int(aggregated[key])
            first, last_agg, _ = self._foreign_backlog_first_seen.setdefault(
                key, (now, agg, now)
            )
            if agg != last_agg:
                # the owner moved the aggregated count: it is alive —
                # restart the no-progress window
                self._foreign_backlog_first_seen[key] = (now, agg, now)
            else:
                self._foreign_backlog_first_seen[key] = (first, last_agg, now)
                if now - first >= steal_after:
                    # steal, and STAY on it until the backlog drains
                    self._stealing.add(key)
                    del self._foreign_backlog_first_seen[key]
                    own.append(t)
        # prune state for tasks no longer foreign-with-backlog
        # (drained, deleted, or reassigned) — the health sampler's
        # lease-age idiom; a stale entry would grow the dict with task
        # churn and hand a RE-CREATED task id an ancient first-seen
        for key in list(self._foreign_backlog_first_seen):
            if key not in live:
                del self._foreign_backlog_first_seen[key]
        self._stealing &= live
        return own

    def run_once(self) -> int:
        """Sweep all leader tasks once; returns number of jobs created.

        Tasks sweep concurrently in a thread pool (the reference spawns
        one worker per task, aggregation_job_creator.rs:210); each
        task's claim/write transactions are independent, so cross-task
        serialization would bound many-task deployments by the slowest
        task."""
        tasks = self.ds.run_tx(lambda tx: tx.get_tasks(), "creator_tasks")
        eligible = self._shard_filter(
            [
                t
                for t in tasks
                if t.role == Role.LEADER
                # parameterized VDAFs (Poplar1): reports aggregate once
                # PER collection parameter; jobs are created by the
                # collection job driver when the parameter is known
                and not t.vdaf.has_aggregation_parameter
            ]
        )
        if len(eligible) <= 1 or self.cfg.max_concurrent_tasks <= 1:
            return sum(self.create_jobs_for_task(t) for t in eligible)
        from concurrent.futures import ThreadPoolExecutor

        workers = min(self.cfg.max_concurrent_tasks, len(eligible))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            return sum(pool.map(self.create_jobs_for_task, eligible))

    def create_jobs_for_task(self, task: Task) -> int:
        if task.query_type.code == TimeInterval.CODE:
            return self._create_time_interval_jobs(task)
        return self._create_fixed_size_jobs(task)

    def _claim(self, task: Task):
        return self.ds.run_tx(
            lambda tx: tx.get_unaggregated_client_reports_for_task(
                task.task_id, self.cfg.max_aggregation_job_size
            ),
            "creator_claim",
        )

    def _create_time_interval_jobs(self, task: Task) -> int:
        """reference create_aggregation_jobs_for_time_interval_task_no_param
        (:511)."""
        created = 0
        while True:
            claimed = self._claim(task)
            if len(claimed) < max(1, self.cfg.min_aggregation_job_size):
                # too few: release claim, try next sweep (reference keeps
                # sub-min reports unaggregated)
                if claimed:
                    self.ds.run_tx(
                        lambda tx: tx.mark_reports_unaggregated(
                            task.task_id, [r for r, _ in claimed]
                        ),
                        "creator_release",
                    )
                return created
            self._write_job(task, claimed, PartialBatchSelector.time_interval())
            created += 1
            if len(claimed) < self.cfg.max_aggregation_job_size:
                return created

    def _create_fixed_size_jobs(self, task: Task) -> int:
        """Batch packing toward max_batch_size (reference
        batch_creator.rs:140-330): claimed reports top up the fullest
        unfilled outstanding batch of their time bucket first, spill
        into new batches, and batches are marked filled exactly when
        their assigned size reaches max_batch_size."""
        created = 0
        max_bs = task.query_type.max_batch_size or self.cfg.max_aggregation_job_size
        while True:
            claimed = self._claim(task)
            if len(claimed) < max(1, self.cfg.min_aggregation_job_size):
                if claimed:
                    self.ds.run_tx(
                        lambda tx: tx.mark_reports_unaggregated(
                            task.task_id, [r for r, _ in claimed]
                        ),
                        "creator_release",
                    )
                return created

            window = task.query_type.batch_time_window_size
            by_bucket: dict = {}
            for rid, t in claimed:
                bucket = t.to_batch_interval_start(window) if window else None
                by_bucket.setdefault(bucket, []).append((rid, t))

            min_job = max(1, self.cfg.min_aggregation_job_size)

            def assign_and_write(tx):
                """One transaction: batch accounting AND job rows commit
                together (a crash between them would otherwise corrupt
                outstanding-batch sizes and orphan claimed reports)."""
                n_jobs = 0
                for bucket, group in by_bucket.items():
                    remaining = list(group)
                    obs = tx.get_outstanding_batches(task.task_id, bucket)
                    while remaining:
                        if obs:
                            ob = obs.pop(0)
                            bid, size = ob.batch_id, ob.size
                        else:
                            bid, size = None, 0  # a new batch, created lazily
                        take = min(max_bs - size, len(remaining))
                        if take <= 0:
                            tx.mark_outstanding_batch_filled(task.task_id, bid)
                            continue
                        if take < min_job and size + take < max_bs:
                            # too small for a job and doesn't complete the
                            # batch: leave these reports for a later pass
                            tx.mark_reports_unaggregated(
                                task.task_id, [r for r, _ in remaining]
                            )
                            break
                        if bid is None:
                            bid = BatchId(secrets.token_bytes(32))
                            tx.put_outstanding_batch(
                                OutstandingBatch(task.task_id, bid, bucket)
                            )
                        chunk, remaining = remaining[:take], remaining[take:]
                        new_size = tx.add_to_outstanding_batch(task.task_id, bid, take)
                        if new_size >= max_bs:
                            tx.mark_outstanding_batch_filled(task.task_id, bid)
                        self._write_job_in_tx(
                            tx, task, chunk, PartialBatchSelector.fixed_size(bid)
                        )
                        n_jobs += 1
                return n_jobs

            n_jobs = self.ds.run_tx(assign_and_write, "creator_fixed_assign")
            created += n_jobs
            if n_jobs == 0:
                # every bucket deferred (sub-min chunks): the same reports
                # would be re-claimed forever — stop this pass
                return created
            if len(claimed) < self.cfg.max_aggregation_job_size:
                return created

    def _write_job(self, task: Task, claimed, pbs: PartialBatchSelector) -> None:
        self.ds.run_tx(
            lambda tx: self._write_job_in_tx(tx, task, claimed, pbs), "creator_write_job"
        )

    def _write_job_in_tx(self, tx, task: Task, claimed, pbs: PartialBatchSelector) -> None:
        job_id = AggregationJobId(secrets.token_bytes(16))
        times = [t.seconds for _, t in claimed]
        # the job's trace is rooted HERE: the creating span's context is
        # persisted in the row, and both the leader driver (every step,
        # every restart) and the helper (via the propagated traceparent)
        # attach their spans under it — one trace id per job, across
        # processes and time (docs/OBSERVABILITY.md)
        with span("creator.create_job", reports=len(claimed)):
            job = AggregationJobModel(
                task.task_id,
                job_id,
                b"",
                pbs.to_bytes(),
                Interval(Time(min(times)), Duration(max(times) - min(times) + 1)),
                AggregationJobState.IN_PROGRESS,
                0,
                trace_context=current_traceparent(),
            )
            tx.put_aggregation_job(job)
            for i, (rid, t) in enumerate(claimed):
                tx.put_report_aggregation(
                    ReportAggregationModel(
                        task.task_id, job_id, rid, t, i, ReportAggregationState.START
                    )
                )
