"""Collection job driver (leader stepper).

Equivalent of reference aggregator/src/aggregator/collection_job_driver.rs:
40-307: acquire leases on collectable collection jobs, compute the
leader aggregate share from the batch-aggregation shard rows, POST an
AggregateShareReq to the helper, store the helper's encrypted share and
finish the job.
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass

from ..core.circuit_breaker import (
    CircuitBreakerConfig,
    CircuitOpenError,
    OutboundCircuitBreakers,
    default_breakers,
    peer_label,
)
from ..core.deadline import DEADLINE_EXCEEDED_STATUS, DeadlineExceeded, deadline_scope
from ..core.retries import Backoff, RequestAborted, retry_http_request
from ..datastore.models import (
    AcquiredCollectionJob,
    AggregateShareJob,
    CollectionJobState,
)
from .. import ledger, metrics
from ..datastore.store import Datastore
from ..messages import (
    AggregateShare,
    AggregateShareReq,
    BatchId,
    BatchSelector,
    Duration,
    Interval,
    Query,
    ReportIdChecksum,
    TimeInterval,
)
from ..task import Task
from ..vdaf.registry import circuit_for
from .accumulator import add_encoded_aggregate_shares

log = logging.getLogger(__name__)


@dataclass
class CollectionJobDriverConfig:
    maximum_attempts_before_failure: int = 10
    http_backoff: Backoff = Backoff()
    # see AggregationJobDriverConfig.worker_lease_clock_skew_s
    worker_lease_clock_skew_s: int = 60
    # see AggregationJobDriverConfig.circuit_breaker / min_step_back_delay_s
    circuit_breaker: CircuitBreakerConfig | None = None
    min_step_back_delay_s: int = 1


class CollectionJobDriver:
    """reference collection_job_driver.rs:40."""

    def __init__(
        self,
        ds: Datastore,
        http,
        cfg: CollectionJobDriverConfig | None = None,
        breakers: OutboundCircuitBreakers | None = None,
        stopper=None,
        peer_health=None,
    ):
        self.ds = ds
        self.http = http
        self.cfg = cfg or CollectionJobDriverConfig()
        self.breakers = (
            breakers if breakers is not None else default_breakers(self.cfg.circuit_breaker)
        )
        self.stopper = stopper
        # peer-outage parking tracker (peer_health.PeerHealthTracker);
        # None = no parking, per-step breaker step-backs only
        self.peer_health = peer_health

    def acquirer(self, lease_duration_s: int = 600, fleet=None):
        """Batched claim acquirer; `fleet` adds the shard predicate +
        steal-after fallback and the replica provenance tag (see
        AggregationJobDriver.acquirer)."""
        from .job_driver import make_claim_acquirer

        shard = fleet.shard_spec() if fleet is not None else None
        holder = fleet.holder_tag() if fleet is not None else None
        return make_claim_acquirer(
            self.ds,
            "collection",
            lambda limit: self.ds.run_tx(
                lambda tx: tx.acquire_incomplete_collection_jobs(
                    Duration(lease_duration_s), limit, shard=shard, holder=holder
                ),
                "acquire_collection_jobs",
            ),
            shard=shard,
            peer_gate=self.peer_health.park_gate()
            if self.peer_health is not None
            else None,
        )

    def stepper(self, acquired: AcquiredCollectionJob) -> None:
        if acquired.lease.attempts > self.cfg.maximum_attempts_before_failure:
            self.abandon_job(acquired)
            return
        try:
            self.step_collection_job(acquired)
        except CircuitOpenError as e:
            self.step_back(
                acquired,
                "circuit_open",
                max(e.retry_in_s, self.cfg.min_step_back_delay_s),
            )
        except RequestAborted:
            self.step_back(acquired, "shutdown_drain", 0.0)
        except DeadlineExceeded:
            # lease budget dead (expired lease / retry bound / helper's
            # conclusive 408): step back, refund the attempt
            self.step_back(acquired, "deadline_expired", 0.0)
        except Exception as e:
            from .job_driver import datastore_reconnect_delay_s, is_datastore_connection_error

            if is_datastore_connection_error(self.ds, e):
                # datastore outage mid-step: step back with the
                # reconnect cooldown instead of burning the attempt
                self.step_back(
                    acquired, "datastore_down", datastore_reconnect_delay_s(self.ds)
                )
                return
            raise

    def step_back(
        self, acquired: AcquiredCollectionJob, reason: str, delay_s: float
    ) -> None:
        """See AggregationJobDriver.step_back: early lease release with
        a reacquire delay, attempt refunded."""
        from ..datastore.store import TxConflict

        delay = max(0, int(delay_s))
        log.warning(
            "stepping back collection job %s (%s): lease released, reacquirable in %ds",
            acquired.collection_job_id, reason, delay,
        )
        metrics.job_step_back_total.add(reason=reason, **metrics.replica_labels())
        # clean hand-back on shutdown: see AggregationJobDriver.step_back
        handback = reason == "shutdown_drain"
        try:
            self.ds.run_tx(
                lambda tx: tx.step_back_collection_job(
                    acquired,
                    reacquire_delay_s=delay,
                    count_attempt=False,
                    handback=handback,
                ),
                "step_back_collection_job",
            )
        except TxConflict:
            log.info(
                "step-back of %s found the lease already gone",
                acquired.collection_job_id,
            )
        except Exception:
            log.warning(
                "step-back of %s could not reach the datastore; lease will age out",
                acquired.collection_job_id,
            )

    def step_collection_job(self, acquired: AcquiredCollectionJob) -> None:
        """reference step_collection_job_generic :108-300."""
        from ..trace import use_traceparent

        def read(tx):
            task = tx.get_task(acquired.task_id)
            job = tx.get_collection_job(acquired.task_id, acquired.collection_job_id)
            return task, job

        task, job = self.ds.run_tx(read, "step_collection_read")
        if task is None or job is None:
            raise RuntimeError("collection job vanished while leased")
        if job.state not in (CollectionJobState.START, CollectionJobState.COLLECTABLE):
            self.ds.run_tx(lambda tx: tx.release_collection_job(acquired), "release")
            return

        # adopt the trace the collection-create handler persisted: the
        # driver's spans (and the helper's aggregate_share handler, via
        # the propagated traceparent) join the collector's trace across
        # processes and driver restarts. The lease budget rides the
        # same scope: device work (Poplar1 IDPF walks) is watchdog-
        # bounded and outbound requests carry DAP-Janus-Deadline.
        with use_traceparent(job.trace_context), deadline_scope(
            self._lease_deadline(acquired)
        ):
            self._step_leased_job(acquired, task, job)

    def _step_leased_job(self, acquired: AcquiredCollectionJob, task: Task, job) -> None:
        if task.vdaf.has_aggregation_parameter:
            # parameterized VDAFs (Poplar1): aggregation happens per
            # collection parameter — the piece the reference punts on
            # (README.md:9-11). Create aggregation jobs for the
            # parameter on first step; wait for them to finish before
            # computing the aggregate share.
            from .poplar1_ops import Poplar1Ops

            pop = Poplar1Ops(task.vdaf.bits)
            field = pop.field_for(pop.decode_param(job.aggregation_parameter))
            if not self._ensure_param_aggregation(task, job):
                self.ds.run_tx(lambda tx: tx.release_collection_job(acquired), "release")
                return
        else:
            field = circuit_for(task.vdaf).FIELD
        query = Query.from_bytes(job.query)

        # tx1: gather + mark collected (reference :160-199); the same
        # read also collects the covered aggregation jobs' persisted
        # trace contexts — the collection span's causality links
        def gather(tx):
            if query.query_type == TimeInterval.CODE:
                interval = Interval.from_bytes(job.batch_identifier)
                rows = tx.get_batch_aggregations_intersecting_interval(
                    task.task_id,
                    interval,
                    aggregation_parameter=job.aggregation_parameter,
                )
                links = tx.get_aggregation_job_trace_contexts(
                    task.task_id, interval=interval
                )
            else:
                from ..messages import PartialBatchSelector

                rows = tx.get_batch_aggregations_for_batch(
                    task.task_id, job.batch_identifier, job.aggregation_parameter
                )
                links = tx.get_aggregation_job_trace_contexts(
                    task.task_id,
                    partial_batch_identifier=PartialBatchSelector.fixed_size(
                        BatchId(job.batch_identifier)
                    ).to_bytes(),
                )
            return rows, links

        rows, links = self.ds.run_tx(gather, "step_collection_gather")
        share = None
        total = 0
        checksum = ReportIdChecksum()
        interval = None
        for row in rows:
            share = add_encoded_aggregate_shares(field, share, row.aggregate_share)
            total += row.report_count
            checksum = checksum.combined_with(row.checksum)
            interval = (
                row.client_timestamp_interval
                if interval is None
                else Interval.merged(interval, row.client_timestamp_interval)
            )

        if share is None or total < task.min_batch_size:
            # not enough reports yet: release and try again later
            self.ds.run_tx(lambda tx: tx.release_collection_job(acquired), "release")
            return

        # DP: noise the leader's own share before release. The noised
        # share is persisted per (batch, agg param) and reused by later
        # collection jobs over the same batch — fresh noise per query
        # would let a collector average it away (max_batch_query_count>1).
        if task.dp_strategy.enabled:
            from ..dp import add_noise_to_agg_share

            existing = self.ds.run_tx(
                lambda tx: tx.get_aggregate_share_job(
                    task.task_id, job.batch_identifier, job.aggregation_parameter
                ),
                "leader_noised_share_lookup",
            )
            if existing is not None:
                share = existing.helper_aggregate_share
            else:
                share = add_noise_to_agg_share(task.dp_strategy, field, share)
                noised = AggregateShareJob(
                    task.task_id,
                    job.batch_identifier,
                    job.aggregation_parameter,
                    share,
                    total,
                    checksum,
                )
                self.ds.run_tx(
                    lambda tx: tx.put_aggregate_share_job(noised),
                    "leader_noised_share_store",
                )

        if query.query_type == TimeInterval.CODE:
            batch_selector = BatchSelector.time_interval(Interval.from_bytes(job.batch_identifier))
        else:
            batch_selector = BatchSelector.fixed_size(BatchId(job.batch_identifier))
        req = AggregateShareReq(batch_selector, job.aggregation_parameter, total, checksum)
        from ..trace import span

        with span("driver.http_aggregate_share", reports=total):
            helper_share = self._send_aggregate_share_request(
                task, req, deadline=self._lease_deadline(acquired)
            )

        def mark_and_store(tx):
            for row in rows:
                tx.mark_batch_aggregations_collected(
                    task.task_id, row.batch_identifier, row.aggregation_parameter
                )
            # conservation ledger: only rows still uncollected at gather
            # time book `collected`, so re-collections of a batch
            # (max_batch_query_count > 1) add nothing
            ledger.count_collected(tx, task.task_id, rows)
            tx.update_collection_job(
                dataclasses.replace(
                    job,
                    state=CollectionJobState.FINISHED,
                    report_count=total,
                    client_timestamp_interval=interval,
                    leader_aggregate_share=share,
                    helper_encrypted_aggregate_share=helper_share.encrypted_aggregate_share.to_bytes(),
                )
            )
            tx.release_collection_job(acquired)

        # the finishing span links back to the aggregation jobs that
        # filled the batch: their persisted trace ids ride as an
        # attribute, so the flight recorder / Chrome trace shows which
        # aggregation traces a released aggregate came from. Capped for
        # span-attribute size, but never silently: the overflow shows.
        from ..trace import trace_id_of

        link_ids = sorted({t for t in (trace_id_of(h) for h in links) if t})
        linked = ",".join(link_ids[:32])
        with span(
            "driver.collect_finish",
            reports=total,
            linked_traces=linked,
            linked_truncated=len(link_ids) > 32,
        ):
            self.ds.run_tx(mark_and_store, "step_collection_store")
        # collect-stage e2e SLO: batch close -> aggregate share
        # released, outside the tx so a retry cannot double-observe.
        # Batch close = the collected batch interval's end for
        # time-interval queries (the documented boundary; the merged
        # report interval can end long before it); fixed-size batch ids
        # carry no time, so the newest report's window stands in.
        if query.query_type == TimeInterval.CODE:
            batch_close = Interval.from_bytes(job.batch_identifier).end.seconds
        else:
            batch_close = interval.end.seconds
        metrics.report_e2e_seconds.observe(
            float(max(0, self.ds.clock.now().seconds - batch_close)),
            stage="collect",
        )
        # cross-aggregator reconciliation (ledger.py): after the books
        # close on our side, ask the helper for its per-batch aggregated
        # counts and export any divergence — the observability analog of
        # a linear tag. Best-effort: the collection is already released.
        self._reconcile_with_helper(task, rows)

    def _reconcile_with_helper(self, task: Task, rows) -> None:
        """Fetch the helper's aggregated report counts (the
        authenticated GET /tasks/{id}/ledger debug endpoint) and compare
        against the batches this collection just covered. Keys are
        "<batch hex>:<aggregation parameter hex>" on both sides: the
        rows here carry a single collection's parameter, and a helper
        payload summed across parameters would read as false divergence
        on any multi-parameter task. Divergence exports as
        janus_ledger_peer_divergence and feeds the conservation SLO via
        the installed evaluator's breach tracking (stage="peer")."""
        ev = ledger.installed_ledger()
        if ev is None or not ev.cfg.reconcile_peer:
            return
        ours: dict[str, int] = {}
        for row in rows:
            key = f"{row.batch_identifier.hex()}:{row.aggregation_parameter.hex()}"
            ours[key] = ours.get(key, 0) + int(row.report_count)
        if not ours:
            return
        try:
            theirs = self._fetch_helper_ledger(task)
        except Exception:
            # an unreachable debug endpoint must never fail a finished
            # collection; the divergence gauge just keeps its last value
            log.warning(
                "peer ledger reconciliation fetch failed for task %s",
                task.task_id,
                exc_info=True,
            )
            return
        divergence = ev.record_peer_divergence(task.task_id, ours, theirs)
        if divergence:
            log.error(
                "cross-aggregator ledger divergence for task %s: %d report(s) "
                "differ between our batch aggregations and the helper's",
                task.task_id,
                divergence,
            )

    def _fetch_helper_ledger(self, task: Task) -> dict[str, int]:
        import base64
        import json

        url = (
            task.helper_aggregator_endpoint.rstrip("/")
            + f"/tasks/{base64.urlsafe_b64encode(task.task_id.data).decode().rstrip('=')}/ledger"
        )
        headers = {}
        if task.aggregator_auth_token:
            headers.update(task.aggregator_auth_token.request_headers())
        status, body = self.http.get(url, headers, timeout=30.0)
        if status != 200:
            raise RuntimeError(f"helper ledger endpoint returned HTTP {status}")
        doc = json.loads(body.decode("utf-8"))
        return {
            str(k): int(v) for k, v in (doc.get("batch_counts") or {}).items()
        }

    def _ensure_param_aggregation(self, task: Task, job) -> bool:
        """Create aggregation jobs for the collection's parameter over
        reports in the batch interval; True when aggregation under this
        parameter is complete and the aggregate share can be computed.

        Max 512 reports per job (host per-report prepare; heavy-hitters
        batches are small)."""
        import secrets as _secrets

        from ..messages import AggregationJobId, PartialBatchSelector, Time
        from ..datastore.models import (
            AggregationJobModel,
            AggregationJobState,
            ReportAggregationModel,
            ReportAggregationState,
        )

        interval = Interval.from_bytes(job.batch_identifier)
        param = job.aggregation_parameter

        def create(tx):
            in_interval = tx.get_client_report_ids_in_interval(task.task_id, interval)
            done = tx.get_aggregated_report_ids_for_param(
                task.task_id, [rid for rid, _ in in_interval], param
            )
            todo = [(rid, t) for rid, t in in_interval if rid.data not in done]
            from ..trace import current_traceparent

            for lo in range(0, len(todo), 512):
                chunk = todo[lo : lo + 512]
                job_id = AggregationJobId(_secrets.token_bytes(16))
                times = [t.seconds for _, t in chunk]
                tx.put_aggregation_job(
                    AggregationJobModel(
                        task.task_id,
                        job_id,
                        param,
                        PartialBatchSelector.time_interval().to_bytes(),
                        Interval(Time(min(times)), Duration(max(times) - min(times) + 1)),
                        AggregationJobState.IN_PROGRESS,
                        0,
                        None,
                        # param-driven jobs are spawned BY the collection:
                        # they join its trace rather than rooting their own
                        trace_context=current_traceparent(),
                    )
                )
                for ord_, (rid, t) in enumerate(chunk):
                    tx.put_report_aggregation(
                        ReportAggregationModel(
                            task.task_id,
                            job_id,
                            rid,
                            t,
                            ord_,
                            ReportAggregationState.START,
                            b"",
                            None,
                        )
                    )
            # conservation ledger, param-fanout lane: creating the
            # (report, param) rows IS the lane's admission (the per-
            # param replay check above makes this exactly-once per
            # (report, param); the canonical `admitted` was booked at
            # upload and must not be debited by per-param outcomes)
            ledger.count_admitted(
                tx, task.task_id, len(todo), aggregation_parameter=param
            )
            if todo:
                return False  # fresh jobs: not ready this pass
            # ready once no job for this param is still in progress
            return tx.count_active_aggregation_jobs_for_param(task.task_id, param) == 0

        return self.ds.run_tx(create, "ensure_param_aggregation")

    def _lease_deadline(self, acquired) -> float:
        from .job_driver import lease_deadline

        return lease_deadline(
            self.ds.clock, acquired.lease, self.cfg.worker_lease_clock_skew_s
        )

    def _send_aggregate_share_request(
        self, task: Task, req: AggregateShareReq, deadline: float | None = None
    ) -> AggregateShare:
        import base64

        from .job_driver import deadline_request_timeout

        url = (
            task.helper_aggregator_endpoint.rstrip("/")
            + f"/tasks/{base64.urlsafe_b64encode(task.task_id.data).decode().rstrip('=')}/aggregate_shares"
        )
        headers = {"Content-Type": AggregateShareReq.MEDIA_TYPE}
        if task.aggregator_auth_token:
            headers.update(task.aggregator_auth_token.request_headers())
        peer = peer_label(task.helper_aggregator_endpoint)
        if self.peer_health is not None:
            # register before any attempt so the tracker can probe a
            # peer that never once answered (see aggregation_job_driver)
            self.peer_health.observe_endpoint(task.helper_aggregator_endpoint)

        def attempt():
            # circuit gate per attempt; see aggregation_job_driver.py
            self.breakers.check(peer)
            try:
                # trailing headers element: a shedding helper's
                # Retry-After paces the retry loop (core/retries.py)
                status, body = self.http.post(
                    url, req.to_bytes(), headers, timeout=deadline_request_timeout(deadline)
                )
            except BaseException:
                self.breakers.record_failure(peer)
                raise
            if 500 <= status < 600:
                self.breakers.record_failure(peer)
            else:
                self.breakers.record_success(peer)
            return status, body, getattr(self.http, "last_response_headers", {})

        status, body = retry_http_request(
            attempt,
            self.cfg.http_backoff,
            deadline=deadline,
            should_abort=(lambda: self.stopper.stopped) if self.stopper is not None else None,
        )
        if status == DEADLINE_EXCEEDED_STATUS:
            raise DeadlineExceeded(
                "helper reported deadline exceeded", last_status=status
            )
        if status != 200:
            raise RuntimeError(f"helper aggregate share failed: HTTP {status}: {body[:300]!r}")
        return AggregateShare.from_bytes(body)

    def abandon_job(self, acquired: AcquiredCollectionJob) -> None:
        def cancel(tx):
            job = tx.get_collection_job(acquired.task_id, acquired.collection_job_id)
            if job is None:
                return
            tx.update_collection_job(
                dataclasses.replace(job, state=CollectionJobState.ABANDONED)
            )
            tx.release_collection_job(acquired)

        self.ds.run_tx(cancel, "abandon_collection_job")
        metrics.job_cancel_counter.add(kind="collection")
        log.warning("abandoned collection job %s", acquired.collection_job_id)
