"""Poplar1 protocol ops for the DAP aggregator.

The reference declares `Poplar1<XofShake128, 16>` but cannot drive it
through DAP: nontrivial aggregation parameters are unsupported
(README.md:9-11; `VdafHasAggregationParameter`,
aggregator_core/src/lib.rs:44). This module is the missing plumbing —
per-(level, prefixes) parameter handling for upload validation, helper
prepare (the quadratic sketch mapped onto ping-pong), the leader
driver, and the collection-driven aggregation-job creation.

Protocol mapping onto DAP ping-pong (2 rounds, the same shape the
continue machinery already serves for the two-round fake). es = the
level field's encoded size; sketch algebra in vdaf.poplar1:

  - leader init: IDPF-evaluates its key at the parameter's prefixes
    and computes its round-1 sketch share [A0, B0];
    PrepareInit.message = PP_INITIALIZE(prep_share = enc(A0)||enc(B0)).
  - helper init: evaluates -> y1 + [A1, B1]; combines A = A0+A1,
    B = B0+B1 and computes its round-2 share sigma1. Parks
    WAITING_HELPER with prep_blob =
    enc(A)||enc(B) || enc(A1)||enc(B1)||enc(sigma1) || enc(y1) and
    answers PP_CONTINUE(prep_msg = enc(A)||enc(B),
    prep_share = enc(A1)||enc(B1)||enc(sigma1)).
  - leader continue: recomputes (A, B) from its own [A0, B0] + the
    helper's [A1, B1], verifies them against the helper's claimed
    prep_msg, computes sigma0, checks sigma0 + sigma1 == 0, parks
    WAITING_LEADER, then sends PP_FINISH(enc(sigma0)); the helper's
    ord-matched continue recomputes sigma from its stored sigma1 and
    accumulates y1 iff sigma == 0 (symmetric verification).

Host-side per-report loops (like the reference's own prepare loops) —
heavy-hitters batches are small; the TPU path stays Prio3's.
"""

from __future__ import annotations

from ..vdaf.poplar1 import (
    Idpf,
    Poplar1,
    Poplar1AggParam,
    decode_input_share,
    decode_public_share,
)
from ..vdaf.poplar1 import SEED_SIZE


class Poplar1Ops:
    def __init__(self, bits: int, verify_key: bytes = b"\x00" * SEED_SIZE):
        assert bits > 0, "poplar1 task missing bit length"
        self.bits = bits
        self.idpf = Idpf(bits)
        self.poplar = Poplar1(bits)
        self.verify_key = verify_key

    # --- aggregation parameter ---
    def decode_param(self, raw: bytes) -> Poplar1AggParam:
        param = Poplar1AggParam.decode(raw)
        if not (0 <= param.level < self.bits):
            raise ValueError(f"poplar1 level {param.level} out of range")
        if not param.prefixes:
            raise ValueError("poplar1 aggregation parameter has no prefixes")
        limit = 1 << (param.level + 1)
        if any(not (0 <= p < limit) for p in param.prefixes):
            raise ValueError("poplar1 prefix out of range for level")
        if list(param.prefixes) != sorted(set(param.prefixes)):
            raise ValueError("poplar1 prefixes must be sorted and distinct")
        return param

    def field_for(self, param: Poplar1AggParam):
        return self.idpf.field_at(param.level)

    def enc_size(self, param: Poplar1AggParam) -> int:
        return self.field_for(param).ENCODED_SIZE

    # --- share handling ---
    def validate_shares(self, public_share: bytes, input_share_payload: bytes, party: int) -> None:
        cws = decode_public_share(self.bits, public_share)
        decode_input_share(self.bits, cws, input_share_payload, party)

    def _key(self, party: int, public_share: bytes, payload: bytes):
        cws = decode_public_share(self.bits, public_share)
        return decode_input_share(self.bits, cws, payload, party)

    def round1(self, party: int, public_share: bytes, payload: bytes, param, nonce: bytes):
        """-> (prep state, y_shares, [A_share, B_share])."""
        key = self._key(party, public_share, payload)
        state, msg1 = self.poplar.prepare_init(party, key, param, self.verify_key, nonce)
        return state, state.y_shares, msg1

    # below this many (report, prefix) evaluations the host walk beats
    # the device dispatch overhead
    DEVICE_MIN_EVALS = 8

    def round1_batch(self, party: int, items, param):
        """Batched round1 over [(public_share, payload, nonce)].

        Returns a list of (state, y_shares, msg1) | ValueError per
        item. Decode failures stay per-report; eligible reports
        evaluate on device in one [reports x prefixes] batched IDPF
        walk + sketch (vdaf.poplar1_jax — VERDICT r4 item 4; the host
        per-report walk remains as the oracle and small-batch path).
        """
        import os

        results: list = [None] * len(items)
        keys = []
        idx = []
        nonces = []
        for i, (ps, payload, nonce) in enumerate(items):
            try:
                keys.append(self._key(party, ps, payload))
                idx.append(i)
                nonces.append(nonce)
            except ValueError as e:
                results[i] = e
        if not keys:
            return results
        use_device = (
            os.environ.get("JANUS_POPLAR1_DEVICE", "1") != "0"
            and self.bits <= 64
            and len(keys) * len(param.prefixes) >= self.DEVICE_MIN_EVALS
        )
        if use_device:
            from ..vdaf.poplar1 import _PrepState
            from ..vdaf.poplar1_jax import prepare_init_batched

            F = self.field_for(param)
            y, A, B, a_sh, c_sh = prepare_init_batched(
                self.bits, party, keys, param, self.verify_key, nonces
            )
            for k, i in enumerate(idx):
                state = _PrepState(F, y[k], party, a_sh[k], c_sh[k])
                results[i] = (state, y[k], [A[k], B[k]])
        else:
            for k, i in enumerate(idx):
                state, msg1 = self.poplar.prepare_init(
                    party, keys[k], param, self.verify_key, nonces[k]
                )
                results[i] = (state, state.y_shares, msg1)
        return results

    def round2(self, state, msg1_leader, msg1_helper):
        """-> (sigma_share, combined [A, B])."""
        F = state.field
        state, msg2 = self.poplar.prepare_next(state, [msg1_leader, msg1_helper])
        A = F.add(msg1_leader[0], msg1_helper[0])
        B = F.add(msg1_leader[1], msg1_helper[1])
        return msg2[0], [A, B]

    # --- codecs ---
    def encode_elem(self, param: Poplar1AggParam, x: int) -> bytes:
        return int(x).to_bytes(self.enc_size(param), "little")

    def decode_elem(self, param: Poplar1AggParam, raw: bytes) -> int:
        F = self.field_for(param)
        if len(raw) != F.ENCODED_SIZE:
            raise ValueError("poplar1 element length mismatch")
        x = int.from_bytes(raw, "little")
        if x >= F.MODULUS:
            raise ValueError("poplar1 element out of range")
        return x

    def encode_vec(self, param: Poplar1AggParam, xs: list[int]) -> bytes:
        return b"".join(self.encode_elem(param, x) for x in xs)

    def decode_vec(self, param: Poplar1AggParam, raw: bytes) -> list[int]:
        return self.decode_fixed_vec(param, raw, len(param.prefixes))

    def decode_fixed_vec(self, param: Poplar1AggParam, raw: bytes, n: int) -> list[int]:
        es = self.enc_size(param)
        if len(raw) != es * n:
            raise ValueError("poplar1 vector length mismatch")
        return [self.decode_elem(param, raw[i : i + es]) for i in range(0, len(raw), es)]
