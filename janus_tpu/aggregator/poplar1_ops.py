"""Poplar1 protocol ops for the DAP aggregator.

The reference declares `Poplar1<XofShake128, 16>` but cannot drive it
through DAP: nontrivial aggregation parameters are unsupported
(README.md:9-11; `VdafHasAggregationParameter`,
aggregator_core/src/lib.rs:44). This module is the missing plumbing —
per-(level, prefixes) parameter handling for upload validation, helper
prepare (the sketch exchange mapped onto ping-pong), the leader
driver, and the collection-driven aggregation-job creation.

Protocol mapping onto DAP ping-pong (2 rounds, the same shape the
continue machinery already serves for the two-round fake):

  - leader init: evaluates its IDPF key share at the parameter's
    prefixes -> y0 (count shares) + sketch share total0;
    PrepareInit.message = PP_INITIALIZE(prep_share=enc(total0)).
  - helper init: evaluates -> y1, total1; combined = total0 + total1
    must reconstruct to 0 (pruned path) or 1 (one-hot path); invalid
    reports reject NOW; valid ones park WAITING_HELPER with
    prep_blob = enc(combined) || enc(total1) || enc(y1) and answer
    PP_CONTINUE(prep_msg=enc(combined), prep_share=enc(total1)).
  - leader continue: re-derives combined from its own total0 + the
    helper's total1, verifies the sketch, parks WAITING_LEADER, then
    sends PP_FINISH(enc(combined)); the helper's ord-matched continue
    compares it against prep_blob[:enc_size] and accumulates y1.

Host-side per-report loops (like the reference's own prepare loops) —
heavy-hitters batches are small; the TPU path stays Prio3's.
"""

from __future__ import annotations

from ..vdaf.poplar1 import (
    Idpf,
    IdpfKey,
    Poplar1AggParam,
    decode_input_share,
    decode_public_share,
)


class Poplar1Ops:
    def __init__(self, bits: int):
        assert bits > 0, "poplar1 task missing bit length"
        self.bits = bits
        self.idpf = Idpf(bits)

    # --- aggregation parameter ---
    def decode_param(self, raw: bytes) -> Poplar1AggParam:
        param = Poplar1AggParam.decode(raw)
        if not (0 <= param.level < self.bits):
            raise ValueError(f"poplar1 level {param.level} out of range")
        if not param.prefixes:
            raise ValueError("poplar1 aggregation parameter has no prefixes")
        limit = 1 << (param.level + 1)
        if any(not (0 <= p < limit) for p in param.prefixes):
            raise ValueError("poplar1 prefix out of range for level")
        if list(param.prefixes) != sorted(set(param.prefixes)):
            raise ValueError("poplar1 prefixes must be sorted and distinct")
        return param

    def field_for(self, param: Poplar1AggParam):
        return self.idpf.field_at(param.level)

    def enc_size(self, param: Poplar1AggParam) -> int:
        return self.field_for(param).ENCODED_SIZE

    # --- share handling ---
    def validate_shares(self, public_share: bytes, input_share_payload: bytes) -> None:
        decode_public_share(self.bits, public_share)
        if len(input_share_payload) != 16:
            raise ValueError("poplar1 input share must be a 16-byte root seed")

    def eval_share(
        self, party: int, public_share: bytes, root_seed: bytes, param: Poplar1AggParam
    ):
        """-> (y_shares [per prefix], total [sketch share]) as field ints."""
        F = self.field_for(param)
        cws = decode_public_share(self.bits, public_share)
        key = decode_input_share(self.bits, cws, root_seed)
        vals = self.idpf.eval_prefixes(party, key, param.level, list(param.prefixes))
        y = [v[0] for v in vals]
        total = 0
        for v in y:
            total = F.add(total, v)
        return y, total

    def sketch_valid(self, param: Poplar1AggParam, combined: int) -> bool:
        return combined in (0, 1)

    # --- codecs ---
    def encode_elem(self, param: Poplar1AggParam, x: int) -> bytes:
        return int(x).to_bytes(self.enc_size(param), "little")

    def decode_elem(self, param: Poplar1AggParam, raw: bytes) -> int:
        F = self.field_for(param)
        if len(raw) != F.ENCODED_SIZE:
            raise ValueError("poplar1 element length mismatch")
        x = int.from_bytes(raw, "little")
        if x >= F.MODULUS:
            raise ValueError("poplar1 element out of range")
        return x

    def encode_vec(self, param: Poplar1AggParam, xs: list[int]) -> bytes:
        return b"".join(self.encode_elem(param, x) for x in xs)

    def decode_vec(self, param: Poplar1AggParam, raw: bytes) -> list[int]:
        es = self.enc_size(param)
        if len(raw) != es * len(param.prefixes):
            raise ValueError("poplar1 out-share length mismatch")
        return [self.decode_elem(param, raw[i : i + es]) for i in range(0, len(raw), es)]
