"""Accumulator: merge verified output shares into sharded batch rows.

Equivalent of reference aggregator/src/aggregator/accumulator.rs: an
in-memory map batch-identifier -> (aggregate share, report count,
checksum, client interval), flushed in the writing transaction to a
random shard row 0..shard_count (contention control; accumulator.rs:92)
with unique-violation converted into a retryable conflict
(accumulator.rs:173-199).

Difference from the reference: the per-batch share here arrives as one
already-reduced device vector per (job, batch bucket) — the device did
the per-report summation (masked tree reduce) — so the host only
merges a handful of vectors per job, not one per report.
"""

from __future__ import annotations

import secrets

from ..messages import Duration, Interval, ReportIdChecksum, TaskId, Time
from ..task import Task
from ..vdaf.registry import circuit_for
from ..datastore.models import BatchAggregation, BatchAggregationState


def count_reports_aggregated(task_id: TaskId, n: int) -> None:
    """Increment the per-task aggregated-reports counter (the
    accumulate-time throughput signal; health_sampler.py exports the
    sampled gauges). Callers must invoke this OUTSIDE any run_tx
    closure — a retried transaction would double the count."""
    if n <= 0:
        return
    from .. import metrics

    metrics.task_reports_aggregated_total.add(
        n, task_id=metrics.task_id_label(task_id.data)
    )


def observe_report_e2e(clock, times, stage: str = "aggregate") -> None:
    """Record janus_report_e2e_seconds{stage} for each client timestamp
    in `times` (clock now - report time, floored at 0): the end-to-end
    SLO signal "how old was this report when its output share was
    verified/released". Call only AFTER the write transaction that
    persists the work has committed — never inside a run_tx closure (a
    retried transaction would observe every report again) and not
    before the write (a failed step retried under a fresh lease would
    leave phantom samples; same discipline as
    count_reports_aggregated)."""
    if clock is None or not times:
        return
    from .. import metrics

    now = clock.now().seconds
    for t in times:
        metrics.report_e2e_seconds.observe(float(max(0, now - t.seconds)), stage=stage)


def observe_finished_report_e2e(clock, ras, unmerged) -> None:
    """Post-commit e2e observation for a write's report-aggregation
    rows: only FINISHED rows whose report actually merged (not in the
    committing attempt's `unmerged` set) count. One definition of the
    retry-discipline-sensitive filter for every driver write path."""
    from ..datastore.models import ReportAggregationState

    observe_report_e2e(
        clock,
        [
            ra.client_time
            for ra in ras
            if ra.state == ReportAggregationState.FINISHED
            and ra.report_id.data not in unmerged
        ],
    )


def add_encoded_aggregate_shares(field, a: bytes | None, b: bytes | None) -> bytes | None:
    """Element-wise mod-p sum of two encoded field vectors."""
    if a is None:
        return b
    if b is None:
        return a
    va = field.decode_vec(a)
    vb = field.decode_vec(b)
    assert len(va) == len(vb)
    return field.encode_vec([field.add(x, y) for x, y in zip(va, vb)])


def fixed_size_batch_id(pbs) -> bytes | None:
    """BatchId bytes for a fixed-size PartialBatchSelector, else None
    (time-interval jobs bucket by time window)."""
    from ..messages import FixedSize

    return pbs.batch_id.data if pbs.query_type == FixedSize.CODE else None


def group_batch_buckets(
    task, metadatas, accept, batch_identifier: bytes | None
) -> dict[bytes, list[int]]:
    """Accepted lane indices grouped by batch identifier — the ONE
    definition of the bucket mapping, shared by the classic per-bucket
    reduce below and the device-resident delta path (a divergence here
    would silently put the two paths' shares in different batches)."""
    buckets: dict[bytes, list[int]] = {}
    for i, md in enumerate(metadatas):
        if not accept[i]:
            continue
        if batch_identifier is not None:
            bid = batch_identifier
        else:
            start = md.time.to_batch_interval_start(task.time_precision)
            bid = Interval(start, task.time_precision).to_bytes()
        buckets.setdefault(bid, []).append(i)
    return buckets


def bucket_metadata(task, metadatas, lanes):
    """(checksum, client interval) over one bucket's lanes — shared by
    the classic and resident accumulate paths."""
    checksum = ReportIdChecksum()
    lo = hi = None
    for i in lanes:
        checksum = checksum.updated_with(metadatas[i].report_id)
        t = metadatas[i].time
        lo = t if lo is None or t < lo else lo
        hi = t if hi is None or t > hi else hi
    interval = Interval(
        lo.to_batch_interval_start(task.time_precision), task.time_precision
    )
    return checksum, interval


def accumulate_batched(
    task, engine, accumulator: "Accumulator", out_shares, accept, metadatas,
    batch_identifier: bytes | None = None, flat_idx=None,
) -> None:
    """Group accepted lanes by batch bucket; one masked device reduce per
    bucket (replaces the reference's per-report Accumulator::update loop,
    accumulator.rs:76-122).

    `batch_identifier`: for fixed-size tasks, the job's BatchId bytes —
    every accepted lane lands in that one batch. None (time-interval
    tasks) buckets lanes by their time_precision window.

    `flat_idx` ([n, compact_len] int32 scatter targets) marks a
    block-sparse task: each bucket's reduce is a scatter-add into a
    dense logical accumulator (engine.aggregate_sparse) instead of the
    compact-width masked sum, so the persisted share is logical-length.

    Does NOT record the e2e SLO histogram: callers observe via
    observe_report_e2e AFTER their write transaction commits, so a
    failed-and-retried step can't leave phantom samples.
    """
    import numpy as np

    n = len(metadatas)
    if n == 0:
        return
    field = accumulator.field
    buckets = group_batch_buckets(task, metadatas, accept, batch_identifier)
    # one reusable mask scratch for the whole job: a many-bucket
    # time-interval job used to allocate a fresh n-bool array per
    # bucket (visible in the PR 8 lane profile); lanes are reset after
    # each dispatch instead
    bucket_mask = np.zeros(n, dtype=bool)
    for bid, lanes in buckets.items():
        bucket_mask[lanes] = True
        if flat_idx is not None:
            share_ints = engine.aggregate_sparse(out_shares, bucket_mask, flat_idx)
        else:
            share_ints = engine.aggregate(out_shares, bucket_mask)
        bucket_mask[lanes] = False
        checksum, interval = bucket_metadata(task, metadatas, lanes)
        accumulator.update(
            bid,
            field.encode_vec(share_ints),
            len(lanes),
            checksum,
            interval,
            [metadatas[i].report_id for i in lanes],
        )


class Accumulator:
    """reference accumulator.rs:32."""

    def __init__(
        self,
        task: Task,
        shard_count: int = 1,
        field=None,
        aggregation_parameter: bytes = b"",
        count_metrics: bool = True,
    ):
        """field/aggregation_parameter: parameterized VDAFs (Poplar1)
        accumulate in a per-parameter field and key their batch rows by
        the parameter; Prio3 uses the circuit field and parameter b"".

        count_metrics: update() increments the per-task aggregated-
        reports counter. Pass False when the Accumulator lives INSIDE a
        run_tx closure (the helper continue path) — a retried
        transaction re-creates it and would double the count; such
        callers count after commit via count_reports_aggregated."""
        self.task = task
        self.field = field if field is not None else circuit_for(task.vdaf).FIELD
        self.agg_param = aggregation_parameter
        self.shard_count = shard_count
        self._count_metrics = count_metrics
        # batch_identifier bytes -> [share bytes | None, count, checksum, interval | None]
        self._state: dict[bytes, list] = {}

    def total_report_count(self) -> int:
        """Reports merged into this accumulator so far."""
        return sum(ent[1] for ent in self._state.values())

    def update(
        self,
        batch_identifier: bytes,
        aggregate_share: bytes | None,
        report_count: int,
        checksum: ReportIdChecksum,
        client_interval: Interval,
        report_ids: list | None = None,
    ) -> None:
        """Merge one already-reduced contribution (device output)."""
        if self._count_metrics:
            # counted at accumulate time, not sampled. The batched
            # paths and the leader driver build their Accumulator (and
            # call update) OUTSIDE the writing transaction, so run_tx
            # retries can't double this; in-transaction accumulators
            # pass count_metrics=False and count after commit.
            count_reports_aggregated(self.task.task_id, report_count)
        ent = self._state.get(batch_identifier)
        if ent is None:
            self._state[batch_identifier] = [
                aggregate_share, report_count, checksum, client_interval, list(report_ids or ())
            ]
            return
        ent[0] = add_encoded_aggregate_shares(self.field, ent[0], aggregate_share)
        ent[1] += report_count
        ent[2] = ent[2].combined_with(checksum)
        ent[3] = Interval.merged(ent[3], client_interval)
        ent[4].extend(report_ids or ())

    def update_single(self, batch_identifier: bytes, out_share: list[int], report_id, client_time: Time) -> None:
        """Scalar convenience path (tests, small flows)."""
        self.update(
            batch_identifier,
            self.field.encode_vec(out_share),
            1,
            ReportIdChecksum.for_report_id(report_id),
            Interval(
                client_time.to_batch_interval_start(self.task.time_precision),
                self.task.time_precision,
            ),
            [report_id],
        )

    def flush_to_datastore(self, tx) -> set:
        """Merge into a random shard row per batch (reference :133-215).

        Returns the report ids that could NOT be merged because their
        batch was already collected; callers mark those report
        aggregations failed with PrepareError.BATCH_COLLECTED instead of
        failing the whole job (reference accumulator.rs:133-215 returns
        the same unmergeable set).

        Does NOT consume the accumulator state: the surrounding
        transaction may be retried after a rollback (run_tx retry loop),
        and a retry must re-flush the same contributions.
        """
        unmerged: set = set()
        for batch_identifier, (share, count, checksum, interval, rids) in self._state.items():
            # a COLLECTED row in ANY shard closes the batch
            if tx.batch_has_collected_shard(
                self.task.task_id, batch_identifier, self.agg_param
            ):
                unmerged.update(r.data for r in rids)
                continue
            ord_ = secrets.randbelow(self.shard_count)
            existing = tx.get_batch_aggregation(
                self.task.task_id, batch_identifier, self.agg_param, ord_
            )
            if existing is None:
                tx.put_batch_aggregation(
                    BatchAggregation(
                        self.task.task_id,
                        batch_identifier,
                        self.agg_param,
                        ord_,
                        BatchAggregationState.AGGREGATING,
                        share,
                        count,
                        interval,
                        checksum,
                    )
                )
                continue
            merged = BatchAggregation(
                self.task.task_id,
                batch_identifier,
                self.agg_param,
                ord_,
                existing.state,
                add_encoded_aggregate_shares(self.field, existing.aggregate_share, share),
                existing.report_count + count,
                Interval.merged(existing.client_timestamp_interval, interval),
                existing.checksum.combined_with(checksum),
            )
            tx.update_batch_aggregation(merged)
        return unmerged
