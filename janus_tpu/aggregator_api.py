"""Ops/control-plane REST API.

Equivalent of reference aggregator_api/src/lib.rs:69-122: an internal
JSON API on a separate listener, bearer-token authenticated, for task
CRUD, task metrics, global HPKE key management, and taskprov peer
management. DTOs are the Task/PeerAggregator dict forms (the analog of
aggregator_api/src/models.rs).

Routes:
  GET    /                                    -> version doc
  GET    /task_ids[?pagination_token=...]     -> paginated task ids
  POST   /tasks                               -> create (fills defaults)
  GET    /tasks/:task_id                      -> task doc (no HPKE privkeys)
  DELETE /tasks/:task_id
  GET    /tasks/:task_id/metrics              -> report counts
  GET    /hpke_configs                        -> global HPKE keypairs
  PUT    /hpke_configs                        -> generate one {config_id?}
  PATCH  /hpke_configs/:config_id             -> {state: pending|active|expired}
  DELETE /hpke_configs/:config_id
  GET    /taskprov/peer_aggregators           -> peers
  PUT    /taskprov/peer_aggregators           -> upsert peer doc
  DELETE /taskprov/peer_aggregators           -> {endpoint, role}
"""

from __future__ import annotations

import base64
import json
import re
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .core.hpke import generate_hpke_config_and_private_key
from .datastore.store import Datastore
from .messages import Role, TaskId
from .task import Task
from .taskprov import PeerAggregator
from .vdaf.registry import VERIFY_KEY_LENGTH


def _b64(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class ApiError(Exception):
    def __init__(self, status: int, detail: str):
        super().__init__(detail)
        self.status = status
        self.detail = detail


PAGE_SIZE = 10  # reference aggregator_api task_ids pagination


class AggregatorApi:
    """Route logic, transport-free (tested directly; served below)."""

    def __init__(self, ds: Datastore, auth_tokens=()):
        self.ds = ds
        self.auth_tokens = tuple(auth_tokens)

    # --- auth ---
    def check_auth(self, headers) -> None:
        if not self.auth_tokens:
            raise ApiError(401, "no API auth tokens configured")
        got = (headers.get("Authorization") or "").removeprefix("Bearer ").strip()
        import hmac

        for tok in self.auth_tokens:
            raw = tok.token if hasattr(tok, "token") else str(tok)
            if hmac.compare_digest(got.encode(), raw.encode()):
                return
        raise ApiError(401, "invalid bearer token")

    # --- handlers ---
    def get_root(self):
        return {"protocol": "DAP-07", "server": "janus_tpu"}

    def get_task_ids(self, pagination_token: str | None):
        ids = sorted(_b64(t.data) for t in self.ds.run_tx(lambda tx: tx.get_task_ids()))
        if pagination_token:
            ids = [i for i in ids if i > pagination_token]
        page, rest = ids[:PAGE_SIZE], ids[PAGE_SIZE:]
        doc = {"task_ids": page}
        if rest:
            doc["pagination_token"] = page[-1]
        return doc

    def post_task(self, doc: dict):
        doc = dict(doc)
        doc.setdefault("task_id", _b64(secrets.token_bytes(32)))
        doc.setdefault("vdaf_verify_key", _b64(secrets.token_bytes(VERIFY_KEY_LENGTH)))
        doc.setdefault("max_batch_query_count", 1)
        doc.setdefault("min_batch_size", 1)
        doc.setdefault("tolerable_clock_skew", 60)
        if doc.get("role") == int(Role.HELPER) and not doc.get("hpke_keys"):
            kp = generate_hpke_config_and_private_key(config_id=0)
            doc["hpke_keys"] = [
                {
                    "config": base64.urlsafe_b64encode(kp.config.to_bytes()).decode(),
                    "private_key": _b64(kp.private_key),
                }
            ]
        try:
            task = Task.from_dict(doc)
        except (KeyError, ValueError, AssertionError) as e:
            raise ApiError(400, f"invalid task document: {e!r}")
        self.ds.run_tx(lambda tx: tx.put_task(task), "api_post_task")
        return self._task_resp(task)

    def _task_resp(self, task: Task) -> dict:
        doc = task.to_dict()
        # never expose HPKE private keys over the ops API
        doc["hpke_keys"] = [k["config"] for k in doc["hpke_keys"]]
        return doc

    def _get_task(self, task_id_s: str) -> Task:
        try:
            tid = TaskId(_unb64(task_id_s))
        except Exception:
            raise ApiError(400, "malformed task id")
        task = self.ds.run_tx(lambda tx: tx.get_task(tid))
        if task is None:
            raise ApiError(404, "no such task")
        return task

    def get_task(self, task_id_s: str):
        return self._task_resp(self._get_task(task_id_s))

    def delete_task(self, task_id_s: str):
        task = self._get_task(task_id_s)
        self.ds.run_tx(lambda tx: tx.delete_task(task.task_id), "api_delete_task")
        return None

    def get_task_metrics(self, task_id_s: str):
        task = self._get_task(task_id_s)
        total, started = self.ds.run_tx(
            lambda tx: tx.count_client_reports_for_task(task.task_id)
        )
        return {"reports": total, "report_aggregations": started}

    # --- global HPKE configs ---
    def get_hpke_configs(self):
        rows = self.ds.run_tx(lambda tx: tx.get_global_hpke_keypairs())
        return [
            {"config": base64.urlsafe_b64encode(kp.config.to_bytes()).decode(), "state": state}
            for kp, state in rows
        ]

    def put_hpke_config(self, doc: dict):
        config_id = doc.get("config_id")
        if config_id is None:
            taken = {
                kp.config.id.id
                for kp, _ in self.ds.run_tx(lambda tx: tx.get_global_hpke_keypairs())
            }
            free = [i for i in range(256) if i not in taken]
            if not free:
                raise ApiError(400, "all 256 HPKE config ids are in use")
            config_id = free[0]
        elif not 0 <= int(config_id) < 256:
            raise ApiError(400, "config_id must be in [0, 255]")
        kp = generate_hpke_config_and_private_key(config_id=int(config_id))
        self.ds.run_tx(lambda tx: tx.put_global_hpke_keypair(kp), "api_put_hpke")
        return {
            "config": base64.urlsafe_b64encode(kp.config.to_bytes()).decode(),
            "state": "pending",
        }

    def patch_hpke_config(self, config_id: int, doc: dict):
        state = doc.get("state")
        if state not in ("pending", "active", "expired"):
            raise ApiError(400, "state must be pending|active|expired")
        self.ds.run_tx(
            lambda tx: tx.set_global_hpke_keypair_state(config_id, state),
            "api_patch_hpke",
        )
        return None

    def delete_hpke_config(self, config_id: int):
        self.ds.run_tx(
            lambda tx: tx.delete_global_hpke_keypair(config_id), "api_delete_hpke"
        )
        return None

    # --- taskprov peers ---
    def get_peers(self):
        peers = self.ds.run_tx(lambda tx: tx.get_taskprov_peer_aggregators())
        return [p.to_dict() for p in peers]

    def put_peer(self, doc: dict):
        try:
            peer = PeerAggregator.from_dict(doc)
        except (KeyError, ValueError, AssertionError) as e:
            raise ApiError(400, f"invalid peer document: {e!r}")
        self.ds.run_tx(lambda tx: tx.put_taskprov_peer_aggregator(peer), "api_put_peer")
        return peer.to_dict()

    def delete_peer(self, doc: dict):
        try:
            endpoint, role = doc["endpoint"], Role(doc["role"])
        except (KeyError, ValueError) as e:
            raise ApiError(400, f"invalid peer selector: {e!r}")
        self.ds.run_tx(
            lambda tx: tx.delete_taskprov_peer_aggregator(endpoint, role),
            "api_delete_peer",
        )
        return None

    # --- dispatch ---
    ROUTES = [
        ("GET", re.compile(r"^/$"), "get_root"),
        ("GET", re.compile(r"^/task_ids$"), "get_task_ids"),
        ("POST", re.compile(r"^/tasks$"), "post_task"),
        ("GET", re.compile(r"^/tasks/([^/]+)$"), "get_task"),
        ("DELETE", re.compile(r"^/tasks/([^/]+)$"), "delete_task"),
        ("GET", re.compile(r"^/tasks/([^/]+)/metrics$"), "get_task_metrics"),
        ("GET", re.compile(r"^/hpke_configs$"), "get_hpke_configs"),
        ("PUT", re.compile(r"^/hpke_configs$"), "put_hpke_config"),
        ("PATCH", re.compile(r"^/hpke_configs/(\d+)$"), "patch_hpke_config"),
        ("DELETE", re.compile(r"^/hpke_configs/(\d+)$"), "delete_hpke_config"),
        ("GET", re.compile(r"^/taskprov/peer_aggregators$"), "get_peers"),
        ("PUT", re.compile(r"^/taskprov/peer_aggregators$"), "put_peer"),
        ("DELETE", re.compile(r"^/taskprov/peer_aggregators$"), "delete_peer"),
    ]

    def handle(self, method: str, path: str, query: dict, headers, body: bytes):
        """-> (status, json-serializable doc or None)."""
        try:
            self.check_auth(headers)
            for m, pat, name in self.ROUTES:
                match = pat.match(path)
                if m == method and match:
                    return self._invoke(name, match, query, body)
            raise ApiError(404, "no such route")
        except ApiError as e:
            return e.status, {"status": e.status, "detail": e.detail}
        except Exception as e:  # never drop the connection on a handler bug
            return 500, {"status": 500, "detail": f"internal error: {type(e).__name__}"}

    def _invoke(self, name: str, match, query: dict, body: bytes):
        try:
            doc = json.loads(body) if body else {}
        except json.JSONDecodeError as e:
            raise ApiError(400, f"malformed JSON body: {e}")
        if name == "get_task_ids":
            return 200, self.get_task_ids(query.get("pagination_token"))
        if name == "post_task":
            return 201, self.post_task(doc)
        if name == "get_task":
            return 200, self.get_task(match.group(1))
        if name == "delete_task":
            return 204, self.delete_task(match.group(1))
        if name == "get_task_metrics":
            return 200, self.get_task_metrics(match.group(1))
        if name == "put_hpke_config":
            return 201, self.put_hpke_config(doc)
        if name == "patch_hpke_config":
            return 200, self.patch_hpke_config(int(match.group(1)), doc)
        if name == "delete_hpke_config":
            return 204, self.delete_hpke_config(int(match.group(1)))
        if name == "put_peer":
            return 201, self.put_peer(doc)
        if name == "delete_peer":
            return 204, self.delete_peer(doc)
        return 200, getattr(self, name)()


class AggregatorApiServer:
    """Threaded HTTP shell around AggregatorApi."""

    def __init__(self, api: AggregatorApi, host: str = "127.0.0.1", port: int = 0):
        from urllib.parse import parse_qsl, urlsplit

        class Handler(BaseHTTPRequestHandler):
            def _dispatch(self, method):
                parts = urlsplit(self.path)
                query = dict(parse_qsl(parts.query))
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                status, doc = api.handle(method, parts.path, query, self.headers, body)
                out = json.dumps(doc).encode() if doc is not None else b""
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                if out:
                    self.wfile.write(out)

            def do_GET(self):  # noqa: N802
                self._dispatch("GET")

            def do_POST(self):  # noqa: N802
                self._dispatch("POST")

            def do_PUT(self):  # noqa: N802
                self._dispatch("PUT")

            def do_PATCH(self):  # noqa: N802
                self._dispatch("PATCH")

            def do_DELETE(self):  # noqa: N802
                self._dispatch("DELETE")

            def log_message(self, fmt, *args):
                pass

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="api-listener", daemon=True
        )

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "AggregatorApiServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
