"""Finite-field arithmetic for VDAF (Prio3) on TPU.

Two fields, chosen to match the VDAF-07 ciphersuite the reference consumes
through the `prio` crate (reference core/src/task.rs:114-650 dispatches
Prio3 types whose fields are Field64/Field128):

  Field64  : p = 2^64 - 2^32 + 1          ("Goldilocks", 2-adicity 32)
  Field128 : p = 2^128 - 7*2^66 + 1       (2-adicity 66)

`field` holds host-side (Python int) implementations used for constant
precomputation and as the differential-test oracle; `jfield` holds the
batched JAX implementations (uint64 limb lanes) that run on TPU.
"""

from .field import Field64, Field128  # noqa: F401
from .jfield import JF64, JF128  # noqa: F401

JFIELD_FOR = {Field64: JF64, Field128: JF128}
