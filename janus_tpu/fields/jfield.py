"""Batched field arithmetic in JAX over uint64 limb lanes.

Design: a field-vector value is a tuple of uint64 arrays (the limbs), all
with identical shape. Field64 values are 1-tuples, Field128 values are
2-tuples (lo, hi). Structural ops (reshape/concat/take/...) map over the
limb tuple, so FLP/NTT code is generic over the field.

Why tuples-of-u64 rather than a trailing limb dim: tuples are pytrees, so
every jax transform (jit/vmap/shard_map) handles them natively, and XLA
sees plain elementwise u64 graphs it can fuse. On TPU, u64 ops lower to
u32 pairs; the Pallas kernels in janus_tpu/ops later specialize the same
math to native u32 where it is hot.

Reduction strategy exploits the sparse moduli (no Montgomery needed):
  Field64:  2^64 ≡ 2^32 - 1,  2^96 ≡ -1          (mod p)
  Field128: 2^128 ≡ 7*2^66 - 1                   (mod p)

The reference does this math on CPU inside the `prio` crate, one report at
a time (reference aggregator/src/aggregator/aggregation_job_driver.rs:363,
aggregator.rs:1777); here every op is elementwise over arbitrarily-shaped
batches.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .field import Field64, Field128

U64 = jnp.uint64

# Read once at import: the flag participates in tracing, not execution,
# and jit caches are not keyed on it — toggling mid-process would
# silently have no effect on already-compiled graphs.
_NO_BARRIERS = os.environ.get("JANUS_NO_BARRIERS") == "1"


def anti_recompute_barrier(x):
    """Materialization point against XLA fusion recomputing long
    producer chains (NTT stages, power doublings, reduction levels).

    Measured effects: on the CPU backend the barriers are load-bearing
    (6x end-to-end on the SumVec step — fusion otherwise duplicates
    each stage into every consumer); on TPU they are neutral (584.6 vs
    ~585 reports/s on the SumVec bench). Set JANUS_NO_BARRIERS=1 *at
    process start* to trace without them.
    """
    if _NO_BARRIERS:
        return x
    return jax.lax.optimization_barrier(x)


_M32 = np.uint64(0xFFFFFFFF)
_ZERO = np.uint64(0)
_ONE = np.uint64(1)


def _u64(x: int) -> np.uint64:
    return np.uint64(x & 0xFFFFFFFFFFFFFFFF)


# ---------------------------------------------------------------------------
# u64 multiprecision primitives (all elementwise over arrays)
# ---------------------------------------------------------------------------


def mul64wide(x, y):
    """Full 64x64 -> 128-bit product as (lo, hi) u64 arrays."""
    xl = x & _M32
    xh = x >> 32
    yl = y & _M32
    yh = y >> 32
    ll = xl * yl
    lh = xl * yh
    hl = xh * yl
    hh = xh * yh
    mid = lh + (ll >> 32)  # cannot wrap: <= (2^32-1)^2 + 2^32-1 < 2^64
    mid2 = mid + hl
    carry = (mid2 < mid).astype(U64)
    lo = (ll & _M32) | (mid2 << 32)
    hi = hh + (mid2 >> 32) + (carry << 32)
    return lo, hi


def adc(a, b, c):
    """a + b + c with c in {0,1}; returns (sum, carry in {0,1})."""
    s1 = a + b
    c1 = (s1 < a).astype(U64)
    s2 = s1 + c
    c2 = (s2 < s1).astype(U64)
    return s2, c1 + c2


def sbb(a, b, brw):
    """a - b - brw with brw in {0,1}; returns (diff, borrow in {0,1})."""
    d1 = a - b
    b1 = (a < b).astype(U64)
    d2 = d1 - brw
    b2 = (d1 < brw).astype(U64)
    return d2, b1 + b2


def add_limbs(a, b):
    """Add equal-length limb lists; returns (limbs, carry_out)."""
    out = []
    c = _ZERO
    for x, y in zip(a, b):
        s, c = adc(x, y, c)
        out.append(s)
    return out, c


def sub_limbs(a, b):
    """Subtract equal-length limb lists; returns (limbs, borrow_out)."""
    out = []
    brw = _ZERO
    for x, y in zip(a, b):
        d, brw = sbb(x, y, brw)
        out.append(d)
    return out, brw


def shl_limbs(a, k, out_len):
    """Shift limb list left by k bits (k < 64*out_len), zero-extended."""
    word = k // 64
    bit = k % 64
    ext = [jnp.zeros_like(a[0])] * word + list(a)
    ext += [jnp.zeros_like(a[0])] * (out_len + 1 - len(ext))
    if bit == 0:
        return ext[:out_len]
    nb = np.uint64(bit)
    inb = np.uint64(64 - bit)
    out = []
    for i in range(out_len):
        lo = ext[i] << nb
        hi = (ext[i - 1] >> inb) if i > 0 else jnp.zeros_like(a[0])
        out.append(lo | hi)
    return out


# ---------------------------------------------------------------------------
# Field64 (Goldilocks)
# ---------------------------------------------------------------------------

_P64 = _u64(Field64.MODULUS)
_EPS64 = _u64(2**32 - 1)  # 2^64 mod p


def _f64_reduce_wide(lo, hi):
    """Reduce a 128-bit value (lo, hi) mod p64. Uses 2^96 ≡ -1, 2^64 ≡ 2^32-1."""
    hl = hi & _M32
    hh = hi >> 32
    # x ≡ lo + hl*(2^32-1) - hh  (mod p)
    t = (hl << 32) - hl
    s = lo + t
    wrapped = s < lo
    s = jnp.where(wrapped, s + _EPS64, s)
    r = s - hh
    borrowed = s < hh
    r = jnp.where(borrowed, r - _EPS64, r)
    r = jnp.where(r >= _P64, r - _P64, r)
    return r


class JF64:
    """Batched Field64 ops. Values are 1-tuples of uint64 arrays, reduced."""

    HOST = Field64
    LIMBS = 1
    MODULUS = Field64.MODULUS

    @staticmethod
    def add(a, b):
        (x,), (y,) = a, b
        s = x + y
        s = jnp.where(s < x, s + _EPS64, s)
        s = jnp.where(s >= _P64, s - _P64, s)
        return (s,)

    @staticmethod
    def sub(a, b):
        (x,), (y,) = a, b
        d = x - y
        d = jnp.where(x < y, d - _EPS64, d)
        d = jnp.where(d >= _P64, d - _P64, d)
        return (d,)

    @staticmethod
    def neg(a):
        (x,) = a
        return (jnp.where(x == _ZERO, _ZERO, _P64 - x),)

    @staticmethod
    def mul(a, b):
        (x,), (y,) = a, b
        return (_f64_reduce_wide(*mul64wide(x, y)),)

    @staticmethod
    def from_ints(arr) -> tuple:
        a = np.asarray(arr, dtype=np.uint64)
        assert (a < np.uint64(Field64.MODULUS)).all()
        return (jnp.asarray(a),)

    @staticmethod
    def to_ints(v) -> np.ndarray:
        (x,) = v
        return np.asarray(jax.device_get(x), dtype=np.uint64).astype(object)


# ---------------------------------------------------------------------------
# Field128
# ---------------------------------------------------------------------------

_P128_LO = _u64(Field128.MODULUS & 0xFFFFFFFFFFFFFFFF)
_P128_HI = _u64(Field128.MODULUS >> 64)


def _ge128(alo, ahi, blo, bhi):
    return (ahi > bhi) | ((ahi == bhi) & (alo >= blo))


def _f128_fold(limbs, hi_len):
    """Given value as limb list [l0, l1, h...], fold H*2^128 ≡ H*(7*2^66 - 1).

    limbs: list of 2 + hi_len u64 arrays. Returns a shorter limb list.
    """
    L = limbs[:2]
    H = limbs[2 : 2 + hi_len]
    # 7H = (H << 3) - H, over hi_len+1 limbs
    h8 = shl_limbs(H, 3, hi_len + 1)
    h7, _ = sub_limbs(h8, H + [jnp.zeros_like(H[0])])
    # (7H) << 66, positioned at limb offset; total value = L + 7H<<66 - H
    sh = shl_limbs(h7, 66, hi_len + 3)
    acc, _ = add_limbs(sh, L + [jnp.zeros_like(L[0])] * (hi_len + 1))
    acc, _ = sub_limbs(acc, H + [jnp.zeros_like(H[0])] * 3)
    # trim known-zero top limbs conservatively: caller knows the bound
    return acc


def _f128_reduce256(r0, r1, r2, r3):
    """Reduce a 256-bit value to a Field128 element (lo, hi)."""
    # fold 1: H = (r2, r3) < 2^128 -> result < 2^198 (4 limbs, top <= 2^6)
    a = _f128_fold([r0, r1, r2, r3], 2)[:4]
    # fold 2: H = (a2, a3) < 2^70 -> result < 2^140 (3 limbs)
    b = _f128_fold(a, 2)[:3]
    # fold 3: H = (b2) < 2^12 -> result < 2^128 + 2^82 (3 limbs, top in {0,1})
    c = _f128_fold([b[0], b[1], b[2]], 1)[:3]
    return _f128_finalize(*c)


def _f128_finalize(lo, hi, top):
    """Canonicalize a (lo, hi, top) value < 2^128 + eps with top in {0,1}."""
    # if top bit set: value - p = value - 2^128 + 7*2^66 - 1
    seven66_lo = _u64((7 * 2**66) & 0xFFFFFFFFFFFFFFFF)
    seven66_hi = _u64((7 * 2**66) >> 64)
    add_lo, cc = adc(lo, seven66_lo, _ZERO)
    add_hi = hi + seven66_hi + cc  # < 2^64: value-2^128 < 2^82, +7*2^66 stays tiny
    d_lo, bb = sbb(add_lo, _ONE, _ZERO)
    d_hi = add_hi - bb
    one = top != _ZERO
    lo = jnp.where(one, d_lo, lo)
    hi = jnp.where(one, d_hi, hi)
    # final conditional subtract (at most once)
    ge = _ge128(lo, hi, _P128_LO, _P128_HI)
    s_lo, bb = sbb(lo, _P128_LO, _ZERO)
    s_hi = hi - _P128_HI - bb
    lo = jnp.where(ge, s_lo, lo)
    hi = jnp.where(ge, s_hi, hi)
    return lo, hi


class JF128:
    """Batched Field128 ops. Values are (lo, hi) tuples of uint64 arrays."""

    HOST = Field128
    LIMBS = 2
    MODULUS = Field128.MODULUS

    @staticmethod
    def add(a, b):
        (alo, ahi), (blo, bhi) = a, b
        lo, c = adc(alo, blo, _ZERO)
        hi1 = ahi + bhi
        w1 = (hi1 < ahi).astype(U64)
        hi = hi1 + c
        w2 = (hi < hi1).astype(U64)
        overflow = (w1 + w2) != _ZERO  # bit 128 set: a+b = 2^128 + (lo,hi)
        # subtract p when overflow or >= p; with overflow, 2^128 - p = 7*2^66 - 1
        seven66m1_lo = _u64((7 * 2**66 - 1) & 0xFFFFFFFFFFFFFFFF)
        seven66m1_hi = _u64((7 * 2**66 - 1) >> 64)
        o_lo, cc = adc(lo, seven66m1_lo, _ZERO)
        o_hi = hi + seven66m1_hi + cc
        lo = jnp.where(overflow, o_lo, lo)
        hi = jnp.where(overflow, o_hi, hi)
        ge = _ge128(lo, hi, _P128_LO, _P128_HI)
        s_lo, bb = sbb(lo, _P128_LO, _ZERO)
        s_hi = hi - _P128_HI - bb
        return (jnp.where(ge, s_lo, lo), jnp.where(ge, s_hi, hi))

    @staticmethod
    def sub(a, b):
        (alo, ahi), (blo, bhi) = a, b
        lo, brw = sbb(alo, blo, _ZERO)
        hi1, brw2 = sbb(ahi, bhi, brw)
        underflow = brw2 != _ZERO
        # add p back on underflow
        p_lo, cc = adc(lo, _P128_LO, _ZERO)
        p_hi = hi1 + _P128_HI + cc
        return (jnp.where(underflow, p_lo, lo), jnp.where(underflow, p_hi, hi1))

    @staticmethod
    def neg(a):
        (lo, hi) = a
        z = (lo == _ZERO) & (hi == _ZERO)
        n_lo, bb = sbb(_P128_LO, lo, _ZERO)
        n_hi = _P128_HI - hi - bb
        return (jnp.where(z, _ZERO, n_lo), jnp.where(z, _ZERO, n_hi))

    @staticmethod
    def mul(a, b):
        (a0, a1), (b0, b1) = a, b
        l00, h00 = mul64wide(a0, b0)
        l01, h01 = mul64wide(a0, b1)
        l10, h10 = mul64wide(a1, b0)
        l11, h11 = mul64wide(a1, b1)
        r0 = l00
        r1, c1 = adc(h00, l01, _ZERO)
        r1, c2 = adc(r1, l10, _ZERO)
        r2, c3 = adc(h01, h10, c1)
        r2, c4 = adc(r2, l11, c2)
        r3 = h11 + c3 + c4
        return _f128_reduce256(r0, r1, r2, r3)

    @staticmethod
    def from_ints(arr) -> tuple:
        a = np.asarray(arr, dtype=object)
        ints = np.vectorize(int, otypes=[object])(a)
        assert (ints < Field128.MODULUS).all() if ints.size else True
        lo = (ints & ((1 << 64) - 1)).astype(np.uint64)
        hi = (ints >> 64).astype(np.uint64)
        return (jnp.asarray(lo), jnp.asarray(hi))

    @staticmethod
    def to_ints(v) -> np.ndarray:
        lo, hi = (np.asarray(jax.device_get(x), dtype=np.uint64) for x in v)
        return lo.astype(object) + (hi.astype(object) << 64)


# ---------------------------------------------------------------------------
# Generic helpers over limb tuples (field-agnostic)
# ---------------------------------------------------------------------------


def fmul_pow2(jf, v, k: int):
    """v * 2^k mod p for a static 0 <= k < 64: pure shifts + sparse-
    moduli folds — ~5x cheaper than a generic jf.mul by the same
    constant (the truncate paths multiply by 2^bit, bit < bits <= 64)."""
    assert 0 <= k < 64, k
    if k == 0:
        return v
    nk = np.uint64(k)
    ink = np.uint64(64 - k)
    if jf.LIMBS == 1:
        (lo,) = v
        return (_f64_reduce_wide(lo << nk, lo >> ink),)
    lo, hi = v
    top = hi >> ink  # < 2^k
    nlo = lo << nk
    nhi = (hi << nk) | (lo >> ink)
    if k <= 32:
        # fold top*2^128 once: result < 2^128 + 7*2^(66+k) < 2^129
        c = _f128_fold([nlo, nhi, top], 1)[:3]
        return _f128_finalize(*c)
    # k up to 63: 7*top*2^66 can reach 2^133 — run the full 256-bit
    # reduction on [nlo, nhi, top, 0]
    return _f128_reduce256(nlo, nhi, top, jnp.zeros_like(top))


def fmap(fn, *vals):
    """Apply an array fn limb-wise over field values."""
    return tuple(fn(*limbs) for limbs in zip(*vals))


# --- tile-shaped structural ops (the streamed/tiled query's vocabulary:
# every consumer used to open-code fmap(lambda v: jax.lax.dynamic_slice...)
# per site; one copy here keeps the tile geometry in one place) ---


def fslice_dyn(v, start, size: int, axis: int = 1):
    """Dynamic slice of a field value along `axis`: `start` may be a
    traced scalar (scan step), `size` is static (the tile width).

    The start index is forced to int32: under jax_enable_x64 a scan
    step is s64, and the XLA SPMD partitioner rewrites sharded
    dynamic-slice offsets in s32 — the mixed compare fails its HLO
    verifier (seen on the len=100k (dp, sp) mesh dryrun)."""
    start = jnp.asarray(start, dtype=jnp.int32)
    return tuple(jax.lax.dynamic_slice_in_dim(x, start, size, axis=axis) for x in v)


def ftile(v, step, tile: int, axis: int = 1):
    """Tile `step` (0-based, traced ok) of width `tile` along `axis`."""
    return fslice_dyn(v, step * tile, tile, axis=axis)


def fput_tile(dst, src, step, axis: int = 1):
    """Write `src` as tile `step` along `axis` — the inverse of ftile;
    tile width is src's (static) extent along `axis`. Same int32 index
    rule as fslice_dyn: scan-stacked ys would carry an s64
    dynamic_update_slice index under x64, which the SPMD partitioner
    cannot rewrite — accumulating tiles into a carried buffer with an
    s32 offset keeps the sharded scan compilable."""
    start = (jnp.asarray(step) * src[0].shape[axis]).astype(jnp.int32)
    return tuple(
        jax.lax.dynamic_update_slice_in_dim(x, u, start, axis=axis)
        for x, u in zip(dst, src)
    )


def fpad_axis(v, pad: int, axis: int = 1):
    """Zero-pad a field value at the end of `axis` (no-op for pad=0) —
    aligns a vector onto a tile grid before a scan consumes it."""
    if pad == 0:
        return v
    widths = [(0, 0)] * v[0].ndim
    widths[axis] = (0, pad)
    return tuple(jnp.pad(x, widths) for x in v)


def freshape(v, shape):
    """Reshape every limb to `shape` (use -1 for the inferred axis)."""
    return tuple(x.reshape(shape) for x in v)


def fzeros(jf, shape):
    return tuple(jnp.zeros(shape, dtype=U64) for _ in range(jf.LIMBS))


def fshape(v):
    return v[0].shape


def fwhere(mask, a, b):
    """Select field values by boolean mask (broadcast against element shape)."""
    return tuple(jnp.where(mask, x, y) for x, y in zip(a, b))


def fconst(jf, value: int, shape=()):
    """Broadcast a host int constant to a field value of given shape."""
    value %= jf.MODULUS
    limbs = []
    for i in range(jf.LIMBS):
        limbs.append(jnp.full(shape, _u64((value >> (64 * i)) & 0xFFFFFFFFFFFFFFFF)))
    return tuple(limbs)


def fpow_const(jf, x, e: int):
    """x^e for a host-known exponent via square-and-multiply (unrolled).

    Each squaring is barriered: for inversion-sized exponents (finv,
    e = p-2) the chain is 64/128 muls deep and XLA's fusion otherwise
    re-inlines the whole producer chain into every consumer — compile
    time explodes from seconds to unbounded (observed on the Lagrange
    query path before the barriers)."""
    result = None
    base = x
    while e:
        if e & 1:
            result = base if result is None else anti_recompute_barrier(jf.mul(result, base))
        e >>= 1
        if e:
            base = anti_recompute_barrier(jf.mul(base, base))
    if result is None:
        return fconst(jf, 1, fshape(x))
    return result


def finv(jf, x):
    return fpow_const(jf, x, jf.MODULUS - 2)


def fsum(jf, v, axis):
    """Sum a field value along an axis via log-depth halving (mod-add tree)."""
    axis = axis % v[0].ndim
    n = v[0].shape[axis]
    if n == 0:
        shape = list(v[0].shape)
        del shape[axis]
        return fzeros(jf, tuple(shape))
    # pad to a power of two with zeros, then halve
    m = 1 << (n - 1).bit_length()
    if m != n:
        pad = [(0, 0)] * v[0].ndim
        pad[axis] = (0, m - n)
        v = fmap(lambda x: jnp.pad(x, pad), v)
    while m > 1:
        # each level slices its input twice; barrier so XLA materializes
        # the level instead of inlining the (arbitrarily deep) producer
        # chain into both slices — measured ~10x on the SumVec verifier
        # where the producer is a 16k-wide field multiply
        if m > 2:
            v = anti_recompute_barrier(v)
        half = m // 2
        a = fmap(lambda x: jax.lax.slice_in_dim(x, 0, half, axis=axis), v)
        b = fmap(lambda x: jax.lax.slice_in_dim(x, half, m, axis=axis), v)
        v = jf.add(a, b)
        m = half
    return fmap(lambda x: jnp.squeeze(x, axis=axis), v)


def fdot(jf, a, b, axis=-1):
    """Inner product along an axis."""
    return fsum(jf, jf.mul(a, b), axis=axis)


@partial(jax.jit, static_argnums=0)
def _jit_mul(jf, a, b):
    return jf.mul(a, b)


def is_zero(v):
    m = v[0] == _ZERO
    for x in v[1:]:
        m = m & (x == _ZERO)
    return m
