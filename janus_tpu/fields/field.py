"""Host-side field arithmetic on Python ints.

Used for (a) precomputing constants (NTT twiddles, inverses, generator
powers) that are shipped to device as arrays, and (b) as the oracle in
differential tests of the JAX implementations.

The parameters mirror the VDAF-07 fields the reference's `prio` dependency
uses (see SURVEY.md section 2.2).
"""

from __future__ import annotations


class _FieldMeta(type):
    def __repr__(cls):
        return cls.__name__


class Field(metaclass=_FieldMeta):
    """A prime field. Subclasses set MODULUS, GEN, NUM_ROOTS_LOG2, ENCODED_SIZE."""

    MODULUS: int
    GEN: int  # multiplicative group generator
    NUM_ROOTS_LOG2: int  # 2-adicity: 2^k | p-1
    ENCODED_SIZE: int  # bytes, little-endian

    @classmethod
    def add(cls, a: int, b: int) -> int:
        return (a + b) % cls.MODULUS

    @classmethod
    def sub(cls, a: int, b: int) -> int:
        return (a - b) % cls.MODULUS

    @classmethod
    def mul(cls, a: int, b: int) -> int:
        return (a * b) % cls.MODULUS

    @classmethod
    def neg(cls, a: int) -> int:
        return (-a) % cls.MODULUS

    @classmethod
    def pow(cls, a: int, e: int) -> int:
        return pow(a, e, cls.MODULUS)

    @classmethod
    def inv(cls, a: int) -> int:
        return pow(a, cls.MODULUS - 2, cls.MODULUS)

    @classmethod
    def root_of_unity(cls, order: int) -> int:
        """Primitive `order`-th root of unity; order must be a power of two."""
        assert order & (order - 1) == 0
        assert order <= 1 << cls.NUM_ROOTS_LOG2
        return pow(cls.GEN, (cls.MODULUS - 1) // order, cls.MODULUS)

    @classmethod
    def encode(cls, a: int) -> bytes:
        return a.to_bytes(cls.ENCODED_SIZE, "little")

    @classmethod
    def decode(cls, data: bytes) -> int:
        assert len(data) == cls.ENCODED_SIZE
        v = int.from_bytes(data, "little")
        if v >= cls.MODULUS:
            raise ValueError("field element out of range")
        return v

    @classmethod
    def encode_vec(cls, vec) -> bytes:
        return b"".join(cls.encode(int(x)) for x in vec)

    @classmethod
    def decode_vec(cls, data: bytes) -> list[int]:
        n = cls.ENCODED_SIZE
        if len(data) % n:
            raise ValueError("bad field vector length")
        return [cls.decode(data[i : i + n]) for i in range(0, len(data), n)]


class Field64(Field):
    MODULUS = 2**64 - 2**32 + 1  # 18446744069414584321
    GEN = 7
    NUM_ROOTS_LOG2 = 32
    ENCODED_SIZE = 8


class Field128(Field):
    MODULUS = 2**128 - 7 * 2**66 + 1  # 340282366920938462946865773367900766209
    GEN = 7
    NUM_ROOTS_LOG2 = 66
    ENCODED_SIZE = 16


def _selfcheck() -> None:
    for f in (Field64, Field128):
        p = f.MODULUS
        assert (p - 1) % (1 << f.NUM_ROOTS_LOG2) == 0
        # GEN generates: g^((p-1)/2) != 1 and g^((p-1)/q) != 1 for small q
        assert pow(f.GEN, (p - 1) // 2, p) != 1
        w = f.root_of_unity(1 << f.NUM_ROOTS_LOG2)
        assert pow(w, 1 << (f.NUM_ROOTS_LOG2 - 1), p) != 1
        assert pow(w, 1 << f.NUM_ROOTS_LOG2, p) == 1


_selfcheck()
