"""taskprov peer-aggregator model + verify-key derivation.

Equivalent of reference aggregator_core/src/taskprov.rs:20-260: a
`PeerAggregator` is the pre-shared relationship with another DAP
aggregator that allows tasks to be provisioned in-band (the
`dap-taskprov` header), including the preshared `verify_key_init` from
which each provisioned task's VDAF verify key is derived with
HKDF-SHA256 per draft-wang-ppm-dap-taskprov-04 section 3.2.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import secrets
from dataclasses import dataclass, replace

from .core.auth import AuthenticationToken
from .core.hpke import generate_hpke_config_and_private_key
from .messages import Duration, HpkeConfig, Role, TaskId

VERIFY_KEY_INIT_LENGTH = 32

# draft-wang-ppm-dap-taskprov-04 section 3.2: HKDF salt = SHA-256("dap-taskprov")
TASKPROV_SALT = hashlib.sha256(b"dap-taskprov").digest()


def hkdf_sha256(salt: bytes, ikm: bytes, info: bytes, length: int) -> bytes:
    """RFC 5869 HKDF-Extract + Expand with SHA-256."""
    prk = hmac.new(salt, ikm, hashlib.sha256).digest()
    out = b""
    block = b""
    counter = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([counter]), hashlib.sha256).digest()
        out += block
        counter += 1
    return out[:length]


@dataclass(frozen=True)
class PeerAggregator:
    """Pre-shared peer relationship (reference aggregator_core/src/taskprov.rs:92).

    (endpoint, role) uniquely identify the peer; `role` is the role the
    PEER plays in provisioned tasks.
    """

    endpoint: str
    role: Role
    verify_key_init: bytes
    collector_hpke_config: HpkeConfig
    report_expiry_age: Duration | None
    tolerable_clock_skew: Duration
    aggregator_auth_tokens: tuple[AuthenticationToken, ...]
    collector_auth_tokens: tuple[AuthenticationToken, ...]

    def __post_init__(self):
        assert self.role in (Role.LEADER, Role.HELPER)
        assert len(self.verify_key_init) == VERIFY_KEY_INIT_LENGTH

    # --- auth (reference taskprov.rs:206-235) ---
    def primary_aggregator_auth_token(self) -> AuthenticationToken:
        return self.aggregator_auth_tokens[-1]

    def check_aggregator_auth(self, headers) -> bool:
        return any(t.matches_headers(headers) for t in self.aggregator_auth_tokens)

    def primary_collector_auth_token(self) -> AuthenticationToken:
        return self.collector_auth_tokens[-1]

    def check_collector_auth(self, headers) -> bool:
        return any(t.matches_headers(headers) for t in self.collector_auth_tokens)

    # --- verify-key derivation (reference taskprov.rs:239-260) ---
    def derive_vdaf_verify_key(self, task_id: TaskId, length: int = 16) -> bytes:
        return hkdf_sha256(TASKPROV_SALT, self.verify_key_init, task_id.data, length)

    # --- serialization (datastore row payload) ---
    def to_dict(self) -> dict:
        return {
            "endpoint": self.endpoint,
            "role": int(self.role),
            "verify_key_init": base64.urlsafe_b64encode(self.verify_key_init).decode(),
            "collector_hpke_config": base64.urlsafe_b64encode(
                self.collector_hpke_config.to_bytes()
            ).decode(),
            "report_expiry_age": (
                self.report_expiry_age.seconds if self.report_expiry_age else None
            ),
            "tolerable_clock_skew": self.tolerable_clock_skew.seconds,
            "aggregator_auth_tokens": [t.to_dict() for t in self.aggregator_auth_tokens],
            "collector_auth_tokens": [t.to_dict() for t in self.collector_auth_tokens],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PeerAggregator":
        return cls(
            endpoint=d["endpoint"],
            role=Role(d["role"]),
            verify_key_init=base64.urlsafe_b64decode(d["verify_key_init"]),
            collector_hpke_config=HpkeConfig.from_bytes(
                base64.urlsafe_b64decode(d["collector_hpke_config"])
            ),
            report_expiry_age=(
                Duration(d["report_expiry_age"])
                if d.get("report_expiry_age") is not None
                else None
            ),
            tolerable_clock_skew=Duration(d["tolerable_clock_skew"]),
            aggregator_auth_tokens=tuple(
                AuthenticationToken.from_dict(t) for t in d["aggregator_auth_tokens"]
            ),
            collector_auth_tokens=tuple(
                AuthenticationToken.from_dict(t) for t in d["collector_auth_tokens"]
            ),
        )


class PeerAggregatorBuilder:
    """Test/provisioning builder (reference taskprov.rs test_util)."""

    def __init__(self):
        self._peer = PeerAggregator(
            endpoint="https://example.com/",
            role=Role.LEADER,
            verify_key_init=secrets.token_bytes(VERIFY_KEY_INIT_LENGTH),
            collector_hpke_config=generate_hpke_config_and_private_key(
                config_id=201
            ).config,
            report_expiry_age=None,
            tolerable_clock_skew=Duration(60),
            aggregator_auth_tokens=(AuthenticationToken.random_bearer(),),
            collector_auth_tokens=(AuthenticationToken.random_bearer(),),
        )

    def with_(self, **kwargs) -> "PeerAggregatorBuilder":
        self._peer = replace(self._peer, **kwargs)
        return self

    def build(self) -> PeerAggregator:
        return self._peer
