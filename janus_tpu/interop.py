"""Interop test API (draft-dcook-ppm-dap-interop-test-design).

Equivalent of the reference's interop_binaries crate: three HTTP
servers — client (`/internal/test/upload`,
janus_interop_client.rs:215-233), aggregator
(`/internal/test/{ready,endpoint_for_task,add_task}` embedding the
full aggregator plus in-process job runners,
janus_interop_aggregator.rs:121-160) and collector
(`add_task`/`collection_start`/`collection_poll`). These let any
conforming DAP implementation drive ours (and vice versa) through a
implementation-neutral JSON API.

Numbers travel as JSON strings per the draft (u64/u128 don't fit
JSON doubles); both forms are accepted on input.
"""

from __future__ import annotations

import base64
import json
import logging
import secrets
import threading
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .aggregator import Aggregator, Config
from .aggregator.aggregation_job_creator import (
    AggregationJobCreator,
    AggregationJobCreatorConfig,
)
from .aggregator.aggregation_job_driver import AggregationJobDriver
from .aggregator.collection_job_driver import CollectionJobDriver
from .aggregator.http_handlers import DapHttpApp
from .aggregator.job_driver import JobDriver, JobDriverConfig, Stopper
from .client import Client, ClientParameters
from .collector import CollectionJobNotReady, Collector, CollectorParameters
from .core.auth import AuthenticationToken
from .core.hpke import generate_hpke_config_and_private_key
from .core.http_client import HttpClient
from .core.time_util import RealClock
from .datastore.store import Datastore
from .messages import (
    BatchId,
    CollectionJobId,
    Duration,
    FixedSize,
    FixedSizeQuery,
    HpkeConfig,
    Interval,
    Query,
    Role,
    TaskId,
    Time,
    TimeInterval,
)
from .task import QueryTypeConfig, Task
from .vdaf.registry import VdafInstance

log = logging.getLogger(__name__)


def unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def b64(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


def vdaf_from_object(obj: dict) -> VdafInstance:
    """Interop VdafObject -> VdafInstance (reference
    interop_binaries/src/lib.rs VdafObject).

    The interop API exists for CROSS-IMPLEMENTATION pairing, so tasks
    default to the spec framing (`xof_mode: draft`, the VDAF-07
    construction a conformant peer speaks — count/sum run it on device,
    vdaf.draft_jax). Same-framework pairs can opt into the fast TPU
    framing with ``"xof_mode": "fast"`` in the VdafObject."""
    import dataclasses

    typ = obj["type"]
    geti = lambda k, d=0: int(obj.get(k, d))
    if typ == "Prio3Count":
        inst = VdafInstance.count()
    elif typ == "Prio3CountVec":
        inst = VdafInstance.count_vec(length=geti("length"), chunk_length=geti("chunk_length"))
    elif typ == "Prio3Sum":
        inst = VdafInstance.sum(bits=geti("bits"))
    elif typ == "Prio3SumVec":
        inst = VdafInstance.sum_vec(
            length=geti("length"), bits=geti("bits"), chunk_length=geti("chunk_length")
        )
    elif typ == "Prio3Histogram":
        inst = VdafInstance.histogram(length=geti("length"), chunk_length=geti("chunk_length"))
    elif typ.startswith("Prio3FixedPoint") and typ.endswith("BitBoundedL2VecSum"):
        bits = int(typ.removeprefix("Prio3FixedPoint").removesuffix("BitBoundedL2VecSum"))
        inst = VdafInstance.fixed_point_vec(length=geti("length"), bits=bits)
    else:
        raise ValueError(f"unsupported VDAF type {typ!r}")
    mode = str(obj.get("xof_mode", "draft"))
    if mode not in ("fast", "draft"):
        raise ValueError(f"unknown xof_mode {mode!r} (want 'fast' or 'draft')")
    return dataclasses.replace(inst, xof_mode=mode)


def measurement_from_json(vdaf: VdafInstance, measurement):
    if vdaf.kind in ("count", "sum", "histogram"):
        return int(measurement)
    if vdaf.kind in ("sumvec", "countvec"):
        return [int(x) for x in measurement]
    if vdaf.kind == "fixedpoint":
        # decimal strings in [-1, 1), matching result_to_json's scale
        scale = 1 << (vdaf.bits - 1)
        return [round(float(x) * scale) for x in measurement]
    raise ValueError(vdaf.kind)


def result_to_json(vdaf: VdafInstance, result):
    if vdaf.kind in ("count", "sum"):
        return str(result)
    if vdaf.kind == "fixedpoint":
        return [float(x) for x in result]
    return [str(x) for x in result]


class _JsonServer:
    """POST-only JSON-over-HTTP shell shared by the three servers."""

    def __init__(self, routes, dap_app=None, host: str = "127.0.0.1", port: int = 0):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _read_body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def do_POST(self):  # noqa: N802
                body = self._read_body()  # read exactly once per request
                handler = routes.get(self.path)
                if handler is not None:
                    try:
                        doc = json.loads(body) if body else {}
                        resp = handler(doc)
                    except Exception as e:
                        log.exception("interop handler error")
                        resp = {"status": "error", "error": f"{type(e).__name__}: {e}"}
                    out = json.dumps(resp).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    self.wfile.write(out)
                    return
                self._dap("POST", body)

            def do_GET(self):  # noqa: N802
                self._dap("GET", self._read_body())

            def do_PUT(self):  # noqa: N802
                self._dap("PUT", self._read_body())

            def do_DELETE(self):  # noqa: N802
                self._dap("DELETE", self._read_body())

            def _dap(self, method, body: bytes):
                """Non-interop paths serve the embedded DAP app (the
                reference mounts the aggregator under the same listener)."""
                if dap_app is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                from urllib.parse import parse_qsl, urlsplit

                parts = urlsplit(self.path)
                query = dict(parse_qsl(parts.query))
                status, ctype, out, _hdrs = dap_app.handle(
                    method, parts.path, query, self.headers, body
                )
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                if out:
                    self.wfile.write(out)

            def log_message(self, fmt, *args):
                pass

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="interop-listener", daemon=True
        )

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}/"

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


# ---------------------------------------------------------------------------
# Interop client
# ---------------------------------------------------------------------------


class InteropClient:
    """reference janus_interop_client.rs: upload via the test API."""

    def __init__(self, http=None, clock=None):
        self.http = http or HttpClient()
        self.clock = clock or RealClock()
        self._clients: dict[str, Client] = {}
        self._lock = threading.Lock()

    def handle_upload(self, doc: dict) -> dict:
        vdaf = vdaf_from_object(doc["vdaf"])
        with self._lock:
            client = self._clients.get(doc["task_id"])
        if client is None:
            params = ClientParameters(
                TaskId(unb64(doc["task_id"])),
                doc["leader"],
                doc["helper"],
                Duration(int(doc["time_precision"])),
            )
            client = Client.with_fetched_configs(params, vdaf, self.http, clock=self.clock)
            with self._lock:
                self._clients[doc["task_id"]] = client
        when = Time(int(doc["time"])) if "time" in doc else None
        client.upload(measurement_from_json(vdaf, doc["measurement"]), when=when)
        return {"status": "success"}

    def server(self, host="127.0.0.1", port=0) -> _JsonServer:
        return _JsonServer(
            {
                "/internal/test/ready": lambda doc: {},
                "/internal/test/upload": self.handle_upload,
            },
            host=host,
            port=port,
        )


# ---------------------------------------------------------------------------
# Interop aggregator
# ---------------------------------------------------------------------------


class InteropAggregator:
    """reference janus_interop_aggregator.rs: the full aggregator plus
    in-process job runners, administered through the test API."""

    def __init__(self, ds: Datastore, clock=None):
        self.ds = ds
        self.clock = clock or RealClock()
        self.aggregator = Aggregator(ds, self.clock, Config())
        self.dap_app = DapHttpApp(self.aggregator)
        self._stopper = Stopper()
        self._runner: threading.Thread | None = None

    # --- job runners (reference embeds drivers in-process, :121-160) ---
    def start_job_runners(self) -> None:
        # Generous HTTP timeout: the peer's FIRST request jit-compiles its
        # batched VDAF engine (tens of seconds cold); a short timeout breaks
        # the pipe and wastes a lease round-trip. Short lease so a failed
        # step retries quickly in test settings.
        http = HttpClient(timeout=180.0)
        creator = AggregationJobCreator(
            self.ds, AggregationJobCreatorConfig(min_aggregation_job_size=1)
        )
        agg_driver = AggregationJobDriver(self.ds, http)
        agg_jd = JobDriver(JobDriverConfig(), agg_driver.acquirer(15), agg_driver.stepper)
        col_driver = CollectionJobDriver(self.ds, http)
        col_jd = JobDriver(JobDriverConfig(), col_driver.acquirer(15), col_driver.stepper)

        def loop():
            # Prefer finishing aggregation before stepping collection jobs
            # (an interop harness uploads then immediately collects), but
            # bound the deferral so a steady upload trickle cannot starve
            # collection forever.
            quiet = 0
            deferred = 0
            while not self._stopper.stopped:
                try:
                    created = creator.run_once()
                    stepped = agg_jd.run_once()
                    quiet = quiet + 1 if (created == 0 and stepped == 0) else 0
                    if quiet >= 2 or deferred >= 20:
                        col_jd.run_once()
                        deferred = 0
                    else:
                        deferred += 1
                except Exception:
                    log.exception("interop job runner pass failed")
                self._stopper.wait(0.3)

        self._runner = threading.Thread(target=loop, name="interop-runner", daemon=True)
        self._runner.start()

    def stop(self) -> None:
        self._stopper.stop()
        if self._runner is not None:
            self._runner.join(timeout=10)

    # --- test API handlers ---
    def handle_ready(self, doc: dict) -> dict:
        return {}

    def handle_endpoint_for_task(self, doc: dict) -> dict:
        return {"status": "success", "endpoint": "/"}

    def handle_add_task(self, doc: dict) -> dict:
        role = Role.LEADER if doc["role"] == "leader" else Role.HELPER
        vdaf = vdaf_from_object(doc["vdaf"])
        qcode = int(doc["query_type"])
        if qcode == TimeInterval.CODE:
            qt = QueryTypeConfig.time_interval()
        elif qcode == FixedSize.CODE:
            mbs = doc.get("max_batch_size")
            qt = QueryTypeConfig.fixed_size(int(mbs) if mbs is not None else None)
        else:
            raise ValueError(f"unsupported query type {qcode}")
        leader_token = AuthenticationToken.bearer(doc["leader_authentication_token"])
        collector_token = None
        if role == Role.LEADER:
            collector_token = AuthenticationToken.bearer(
                doc["collector_authentication_token"]
            )
        task = Task(
            task_id=TaskId(unb64(doc["task_id"])),
            leader_aggregator_endpoint=doc["leader"],
            helper_aggregator_endpoint=doc["helper"],
            query_type=qt,
            vdaf=vdaf,
            role=role,
            vdaf_verify_key=unb64(doc["vdaf_verify_key"]),
            max_batch_query_count=int(doc.get("max_batch_query_count", 1)),
            task_expiration=(
                Time(int(doc["task_expiration"]))
                if doc.get("task_expiration") is not None
                else None
            ),
            report_expiry_age=None,
            min_batch_size=int(doc["min_batch_size"]),
            time_precision=Duration(int(doc["time_precision"])),
            tolerable_clock_skew=Duration(60),
            collector_hpke_config=HpkeConfig.from_bytes(
                unb64(doc["collector_hpke_config"])
            ),
            aggregator_auth_token=leader_token,
            collector_auth_token=collector_token,
            hpke_keys=(generate_hpke_config_and_private_key(config_id=0),),
        )
        self.ds.run_tx(lambda tx: tx.put_task(task), "interop_add_task")
        # Warm the engine now: add_task has no client timeout, whereas
        # the job runners' short test leases (15s) cannot absorb a
        # first multi-minute engine compile mid-protocol.
        from .binary_utils import warmup_engines

        warmup_engines(self.ds)
        return {"status": "success"}

    def server(self, host="127.0.0.1", port=0) -> _JsonServer:
        return _JsonServer(
            {
                "/internal/test/ready": self.handle_ready,
                "/internal/test/endpoint_for_task": self.handle_endpoint_for_task,
                "/internal/test/add_task": self.handle_add_task,
            },
            dap_app=self.dap_app,
            host=host,
            port=port,
        )


# ---------------------------------------------------------------------------
# Interop collector
# ---------------------------------------------------------------------------


@dataclass
class _CollectorTaskState:
    collector: Collector
    auth_token: AuthenticationToken


@dataclass
class _CollectionHandle:
    collector: Collector
    job_id: CollectionJobId
    query: Query
    vdaf: VdafInstance
    agg_param: bytes


class InteropCollector:
    """reference janus_interop_collector.rs: add_task (generates the
    collector HPKE keypair), collection_start, collection_poll."""

    def __init__(self, http=None):
        self.http = http or HttpClient()
        self._tasks: dict[str, _CollectorTaskState] = {}
        self._handles: dict[str, _CollectionHandle] = {}
        self._lock = threading.Lock()

    def handle_add_task(self, doc: dict) -> dict:
        vdaf = vdaf_from_object(doc["vdaf"])
        kp = generate_hpke_config_and_private_key(config_id=200)
        token = AuthenticationToken.bearer(doc["collector_authentication_token"])
        params = CollectorParameters(
            TaskId(unb64(doc["task_id"])), doc["leader"], token, kp
        )
        with self._lock:
            self._tasks[doc["task_id"]] = _CollectorTaskState(
                Collector(params, vdaf, self.http), token
            )
        return {
            "status": "success",
            "collector_hpke_config": b64(kp.config.to_bytes()),
        }

    def _query_from_json(self, doc: dict) -> Query:
        q = doc["query"]
        qcode = int(q["type"])
        if qcode == TimeInterval.CODE:
            return Query.time_interval(
                Interval(
                    Time(int(q["batch_interval_start"])),
                    Duration(int(q["batch_interval_duration"])),
                )
            )
        if qcode == FixedSize.CODE:
            sub = q.get("subtype")
            if sub is not None and int(sub) == FixedSizeQuery.BY_BATCH_ID:
                return Query.fixed_size(
                    FixedSizeQuery(FixedSizeQuery.BY_BATCH_ID, BatchId(unb64(q["batch_id"])))
                )
            return Query.fixed_size(FixedSizeQuery(FixedSizeQuery.CURRENT_BATCH))
        raise ValueError(f"unsupported query type {qcode}")

    def handle_collection_start(self, doc: dict) -> dict:
        state = self._tasks[doc["task_id"]]
        query = self._query_from_json(doc)
        agg_param = unb64(doc.get("agg_param", ""))
        job_id = state.collector.start_collection(query, agg_param)
        handle = b64(secrets.token_bytes(16))
        with self._lock:
            self._handles[handle] = _CollectionHandle(
                state.collector, job_id, query, state.collector.vdaf, agg_param
            )
        return {"status": "success", "handle": handle}

    def handle_collection_poll(self, doc: dict) -> dict:
        with self._lock:
            h = self._handles[doc["handle"]]
        try:
            res = h.collector.poll_once(h.job_id, h.query, h.agg_param)
        except CollectionJobNotReady:
            return {"status": "in progress"}
        out = {
            "status": "complete",
            "report_count": str(res.report_count),
            "result": result_to_json(h.vdaf, res.aggregate_result),
        }
        if h.query.query_type == FixedSize.CODE and res.partial_batch_selector is not None:
            out["batch_id"] = b64(res.partial_batch_selector.batch_id.data)
        return out

    def server(self, host="127.0.0.1", port=0) -> _JsonServer:
        return _JsonServer(
            {
                "/internal/test/ready": lambda doc: {},
                "/internal/test/add_task": self.handle_add_task,
                "/internal/test/collection_start": self.handle_collection_start,
                "/internal/test/collection_poll": self.handle_collection_poll,
            },
            host=host,
            port=port,
        )
