"""Differential privacy for aggregate shares.

The reference's DP surface at this version is the taskprov `DpConfig`
wire message with mechanisms Reserved|None (messages/src/taskprov.rs
DpMechanism) — no noise is ever applied. This module goes further and
implements a working zCDP strategy: each aggregator adds exact
discrete-Gaussian noise to its own aggregate share before release, so
the collector's unsharded result carries the summed noise of both
parties (sigma_total = sqrt(2) * sigma per party).

Sampler: the exact discrete Gaussian of Canonne-Kamath-Steinke
(NeurIPS 2020, "The Discrete Gaussian for Differential Privacy"):
rejection-sample a discrete Laplace from Bernoulli(exp(-x/t)) draws,
then accept with a Gaussian correction — no floating-point error in
the distribution's tails, which matters for DP guarantees.
"""

from __future__ import annotations

import math
import secrets
from dataclasses import dataclass
from fractions import Fraction


def _bernoulli(p: Fraction) -> bool:
    """Exact Bernoulli(p) for rational p in [0, 1]."""
    assert 0 <= p <= 1
    # sample a uniform rational in [0,1) bit by bit against p
    num, den = p.numerator, p.denominator
    r = secrets.randbelow(den)
    return r < num


def _bernoulli_exp_frac(gamma: Fraction) -> bool:
    """Bernoulli(exp(-gamma)) for gamma in [0, 1] (CKS algorithm 1)."""
    k = 1
    while True:
        if not _bernoulli(gamma / k):
            return k % 2 == 1
        k += 1


def _bernoulli_exp(gamma: Fraction) -> bool:
    """Bernoulli(exp(-gamma)) for any gamma >= 0."""
    while gamma > 1:
        if not _bernoulli_exp_frac(Fraction(1)):
            return False
        gamma -= 1
    return _bernoulli_exp_frac(gamma)


def _discrete_laplace(t: int) -> int:
    """Discrete Laplace with scale t (CKS algorithm 2): P[X=x] ∝ exp(-|x|/t)."""
    while True:
        u = secrets.randbelow(t)
        if not _bernoulli_exp(Fraction(u, t)):
            continue
        v = 0
        while _bernoulli_exp(Fraction(1)):
            v += 1
        mag = u + t * v
        if secrets.randbelow(2) == 0:
            if mag == 0:
                continue
            return -mag
        return mag


def discrete_gaussian(sigma: Fraction) -> int:
    """Exact discrete Gaussian: P[X=x] ∝ exp(-x^2 / (2 sigma^2))."""
    sigma = Fraction(sigma)
    t = math.floor(sigma) + 1
    sigma2 = sigma * sigma
    while True:
        y = _discrete_laplace(t)
        gamma = (abs(y) - sigma2 / t) ** 2 / (2 * sigma2)
        if _bernoulli_exp(gamma):
            return y


@dataclass(frozen=True)
class DpStrategy:
    """Per-task DP configuration applied by each aggregator to its own
    aggregate share at release time."""

    mechanism: str = "none"  # "none" | "discrete_gaussian"
    sigma: float = 0.0  # per-party noise scale, in field units

    def to_dict(self) -> dict:
        return {"mechanism": self.mechanism, "sigma": self.sigma}

    @classmethod
    def from_dict(cls, d: dict | None) -> "DpStrategy":
        if not d:
            return cls()
        return cls(mechanism=d.get("mechanism", "none"), sigma=float(d.get("sigma", 0.0)))

    @property
    def enabled(self) -> bool:
        return self.mechanism == "discrete_gaussian" and self.sigma > 0


def add_noise_to_agg_share(strategy: DpStrategy, field, share: bytes | None) -> bytes | None:
    """Add per-element discrete-Gaussian noise (mod p) to an encoded
    aggregate share. No-op for mechanism 'none' or an empty share."""
    if share is None or not strategy.enabled:
        return share
    sigma = Fraction(strategy.sigma).limit_denominator(1 << 20)
    vec = field.decode_vec(share)
    noised = [field.add(x, discrete_gaussian(sigma) % field.MODULUS) for x in vec]
    return field.encode_vec(noised)
