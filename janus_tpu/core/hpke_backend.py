"""Crypto primitive backend: `cryptography` when installed, else the
system libcrypto via ctypes.

This image bakes in the JAX toolchain but not the `cryptography`
wheel, which used to make `janus_tpu.core` (and with it the whole
aggregator package and 21 tier-1 test files) unimportable. The HPKE
layer only needs four primitives — AES-GCM, ChaCha20-Poly1305, X25519
and P-256 ECDH — all of which OpenSSL >= 1.1.1 (present wherever
CPython's `ssl` works) provides. This module exposes exactly that
surface:

- ``AESGCM`` / ``ChaCha20Poly1305``: ``ctor(key)`` with
  ``encrypt(nonce, data, aad)`` / ``decrypt(nonce, data, aad)`` —
  the `cryptography` AEAD interface.
- ``x25519_generate() -> (pk, sk)``, ``x25519_public(sk)``,
  ``x25519_exchange(sk, peer_pk)`` over raw 32-byte strings.
- ``p256_generate() -> (pk_uncompressed, sk_be32)``,
  ``p256_exchange(sk_be32, peer_uncompressed) -> x_be32``.
- Batch forms for the ingest hot path (docs/INGEST.md "Batched
  decrypt"): ``x25519_exchange_batch(sk, peer_pks)`` runs a whole
  decrypt window's exchanges through ONE private-key object and ONE
  derive context (the per-call EVP_PKEY parse + ctx create + free is
  ~60% of a scalar exchange through ctypes), and
  ``aead_open_batch(ctor, keys, nonces, cts, aads)`` opens a window
  through one reused cipher context. Failed lanes come back as None
  instead of raising, so one bad report can't fail its window.
- ``BATCH_RELEASES_GIL``: True when the batch calls release the GIL
  (the `cryptography` wheel does around its own native code). The
  ctypes-libcrypto fallback deliberately holds it (PyDLL, see below),
  so the ingest decrypt pool sizes itself from this flag instead of
  assuming crypto parallelism that isn't there.

When `cryptography` is importable the functions delegate to it
(identical behavior to the previous hard dependency); otherwise AEAD +
X25519 go through libcrypto's EVP interface and P-256 ECDH through
libcrypto's EC_KEY/ECDH_compute_key (constant-time scalar mult, like
every other production path). Only when those EC symbols are absent
does P-256 fall back to ~40 lines of affine curve arithmetic on
Python ints (scalar mult + on-curve validation only — no signing, no
wire parsing beyond the X9.62 uncompressed point). That Python ladder
is VARIABLE-TIME in the private scalar: acceptable for the ephemeral
encap side, but a long-term decap key served to untrusted clients
would leak timing — hence it is strictly the last resort and logs a
warning at import. Byte-exactness of every suite is enforced by the
RFC 9180 vector corpus in tests/test_hpke_vectors.py.
"""

from __future__ import annotations

import secrets

__all__ = [
    "BACKEND",
    "BATCH_RELEASES_GIL",
    "AESGCM",
    "ChaCha20Poly1305",
    "aead_open_batch",
    "x25519_generate",
    "x25519_public",
    "x25519_exchange",
    "x25519_exchange_batch",
    "p256_generate",
    "p256_exchange",
]

try:  # pragma: no cover - exercised where the wheel exists
    from cryptography.hazmat.primitives.asymmetric import ec as _ec
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey as _X25519Priv,
        X25519PublicKey as _X25519Pub,
    )
    from cryptography.hazmat.primitives.ciphers.aead import (
        AESGCM,
        ChaCha20Poly1305,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding as _Encoding,
        PublicFormat as _PublicFormat,
    )

    BACKEND = "cryptography"
    # the wheel's AEAD/ECDH primitives release the GIL around their
    # native work, so a batched open parallelizes across pool workers
    BATCH_RELEASES_GIL = True

    def x25519_generate() -> tuple[bytes, bytes]:
        sk = _X25519Priv.generate()
        return sk.public_key().public_bytes_raw(), sk.private_bytes_raw()

    def x25519_public(sk: bytes) -> bytes:
        return _X25519Priv.from_private_bytes(sk).public_key().public_bytes_raw()

    def x25519_exchange(sk: bytes, peer_pk: bytes) -> bytes:
        return _X25519Priv.from_private_bytes(sk).exchange(
            _X25519Pub.from_public_bytes(peer_pk)
        )

    def x25519_exchange_batch(sk: bytes, peer_pks) -> list:
        """One decap key against a window of encapsulated keys; a bad
        lane (malformed point) is None, never an exception — the HPKE
        layer maps it to that report's reject."""
        priv = _X25519Priv.from_private_bytes(sk)
        out = []
        for pk in peer_pks:
            if pk is None:
                out.append(None)
                continue
            try:
                out.append(priv.exchange(_X25519Pub.from_public_bytes(pk)))
            except Exception:
                out.append(None)
        return out

    def aead_open_batch(ctor, keys, nonces, cts, aads) -> list:
        """Open a window of AEAD ciphertexts (same algorithm, per-lane
        keys/nonces). Failed lanes (auth failure, malformed input, or a
        None key from an upstream failed lane) are None."""
        out = []
        for key, nonce, ct, aad in zip(keys, nonces, cts, aads):
            if key is None:
                out.append(None)
                continue
            try:
                out.append(ctor(key).decrypt(nonce, ct, aad or None))
            except Exception:
                out.append(None)
        return out

    _CURVE = _ec.SECP256R1()

    def p256_generate() -> tuple[bytes, bytes]:
        sk = _ec.generate_private_key(_CURVE)
        pk = sk.public_key().public_bytes(
            _Encoding.X962, _PublicFormat.UncompressedPoint
        )
        return pk, sk.private_numbers().private_value.to_bytes(32, "big")

    def p256_exchange(sk: bytes, peer_pk: bytes) -> bytes:
        priv = _ec.derive_private_key(int.from_bytes(sk, "big"), _CURVE)
        pub = _ec.EllipticCurvePublicKey.from_encoded_point(_CURVE, peer_pk)
        return priv.exchange(_ec.ECDH(), pub)

except ImportError:
    import ctypes
    import ctypes.util
    import threading

    BACKEND = "libcrypto"
    # PyDLL holds the GIL across every EVP call (deliberately — see the
    # convoy note below), so a batched open through this backend
    # serializes pool workers; the ingest pipeline sizes its decrypt
    # pool from this flag (docs/INGEST.md "Batched decrypt").
    BATCH_RELEASES_GIL = False

    _name = ctypes.util.find_library("crypto")
    # PyDLL, not CDLL: these EVP/EC calls are microsecond-scale and
    # never call back into Python, but a CDLL handle releases and
    # reacquires the GIL around EVERY call — and one hpke_open makes
    # dozens of them. Under a threaded server (the ingest decrypt
    # pool + handler pool) that per-call release triggers the new-GIL
    # convoy effect: each reacquire can wait a full switch interval
    # behind the other runnable threads. Measured on a 2-core host:
    # 8-thread hpke_open ran 7x SLOWER than single-threaded through
    # CDLL; through PyDLL threaded matches serial. The bulk work the
    # decrypt pool actually parallelizes (numpy share validation)
    # releases the GIL on its own.
    _lib = ctypes.PyDLL(_name or "libcrypto.so")

    _vp = ctypes.c_void_p
    _int = ctypes.c_int
    _sz = ctypes.c_size_t
    _cp = ctypes.c_char_p

    def _fn(name, restype, argtypes):
        f = getattr(_lib, name)
        f.restype = restype
        f.argtypes = argtypes
        return f

    # EVP AEAD
    _ctx_new = _fn("EVP_CIPHER_CTX_new", _vp, [])
    _ctx_free = _fn("EVP_CIPHER_CTX_free", None, [_vp])
    _ctx_reset = _fn("EVP_CIPHER_CTX_reset", _int, [_vp])
    _init = _fn("EVP_CipherInit_ex", _int, [_vp, _vp, _vp, _cp, _cp, _int])
    _ctrl = _fn("EVP_CIPHER_CTX_ctrl", _int, [_vp, _int, _int, _vp])
    _update = _fn("EVP_CipherUpdate", _int, [_vp, _cp, ctypes.POINTER(_int), _cp, _int])
    _final = _fn("EVP_CipherFinal_ex", _int, [_vp, _cp, ctypes.POINTER(_int)])
    _aes128 = _fn("EVP_aes_128_gcm", _vp, [])
    _aes256 = _fn("EVP_aes_256_gcm", _vp, [])
    _chacha = _fn("EVP_chacha20_poly1305", _vp, [])

    # The EVP_CIPHER objects are process-lifetime statics: fetch each
    # once at import instead of one EVP_aes_128_gcm() ctypes round-trip
    # per encrypt/decrypt call.
    _AES128_CIPHER = _aes128()
    _AES256_CIPHER = _aes256()
    _CHACHA_CIPHER = _chacha()

    _SET_IVLEN, _GET_TAG, _SET_TAG = 0x9, 0x10, 0x11
    _TAG = 16

    # Reusable EVP_CIPHER_CTX pool: context allocation + free was a
    # malloc/free pair and two ctypes calls on EVERY AEAD op. A context
    # is fully re-initialized by EVP_CIPHER_CTX_reset + EVP_CipherInit_ex
    # at the top of each run, so pooled reuse is safe across keys,
    # ciphers and threads (a context is only ever held by one caller at
    # a time; the pool hands it out under a lock). Batch opens hold one
    # context for their whole window.
    _CTX_POOL: list = []
    _CTX_POOL_LOCK = threading.Lock()
    _CTX_POOL_CAP = 16

    def _ctx_acquire():
        with _CTX_POOL_LOCK:
            if _CTX_POOL:
                return _CTX_POOL.pop()
        ctx = _ctx_new()
        if not ctx:
            raise MemoryError("EVP_CIPHER_CTX_new failed")
        return ctx

    def _ctx_release(ctx) -> None:
        with _CTX_POOL_LOCK:
            if len(_CTX_POOL) < _CTX_POOL_CAP:
                _CTX_POOL.append(ctx)
                return
        _ctx_free(ctx)

    def _aead_run(cipher, key, nonce, data, aad, enc: bool, ctx=None) -> bytes:
        own_ctx = ctx is None
        if own_ctx:
            ctx = _ctx_acquire()
        try:
            # reset FIRST: the context may carry a previous op's state
            # (including a failed one) — reset returns it to fresh
            if _ctx_reset(ctx) != 1:
                raise ValueError("cipher ctx reset failed")
            if _init(ctx, cipher, None, None, None, int(enc)) != 1:
                raise ValueError("cipher init failed")
            # 12 bytes is the default IV length of all three AEADs;
            # only non-default lengths need the ctrl round-trip
            if len(nonce) != 12 and _ctrl(ctx, _SET_IVLEN, len(nonce), None) != 1:
                raise ValueError("bad nonce length")
            if _init(ctx, None, None, key, nonce, int(enc)) != 1:
                raise ValueError("key/nonce init failed")
            if enc:
                pt = data
            else:
                if len(data) < _TAG:
                    raise ValueError("ciphertext shorter than tag")
                pt, tag = data[:-_TAG], data[-_TAG:]
                if _ctrl(ctx, _SET_TAG, _TAG, ctypes.create_string_buffer(tag, _TAG)) != 1:
                    raise ValueError("set tag failed")
            outl = _int(0)
            if aad and _update(ctx, None, ctypes.byref(outl), aad, len(aad)) != 1:
                raise ValueError("aad update failed")
            out = ctypes.create_string_buffer(max(1, len(pt)))
            if _update(ctx, out, ctypes.byref(outl), pt, len(pt)) != 1:
                raise ValueError("update failed")
            n = outl.value
            fin = ctypes.create_string_buffer(_TAG)
            if _final(ctx, fin, ctypes.byref(outl)) != 1:
                raise ValueError("AEAD decryption failed: invalid tag" if not enc else "final failed")
            n += outl.value
            body = out.raw[:n]
            if not enc:
                return body
            tag = ctypes.create_string_buffer(_TAG)
            if _ctrl(ctx, _GET_TAG, _TAG, tag) != 1:
                raise ValueError("get tag failed")
            return body + tag.raw
        finally:
            if own_ctx:
                _ctx_release(ctx)

    class _EvpAead:
        _key_sizes: tuple[int, ...] = ()

        def __init__(self, key: bytes):
            if len(key) not in self._key_sizes:
                raise ValueError(f"invalid key size {len(key)}")
            self._key = bytes(key)

        def _cipher(self):
            raise NotImplementedError

        def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
            return _aead_run(self._cipher(), self._key, bytes(nonce), bytes(data), bytes(aad or b""), True)

        def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
            return _aead_run(self._cipher(), self._key, bytes(nonce), bytes(data), bytes(aad or b""), False)

    class AESGCM(_EvpAead):
        _key_sizes = (16, 32)

        def _cipher(self):
            return _AES128_CIPHER if len(self._key) == 16 else _AES256_CIPHER

    class ChaCha20Poly1305(_EvpAead):
        _key_sizes = (32,)

        def _cipher(self):
            return _CHACHA_CIPHER

    def aead_open_batch(ctor, keys, nonces, cts, aads) -> list:
        """Open a window of AEAD ciphertexts (same algorithm, per-lane
        keys/nonces) through ONE pooled cipher context held for the
        whole window. Failed lanes (auth failure, malformed input, or a
        None key from an upstream failed lane) are None.

        Specialized against _aead_run for the window shape: HPKE
        nonces are always 12 bytes (every suite's default IV length),
        so cipher + key + nonce initialize in a single
        EVP_CipherInit_ex, and the output/tag scratch buffers are
        allocated once for the window's largest ciphertext instead of
        per lane."""
        n_lanes = len(cts)
        out: list = [None] * n_lanes
        max_pt = 0
        for i in range(n_lanes):
            if keys[i] is not None and len(cts[i]) >= _TAG:
                max_pt = max(max_pt, len(cts[i]) - _TAG)
        buf = ctypes.create_string_buffer(max(1, max_pt))
        tag_buf = ctypes.create_string_buffer(_TAG)
        fin = ctypes.create_string_buffer(_TAG)
        outl = _int(0)
        outl_ref = ctypes.byref(outl)
        # the EVP_CIPHER depends only on the key length (AESGCM picks
        # AES-128 vs AES-256 by it), so it resolves once per length —
        # an HPKE window has one, but the surface stays general
        ciphers: dict = {}
        ctx = _ctx_acquire()
        reset, init, ctrl, update, final, memmove = (
            _ctx_reset, _init, _ctrl, _update, _final, ctypes.memmove,
        )
        try:
            for i in range(n_lanes):
                key = keys[i]
                if key is None:
                    continue
                data = bytes(cts[i])
                if len(data) < _TAG:
                    continue
                cipher = ciphers.get(len(key))
                if cipher is None:
                    try:
                        cipher = ciphers[len(key)] = ctor(key)._cipher()
                    except ValueError:
                        continue
                nonce = bytes(nonces[i])
                if len(nonce) != 12:
                    # non-default IV length needs the split-init +
                    # SET_IVLEN sequence (12 is every AEAD's default;
                    # a shorter nonce through the one-shot init would
                    # be an OOB read, a longer one a silent truncation)
                    try:
                        out[i] = _aead_run(
                            cipher, key, nonce, data, bytes(aads[i] or b""),
                            False, ctx=ctx,
                        )
                    except ValueError:
                        pass
                    continue
                pt, tag = data[:-_TAG], data[-_TAG:]
                aad = bytes(aads[i] or b"")
                memmove(tag_buf, tag, _TAG)
                if (
                    reset(ctx) != 1
                    or init(ctx, cipher, None, key, nonce, 0) != 1
                    or ctrl(ctx, _SET_TAG, _TAG, tag_buf) != 1
                ):
                    continue
                if aad and update(ctx, None, outl_ref, aad, len(aad)) != 1:
                    continue
                if update(ctx, buf, outl_ref, pt, len(pt)) != 1:
                    continue
                n = outl.value
                if final(ctx, fin, outl_ref) != 1:
                    continue  # auth failure: reject this lane only
                out[i] = buf[: n + outl.value]
        finally:
            _ctx_release(ctx)
        return out

    # EVP X25519 (NID_X25519)
    _X25519 = 1034
    _pkey_ctx_new_id = _fn("EVP_PKEY_CTX_new_id", _vp, [_int, _vp])
    _pkey_ctx_new = _fn("EVP_PKEY_CTX_new", _vp, [_vp, _vp])
    _pkey_ctx_free = _fn("EVP_PKEY_CTX_free", None, [_vp])
    _pkey_free = _fn("EVP_PKEY_free", None, [_vp])
    _keygen_init = _fn("EVP_PKEY_keygen_init", _int, [_vp])
    _keygen = _fn("EVP_PKEY_keygen", _int, [_vp, ctypes.POINTER(_vp)])
    _new_raw_priv = _fn("EVP_PKEY_new_raw_private_key", _vp, [_int, _vp, _cp, _sz])
    _new_raw_pub = _fn("EVP_PKEY_new_raw_public_key", _vp, [_int, _vp, _cp, _sz])
    _get_raw_priv = _fn("EVP_PKEY_get_raw_private_key", _int, [_vp, _cp, ctypes.POINTER(_sz)])
    _get_raw_pub = _fn("EVP_PKEY_get_raw_public_key", _int, [_vp, _cp, ctypes.POINTER(_sz)])
    _derive_init = _fn("EVP_PKEY_derive_init", _int, [_vp])
    _derive_peer = _fn("EVP_PKEY_derive_set_peer", _int, [_vp, _vp])
    _derive = _fn("EVP_PKEY_derive", _int, [_vp, _cp, ctypes.POINTER(_sz)])

    def _raw32(getter, pkey) -> bytes:
        buf = ctypes.create_string_buffer(32)
        n = _sz(32)
        if getter(pkey, buf, ctypes.byref(n)) != 1 or n.value != 32:
            raise ValueError("raw key extraction failed")
        return buf.raw

    def x25519_generate() -> tuple[bytes, bytes]:
        pctx = _pkey_ctx_new_id(_X25519, None)
        if not pctx:
            raise MemoryError("EVP_PKEY_CTX_new_id failed")
        try:
            pkey = _vp()
            if _keygen_init(pctx) != 1 or _keygen(pctx, ctypes.byref(pkey)) != 1:
                raise ValueError("X25519 keygen failed")
            try:
                return _raw32(_get_raw_pub, pkey), _raw32(_get_raw_priv, pkey)
            finally:
                _pkey_free(pkey)
        finally:
            _pkey_ctx_free(pctx)

    def x25519_public(sk: bytes) -> bytes:
        # pass the REAL length: a short scalar with a hardcoded 32 was
        # an out-of-bounds read into whatever followed the bytes object
        pkey = _new_raw_priv(_X25519, None, bytes(sk), len(sk))
        if not pkey:
            raise ValueError("bad X25519 private key")
        try:
            return _raw32(_get_raw_pub, pkey)
        finally:
            _pkey_free(pkey)

    def x25519_exchange(sk: bytes, peer_pk: bytes) -> bytes:
        pkey = _new_raw_priv(_X25519, None, bytes(sk), len(sk))
        if not pkey:
            raise ValueError("bad X25519 private key")
        # length passed explicitly (the encapsulated key on the decap
        # side is attacker-controlled: libcrypto must see the actual
        # size and reject it, not read 32 bytes regardless)
        peer = _new_raw_pub(_X25519, None, bytes(peer_pk), len(peer_pk))
        if not peer:
            _pkey_free(pkey)
            raise ValueError("bad X25519 public key")
        pctx = _pkey_ctx_new(pkey, None)
        try:
            if not pctx or _derive_init(pctx) != 1 or _derive_peer(pctx, peer) != 1:
                raise ValueError("X25519 derive init failed")
            out = ctypes.create_string_buffer(32)
            n = _sz(32)
            if _derive(pctx, out, ctypes.byref(n)) != 1 or n.value != 32:
                raise ValueError("X25519 derive failed")
            return out.raw
        finally:
            if pctx:
                _pkey_ctx_free(pctx)
            _pkey_free(peer)
            _pkey_free(pkey)

    def x25519_exchange_batch(sk: bytes, peer_pks) -> list:
        """One decap key against a window of encapsulated keys.

        The scalar form pays an EVP_PKEY parse, a derive-context create
        + init, and three frees PER CALL — ~60% of its measured cost on
        this host (~79 µs scalar vs ~30 µs/lane batched; the X25519
        scalar mult itself is ~28 µs). Here the private key object and
        derive context are built once and each lane only parses its
        peer key, swaps it in with EVP_PKEY_derive_set_peer, and
        derives. Bad lanes (malformed/wrong-length peer keys) are None,
        never an exception — the HPKE layer maps them to that report's
        reject."""
        pkey = _new_raw_priv(_X25519, None, bytes(sk), len(sk))
        if not pkey:
            raise ValueError("bad X25519 private key")
        pctx = _pkey_ctx_new(pkey, None)
        try:
            if not pctx or _derive_init(pctx) != 1:
                raise ValueError("X25519 derive init failed")
            out = ctypes.create_string_buffer(32)
            n = _sz(32)
            n_ref = ctypes.byref(n)
            res: list = []
            append = res.append
            new_pub, set_peer, derive, free = (
                _new_raw_pub, _derive_peer, _derive, _pkey_free,
            )
            for pk in peer_pks:
                if pk is None:
                    append(None)
                    continue
                peer = new_pub(_X25519, None, bytes(pk), len(pk))
                if not peer:
                    append(None)
                    continue
                try:
                    n.value = 32
                    if set_peer(pctx, peer) != 1 or derive(pctx, out, n_ref) != 1 or n.value != 32:
                        append(None)
                        continue
                    append(out.raw)
                finally:
                    free(peer)
            return res
        finally:
            if pctx:
                _pkey_ctx_free(pctx)
            _pkey_free(pkey)

    # P-256 ECDH, preferred path: libcrypto's EC_KEY + ECDH_compute_key
    # (constant-time scalar multiplication). The symbols are deprecated
    # in OpenSSL 3.0 but still exported; if a future libcrypto drops
    # them we fall back to the Python ladder below (variable-time — see
    # module docstring).
    _NID_P256 = 415  # NID_X9_62_prime256v1
    _UNCOMPRESSED = 4  # POINT_CONVERSION_UNCOMPRESSED

    try:
        _ec_key_new = _fn("EC_KEY_new_by_curve_name", _vp, [_int])
        _ec_key_free = _fn("EC_KEY_free", None, [_vp])
        _ec_key_gen = _fn("EC_KEY_generate_key", _int, [_vp])
        _ec_key_set_priv = _fn("EC_KEY_set_private_key", _int, [_vp, _vp])
        _ec_key_get_priv = _fn("EC_KEY_get0_private_key", _vp, [_vp])
        _ec_key_get_pub = _fn("EC_KEY_get0_public_key", _vp, [_vp])
        _ec_key_get_group = _fn("EC_KEY_get0_group", _vp, [_vp])
        _ec_point_new = _fn("EC_POINT_new", _vp, [_vp])
        _ec_point_free = _fn("EC_POINT_free", None, [_vp])
        _ec_oct2point = _fn("EC_POINT_oct2point", _int, [_vp, _vp, _cp, _sz, _vp])
        _ec_point2oct = _fn(
            "EC_POINT_point2oct", _sz, [_vp, _vp, _int, _cp, _sz, _vp]
        )
        _ec_is_on_curve = _fn("EC_POINT_is_on_curve", _int, [_vp, _vp, _vp])
        _ecdh_compute = _fn("ECDH_compute_key", _int, [_cp, _sz, _vp, _vp, _vp])
        _bn_bin2bn = _fn("BN_bin2bn", _vp, [_cp, _int, _vp])
        _bn_bn2binpad = _fn("BN_bn2binpad", _int, [_vp, _cp, _int])
        _bn_free = _fn("BN_free", None, [_vp])
        _HAVE_EC = True
    except AttributeError:  # pragma: no cover - ancient/shorn libcrypto
        import logging

        logging.getLogger(__name__).warning(
            "libcrypto lacks EC_KEY/ECDH symbols; P-256 HPKE falls back "
            "to variable-time Python curve arithmetic (timing side "
            "channel on long-term decap keys)"
        )
        _HAVE_EC = False

    def _ec_p256_generate() -> tuple[bytes, bytes]:
        key = _ec_key_new(_NID_P256)
        if not key:
            raise MemoryError("EC_KEY_new_by_curve_name failed")
        try:
            if _ec_key_gen(key) != 1:
                raise ValueError("P-256 keygen failed")
            sk = ctypes.create_string_buffer(32)
            if _bn_bn2binpad(_ec_key_get_priv(key), sk, 32) != 32:
                raise ValueError("P-256 private key extraction failed")
            pk = ctypes.create_string_buffer(65)
            n = _ec_point2oct(
                _ec_key_get_group(key), _ec_key_get_pub(key),
                _UNCOMPRESSED, pk, 65, None,
            )
            if n != 65:
                raise ValueError("P-256 public key encoding failed")
            return pk.raw, sk.raw
        finally:
            _ec_key_free(key)

    def _ec_p256_exchange(sk: bytes, peer_pk: bytes) -> bytes:
        if len(sk) != 32:
            raise ValueError("bad P-256 private key")
        key = _ec_key_new(_NID_P256)
        if not key:
            raise MemoryError("EC_KEY_new_by_curve_name failed")
        bn = _bn_bin2bn(bytes(sk), 32, None)
        peer = None
        try:
            if not bn or _ec_key_set_priv(key, bn) != 1:
                raise ValueError("bad P-256 private key")
            group = _ec_key_get_group(key)
            peer = _ec_point_new(group)
            if (
                not peer
                or _ec_oct2point(group, peer, bytes(peer_pk), len(peer_pk), None) != 1
                or _ec_is_on_curve(group, peer, None) != 1
            ):
                raise ValueError("bad P-256 public key")
            out = ctypes.create_string_buffer(32)
            if _ecdh_compute(out, 32, peer, key, None) != 32:
                raise ValueError("P-256 ECDH failed")
            return out.raw
        finally:
            if peer:
                _ec_point_free(peer)
            if bn:
                _bn_free(bn)
            _ec_key_free(key)

    # P-256 ECDH on Python ints (affine; modern pow(x, -1, p) inversion).
    # LAST RESORT ONLY (_HAVE_EC False): the double-and-add ladder
    # branches on secret scalar bits — variable-time.
    _PP = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
    _PA = _PP - 3
    _PB = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
    _PN = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
    _PG = (
        0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
        0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
    )

    def _p256_on_curve(x: int, y: int) -> bool:
        return (y * y - (x * x * x + _PA * x + _PB)) % _PP == 0

    def _p256_add(P, Q):
        if P is None:
            return Q
        if Q is None:
            return P
        x1, y1 = P
        x2, y2 = Q
        if x1 == x2:
            if (y1 + y2) % _PP == 0:
                return None
            lam = (3 * x1 * x1 + _PA) * pow(2 * y1, -1, _PP) % _PP
        else:
            lam = (y2 - y1) * pow(x2 - x1, -1, _PP) % _PP
        x3 = (lam * lam - x1 - x2) % _PP
        return x3, (lam * (x1 - x3) - y1) % _PP

    def _p256_mul(k: int, P):
        R = None
        while k:
            if k & 1:
                R = _p256_add(R, P)
            P = _p256_add(P, P)
            k >>= 1
        return R

    def _p256_decode(pk: bytes):
        if len(pk) != 65 or pk[0] != 4:
            raise ValueError("bad P-256 point encoding")
        x = int.from_bytes(pk[1:33], "big")
        y = int.from_bytes(pk[33:], "big")
        if not _p256_on_curve(x, y):
            raise ValueError("P-256 point not on curve")
        return x, y

    def _p256_encode(P) -> bytes:
        x, y = P
        return b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")

    def _py_p256_generate() -> tuple[bytes, bytes]:
        sk = secrets.randbelow(_PN - 1) + 1
        return _p256_encode(_p256_mul(sk, _PG)), sk.to_bytes(32, "big")

    def _py_p256_exchange(sk: bytes, peer_pk: bytes) -> bytes:
        d = int.from_bytes(sk, "big")
        if not 1 <= d < _PN:
            raise ValueError("bad P-256 private key")
        S = _p256_mul(d, _p256_decode(peer_pk))
        if S is None:
            raise ValueError("P-256 ECDH produced the point at infinity")
        return S[0].to_bytes(32, "big")

    p256_generate = _ec_p256_generate if _HAVE_EC else _py_p256_generate
    p256_exchange = _ec_p256_exchange if _HAVE_EC else _py_p256_exchange
