"""In-process TCP fault proxy (toxiproxy-style) for wire-level chaos.

Every prior fault drill injected failures IN-PROCESS (failpoints.py
raises a synthetic URLError before a socket is touched), so the
retry/breaker/lease machinery had never seen a real wire pathology:
mid-body stalls, truncated responses, RSTs, slow-drip bodies,
minutes-long blackholes. `FaultProxy` closes that gap: it listens on a
loopback port, pumps bytes to/from a real upstream (the helper
aggregator), and applies a runtime-togglable chain of *toxics* per
direction — so a REAL leader driver binary talks to a REAL helper
through a hostile wire, from chaos_run and tests, with zero external
dependencies.

Directions follow the toxiproxy convention, named from the proxy
client's point of view:

    "up"   = client -> upstream   (the leader's request bytes)
    "down" = upstream -> client   (the helper's response bytes)

Toxic taxonomy (dicts, so chaos_run schedules read like YAML):

    {"kind": "latency",   "latency_s": 0.05, "jitter_s": 0.02}
        sleep latency±jitter before forwarding each chunk
    {"kind": "bandwidth", "bytes_per_s": 8192}
        cap forward throughput (sleeps len(chunk)/rate per chunk)
    {"kind": "slicer",    "slice_bytes": 64, "delay_s": 0.05}
        slow-drip: forward in slice_bytes pieces with delay_s between
        them — each read still makes "progress", defeating any
        per-read socket timeout on the receiver
    {"kind": "reset",     "after_bytes": 0}
        hard RST (SO_LINGER 0) once after_bytes of this direction have
        been forwarded; 0 = pre-body (first chunk resets immediately)
    {"kind": "truncate",  "after_bytes": 100}
        forward exactly after_bytes, then close BOTH sockets cleanly
        (FIN): the receiver sees a short body, not an error
    {"kind": "blackhole"}
        swallow everything: bytes of this direction are read and
        dropped, nothing is forwarded, no response ever comes — the
        client's own timeout is the only way out

Every toxic takes an optional "count": the number of CONNECTIONS it
applies to before expiring (toxiproxy's toxicity knob made
deterministic). Omitted = applies until cleared. Toxic chains are
re-read per chunk, so `set_toxics` / `clear` mid-connection affect
live flows — exactly how a real outage starts in the middle of a
response body.
"""

from __future__ import annotations

import logging
import random
import socket
import struct
import threading
import time

log = logging.getLogger(__name__)

_CHUNK = 65536
# bounded sleep quantum so stop() never waits behind a long toxic sleep
_SLEEP_QUANTUM = 0.05

TOXIC_KINDS = ("latency", "bandwidth", "slicer", "reset", "truncate", "blackhole")


class _ConnReset(Exception):
    """Internal: the reset toxic fired — RST both sockets."""


class _ConnTruncate(Exception):
    """Internal: the truncate toxic fired — FIN both sockets."""


class _Toxic:
    """One armed toxic instance plus its remaining connection budget."""

    __slots__ = ("spec", "remaining", "fired")

    def __init__(self, spec: dict):
        kind = spec.get("kind")
        if kind not in TOXIC_KINDS:
            raise ValueError(f"unknown toxic kind {kind!r} (want one of {TOXIC_KINDS})")
        self.spec = dict(spec)
        count = spec.get("count")
        self.remaining = None if count is None else int(count)
        self.fired = 0


class FaultProxy:
    """TCP proxy between `127.0.0.1:port` and `(upstream_host,
    upstream_port)` with per-direction toxic chains. Thread-per-pump;
    `start()`/`stop()` bound every thread's lifetime."""

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        connect_timeout_s: float = 10.0,
    ):
        self.upstream_host = upstream_host
        self.upstream_port = int(upstream_port)
        self.listen_host = listen_host
        self._requested_port = int(listen_port)
        self.connect_timeout_s = connect_timeout_s
        self.port: int | None = None
        self._lock = threading.Lock()
        self._toxics: dict[str, list[_Toxic]] = {"up": [], "down": []}
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._conns: list[tuple[socket.socket, socket.socket]] = []
        self._stopped = threading.Event()
        # wire stats (chaos gates assert toxics actually FIRED — a lane
        # that silently never touched the wire proves nothing)
        self.stats = {
            "connections_total": 0,
            "bytes_up": 0,
            "bytes_down": 0,
            "resets": 0,
            "truncates": 0,
            "blackholed_chunks": 0,
            "toxic_fired": {},  # kind -> count
        }

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "FaultProxy":
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self.listen_host, self._requested_port))
        listener.listen(64)
        listener.settimeout(0.2)
        self._listener = listener
        self.port = listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="netsim-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for a, b in conns:
            for s in (a, b):
                self._fin(s)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def __enter__(self) -> "FaultProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def url(self) -> str:
        """Base HTTP URL of the proxy listener (chaos task endpoints)."""
        return f"http://{self.listen_host}:{self.port}/"

    # ------------------------------------------------------------------
    # toxic control (runtime-togglable, per direction)
    # ------------------------------------------------------------------
    def set_toxics(self, direction: str, toxics: list[dict]) -> None:
        """Replace the toxic chain for one direction ("up"/"down").
        Live connections see the change on their next chunk."""
        if direction not in ("up", "down"):
            raise ValueError(f"direction must be 'up' or 'down', not {direction!r}")
        armed = [_Toxic(t) for t in toxics]
        with self._lock:
            self._toxics[direction] = armed

    def add_toxic(self, direction: str, toxic: dict) -> None:
        if direction not in ("up", "down"):
            raise ValueError(f"direction must be 'up' or 'down', not {direction!r}")
        with self._lock:
            self._toxics[direction].append(_Toxic(toxic))

    def clear(self, direction: str | None = None) -> None:
        """Drop all toxics (or one direction's); the wire heals."""
        with self._lock:
            for d in ("up", "down") if direction is None else (direction,):
                self._toxics[d] = []

    def toxics(self) -> dict[str, list[dict]]:
        with self._lock:
            return {
                d: [dict(t.spec, fired=t.fired) for t in chain]
                for d, chain in self._toxics.items()
            }

    def _claim_toxics(self, direction: str) -> list[dict]:
        """Snapshot this direction's active toxic specs for ONE new
        connection, consuming one unit of each budgeted toxic's count
        and expiring exhausted ones."""
        with self._lock:
            chain = self._toxics[direction]
            claimed: list[dict] = []
            survivors: list[_Toxic] = []
            for t in chain:
                if t.remaining is None:
                    claimed.append(t.spec)
                    survivors.append(t)
                elif t.remaining > 0:
                    t.remaining -= 1
                    claimed.append(t.spec)
                    if t.remaining > 0:
                        survivors.append(t)
                # remaining == 0 on entry: already spent, drop it
            self._toxics[direction] = survivors
            return claimed

    def _count_fired(self, kind: str) -> None:
        with self._lock:
            fired = self.stats["toxic_fired"]
            fired[kind] = fired.get(kind, 0) + 1

    # ------------------------------------------------------------------
    # data plane
    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                client, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                upstream = socket.create_connection(
                    (self.upstream_host, self.upstream_port),
                    timeout=self.connect_timeout_s,
                )
            except OSError as e:
                log.debug("netsim: upstream dial failed: %s", e)
                try:
                    client.close()
                except OSError:
                    pass
                continue
            with self._lock:
                self.stats["connections_total"] += 1
                self._conns.append((client, upstream))
            # per-connection toxic snapshot: a budgeted toxic ("count")
            # is claimed at accept time so exactly N connections feel it
            conn_toxics = {
                "up": self._claim_toxics("up"),
                "down": self._claim_toxics("down"),
            }
            for direction, src, dst in (
                ("up", client, upstream),
                ("down", upstream, client),
            ):
                threading.Thread(
                    target=self._pump,
                    args=(direction, src, dst, client, upstream, conn_toxics),
                    name=f"netsim-{direction}",
                    daemon=True,
                ).start()

    def _sleep(self, seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while not self._stopped.is_set():
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(left, _SLEEP_QUANTUM))

    @staticmethod
    def _rst(sock: socket.socket) -> None:
        """Abortive close: RST instead of FIN. SHUT_RD first — it is
        local-only for TCP (nothing on the wire) but wakes a sibling
        pump thread blocked in recv() on this fd; a close() alone is
        DEFERRED by the kernel while that syscall holds the file ref,
        so the RST would never be sent."""
        try:
            sock.shutdown(socket.SHUT_RD)
        except OSError:
            pass
        try:
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
            )
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    @staticmethod
    def _fin(sock: socket.socket) -> None:
        """Clean close that actually reaches the peer NOW: shutdown(2)
        acts on the socket immediately (FIN on the wire, blocked
        sibling recv() woken) even while another pump thread's
        in-flight recv holds the fd's file ref and defers close(2)."""
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _pump(
        self,
        direction: str,
        src: socket.socket,
        dst: socket.socket,
        client: socket.socket,
        upstream: socket.socket,
        conn_toxics: dict,
    ) -> None:
        forwarded = 0
        byte_key = "bytes_up" if direction == "up" else "bytes_down"
        try:
            while not self._stopped.is_set():
                try:
                    chunk = src.recv(_CHUNK)
                except OSError:
                    break
                if not chunk:
                    # clean EOF: half-close toward dst so e.g. an HTTP
                    # request body boundary still propagates
                    try:
                        dst.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    break
                # live chain = the proxy's CURRENT chain for kinds armed
                # after the connection started, plus this connection's
                # claimed budgeted toxics
                with self._lock:
                    live = [t.spec for t in self._toxics[direction]]
                chain = conn_toxics[direction] + [
                    s for s in live if s not in conn_toxics[direction]
                ]
                try:
                    forwarded = self._apply_chain(
                        chain, direction, chunk, dst, forwarded, byte_key
                    )
                except _ConnReset:
                    with self._lock:
                        self.stats["resets"] += 1
                    self._rst(client)
                    self._rst(upstream)
                    return
                except _ConnTruncate:
                    with self._lock:
                        self.stats["truncates"] += 1
                    for s in (client, upstream):
                        self._fin(s)
                    return
                except OSError:
                    break
        finally:
            # one side died: tear down both (a half-dead proxy flow
            # would look like a stall, which is the blackhole's job)
            for s in (src, dst):
                self._fin(s)

    def _apply_chain(
        self,
        chain: list[dict],
        direction: str,
        chunk: bytes,
        dst: socket.socket,
        forwarded: int,
        byte_key: str,
    ) -> int:
        """Run one received chunk through the toxic chain, forwarding
        whatever survives. Returns the updated forwarded-byte count."""
        for spec in chain:
            kind = spec["kind"]
            if kind == "blackhole":
                with self._lock:
                    self.stats["blackholed_chunks"] += 1
                self._count_fired("blackhole")
                return forwarded  # swallowed; never forwarded
            if kind == "latency":
                jitter = float(spec.get("jitter_s", 0.0))
                delay = float(spec.get("latency_s", 0.0))
                if jitter:
                    delay += random.uniform(-jitter, jitter)
                if delay > 0:
                    self._count_fired("latency")
                    self._sleep(delay)
            elif kind == "bandwidth":
                rate = float(spec.get("bytes_per_s", 0.0))
                if rate > 0:
                    self._count_fired("bandwidth")
                    self._sleep(len(chunk) / rate)
            elif kind == "reset":
                if forwarded + len(chunk) > int(spec.get("after_bytes", 0)) or not chunk:
                    allowed = max(0, int(spec.get("after_bytes", 0)) - forwarded)
                    if allowed:
                        dst.sendall(chunk[:allowed])
                        with self._lock:
                            self.stats[byte_key] += allowed
                    self._count_fired("reset")
                    raise _ConnReset()
            elif kind == "truncate":
                limit = int(spec.get("after_bytes", 0))
                if forwarded + len(chunk) >= limit:
                    allowed = max(0, limit - forwarded)
                    if allowed:
                        dst.sendall(chunk[:allowed])
                        with self._lock:
                            self.stats[byte_key] += allowed
                    self._count_fired("truncate")
                    raise _ConnTruncate()
            elif kind == "slicer":
                size = max(1, int(spec.get("slice_bytes", 64)))
                delay = float(spec.get("delay_s", 0.05))
                self._count_fired("slicer")
                for off in range(0, len(chunk), size):
                    dst.sendall(chunk[off : off + size])
                    with self._lock:
                        self.stats[byte_key] += len(chunk[off : off + size])
                    if off + size < len(chunk):
                        self._sleep(delay)
                return forwarded + len(chunk)
        dst.sendall(chunk)
        with self._lock:
            self.stats[byte_key] += len(chunk)
        return forwarded + len(chunk)
