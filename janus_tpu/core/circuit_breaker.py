"""Per-peer outbound circuit breaker for leader->helper traffic.

A dead or melting helper otherwise burns the whole lease inside
`retry_http_request` on every job step, for every job, until the
drivers' attempt budgets abandon real work. The breaker makes the
failure cheap and the recovery automatic:

    CLOSED ──(failure_threshold consecutive failures)──▶ OPEN
    OPEN   ──(open_cooldown_s elapsed)────────────────▶ HALF_OPEN
    HALF_OPEN: one in-flight probe request is admitted;
               success ▶ CLOSED, failure ▶ OPEN (cooldown restarts)

While OPEN (or while the half-open probe slot is taken), `check()`
raises CircuitOpenError immediately — the job drivers treat that as a
*step-back* (release the lease early with a reacquire delay, do not
count an attempt; aggregation_job_driver.py) so a helper outage parks
jobs cheaply instead of marching them toward abandonment.

"Failure" is a transport error or a retryable 5xx on one HTTP attempt;
a conclusive response (2xx/4xx, including DAP problem documents) is a
success — the peer is alive and talking protocol, even if it rejects
the request.

Observability: `janus_outbound_circuit_state{peer}` (0=closed, 1=open,
2=half-open), `janus_outbound_circuit_transitions_total{peer,to}`, and
an `outbound_circuit` /statusz section with per-peer counters.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from urllib.parse import urlsplit

log = logging.getLogger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_VALUE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


class CircuitOpenError(RuntimeError):
    """The breaker for this peer is open: fail fast, step back."""

    def __init__(self, peer: str, retry_in_s: float):
        super().__init__(
            f"outbound circuit to {peer} is open (retry in {retry_in_s:.1f}s)"
        )
        self.peer = peer
        self.retry_in_s = max(0.0, retry_in_s)


@dataclass(frozen=True)
class CircuitBreakerConfig:
    """YAML `outbound_circuit_breaker:` section of the job driver
    binaries (config.py)."""

    # consecutive per-attempt failures before the circuit opens
    failure_threshold: int = 5
    # how long an open circuit rejects before admitting a probe
    open_cooldown_s: float = 30.0
    # successes required in half-open before closing (1 = first good
    # probe closes)
    close_threshold: int = 1
    enabled: bool = True

    @classmethod
    def from_dict(cls, d: dict | None) -> "CircuitBreakerConfig":
        d = d or {}
        return cls(
            failure_threshold=int(d.get("failure_threshold", 5)),
            open_cooldown_s=float(d.get("open_cooldown_secs", 30.0)),
            close_threshold=int(d.get("close_threshold", 1)),
            enabled=bool(d.get("enabled", True)),
        )


def peer_label(url: str) -> str:
    """Stable per-peer metric label from an endpoint URL: host[:port]."""
    try:
        netloc = urlsplit(url).netloc
        return netloc or url
    except ValueError:
        return url


class _PeerCircuit:
    __slots__ = (
        "peer",
        "state",
        "consecutive_failures",
        "half_open_successes",
        "opened_at",
        "probe_in_flight",
        "opens",
        "total_failures",
        "total_successes",
    )

    def __init__(self, peer: str):
        self.peer = peer
        self.state = CLOSED
        self.consecutive_failures = 0
        self.half_open_successes = 0
        self.opened_at = 0.0
        self.probe_in_flight = False
        self.opens = 0
        self.total_failures = 0
        self.total_successes = 0


class OutboundCircuitBreakers:
    """Registry of per-peer breakers sharing one config. Process-wide:
    both job drivers in one process see the same peer state (a helper
    that is down for aggregation steps is down for aggregate-share
    fetches too)."""

    def __init__(self, cfg: CircuitBreakerConfig | None = None):
        self.cfg = cfg or CircuitBreakerConfig()
        self._lock = threading.Lock()
        self._peers: dict[str, _PeerCircuit] = {}

    def _get(self, peer: str) -> _PeerCircuit:
        pc = self._peers.get(peer)
        if pc is None:
            pc = self._peers[peer] = _PeerCircuit(peer)
            self._publish(pc)
        return pc

    def _publish(self, pc: _PeerCircuit) -> None:
        from .. import metrics

        metrics.outbound_circuit_state.set(_STATE_VALUE[pc.state], peer=pc.peer)

    def _transition(self, pc: _PeerCircuit, to: str) -> None:
        from .. import metrics

        if pc.state == to:
            return
        log.warning("outbound circuit %s: %s -> %s", pc.peer, pc.state, to)
        pc.state = to
        metrics.outbound_circuit_transitions.add(peer=pc.peer, to=to)
        self._publish(pc)

    # ------------------------------------------------------------------
    # the call-site protocol
    # ------------------------------------------------------------------
    def check(self, peer: str) -> None:
        """Gate one request attempt. Raises CircuitOpenError while the
        peer's circuit rejects; transitions OPEN->HALF_OPEN (admitting
        this caller as the probe) once the cooldown has elapsed."""
        if not self.cfg.enabled:
            return
        with self._lock:
            pc = self._get(peer)
            if pc.state == CLOSED:
                return
            now = time.monotonic()
            if pc.state == OPEN:
                remaining = pc.opened_at + self.cfg.open_cooldown_s - now
                if remaining > 0:
                    raise CircuitOpenError(peer, remaining)
                self._transition(pc, HALF_OPEN)
                pc.half_open_successes = 0
                pc.probe_in_flight = True
                return
            # HALF_OPEN: admit one probe at a time
            if pc.probe_in_flight:
                raise CircuitOpenError(peer, self.cfg.open_cooldown_s)
            pc.probe_in_flight = True

    def record_success(self, peer: str) -> None:
        if not self.cfg.enabled:
            return
        with self._lock:
            pc = self._get(peer)
            pc.total_successes += 1
            pc.consecutive_failures = 0
            if pc.state == HALF_OPEN:
                pc.probe_in_flight = False
                pc.half_open_successes += 1
                if pc.half_open_successes >= self.cfg.close_threshold:
                    self._transition(pc, CLOSED)

    def record_failure(self, peer: str) -> None:
        if not self.cfg.enabled:
            return
        with self._lock:
            pc = self._get(peer)
            pc.total_failures += 1
            pc.consecutive_failures += 1
            if pc.state == HALF_OPEN:
                # the probe failed: back to a full cooldown
                pc.probe_in_flight = False
                pc.opened_at = time.monotonic()
                pc.opens += 1
                self._transition(pc, OPEN)
            elif (
                pc.state == CLOSED
                and pc.consecutive_failures >= self.cfg.failure_threshold
            ):
                pc.opened_at = time.monotonic()
                pc.opens += 1
                self._transition(pc, OPEN)

    def state(self, peer: str) -> str:
        with self._lock:
            return self._get(peer).state

    def peer_states(self) -> dict[str, str]:
        """Snapshot of every known peer's state — the peer-health
        tracker's parking input (aggregator/peer_health.py). Read-only:
        never creates a peer entry."""
        with self._lock:
            return {p: pc.state for p, pc in self._peers.items()}

    def retry_in_s(self, peer: str) -> float:
        """Seconds until the peer's circuit will admit a probe (0 when
        closed/half-open) — the job drivers' step-back reacquire delay."""
        with self._lock:
            pc = self._get(peer)
            if pc.state != OPEN:
                return 0.0
            return max(
                0.0, pc.opened_at + self.cfg.open_cooldown_s - time.monotonic()
            )

    def status(self) -> dict:
        """/statusz section body."""
        with self._lock:
            return {
                "config": {
                    "failure_threshold": self.cfg.failure_threshold,
                    "open_cooldown_s": self.cfg.open_cooldown_s,
                    "close_threshold": self.cfg.close_threshold,
                    "enabled": self.cfg.enabled,
                },
                "peers": {
                    pc.peer: {
                        "state": pc.state,
                        "consecutive_failures": pc.consecutive_failures,
                        "opens": pc.opens,
                        "total_failures": pc.total_failures,
                        "total_successes": pc.total_successes,
                        "retry_in_s": round(
                            max(
                                0.0,
                                pc.opened_at
                                + self.cfg.open_cooldown_s
                                - time.monotonic(),
                            ),
                            3,
                        )
                        if pc.state == OPEN
                        else 0.0,
                    }
                    for pc in self._peers.values()
                },
            }


# Process-wide default registry, shared by both job drivers and exposed
# on /statusz (registered on first use so processes with no outbound
# traffic don't grow an empty section).
_default_lock = threading.Lock()
_default: OutboundCircuitBreakers | None = None


def default_breakers(cfg: CircuitBreakerConfig | None = None) -> OutboundCircuitBreakers:
    """The process's shared breaker registry. The first caller's config
    wins (both driver binaries parse the same YAML section); later
    callers passing a config replace it only if none was set."""
    global _default
    with _default_lock:
        if _default is None:
            _default = OutboundCircuitBreakers(cfg)
            from ..statusz import register_status_provider

            register_status_provider("outbound_circuit", _default.status)
        elif cfg is not None and _default.cfg == CircuitBreakerConfig():
            _default.cfg = cfg
        return _default


def reset_default_breakers() -> None:
    """Test hook: drop the process-wide registry (and its /statusz
    section name gets re-registered by the next default_breakers())."""
    global _default
    with _default_lock:
        _default = None
