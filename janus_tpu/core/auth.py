"""Authentication tokens with constant-time comparison.

Equivalent of reference core/src/task.rs AuthenticationToken
({Bearer, DapAuth}; constant-time eq via ring::constant_time — here
hmac.compare_digest).
"""

from __future__ import annotations

import base64
import hmac
import secrets
from dataclasses import dataclass

DAP_AUTH_HEADER = "DAP-Auth-Token"


@dataclass(frozen=True)
class AuthenticationToken:
    kind: str  # "Bearer" | "DapAuth"
    token: str

    @classmethod
    def bearer(cls, token: str) -> "AuthenticationToken":
        return cls("Bearer", token)

    @classmethod
    def dap_auth(cls, token: str) -> "AuthenticationToken":
        return cls("DapAuth", token)

    @classmethod
    def random_bearer(cls) -> "AuthenticationToken":
        return cls.bearer(base64.urlsafe_b64encode(secrets.token_bytes(16)).rstrip(b"=").decode())

    def request_headers(self) -> dict[str, str]:
        if self.kind == "Bearer":
            return {"Authorization": f"Bearer {self.token}"}
        return {DAP_AUTH_HEADER: self.token}

    def matches_headers(self, headers) -> bool:
        """Constant-time check of an incoming header map (case-insensitive keys)."""
        lowered = {k.lower(): v for k, v in headers.items()}
        if self.kind == "Bearer":
            got = lowered.get("authorization", "")
            want = f"Bearer {self.token}"
            return hmac.compare_digest(got.encode(), want.encode())
        got = lowered.get(DAP_AUTH_HEADER.lower(), "")
        return hmac.compare_digest(got.encode(), self.token.encode())

    def to_dict(self) -> dict:
        return {"kind": self.kind, "token": self.token}

    @classmethod
    def from_dict(cls, d: dict) -> "AuthenticationToken":
        return cls(d["kind"], d["token"])
