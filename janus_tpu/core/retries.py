"""HTTP retry with exponential backoff.

Equivalent of reference core/src/retries.rs:30-72
(retry_http_request + test variants): retries transport errors and
retryable status codes (5xx, 429) with capped exponential backoff and
jitter.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass


class DeadlineExceeded(TimeoutError):
    """The retry deadline (lease bound) tripped before a conclusive
    response. Carries the last retryable status, if any, so callers can
    log it — but deliberately NOT as a (status, body) return value: a
    stale 5xx from an earlier attempt must not masquerade as the
    conclusive outcome of the request."""

    def __init__(self, msg: str, last_status: int | None = None):
        super().__init__(msg)
        self.last_status = last_status


@dataclass(frozen=True)
class Backoff:
    initial: float = 0.1
    multiplier: float = 2.0
    max_interval: float = 5.0
    max_elapsed: float = 30.0
    jitter: float = 0.25

    @classmethod
    def test(cls) -> "Backoff":
        """Fast backoff for tests (reference test_util variants)."""
        return cls(initial=0.001, max_interval=0.01, max_elapsed=0.25)


RETRYABLE_STATUS = {429, 500, 502, 503, 504}


def is_retryable_status(status: int) -> bool:
    return status in RETRYABLE_STATUS


def retry_http_request(
    do_request, backoff: Backoff = Backoff(), sleep=time.sleep, deadline: float | None = None
):
    """Call do_request() until success or budget exhausted.

    do_request returns (status:int, body) or raises OSError-likes for
    transport failures. Returns the last (status, body); raises the
    last transport error if every attempt failed by exception.

    deadline: optional time.monotonic() value after which no further
    attempt or backoff sleep is started (the lease-bounded job step,
    reference job_driver.rs:191-196 — a stuck helper must not outlive
    the worker's lease and run concurrently with its re-acquirer).
    Raises DeadlineExceeded (a TimeoutError) if the deadline passes
    before any conclusive response — a stale retryable (status, body)
    from an earlier attempt is never returned as if conclusive.
    """
    interval = backoff.initial
    elapsed = 0.0
    last_exc = None
    status = body = None
    while True:
        if deadline is not None and time.monotonic() >= deadline:
            if last_exc is not None:
                raise last_exc
            raise DeadlineExceeded(
                "request deadline (lease bound) exceeded", last_status=status
            )
        try:
            status, body = do_request()
            if not is_retryable_status(status):
                return status, body
            last_exc = None
        except (OSError, ConnectionError) as e:
            last_exc = e
        budget_spent = elapsed + interval > backoff.max_elapsed
        deadline_near = deadline is not None and time.monotonic() + interval >= deadline
        if budget_spent or deadline_near:
            if last_exc is not None:
                raise last_exc
            if budget_spent:
                # backoff budget exhausted: the last (retryable) response
                # IS the documented conclusive outcome
                return status, body
            raise DeadlineExceeded(
                "request deadline (lease bound) exceeded", last_status=status
            )
        delay = interval * (1 + random.uniform(-backoff.jitter, backoff.jitter))
        sleep(delay)
        elapsed += delay
        interval = min(interval * backoff.multiplier, backoff.max_interval)
