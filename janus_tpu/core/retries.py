"""HTTP retry with exponential backoff.

Equivalent of reference core/src/retries.rs:30-72
(retry_http_request + test variants): retries transport errors and
retryable status codes (5xx, 429) with capped exponential backoff and
jitter.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass

from .. import failpoints

# Canonical home moved to core.deadline (the same type now covers the
# lease-bounded retry loop, the watchdog-bounded engine dispatch and
# the helper's propagated request budget); re-exported here for the
# existing importers.
from .deadline import DeadlineExceeded  # noqa: F401


class RequestAborted(Exception):
    """The caller's should_abort() tripped mid-retry (driver shutdown
    drain): the request is abandoned without a conclusive response so
    the job step can step back and release its lease immediately."""


@dataclass(frozen=True)
class Backoff:
    initial: float = 0.1
    multiplier: float = 2.0
    max_interval: float = 5.0
    max_elapsed: float = 30.0
    jitter: float = 0.25

    @classmethod
    def test(cls) -> "Backoff":
        """Fast backoff for tests (reference test_util variants)."""
        return cls(initial=0.001, max_interval=0.01, max_elapsed=0.25)


RETRYABLE_STATUS = {429, 500, 502, 503, 504}


def is_retryable_status(status: int) -> bool:
    return status in RETRYABLE_STATUS


def parse_retry_after(value) -> float | None:
    """Seconds to wait per an HTTP Retry-After header value (delta
    seconds or HTTP-date), or None if absent/unparseable."""
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        pass
    try:
        from email.utils import parsedate_to_datetime

        dt = parsedate_to_datetime(str(value))
        return max(0.0, dt.timestamp() - time.time())
    except Exception:
        return None


def _retry_after_from(headers) -> float | None:
    if not headers:
        return None
    lowered = {str(k).lower(): v for k, v in headers.items()}
    return parse_retry_after(lowered.get("retry-after"))


def retry_http_request(
    do_request,
    backoff: Backoff = Backoff(),
    sleep=time.sleep,
    deadline: float | None = None,
    should_abort=None,
):
    """Call do_request() until success or budget exhausted.

    do_request returns (status:int, body) — or (status, body, headers)
    to let a server-sent Retry-After steer the backoff — or raises
    OSError-likes for transport failures. Returns the last
    (status, body); raises the last transport error if every attempt
    failed by exception.

    On a retryable status carrying a Retry-After header (the admission
    controller's 429s, a peer's 503), the next sleep honors the
    server's delay instead of the exponential interval, clamped to
    `backoff.max_interval` — a well-behaved client backs off when told
    to, but a hostile/huge value cannot park a lease-bounded worker —
    and still bounded by the deadline below.

    deadline: optional time.monotonic() value after which no further
    attempt or backoff sleep is started (the lease-bounded job step,
    reference job_driver.rs:191-196 — a stuck helper must not outlive
    the worker's lease and run concurrently with its re-acquirer).
    Raises DeadlineExceeded (a TimeoutError) if the deadline passes
    before any conclusive response — a stale retryable (status, body)
    from an earlier attempt is never returned as if conclusive.

    should_abort: optional callable checked before every attempt and
    every backoff sleep; when it returns True the loop raises
    RequestAborted instead of spending more of the budget (the job
    drivers pass the shutdown Stopper so SIGTERM drains in-flight
    steps instead of retrying a dead helper through a full lease).
    """
    interval = backoff.initial
    elapsed = 0.0
    last_exc = None
    status = body = None
    while True:
        if should_abort is not None and should_abort():
            raise RequestAborted("request abandoned (shutdown drain)")
        if deadline is not None and time.monotonic() >= deadline:
            if last_exc is not None:
                raise last_exc
            raise DeadlineExceeded(
                "request deadline (lease bound) exceeded", last_status=status
            )
        retry_after = None
        try:
            # inside the try: an injected transport error is retried
            # exactly like a real one
            failpoints.hit(
                "retry.attempt",
                error_factory=lambda: OSError(
                    "injected transport error (failpoint retry.attempt)"
                ),
            )
            result = do_request()
            status, body = result[0], result[1]
            if not is_retryable_status(status):
                return status, body
            if len(result) > 2:
                retry_after = _retry_after_from(result[2])
            last_exc = None
        except (OSError, ConnectionError) as e:
            last_exc = e
        if retry_after is not None:
            # honor the server's schedule (clamped); no jitter — the
            # server already paced us, and the admission bucket's
            # refill estimate is the actual earliest useful retry.
            # Floor at the backoff's initial interval: a hostile/buggy
            # "Retry-After: 0" (or an HTTP-date in the past) must not
            # collapse this loop into a zero-sleep spin that never
            # spends the max_elapsed budget.
            next_delay = min(max(retry_after, backoff.initial), backoff.max_interval)
        else:
            next_delay = interval
        budget_spent = elapsed + next_delay > backoff.max_elapsed
        deadline_near = (
            deadline is not None and time.monotonic() + next_delay >= deadline
        )
        if budget_spent or deadline_near:
            if last_exc is not None:
                raise last_exc
            if budget_spent:
                # backoff budget exhausted: the last (retryable) response
                # IS the documented conclusive outcome
                return status, body
            raise DeadlineExceeded(
                "request deadline (lease bound) exceeded", last_status=status
            )
        if retry_after is None:
            next_delay = interval * (1 + random.uniform(-backoff.jitter, backoff.jitter))
        if should_abort is not None and should_abort():
            raise RequestAborted("request abandoned (shutdown drain)")
        sleep(next_delay)
        elapsed += next_delay
        interval = min(interval * backoff.multiplier, backoff.max_interval)
