"""HPKE (RFC 9180) single-shot seal/open with DAP application labels.

Equivalent of reference core/src/hpke.rs:27-120: base mode with the
DAP-07 application-info labels ("dap-07 input share",
"dap-07 aggregate share") and sender/recipient roles bound into the
key schedule info.

Suite matrix (reference core/src/hpke.rs:214-215,456 round_trip_check):
KEMs DHKEM(X25519, HKDF-SHA256) + DHKEM(P-256, HKDF-SHA256); KDFs
HKDF-SHA256/384/512; AEADs AES-128-GCM / AES-256-GCM /
ChaCha20Poly1305 — any combination. KEM/AEAD primitives come from
`core.hpke_backend` (the `cryptography` package when installed, else
the system libcrypto via ctypes — this image ships no crypto wheels);
the HKDF labeling is implemented here to match RFC 9180 exactly.
"""

from __future__ import annotations

import enum
import hashlib
import hmac
from dataclasses import dataclass

from .hpke_backend import (
    AESGCM,
    ChaCha20Poly1305,
    aead_open_batch,
    p256_exchange,
    p256_generate,
    x25519_exchange,
    x25519_exchange_batch,
    x25519_generate,
)
from ..messages import HpkeAeadId, HpkeCiphertext, HpkeConfig, HpkeConfigId, HpkeKdfId, HpkeKemId, Role

NN = 12  # nonce size, all three AEADs

_KDF_HASH = {
    HpkeKdfId.HKDF_SHA256: hashlib.sha256,
    HpkeKdfId.HKDF_SHA384: hashlib.sha384,
    HpkeKdfId.HKDF_SHA512: hashlib.sha512,
}

# openssl digest names for hmac.digest()'s one-shot C fast path (the
# batch open uses it; ~1.0 µs/call vs ~1.8 for hmac.new().digest())
_KDF_NAME = {
    HpkeKdfId.HKDF_SHA256: "sha256",
    HpkeKdfId.HKDF_SHA384: "sha384",
    HpkeKdfId.HKDF_SHA512: "sha512",
}

_AEAD = {  # id -> (constructor, Nk)
    HpkeAeadId.AES_128_GCM: (AESGCM, 16),
    HpkeAeadId.AES_256_GCM: (AESGCM, 32),
    HpkeAeadId.CHACHA20POLY1305: (ChaCha20Poly1305, 32),
}


class HpkeError(Exception):
    pass


def _labeled_extract(suite_id: bytes, hashfn, salt: bytes, label: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, b"HPKE-v1" + suite_id + label + ikm, hashfn).digest()


def _labeled_expand(suite_id: bytes, hashfn, prk: bytes, label: bytes, info: bytes, length: int) -> bytes:
    labeled_info = length.to_bytes(2, "big") + b"HPKE-v1" + suite_id + label + info
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac.new(prk, t + labeled_info + bytes([i]), hashfn).digest()
        out += t
        i += 1
    return out[:length]


# ---------------------------------------------------------------------------
# KEMs (both use HKDF-SHA256 internally per their RFC 9180 definitions)
# ---------------------------------------------------------------------------


class _X25519Kem:
    ID = HpkeKemId.X25519_HKDF_SHA256
    NSECRET = 32

    @staticmethod
    def generate() -> tuple[bytes, bytes]:
        return x25519_generate()

    @staticmethod
    def encap(pk_bytes: bytes) -> tuple[bytes, bytes]:
        pk_e, sk_e = x25519_generate()
        return x25519_exchange(sk_e, pk_bytes), pk_e

    @staticmethod
    def decap(sk_bytes: bytes, enc: bytes) -> bytes:
        return x25519_exchange(sk_bytes, enc)


class _P256Kem:
    ID = HpkeKemId.P256_HKDF_SHA256
    NSECRET = 32

    @staticmethod
    def generate() -> tuple[bytes, bytes]:
        return p256_generate()

    @staticmethod
    def encap(pk_bytes: bytes) -> tuple[bytes, bytes]:
        enc, sk_e = p256_generate()
        return p256_exchange(sk_e, pk_bytes), enc

    @staticmethod
    def decap(sk_bytes: bytes, enc: bytes) -> bytes:
        return p256_exchange(sk_bytes, enc)


_KEMS = {k.ID: k for k in (_X25519Kem, _P256Kem)}


def _extract_and_expand(kem, dh: bytes, kem_context: bytes) -> bytes:
    kem_suite_id = b"KEM" + int(kem.ID).to_bytes(2, "big")
    eae_prk = _labeled_extract(kem_suite_id, hashlib.sha256, b"", b"eae_prk", dh)
    return _labeled_expand(
        kem_suite_id, hashlib.sha256, eae_prk, b"shared_secret", kem_context, kem.NSECRET
    )


def _key_schedule(config: HpkeConfig, shared_secret: bytes, info: bytes):
    """Base mode key schedule -> (aead instance, base_nonce)."""
    suite_id = (
        b"HPKE"
        + int(config.kem_id).to_bytes(2, "big")
        + int(config.kdf_id).to_bytes(2, "big")
        + int(config.aead_id).to_bytes(2, "big")
    )
    hashfn = _KDF_HASH[config.kdf_id]
    ctor, nk = _AEAD[config.aead_id]
    psk_id_hash = _labeled_extract(suite_id, hashfn, b"", b"psk_id_hash", b"")
    info_hash = _labeled_extract(suite_id, hashfn, b"", b"info_hash", info)
    key_schedule_context = b"\x00" + psk_id_hash + info_hash
    secret = _labeled_extract(suite_id, hashfn, shared_secret, b"secret", b"")
    key = _labeled_expand(suite_id, hashfn, secret, b"key", key_schedule_context, nk)
    base_nonce = _labeled_expand(suite_id, hashfn, secret, b"base_nonce", key_schedule_context, NN)
    return ctor(key), base_nonce


class Label(enum.Enum):
    """DAP application-info labels (reference core/src/hpke.rs:45)."""

    INPUT_SHARE = b"dap-07 input share"
    AGGREGATE_SHARE = b"dap-07 aggregate share"


@dataclass(frozen=True)
class HpkeApplicationInfo:
    """label || sender role || recipient role (reference core/src/hpke.rs:62)."""

    label: Label
    sender: Role
    recipient: Role

    def bytes(self) -> bytes:
        return self.label.value + bytes([self.sender.value, self.recipient.value])


@dataclass(frozen=True)
class HpkeKeypair:
    config: HpkeConfig
    private_key: bytes  # raw X25519 scalar / P-256 big-endian scalar

    def config_id(self) -> HpkeConfigId:
        return self.config.id


def generate_hpke_config_and_private_key(
    config_id: int = 0,
    kem_id: HpkeKemId = HpkeKemId.X25519_HKDF_SHA256,
    kdf_id: HpkeKdfId = HpkeKdfId.HKDF_SHA256,
    aead_id: HpkeAeadId = HpkeAeadId.AES_128_GCM,
) -> HpkeKeypair:
    """reference core/src/hpke.rs generate_hpke_config_and_private_key."""
    kem = _kem_for(kem_id)
    _check_ciphersuite(kem_id, kdf_id, aead_id)
    pk_bytes, sk_bytes = kem.generate()
    config = HpkeConfig(HpkeConfigId(config_id), kem_id, kdf_id, aead_id, pk_bytes)
    return HpkeKeypair(config, sk_bytes)


def _kem_for(kem_id) -> type:
    try:
        return _KEMS[kem_id]
    except KeyError:
        raise HpkeError(f"unsupported HPKE KEM {kem_id}")


def _check_ciphersuite(kem_id, kdf_id, aead_id) -> None:
    if kdf_id not in _KDF_HASH or aead_id not in _AEAD:
        raise HpkeError(f"unsupported HPKE ciphersuite {kem_id}/{kdf_id}/{aead_id}")


def hpke_seal(
    config: HpkeConfig,
    application_info: HpkeApplicationInfo,
    plaintext: bytes,
    aad: bytes,
) -> HpkeCiphertext:
    """Single-shot base-mode seal to `config`'s public key."""
    kem = _kem_for(config.kem_id)
    _check_ciphersuite(config.kem_id, config.kdf_id, config.aead_id)
    dh, enc = kem.encap(config.public_key)
    shared_secret = _extract_and_expand(kem, dh, enc + config.public_key)
    aead, base_nonce = _key_schedule(config, shared_secret, application_info.bytes())
    ct = aead.encrypt(base_nonce, plaintext, aad)
    return HpkeCiphertext(config.id, enc, ct)


def hpke_open(
    keypair: HpkeKeypair,
    application_info: HpkeApplicationInfo,
    ciphertext: HpkeCiphertext,
    aad: bytes,
) -> bytes:
    """Single-shot base-mode open with the recipient private key."""
    kem = _kem_for(keypair.config.kem_id)
    _check_ciphersuite(keypair.config.kem_id, keypair.config.kdf_id, keypair.config.aead_id)
    if ciphertext.config_id != keypair.config.id:
        raise HpkeError(
            f"config id mismatch: {ciphertext.config_id} != {keypair.config.id}"
        )
    try:
        dh = kem.decap(keypair.private_key, ciphertext.encapsulated_key)
    except Exception as e:  # malformed point / key
        raise HpkeError(f"KEM decap failed: {e}") from e
    kem_context = ciphertext.encapsulated_key + keypair.config.public_key
    shared_secret = _extract_and_expand(kem, dh, kem_context)
    aead, base_nonce = _key_schedule(keypair.config, shared_secret, application_info.bytes())
    try:
        return aead.decrypt(base_nonce, ciphertext.payload, aad)
    except Exception as e:  # InvalidTag
        raise HpkeError(f"decryption failed: {e}") from e


def hpke_open_batch(
    keypair: HpkeKeypair,
    application_info: HpkeApplicationInfo,
    encs,
    payloads,
    aads,
) -> list:
    """Batched single-shot base-mode open: a whole decrypt window
    against ONE recipient keypair (the ingest hot path — every upload
    in a flush window addresses the same task HPKE config; the caller
    groups lanes by config id first, so no per-lane config-id check is
    needed here).

    `encs` / `payloads` / `aads` are parallel per-lane columns
    (encapsulated key, AEAD ciphertext, AAD). Returns a list aligned
    with them: plaintext bytes for lanes that opened, an `HpkeError`
    INSTANCE for lanes that failed — the per-lane value form of the
    exceptions `hpke_open` raises, so one tampered report rejects its
    own lane and never its window. Equivalence with the per-report
    oracle (same plaintexts, errors on the same indexes) is fuzz-pinned
    by tests/test_ingest_batch.py.

    What the batch amortizes over the window:
    - KEM decap runs through one EVP private-key object + derive
      context (`x25519_exchange_batch`) instead of a full parse/create/
      free cycle per report (P-256 lanes fall back to per-lane decap —
      the EC_KEY surface has no cheap peer swap).
    - The key-schedule constants (suite ids, psk_id/info hashes, the
      key-schedule context, every labeled-info template) are computed
      once; per lane only the secret-dependent HMACs remain, issued
      through `hmac.digest`'s one-shot C path.
    - AEAD opens share one cipher context (`aead_open_batch`).

    GIL note: whether this call parallelizes across decrypt-pool
    workers is a backend property (`hpke_backend.BATCH_RELEASES_GIL`);
    the ctypes-libcrypto fallback holds the GIL for the whole window by
    design (PyDLL convoy note in hpke_backend)."""
    import hmac as _hmac

    config = keypair.config
    kem = _kem_for(config.kem_id)
    _check_ciphersuite(config.kem_id, config.kdf_id, config.aead_id)
    n = len(encs)
    out: list = [None] * n

    # --- KEM decap column ---
    if kem is _X25519Kem:
        try:
            dhs = x25519_exchange_batch(keypair.private_key, encs)
        except Exception:
            # a bad RECIPIENT key (corrupt provisioning) fails every
            # lane's decap in the oracle too — per-lane rejects, never
            # a window-wide exception
            dhs = [None] * n
    else:
        dhs = []
        for enc in encs:
            try:
                dhs.append(kem.decap(keypair.private_key, enc))
            except Exception:
                dhs.append(None)

    # --- per-suite constants, computed once for the window ---
    kem_suite_id = b"KEM" + int(kem.ID).to_bytes(2, "big")
    # extract_and_expand templates (KEM KDF is always HKDF-SHA256)
    eae_msg_prefix = b"HPKE-v1" + kem_suite_id + b"eae_prk"
    ss_info_prefix = (
        kem.NSECRET.to_bytes(2, "big") + b"HPKE-v1" + kem_suite_id + b"shared_secret"
    )
    pk = config.public_key

    suite_id = (
        b"HPKE"
        + int(config.kem_id).to_bytes(2, "big")
        + int(config.kdf_id).to_bytes(2, "big")
        + int(config.aead_id).to_bytes(2, "big")
    )
    hashfn = _KDF_HASH[config.kdf_id]
    hname = _KDF_NAME[config.kdf_id]
    digest_size = hashfn().digest_size
    ctor, nk = _AEAD[config.aead_id]
    info = application_info.bytes()
    psk_id_hash = _labeled_extract(suite_id, hashfn, b"", b"psk_id_hash", b"")
    info_hash = _labeled_extract(suite_id, hashfn, b"", b"info_hash", info)
    key_schedule_context = b"\x00" + psk_id_hash + info_hash
    secret_msg = b"HPKE-v1" + suite_id + b"secret"
    key_info = (
        nk.to_bytes(2, "big") + b"HPKE-v1" + suite_id + b"key" + key_schedule_context
        + b"\x01"
    )
    nonce_info = (
        NN.to_bytes(2, "big") + b"HPKE-v1" + suite_id + b"base_nonce"
        + key_schedule_context + b"\x01"
    )
    # every derived length (NSECRET=32, nk<=32, NN=12) fits one HKDF
    # round of every supported hash, so expand == one truncated HMAC;
    # guarded here so a future suite can't silently truncate wrong
    assert max(kem.NSECRET, nk, NN) <= digest_size

    # --- per-lane key schedule (secret-dependent HMACs only) ---
    keys: list = [None] * n
    nonces: list = [None] * n
    hd = _hmac.digest
    ss_suffix = pk + b"\x01"
    nsecret = kem.NSECRET
    for i in range(n):
        dh = dhs[i]
        if dh is None:
            out[i] = HpkeError("KEM decap failed: bad encapsulated key")
            continue
        eae_prk = hd(b"", eae_msg_prefix + dh, "sha256")
        shared_secret = hd(eae_prk, ss_info_prefix + encs[i] + ss_suffix, "sha256")[
            :nsecret
        ]
        secret = hd(shared_secret, secret_msg, hname)
        keys[i] = hd(secret, key_info, hname)[:nk]
        nonces[i] = hd(secret, nonce_info, hname)[:NN]

    # --- AEAD open column ---
    opened = aead_open_batch(ctor, keys, nonces, payloads, aads)
    for i in range(n):
        if out[i] is not None:
            continue
        if opened[i] is None:
            # the message the per-report oracle's AEAD reject carries
            out[i] = HpkeError("decryption failed: AEAD decryption failed: invalid tag")
        else:
            out[i] = opened[i]
    return out
