"""HPKE (RFC 9180) single-shot seal/open with DAP application labels.

Equivalent of reference core/src/hpke.rs:27-120: base-mode
DHKEM(X25519, HKDF-SHA256) + HKDF-SHA256 + AES-128-GCM, with the
DAP-07 application-info labels ("dap-07 input share",
"dap-07 aggregate share") and sender/recipient roles bound into the
key schedule info.

KEM/AEAD primitives come from the `cryptography` package (the
reference's equivalent dependency is the hpke-dispatch crate); the
HKDF labeling is implemented here to match RFC 9180 exactly.
"""

from __future__ import annotations

import enum
import hashlib
import hmac
import secrets
from dataclasses import dataclass

from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import AESGCM

from ..messages import HpkeAeadId, HpkeCiphertext, HpkeConfig, HpkeConfigId, HpkeKdfId, HpkeKemId, Role

# suite constants: DHKEM(X25519, HKDF-SHA256)=0x0020, HKDF-SHA256=0x0001, AES-128-GCM=0x0001
KEM_ID = 0x0020
KDF_ID = 0x0001
AEAD_ID = 0x0001
NK = 16  # AES-128 key
NN = 12  # GCM nonce
NH = 32  # SHA-256
NSECRET = 32

_SUITE_ID = b"HPKE" + KEM_ID.to_bytes(2, "big") + KDF_ID.to_bytes(2, "big") + AEAD_ID.to_bytes(2, "big")
_KEM_SUITE_ID = b"KEM" + KEM_ID.to_bytes(2, "big")


class HpkeError(Exception):
    pass


def _hmac_sha256(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def _labeled_extract(suite_id: bytes, salt: bytes, label: bytes, ikm: bytes) -> bytes:
    return _hmac_sha256(salt, b"HPKE-v1" + suite_id + label + ikm)


def _labeled_expand(suite_id: bytes, prk: bytes, label: bytes, info: bytes, length: int) -> bytes:
    labeled_info = length.to_bytes(2, "big") + b"HPKE-v1" + suite_id + label + info
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = _hmac_sha256(prk, t + labeled_info + bytes([i]))
        out += t
        i += 1
    return out[:length]


def _extract_and_expand(dh: bytes, kem_context: bytes) -> bytes:
    eae_prk = _labeled_extract(_KEM_SUITE_ID, b"", b"eae_prk", dh)
    return _labeled_expand(_KEM_SUITE_ID, eae_prk, b"shared_secret", kem_context, NSECRET)


def _key_schedule(shared_secret: bytes, info: bytes) -> tuple[bytes, bytes]:
    """Base mode key schedule -> (key, base_nonce)."""
    psk_id_hash = _labeled_extract(_SUITE_ID, b"", b"psk_id_hash", b"")
    info_hash = _labeled_extract(_SUITE_ID, b"", b"info_hash", info)
    key_schedule_context = b"\x00" + psk_id_hash + info_hash
    secret = _labeled_extract(_SUITE_ID, shared_secret, b"secret", b"")
    key = _labeled_expand(_SUITE_ID, secret, b"key", key_schedule_context, NK)
    base_nonce = _labeled_expand(_SUITE_ID, secret, b"base_nonce", key_schedule_context, NN)
    return key, base_nonce


class Label(enum.Enum):
    """DAP application-info labels (reference core/src/hpke.rs:45)."""

    INPUT_SHARE = b"dap-07 input share"
    AGGREGATE_SHARE = b"dap-07 aggregate share"


@dataclass(frozen=True)
class HpkeApplicationInfo:
    """label || sender role || recipient role (reference core/src/hpke.rs:62)."""

    label: Label
    sender: Role
    recipient: Role

    def bytes(self) -> bytes:
        return self.label.value + bytes([self.sender.value, self.recipient.value])


@dataclass(frozen=True)
class HpkeKeypair:
    config: HpkeConfig
    private_key: bytes  # raw X25519 scalar

    def config_id(self) -> HpkeConfigId:
        return self.config.id


def generate_hpke_config_and_private_key(config_id: int = 0) -> HpkeKeypair:
    """reference core/src/hpke.rs generate_hpke_config_and_private_key."""
    sk = X25519PrivateKey.generate()
    pk_bytes = sk.public_key().public_bytes_raw()
    config = HpkeConfig(
        HpkeConfigId(config_id),
        HpkeKemId.X25519_HKDF_SHA256,
        HpkeKdfId.HKDF_SHA256,
        HpkeAeadId.AES_128_GCM,
        pk_bytes,
    )
    return HpkeKeypair(config, sk.private_bytes_raw())


def _check_config(config: HpkeConfig) -> None:
    if (
        config.kem_id != HpkeKemId.X25519_HKDF_SHA256
        or config.kdf_id != HpkeKdfId.HKDF_SHA256
        or config.aead_id != HpkeAeadId.AES_128_GCM
    ):
        raise HpkeError(f"unsupported HPKE ciphersuite {config}")


def hpke_seal(
    config: HpkeConfig,
    application_info: HpkeApplicationInfo,
    plaintext: bytes,
    aad: bytes,
) -> HpkeCiphertext:
    """Single-shot base-mode seal to `config`'s public key."""
    _check_config(config)
    pk_r = X25519PublicKey.from_public_bytes(config.public_key)
    sk_e = X25519PrivateKey.generate()
    enc = sk_e.public_key().public_bytes_raw()
    dh = sk_e.exchange(pk_r)
    shared_secret = _extract_and_expand(dh, enc + config.public_key)
    key, base_nonce = _key_schedule(shared_secret, application_info.bytes())
    ct = AESGCM(key).encrypt(base_nonce, plaintext, aad)
    return HpkeCiphertext(config.id, enc, ct)


def hpke_open(
    keypair: HpkeKeypair,
    application_info: HpkeApplicationInfo,
    ciphertext: HpkeCiphertext,
    aad: bytes,
) -> bytes:
    """Single-shot base-mode open with the recipient private key."""
    _check_config(keypair.config)
    if ciphertext.config_id != keypair.config.id:
        raise HpkeError(
            f"config id mismatch: {ciphertext.config_id} != {keypair.config.id}"
        )
    sk_r = X25519PrivateKey.from_private_bytes(keypair.private_key)
    pk_e = X25519PublicKey.from_public_bytes(ciphertext.encapsulated_key)
    dh = sk_r.exchange(pk_e)
    kem_context = ciphertext.encapsulated_key + keypair.config.public_key
    shared_secret = _extract_and_expand(dh, kem_context)
    key, base_nonce = _key_schedule(shared_secret, application_info.bytes())
    try:
        return AESGCM(key).decrypt(base_nonce, ciphertext.payload, aad)
    except Exception as e:  # InvalidTag
        raise HpkeError(f"decryption failed: {e}") from e
