"""Clock abstraction with a controllable test clock.

Equivalent of reference core/src/time.rs:11-87 (`Clock`, `RealClock`,
`MockClock`); the interval/rounding extension methods live on the
message types themselves (janus_tpu.messages.core.Time/Interval).
"""

from __future__ import annotations

import threading
import time as _time

from ..messages import Duration, Time


class Clock:
    def now(self) -> Time:
        raise NotImplementedError


class RealClock(Clock):
    def now(self) -> Time:
        return Time(int(_time.time()))


class MockClock(Clock):
    """Settable/advanceable clock for tests (reference core/src/time.rs:42)."""

    def __init__(self, when: Time = Time(1577836800)):  # 2020-01-01T00:00:00Z
        self._now = when
        self._lock = threading.Lock()

    def now(self) -> Time:
        with self._lock:
            return self._now

    def advance(self, d: Duration) -> None:
        with self._lock:
            self._now = self._now.add(d)

    def set(self, when: Time) -> None:
        with self._lock:
            self._now = when
