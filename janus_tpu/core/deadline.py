"""Request/step deadline propagation (docs/ROBUSTNESS.md "Device hangs
& deadlines").

One ambient deadline per unit of work, carried in a contextvar:

* a **job driver** enters `deadline_scope(lease_deadline(...))` around a
  leased step, so every stage of the step — engine dispatch, helper
  HTTP, datastore writes — shares the lease budget;
* the **HTTP client** stamps the remaining budget on outbound requests
  as the `DAP-Janus-Deadline` header (seconds, decimal — a duration,
  not a wall-clock instant, so leader/helper clock skew cannot corrupt
  it);
* the **helper** turns the header back into an absolute monotonic
  deadline at admission — backdated by the time the request sat in the
  accept queue (`set_request_queue_age`, stamped by the serving layer)
  so a request that expired while queued is shed before any HPKE work —
  and enters `deadline_scope` for the handler, where
  `check(stage)` raises `DeadlineExceeded` between stages and the
  device watchdog bounds the engine dispatch itself.

`DeadlineExceeded` is the one exception type for "the budget is dead":
the retry loop (core/retries.py), the watchdog-bounded engine and the
helper handler all raise it, and the job drivers translate it into a
step-back (`janus_job_step_back_total{reason="deadline_expired"}`)
instead of a failed attempt. A helper that hits it mid-handler answers
the conclusive `DEADLINE_EXCEEDED_STATUS` (408 — deliberately NOT a
retryable 5xx: dead work must be dropped, never amplified by retries
against the same dead budget), which the leader maps back to
DeadlineExceeded and steps back on.

With no scope entered, every hook here is a no-op: `current_deadline()`
is one contextvar read, so un-deadlined paths (tests, bench, uploads)
pay nothing.
"""

from __future__ import annotations

import contextlib
import contextvars
import time

# Header carrying the sender's REMAINING budget in seconds (decimal).
# A duration survives clock skew between aggregators; the receiver
# anchors it to its own monotonic clock at admission.
DEADLINE_HEADER = "DAP-Janus-Deadline"

# Conclusive "your budget is dead" answer (helper -> leader). 408 is
# not in core.retries.RETRYABLE_STATUS, so the leader's retry loop
# returns it immediately and the driver steps back instead of hammering
# the helper with more already-dead work.
DEADLINE_EXCEEDED_STATUS = 408

# Refuse to anchor absurd header values: a buggy/hostile remaining
# beyond this simply means "effectively unbounded" and is clamped.
MAX_REMAINING_S = 24 * 3600.0


class DeadlineExceeded(TimeoutError):
    """The work's deadline (lease bound / propagated request budget)
    tripped before completion. Carries the last retryable status, if
    any, so callers can log it — but deliberately NOT as a
    (status, body) return value: a stale 5xx from an earlier attempt
    must not masquerade as the conclusive outcome of the request."""

    def __init__(self, msg: str, last_status: int | None = None):
        super().__init__(msg)
        self.last_status = last_status


_deadline_var: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "janus_deadline", default=None
)
# seconds the CURRENT request spent in the server's accept queue before
# a handler thread picked it up (set per-request by DapServer)
_queue_age_var: contextvars.ContextVar[float] = contextvars.ContextVar(
    "janus_request_queue_age", default=0.0
)


def current_deadline() -> float | None:
    """The ambient time.monotonic() deadline, or None (unbounded)."""
    return _deadline_var.get()


def remaining_s() -> float | None:
    """Seconds left on the ambient deadline (may be negative), or None."""
    dl = _deadline_var.get()
    if dl is None:
        return None
    return dl - time.monotonic()


@contextlib.contextmanager
def deadline_scope(deadline: float | None):
    """Set the ambient deadline (a time.monotonic() value, or None to
    explicitly clear an inherited one) for the duration of the block."""
    token = _deadline_var.set(deadline)
    try:
        yield deadline
    finally:
        _deadline_var.reset(token)


def check(stage: str) -> None:
    """Raise DeadlineExceeded if the ambient deadline has passed.
    Sprinkled between a handler's stages (decrypt loop, pre-run_tx) so
    dead work is dropped at the next seam instead of carried through to
    a response nobody is waiting for. Counted per stage in
    janus_request_deadline_exceeded_total."""
    dl = _deadline_var.get()
    if dl is None or time.monotonic() < dl:
        return
    from .. import metrics

    metrics.request_deadline_exceeded_total.add(stage=stage)
    raise DeadlineExceeded(f"deadline exceeded during {stage}")


def header_value(deadline: float | None) -> str | None:
    """Encode a monotonic deadline as the DAP-Janus-Deadline header
    value (remaining seconds), or None when unbounded/already dead (an
    expired budget is the sender's problem to step back on, not a
    header worth sending)."""
    if deadline is None:
        return None
    rem = deadline - time.monotonic()
    if rem <= 0:
        return None
    return f"{min(rem, MAX_REMAINING_S):.3f}"


def parse_header(headers, queue_age_s: float = 0.0) -> float | None:
    """Absolute monotonic deadline from a request's headers, or None.

    `queue_age_s` backdates the anchor: the sender stamped its
    remaining budget when the request left its socket, so time the
    request spent waiting in OUR accept queue has already been spent —
    a request that expired while queued parses to a deadline in the
    past and is shed at admission. Unparseable/negative values are
    ignored (None): the deadline contract is an optimization, never a
    correctness dependency."""
    raw = None
    for k, v in headers.items():
        if str(k).lower() == DEADLINE_HEADER.lower():
            raw = v
            break
    if raw is None:
        return None
    try:
        rem = float(raw)
    except (TypeError, ValueError):
        return None
    if rem < 0:
        return None
    rem = min(rem, MAX_REMAINING_S)
    return time.monotonic() - max(0.0, queue_age_s) + rem


def set_request_queue_age(age_s: float) -> None:
    """Record how long the current request sat in the accept queue
    (stamped by the serving layer before dispatching to handlers)."""
    _queue_age_var.set(max(0.0, age_s))


def request_queue_age() -> float:
    return _queue_age_var.get()
