"""Minimal HTTP client with the (status, body) convention used by
retry_http_request. The reference uses reqwest (aggregator.rs:3033
send_request_to_helper); this wraps urllib for the same purpose.
"""

from __future__ import annotations

import socket
import threading
import urllib.error
import urllib.request

from .. import failpoints
from . import deadline


def _injected_transport_error() -> urllib.error.URLError:
    return urllib.error.URLError("injected transport error (failpoint helper.request)")


def _injected_timeout() -> urllib.error.URLError:
    # what a real socket timeout looks like through urllib: a URLError
    # wrapping socket.timeout (an OSError), so retry loops treat it as
    # any other transport failure
    return urllib.error.URLError(socket.timeout("injected timeout (failpoint)"))


def fetch_any_status(
    url: str,
    method: str = "GET",
    body: bytes | None = None,
    headers: dict | None = None,
    timeout: float = 10.0,
) -> tuple[int, bytes]:
    """One request returning (status, body) for ANY status — urllib
    raises HTTPError on non-2xx, but probes of degraded endpoints
    (/readyz answering 503, shed routes) need the status and body, not
    an exception. Shared by scripts/scrape_check.py and the chaos
    harness so the quirk-workaround lives once."""
    req = urllib.request.Request(url, data=body, headers=headers or {}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class HttpClient:
    # Default generous: a cold aggregator's first request per task can
    # legitimately take minutes (XLA engine compile). The job drivers
    # cap per-request timeouts by lease remaining (job_driver.py
    # deadline_request_timeout), so hot paths stay bounded.
    def __init__(self, timeout: float = 300.0):
        self.timeout = timeout
        self._local = threading.local()

    @property
    def last_response_headers(self) -> dict:
        """Response headers of this thread's most recent request
        (clients are shared across driver worker threads)."""
        return getattr(self._local, "headers", {})

    @last_response_headers.setter
    def last_response_headers(self, value: dict) -> None:
        self._local.headers = value

    def request(
        self,
        method: str,
        url: str,
        body: bytes | None = None,
        headers: dict | None = None,
        timeout: float | None = None,
    ):
        # clear this thread's previous response headers FIRST: a thrown
        # URLError below would otherwise leave the prior response's
        # headers visible, and retry_http_request could honor a stale
        # Retry-After from an earlier attempt
        self.last_response_headers = {}
        # fault injection for the whole outbound path (error = transport
        # failure, delay = slow WAN, timeout = hung peer, crash = the
        # process dies mid-request); docs/ROBUSTNESS.md
        failpoints.hit(
            "helper.request",
            error_factory=_injected_transport_error,
            timeout_factory=_injected_timeout,
        )
        headers = dict(headers or {})
        if not any(k.lower() == "traceparent" for k in headers):
            from ..trace import current_traceparent

            tp = current_traceparent()
            if tp is not None:
                headers["traceparent"] = tp
        # deadline propagation (core/deadline.py): inside a driver's
        # lease-bounded step the REMAINING budget rides every outbound
        # request (re-stamped per retry attempt, so the helper always
        # sees the true residue), and the helper sheds work whose
        # budget died in transit or in its accept queue
        if not any(k.lower() == deadline.DEADLINE_HEADER.lower() for k in headers):
            dl = deadline.header_value(deadline.current_deadline())
            if dl is not None:
                headers[deadline.DEADLINE_HEADER] = dl
        req = urllib.request.Request(url, data=body, method=method, headers=headers)
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout if timeout is None else min(self.timeout, timeout)
            ) as resp:
                self.last_response_headers = dict(resp.headers.items())
                # slow-body injection: the peer answered but trickles
                # the payload
                failpoints.hit("helper.response", timeout_factory=_injected_timeout)
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            self.last_response_headers = dict(e.headers.items())
            try:
                err_body = e.read()
            except OSError as read_err:
                # connection reset while draining the error body: this
                # is a transport failure, not a conclusive response —
                # surface it as a retryable URLError instead of letting
                # a raw ConnectionResetError escape the retry loop
                raise urllib.error.URLError(read_err) from read_err
            return e.code, err_body

    def get(self, url: str, headers: dict | None = None, timeout: float | None = None):
        return self.request("GET", url, None, headers, timeout)

    def put(self, url: str, body: bytes, headers: dict | None = None, timeout: float | None = None):
        return self.request("PUT", url, body, headers, timeout)

    def post(self, url: str, body: bytes, headers: dict | None = None, timeout: float | None = None):
        return self.request("POST", url, body, headers, timeout)

    def delete(self, url: str, headers: dict | None = None, timeout: float | None = None):
        return self.request("DELETE", url, None, headers, timeout)
