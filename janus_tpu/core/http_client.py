"""Minimal HTTP client with the (status, body) convention used by
retry_http_request. The reference uses reqwest (aggregator.rs:3033
send_request_to_helper); this wraps urllib for the same purpose.
"""

from __future__ import annotations

import http.client
import socket
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

from .. import failpoints
from . import deadline

# chunked body reads: each recv is bounded by the socket timeout AND the
# whole body by the wall-clock budget below
_READ_CHUNK = 65536


class PeerResponseTooLarge(Exception):
    """The peer's response body exceeded the configured size cap. NOT an
    OSError on purpose: retry_http_request must let it propagate — a
    peer streaming gigabytes is misbehaving, and replaying the request
    would just stream them again. The driver step fails (attempt
    counted) instead of the process OOMing."""

    def __init__(self, url: str, limit_bytes: int):
        super().__init__(
            f"response body from {url} exceeded the {limit_bytes}-byte cap"
        )
        self.url = url
        self.limit_bytes = limit_bytes


@dataclass(frozen=True)
class HttpClientConfig:
    """YAML `helper_http:` stanza of the job driver binaries: the
    per-ATTEMPT half of the overall-deadline/per-attempt-timeout split.
    The retry loop's overall budget stays the lease deadline
    (job_driver.py deadline_request_timeout); each attempt is
    additionally capped here so a blackholed peer burns seconds per
    attempt, not the whole lease on attempt one."""

    # connect + per-read socket timeout AND the default body budget of
    # one attempt. The default stays as generous as HttpClient's (a cold
    # aggregator's first request per task legitimately takes minutes of
    # XLA compile); deployments that pre-warm engines should tighten it.
    attempt_timeout_s: float = 300.0
    # wall-clock budget for reading ONE response body (None = the
    # attempt timeout): a slow-drip peer feeds a byte per read and
    # resets the per-read socket timer forever — only a wall clock
    # bounds it
    body_budget_s: float | None = None
    # response body size cap (a misbehaving peer must reject cleanly,
    # not OOM the driver)
    max_response_bytes: int = 64 << 20

    @classmethod
    def from_dict(cls, d: dict | None) -> "HttpClientConfig":
        d = d or {}
        budget = d.get("body_budget_secs")
        return cls(
            attempt_timeout_s=float(d.get("attempt_timeout_secs", 300.0)),
            body_budget_s=None if budget is None else float(budget),
            max_response_bytes=int(float(d.get("max_response_mb", 64.0)) * (1 << 20)),
        )

    def build(self) -> "HttpClient":
        return HttpClient(
            timeout=self.attempt_timeout_s,
            body_budget_s=self.body_budget_s,
            max_response_bytes=self.max_response_bytes,
        )


def _injected_transport_error() -> urllib.error.URLError:
    return urllib.error.URLError("injected transport error (failpoint helper.request)")


def _injected_timeout() -> urllib.error.URLError:
    # what a real socket timeout looks like through urllib: a URLError
    # wrapping socket.timeout (an OSError), so retry loops treat it as
    # any other transport failure
    return urllib.error.URLError(socket.timeout("injected timeout (failpoint)"))


def fetch_any_status(
    url: str,
    method: str = "GET",
    body: bytes | None = None,
    headers: dict | None = None,
    timeout: float = 10.0,
) -> tuple[int, bytes]:
    """One request returning (status, body) for ANY status — urllib
    raises HTTPError on non-2xx, but probes of degraded endpoints
    (/readyz answering 503, shed routes) need the status and body, not
    an exception. Shared by scripts/scrape_check.py and the chaos
    harness so the quirk-workaround lives once."""
    req = urllib.request.Request(url, data=body, headers=headers or {}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class HttpClient:
    # Default generous: a cold aggregator's first request per task can
    # legitimately take minutes (XLA engine compile). The job drivers
    # cap per-request timeouts by lease remaining (job_driver.py
    # deadline_request_timeout) and configure the per-attempt split via
    # the `helper_http:` stanza (HttpClientConfig), so hot paths stay
    # bounded.
    def __init__(
        self,
        timeout: float = 300.0,
        body_budget_s: float | None = None,
        max_response_bytes: int = 64 << 20,
    ):
        self.timeout = timeout
        # wall-clock budget for one response body read; None = the
        # effective per-attempt timeout (socket timeouts are per READ —
        # a slow-drip peer resets that timer on every byte, so only a
        # wall clock bounds the whole body)
        self.body_budget_s = body_budget_s
        self.max_response_bytes = max_response_bytes
        self._local = threading.local()

    def _read_body(self, resp, url: str, budget_s: float | None) -> bytes:
        """Chunked body read under a WALL-CLOCK budget and a size cap.
        A budget breach surfaces as a URLError-wrapped timeout (a
        transport failure: retryable, breaker-counted); a size breach
        as PeerResponseTooLarge (non-retryable by construction). A
        truncated/garbled body (http.client.IncompleteRead and kin are
        HTTPException, not OSError) is normalized to URLError too, so
        a mid-body FIN retries like any torn connection instead of
        escaping the retry loop as a raw stdlib internal."""
        chunks: list[bytes] = []
        total = 0
        t0 = time.monotonic()
        while True:
            if budget_s is not None and time.monotonic() - t0 > budget_s:
                raise urllib.error.URLError(
                    socket.timeout(
                        f"response body read exceeded the {budget_s:g}s "
                        f"wall-clock budget ({total} bytes in)"
                    )
                )
            try:
                chunk = resp.read(_READ_CHUNK)
            except http.client.HTTPException as e:
                raise urllib.error.URLError(e) from e
            if not chunk:
                # stdlib quirk: read(amt) returns b"" on a premature FIN
                # instead of raising IncompleteRead (only the readall
                # path raises) — check the undelivered Content-Length
                # residue ourselves, or a truncated wire would surface
                # as a silently short body
                remaining = getattr(resp, "length", None)
                if remaining:
                    short = http.client.IncompleteRead(b"", remaining)
                    raise urllib.error.URLError(short)
                return b"".join(chunks)
            total += len(chunk)
            if self.max_response_bytes and total > self.max_response_bytes:
                raise PeerResponseTooLarge(url, self.max_response_bytes)
            chunks.append(chunk)

    @property
    def last_response_headers(self) -> dict:
        """Response headers of this thread's most recent request
        (clients are shared across driver worker threads)."""
        return getattr(self._local, "headers", {})

    @last_response_headers.setter
    def last_response_headers(self, value: dict) -> None:
        self._local.headers = value

    def request(
        self,
        method: str,
        url: str,
        body: bytes | None = None,
        headers: dict | None = None,
        timeout: float | None = None,
    ):
        # clear this thread's previous response headers FIRST: a thrown
        # URLError below would otherwise leave the prior response's
        # headers visible, and retry_http_request could honor a stale
        # Retry-After from an earlier attempt
        self.last_response_headers = {}
        # fault injection for the whole outbound path (error = transport
        # failure, delay = slow WAN, timeout = hung peer, crash = the
        # process dies mid-request); docs/ROBUSTNESS.md
        failpoints.hit(
            "helper.request",
            error_factory=_injected_transport_error,
            timeout_factory=_injected_timeout,
        )
        headers = dict(headers or {})
        if not any(k.lower() == "traceparent" for k in headers):
            from ..trace import current_traceparent

            tp = current_traceparent()
            if tp is not None:
                headers["traceparent"] = tp
        # deadline propagation (core/deadline.py): inside a driver's
        # lease-bounded step the REMAINING budget rides every outbound
        # request (re-stamped per retry attempt, so the helper always
        # sees the true residue), and the helper sheds work whose
        # budget died in transit or in its accept queue
        if not any(k.lower() == deadline.DEADLINE_HEADER.lower() for k in headers):
            dl = deadline.header_value(deadline.current_deadline())
            if dl is not None:
                headers[deadline.DEADLINE_HEADER] = dl
        req = urllib.request.Request(url, data=body, method=method, headers=headers)
        effective_timeout = (
            self.timeout if timeout is None else min(self.timeout, timeout)
        )
        # the body budget defaults to the per-attempt timeout: one
        # attempt (connect + headers + WHOLE body) is then wall-clock
        # bounded even against a slow-drip peer
        budget = self.body_budget_s
        if budget is None:
            budget = effective_timeout
        try:
            with urllib.request.urlopen(req, timeout=effective_timeout) as resp:
                self.last_response_headers = dict(resp.headers.items())
                # slow-body injection: the peer answered but trickles
                # the payload
                failpoints.hit("helper.response", timeout_factory=_injected_timeout)
                return resp.status, self._read_body(resp, url, budget)
        except urllib.error.HTTPError as e:
            self.last_response_headers = dict(e.headers.items())
            try:
                # the error body rides the same budget + size cap: a
                # slow-dripped 503 page pins a worker exactly like a
                # slow-dripped 200 would
                err_body = self._read_body(e, url, budget)
            except OSError as read_err:
                # connection reset while draining the error body: this
                # is a transport failure, not a conclusive response —
                # surface it as a retryable URLError instead of letting
                # a raw ConnectionResetError escape the retry loop
                raise urllib.error.URLError(read_err) from read_err
            return e.code, err_body

    def get(self, url: str, headers: dict | None = None, timeout: float | None = None):
        return self.request("GET", url, None, headers, timeout)

    def put(self, url: str, body: bytes, headers: dict | None = None, timeout: float | None = None):
        return self.request("PUT", url, body, headers, timeout)

    def post(self, url: str, body: bytes, headers: dict | None = None, timeout: float | None = None):
        return self.request("POST", url, body, headers, timeout)

    def delete(self, url: str, headers: dict | None = None, timeout: float | None = None):
        return self.request("DELETE", url, None, headers, timeout)
