"""Shared runtime utilities: HPKE, clocks, auth tokens, retries.

Python equivalent of the reference's `core` crate (SURVEY.md section
2.3). The VDAF registry lives in janus_tpu.vdaf.registry.
"""

from .hpke import (
    HpkeApplicationInfo,
    HpkeKeypair,
    Label,
    generate_hpke_config_and_private_key,
    hpke_open,
    hpke_seal,
)
from .time_util import Clock, MockClock, RealClock
from .auth import AuthenticationToken, DAP_AUTH_HEADER

__all__ = [
    "HpkeApplicationInfo",
    "HpkeKeypair",
    "Label",
    "generate_hpke_config_and_private_key",
    "hpke_open",
    "hpke_seal",
    "Clock",
    "MockClock",
    "RealClock",
    "AuthenticationToken",
    "DAP_AUTH_HEADER",
]
