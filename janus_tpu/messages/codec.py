"""TLS-syntax (RFC 8446 presentation language) codec primitives.

Equivalent of the `prio::codec` surface the reference's messages crate
builds on (Encode/Decode/encode_u16_items etc., SURVEY.md section 2.2):
big-endian fixed-width integers and length-prefixed opaque vectors.
"""

from __future__ import annotations

import struct


class DecodeError(ValueError):
    pass


class Encoder:
    __slots__ = ("_parts",)

    def __init__(self):
        self._parts: list[bytes] = []

    def bytes(self) -> bytes:
        return b"".join(self._parts)

    def write(self, raw: bytes) -> "Encoder":
        self._parts.append(raw)
        return self

    def u8(self, v: int) -> "Encoder":
        return self.write(struct.pack(">B", v))

    def u16(self, v: int) -> "Encoder":
        return self.write(struct.pack(">H", v))

    def u32(self, v: int) -> "Encoder":
        return self.write(struct.pack(">I", v))

    def u64(self, v: int) -> "Encoder":
        return self.write(struct.pack(">Q", v))

    def opaque_u8(self, raw: bytes) -> "Encoder":
        assert len(raw) < (1 << 8)
        return self.u8(len(raw)).write(raw)

    def opaque_u16(self, raw: bytes) -> "Encoder":
        assert len(raw) < (1 << 16)
        return self.u16(len(raw)).write(raw)

    def opaque_u32(self, raw: bytes) -> "Encoder":
        assert len(raw) < (1 << 32)
        return self.u32(len(raw)).write(raw)

    def items_u16(self, items) -> "Encoder":
        """u16-length-prefixed (in bytes) list of encodable items."""
        inner = Encoder()
        for it in items:
            it.encode(inner)
        return self.opaque_u16(inner.bytes())

    def items_u32(self, items) -> "Encoder":
        inner = Encoder()
        for it in items:
            it.encode(inner)
        return self.opaque_u32(inner.bytes())


class Decoder:
    __slots__ = ("_buf", "_pos", "_end")

    def __init__(self, buf: bytes, pos: int = 0, end: int | None = None):
        self._buf = buf
        self._pos = pos
        self._end = len(buf) if end is None else end

    @property
    def remaining(self) -> int:
        return self._end - self._pos

    def finish(self) -> None:
        if self.remaining != 0:
            raise DecodeError(f"{self.remaining} trailing bytes")

    def take(self, n: int) -> bytes:
        if self.remaining < n:
            raise DecodeError("unexpected end of input")
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack(">H", self.take(2))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self.take(8))[0]

    def opaque_u8(self) -> bytes:
        return self.take(self.u8())

    def opaque_u16(self) -> bytes:
        return self.take(self.u16())

    def opaque_u32(self) -> bytes:
        return self.take(self.u32())

    def sub(self, n: int) -> "Decoder":
        """A decoder over the next n bytes (consumed from self)."""
        if self.remaining < n:
            raise DecodeError("unexpected end of input")
        d = Decoder(self._buf, self._pos, self._pos + n)
        self._pos += n
        return d

    def items_u16(self, decode_one) -> list:
        d = self.sub(self.u16())
        out = []
        while d.remaining:
            out.append(decode_one(d))
        return out

    def items_u32(self, decode_one) -> list:
        d = self.sub(self.u32())
        out = []
        while d.remaining:
            out.append(decode_one(d))
        return out


# Ping-pong message framing (prio topology::ping_pong): u8 tag, then
# 1 (initialize/finish) or 2 (continue) opaque-u32 fields. DAP embeds
# these messages inline (self-delimiting, no outer length prefix) in
# PrepareInit/PrepareContinue/PrepareStepResult. Single home for the
# tag->field-count mapping; vdaf.wire imports these constants.
PP_INITIALIZE = 0
PP_CONTINUE = 1
PP_FINISH = 2


def decode_pingpong_frame(dec: Decoder) -> bytes:
    """Consume one self-delimiting ping-pong message, return its raw bytes."""
    start = dec._pos
    tag = dec.u8()
    if tag == PP_INITIALIZE or tag == PP_FINISH:
        dec.opaque_u32()
    elif tag == PP_CONTINUE:
        dec.opaque_u32()
        dec.opaque_u32()
    else:
        raise DecodeError(f"bad ping-pong message tag {tag}")
    return dec._buf[start : dec._pos]


def check_pingpong_frame(raw: bytes) -> None:
    """Raise DecodeError unless raw is exactly one ping-pong message."""
    dec = Decoder(raw)
    decode_pingpong_frame(dec)
    dec.finish()


class Codec:
    """Mixin: encode to / decode from bytes via Encoder/Decoder methods."""

    def encode(self, enc: Encoder) -> None:
        raise NotImplementedError

    @classmethod
    def decode(cls, dec: Decoder):
        raise NotImplementedError

    def to_bytes(self) -> bytes:
        enc = Encoder()
        self.encode(enc)
        return enc.bytes()

    @classmethod
    def from_bytes(cls, raw: bytes, *args, **kwargs):
        dec = Decoder(raw)
        out = cls.decode(dec, *args, **kwargs)
        dec.finish()
        return out
