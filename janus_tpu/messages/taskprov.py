"""taskprov wire messages (draft-wang-ppm-dap-taskprov-04).

Equivalent of the reference's messages/src/taskprov.rs:17 — the in-band
task-provisioning extension: a `TaskConfig` carried base64url-encoded in
the `dap-taskprov` request header, whose SHA-256 digest IS the task ID.
Byte layouts follow the draft's TLS presentation language so the two
cooperating aggregators (and other DAP implementations) interoperate.
"""

from __future__ import annotations

import enum
import hashlib
import struct
from dataclasses import dataclass

from .codec import Codec, DecodeError, Decoder, Encoder
from .core import Duration, TaskId, Time

TASKPROV_HEADER = "dap-taskprov"  # reference core/src/lib.rs:40


class DpMechanism(enum.IntEnum):
    """reference messages/src/taskprov.rs (DpMechanism)."""

    RESERVED = 0
    NONE = 1


@dataclass(frozen=True)
class DpConfig(Codec):
    """Differential-privacy configuration (mostly unspecified upstream).

    reference messages/src/taskprov.rs (DpConfig).
    """

    dp_mechanism: DpMechanism = DpMechanism.NONE

    def encode(self, enc: Encoder) -> None:
        enc.u8(int(self.dp_mechanism))

    @classmethod
    def decode(cls, dec: Decoder) -> "DpConfig":
        v = dec.u8()
        try:
            return cls(DpMechanism(v))
        except ValueError:
            raise DecodeError(f"unexpected DpMechanism {v}")


class TaskprovQueryType(enum.IntEnum):
    RESERVED = 0
    TIME_INTERVAL = 1
    FIXED_SIZE = 2


@dataclass(frozen=True)
class QueryConfig(Codec):
    """Batch properties for a provisioned task.

    reference messages/src/taskprov.rs (QueryConfig). Note the draft
    encodes the query-type byte FIRST but its parameter (fixed-size
    max_batch_size) LAST, after min_batch_size.
    """

    time_precision: Duration
    max_batch_query_count: int
    min_batch_size: int
    query_type: TaskprovQueryType
    max_batch_size: int | None = None  # fixed-size only

    def __post_init__(self):
        if (self.query_type == TaskprovQueryType.FIXED_SIZE) != (
            self.max_batch_size is not None
        ):
            raise ValueError("max_batch_size iff fixed-size query")

    def encode(self, enc: Encoder) -> None:
        enc.u8(int(self.query_type))
        self.time_precision.encode(enc)
        enc.u16(self.max_batch_query_count)
        enc.u32(self.min_batch_size)
        if self.query_type == TaskprovQueryType.FIXED_SIZE:
            enc.u32(self.max_batch_size)

    @classmethod
    def decode(cls, dec: Decoder) -> "QueryConfig":
        qt = dec.u8()
        time_precision = Duration.decode(dec)
        max_bqc = dec.u16()
        min_bs = dec.u32()
        try:
            qt = TaskprovQueryType(qt)
        except ValueError:
            raise DecodeError(f"unexpected taskprov QueryType {qt}")
        max_batch_size = dec.u32() if qt == TaskprovQueryType.FIXED_SIZE else None
        return cls(time_precision, max_bqc, min_bs, qt, max_batch_size)


class VdafTypeCode(enum.IntEnum):
    PRIO3_COUNT = 0x00000000
    PRIO3_SUM = 0x00000001
    PRIO3_HISTOGRAM = 0x00000002
    POPLAR1 = 0x00001000


@dataclass(frozen=True)
class VdafType(Codec):
    """VDAF type + parameters (reference messages/src/taskprov.rs VdafType).

    Exactly one parameter set is used per code: `bits` for
    PRIO3_SUM (u8) and POPLAR1 (u16), `buckets` (u24-prefixed list of
    u64 bucket boundaries) for PRIO3_HISTOGRAM.
    """

    code: VdafTypeCode
    bits: int = 0
    buckets: tuple[int, ...] = ()

    @classmethod
    def prio3_count(cls) -> "VdafType":
        return cls(VdafTypeCode.PRIO3_COUNT)

    @classmethod
    def prio3_sum(cls, bits: int) -> "VdafType":
        return cls(VdafTypeCode.PRIO3_SUM, bits=bits)

    @classmethod
    def prio3_histogram(cls, buckets) -> "VdafType":
        if not buckets:
            raise ValueError("buckets must not be empty for Prio3Histogram")
        return cls(VdafTypeCode.PRIO3_HISTOGRAM, buckets=tuple(buckets))

    @classmethod
    def poplar1(cls, bits: int) -> "VdafType":
        return cls(VdafTypeCode.POPLAR1, bits=bits)

    def encode(self, enc: Encoder) -> None:
        enc.u32(int(self.code))
        if self.code == VdafTypeCode.PRIO3_SUM:
            enc.u8(self.bits)
        elif self.code == VdafTypeCode.PRIO3_HISTOGRAM:
            raw = b"".join(struct.pack(">Q", b) for b in self.buckets)
            assert len(raw) < (1 << 24)
            enc.write(len(raw).to_bytes(3, "big")).write(raw)
        elif self.code == VdafTypeCode.POPLAR1:
            enc.u16(self.bits)

    @classmethod
    def decode(cls, dec: Decoder) -> "VdafType":
        code = dec.u32()
        try:
            code = VdafTypeCode(code)
        except ValueError:
            raise DecodeError(f"unexpected VdafType {code:#x}")
        if code == VdafTypeCode.PRIO3_SUM:
            return cls(code, bits=dec.u8())
        if code == VdafTypeCode.PRIO3_HISTOGRAM:
            n = int.from_bytes(dec.take(3), "big")
            if n % 8:
                raise DecodeError("histogram bucket list not a multiple of 8 bytes")
            sub = dec.sub(n)
            buckets = tuple(sub.u64() for _ in range(n // 8))
            if not buckets:
                raise DecodeError("buckets must not be empty for Prio3Histogram")
            return cls(code, buckets=buckets)
        if code == VdafTypeCode.POPLAR1:
            return cls(code, bits=dec.u16())
        return cls(code)

    def to_vdaf_instance(self):
        """Map to a VdafInstance (reference core/src/task.rs:89-110)."""
        from ..vdaf.registry import VdafInstance

        if self.code == VdafTypeCode.PRIO3_COUNT:
            return VdafInstance.count()
        if self.code == VdafTypeCode.PRIO3_SUM:
            return VdafInstance.sum(self.bits)
        if self.code == VdafTypeCode.PRIO3_HISTOGRAM:
            # bucket boundaries -> bucket count (top bucket extends to
            # infinity), as the reference translates pre-VDAF-06 configs
            return VdafInstance.histogram(len(self.buckets) + 1)
        if self.code == VdafTypeCode.POPLAR1:
            return VdafInstance.poplar1(self.bits)
        raise ValueError(f"unsupported taskprov VdafType {self.code!r}")


@dataclass(frozen=True)
class VdafConfig(Codec):
    """reference messages/src/taskprov.rs (VdafConfig)."""

    dp_config: DpConfig
    vdaf_type: VdafType

    def encode(self, enc: Encoder) -> None:
        self.dp_config.encode(enc)
        self.vdaf_type.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder) -> "VdafConfig":
        return cls(DpConfig.decode(dec), VdafType.decode(dec))


def _encode_url(enc: Encoder, url: str) -> None:
    enc.opaque_u16(url.encode())


def _decode_url(dec: Decoder) -> str:
    raw = dec.opaque_u16()
    try:
        return raw.decode("ascii")
    except UnicodeDecodeError:
        raise DecodeError("aggregator endpoint URL is not ASCII")


@dataclass(frozen=True)
class TaskConfig(Codec):
    """Complete in-band task description.

    reference messages/src/taskprov.rs (TaskConfig): task_info
    (u8-prefixed, nonempty), aggregator endpoints (u16-prefixed list of
    u16-prefixed URLs, [leader, helper]), query config, expiration,
    VDAF config.
    """

    task_info: bytes
    aggregator_endpoints: tuple[str, ...]
    query_config: QueryConfig
    task_expiration: Time
    vdaf_config: VdafConfig

    def __post_init__(self):
        if not self.task_info:
            raise ValueError("task_info must not be empty")
        if not self.aggregator_endpoints:
            raise ValueError("aggregator_endpoints must not be empty")

    def encode(self, enc: Encoder) -> None:
        enc.opaque_u8(self.task_info)
        inner = Encoder()
        for url in self.aggregator_endpoints:
            _encode_url(inner, url)
        enc.opaque_u16(inner.bytes())
        self.query_config.encode(enc)
        self.task_expiration.encode(enc)
        self.vdaf_config.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder) -> "TaskConfig":
        task_info = dec.opaque_u8()
        if not task_info:
            raise DecodeError("task_info must not be empty")
        url_dec = dec.sub(dec.u16())
        endpoints = []
        while url_dec.remaining:
            endpoints.append(_decode_url(url_dec))
        if not endpoints:
            raise DecodeError("aggregator_endpoints must not be empty")
        return cls(
            task_info,
            tuple(endpoints),
            QueryConfig.decode(dec),
            Time.decode(dec),
            VdafConfig.decode(dec),
        )

    def computed_task_id(self) -> TaskId:
        """taskprov task ID = SHA-256 of the encoded config
        (reference http_handlers.rs:592)."""
        return TaskId(hashlib.sha256(self.to_bytes()).digest())

    def leader_url(self) -> str:
        return self.aggregator_endpoints[0]

    def helper_url(self) -> str:
        if len(self.aggregator_endpoints) < 2:
            raise ValueError("taskprov configuration is missing the helper")
        return self.aggregator_endpoints[1]
