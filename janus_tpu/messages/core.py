"""DAP-07 message structs (TLS syntax), byte layouts per draft-ietf-ppm-dap-07.

Each class mirrors one struct of the reference's messages crate; the
`file:line` in each docstring cites the reference definition
(messages/src/lib.rs unless noted). Wire layout follows the DAP-07
presentation-language definitions so that cross-implementation interop
(SURVEY.md section 2.9) stays possible.
"""

from __future__ import annotations

import enum
import hashlib
import secrets
import struct
from dataclasses import dataclass, field

from .codec import (
    PP_CONTINUE,
    PP_FINISH,
    PP_INITIALIZE,
    Codec,
    DecodeError,
    Decoder,
    Encoder,
    check_pingpong_frame,
    decode_pingpong_frame,
)


def _fixed(name, size, *, doc=""):
    """Generate a fixed-length opaque byte newtype (TaskId, ReportId...)."""

    @dataclass(frozen=True)
    class Fixed(Codec):
        data: bytes

        SIZE = size

        def __post_init__(self):
            if len(self.data) != size:
                raise ValueError(f"{name} must be {size} bytes")

        def encode(self, enc: Encoder) -> None:
            enc.write(self.data)

        @classmethod
        def decode(cls, dec: Decoder):
            return cls(dec.take(size))

        @classmethod
        def random(cls):
            return cls(secrets.token_bytes(size))

        def __repr__(self):
            return f"{name}({self.data.hex()[:16]}…)"

    Fixed.__name__ = Fixed.__qualname__ = name
    Fixed.__doc__ = doc
    return Fixed


TaskId = _fixed("TaskId", 32, doc="reference messages/src/lib.rs:618")
BatchId = _fixed("BatchId", 32, doc="reference messages/src/lib.rs:273")
ReportId = _fixed("ReportId", 16, doc="reference messages/src/lib.rs:344")
AggregationJobId = _fixed("AggregationJobId", 16, doc="reference messages/src/lib.rs:2366")
CollectionJobId = _fixed("CollectionJobId", 16, doc="reference messages/src/lib.rs:1626")


@dataclass(frozen=True)
class ReportIdChecksum(Codec):
    """XOR-combined SHA-256 digests of report IDs.

    reference messages/src/lib.rs:426 + core/src/report_id.rs:7.
    """

    data: bytes = b"\x00" * 32

    SIZE = 32

    def __post_init__(self):
        if len(self.data) != 32:
            raise ValueError("checksum must be 32 bytes")

    def encode(self, enc: Encoder) -> None:
        enc.write(self.data)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(dec.take(32))

    @classmethod
    def for_report_id(cls, report_id: ReportId) -> "ReportIdChecksum":
        return cls(hashlib.sha256(report_id.data).digest())

    def updated_with(self, report_id: ReportId) -> "ReportIdChecksum":
        return self.combined_with(self.for_report_id(report_id))

    def combined_with(self, other: "ReportIdChecksum") -> "ReportIdChecksum":
        return ReportIdChecksum(bytes(a ^ b for a, b in zip(self.data, other.data)))


@dataclass(frozen=True, order=True)
class Duration(Codec):
    """Seconds; reference messages/src/lib.rs:128."""

    seconds: int

    def encode(self, enc: Encoder) -> None:
        enc.u64(self.seconds)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(dec.u64())


@dataclass(frozen=True, order=True)
class Time(Codec):
    """Seconds since UNIX epoch; reference messages/src/lib.rs:168."""

    seconds: int

    def encode(self, enc: Encoder) -> None:
        enc.u64(self.seconds)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(dec.u64())

    def to_batch_interval_start(self, time_precision: Duration) -> "Time":
        """Round down to a multiple of the task time precision
        (reference core/src/time.rs:177 TimeExt)."""
        p = time_precision.seconds
        return Time(self.seconds - self.seconds % p)

    def add(self, d: Duration) -> "Time":
        return Time(self.seconds + d.seconds)

    def sub(self, d: Duration) -> "Time":
        return Time(self.seconds - d.seconds)


@dataclass(frozen=True)
class Interval(Codec):
    """Half-open [start, start+duration); reference messages/src/lib.rs:210."""

    start: Time
    duration: Duration

    def __post_init__(self):
        # Match the reference's Interval::new overflow check
        # (messages/src/lib.rs:210): end must fit in u64.
        if self.start.seconds + self.duration.seconds > 0xFFFFFFFFFFFFFFFF:
            raise DecodeError("interval end overflows u64")

    def encode(self, enc: Encoder) -> None:
        self.start.encode(enc)
        self.duration.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(Time.decode(dec), Duration.decode(dec))

    @property
    def end(self) -> Time:
        return self.start.add(self.duration)

    def contains(self, t: Time) -> bool:
        return self.start <= t < self.end

    def aligned_to(self, time_precision: Duration) -> bool:
        p = time_precision.seconds
        return self.start.seconds % p == 0 and self.duration.seconds % p == 0

    @classmethod
    def merged(cls, a: "Interval", b: "Interval") -> "Interval":
        """Smallest interval covering both (reference core/src/time.rs:265)."""
        start = min(a.start, b.start)
        end = max(a.end, b.end)
        return cls(start, Duration(end.seconds - start.seconds))


class Role(enum.IntEnum):
    """reference messages/src/lib.rs:495."""

    COLLECTOR = 0
    CLIENT = 1
    LEADER = 2
    HELPER = 3

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.value)

    @classmethod
    def decode(cls, dec: Decoder):
        try:
            return cls(dec.u8())
        except ValueError as e:
            raise DecodeError(str(e))

    def to_bytes(self) -> bytes:  # shadow int.to_bytes for codec symmetry
        return bytes([self.value])

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Role":
        dec = Decoder(raw)
        out = cls.decode(dec)
        dec.finish()
        return out


class HpkeKemId(enum.IntEnum):
    """RFC 9180 KEM registry; reference messages/src/lib.rs:747."""

    P256_HKDF_SHA256 = 0x0010
    X25519_HKDF_SHA256 = 0x0020


class HpkeKdfId(enum.IntEnum):
    HKDF_SHA256 = 0x0001
    HKDF_SHA384 = 0x0002
    HKDF_SHA512 = 0x0003


class HpkeAeadId(enum.IntEnum):
    AES_128_GCM = 0x0001
    AES_256_GCM = 0x0002
    CHACHA20POLY1305 = 0x0003


@dataclass(frozen=True)
class HpkeConfigId(Codec):
    """u8 config id; reference messages/src/lib.rs:835."""

    id: int

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.id)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(dec.u8())


class ExtensionType(enum.IntEnum):
    """reference messages/src/lib.rs:837."""

    TBD = 0
    TASKPROV = 0xFF00


@dataclass(frozen=True)
class Extension(Codec):
    """reference messages/src/lib.rs:837."""

    extension_type: int
    extension_data: bytes = b""

    def encode(self, enc: Encoder) -> None:
        enc.u16(self.extension_type)
        enc.opaque_u16(self.extension_data)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(dec.u16(), dec.opaque_u16())


@dataclass(frozen=True)
class HpkeCiphertext(Codec):
    """reference messages/src/lib.rs:915."""

    config_id: HpkeConfigId
    encapsulated_key: bytes
    payload: bytes

    def encode(self, enc: Encoder) -> None:
        self.config_id.encode(enc)
        enc.opaque_u16(self.encapsulated_key)
        enc.opaque_u32(self.payload)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(HpkeConfigId.decode(dec), dec.opaque_u16(), dec.opaque_u32())


@dataclass(frozen=True)
class HpkeConfig(Codec):
    """reference messages/src/lib.rs:1079."""

    id: HpkeConfigId
    kem_id: HpkeKemId
    kdf_id: HpkeKdfId
    aead_id: HpkeAeadId
    public_key: bytes

    def encode(self, enc: Encoder) -> None:
        self.id.encode(enc)
        enc.u16(self.kem_id)
        enc.u16(self.kdf_id)
        enc.u16(self.aead_id)
        enc.opaque_u16(self.public_key)

    @classmethod
    def decode(cls, dec: Decoder):
        cid = HpkeConfigId.decode(dec)
        algs = []
        for reg in (HpkeKemId, HpkeKdfId, HpkeAeadId):
            v = dec.u16()
            try:
                algs.append(reg(v))
            except ValueError:
                raise DecodeError(f"unsupported {reg.__name__} {v:#x}")
        return cls(cid, *algs, dec.opaque_u16())


@dataclass(frozen=True)
class HpkeConfigList(Codec):
    """reference messages/src/lib.rs:1171."""

    configs: tuple

    def encode(self, enc: Encoder) -> None:
        enc.items_u16(self.configs)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(tuple(dec.items_u16(HpkeConfig.decode)))


@dataclass(frozen=True)
class ReportMetadata(Codec):
    """reference messages/src/lib.rs:1209."""

    report_id: ReportId
    time: Time

    def encode(self, enc: Encoder) -> None:
        self.report_id.encode(enc)
        self.time.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(ReportId.decode(dec), Time.decode(dec))


@dataclass(frozen=True)
class PlaintextInputShare(Codec):
    """reference messages/src/lib.rs:1253."""

    extensions: tuple
    payload: bytes

    def encode(self, enc: Encoder) -> None:
        enc.items_u16(self.extensions)
        enc.opaque_u32(self.payload)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(tuple(dec.items_u16(Extension.decode)), dec.opaque_u32())


@dataclass(frozen=True)
class Report(Codec):
    """reference messages/src/lib.rs:1309."""

    metadata: ReportMetadata
    public_share: bytes
    leader_encrypted_input_share: HpkeCiphertext
    helper_encrypted_input_share: HpkeCiphertext

    MEDIA_TYPE = "application/dap-report"

    def encode(self, enc: Encoder) -> None:
        self.metadata.encode(enc)
        enc.opaque_u32(self.public_share)
        self.leader_encrypted_input_share.encode(enc)
        self.helper_encrypted_input_share.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(
            ReportMetadata.decode(dec),
            dec.opaque_u32(),
            HpkeCiphertext.decode(dec),
            HpkeCiphertext.decode(dec),
        )


# ---------------------------------------------------------------------------
# Query types (reference messages/src/lib.rs:1929-2040)
# ---------------------------------------------------------------------------


class TimeInterval:
    """Batch = an aligned time interval. reference messages/src/lib.rs:1993."""

    CODE = 1
    BatchIdentifier = Interval
    name = "time_interval"


class FixedSize:
    """Batch = a leader-assigned BatchId. reference messages/src/lib.rs:2012."""

    CODE = 2
    BatchIdentifier = BatchId
    name = "fixed_size"


QUERY_TYPES = {TimeInterval.CODE: TimeInterval, FixedSize.CODE: FixedSize}


@dataclass(frozen=True)
class FixedSizeQuery(Codec):
    """fixed-size query body: by_batch_id(0) | current_batch(1).

    reference messages/src/lib.rs:1435 (Query enum internals).
    """

    BY_BATCH_ID = 0
    CURRENT_BATCH = 1

    kind: int
    batch_id: BatchId | None = None

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.kind)
        if self.kind == self.BY_BATCH_ID:
            assert self.batch_id is not None
            self.batch_id.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        kind = dec.u8()
        if kind == cls.BY_BATCH_ID:
            return cls(kind, BatchId.decode(dec))
        if kind == cls.CURRENT_BATCH:
            return cls(kind)
        raise DecodeError(f"bad FixedSizeQuery kind {kind}")


@dataclass(frozen=True)
class Query(Codec):
    """reference messages/src/lib.rs:1435."""

    query_type: int
    batch_interval: Interval | None = None
    fixed_size_query: FixedSizeQuery | None = None

    @classmethod
    def time_interval(cls, interval: Interval) -> "Query":
        return cls(TimeInterval.CODE, batch_interval=interval)

    @classmethod
    def fixed_size(cls, fsq: FixedSizeQuery) -> "Query":
        return cls(FixedSize.CODE, fixed_size_query=fsq)

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.query_type)
        if self.query_type == TimeInterval.CODE:
            self.batch_interval.encode(enc)
        elif self.query_type == FixedSize.CODE:
            self.fixed_size_query.encode(enc)
        else:
            raise ValueError(f"bad query type {self.query_type}")

    @classmethod
    def decode(cls, dec: Decoder):
        qt = dec.u8()
        if qt == TimeInterval.CODE:
            return cls(qt, batch_interval=Interval.decode(dec))
        if qt == FixedSize.CODE:
            return cls(qt, fixed_size_query=FixedSizeQuery.decode(dec))
        raise DecodeError(f"bad query type {qt}")


@dataclass(frozen=True)
class CollectionReq(Codec):
    """reference messages/src/lib.rs:1507."""

    query: Query
    aggregation_parameter: bytes = b""

    MEDIA_TYPE = "application/dap-collect-req"

    def encode(self, enc: Encoder) -> None:
        self.query.encode(enc)
        enc.opaque_u32(self.aggregation_parameter)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(Query.decode(dec), dec.opaque_u32())


@dataclass(frozen=True)
class PartialBatchSelector(Codec):
    """reference messages/src/lib.rs:1562."""

    query_type: int
    batch_id: BatchId | None = None

    @classmethod
    def time_interval(cls) -> "PartialBatchSelector":
        return cls(TimeInterval.CODE)

    @classmethod
    def fixed_size(cls, batch_id: BatchId) -> "PartialBatchSelector":
        return cls(FixedSize.CODE, batch_id)

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.query_type)
        if self.query_type == FixedSize.CODE:
            self.batch_id.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        qt = dec.u8()
        if qt == TimeInterval.CODE:
            return cls(qt)
        if qt == FixedSize.CODE:
            return cls(qt, BatchId.decode(dec))
        raise DecodeError(f"bad query type {qt}")


@dataclass(frozen=True)
class BatchSelector(Codec):
    """reference messages/src/lib.rs:2661."""

    query_type: int
    batch_interval: Interval | None = None
    batch_id: BatchId | None = None

    @classmethod
    def time_interval(cls, interval: Interval) -> "BatchSelector":
        return cls(TimeInterval.CODE, batch_interval=interval)

    @classmethod
    def fixed_size(cls, batch_id: BatchId) -> "BatchSelector":
        return cls(FixedSize.CODE, batch_id=batch_id)

    @property
    def batch_identifier(self):
        return self.batch_interval if self.query_type == TimeInterval.CODE else self.batch_id

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.query_type)
        if self.query_type == TimeInterval.CODE:
            self.batch_interval.encode(enc)
        elif self.query_type == FixedSize.CODE:
            self.batch_id.encode(enc)
        else:
            raise ValueError(f"bad query type {self.query_type}")

    @classmethod
    def decode(cls, dec: Decoder):
        qt = dec.u8()
        if qt == TimeInterval.CODE:
            return cls(qt, batch_interval=Interval.decode(dec))
        if qt == FixedSize.CODE:
            return cls(qt, batch_id=BatchId.decode(dec))
        raise DecodeError(f"bad query type {qt}")


@dataclass(frozen=True)
class Collection(Codec):
    """reference messages/src/lib.rs:1685."""

    partial_batch_selector: PartialBatchSelector
    report_count: int
    interval: Interval
    leader_encrypted_agg_share: HpkeCiphertext
    helper_encrypted_agg_share: HpkeCiphertext

    MEDIA_TYPE = "application/dap-collection"

    def encode(self, enc: Encoder) -> None:
        self.partial_batch_selector.encode(enc)
        enc.u64(self.report_count)
        self.interval.encode(enc)
        self.leader_encrypted_agg_share.encode(enc)
        self.helper_encrypted_agg_share.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(
            PartialBatchSelector.decode(dec),
            dec.u64(),
            Interval.decode(dec),
            HpkeCiphertext.decode(dec),
            HpkeCiphertext.decode(dec),
        )


@dataclass(frozen=True)
class InputShareAad(Codec):
    """HPKE AAD for input shares; reference messages/src/lib.rs:1780."""

    task_id: TaskId
    metadata: ReportMetadata
    public_share: bytes

    def encode(self, enc: Encoder) -> None:
        self.task_id.encode(enc)
        self.metadata.encode(enc)
        enc.opaque_u32(self.public_share)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(TaskId.decode(dec), ReportMetadata.decode(dec), dec.opaque_u32())


@dataclass(frozen=True)
class AggregateShareAad(Codec):
    """HPKE AAD for aggregate shares; reference messages/src/lib.rs:1846."""

    task_id: TaskId
    aggregation_parameter: bytes
    batch_selector: BatchSelector

    def encode(self, enc: Encoder) -> None:
        self.task_id.encode(enc)
        enc.opaque_u32(self.aggregation_parameter)
        self.batch_selector.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(TaskId.decode(dec), dec.opaque_u32(), BatchSelector.decode(dec))


@dataclass(frozen=True)
class ReportShare(Codec):
    """reference messages/src/lib.rs:2068."""

    metadata: ReportMetadata
    public_share: bytes
    encrypted_input_share: HpkeCiphertext

    def encode(self, enc: Encoder) -> None:
        self.metadata.encode(enc)
        enc.opaque_u32(self.public_share)
        self.encrypted_input_share.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(ReportMetadata.decode(dec), dec.opaque_u32(), HpkeCiphertext.decode(dec))


@dataclass(frozen=True)
class PrepareInit(Codec):
    """reference messages/src/lib.rs:2139.

    `message` is one self-delimiting ping-pong message, embedded inline
    (no outer length prefix) per DAP-07.
    """

    report_share: ReportShare
    message: bytes  # ping-pong initialize message (leader prep share)

    def __post_init__(self):
        check_pingpong_frame(self.message)

    def encode(self, enc: Encoder) -> None:
        self.report_share.encode(enc)
        enc.write(self.message)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(ReportShare.decode(dec), decode_pingpong_frame(dec))


class PrepareError(enum.IntEnum):
    """reference messages/src/lib.rs:2288."""

    BATCH_COLLECTED = 0
    REPORT_REPLAYED = 1
    REPORT_DROPPED = 2
    HPKE_UNKNOWN_CONFIG_ID = 3
    HPKE_DECRYPT_ERROR = 4
    VDAF_PREP_ERROR = 5
    BATCH_SATURATED = 6
    TASK_EXPIRED = 7
    INVALID_MESSAGE = 8
    REPORT_TOO_EARLY = 9

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.value)

    @classmethod
    def decode(cls, dec: Decoder):
        try:
            return cls(dec.u8())
        except ValueError as e:
            raise DecodeError(str(e))

    def to_bytes(self) -> bytes:  # shadow int.to_bytes for codec symmetry
        return bytes([self.value])

    @classmethod
    def from_bytes(cls, raw: bytes) -> "PrepareError":
        dec = Decoder(raw)
        out = cls.decode(dec)
        dec.finish()
        return out


@dataclass(frozen=True)
class PrepareStepResult(Codec):
    """continue(0) | finished(1) | reject(2); reference messages/src/lib.rs:2235."""

    CONTINUE = 0
    FINISHED = 1
    REJECT = 2

    kind: int
    message: bytes | None = None
    prepare_error: PrepareError | None = None

    def __post_init__(self):
        if self.kind == self.CONTINUE:
            if self.message is None:
                raise DecodeError("continue PrepareStepResult requires a message")
            check_pingpong_frame(self.message)

    @classmethod
    def cont(cls, message: bytes) -> "PrepareStepResult":
        return cls(cls.CONTINUE, message=message)

    @classmethod
    def finished(cls) -> "PrepareStepResult":
        return cls(cls.FINISHED)

    @classmethod
    def reject(cls, err: PrepareError) -> "PrepareStepResult":
        return cls(cls.REJECT, prepare_error=err)

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.kind)
        if self.kind == self.CONTINUE:
            enc.write(self.message)
        elif self.kind == self.REJECT:
            self.prepare_error.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        kind = dec.u8()
        if kind == cls.CONTINUE:
            return cls(kind, message=decode_pingpong_frame(dec))
        if kind == cls.FINISHED:
            return cls(kind)
        if kind == cls.REJECT:
            return cls(kind, prepare_error=PrepareError.decode(dec))
        raise DecodeError(f"bad PrepareStepResult kind {kind}")


@dataclass(frozen=True)
class PrepareResp(Codec):
    """reference messages/src/lib.rs:2189."""

    report_id: ReportId
    result: PrepareStepResult

    def encode(self, enc: Encoder) -> None:
        self.report_id.encode(enc)
        self.result.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(ReportId.decode(dec), PrepareStepResult.decode(dec))


@dataclass(frozen=True)
class PrepareContinue(Codec):
    """reference messages/src/lib.rs:2322."""

    report_id: ReportId
    message: bytes

    def __post_init__(self):
        check_pingpong_frame(self.message)

    def encode(self, enc: Encoder) -> None:
        self.report_id.encode(enc)
        enc.write(self.message)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(ReportId.decode(dec), decode_pingpong_frame(dec))


@dataclass(frozen=True)
class AggregationJobStep(Codec):
    """u16 step counter; reference messages/src/lib.rs:2507."""

    step: int

    def encode(self, enc: Encoder) -> None:
        enc.u16(self.step)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(dec.u16())

    def increment(self) -> "AggregationJobStep":
        return AggregationJobStep(self.step + 1)


@dataclass(frozen=True)
class AggregationJobInitializeReq(Codec):
    """reference messages/src/lib.rs:2432."""

    aggregation_parameter: bytes
    partial_batch_selector: PartialBatchSelector
    prepare_inits: tuple

    MEDIA_TYPE = "application/dap-aggregation-job-init-req"

    def encode(self, enc: Encoder) -> None:
        enc.opaque_u32(self.aggregation_parameter)
        self.partial_batch_selector.encode(enc)
        enc.items_u32(self.prepare_inits)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(
            dec.opaque_u32(),
            PartialBatchSelector.decode(dec),
            tuple(dec.items_u32(PrepareInit.decode)),
        )


@dataclass(frozen=True)
class AggregationJobContinueReq(Codec):
    """reference messages/src/lib.rs:2564."""

    step: AggregationJobStep
    prepare_continues: tuple

    MEDIA_TYPE = "application/dap-aggregation-job-continue-req"

    def encode(self, enc: Encoder) -> None:
        self.step.encode(enc)
        enc.items_u32(self.prepare_continues)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(AggregationJobStep.decode(dec), tuple(dec.items_u32(PrepareContinue.decode)))


@dataclass(frozen=True)
class AggregationJobResp(Codec):
    """reference messages/src/lib.rs:2619."""

    prepare_resps: tuple

    MEDIA_TYPE = "application/dap-aggregation-job-resp"

    def encode(self, enc: Encoder) -> None:
        enc.items_u32(self.prepare_resps)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(tuple(dec.items_u32(PrepareResp.decode)))


# ---------------------------------------------------------------------------
# columnar leader<->helper codec (ISSUE 9): the leader's hot path builds
# whole request bodies from pre-framed rows and parses whole responses
# into parallel columns, bypassing the per-report dataclass/Encoder
# machinery while keeping the wire bytes bit-identical (pinned by the
# codec-equivalence fuzz in tests/test_wire_columnar.py).
# ---------------------------------------------------------------------------


class PreEncoded(Codec):
    """An already-encoded wire item: encode() splices the raw bytes
    verbatim. The columnar leader codecs build whole batches of
    PrepareInit/PrepareContinue bodies in vectorized passes and hand
    them to the existing request containers through this, so the
    container's items_u32 framing — and therefore the request bytes —
    stays bit-identical to the per-item encode path. (A slotted plain
    class, not a dataclass: one is built per report on the hot path.)"""

    __slots__ = ("raw",)

    def __init__(self, raw: bytes):
        self.raw = raw

    def __eq__(self, other):
        return isinstance(other, PreEncoded) and self.raw == other.raw

    def __repr__(self):
        return f"PreEncoded({len(self.raw)}B)"

    def encode(self, enc: Encoder) -> None:
        enc.write(self.raw)


def encode_report_share_raw(
    report_id: bytes, time_seconds: int, public_share: bytes, ciphertext: HpkeCiphertext
) -> bytes:
    """ReportShare.to_bytes without the Encoder/dataclass machinery
    (the leader init hot loop builds one per pending report)."""
    return b"".join(
        (
            report_id,
            struct.pack(">QI", time_seconds, len(public_share)),
            public_share,
            struct.pack(">BH", ciphertext.config_id.id, len(ciphertext.encapsulated_key)),
            ciphertext.encapsulated_key,
            struct.pack(">I", len(ciphertext.payload)),
            ciphertext.payload,
        )
    )


class PrepareRespColumn:
    """An AggregationJobResp body parsed into parallel columns: 16-byte
    report ids, PrepareStepResult kinds, raw ping-pong message frames
    (kind=continue) and PrepareError values (kind=reject) — no
    per-report dataclass construction. Accepts exactly the inputs
    AggregationJobResp.from_bytes accepts and raises DecodeError on
    exactly the inputs it rejects."""

    __slots__ = ("report_ids", "kinds", "messages", "errors")

    def __init__(self, report_ids, kinds, messages, errors):
        self.report_ids: list[bytes] = report_ids
        self.kinds: bytearray = kinds
        self.messages: list[bytes | None] = messages
        self.errors: list[PrepareError | None] = errors

    def __len__(self) -> int:
        return len(self.report_ids)


def decode_prepare_resps_fast(raw: bytes) -> PrepareRespColumn:
    """Columnar AggregationJobResp parse (see PrepareRespColumn)."""
    total = len(raw)
    if total < 4:
        raise DecodeError("unexpected end of input")
    (body_len,) = struct.unpack_from(">I", raw, 0)
    end = 4 + body_len
    if end > total:
        raise DecodeError("unexpected end of input")
    if end != total:
        raise DecodeError(f"{total - end} trailing bytes")
    ids: list[bytes] = []
    kinds = bytearray()
    msgs: list[bytes | None] = []
    errs: list[PrepareError | None] = []
    pos = 4
    while pos < end:
        if end - pos < 17:
            raise DecodeError("unexpected end of input")
        rid = raw[pos : pos + 16]
        kind = raw[pos + 16]
        pos += 17
        msg = None
        err = None
        if kind == PrepareStepResult.CONTINUE:
            # one self-delimiting ping-pong frame, kept raw
            frame_start = pos
            if pos >= end:
                raise DecodeError("unexpected end of input")
            tag = raw[pos]
            pos += 1
            fields = 2 if tag == PP_CONTINUE else 1
            if tag not in (PP_INITIALIZE, PP_CONTINUE, PP_FINISH):
                raise DecodeError(f"bad ping-pong message tag {tag}")
            for _ in range(fields):
                if end - pos < 4:
                    raise DecodeError("unexpected end of input")
                (flen,) = struct.unpack_from(">I", raw, pos)
                pos += 4
                if end - pos < flen:
                    raise DecodeError("unexpected end of input")
                pos += flen
            msg = raw[frame_start:pos]
        elif kind == PrepareStepResult.REJECT:
            if pos >= end:
                raise DecodeError("unexpected end of input")
            try:
                err = PrepareError(raw[pos])
            except ValueError as e:
                raise DecodeError(str(e))
            pos += 1
        elif kind != PrepareStepResult.FINISHED:
            raise DecodeError(f"bad PrepareStepResult kind {kind}")
        ids.append(rid)
        kinds.append(kind)
        msgs.append(msg)
        errs.append(err)
    return PrepareRespColumn(ids, kinds, msgs, errs)


class ReportColumn:
    """A window of upload bodies parsed into parallel columns: 16-byte
    report ids, u64 client times, public shares, and the two HPKE
    ciphertexts decomposed into (config id, encapsulated key, payload)
    columns — no per-report dataclass/Decoder machinery (the upload
    analog of PrepareRespColumn; ISSUE 11). A lane that fails to parse
    carries its DecodeError in `errors` and None in the data columns,
    so one malformed upload rejects its own lane, never its window.
    Accept/reject per lane is identical to `Report.from_bytes`
    (fuzz-pinned by tests/test_ingest_batch.py)."""

    __slots__ = (
        "report_ids",
        "times",
        "public_shares",
        "leader_config_ids",
        "leader_encs",
        "leader_payloads",
        "helper_config_ids",
        "helper_encs",
        "helper_payloads",
        "errors",
    )

    def __init__(self):
        self.report_ids: list[bytes | None] = []
        self.times: list[int | None] = []
        self.public_shares: list[bytes | None] = []
        self.leader_config_ids: list[int | None] = []
        self.leader_encs: list[bytes | None] = []
        self.leader_payloads: list[bytes | None] = []
        self.helper_config_ids: list[int | None] = []
        self.helper_encs: list[bytes | None] = []
        self.helper_payloads: list[bytes | None] = []
        self.errors: list[DecodeError | None] = []

    def __len__(self) -> int:
        return len(self.report_ids)

    def report(self, i: int) -> Report:
        """Realize lane i as the Report dataclass (the single-report
        fallback path and TaskAggregator doubles without the batch
        surface use this; the batched stages never do)."""
        if self.errors[i] is not None:
            raise self.errors[i]
        return Report(
            ReportMetadata(ReportId(self.report_ids[i]), Time(self.times[i])),
            self.public_shares[i],
            HpkeCiphertext(
                HpkeConfigId(self.leader_config_ids[i]),
                self.leader_encs[i],
                self.leader_payloads[i],
            ),
            HpkeCiphertext(
                HpkeConfigId(self.helper_config_ids[i]),
                self.helper_encs[i],
                self.helper_payloads[i],
            ),
        )

    def helper_ciphertext(self, i: int) -> HpkeCiphertext:
        return HpkeCiphertext(
            HpkeConfigId(self.helper_config_ids[i]),
            self.helper_encs[i],
            self.helper_payloads[i],
        )


def _parse_report_fast(raw: bytes):
    """One upload body -> (rid, time, public_share, leader_ct_parts,
    helper_ct_parts); raises DecodeError on exactly the inputs
    Report.from_bytes rejects (truncation anywhere, trailing bytes —
    there are no value-level rejects in the Report layout: any u8
    config id and any u64 time are valid)."""
    total = len(raw)
    if total < 28:  # report_id(16) + time(8) + public-share length(4)
        raise DecodeError("unexpected end of input")
    rid = raw[0:16]
    t, plen = struct.unpack_from(">QI", raw, 16)
    pos = 28
    if total - pos < plen:
        raise DecodeError("unexpected end of input")
    pub = raw[pos : pos + plen]
    pos += plen
    cts = []
    for _ in range(2):
        if total - pos < 3:
            raise DecodeError("unexpected end of input")
        cfg = raw[pos]
        (elen,) = struct.unpack_from(">H", raw, pos + 1)
        pos += 3
        if total - pos < elen:
            raise DecodeError("unexpected end of input")
        enc = raw[pos : pos + elen]
        pos += elen
        if total - pos < 4:
            raise DecodeError("unexpected end of input")
        (paylen,) = struct.unpack_from(">I", raw, pos)
        pos += 4
        if total - pos < paylen:
            raise DecodeError("unexpected end of input")
        pay = raw[pos : pos + paylen]
        pos += paylen
        cts.append((cfg, enc, pay))
    if pos != total:
        raise DecodeError(f"{total - pos} trailing bytes")
    return rid, t, pub, cts[0], cts[1]


def plaintext_input_share_payload_fast(raw: bytes) -> bytes:
    """PlaintextInputShare.from_bytes(raw).payload without the
    Decoder/dataclass machinery, accepting and rejecting exactly the
    same inputs (the extension list's inner structure is still walked —
    a skip-over parser would admit bodies the codec rejects). The
    batched decrypt stage runs this once per opened plaintext."""
    total = len(raw)
    if total < 2:
        raise DecodeError("unexpected end of input")
    (elen,) = struct.unpack_from(">H", raw, 0)
    pos = 2
    ext_end = 2 + elen
    if total < ext_end:
        raise DecodeError("unexpected end of input")
    while pos < ext_end:
        if ext_end - pos < 4:  # u16 type + u16 data length
            raise DecodeError("unexpected end of input")
        (dlen,) = struct.unpack_from(">H", raw, pos + 2)
        pos += 4 + dlen
        if pos > ext_end:
            raise DecodeError("unexpected end of input")
    if total - ext_end < 4:
        raise DecodeError("unexpected end of input")
    (plen,) = struct.unpack_from(">I", raw, ext_end)
    pos = ext_end + 4
    if total - pos < plen:
        raise DecodeError("unexpected end of input")
    if pos + plen != total:
        raise DecodeError(f"{total - pos - plen} trailing bytes")
    return raw[pos : pos + plen]


def decode_reports_fast(bodies) -> ReportColumn:
    """Columnar upload-window decode (see ReportColumn)."""
    col = ReportColumn()
    for raw in bodies:
        try:
            rid, t, pub, lct, hct = _parse_report_fast(raw)
        except DecodeError as e:
            col.report_ids.append(None)
            col.times.append(None)
            col.public_shares.append(None)
            col.leader_config_ids.append(None)
            col.leader_encs.append(None)
            col.leader_payloads.append(None)
            col.helper_config_ids.append(None)
            col.helper_encs.append(None)
            col.helper_payloads.append(None)
            col.errors.append(e)
            continue
        col.report_ids.append(rid)
        col.times.append(t)
        col.public_shares.append(pub)
        col.leader_config_ids.append(lct[0])
        col.leader_encs.append(lct[1])
        col.leader_payloads.append(lct[2])
        col.helper_config_ids.append(hct[0])
        col.helper_encs.append(hct[1])
        col.helper_payloads.append(hct[2])
        col.errors.append(None)
    return col


@dataclass(frozen=True)
class AggregateShareReq(Codec):
    """reference messages/src/lib.rs:2733."""

    batch_selector: BatchSelector
    aggregation_parameter: bytes
    report_count: int
    checksum: ReportIdChecksum

    MEDIA_TYPE = "application/dap-aggregate-share-req"

    def encode(self, enc: Encoder) -> None:
        self.batch_selector.encode(enc)
        enc.opaque_u32(self.aggregation_parameter)
        enc.u64(self.report_count)
        self.checksum.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(
            BatchSelector.decode(dec),
            dec.opaque_u32(),
            dec.u64(),
            ReportIdChecksum.decode(dec),
        )


@dataclass(frozen=True)
class AggregateShare(Codec):
    """reference messages/src/lib.rs:2819."""

    encrypted_aggregate_share: HpkeCiphertext

    MEDIA_TYPE = "application/dap-aggregate-share"

    def encode(self, enc: Encoder) -> None:
        self.encrypted_aggregate_share.encode(enc)

    @classmethod
    def decode(cls, dec: Decoder):
        return cls(HpkeCiphertext.decode(dec))
