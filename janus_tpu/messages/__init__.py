"""DAP-07 wire messages with TLS-syntax encoding.

Python equivalent of the reference's `messages` crate
(messages/src/lib.rs:58-2850): every DAP struct with byte-exact
TLS-syntax Encode/Decode, the TimeInterval/FixedSize query-type
abstraction (messages/src/lib.rs:1929-2040), and the DAP problem-type
registry (messages/src/problem_type.rs:5-47).

The hot path never touches these Python codecs per report — report
batches are decoded column-wise into arrays by the aggregator layer —
but protocol conformance (byte-exact round-trips) is defined here and
locked by tests/test_messages.py.
"""

from .codec import Decoder, Encoder, DecodeError
from .core import (
    AggregateShare,
    AggregateShareAad,
    AggregateShareReq,
    AggregationJobContinueReq,
    AggregationJobId,
    AggregationJobInitializeReq,
    AggregationJobResp,
    AggregationJobStep,
    BatchId,
    BatchSelector,
    Collection,
    CollectionJobId,
    CollectionReq,
    Duration,
    Extension,
    ExtensionType,
    FixedSize,
    FixedSizeQuery,
    HpkeAeadId,
    HpkeCiphertext,
    HpkeConfig,
    HpkeConfigId,
    HpkeConfigList,
    HpkeKdfId,
    HpkeKemId,
    InputShareAad,
    Interval,
    PartialBatchSelector,
    PlaintextInputShare,
    PreEncoded,
    PrepareContinue,
    PrepareError,
    PrepareInit,
    PrepareResp,
    PrepareRespColumn,
    PrepareStepResult,
    Query,
    Report,
    ReportColumn,
    ReportId,
    ReportIdChecksum,
    ReportMetadata,
    ReportShare,
    Role,
    TaskId,
    Time,
    TimeInterval,
    QUERY_TYPES,
    decode_prepare_resps_fast,
    decode_reports_fast,
    encode_report_share_raw,
    plaintext_input_share_payload_fast,
)
from .problem_type import DapProblemType

__all__ = [n for n in dir() if not n.startswith("_")]
