"""DAP problem-details types (urn:ietf:params:ppm:dap:error:*).

Equivalent of reference messages/src/problem_type.rs:5-47 — the 15
RFC 7807 problem types DAP defines, plus helpers to build a
problem-details JSON document.
"""

from __future__ import annotations

import enum

_PREFIX = "urn:ietf:params:ppm:dap:error:"


class DapProblemType(enum.Enum):
    INVALID_MESSAGE = "invalidMessage"
    UNRECOGNIZED_TASK = "unrecognizedTask"
    MISSING_TASK_ID = "missingTaskID"
    UNRECOGNIZED_AGGREGATION_JOB = "unrecognizedAggregationJob"
    OUTDATED_CONFIG = "outdatedConfig"
    REPORT_REJECTED = "reportRejected"
    REPORT_TOO_EARLY = "reportTooEarly"
    BATCH_INVALID = "batchInvalid"
    INVALID_BATCH_SIZE = "invalidBatchSize"
    BATCH_QUERY_COUNT_EXCEEDED = "batchQueryCountExceeded"
    BATCH_MISMATCH = "batchMismatch"
    UNAUTHORIZED_REQUEST = "unauthorizedRequest"
    BATCH_OVERLAP = "batchOverlap"
    STEP_MISMATCH = "stepMismatch"
    UNRECOGNIZED_COLLECTION_JOB = "unrecognizedCollectionJob"
    INVALID_TASK = "invalidTask"  # taskprov opt-out

    @property
    def type_uri(self) -> str:
        return _PREFIX + self.value

    @classmethod
    def from_uri(cls, uri: str) -> "DapProblemType":
        if not uri.startswith(_PREFIX):
            raise ValueError(f"not a DAP problem type: {uri}")
        return cls(uri[len(_PREFIX) :])

    def http_status(self) -> int:
        return 400

    def document(self, task_id: str | None = None, detail: str | None = None) -> dict:
        """RFC 7807 problem-details body as the reference emits
        (aggregator/src/aggregator/problem_details.rs)."""
        doc = {
            "type": self.type_uri,
            "title": self.value,
            "status": self.http_status(),
        }
        if task_id is not None:
            doc["taskid"] = task_id
        if detail is not None:
            doc["detail"] = detail
        return doc
