"""Report-flow conservation ledger (docs/OBSERVABILITY.md
"Conservation accounting").

Every metric family this repo exports counts *events*; none of them
*balance*. This module treats the report pipeline as a balanced
accounting equation over datastore-backed per-task lifecycle counters
(the task_counters table): every admitted report must end in exactly
one terminal state — aggregated, rejected{reason}, expired — or be
attributably in-flight (unclaimed client_reports, a live job's
report_aggregations, aggregated mass awaiting collection). The books
close per (task, stage):

    stage="ingest":  admitted - aggregated - rejected - expired
                     - pending_reports - pending_aggregation  == 0
    stage="param":   admitted_param - aggregated_param - rejected_param
                     - expired_param - pending_aggregation_param == 0
    stage="collect": aggregated + aggregated_param - collected
                     - awaiting_collection == 0

A sustained positive residual is a silently lost report; a sustained
negative one is a double-count (e.g. a replayed job step whose
counters were incremented outside its transaction). Counter updates
therefore always ride INSIDE the transaction of the state change they
count — run_tx retries re-run the whole closure, so in-tx increments
are exactly-once where in-process counters double-count, and a fleet
of driver binaries over one datastore shares one consistent set of
books.

The evaluator runs at health-sampler cadence, exports
janus_ledger_imbalance{task_id,stage} plus janus_ledger_breach_active
once a residual stays nonzero past the grace window (transient
read-snapshot skew between the counter read and the in-flight read —
e.g. a report admitted between the two statements under Postgres
read-committed — self-clears within a tick), and feeds the
`conservation` SLO signal kind (slo.py). Cross-aggregator
reconciliation (the collection driver fetching the helper's per-batch
aggregated counts) reports through record_peer_divergence and pages
through the same breach gauge with stage="peer".

Resident-share loss (engine_resident_flushes_total{outcome="lost"}) is
a SHARE-mass loss, not a count loss: the counts were durable at each
job's commit, so the count books above still close — which is exactly
why it gets its own `lost` counter + builtin SLO (resident_lost)
instead of a seat in the count equation.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from . import metrics
from .metrics import task_id_label

log = logging.getLogger(__name__)

# Counter-name taxonomy (task_counters.counter_name). Rejections are
# per-reason: "rejected:<prepare error name>".
ADMITTED = "admitted"
AGGREGATED = "aggregated"
COLLECTED = "collected"
EXPIRED = "expired"
EXPIRED_RECLAIMED = "expired_reclaimed"
LOST = "lost"
REJECTED_PREFIX = "rejected:"

# Parameter-fanout lane (VDAFs with nontrivial aggregation parameters,
# e.g. Poplar1): one admitted report legitimately aggregates once PER
# collection parameter, so booking those FINISHED rows as `aggregated`
# would debit a single `admitted` several times and drive the ingest
# residual permanently negative. The fanout keeps its own books —
# admission is the creation of the (report, param) report_aggregations
# row (leader: _ensure_param_aggregation; helper: the init handler) and
# every such admission must reach exactly one param-lane terminal:
#
#   stage="param": admitted_param - aggregated_param
#                  - Σ rejected_param:<reason> - expired_param
#                  - pending_aggregation_param               == 0
#
# The canonical ingest equation never sees the fanout (a param task's
# client_reports stay in pending_reports until GC expiry), while the
# collect equation uses aggregated + aggregated_param: batch
# aggregation rows carry the param mass, and collections drain it.
ADMITTED_PARAM = "admitted_param"
AGGREGATED_PARAM = "aggregated_param"
EXPIRED_PARAM = "expired_param"
REJECTED_PARAM_PREFIX = "rejected_param:"


@dataclass
class LedgerConfig:
    """The YAML `ledger:` stanza (CommonConfig). `grace_s` is how long
    a nonzero residual must persist before it counts as a breach
    (feeds janus_ledger_breach_active and the conservation SLO);
    `reconcile_peer` turns the leader collection driver's
    fetch-the-helper's-counts pass on/off."""

    enabled: bool = True
    grace_s: float = 120.0
    reconcile_peer: bool = True

    @classmethod
    def from_dict(cls, d: dict | None) -> "LedgerConfig":
        d = d or {}
        return cls(
            enabled=bool(d.get("enabled", True)),
            grace_s=float(d.get("grace_secs", d.get("grace_s", 120.0))),
            reconcile_peer=bool(d.get("reconcile_peer", True)),
        )


# ---------------------------------------------------------------------------
# Transaction-side counting helpers — the choke points call these INSIDE
# the write transaction of the state change being counted.
# ---------------------------------------------------------------------------


def count_admitted(tx, task_id, n: int, aggregation_parameter: bytes = b"") -> None:
    """A report became durable (fresh put, not a replay): leader
    report_writer flush / journal replay, or the helper's init handler
    writing the job's report_aggregations rows (the helper has no
    client_reports — the RA rows ARE its admission record). A non-empty
    aggregation parameter books into the param-fanout lane instead
    (one admission per (report, param))."""
    if n > 0:
        key = ADMITTED_PARAM if aggregation_parameter else ADMITTED
        tx.increment_task_counters(task_id, {key: n})


def count_ra_outcomes(
    tx, task_id, ras, unmerged=frozenset(), aggregation_parameter: bytes = b""
) -> None:
    """Book the terminal outcomes of a report_aggregations write batch:
    FINISHED rows whose share merged are `aggregated`, FINISHED rows in
    the flush's unmergeable set are rejected:batch_collected (the
    caller rewrites the row the same way), FAILED rows are
    rejected:<reason>. Non-terminal (waiting) rows stay in-flight and
    are not booked. Rows of a job with a non-empty aggregation
    parameter book into the param-fanout lane (`aggregated_param` /
    `rejected_param:<reason>`): a report FINISHES once per parameter,
    so those terminals must never debit the single `admitted`."""
    from .datastore.models import ReportAggregationState

    agg_key = AGGREGATED_PARAM if aggregation_parameter else AGGREGATED
    rej_prefix = REJECTED_PARAM_PREFIX if aggregation_parameter else REJECTED_PREFIX
    deltas: dict[str, int] = {}
    for ra in ras:
        if ra.state == ReportAggregationState.FINISHED:
            if ra.report_id.data in unmerged:
                key = rej_prefix + "batch_collected"
            else:
                key = agg_key
        elif ra.state == ReportAggregationState.FAILED:
            err = getattr(ra, "prepare_error", None)
            name = err.name.lower() if err is not None else "unknown"
            key = rej_prefix + name
        else:
            continue
        deltas[key] = deltas.get(key, 0) + 1
    if deltas:
        tx.increment_task_counters(task_id, deltas)


def count_collected(tx, task_id, rows) -> None:
    """Book the aggregated mass a collection is about to mark collected
    — only rows still uncollected at gather time, so a re-query of the
    same batch (max_batch_query_count > 1) books nothing twice."""
    from .datastore.models import BatchAggregationState

    n = sum(
        int(row.report_count)
        for row in rows
        if row.state != BatchAggregationState.COLLECTED
    )
    if n > 0:
        tx.increment_task_counters(task_id, {COLLECTED: n})


def count_lost(ds, task_id, n: int) -> None:
    """Book resident-share loss. Best-effort OWN transaction: two of
    the three loss paths are failure paths where the original
    transaction is gone (tx failure, delta-fetch failure), so this
    cannot ride a state-change tx; if the datastore is down too, the
    loss still reaches the in-process lost metric + ERROR log."""
    if n <= 0:
        return
    try:
        ds.run_tx(
            lambda tx: tx.increment_task_counters(task_id, {LOST: n}),
            "ledger_count_lost",
        )
    except Exception:
        log.warning(
            "could not book %d lost resident share(s) for task %s in the "
            "ledger; the in-process metric still carries the loss",
            n,
            task_id,
        )


# ---------------------------------------------------------------------------
# Evaluator
# ---------------------------------------------------------------------------


@dataclass
class _BreachTrack:
    first_nonzero: float | None = None
    value: float = 0.0


class LedgerEvaluator:
    """Periodic balance evaluation over one datastore. `evaluate_once()`
    runs at health-sampler cadence (HealthSampler calls it when a
    ledger is installed); the latest balance document is held for the
    `ledger` statusz section and GET /debug/ledger — readers get the
    last COMPLETE document under a lock, never a torn mid-evaluation
    view, and a datastore error keeps the previous document."""

    def __init__(self, ds, cfg: LedgerConfig | None = None):
        self.ds = ds
        self.cfg = cfg or LedgerConfig()
        self._lock = threading.Lock()
        # complete shape from birth: a scrape racing the first sampler
        # tick still sees every documented key (scrape_check pins them)
        self._doc: dict = {
            "enabled": True,
            "evaluations": 0,
            "tasks": {},
            "breaches": [],
        }
        self._evaluations = 0
        # (task label, stage) -> breach tracking state
        self._tracks: dict[tuple[str, str], _BreachTrack] = {}
        # task label -> latest peer reconciliation result
        self._peer: dict[str, dict] = {}

    # -- feed: cross-aggregator reconciliation (collection driver) -----
    def record_peer_divergence(
        self, task_id, ours: dict[str, int], theirs: dict[str, int]
    ) -> int:
        """Compare our aggregated counts against the helper's, keyed by
        (batch identifier, aggregation parameter) — per-param keys keep
        a multi-parameter task's fanout from inflating one batch's
        count — and restricted to the keys WE cover (the helper may not
        have created rows for a batch still aggregating on its side).
        Returns the total absolute divergence and exports it."""
        label = task_id_label(task_id.data)
        divergence = 0
        detail = {}
        for bid, n in ours.items():
            peer_n = int(theirs.get(bid, 0))
            if peer_n != n:
                divergence += abs(n - peer_n)
                detail[bid] = {"ours": n, "helper": peer_n}
        rl = metrics.replica_labels()
        metrics.ledger_peer_divergence.set(float(divergence), task_id=label, **rl)
        with self._lock:
            self._peer[label] = {
                "divergence": divergence,
                "batches_compared": len(ours),
                "mismatched": detail,
                "at_unix": time.time(),
            }
        self._breach_update(label, "peer", float(divergence), time.monotonic())
        return divergence

    # -- evaluation ----------------------------------------------------
    def evaluate_once(self) -> dict:
        try:
            doc = self._evaluate()
        except Exception:
            metrics.ledger_evaluations_total.add(outcome="error")
            log.exception("ledger evaluation failed; keeping previous balance")
            with self._lock:
                return dict(self._doc)
        metrics.ledger_evaluations_total.add(outcome="ok")
        with self._lock:
            self._doc = doc
            return dict(doc)

    def _evaluate(self) -> dict:
        def read(tx):
            return tx.get_all_task_counters(), tx.ledger_inflight_by_task()

        counters, inflight = self.ds.run_tx(read, "ledger_snapshot")
        now_mono = time.monotonic()
        rl = metrics.replica_labels()
        self._evaluations += 1
        with self._lock:
            peer_snapshot = dict(self._peer)
        tasks_doc: dict[str, dict] = {}
        for task_id_bytes in sorted(set(counters) | set(inflight)):
            c = counters.get(task_id_bytes, {})
            f = inflight.get(task_id_bytes, {})
            label = task_id_label(task_id_bytes)
            admitted = c.get(ADMITTED, 0)
            aggregated = c.get(AGGREGATED, 0)
            collected = c.get(COLLECTED, 0)
            expired = c.get(EXPIRED, 0)
            lost = c.get(LOST, 0)
            rejected = {
                k[len(REJECTED_PREFIX):]: v
                for k, v in c.items()
                if k.startswith(REJECTED_PREFIX)
            }
            rejected_total = sum(rejected.values())
            admitted_param = c.get(ADMITTED_PARAM, 0)
            aggregated_param = c.get(AGGREGATED_PARAM, 0)
            expired_param = c.get(EXPIRED_PARAM, 0)
            rejected_param = {
                k[len(REJECTED_PARAM_PREFIX):]: v
                for k, v in c.items()
                if k.startswith(REJECTED_PARAM_PREFIX)
            }
            pending_reports = f.get("pending_reports", 0)
            pending_aggregation = f.get("pending_aggregation", 0)
            pending_aggregation_param = f.get("pending_aggregation_param", 0)
            awaiting_collection = f.get("awaiting_collection", 0)

            ingest = (
                admitted
                - aggregated
                - rejected_total
                - expired
                - pending_reports
                - pending_aggregation
            )
            param = (
                admitted_param
                - aggregated_param
                - sum(rejected_param.values())
                - expired_param
                - pending_aggregation_param
            )
            # collect balances COUNT mass through batch_aggregations,
            # which carries both lanes (param tasks' shards are keyed by
            # their aggregation parameter but drain through the same
            # collected/awaiting accounting)
            collect = aggregated + aggregated_param - collected - awaiting_collection
            metrics.ledger_imbalance.set(float(ingest), task_id=label, stage="ingest", **rl)
            metrics.ledger_imbalance.set(float(param), task_id=label, stage="param", **rl)
            metrics.ledger_imbalance.set(float(collect), task_id=label, stage="collect", **rl)
            self._breach_update(label, "ingest", float(ingest), now_mono)
            self._breach_update(label, "param", float(param), now_mono)
            self._breach_update(label, "collect", float(collect), now_mono)

            tasks_doc[label] = {
                "admitted": admitted,
                "aggregated": aggregated,
                "rejected": rejected,
                "expired": expired,
                "expired_reclaimed": c.get(EXPIRED_RECLAIMED, 0),
                "lost": lost,
                "collected": collected,
                "param": {
                    "admitted": admitted_param,
                    "aggregated": aggregated_param,
                    "rejected": rejected_param,
                    "expired": expired_param,
                },
                "in_flight": {
                    "pending_reports": pending_reports,
                    "pending_aggregation": pending_aggregation,
                    "pending_aggregation_param": pending_aggregation_param,
                    "awaiting_collection": awaiting_collection,
                },
                "imbalance": {"ingest": ingest, "param": param, "collect": collect},
                "peer": peer_snapshot.get(label),
            }

        # peer tracks only gain fresh values when a collection finishes
        # (record_peer_divergence); re-evaluating them here keeps the
        # breach gauge and the breach list advancing every sampler tick
        # even when no collection runs during the grace window.
        with self._lock:
            peer_tracks = [
                (label, tr.value)
                for (label, stage), tr in self._tracks.items()
                if stage == "peer"
            ]
        for label, value in peer_tracks:
            self._breach_update(label, "peer", value, now_mono)

        with self._lock:
            breaches = sorted(
                f"{label}/{stage}"
                for (label, stage), tr in self._tracks.items()
                if self._breached(tr, now_mono)
            )
        return {
            "enabled": True,
            "evaluations": self._evaluations,
            "grace_s": self.cfg.grace_s,
            "evaluated_at_unix": time.time(),
            "tasks": tasks_doc,
            "breaches": breaches,
        }

    # -- breach tracking -----------------------------------------------
    def _breach_update(self, label: str, stage: str, value: float, now_mono: float) -> None:
        # _tracks is shared between the sampler thread (_evaluate) and
        # collection-driver threads (record_peer_divergence): mutate it
        # only under the lock, and do the metric/log I/O outside it.
        with self._lock:
            tr = self._tracks.setdefault((label, stage), _BreachTrack())
            tr.value = value
            if value == 0:
                tr.first_nonzero = None
            elif tr.first_nonzero is None:
                tr.first_nonzero = now_mono
            breached = self._breached(tr, now_mono)
        metrics.ledger_breach_active.set(
            1.0 if breached else 0.0,
            task_id=label,
            stage=stage,
            **metrics.replica_labels(),
        )
        if breached:
            log.error(
                "conservation breach: task %s stage %s residual %g nonzero "
                "for more than the %gs grace window",
                label,
                stage,
                value,
                self.cfg.grace_s,
            )

    def _breached(self, tr: _BreachTrack, now_mono: float) -> bool:
        return (
            tr.first_nonzero is not None
            and (now_mono - tr.first_nonzero) >= self.cfg.grace_s
        )

    # -- surfaces ------------------------------------------------------
    def document(self) -> dict:
        """The latest complete balance document (GET /debug/ledger).
        Lock-protected copy: a concurrent evaluation never hands a
        reader a torn half-written table."""
        with self._lock:
            return dict(self._doc)

    def status(self) -> dict:
        """The `ledger` statusz section: the balance table, compressed
        to what an operator scans first."""
        with self._lock:
            doc = dict(self._doc)
        return {
            "enabled": True,
            "evaluations": doc.get("evaluations", 0),
            "grace_s": self.cfg.grace_s,
            "breaches": doc.get("breaches", []),
            "imbalance": {
                label: t.get("imbalance")
                for label, t in (doc.get("tasks") or {}).items()
            },
        }


# ---------------------------------------------------------------------------
# Process-ambient install (mirrors flight_recorder: the binary that owns
# the datastore installs one evaluator; the health listener's
# /debug/ledger route and the statusz section read it ambiently).
# ---------------------------------------------------------------------------

_installed: LedgerEvaluator | None = None


def install_ledger(ds, cfg: LedgerConfig | None = None) -> LedgerEvaluator | None:
    """Create + register the process's ledger evaluator (None when the
    config disables it). Registers the `ledger` statusz section."""
    global _installed
    cfg = cfg or LedgerConfig()
    if not cfg.enabled:
        _installed = None
        return None
    ev = LedgerEvaluator(ds, cfg)
    _installed = ev
    from .statusz import register_status_provider

    register_status_provider("ledger", ev.status)
    return ev


def uninstall_ledger() -> None:
    global _installed
    ev, _installed = _installed, None
    if ev is not None:
        from .statusz import unregister_status_provider

        unregister_status_provider("ledger", ev.status)


def installed_ledger() -> LedgerEvaluator | None:
    return _installed


def ledger_document() -> dict:
    """GET /debug/ledger payload for this process."""
    ev = _installed
    if ev is None:
        return {"enabled": False}
    return ev.document()
