"""In-process SLO burn-rate engine (`/alertz`).

The paper's production posture — two independently-operated
aggregators serving millions of clients — means an operator must be
able to answer "are we meeting our objectives, and which request blew
the budget?" WITHOUT standing up an external Prometheus first. This
module evaluates multi-window multi-burn-rate alerts (the Google SRE
Workbook method: a fast 14.4x/1h rung that pages and a slow 6x/6h rung
that tickets) directly over the in-process metrics registry:

  - `SloDefinition`: objective + signal + burn-rate ladder. Signals
    read the registry's own series — a counter good/bad ratio
    (upload availability), a latency histogram threshold (the
    janus_report_e2e_seconds stages), or a condition set over gauges/
    counter deltas (datastore-up, device health).
  - `SloEngine`: a low-cadence thread snapshots each signal's
    cumulative (bad, total) every tick into a bounded sliding window,
    computes burn rates per configured window, drives alert state
    (firing-since, recovery), and exports
    `janus_alert_active{alert,severity}`,
    `janus_slo_error_budget_remaining_ratio{slo}` and
    `janus_slo_burn_rate{slo,window}`.
  - `GET /alertz` (binary_utils.HealthServer) serves the full state:
    per-alert burn rates vs thresholds, budget remaining,
    firing-since, and the evidence series behind the numbers.

Definitions are configurable via the YAML `slo:` stanza
(docs/samples/*.yaml) with BUILTIN_SLOS as defaults;
`python -m janus_tpu.tools.gen_alert_rules` renders the same
definitions as a Prometheus rule file (docs/alerts/janus-alerts.yaml)
for deployments that DO run an external stack, so the two can never
drift.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field

from . import metrics
from .metrics import REGISTRY, compile_matchers

log = logging.getLogger(__name__)

# The SRE Workbook's recommended ladder (table 5-2), expressed as
# (long window, short window, burn-rate threshold, severity): the fast
# rung catches an outage in minutes, the slow rung catches a trickle
# that would quietly exhaust a 30d budget in days.
DEFAULT_LADDER = (
    {"long_secs": 3600.0, "short_secs": 300.0, "burn_rate": 14.4, "severity": "page"},
    {"long_secs": 21600.0, "short_secs": 1800.0, "burn_rate": 6.0, "severity": "ticket"},
)


def format_window(seconds: float) -> str:
    """Human window label for the janus_slo_burn_rate series ("1h",
    "5m", "90s") — stable across config round-trips."""
    seconds = float(seconds)
    if seconds >= 3600 and seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds >= 60 and seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{seconds:g}s"


# ---------------------------------------------------------------------------
# Signals: each reads cumulative (bad, total) event counts from the
# live registry. `read(engine)` returns (bad, total, has_data);
# has_data=False (no matching series yet) freezes the window instead of
# recording a fake all-good sample.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Selector:
    """One registry series selection: metric name + label matchers
    (exact / "~regex" / list-of-alternatives, metrics.compile_matchers)."""

    metric: str
    labels: tuple = ()  # compiled matcher tuple

    @classmethod
    def from_dict(cls, d: dict) -> "Selector":
        return cls(metric=str(d["metric"]), labels=compile_matchers(d.get("labels")))

    def read(self) -> tuple[float, int]:
        m = REGISTRY.get(self.metric)
        if m is None or not hasattr(m, "sum_matching"):
            return 0.0, 0
        return m.sum_matching(self.labels)

    def describe(self) -> str:
        if not self.labels:
            return self.metric
        inner = []
        for name, kind, want in self.labels:
            if kind == "eq":
                inner.append(f'{name}="{want}"')
            elif kind == "re":
                inner.append(f'{name}=~"{want.pattern}"')
            else:
                inner.append(f'{name}=~"{"|".join(sorted(want))}"')
        return self.metric + "{" + ",".join(inner) + "}"


@dataclass(frozen=True)
class RatioSignal:
    """Availability ratio over counters: bad/(good+bad). Several
    selectors may feed each side (e.g. 5xx statuses + shed counter)."""

    kind = "counter_ratio"
    good: tuple[Selector, ...]
    bad: tuple[Selector, ...]

    @classmethod
    def from_dict(cls, d: dict) -> "RatioSignal":
        def sels(raw):
            raw = raw if isinstance(raw, (list, tuple)) else [raw]
            return tuple(Selector.from_dict(s) for s in raw)

        return cls(good=sels(d["good"]), bad=sels(d["bad"]))

    def read(self, engine) -> tuple[float, float, bool]:
        good = bad = 0.0
        matched = 0
        for s in self.good:
            v, n = s.read()
            good += v
            matched += n
        for s in self.bad:
            v, n = s.read()
            bad += v
            matched += n
        return bad, good + bad, matched > 0

    def evidence(self) -> dict:
        out = {}
        for side, sels in (("good", self.good), ("bad", self.bad)):
            for s in sels:
                v, n = s.read()
                out[f"{side}:{s.describe()}"] = v if n else None
        return out


@dataclass(frozen=True)
class LatencySignal:
    """Latency objective over a registry histogram: an observation is
    good when <= threshold_s (rounded UP to the histogram's nearest
    bucket bound, recorded as effective_threshold_s)."""

    kind = "histogram_latency"
    metric: str
    labels: tuple
    threshold_s: float

    @classmethod
    def from_dict(cls, d: dict) -> "LatencySignal":
        return cls(
            metric=str(d["metric"]),
            labels=compile_matchers(d.get("labels")),
            threshold_s=float(d["threshold_s"]),
        )

    def _histogram(self):
        m = REGISTRY.get(self.metric)
        return m if isinstance(m, metrics.Histogram) else None

    def effective_threshold_s(self) -> float:
        h = self._histogram()
        return h.nearest_bucket_le(self.threshold_s) if h else self.threshold_s

    def read(self, engine) -> tuple[float, float, bool]:
        h = self._histogram()
        if h is None:
            return 0.0, 0.0, False
        good, total, n = h.le_total_matching(
            h.nearest_bucket_le(self.threshold_s), self.labels
        )
        return total - good, total, n > 0

    def evidence(self) -> dict:
        h = self._histogram()
        desc = Selector(self.metric, self.labels).describe()
        if h is None:
            return {desc: None}
        good, total, n = h.le_total_matching(
            h.nearest_bucket_le(self.threshold_s), self.labels
        )
        return {
            f"{desc} observations": total if n else None,
            f"{desc} over {self.effective_threshold_s():g}s": (total - good) if n else None,
        }


@dataclass(frozen=True)
class Condition:
    """One boolean sub-condition of a ConditionSignal. mode="value"
    compares the matched series' sum against `value` with `op`;
    mode="delta" compares the sum's increase since the previous tick
    (counters: "any hung dispatch since last look is a bad tick")."""

    selector: Selector
    op: str = ">"  # > < >= <= == !=
    value: float = 0.0
    mode: str = "value"  # value | delta

    _OPS = {
        ">": lambda a, b: a > b,
        "<": lambda a, b: a < b,
        ">=": lambda a, b: a >= b,
        "<=": lambda a, b: a <= b,
        "==": lambda a, b: a == b,
        "!=": lambda a, b: a != b,
    }

    @classmethod
    def from_dict(cls, d: dict) -> "Condition":
        op = str(d.get("op", ">"))
        if op not in cls._OPS:
            raise ValueError(f"unknown condition op {op!r}")
        mode = str(d.get("mode", "value"))
        if mode not in ("value", "delta"):
            # a typo ('deltas') would silently degrade to cumulative
            # semantics and latch the SLO bad forever after one event
            raise ValueError(f"unknown condition mode {mode!r} (want value|delta)")
        return cls(
            selector=Selector.from_dict(d),
            op=op,
            value=float(d.get("value", 0.0)),
            mode=mode,
        )

    def describe(self) -> str:
        base = self.selector.describe()
        if self.mode == "delta":
            return f"increase({base}) {self.op} {self.value:g}"
        return f"{base} {self.op} {self.value:g}"


@dataclass(frozen=True)
class ConditionSignal:
    """Time-based SLO: every evaluation tick is one event, bad when ANY
    condition holds. The burn rate is then the fraction of recent time
    the system was in the bad state, over the error budget."""

    kind = "condition"
    conditions: tuple[Condition, ...]

    @classmethod
    def from_dict(cls, d: dict) -> "ConditionSignal":
        raw = d["conditions"]
        return cls(conditions=tuple(Condition.from_dict(c) for c in raw))

    def read(self, engine) -> tuple[float, float, bool]:
        """Engine-side state: cumulative bad/total tick counts and the
        per-condition previous sums for delta mode live in
        engine._condition_state[id(self)]."""
        st = engine._condition_state.setdefault(
            id(self), {"bad": 0.0, "total": 0.0, "prev": {}}
        )
        any_bad = False
        any_data = False
        for i, cond in enumerate(self.conditions):
            v, n = cond.selector.read()
            if cond.mode == "delta":
                prev = st["prev"].get(i)
                st["prev"][i] = v
                if prev is None:
                    continue  # first sight: no delta yet
                any_data = True
                if Condition._OPS[cond.op](v - prev, cond.value):
                    any_bad = True
            else:
                if n == 0:
                    continue  # series not born yet: unknown, not good
                any_data = True
                if Condition._OPS[cond.op](v, cond.value):
                    any_bad = True
        if any_data:
            st["total"] += 1.0
            if any_bad:
                st["bad"] += 1.0
        return st["bad"], st["total"], any_data

    def evidence(self) -> dict:
        out = {}
        for cond in self.conditions:
            v, n = cond.selector.read()
            out[cond.describe()] = v if n else None
        return out


@dataclass(frozen=True)
class TrendSignal:
    """Time-based SLO over the flight recorder's leak verdicts
    (docs/OBSERVABILITY.md "Flight recorder and trend alerts"): every
    evaluation tick is one event, bad while any matched series of
    `metric` (default janus_flight_leak_active, 1 while a leak-gated
    series shows a sustained positive trend) is above zero. A slow
    resource leak is invisible to every point-in-time signal — this is
    how it pages through the same burn-rate ladder. The verdict gauges
    are only born once the recorder's first analysis pass runs, so a
    process without a recorder reports no_data rather than fake
    health."""

    kind = "trend"
    metric: str = "janus_flight_leak_active"
    labels: tuple = ()

    @classmethod
    def from_dict(cls, d: dict) -> "TrendSignal":
        return cls(
            metric=str(d.get("metric", "janus_flight_leak_active")),
            labels=compile_matchers(d.get("labels")),
        )

    def _read_raw(self) -> tuple[float, int]:
        m = REGISTRY.get(self.metric)
        if m is None or not hasattr(m, "sum_matching"):
            return 0.0, 0
        return m.sum_matching(self.labels)

    def read(self, engine) -> tuple[float, float, bool]:
        st = engine._condition_state.setdefault(
            id(self), {"bad": 0.0, "total": 0.0, "prev": {}}
        )
        v, n = self._read_raw()
        if n == 0:
            return st["bad"], st["total"], st["total"] > 0
        st["total"] += 1.0
        if v > 0:
            st["bad"] += 1.0
        return st["bad"], st["total"], True

    def evidence(self) -> dict:
        desc = Selector(self.metric, self.labels).describe()
        v, n = self._read_raw()
        out = {f"{desc} leaking series": v if n else None}
        leak, slopes = REGISTRY.get(self.metric), REGISTRY.get("janus_flight_slope")
        if v > 0 and hasattr(leak, "_values") and hasattr(slopes, "_values"):
            with leak._lock:
                leak_vals = dict(leak._values)
            with slopes._lock:
                slope_vals = dict(slopes._values)
            for key, active in sorted(leak_vals.items()):
                if active > 0:
                    out[f"slope{dict(key)}"] = slope_vals.get(key)
        return out


@dataclass(frozen=True)
class ConservationSignal:
    """Time-based SLO over the report-flow conservation ledger
    (janus_tpu/ledger.py; docs/OBSERVABILITY.md "Conservation
    accounting"): every evaluation tick is one event, bad while any
    matched series of `metric` (default janus_ledger_breach_active — 1
    once a per-(task, stage) imbalance has stayed nonzero past the
    ledger's grace window) is above zero. A silently lost or
    double-counted report moves no rate and no latency histogram — the
    unbalanced books are the only signal, and this is how they page
    through the same burn-rate ladder. The breach gauges are only born
    once an installed evaluator's first pass runs, so a process without
    a ledger reports no_data rather than fake health."""

    kind = "conservation"
    metric: str = "janus_ledger_breach_active"
    labels: tuple = ()

    @classmethod
    def from_dict(cls, d: dict) -> "ConservationSignal":
        return cls(
            metric=str(d.get("metric", "janus_ledger_breach_active")),
            labels=compile_matchers(d.get("labels")),
        )

    def _read_raw(self) -> tuple[float, int]:
        m = REGISTRY.get(self.metric)
        if m is None or not hasattr(m, "sum_matching"):
            return 0.0, 0
        return m.sum_matching(self.labels)

    def read(self, engine) -> tuple[float, float, bool]:
        st = engine._condition_state.setdefault(
            id(self), {"bad": 0.0, "total": 0.0, "prev": {}}
        )
        v, n = self._read_raw()
        if n == 0:
            return st["bad"], st["total"], st["total"] > 0
        st["total"] += 1.0
        if v > 0:
            st["bad"] += 1.0
        return st["bad"], st["total"], True

    def evidence(self) -> dict:
        desc = Selector(self.metric, self.labels).describe()
        v, n = self._read_raw()
        out = {f"{desc} breached series": v if n else None}
        breach = REGISTRY.get(self.metric)
        imbalance = REGISTRY.get("janus_ledger_imbalance")
        if v > 0 and hasattr(breach, "_values") and hasattr(imbalance, "_values"):
            with breach._lock:
                breach_vals = dict(breach._values)
            with imbalance._lock:
                imbalance_vals = dict(imbalance._values)
            for key, active in sorted(breach_vals.items()):
                if active > 0:
                    out[f"imbalance{dict(key)}"] = imbalance_vals.get(key)
        return out


_SIGNAL_KINDS = {
    "counter_ratio": RatioSignal,
    "histogram_latency": LatencySignal,
    "condition": ConditionSignal,
    "trend": TrendSignal,
    "conservation": ConservationSignal,
}


def signal_from_dict(d: dict):
    kind = str(d.get("kind", ""))
    cls = _SIGNAL_KINDS.get(kind)
    if cls is None:
        raise ValueError(
            f"unknown SLO signal kind {kind!r} (want one of {sorted(_SIGNAL_KINDS)})"
        )
    return cls.from_dict(d)


# ---------------------------------------------------------------------------
# Definitions
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BurnWindow:
    long_s: float
    short_s: float
    burn_rate: float
    severity: str

    @classmethod
    def from_dict(cls, d: dict) -> "BurnWindow":
        return cls(
            long_s=float(d["long_secs"]),
            short_s=float(d["short_secs"]),
            burn_rate=float(d["burn_rate"]),
            severity=str(d.get("severity", "page")),
        )


@dataclass(frozen=True)
class SloDefinition:
    name: str
    objective: float  # e.g. 0.999 -> error budget 0.001
    signal: object
    description: str = ""
    windows: tuple[BurnWindow, ...] = tuple(
        BurnWindow.from_dict(w) for w in DEFAULT_LADDER
    )
    enabled: bool = True

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.objective)

    @classmethod
    def from_dict(cls, d: dict) -> "SloDefinition":
        windows = tuple(
            BurnWindow.from_dict(w) for w in d.get("windows", DEFAULT_LADDER)
        )
        return cls(
            name=str(d["name"]),
            objective=float(d["objective"]),
            signal=signal_from_dict(d["signal"]),
            description=str(d.get("description", "")),
            windows=windows,
            enabled=bool(d.get("enabled", True)),
        )


def BUILTIN_SLOS() -> list[SloDefinition]:
    """The shipped defaults — one per operational question the paper's
    deployment posture forces (docs/OBSERVABILITY.md "SLO engine"):
    upload availability, aggregate/collect end-to-end latency,
    datastore reachability, device-path health. YAML `slo.definitions`
    entries override these by name."""
    return [
        SloDefinition(
            name="upload_availability",
            description=(
                "client uploads answered 201 vs shed (429/503) or failed "
                "(5xx) at the DAP upload route"
            ),
            objective=0.999,
            signal=RatioSignal(
                good=(
                    Selector(
                        "janus_http_requests",
                        compile_matchers({"route": "upload", "status": "201"}),
                    ),
                ),
                # 429/503 sheds and 5xx failures all land on the same
                # route counter, so one selector cannot double-count a
                # shed that also rides janus_upload_shed_total
                bad=(
                    Selector(
                        "janus_http_requests",
                        compile_matchers({"route": "upload", "status": "~(429|5..)"}),
                    ),
                ),
            ),
        ),
        SloDefinition(
            name="aggregate_step_latency",
            description=(
                "end-to-end report aggregation latency (client timestamp "
                "-> verified output share, janus_report_e2e_seconds"
                '{stage="aggregate"}) under 15 minutes'
            ),
            objective=0.99,
            signal=LatencySignal(
                metric="janus_report_e2e_seconds",
                labels=compile_matchers({"stage": "aggregate"}),
                threshold_s=900.0,
            ),
        ),
        SloDefinition(
            name="collect_latency",
            description=(
                "batch close -> aggregate share released "
                '(janus_report_e2e_seconds{stage="collect"}) under 1 hour'
            ),
            objective=0.99,
            signal=LatencySignal(
                metric="janus_report_e2e_seconds",
                labels=compile_matchers({"stage": "collect"}),
                threshold_s=3600.0,
            ),
        ),
        SloDefinition(
            name="datastore_up",
            description=(
                "the datastore supervisor reports the database reachable "
                "(janus_datastore_up)"
            ),
            objective=0.999,
            signal=ConditionSignal(
                conditions=(
                    Condition(
                        selector=Selector("janus_datastore_up", ()),
                        op="==",
                        value=0.0,
                    ),
                )
            ),
        ),
        SloDefinition(
            name="device_health",
            description=(
                "the device path is healthy: no new hung dispatches, no "
                "watchdog-parked threads, and no engine resident off the "
                "device (quarantined / host_fallback / timed_fallback)"
            ),
            objective=0.99,
            signal=ConditionSignal(
                conditions=(
                    Condition(
                        selector=Selector("janus_hung_dispatches_total", ()),
                        op=">",
                        value=0.0,
                        mode="delta",
                    ),
                    Condition(
                        selector=Selector("janus_abandoned_dispatch_threads", ()),
                        op=">",
                        value=0.0,
                    ),
                    Condition(
                        selector=Selector(
                            "janus_engine_backend",
                            compile_matchers(
                                {
                                    "state": "~(quarantined|host_fallback|timed_fallback)"
                                }
                            ),
                        ),
                        op=">",
                        value=0.0,
                    ),
                )
            ),
        ),
        SloDefinition(
            name="peer_reachable",
            description=(
                "the other aggregator is reachable: no peer is parked by "
                "the peer-health tracker (janus_peer_parked; "
                "aggregator/peer_health.py)"
            ),
            objective=0.999,
            signal=ConditionSignal(
                conditions=(
                    Condition(
                        selector=Selector("janus_peer_parked", ()),
                        op=">",
                        value=0.0,
                    ),
                )
            ),
        ),
        SloDefinition(
            name="report_conservation",
            description=(
                "the report-flow books close: no per-(task, stage) "
                "conservation imbalance — lost or double-counted reports "
                "— sustained past the ledger grace window, and no "
                "cross-aggregator divergence (janus_ledger_breach_active)"
            ),
            objective=0.999,
            signal=ConservationSignal(),
        ),
        SloDefinition(
            name="resident_lost",
            description=(
                "no resident aggregate share was lost on the flush path "
                '(janus_engine_resident_flushes_total{outcome="lost"}): '
                "count books still balance (counts are durable at job "
                "commit), but the lost share mass silently skews the "
                "released aggregate"
            ),
            objective=0.999,
            signal=ConditionSignal(
                conditions=(
                    Condition(
                        selector=Selector(
                            "janus_engine_resident_flushes_total",
                            compile_matchers({"outcome": "lost"}),
                        ),
                        op=">",
                        value=0.0,
                        mode="delta",
                    ),
                )
            ),
        ),
        SloDefinition(
            name="resource_trend",
            description=(
                "no leak-gated flight-recorder series (RSS, engine "
                "resident bytes, datastore rows, journal/manifest/AOT "
                "artifact bytes) shows a sustained positive trend "
                "(janus_flight_leak_active)"
            ),
            objective=0.99,
            signal=TrendSignal(),
        ),
    ]


@dataclass
class SloEngineConfig:
    """The YAML `slo:` stanza (CommonConfig). `definitions` entries
    merge over BUILTIN_SLOS by name (set `enabled: false` to drop a
    built-in); `window_scale` shrinks every ladder window uniformly —
    the chaos/bench smokes use it to make hour-scale alerting
    observable in seconds without forking the definitions."""

    enabled: bool = True
    evaluation_interval_s: float = 10.0
    window_scale: float = 1.0
    budget_window_s: float | None = None  # default: longest ladder window
    definitions: tuple = ()  # raw dicts, merged in build_definitions

    @classmethod
    def from_dict(cls, d: dict | None) -> "SloEngineConfig":
        d = d or {}
        return cls(
            enabled=bool(d.get("enabled", True)),
            evaluation_interval_s=float(d.get("evaluation_interval_secs", 10.0)),
            window_scale=float(d.get("window_scale", 1.0)),
            budget_window_s=(
                float(d["budget_window_secs"]) if "budget_window_secs" in d else None
            ),
            definitions=tuple(d.get("definitions", ())),
        )

    def build_definitions(self) -> list[SloDefinition]:
        defs = {s.name: s for s in BUILTIN_SLOS()}
        for raw in self.definitions:
            name = str(raw.get("name", ""))
            if not name:
                raise ValueError("slo definition without a name")
            if name in defs and "signal" not in raw:
                # partial override of a built-in (objective, windows,
                # enabled) without re-stating its signal
                base = defs[name]
                merged = {
                    "name": name,
                    "objective": raw.get("objective", base.objective),
                    "description": raw.get("description", base.description),
                    "enabled": raw.get("enabled", base.enabled),
                }
                windows = raw.get("windows")
                new = SloDefinition(
                    name=name,
                    objective=float(merged["objective"]),
                    signal=base.signal,
                    description=str(merged["description"]),
                    windows=(
                        tuple(BurnWindow.from_dict(w) for w in windows)
                        if windows
                        else base.windows
                    ),
                    enabled=bool(merged["enabled"]),
                )
                defs[name] = new
            else:
                defs[name] = SloDefinition.from_dict(raw)
        return [s for s in defs.values() if s.enabled]


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class _SloState:
    """Per-SLO sliding window of cumulative (t, bad, total) samples."""

    __slots__ = ("definition", "samples", "alerts", "no_data")

    def __init__(self, definition: SloDefinition):
        self.definition = definition
        self.samples: collections.deque = collections.deque()
        # one state per LADDER RUNG (keyed by index — severities may
        # repeat, e.g. the Workbook's 3-rung ladder has two page rungs,
        # and a later same-severity rung must not clobber an earlier
        # firing one): {"firing": bool, "since": unix}
        self.alerts = [
            {"firing": False, "since": None} for _ in definition.windows
        ]
        self.no_data = True

    def append(self, t: float, bad: float, total: float, retention_s: float) -> None:
        self.samples.append((t, bad, total))
        cutoff = t - retention_s
        while len(self.samples) > 1 and self.samples[1][0] <= cutoff:
            self.samples.popleft()

    def window_delta(self, window_s: float, now: float) -> tuple[float, float, float]:
        """(bad delta, total delta, actual covered seconds) between now
        and the newest sample at or before now-window (best effort: a
        freshly-started engine covers what it has)."""
        if not self.samples:
            return 0.0, 0.0, 0.0
        newest = self.samples[-1]
        cutoff = now - window_s
        base = self.samples[0]
        for s in self.samples:
            if s[0] <= cutoff:
                base = s
            else:
                break
        return (
            newest[1] - base[1],
            newest[2] - base[2],
            max(0.0, newest[0] - base[0]),
        )


class SloEngine:
    """Evaluates the definitions on a low-cadence thread (or on demand
    via evaluate_once for tests). Thread-safe snapshot readers:
    alertz_doc() for GET /alertz, status() for the /statusz section."""

    def __init__(
        self,
        definitions: list[SloDefinition] | None = None,
        interval_s: float = 10.0,
        window_scale: float = 1.0,
        budget_window_s: float | None = None,
        time_fn=time.time,
    ):
        self.interval_s = max(0.01, float(interval_s))
        self.window_scale = max(1e-9, float(window_scale))
        self._time = time_fn
        defs = BUILTIN_SLOS() if definitions is None else list(definitions)
        self._states = {d.name: _SloState(d) for d in defs if d.enabled}
        longest = max(
            (w.long_s for st in self._states.values() for w in st.definition.windows),
            default=3600.0,
        )
        self.budget_window_s = (
            float(budget_window_s)
            if budget_window_s is not None
            else longest * self.window_scale
        )
        self._retention_s = (
            max(longest * self.window_scale, self.budget_window_s) + 10 * self.interval_s
        )
        self._condition_state: dict = {}
        self._lock = threading.Lock()
        self._last_eval: float | None = None
        self._eval_count = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @classmethod
    def from_config(cls, cfg: SloEngineConfig, time_fn=time.time) -> "SloEngine":
        return cls(
            definitions=cfg.build_definitions(),
            interval_s=cfg.evaluation_interval_s,
            window_scale=cfg.window_scale,
            budget_window_s=cfg.budget_window_s,
            time_fn=time_fn,
        )

    # --- evaluation ---

    def evaluate_once(self, now: float | None = None) -> None:
        now = self._time() if now is None else now
        with self._lock:
            for st in self._states.values():
                try:
                    self._evaluate_slo(st, now)
                except Exception:
                    # one broken definition must not kill the ladder
                    log.exception("SLO %s evaluation failed", st.definition.name)
            self._last_eval = now
            self._eval_count += 1

    def _evaluate_slo(self, st: _SloState, now: float) -> None:
        d = st.definition
        bad, total, has_data = d.signal.read(self)
        st.no_data = not has_data
        if has_data:
            st.append(now, bad, total, self._retention_s)

        burns: dict[float, float] = {}
        for w in d.windows:
            for win_s in (w.long_s, w.short_s):
                if win_s not in burns:
                    burns[win_s] = self._burn(st, win_s * self.window_scale, now)
        # per-replica labels ({} until a fleet identity is configured):
        # N replicas' SLO engines exporting to one scrape plane stay
        # truthful — each replica's burn is its own series, never a
        # last-write-wins blend (docs/ARCHITECTURE.md "Running a fleet")
        rl = metrics.replica_labels()
        for win_s, burn in burns.items():
            metrics.slo_burn_rate.set(
                burn, slo=d.name, window=format_window(win_s), **rl
            )

        # budget remaining over the budget window
        bad_d, total_d, _ = st.window_delta(self.budget_window_s, now)
        err_ratio = (bad_d / total_d) if total_d > 0 else 0.0
        metrics.slo_error_budget_remaining.set(
            1.0 - err_ratio / d.budget, slo=d.name, **rl
        )

        severity_firing: dict[str, bool] = {}
        for i, w in enumerate(d.windows):
            firing = (
                burns[w.long_s] >= w.burn_rate and burns[w.short_s] >= w.burn_rate
            )
            state = st.alerts[i]
            if firing and not state["firing"]:
                state["firing"] = True
                state["since"] = now
                log.warning(
                    "SLO alert firing: %s severity=%s burn(long=%s)=%.1f "
                    "burn(short=%s)=%.1f threshold=%.1f",
                    d.name,
                    w.severity,
                    format_window(w.long_s),
                    burns[w.long_s],
                    format_window(w.short_s),
                    burns[w.short_s],
                    w.burn_rate,
                )
            elif not firing and state["firing"]:
                state["firing"] = False
                state["since"] = None
                log.info("SLO alert resolved: %s severity=%s", d.name, w.severity)
            # the gauge has one series per (alert, severity): it reads 1
            # while ANY rung of that severity fires
            severity_firing[w.severity] = (
                severity_firing.get(w.severity, False) or state["firing"]
            )
        for severity, firing in severity_firing.items():
            metrics.alert_active.set(
                1.0 if firing else 0.0, alert=d.name, severity=severity, **rl
            )

    def _burn(self, st: _SloState, window_s: float, now: float) -> float:
        bad_d, total_d, covered = st.window_delta(window_s, now)
        if total_d <= 0 or covered <= 0:
            return 0.0
        return (bad_d / total_d) / st.definition.budget

    # --- lifecycle ---

    def start(self) -> "SloEngine":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="slo-engine", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        # first pass immediately: a post-restart scrape must not wait a
        # full interval for the alert gauges to exist
        while True:
            try:
                self.evaluate_once()
            except Exception:
                log.exception("SLO evaluation pass failed")
            if self._stop.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    # --- snapshots ---

    def alertz_doc(self) -> dict:
        """The GET /alertz payload."""
        with self._lock:
            now = self._time()
            slos = []
            alerts = []
            for st in self._states.values():
                d = st.definition
                window_burns = {}
                for w in d.windows:
                    for win_s in (w.long_s, w.short_s):
                        window_burns.setdefault(
                            format_window(win_s),
                            round(self._burn(st, win_s * self.window_scale, now), 4),
                        )
                bad_d, total_d, covered = st.window_delta(self.budget_window_s, now)
                err_ratio = (bad_d / total_d) if total_d > 0 else 0.0
                slo_doc = {
                    "name": d.name,
                    "description": d.description,
                    "objective": d.objective,
                    "signal_kind": d.signal.kind,
                    "no_data": st.no_data,
                    "burn_rates": window_burns,
                    "error_budget_remaining_ratio": round(
                        1.0 - err_ratio / d.budget, 4
                    ),
                    "budget_window_events": total_d,
                    "budget_window_bad_events": bad_d,
                    "budget_window_covered_s": round(covered, 3),
                    "evidence": d.signal.evidence(),
                }
                if isinstance(d.signal, LatencySignal):
                    slo_doc["effective_threshold_s"] = d.signal.effective_threshold_s()
                slos.append(slo_doc)
                for i, w in enumerate(d.windows):
                    state = st.alerts[i]
                    alerts.append(
                        {
                            "alert": d.name,
                            "severity": w.severity,
                            "state": "firing" if state["firing"] else "ok",
                            "burn_rate_threshold": w.burn_rate,
                            "long_window": format_window(w.long_s),
                            "short_window": format_window(w.short_s),
                            "burn_rate_long": round(
                                self._burn(st, w.long_s * self.window_scale, now), 4
                            ),
                            "burn_rate_short": round(
                                self._burn(st, w.short_s * self.window_scale, now), 4
                            ),
                            "firing_since_unix": state["since"],
                            **(
                                {"firing_for_s": round(now - state["since"], 3)}
                                if state["since"] is not None
                                else {}
                            ),
                        }
                    )
            return {
                "enabled": True,
                "evaluation_interval_s": self.interval_s,
                "window_scale": self.window_scale,
                "budget_window_s": self.budget_window_s,
                "last_evaluation_unix": self._last_eval,
                "evaluations": self._eval_count,
                "firing": sorted(
                    {
                        f'{a["alert"]}/{a["severity"]}'
                        for a in alerts
                        if a["state"] == "firing"
                    }
                ),
                "alerts": alerts,
                "slos": slos,
            }

    def status(self) -> dict:
        """The compact /statusz `slo` section."""
        doc = self.alertz_doc()
        return {
            "evaluations": doc["evaluations"],
            "last_evaluation_unix": doc["last_evaluation_unix"],
            "firing": doc["firing"],
            "budget_remaining": {
                s["name"]: s["error_budget_remaining_ratio"] for s in doc["slos"]
            },
            "no_data": sorted(s["name"] for s in doc["slos"] if s["no_data"]),
        }


# ---------------------------------------------------------------------------
# Process-wide engine (the health listener's /alertz reads it)
# ---------------------------------------------------------------------------

_engine: SloEngine | None = None
_engine_lock = threading.Lock()


def install_slo_engine(cfg: SloEngineConfig | None = None, start: bool = True) -> SloEngine:
    """Install (replacing any previous) the process-wide engine and
    register its /statusz section. janus_main calls this with the YAML
    stanza; tests/bench call it with a scaled config."""
    global _engine
    from .statusz import register_status_provider

    cfg = cfg or SloEngineConfig()
    engine = SloEngine.from_config(cfg)
    # one stable bound-method object per engine: the identity-guarded
    # unregister below must see the same callable that was registered
    engine._status_provider = engine.status
    with _engine_lock:
        prev, _engine = _engine, engine
    if prev is not None:
        prev.stop()
    register_status_provider("slo", engine._status_provider)
    if start:
        engine.start()
    return engine


def uninstall_slo_engine() -> None:
    global _engine
    from .statusz import unregister_status_provider

    with _engine_lock:
        engine, _engine = _engine, None
    if engine is not None:
        engine.stop()
        unregister_status_provider("slo", getattr(engine, "_status_provider", None))
    return None


def get_slo_engine() -> SloEngine | None:
    return _engine


def alertz_snapshot() -> dict:
    """The GET /alertz payload for this process: the installed engine's
    state, or a well-formed disabled document (every binary serves the
    route; a process without an engine — e.g. slo.enabled: false —
    still answers with valid JSON)."""
    engine = _engine
    if engine is None:
        return {"enabled": False, "firing": [], "alerts": [], "slos": []}
    return engine.alertz_doc()
