"""Task model: per-task DAP configuration for an aggregator.

Equivalent of reference aggregator_core/src/task.rs:97-139 (`Task`),
:492 (`SerializedTask` YAML form), :677 (`TaskBuilder`). A task binds a
TaskId to endpoints, query type, VDAF, role, verify key, batch/time
parameters, auth tokens and HPKE keys.
"""

from __future__ import annotations

import base64
import secrets
from dataclasses import dataclass, field, replace

from .core.auth import AuthenticationToken
from .core.hpke import HpkeKeypair, generate_hpke_config_and_private_key
from .messages import Duration, HpkeConfig, Role, TaskId, Time, TimeInterval, FixedSize, QUERY_TYPES
from .vdaf.registry import VERIFY_KEY_LENGTH, VdafInstance


def _dp_from_dict(d):
    from .dp import DpStrategy

    return DpStrategy.from_dict(d)


@dataclass(frozen=True)
class QueryTypeConfig:
    """TimeInterval, or FixedSize{max_batch_size, batch_time_window_size}."""

    code: int
    max_batch_size: int | None = None
    batch_time_window_size: Duration | None = None

    @classmethod
    def time_interval(cls) -> "QueryTypeConfig":
        return cls(TimeInterval.CODE)

    @classmethod
    def fixed_size(cls, max_batch_size: int | None = None, batch_time_window_size: Duration | None = None) -> "QueryTypeConfig":
        return cls(FixedSize.CODE, max_batch_size, batch_time_window_size)

    @property
    def query_type(self):
        return QUERY_TYPES[self.code]

    def to_dict(self) -> dict:
        d = {"code": self.code}
        if self.max_batch_size is not None:
            d["max_batch_size"] = self.max_batch_size
        if self.batch_time_window_size is not None:
            d["batch_time_window_size"] = self.batch_time_window_size.seconds
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "QueryTypeConfig":
        return cls(
            d["code"],
            d.get("max_batch_size"),
            Duration(d["batch_time_window_size"]) if d.get("batch_time_window_size") is not None else None,
        )


@dataclass(frozen=True)
class Task:
    """reference aggregator_core/src/task.rs:97."""

    task_id: TaskId
    leader_aggregator_endpoint: str
    helper_aggregator_endpoint: str
    query_type: QueryTypeConfig
    vdaf: VdafInstance
    role: Role
    vdaf_verify_key: bytes
    max_batch_query_count: int
    task_expiration: Time | None
    report_expiry_age: Duration | None
    min_batch_size: int
    time_precision: Duration
    tolerable_clock_skew: Duration
    collector_hpke_config: HpkeConfig | None
    aggregator_auth_token: AuthenticationToken | None
    collector_auth_token: AuthenticationToken | None
    hpke_keys: tuple[HpkeKeypair, ...] = ()
    # DP noise each aggregator adds to its own aggregate share at release
    # (beyond the reference, whose DpMechanism is only Reserved|None)
    dp_strategy: "DpStrategy" = None  # type: ignore[assignment]

    def __post_init__(self):
        assert self.role in (Role.LEADER, Role.HELPER)
        assert len(self.vdaf_verify_key) == VERIFY_KEY_LENGTH
        assert self.time_precision.seconds > 0
        if self.dp_strategy is None:
            from .dp import DpStrategy

            object.__setattr__(self, "dp_strategy", DpStrategy())

    def peer_endpoint(self) -> str:
        return (
            self.helper_aggregator_endpoint
            if self.role == Role.LEADER
            else self.leader_aggregator_endpoint
        )

    def hpke_keypair(self, config_id) -> HpkeKeypair | None:
        for kp in self.hpke_keys:
            if kp.config.id == config_id:
                return kp
        return None

    def report_expired(self, report_time: Time, now: Time) -> bool:
        """GC cutoff check (reference aggregator.rs:1362-1370)."""
        if self.report_expiry_age is None:
            return False
        return report_time.add(self.report_expiry_age) < now

    def to_dict(self) -> dict:
        """Serialized form (reference SerializedTask, task.rs:492)."""

        def b64(b: bytes) -> str:
            return base64.urlsafe_b64encode(b).decode().rstrip("=")

        return {
            "task_id": b64(self.task_id.data),
            "leader_aggregator_endpoint": self.leader_aggregator_endpoint,
            "helper_aggregator_endpoint": self.helper_aggregator_endpoint,
            "query_type": self.query_type.to_dict(),
            "vdaf": self.vdaf.to_dict(),
            "role": int(self.role),
            "vdaf_verify_key": b64(self.vdaf_verify_key),
            "max_batch_query_count": self.max_batch_query_count,
            "task_expiration": self.task_expiration.seconds if self.task_expiration else None,
            "report_expiry_age": self.report_expiry_age.seconds if self.report_expiry_age else None,
            "min_batch_size": self.min_batch_size,
            "time_precision": self.time_precision.seconds,
            "tolerable_clock_skew": self.tolerable_clock_skew.seconds,
            "collector_hpke_config": (
                base64.urlsafe_b64encode(self.collector_hpke_config.to_bytes()).decode()
                if self.collector_hpke_config
                else None
            ),
            "aggregator_auth_token": self.aggregator_auth_token.to_dict() if self.aggregator_auth_token else None,
            "collector_auth_token": self.collector_auth_token.to_dict() if self.collector_auth_token else None,
            "hpke_keys": [
                {
                    "config": base64.urlsafe_b64encode(kp.config.to_bytes()).decode(),
                    "private_key": b64(kp.private_key),
                }
                for kp in self.hpke_keys
            ],
            "dp_strategy": self.dp_strategy.to_dict() if self.dp_strategy.enabled else None,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Task":
        def unb64(s: str) -> bytes:
            return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))

        return cls(
            task_id=TaskId(unb64(d["task_id"])),
            leader_aggregator_endpoint=d["leader_aggregator_endpoint"],
            helper_aggregator_endpoint=d["helper_aggregator_endpoint"],
            query_type=QueryTypeConfig.from_dict(d["query_type"]),
            vdaf=VdafInstance.from_dict(d["vdaf"]),
            role=Role(d["role"]),
            vdaf_verify_key=unb64(d["vdaf_verify_key"]),
            max_batch_query_count=d["max_batch_query_count"],
            task_expiration=Time(d["task_expiration"]) if d.get("task_expiration") is not None else None,
            report_expiry_age=Duration(d["report_expiry_age"]) if d.get("report_expiry_age") is not None else None,
            min_batch_size=d["min_batch_size"],
            time_precision=Duration(d["time_precision"]),
            tolerable_clock_skew=Duration(d["tolerable_clock_skew"]),
            collector_hpke_config=(
                HpkeConfig.from_bytes(base64.urlsafe_b64decode(d["collector_hpke_config"]))
                if d.get("collector_hpke_config")
                else None
            ),
            aggregator_auth_token=(
                AuthenticationToken.from_dict(d["aggregator_auth_token"])
                if d.get("aggregator_auth_token")
                else None
            ),
            collector_auth_token=(
                AuthenticationToken.from_dict(d["collector_auth_token"])
                if d.get("collector_auth_token")
                else None
            ),
            hpke_keys=tuple(
                HpkeKeypair(
                    HpkeConfig.from_bytes(base64.urlsafe_b64decode(k["config"])),
                    unb64(k["private_key"]),
                )
                for k in d.get("hpke_keys", ())
            ),
            dp_strategy=_dp_from_dict(d.get("dp_strategy")),
        )


class TaskBuilder:
    """Fluent builder with sane test defaults (reference task.rs:677)."""

    def __init__(self, query_type: QueryTypeConfig, vdaf: VdafInstance, role: Role):
        self._task = Task(
            task_id=TaskId.random(),
            leader_aggregator_endpoint="https://leader.example.com/",
            helper_aggregator_endpoint="https://helper.example.com/",
            query_type=query_type,
            vdaf=vdaf,
            role=role,
            vdaf_verify_key=secrets.token_bytes(VERIFY_KEY_LENGTH),
            max_batch_query_count=1,
            task_expiration=None,
            report_expiry_age=None,
            min_batch_size=1,
            time_precision=Duration(3600),
            tolerable_clock_skew=Duration(60),
            collector_hpke_config=generate_hpke_config_and_private_key(config_id=200).config,
            aggregator_auth_token=AuthenticationToken.random_bearer(),
            collector_auth_token=AuthenticationToken.random_bearer(),
            hpke_keys=(generate_hpke_config_and_private_key(config_id=0),),
        )

    def with_(self, **kwargs) -> "TaskBuilder":
        self._task = replace(self._task, **kwargs)
        return self

    def build(self) -> Task:
        return self._task
