"""DAP collector: create collection jobs, poll, decrypt, unshard.

Equivalent of reference collector/src/lib.rs:155-650
(`CollectorParameters`, `Collector::collect` = start_collection +
poll_once/poll_until_complete, HPKE-open of both aggregate shares,
vdaf.unshard).
"""

from __future__ import annotations

import secrets
import time as _time
from dataclasses import dataclass

from .core.hpke import HpkeApplicationInfo, HpkeKeypair, Label, hpke_open
from .core.auth import AuthenticationToken
from .core.retries import Backoff, retry_http_request
from .messages import (
    AggregateShareAad,
    BatchSelector,
    Collection,
    CollectionJobId,
    CollectionReq,
    Interval,
    Query,
    Role,
    TaskId,
    TimeInterval,
)
from .vdaf.registry import VdafInstance, circuit_for, prio3_host
from .client import b64url


@dataclass
class CollectorParameters:
    """reference collector/src/lib.rs:155."""

    task_id: TaskId
    leader_endpoint: str
    auth_token: AuthenticationToken
    hpke_keypair: HpkeKeypair  # collector's own keypair

    def collection_job_uri(self, collection_job_id: CollectionJobId) -> str:
        return (
            self.leader_endpoint.rstrip("/")
            + f"/tasks/{b64url(self.task_id.data)}/collection_jobs/{b64url(collection_job_id.data)}"
        )


@dataclass
class CollectionResult:
    """reference collector/src/lib.rs:279 `Collection`."""

    report_count: int
    interval: Interval
    aggregate_result: object
    partial_batch_selector: object = None  # set for fixed-size queries


class CollectionJobNotReady(Exception):
    """202 poll response; retry_after_s carries the leader's Retry-After
    hint when present (reference collector/src/lib.rs:466)."""

    def __init__(self, retry_after_s: float | None = None):
        super().__init__("collection job not ready")
        self.retry_after_s = retry_after_s


class Collector:
    """reference collector/src/lib.rs:359."""

    def __init__(self, params: CollectorParameters, vdaf: VdafInstance, http):
        self.params = params
        self.vdaf = vdaf
        self.prio3 = prio3_host(vdaf) if vdaf.kind != "poplar1" else None
        self.http = http

    def start_collection(self, query: Query, agg_param: bytes = b"") -> CollectionJobId:
        """PUT the CollectionReq (reference :384)."""
        job_id = CollectionJobId(secrets.token_bytes(16))
        req = CollectionReq(query, agg_param)
        headers = {"Content-Type": CollectionReq.MEDIA_TYPE}
        headers.update(self.params.auth_token.request_headers())
        status, body = retry_http_request(
            lambda: self.http.put(
                self.params.collection_job_uri(job_id), req.to_bytes(), headers
            )
            + (getattr(self.http, "last_response_headers", {}),)
        )
        if status not in (200, 201):
            raise RuntimeError(f"collection create failed: HTTP {status}: {body[:300]!r}")
        return job_id

    def poll_once(self, job_id: CollectionJobId, query: Query, agg_param: bytes = b""):
        """POST-poll the job (reference :440); raises CollectionJobNotReady."""
        headers = dict(self.params.auth_token.request_headers())
        status, body = retry_http_request(
            lambda: self.http.post(self.params.collection_job_uri(job_id), b"", headers)
            + (getattr(self.http, "last_response_headers", {}),)
        )
        if status == 202:
            ra = None
            hdrs = getattr(self.http, "last_response_headers", {})
            raw = next((v for k, v in hdrs.items() if k.lower() == "retry-after"), None)
            if raw is not None:
                try:
                    ra = max(0.0, float(raw))  # delta-seconds form only
                except ValueError:
                    ra = None
            raise CollectionJobNotReady(retry_after_s=ra)
        if status != 200:
            raise RuntimeError(f"collection poll failed: HTTP {status}: {body[:300]!r}")
        collection = Collection.from_bytes(body)
        return self._unshard(collection, query, agg_param)

    def poll_until_complete(
        self, job_id: CollectionJobId, query: Query, agg_param: bytes = b"", timeout_s: float = 60.0, poll_interval_s: float = 0.2
    ) -> CollectionResult:
        """reference :561 — honors the leader's Retry-After on 202
        (collector/src/lib.rs:466), falling back to poll_interval_s."""
        deadline = _time.monotonic() + timeout_s
        while True:
            try:
                return self.poll_once(job_id, query, agg_param)
            except CollectionJobNotReady as e:
                # a 0 (or absent) hint keeps the local floor — never
                # busy-loop POSTs against the leader
                wait = (
                    poll_interval_s
                    if not e.retry_after_s  # None or 0
                    else e.retry_after_s
                )
                # cap to the remaining budget so a hint >= budget still
                # gets one final poll at the deadline instead of an
                # immediate TimeoutError
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("collection job did not complete in time")
                _time.sleep(min(wait, remaining))

    def collect(self, query: Query, agg_param: bytes = b"", timeout_s: float = 60.0) -> CollectionResult:
        """start + poll to completion (reference :619)."""
        job_id = self.start_collection(query, agg_param)
        return self.poll_until_complete(job_id, query, agg_param, timeout_s)

    def _unshard(self, collection: Collection, query: Query, agg_param: bytes) -> CollectionResult:
        """Decrypt both aggregate shares + vdaf.unshard (reference :500-560)."""
        if query.query_type == TimeInterval.CODE:
            batch_selector = BatchSelector.time_interval(query.batch_interval)
        else:
            batch_selector = BatchSelector.fixed_size(collection.partial_batch_selector.batch_id)
        aad = AggregateShareAad(self.params.task_id, agg_param, batch_selector).to_bytes()
        if self.vdaf.kind == "poplar1":
            from .vdaf.poplar1 import Poplar1, Poplar1AggParam

            poplar = Poplar1(self.vdaf.bits)
            p1_param = Poplar1AggParam.decode(agg_param)
            field = poplar.idpf.field_at(p1_param.level)
        else:
            field = circuit_for(self.vdaf).FIELD
        shares = []
        for role, ct in (
            (Role.LEADER, collection.leader_encrypted_agg_share),
            (Role.HELPER, collection.helper_encrypted_agg_share),
        ):
            pt = hpke_open(
                self.params.hpke_keypair,
                HpkeApplicationInfo(Label.AGGREGATE_SHARE, role, Role.COLLECTOR),
                ct,
                aad,
            )
            shares.append(field.decode_vec(pt))
        if self.vdaf.kind == "poplar1":
            result = poplar.unshard(p1_param, shares)
        else:
            result = self.prio3.unshard(shares, collection.report_count)
        pbs = (
            collection.partial_batch_selector
            if query.query_type != TimeInterval.CODE
            else None
        )
        return CollectionResult(collection.report_count, collection.interval, result, pbs)
