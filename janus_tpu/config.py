"""YAML configuration for the five binaries.

Equivalent of reference aggregator/src/config.rs: CommonConfig shared
by every binary (database, logging, health-check listener), the
JobDriverConfig knobs (config.rs:121-141) and per-binary sections.
Secrets (datastore keys) arrive via flags/env, never the YAML file
(binary_utils.rs:40-66).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import yaml

from .aggregator import Config as AggregatorProtocolConfig
from .aggregator.aggregation_job_creator import AggregationJobCreatorConfig
from .aggregator.aggregation_job_driver import ResidentConfig
from .aggregator.job_driver import JobDriverConfig
from .aggregator.peer_health import PeerHealthConfig
from .aggregator.step_pipeline import StepPipelineConfig
from .core.circuit_breaker import CircuitBreakerConfig
from .core.http_client import HttpClientConfig
from .flight_recorder import FlightRecorderConfig
from .ledger import LedgerConfig
from .profiler import ProfilerConfig
from .slo import SloEngineConfig
from .trace import TraceConfiguration


@dataclass
class FleetConfig:
    """YAML `fleet:` stanza (docs/ARCHITECTURE.md "Running a fleet"):
    the replica's identity and its slice of the job-claim shard space.
    Every field is env-overridable (JANUS_REPLICA_ID /
    JANUS_SHARD_COUNT / JANUS_SHARD_INDEX / JANUS_STEAL_AFTER_S) so a
    container fleet can stamp per-replica identity onto one shared
    YAML file."""

    # stable replica identity; None auto-generates hostname-pid (and
    # keeps the per-replica metric labels OFF — single-process
    # deployments keep their exact label sets)
    replica_id: str | None = None
    # shard predicate over the persisted job shard keys: this replica
    # claims shard_key % shard_count == shard_index immediately, any
    # other shard only after steal_after_secs of eligibility (a dead
    # replica's shard drains instead of starving)
    shard_count: int = 1
    shard_index: int = 0
    steal_after_secs: float = 30.0

    @classmethod
    def from_dict(cls, d: dict | None) -> "FleetConfig":
        import os

        d = d or {}
        replica_id = os.environ.get("JANUS_REPLICA_ID") or d.get("replica_id")
        count = os.environ.get("JANUS_SHARD_COUNT") or d.get("shard_count", 1)
        index = os.environ.get("JANUS_SHARD_INDEX") or d.get("shard_index", 0)
        steal = os.environ.get("JANUS_STEAL_AFTER_S") or d.get(
            "steal_after_secs", 30.0
        )
        return cls(
            replica_id=str(replica_id) if replica_id else None,
            shard_count=max(1, int(count)),
            shard_index=int(index),
            steal_after_secs=max(0.0, float(steal)),
        )

    def resolved_replica_id(self) -> str:
        from .metrics import default_replica_id

        return self.replica_id or default_replica_id()

    def shard_spec(self):
        """ShardSpec for the batched lease claims (None when the fleet
        is unsharded — the predicate compiles away entirely)."""
        from .datastore.models import ShardSpec

        import math

        if self.shard_count <= 1:
            return None
        return ShardSpec(
            shard_count=self.shard_count,
            shard_index=self.shard_index % self.shard_count,
            # ceil, never truncate: the claim predicate works in whole
            # seconds, and a fractional steal_after (0.5) must round to
            # a 1 s fence — int() would silently DISABLE stealing
            # fencing while the creator path honors the float
            steal_after_s=math.ceil(max(0.0, self.steal_after_secs)),
        )

    def holder_tag(self) -> bytes:
        """8-byte provenance tag stamped into every lease token this
        replica mints."""
        from .datastore.store import replica_holder_tag

        return replica_holder_tag(self.resolved_replica_id())


@dataclass
class EngineConfig:
    """YAML `engine:` stanza (docs/ARCHITECTURE.md "Resident aggregate
    state"): engine-layer knobs shared by every binary with a device
    path."""

    # persistent XLA compilation cache directory; overrides the
    # top-level compilation_cache_dir when set (the cheap slice of the
    # cold-start roadmap item: restarts and canary rebuilds reload
    # compiled executables from disk instead of recompiling). The cache
    # is ON by default via CommonConfig.compilation_cache_dir; set
    # `compilation_cache_dir: null` (and no engine-level dir) to
    # explicitly disable it.
    compile_cache_dir: str | None = None
    # process-wide device-byte bound on resident aggregate buffers
    # (EngineCache.RESIDENT_MAX_BYTES; LRU overflow evicts through the
    # flush path). 0/None keeps the class default.
    resident_max_bytes: int | None = None
    # merge small jobs across TASKS into one device dispatch (per-lane
    # verify keys). None keeps the process default (on).
    cross_task_coalesce: bool | None = None
    # --- geometry-manifest prewarm (docs/ARCHITECTURE.md "Cold-start
    # and prewarm") ---
    # persisted shape manifest of observed dispatch specializations.
    # None (default) puts it next to the compile cache
    # (<cache_dir>/shape_manifest.jsonl); "" disables recording AND
    # manifest-driven prewarm. The JANUS_SHAPE_MANIFEST env var is the
    # operator override.
    shape_manifest_path: str | None = None
    shape_manifest_max_entries: int = 512
    # serialized-executable AOT cache (<compile cache dir>/aot): a
    # restarted process deserializes compiled engine programs instead
    # of re-tracing them — the layer that takes a warm restart from
    # ~trace-per-program to ~tens of ms per program. JANUS_AOT_CACHE
    # env overrides ("0" disables, a path relocates).
    aot_cache: bool = True
    # AOT-compile the manifest's recorded specializations at boot,
    # before /readyz reports ready (highest recorded cost first,
    # bounded by the boot budget; the remainder warms in background)
    prewarm: bool = True
    prewarm_boot_budget_secs: float = 30.0
    # --- mesh serving geometry (docs/ARCHITECTURE.md "Multi-chip
    # serving") ---
    # `mesh: {dp, sp}` pins the serving mesh axes (dp = report batch,
    # sp = measurement/out-share columns) instead of auto-selecting
    # from the device count. Validated per engine — a single-device
    # process, or a request for more devices than exist, falls back to
    # the unsharded path. JANUS_MESH_DP / JANUS_MESH_SP envs override.
    mesh_dp: int | None = None
    mesh_sp: int | None = None

    @classmethod
    def from_dict(cls, d: dict | None) -> "EngineConfig":
        d = d or {}
        rmb = d.get("resident_max_bytes")
        xt = d.get("cross_task_coalesce")
        mesh = d.get("mesh") or {}
        mdp = mesh.get("dp")
        msp = mesh.get("sp")
        return cls(
            compile_cache_dir=d.get("compile_cache_dir"),
            resident_max_bytes=int(rmb) if rmb is not None else None,
            cross_task_coalesce=bool(xt) if xt is not None else None,
            shape_manifest_path=d.get("shape_manifest_path"),
            shape_manifest_max_entries=int(d.get("shape_manifest_max_entries", 512)),
            aot_cache=bool(d.get("aot_cache", True)),
            prewarm=bool(d.get("prewarm", True)),
            prewarm_boot_budget_secs=float(d.get("prewarm_boot_budget_secs", 30.0)),
            mesh_dp=int(mdp) if mdp is not None else None,
            mesh_sp=int(msp) if msp is not None else None,
        )


@dataclass
class DbConfig:
    """reference config.rs:61 (url + connection knobs). `url` selects
    the engine: a postgres://…/postgresql://… URL opens the Postgres
    backend (multi-host work queue, datastore.rs:203); any other value
    is a SQLite filesystem path (or ":memory:") for single-host
    deployments and tests."""

    url: str = "janus.sqlite"
    # WARN-log threshold for one datastore transaction (run_tx wall
    # time, retries included); <= 0 disables the warning.
    slow_tx_warn_secs: float = 1.0
    # Cap on one run_tx retry sleep (full-jitter exponential backoff
    # below it). Stretch for outage-heavy deployments so a retry storm
    # spreads out; janus_tx_retries_total{tx,kind} counts the retries.
    retry_max_interval_secs: float = 0.128
    # Datastore connection supervision (docs/ROBUSTNESS.md "Datastore
    # outages"): background health-probe period driving the
    # up/degraded/down/recovering state machine, /readyz, degraded-mode
    # shedding and the upload journal spill decision. 0 disables.
    health_probe_interval_secs: float = 5.0
    # consecutive connection-class failures before the state goes down
    down_after_failures: int = 3
    # ceiling of the jittered reconnect/probe backoff while down
    reconnect_max_interval_secs: float = 30.0

    @classmethod
    def from_dict(cls, d: dict) -> "DbConfig":
        return cls(
            url=str(d.get("url", "janus.sqlite")),
            slow_tx_warn_secs=float(d.get("slow_tx_warn_secs", 1.0)),
            retry_max_interval_secs=float(d.get("retry_max_interval_secs", 0.128)),
            health_probe_interval_secs=float(
                d.get("health_probe_interval_secs", 5.0)
            ),
            down_after_failures=int(d.get("down_after_failures", 3)),
            reconnect_max_interval_secs=float(
                d.get("reconnect_max_interval_secs", 30.0)
            ),
        )


@dataclass
class TaskprovConfig:
    """reference config.rs:93."""

    enabled: bool = False

    @classmethod
    def from_dict(cls, d: dict | None) -> "TaskprovConfig":
        return cls(enabled=bool((d or {}).get("enabled", False)))


@dataclass
class CommonConfig:
    """reference config.rs:28-45."""

    database: DbConfig = field(default_factory=DbConfig)
    logging_config: TraceConfiguration = field(default_factory=TraceConfiguration)
    health_check_listen_address: str = "0.0.0.0:9001"
    # Which JAX backend this process uses (e.g. "cpu", "tpu"). A TPU chip
    # is single-process: give it to the VDAF hot path (the helper-side
    # aggregator server, and the leader-side aggregation job driver) and
    # pin every other process to "cpu". None = leave the environment alone.
    jax_platform: str | None = None
    # Persistent XLA compilation cache directory. First compile of a
    # (VDAF, step, batch-bucket) is minutes; with the cache a process
    # restart reloads compiled executables in seconds. None disables.
    compilation_cache_dir: str | None = "~/.cache/janus_tpu_xla"
    # Warm the engines for every provisioned task at boot (trace+compile
    # the helper/leader steps for the smallest batch bucket) instead of
    # stalling the first request. Only the VDAF-hot-path binaries use it.
    warmup_engines_at_boot: bool = False
    # With warmup_buckets set (e.g. [32, 256, 1024]), warmup runs in a
    # background thread per ascending bucket — serving starts
    # immediately and big job buckets compile ahead of their first job.
    warmup_buckets: tuple[int, ...] = ()
    # Period of the job/task health sampler (aggregator/health_sampler.py:
    # janus_jobs backlog gauges, lease age, aggregation lag). 0 disables.
    # Wired by the aggregator server and both job driver binaries.
    health_sampler_interval_s: float = 15.0
    # Fault injection (janus_tpu/failpoints.py; docs/ROBUSTNESS.md): a
    # spec string ("datastore.commit=error:0.3;helper.request=delay:2")
    # or a {name: "action:arg,..."} mapping. The JANUS_FAILPOINTS env
    # var overrides. None (the default) arms nothing and every
    # instrumented site compiles to a one-flag-check no-op.
    failpoints: object = None
    # Device-path watchdog + quarantine (YAML `device_watchdog:`
    # section; docs/ROBUSTNESS.md "Device hangs & deadlines"): parked
    # abandoned-dispatch threads tolerated before the process trips
    # host-only mode, and the quarantined engine's canary cadence.
    watchdog_abandoned_thread_cap: int = 8
    quarantine_canary_delay_secs: float = 5.0
    quarantine_canary_timeout_secs: float = 30.0
    # In-process SLO burn-rate engine (YAML `slo:` section;
    # docs/OBSERVABILITY.md "SLO engine & /alertz"): evaluation cadence
    # and alert definitions (merged over the shipped defaults by name).
    # Enabled by default — every binary answers GET /alertz.
    slo: SloEngineConfig = field(default_factory=SloEngineConfig)
    # Engine-layer knobs (YAML `engine:` section): compile cache dir
    # override, resident-buffer byte bound, cross-task coalescing.
    engine: EngineConfig = field(default_factory=EngineConfig)
    # Always-on sampling profiler (YAML `profiler:` section;
    # docs/OBSERVABILITY.md "Continuous profiling"): wall-clock stack
    # sampling rate and window ring behind GET /debug/profile. Enabled
    # by default in every binary.
    profiler: ProfilerConfig = field(default_factory=ProfilerConfig)
    # Telemetry flight recorder (YAML `flight:` section;
    # docs/OBSERVABILITY.md "Flight recorder and trend alerts"):
    # low-cadence resource/metric history ring behind GET /debug/flight
    # plus the trend/leak analyzer feeding the `trend` SLO signal.
    # Enabled by default in every binary (memory-only until `dir` set).
    flight: FlightRecorderConfig = field(default_factory=FlightRecorderConfig)
    # Report-flow conservation ledger (YAML `ledger:` section;
    # docs/OBSERVABILITY.md "Conservation accounting"): per-task balance
    # evaluation at health-sampler cadence behind GET /debug/ledger,
    # grace window before an imbalance pages, and the leader collection
    # driver's cross-aggregator reconciliation fetch. Enabled by default
    # in every datastore-owning binary.
    ledger: LedgerConfig = field(default_factory=LedgerConfig)
    # Fleet identity + job-claim sharding (YAML `fleet:` section;
    # docs/ARCHITECTURE.md "Running a fleet"): replica id stamped into
    # lease tokens/metrics/traces, and this replica's slice of the
    # shard space for the batched lease claims. Env-overridable
    # (JANUS_REPLICA_ID / JANUS_SHARD_COUNT / JANUS_SHARD_INDEX /
    # JANUS_STEAL_AFTER_S) for container fleets.
    fleet: FleetConfig = field(default_factory=FleetConfig)

    @classmethod
    def from_dict(cls, d: dict) -> "CommonConfig":
        wd = d.get("device_watchdog", {}) or {}
        return cls(
            database=DbConfig.from_dict(d.get("database", {})),
            logging_config=TraceConfiguration.from_dict(d.get("logging_config")),
            health_check_listen_address=str(
                d.get("health_check_listen_address", "0.0.0.0:9001")
            ),
            jax_platform=d.get("jax_platform"),
            compilation_cache_dir=d.get("compilation_cache_dir", "~/.cache/janus_tpu_xla"),
            warmup_engines_at_boot=bool(d.get("warmup_engines_at_boot", False)),
            warmup_buckets=tuple(int(b) for b in d.get("warmup_buckets", ())),
            health_sampler_interval_s=float(d.get("health_sampler_interval_secs", 15.0)),
            failpoints=d.get("failpoints"),
            watchdog_abandoned_thread_cap=int(wd.get("abandoned_thread_cap", 8)),
            quarantine_canary_delay_secs=float(wd.get("canary_delay_secs", 5.0)),
            quarantine_canary_timeout_secs=float(wd.get("canary_timeout_secs", 30.0)),
            slo=SloEngineConfig.from_dict(d.get("slo")),
            engine=EngineConfig.from_dict(d.get("engine")),
            profiler=ProfilerConfig.from_dict(d.get("profiler")),
            flight=FlightRecorderConfig.from_dict(d.get("flight")),
            ledger=LedgerConfig.from_dict(d.get("ledger")),
            fleet=FleetConfig.from_dict(d.get("fleet")),
        )


def _job_driver_from_dict(d: dict) -> JobDriverConfig:
    """reference config.rs:121-141 field names."""
    return JobDriverConfig(
        job_discovery_interval_s=d.get("min_job_discovery_delay_secs", 0.2),
        max_job_discovery_interval_s=d.get("max_job_discovery_delay_secs", 5.0),
        max_concurrent_job_workers=int(d.get("max_concurrent_job_workers", 4)),
        worker_lease_duration_s=int(d.get("worker_lease_duration_secs", 600)),
        maximum_attempts_before_failure=int(
            d.get("maximum_attempts_before_failure", 10)
        ),
        discovery_jitter=float(d.get("job_discovery_jitter", 0.25)),
    )


@dataclass
class AggregatorConfig:
    """reference aggregator/src/bin/aggregator.rs Config."""

    common: CommonConfig = field(default_factory=CommonConfig)
    listen_address: str = "0.0.0.0:8080"
    aggregator_api_listen_address: str | None = None
    aggregator_api_auth_tokens: tuple[str, ...] = ()
    max_upload_batch_size: int = 100
    max_upload_batch_write_delay_ms: int = 0
    batch_aggregation_shard_count: int = 1
    taskprov: TaskprovConfig = field(default_factory=TaskprovConfig)
    garbage_collection_interval_s: float | None = None
    collection_retry_after_s: int = 1
    # --- ingest pipeline + admission control (YAML `ingest:` section;
    # docs/INGEST.md tuning table) ---
    ingest_decrypt_workers: int = 0  # 0 = GIL-capability-sized (INGEST.md)
    ingest_decode_workers: int = 1
    # flush-window batching of decode+decrypt (docs/INGEST.md "Batched
    # decrypt"); window 1 restores the per-report path
    ingest_batch_window: int = 32
    ingest_batch_linger_ms: float = 2.0
    # must stay below max_handler_threads (each in-flight upload parks
    # a handler thread, so a larger bound can never fill)
    ingest_queue_depth: int = 24
    upload_bucket_rate: float = 0.0  # 0 = unlimited
    upload_bucket_burst: int = 0
    aggregate_bucket_rate: float = 0.0
    aggregate_bucket_burst: int = 0
    shed_priority: tuple = ("upload", "aggregate")
    queue_high_watermark: float = 0.75
    upload_shed_retry_after_s: float = 1.0
    max_handler_threads: int = 32
    # --- durable upload spill journal (YAML `upload_journal:` section;
    # docs/ROBUSTNESS.md "Datastore outages"). No path = disarmed: the
    # upload flush path is unchanged and adds no fsyncs. ---
    upload_journal_path: str | None = None
    upload_journal_max_segment_bytes: int = 8 << 20
    upload_journal_max_total_bytes: int = 256 << 20
    upload_journal_max_segments: int = 1024
    # commit latency past this spills subsequent flushes to the journal
    # (bounded ack latency through a brownout); 0 = connection-class
    # errors / datastore-down only
    upload_journal_spill_latency_secs: float = 0.0
    upload_journal_replay_interval_secs: float = 1.0
    # Retry-After advertised on the 503 when the journal is full
    upload_journal_full_retry_after_secs: float = 30.0

    @classmethod
    def from_dict(cls, d: dict) -> "AggregatorConfig":
        gc = d.get("garbage_collection", {}) or {}
        api = d.get("aggregator_api", {}) or {}
        ingest = d.get("ingest", {}) or {}
        journal = d.get("upload_journal", {}) or {}
        return cls(
            common=CommonConfig.from_dict(d),
            listen_address=str(d.get("listen_address", "0.0.0.0:8080")),
            aggregator_api_listen_address=api.get("listen_address"),
            aggregator_api_auth_tokens=tuple(api.get("auth_tokens", ())),
            max_upload_batch_size=int(d.get("max_upload_batch_size", 100)),
            max_upload_batch_write_delay_ms=int(
                d.get("max_upload_batch_write_delay_ms", 0)
            ),
            batch_aggregation_shard_count=int(
                d.get("batch_aggregation_shard_count", 1)
            ),
            taskprov=TaskprovConfig.from_dict(d.get("taskprov_config")),
            garbage_collection_interval_s=gc.get("gc_frequency_s"),
            collection_retry_after_s=int(d.get("collection_retry_after_secs", 1)),
            ingest_decrypt_workers=int(ingest.get("decrypt_workers", 0)),
            ingest_decode_workers=int(ingest.get("decode_workers", 1)),
            ingest_batch_window=int(ingest.get("decrypt_batch_window", 32)),
            ingest_batch_linger_ms=float(ingest.get("decrypt_batch_linger_ms", 2.0)),
            ingest_queue_depth=int(ingest.get("queue_depth", 24)),
            upload_bucket_rate=float(ingest.get("upload_bucket_rate", 0.0)),
            upload_bucket_burst=int(ingest.get("upload_bucket_burst", 0)),
            aggregate_bucket_rate=float(ingest.get("aggregate_bucket_rate", 0.0)),
            aggregate_bucket_burst=int(ingest.get("aggregate_bucket_burst", 0)),
            shed_priority=tuple(ingest.get("shed_priority", ("upload", "aggregate"))),
            queue_high_watermark=float(ingest.get("queue_high_watermark", 0.75)),
            upload_shed_retry_after_s=float(ingest.get("shed_retry_after_secs", 1.0)),
            max_handler_threads=int(ingest.get("max_handler_threads", 32)),
            upload_journal_path=journal.get("path"),
            upload_journal_max_segment_bytes=int(
                journal.get("max_segment_bytes", 8 << 20)
            ),
            upload_journal_max_total_bytes=int(
                journal.get("max_total_bytes", 256 << 20)
            ),
            upload_journal_max_segments=int(journal.get("max_segments", 1024)),
            upload_journal_spill_latency_secs=float(
                journal.get("spill_commit_latency_secs", 0.0)
            ),
            upload_journal_replay_interval_secs=float(
                journal.get("replay_interval_secs", 1.0)
            ),
            upload_journal_full_retry_after_secs=float(
                journal.get("full_retry_after_secs", 30.0)
            ),
        )

    def protocol_config(self) -> AggregatorProtocolConfig:
        return AggregatorProtocolConfig(
            max_upload_batch_size=self.max_upload_batch_size,
            max_upload_batch_write_delay_ms=self.max_upload_batch_write_delay_ms,
            batch_aggregation_shard_count=self.batch_aggregation_shard_count,
            taskprov_enabled=self.taskprov.enabled,
            collection_retry_after_s=self.collection_retry_after_s,
            ingest_decrypt_workers=self.ingest_decrypt_workers,
            ingest_decode_workers=self.ingest_decode_workers,
            ingest_batch_window=self.ingest_batch_window,
            ingest_batch_linger_ms=self.ingest_batch_linger_ms,
            ingest_queue_depth=self.ingest_queue_depth,
            upload_bucket_rate=self.upload_bucket_rate,
            upload_bucket_burst=self.upload_bucket_burst,
            aggregate_bucket_rate=self.aggregate_bucket_rate,
            aggregate_bucket_burst=self.aggregate_bucket_burst,
            shed_priority=self.shed_priority,
            queue_high_watermark=self.queue_high_watermark,
            upload_shed_retry_after_s=self.upload_shed_retry_after_s,
            max_handler_threads=self.max_handler_threads,
            upload_journal_path=self.upload_journal_path,
            upload_journal_max_segment_bytes=self.upload_journal_max_segment_bytes,
            upload_journal_max_total_bytes=self.upload_journal_max_total_bytes,
            upload_journal_max_segments=self.upload_journal_max_segments,
            upload_journal_spill_latency_s=self.upload_journal_spill_latency_secs,
            upload_journal_replay_interval_s=self.upload_journal_replay_interval_secs,
            upload_journal_full_retry_after_s=self.upload_journal_full_retry_after_secs,
        )


@dataclass
class JobCreatorConfig:
    """reference aggregator/src/bin/aggregation_job_creator.rs Config."""

    common: CommonConfig = field(default_factory=CommonConfig)
    aggregation_job_creation_interval_s: float = 1.0
    min_aggregation_job_size: int = 10
    max_aggregation_job_size: int = 100
    max_concurrent_tasks: int = 8

    @classmethod
    def from_dict(cls, d: dict) -> "JobCreatorConfig":
        # (tasks_update_frequency_secs is accepted but unused: the creator
        # re-reads the task list on every pass, unlike the reference's
        # long-lived per-task workers, aggregation_job_creator.rs:154)
        return cls(
            common=CommonConfig.from_dict(d),
            aggregation_job_creation_interval_s=float(
                d.get("aggregation_job_creation_interval_secs", 1.0)
            ),
            min_aggregation_job_size=int(d.get("min_aggregation_job_size", 10)),
            max_aggregation_job_size=int(d.get("max_aggregation_job_size", 100)),
            max_concurrent_tasks=int(d.get("max_concurrent_tasks", 8)),
        )

    def creator_config(self) -> AggregationJobCreatorConfig:
        return AggregationJobCreatorConfig(
            min_aggregation_job_size=self.min_aggregation_job_size,
            max_aggregation_job_size=self.max_aggregation_job_size,
            max_concurrent_tasks=self.max_concurrent_tasks,
        )


@dataclass
class JobDriverBinaryConfig:
    """reference aggregator/src/bin/{aggregation,collection}_job_driver.rs."""

    common: CommonConfig = field(default_factory=CommonConfig)
    job_driver: JobDriverConfig = field(default_factory=JobDriverConfig)
    # leader->helper outbound circuit breaker knobs (YAML
    # `outbound_circuit_breaker:` section; docs/ROBUSTNESS.md)
    outbound_circuit_breaker: CircuitBreakerConfig = field(
        default_factory=CircuitBreakerConfig
    )
    # peer-outage parking + half-open probing (YAML `peer_health:`
    # section; docs/ARCHITECTURE.md "Surviving the other aggregator")
    peer_health: PeerHealthConfig = field(default_factory=PeerHealthConfig)
    # per-attempt timeout / body budget / size cap for the outbound
    # helper client (YAML `helper_http:` section)
    helper_http: HttpClientConfig = field(default_factory=HttpClientConfig)
    # stage-pipelined leader stepper knobs (YAML `step_pipeline:`
    # section; docs/ARCHITECTURE.md "The stepper pipeline"). Enabled by
    # default — `step_pipeline: {enabled: false}` restores the serial
    # per-worker stepper.
    step_pipeline: StepPipelineConfig = field(default_factory=StepPipelineConfig)
    # device-resident accumulator state (YAML `resident_accumulators:`
    # section; docs/ARCHITECTURE.md "Resident aggregate state"). Off by
    # default — the per-job share fetch+write stays crash-durable.
    resident_accumulators: ResidentConfig = field(default_factory=ResidentConfig)

    @classmethod
    def from_dict(cls, d: dict) -> "JobDriverBinaryConfig":
        return cls(
            common=CommonConfig.from_dict(d),
            job_driver=_job_driver_from_dict(d),
            outbound_circuit_breaker=CircuitBreakerConfig.from_dict(
                d.get("outbound_circuit_breaker")
            ),
            peer_health=PeerHealthConfig.from_dict(d.get("peer_health")),
            helper_http=HttpClientConfig.from_dict(d.get("helper_http")),
            step_pipeline=StepPipelineConfig.from_dict(d.get("step_pipeline")),
            resident_accumulators=ResidentConfig.from_dict(
                d.get("resident_accumulators")
            ),
        )


def load_config(path: str, cls):
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    return cls.from_dict(doc)
