"""Batched FLP prove/query/decide on device — the TPU heart.

The reference runs the FLP per report, serially, on CPU inside the
external `prio` crate (invoked from
aggregator/src/aggregator/aggregation_job_driver.rs:329-402 and
aggregator/src/aggregator.rs:1775-1797). Here one traced computation
processes a whole report batch: every value is a limb-tuple field array
with a leading [batch] axis, wire/gadget polynomial interpolation is
the batched NTT of janus_tpu.ops.ntt, and gadget evaluation is
elementwise — so XLA sees large fused elementwise graphs it can tile
onto the VPU, with throughput scaling in the batch dimension.

Semantics are byte/element-identical to the host oracle
(janus_tpu.vdaf.reference), enforced by differential tests. All four
Prio3 circuits (Count/Sum/SumVec/Histogram) have exactly one gadget
use of degree 2; the adapters below encode each circuit's gadget-call
schedule as static reshapes over the batch.

Per-report validity never branches: invalid reports yield a False lane
in the decision mask and are dropped at accumulation time (masked
aggregate), which is the static-shape answer to the reference's
per-report error handling (SURVEY.md section 7, "Ragged/failure-laden
batches").
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.jfield import (
    JF64,
    JF128,
    fconst,
    fmap,
    fmul_pow2,
    fpad_axis,
    fpow_const,
    freshape,
    fsum,
    ftile,
    fwhere,
    is_zero,
    anti_recompute_barrier,
)

# FLP query via MXU limb contraction (ops/limbmm.py) for the chunked
# circuits. Read once at import (participates in tracing, like
# JANUS_NO_BARRIERS): JANUS_QUERY_MM=0 falls back to the VPU fold path.
_QUERY_MM = os.environ.get("JANUS_QUERY_MM", "1") != "0"
from ..ops.ntt import (
    intt_batched,
    lagrange_eval_weights,
    ntt_batched,
    poly_eval_powers,
    powers,
)
from .reference import (
    EVAL_POINT_CANDIDATES,
    Circuit,
    Count,
    FixedPointVec,
    Histogram,
    SparseSumVec,
    Sum,
    SumVec,
    next_pow2,
)


def jf_for(circuit: Circuit):
    return {8: JF64, 16: JF128}[circuit.FIELD.ENCODED_SIZE]


# ---------------------------------------------------------------------------
# Per-circuit batched adapters
# ---------------------------------------------------------------------------


class BatchedCircuit:
    """Vectorized gadget schedule for one validity circuit.

    All methods take/return limb-tuple field values with a leading
    [batch] axis. `calls_inputs` returns [batch, calls, arity];
    `gadget_eval` consumes wires with arity on axis 1 ([batch, arity,
    ...]) and returns the gadget output with that axis dropped.
    """

    def __init__(self, circ: Circuit):
        self.circ = circ
        self.jf = jf_for(circ)
        use = circ.gadget_uses[0]
        assert len(circ.gadget_uses) == 1, "Prio3 circuits have one gadget use"
        self.arity = use.gadget.arity
        self.calls = use.calls
        self.m = use.wire_poly_len
        self.gp_len = use.gadget_poly_len
        self.n2 = next_pow2(self.gp_len)

    # --- measurement plumbing (host-side, numpy-vectorized) ---
    def encode_batch(self, measurements) -> np.ndarray:
        """[batch] measurements -> [batch, input_len] uint64 (< p)."""
        raise NotImplementedError

    # --- schedule ---
    def calls_inputs(self, inp, joint_rand, shares_inv: int):
        raise NotImplementedError

    def gadget_eval(self, wires):
        raise NotImplementedError

    def finish(self, inp, joint_rand, gadget_outs, shares_inv: int):
        raise NotImplementedError

    def truncate(self, inp):
        raise NotImplementedError

    # --- helpers ---
    def _sic(self, shares_inv: int, shape=()):
        return fconst(self.jf, shares_inv, shape)


class BCount(BatchedCircuit):
    def encode_batch(self, measurements):
        a = np.asarray(measurements, dtype=np.uint64)
        assert ((a == 0) | (a == 1)).all()
        return a[:, None]

    def calls_inputs(self, inp, joint_rand, shares_inv):
        # [[x, x]]: one call, arity 2
        return fmap(lambda x: x[:, :, None].repeat(2, axis=2), inp)

    def gadget_eval(self, wires):
        jf = self.jf
        w0 = fmap(lambda x: x[:, 0], wires)
        w1 = fmap(lambda x: x[:, 1], wires)
        return jf.mul(w0, w1)

    def finish(self, inp, joint_rand, gadget_outs, shares_inv):
        jf = self.jf
        return jf.sub(fmap(lambda x: x[:, 0], gadget_outs), fmap(lambda x: x[:, 0], inp))

    def truncate(self, inp):
        return inp


class BSum(BatchedCircuit):
    def encode_batch(self, measurements):
        a = np.asarray(measurements, dtype=np.uint64)
        bits = self.circ.bits
        if bits < 64:
            assert (a < (np.uint64(1) << np.uint64(bits))).all()
        return (a[:, None] >> np.arange(bits, dtype=np.uint64)[None, :]) & np.uint64(1)

    def calls_inputs(self, inp, joint_rand, shares_inv):
        return fmap(lambda x: x[:, :, None], inp)  # [batch, bits, 1]

    def gadget_eval(self, wires):
        jf = self.jf
        x = fmap(lambda w: w[:, 0], wires)
        return jf.sub(jf.mul(x, x), x)  # x^2 - x

    def finish(self, inp, joint_rand, gadget_outs, shares_inv):
        jf = self.jf
        r = fmap(lambda x: x[:, 0], joint_rand)
        pw = powers(jf, r, self.calls + 1)  # [batch, calls+1]
        rp = fmap(lambda x: x[..., 1:], pw)  # r^1..r^calls
        return fsum(jf, jf.mul(rp, gadget_outs), axis=-1)

    def truncate(self, inp):
        jf = self.jf
        return fmap(
            lambda x: x[:, None],
            _pow2_weighted_sum(jf, inp, self.circ.bits, axis=-1),
        )


class _BChunked(BatchedCircuit):
    """Shared ParallelSum(Mul, chunk) schedule of SumVec and Histogram."""

    def _pair_inputs(self, inp, joint_rand, shares_inv):
        """(r^{i+1} x_i, x_i - shares_inv) pairs -> [batch, calls, 2*chunk]."""
        jf = self.jf
        n = self.circ.input_len
        ch = self.circ.chunk_length
        r = fmap(lambda x: x[:, 0], joint_rand)
        pw = powers(jf, r, n + 1)
        rp = fmap(lambda x: x[..., 1:], pw)  # [batch, n]: r^1..r^n
        a = jf.mul(rp, inp)
        b = jf.sub(inp, self._sic(shares_inv))
        # interleave (a_i, b_i) then pad to calls*chunk pairs
        pairs = fmap(
            lambda x, y: jnp.stack([x, y], axis=-1).reshape(x.shape[0], -1), a, b
        )
        total = self.calls * ch * 2
        pad = total - pairs[0].shape[-1]
        if pad:
            pairs = fmap(lambda x: jnp.pad(x, ((0, 0), (0, pad))), pairs)
        return fmap(lambda x: x.reshape(x.shape[0], self.calls, 2 * ch), pairs)

    def calls_inputs(self, inp, joint_rand, shares_inv):
        return self._pair_inputs(inp, joint_rand, shares_inv)

    def gadget_eval(self, wires):
        # wires [batch, 2*chunk, ...] -> sum_c w[2c]*w[2c+1]
        jf = self.jf
        ch = self.circ.chunk_length
        shaped = fmap(
            lambda w: w.reshape((w.shape[0], ch, 2) + w.shape[2:]), wires
        )
        x = fmap(lambda w: w[:, :, 0], shaped)
        y = fmap(lambda w: w[:, :, 1], shaped)
        return fsum(jf, jf.mul(x, y), axis=1)


class BSumVec(_BChunked):
    def encode_batch(self, measurements):
        a = np.asarray(measurements, dtype=np.uint64)  # [batch, length]
        bits = self.circ.bits
        out = (a[:, :, None] >> np.arange(bits, dtype=np.uint64)[None, None, :]) & np.uint64(1)
        return out.reshape(a.shape[0], -1)

    def finish(self, inp, joint_rand, gadget_outs, shares_inv):
        return fsum(self.jf, gadget_outs, axis=-1)

    def truncate(self, inp):
        # bits-major [batch, bits, length] layout: a trailing dim of
        # `bits` (16) pads 8x against the TPU's (8, 128) tile — at
        # len=100k batch=16 that one layout choice cost 683 MB of HBM
        # padding per limb temp (measured via compiled.memory_analysis)
        jf = self.jf
        bits = self.circ.bits
        length = self.circ.length
        v = fmap(
            lambda x: jnp.swapaxes(x.reshape(x.shape[0], length, bits), 1, 2), inp
        )
        return _pow2_weighted_sum(jf, v, bits)


class BHistogram(_BChunked):
    def encode_batch(self, measurements):
        a = np.asarray(measurements, dtype=np.int64)
        assert ((0 <= a) & (a < self.circ.length)).all()
        out = np.zeros((a.shape[0], self.circ.length), dtype=np.uint64)
        out[np.arange(a.shape[0]), a] = 1
        return out

    def finish(self, inp, joint_rand, gadget_outs, shares_inv):
        jf = self.jf
        bit_check = fsum(jf, gadget_outs, axis=-1)
        sum_check = jf.sub(fsum(jf, inp, axis=-1), self._sic(shares_inv))
        jr1 = fmap(lambda x: x[:, 1], joint_rand)
        return jf.add(bit_check, jf.mul(jr1, sum_check))

    def truncate(self, inp):
        return inp


class BFixedPointVec(_BChunked):
    """Device twin of reference.FixedPointVec: bit-check calls followed by
    squared-entry norm calls through the same ParallelSum(Mul) gadget."""

    def encode_batch(self, measurements):
        circ = self.circ
        a = np.asarray(measurements, dtype=np.int64)  # [batch, length] signed
        assert a.ndim == 2 and a.shape[1] == circ.length
        assert ((-circ.offset <= a) & (a < circ.offset)).all()
        u = a.astype(np.uint64) + np.uint64(circ.offset)  # offset binary, mod 2^64
        bits = np.arange(circ.bits, dtype=np.uint64)
        entry_bits = ((u[:, :, None] >> bits[None, None, :]) & np.uint64(1)).reshape(
            a.shape[0], -1
        )
        norms = (a.astype(object) ** 2).sum(axis=1)  # exact ints (b=64 > u64)
        assert all(int(n) < (1 << circ.norm_bits) for n in norms), "L2 norm must be < 1"
        norm_bits = np.array(
            [[(int(n) >> j) & 1 for j in range(circ.norm_bits)] for n in norms],
            dtype=np.uint64,
        )
        return np.concatenate([entry_bits, norm_bits], axis=1)

    def _interleaved_pairs(self, a, b, n_calls):
        """(a_i, b_i) pairs padded/reshaped to [batch, n_calls, 2*chunk]."""
        ch = self.circ.chunk_length
        pairs = fmap(
            lambda x, y: jnp.stack([x, y], axis=-1).reshape(x.shape[0], -1), a, b
        )
        pad = n_calls * ch * 2 - pairs[0].shape[-1]
        if pad:
            pairs = fmap(lambda x: jnp.pad(x, ((0, 0), (0, pad))), pairs)
        return fmap(lambda x: x.reshape(x.shape[0], n_calls, 2 * ch), pairs)

    def _entry_values(self, inp, shares_inv):
        """[batch, length] shares of v_e (offset split per share)."""
        jf = self.jf
        circ = self.circ
        # bits-major layout, same tiling rationale as BSumVec.truncate
        v = fmap(
            lambda x: jnp.swapaxes(
                x[:, : circ.length * circ.bits].reshape(
                    x.shape[0], circ.length, circ.bits
                ),
                1,
                2,
            ),
            inp,
        )
        two_pows = fmap(lambda w: w[:, None], _two_power_consts(jf, circ.bits))
        u = fsum(jf, jf.mul(v, two_pows), axis=1)
        off = fconst(jf, (circ.offset * shares_inv) % jf.MODULUS)
        return jf.sub(u, off)

    def calls_inputs(self, inp, joint_rand, shares_inv):
        jf = self.jf
        circ = self.circ
        r = fmap(lambda x: x[:, 0], joint_rand)
        pw = powers(jf, r, circ.n_bits + 1)
        rp = fmap(lambda x: x[..., 1:], pw)
        a = jf.mul(rp, inp)
        b = jf.sub(inp, self._sic(shares_inv))
        bit_calls = self._interleaved_pairs(a, b, circ.calls_bits)
        y = self._entry_values(inp, shares_inv)
        sq_calls = self._interleaved_pairs(y, y, circ.calls_sq)
        return fmap(lambda p, q: jnp.concatenate([p, q], axis=1), bit_calls, sq_calls)

    def finish(self, inp, joint_rand, gadget_outs, shares_inv):
        jf = self.jf
        circ = self.circ
        bit_check = fsum(
            jf, fmap(lambda x: x[:, : circ.calls_bits], gadget_outs), axis=-1
        )
        norm = fsum(jf, fmap(lambda x: x[:, circ.calls_bits :], gadget_outs), axis=-1)
        nb = fmap(lambda x: x[:, circ.length * circ.bits :], inp)
        claimed = fsum(jf, jf.mul(nb, _two_power_consts(jf, circ.norm_bits)), axis=-1)
        r1 = fmap(lambda x: x[:, 1], joint_rand)
        return jf.add(bit_check, jf.mul(r1, jf.sub(norm, claimed)))

    def truncate(self, inp):
        jf = self.jf
        circ = self.circ
        v = fmap(
            lambda x: x[:, : circ.length * circ.bits].reshape(
                x.shape[0], circ.length, circ.bits
            ),
            inp,
        )
        return fsum(jf, jf.mul(v, _two_power_consts(jf, circ.bits)), axis=-1)


_ADAPTERS = {
    Count: BCount,
    Sum: BSum,
    SumVec: BSumVec,
    # the sparse FLP is SumVec over the COMPACT encoding — the device
    # prepare/verify legs reuse BSumVec verbatim; only aggregation
    # differs (the scatter-merge kernel in aggregator.engine_cache)
    SparseSumVec: BSumVec,
    Histogram: BHistogram,
    FixedPointVec: BFixedPointVec,
}


def _two_power_consts(jf, bits: int):
    """[2^0, ..., 2^{bits-1}] mod p as a device field constant."""
    tp = np.array([pow(2, j, jf.MODULUS) for j in range(bits)], dtype=object)
    return tuple(
        jnp.asarray(((tp >> (64 * i)) & ((1 << 64) - 1)).astype(np.uint64))
        for i in range(jf.LIMBS)
    )


def _pow2_weighted_sum(jf, v, bits: int, axis: int = 1):
    """sum_b 2^b * v[:, b, ...] over a bits-major axis via shift-based
    const-muls (fmul_pow2) — replaces the generic jf.mul by
    _two_power_consts in the truncate paths (~5x fewer VPU ops; exact
    same field elements)."""
    acc = fmap(lambda x: jnp.take(x, 0, axis=axis), v)
    for b in range(1, bits):
        acc = jf.add(
            acc, fmul_pow2(jf, fmap(lambda x: jnp.take(x, b, axis=axis), v), b)
        )
    return acc


def batched_circuit(circ: Circuit) -> BatchedCircuit:
    return _ADAPTERS[type(circ)](circ)


# ---------------------------------------------------------------------------
# FLP prove / query / decide (batched)
# ---------------------------------------------------------------------------


def _wire_polys(bc: BatchedCircuit, seeds, ci):
    """Interpolate wire polynomials: [batch, arity, m] coefficients.

    seeds: [batch, arity] (prove rand or proof-share head); ci: calls
    inputs [batch, calls, arity]. Wire j's values on the NTT domain are
    [seed_j, ci[0][j], ..., ci[calls-1][j], 0...].
    """
    jf = bc.jf
    ci_t = fmap(lambda x: jnp.swapaxes(x, 1, 2), ci)  # [batch, arity, calls]
    evals = fmap(
        lambda s, c: jnp.concatenate([s[:, :, None], c], axis=-1), seeds, ci_t
    )
    if 1 + bc.calls < bc.m:
        pad = bc.m - (1 + bc.calls)
        evals = fmap(lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, pad))), evals)
    return intt_batched(jf, evals)


def flp_prove_batched(bc: BatchedCircuit, inp, prove_rand, joint_rand):
    """proof [batch, proof_len] matching reference.flp_prove element-wise."""
    jf = bc.jf
    ci = anti_recompute_barrier(bc.calls_inputs(inp, joint_rand, 1))
    wp = _wire_polys(bc, prove_rand, ci)
    wire_evals = ntt_batched(jf, wp, bc.n2)  # [batch, arity, n2]
    gadget_evals = bc.gadget_eval(wire_evals)  # [batch, n2]
    gpoly = intt_batched(jf, gadget_evals)
    gpoly = fmap(lambda x: x[..., : bc.gp_len], gpoly)
    return fmap(lambda s, g: jnp.concatenate([s, g], axis=-1), prove_rand, gpoly)


def _pick_eval_point(jf, cands, m: int):
    """First candidate t (of EVAL_POINT_CANDIDATES) with t^m != 1
    (branch-free draw; bound analysis SECURITY-NOTES.md #4)."""
    tm = fpow_const(jf, cands, m)  # [batch, 4]
    ok = ~is_zero(jf.sub(tm, fconst(jf, 1, tm[0].shape)))
    idx = jnp.argmax(ok, axis=-1)  # first True (0 if none; prob ~2^-128)
    return fmap(lambda x: jnp.take_along_axis(x, idx[:, None], axis=-1)[:, 0], cands)


def _query_proof_side(bc: BatchedCircuit, proof_share, query_rand):
    """Shared proof-share setup of every query variant: split
    seeds/gadget coefficients, pick the eval point t, evaluate the
    gadget polynomial at the call points, and compute t's Lagrange
    weights. Returns (seeds, gcoeffs, t, outs, pw, L0, Lc) — a single
    copy keeps the MM/fold/streamed paths bit-identical by
    construction."""
    jf = bc.jf
    seeds = fmap(lambda x: x[..., : bc.arity], proof_share)
    gcoeffs = fmap(lambda x: x[..., bc.arity : bc.arity + bc.gp_len], proof_share)
    assert query_rand[0].shape[-1] == EVAL_POINT_CANDIDATES
    t = anti_recompute_barrier(_pick_eval_point(jf, query_rand, bc.m))
    # gadget outputs at call points alpha^{k+1}: fold mod x^m - 1, NTT_m
    folds = -(-bc.gp_len // bc.m)
    padded = fmap(lambda x: jnp.pad(x, ((0, 0), (0, folds * bc.m - bc.gp_len))), gcoeffs)
    gfold = fsum(jf, fmap(lambda x: x.reshape(x.shape[0], folds, bc.m), padded), axis=1)
    gevals = ntt_batched(jf, gfold, bc.m)  # values at alpha^0..alpha^{m-1}
    outs = fmap(lambda x: x[..., 1 : bc.calls + 1], gevals)
    pw = anti_recompute_barrier(powers(jf, t, bc.gp_len))
    L = anti_recompute_barrier(lagrange_eval_weights(jf, pw, bc.m))
    L0 = fmap(lambda x: x[:, 0], L)
    Lc = fmap(lambda x: x[:, 1 : 1 + bc.calls], L)
    return seeds, gcoeffs, t, outs, pw, L0, Lc


def _chunked_wire_weights(bc: BatchedCircuit, Lc, r):
    """Per-call weight rows for the MXU wire fold of the
    ParallelSum(Mul, chunk) schedule.

    wire_t[2i]   = r^{i+1} * sum_call (L_call * r^{call*ch}) * X[call, i]
    wire_t[2i+1] =           sum_call  L_call               * X[call, i]
                   - shares_inv * (sum of L over calls whose position i
                                   is a real input element)

    Returns (w [batch, 2, n_calls], rc1 [batch, ch]) where n_calls is
    Lc's call axis (>= bc.calls when the streamed plan pads) and rc1 is
    r^1..r^ch. The decomposition r^{k+1} = r^{call*ch} * r^{i+1}
    replaces the O(input_len) power ladder of the fold path with
    O(calls + ch) muls.
    """
    jf = bc.jf
    ch = bc.circ.chunk_length
    n_calls = Lc[0].shape[-1]
    rc = anti_recompute_barrier(powers(jf, r, ch + 1))  # [batch, ch+1]
    rc1 = fmap(lambda x: x[:, 1:], rc)  # r^1..r^ch
    rch = fmap(lambda x: x[:, ch], rc)  # r^ch
    rpow_ch = anti_recompute_barrier(powers(jf, rch, n_calls))  # r^{call*ch}
    u0 = jf.mul(Lc, rpow_ch)
    w = fmap(lambda a, b: jnp.stack([a, b], axis=1), u0, Lc)
    return w, rc1


def _chunked_b_correction(bc: BatchedCircuit, Lc, shares_inv):
    """shares_inv * SL_i (see _chunked_wire_weights): SL for positions
    covered by every call, minus the last call's weight at padded
    positions (input_len is not a multiple of chunk)."""
    jf = bc.jf
    ch = bc.circ.chunk_length
    SL = fsum(jf, Lc, axis=-1)  # [batch]
    rem = bc.circ.input_len - (bc.calls - 1) * ch
    SLvec = fmap(lambda x: jnp.broadcast_to(x[:, None], (x.shape[0], ch)), SL)
    if rem < ch:
        L_last = fmap(lambda x: x[:, bc.calls - 1], Lc)
        SLpad = jf.sub(SL, L_last)
        mask = jnp.arange(ch) < rem
        SLvec = fwhere(
            mask[None, :],
            SLvec,
            fmap(lambda x: jnp.broadcast_to(x[:, None], (x.shape[0], ch)), SLpad),
        )
    return jf.mul(SLvec, fconst(jf, shares_inv))


def _chunked_X(bc: BatchedCircuit, inp_share):
    """[batch, input_len] share -> zero-padded [batch, calls, ch]."""
    ch = bc.circ.chunk_length
    pad = bc.calls * ch - bc.circ.input_len
    x = inp_share
    if pad:
        x = fmap(lambda v: jnp.pad(v, ((0, 0), (0, pad))), x)
    return fmap(lambda v: v.reshape(v.shape[0], bc.calls, ch), x)


def flp_query_batched(bc: BatchedCircuit, inp_share, proof_share, query_rand, joint_rand, num_shares: int):
    """verifier share [batch, verifier_len] matching reference.flp_query."""
    if _QUERY_MM and type(bc.circ) in (SumVec, SparseSumVec, Histogram):
        return _flp_query_batched_mm(
            bc, inp_share, proof_share, query_rand, joint_rand, num_shares
        )
    jf = bc.jf
    F = bc.circ.FIELD
    shares_inv = F.inv(num_shares)
    # the calls-inputs tensor is reused by the wire interpolation AND the
    # evaluation-at-t path; barrier so XLA shares it instead of
    # recomputing the (r-powers x input) products per consumer
    ci = anti_recompute_barrier(bc.calls_inputs(inp_share, joint_rand, shares_inv))
    seeds, gcoeffs, t, outs, pw, L0, Lc = _query_proof_side(bc, proof_share, query_rand)

    # Wire polys evaluated at t WITHOUT interpolating coefficients:
    # wire j's domain values are [seed_j, ci[*, j], 0...], so
    # wire_j(t) = seed_j*L_0(t) + sum_i ci[i, j]*L_{i+1}(t) with L the
    # Lagrange basis at t (= iNTT of t's powers, ops/ntt.py). Skips the
    # [batch, arity, m] wire-poly iNTT the host oracle does
    # (reference.flp_query:694-699); same field elements, and the peak
    # tensor drops from [batch, arity, m] to the [batch, calls, arity]
    # inputs — the len=100k memory win.
    prod = jf.mul(ci, fmap(lambda x: x[:, :, None], Lc))  # [batch, calls, arity]
    wire_t = jf.add(
        fsum(jf, prod, axis=1),
        jf.mul(seeds, fmap(lambda x: x[:, None], L0)),
    )  # [batch, arity]
    proof_t = poly_eval_powers(jf, gcoeffs, pw)  # [batch]

    v = bc.finish(inp_share, joint_rand, outs, shares_inv)  # [batch]
    return fmap(
        lambda a, b, c: jnp.concatenate([a[:, None], b, c[:, None]], axis=-1),
        v,
        wire_t,
        proof_t,
    )


def _flp_query_batched_mm(
    bc: BatchedCircuit, inp_share, proof_share, query_rand, joint_rand, num_shares: int
):
    """MXU twin of flp_query_batched for the ParallelSum(Mul, chunk)
    circuits (SumVec/Histogram): field-element identical (differential
    tested vs the fold path and the host oracle), but the O(input_len)
    wire fold runs as one limb-decomposed int8 matmul
    (ops/limbmm.fold_contract) instead of u64-emulated VPU multiplies.
    This is the round-5 answer to the instruction-mix headroom
    (BASELINE.md roofline): the contraction over gadget calls is where
    ~all the query's multiplies live, and the MXU does it at ~40x the
    VPU's integer rate. Replaces the reference's per-report CPU query
    (aggregation_job_driver.rs:329-402) at every chunked length.
    """
    from ..ops.limbmm import fold_contract

    jf = bc.jf
    F = bc.circ.FIELD
    shares_inv = F.inv(num_shares)
    batch = inp_share[0].shape[0]

    seeds, gcoeffs, t, outs, pw, L0, Lc = _query_proof_side(bc, proof_share, query_rand)

    r = fmap(lambda x: x[:, 0], joint_rand)
    w, rc1 = _chunked_wire_weights(bc, Lc, r)
    X = _chunked_X(bc, inp_share)
    Fw = fold_contract(jf, w, X)  # [batch, 2, ch]
    A = jf.mul(fmap(lambda x: x[:, 0], Fw), rc1)
    B = jf.sub(
        fmap(lambda x: x[:, 1], Fw), _chunked_b_correction(bc, Lc, shares_inv)
    )
    wire_t = fmap(lambda a, b: jnp.stack([a, b], axis=-1).reshape(batch, -1), A, B)
    wire_t = jf.add(wire_t, jf.mul(seeds, fmap(lambda x: x[:, None], L0)))
    proof_t = poly_eval_powers(jf, gcoeffs, pw)

    v = bc.finish(inp_share, joint_rand, outs, shares_inv)
    return fmap(
        lambda a, b, c: jnp.concatenate([a[:, None], b, c[:, None]], axis=-1),
        v,
        wire_t,
        proof_t,
    )


# ---------------------------------------------------------------------------
# Streamed FLP query + truncate (large-input circuits)
# ---------------------------------------------------------------------------

# Stream the query once the expanded share would dominate HBM: below
# this the whole-share path is faster (no scan sequentialization).
STREAM_MIN_INPUT_LEN = 1 << 17
# Fewer, larger scan steps since the MM query shrank the per-step
# working set: 8 steps halves the sequential scan overhead that was
# ~40% of helper_init at len=100k (r5 profile) at ~2x the transient
# per-step memory (still O(group)).
_STREAM_TARGET_STEPS = 8
# Hard cap on the per-step tile, in input-share ELEMENTS. The r5 plan
# sized the group as input_len/_STREAM_TARGET_STEPS — memory
# PROPORTIONAL, which is why len=100k (input_len 1.6M) could not reach
# the batch>=256 amortization knee inside the 15.75 GB v5e budget
# (ISSUE r6). With the cap the tile is FIXED at north-star lengths, so
# the scan's working set scales with batch x TILE no matter how long
# the measurement vector grows; extra length only adds scan steps
# (the nested-scan sponge already made long chains linear, r5).
# Floor: the tile must stay a multiple of the lcm(7, bits) x chunk
# alignment quantum (XOF block + truncate-grid alignment, stream_plan),
# so a chunk length coprime with the alignment floors the tile at
# a*ch elements even when that exceeds this clamp.
STREAM_TILE_ELEMS = int(os.environ.get("JANUS_STREAM_TILE", str(1 << 16)))


class StreamPlan:
    """Group geometry for the streamed query: the input is processed in
    `n_steps` scan steps of `gcalls` gadget calls (= `group` input
    elements) each. `group` is aligned to both the XOF block quantum
    (7 Field128 elements per 168-byte counter block) and `bits` (so
    SumVec truncate tiles never straddle a group)."""

    __slots__ = ("gcalls", "n_steps", "group", "bits")

    def __init__(self, gcalls: int, n_steps: int, group: int, bits: int):
        self.gcalls = gcalls
        self.n_steps = n_steps
        self.group = group
        self.bits = bits


def stream_plan(
    bc: BatchedCircuit,
    min_input_len: int | None = None,
    tile_elems: int | None = None,
) -> StreamPlan | None:
    """A StreamPlan for circuits worth streaming, else None.

    SumVec and Histogram only: their query consumes the expanded share
    as per-call folds, so it streams. (FixedPointVec's two-pass entry
    values could stream too but its deployed lengths don't need it;
    Count/Sum inputs are tiny.)

    The group (tile) size is min(input_len/_STREAM_TARGET_STEPS,
    tile_elems), alignment-rounded: short streams keep the measured
    8-step optimum, long streams clamp to the fixed tile so peak memory
    is length-independent (STREAM_TILE_ELEMS rationale above).
    """
    import math

    circ = bc.circ
    if type(circ) not in (SumVec, SparseSumVec, Histogram):
        return None
    if bc.jf.LIMBS != 2:
        return None  # block alignment below assumes 7 F128 elements/block
    if circ.input_len < (STREAM_MIN_INPUT_LEN if min_input_len is None else min_input_len):
        return None
    ch = circ.chunk_length
    bits = getattr(circ, "bits", 1)
    align = math.lcm(7, bits)
    a = align // math.gcd(align, ch)  # smallest gcalls with align | gcalls*ch
    tile = STREAM_TILE_ELEMS if tile_elems is None else tile_elems
    desired_calls = min(bc.calls / _STREAM_TARGET_STEPS, max(1.0, tile / ch))
    gcalls = a * max(1, round(desired_calls / a))
    n_steps = -(-bc.calls // gcalls)
    return StreamPlan(gcalls, n_steps, gcalls * ch, bits)


def describe_engine_geometry(bc: BatchedCircuit) -> dict:
    """Static geometry of a batched circuit for introspection
    (/statusz engine-cache section, bench riders): the tensor shapes
    that drive the HBM feasibility bound and the streamed-query plan,
    in one JSON-shaped dict."""
    circ = bc.circ
    plan = stream_plan(bc)
    return {
        "circuit": type(circ).__name__,
        "input_len": getattr(circ, "input_len", None),
        "output_len": getattr(circ, "output_len", None),
        "verifier_len": getattr(circ, "verifier_len", None),
        "gadget_calls": getattr(bc, "calls", None),
        "field_limbs": bc.jf.LIMBS,
        "stream_plan": (
            {
                "tile_elems": plan.group,
                "gcalls": plan.gcalls,
                "n_steps": plan.n_steps,
            }
            if plan is not None
            else None
        ),
    }


def sliced_meas_source(bc: BatchedCircuit, plan: StreamPlan, meas):
    """meas_source over a device-resident [batch, input_len] share
    (leader side): pad to the group grid once, dynamic-slice per step."""
    total = plan.n_steps * plan.group
    n = bc.circ.input_len
    meas = fpad_axis(meas, total - n) if total > n else meas

    def src(step):
        return ftile(meas, step, plan.group, axis=1)

    return src


def flp_query_streamed(
    bc: BatchedCircuit, plan: StreamPlan, meas_source, proof_share, query_rand, joint_rand, num_shares: int
):
    """Streamed twin of flp_query_batched, fused with truncate.

    meas_source(step) -> input-share elements [batch, group] for
    element range [step*group, (step+1)*group) (values beyond input_len
    are masked here). Returns (verifier, out_share) — field-element
    identical to (flp_query_batched(...), bc.truncate(meas)) (the fold
    order differs but field addition is exact mod p), with peak memory
    O(group) instead of O(input_len): the expanded share never fully
    materializes. This is what lifts the SumVec len=100k single-chip
    batch cap (BASELINE.md roofline: the limiter was HBM capacity).
    Replaces the reference's per-report query loop
    (aggregation_job_driver.rs:329-402) at north-star lengths.
    """
    jf = bc.jf
    circ = bc.circ
    F = circ.FIELD
    shares_inv = F.inv(num_shares)
    n = circ.input_len
    G = plan.group
    ch = circ.chunk_length
    gcalls = plan.gcalls
    batch = query_rand[0].shape[0]
    is_sumvec = isinstance(circ, SumVec)

    # --- proof-share side (small; shared with flp_query_batched) ---
    seeds, gcoeffs, t, outs, pw, L0, Lc = _query_proof_side(bc, proof_share, query_rand)
    # call weights zero-padded so tail calls beyond `calls` contribute 0
    padc = plan.n_steps * gcalls - bc.calls
    if padc:
        Lc = fpad_axis(Lc, padc)

    # --- streamed input-share folds ---
    r = fmap(lambda x: x[:, 0], joint_rand)
    s_const = fconst(jf, shares_inv)

    from ..fields.jfield import fput_tile, fzeros

    # truncate-output width of one step's tile: the scan accumulates
    # each step's contribution into a carried [batch, n_steps * gp]
    # buffer (fput_tile) instead of scan-stacked ys — the ys path emits
    # an s64-indexed dynamic_update_slice under x64 that the SPMD
    # partitioner rejects on a (dp, sp) mesh (fput_tile rationale).
    gp = G // plan.bits if is_sumvec else G

    if _QUERY_MM:
        # MXU form (see _flp_query_batched_mm): each step's fold is one
        # limb matmul over its gcalls; r-powers and the shares_inv
        # correction are applied once after the scan.
        from ..ops.limbmm import fold_contract

        w_full, rc1 = _chunked_wire_weights(bc, Lc, r)  # Lc is step-padded

        def body(carry, step):
            F0, F1, S, P = carry
            x = meas_source(step)  # [batch, G]
            mask = (step * G + jnp.arange(G)) < n  # [G]
            x = fmap(lambda v: jnp.where(mask[None, :], v, jnp.zeros_like(v)), x)
            Xg = freshape(x, (batch, gcalls, ch))
            wg = ftile(w_full, step, gcalls, axis=2)
            Fg = fold_contract(jf, wg, Xg)  # [batch, 2, ch]
            F0 = jf.add(F0, fmap(lambda v: v[:, 0], Fg))
            F1 = jf.add(F1, fmap(lambda v: v[:, 1], Fg))
            S = jf.add(S, fsum(jf, x, axis=-1))
            if is_sumvec:  # bits-major fold: out[e] = sum_b 2^b x_{e*bits+b}
                v = fmap(
                    lambda w: jnp.swapaxes(
                        w.reshape(batch, G // plan.bits, plan.bits), 1, 2
                    ),
                    x,
                )
                part = _pow2_weighted_sum(jf, v, plan.bits)
            else:  # histogram truncate is the identity
                part = x
            P = fput_tile(P, part, step)
            return (F0, F1, S, P), None

        init = (
            fzeros(jf, (batch, ch)),
            fzeros(jf, (batch, ch)),
            fzeros(jf, (batch,)),
            fzeros(jf, (batch, plan.n_steps * gp)),
        )
        carry, _ = jax.lax.scan(
            body, init, jnp.arange(plan.n_steps, dtype=jnp.int32)
        )
        F0, F1, S, parts = carry
        W0 = jf.mul(F0, rc1)
        W1 = jf.sub(F1, _chunked_b_correction(bc, Lc, shares_inv))
    else:
        rt = anti_recompute_barrier(powers(jf, r, G))  # [batch, G]: r^0..r^{G-1}
        rstep = fpow_const(jf, r, G)  # r^G
        two_pows = _two_power_consts(jf, plan.bits) if is_sumvec else None

        def body(carry, step):
            base, W0, W1, S, P = carry  # base = r^{step*G + 1}
            x = meas_source(step)  # [batch, G]
            mask = (step * G + jnp.arange(G)) < n  # [G]
            x = fmap(lambda v: jnp.where(mask[None, :], v, jnp.zeros_like(v)), x)
            # gadget wire pair (a, b) per element k: (r^{k+1} x_k, x_k - 1/shares)
            a = jf.mul(jf.mul(fmap(lambda v: v[:, None], base), rt), x)
            b = fmap(
                lambda v, z: jnp.where(mask[None, :], v, z),
                jf.sub(x, s_const),
                fzeros(jf, (batch, G)),
            )
            a_r = freshape(a, (batch, gcalls, ch))
            b_r = freshape(b, (batch, gcalls, ch))
            Lg = ftile(Lc, step, gcalls, axis=1)
            Lg3 = fmap(lambda v: v[:, :, None], Lg)
            W0 = jf.add(W0, fsum(jf, jf.mul(a_r, Lg3), axis=1))
            W1 = jf.add(W1, fsum(jf, jf.mul(b_r, Lg3), axis=1))
            S = jf.add(S, fsum(jf, x, axis=-1))
            if is_sumvec:  # bits-major fold: out[e] = sum_b 2^b x_{e*bits+b}
                v = fmap(
                    lambda w: jnp.swapaxes(w.reshape(batch, G // plan.bits, plan.bits), 1, 2), x
                )
                part = fsum(jf, jf.mul(v, fmap(lambda w: w[:, None], two_pows)), axis=1)
            else:  # histogram truncate is the identity
                part = x
            base = jf.mul(base, rstep)
            P = fput_tile(P, part, step)
            return (base, W0, W1, S, P), None

        init = (
            r,
            fzeros(jf, (batch, ch)),
            fzeros(jf, (batch, ch)),
            fzeros(jf, (batch,)),
            fzeros(jf, (batch, plan.n_steps * gp)),
        )
        carry, _ = jax.lax.scan(
            body, init, jnp.arange(plan.n_steps, dtype=jnp.int32)
        )
        _, W0, W1, S, parts = carry

    out_share = fmap(lambda v: v[:, : circ.output_len], parts)

    # wire_t interleaves (a, b) per chunk position: index 2c from W0[c]
    wire_t = fmap(lambda p, q: jnp.stack([p, q], axis=-1).reshape(batch, -1), W0, W1)
    wire_t = jf.add(wire_t, jf.mul(seeds, fmap(lambda x: x[:, None], L0)))
    proof_t = poly_eval_powers(jf, gcoeffs, pw)

    # circuit output v = bc.finish(...) without the full input tensor
    if is_sumvec:
        v = fsum(jf, outs, axis=-1)
    else:
        bit_check = fsum(jf, outs, axis=-1)
        sum_check = jf.sub(S, s_const)
        jr1 = fmap(lambda x: x[:, 1], joint_rand)
        v = jf.add(bit_check, jf.mul(jr1, sum_check))

    verifier = fmap(
        lambda a, b, c: jnp.concatenate([a[:, None], b, c[:, None]], axis=-1),
        v,
        wire_t,
        proof_t,
    )
    return verifier, out_share


def flp_decide_batched(bc: BatchedCircuit, verifier):
    """Boolean accept mask [batch] over combined verifier messages."""
    jf = bc.jf
    v0 = fmap(lambda x: x[:, 0], verifier)
    wires = fmap(lambda x: x[:, 1 : 1 + bc.arity], verifier)
    y = fmap(lambda x: x[:, 1 + bc.arity], verifier)
    circuit_ok = is_zero(v0)
    g = bc.gadget_eval(wires)
    gadget_ok = is_zero(jf.sub(g, y))
    return circuit_ok & gadget_ok
