"""Wire encodings for Prio3 shares + the ping-pong prepare protocol,
and columnar (de)serialization between wire bytes and device arrays.

Capability-equivalent of the reference's reliance on prio's codec for
input shares / prep shares / prep messages and
`topology::ping_pong::PingPongMessage` (SURVEY.md section 2.2). Field
vectors are little-endian fixed-width elements (Field.encode_vec);
seeds are 16 bytes.

Share payloads (inside HPKE plaintext / PlaintextInputShare.payload):
  leader: meas_share_vec || proof_share_vec || [blind 16B]
  helper: seed 16B || [blind 16B]
Public share: joint-rand parts part0 || part1 (or empty).

Ping-pong messages (PrepareInit.message / PrepareResp continue payload):
  initialize(0): u8 tag || opaque u32 prep_share
  continue  (1): u8 tag || opaque u32 prep_msg || opaque u32 prep_share
  finish    (2): u8 tag || opaque u32 prep_msg
Prep share: verifier_share_vec || [joint_rand_part 16B]
Prep message: [joint_rand_seed 16B]

The column codecs below convert whole report batches at once with
numpy (no per-report Python loops on the hot path).
"""

from __future__ import annotations

import numpy as np

from ..messages.codec import (
    PP_CONTINUE,
    PP_FINISH,
    PP_INITIALIZE,
    DecodeError,
    Decoder,
    Encoder,
)
from .prio3_jax import Prio3Batched
from .reference import (
    Circuit,
    SparsePublicShare,
    SparseSumVec,
    validate_block_indices,
)

SEED_SIZE = 16

# sparse block indices on the wire: one big-endian u32 per lane,
# 0xFFFFFFFF encoding the padding index -1
IDX_ENC_SIZE = 4
IDX_PADDING = 0xFFFFFFFF


def encode_block_indices(indices) -> bytes:
    """Front-packed block indices (-1 padding) -> the public-share
    prefix blob."""
    out = bytearray()
    for ix in indices:
        out += (IDX_PADDING if int(ix) == -1 else int(ix)).to_bytes(4, "big")
    return bytes(out)


def decode_block_indices(blob: bytes, circ: "SparseSumVec") -> tuple[int, ...]:
    """Reference decoder for the index blob: parse + the full
    `validate_block_indices` predicate. Raises DecodeError — the
    existing per-report/per-lane rejection plumbing at every
    decode_public_share call site (upload, leader staging, helper
    aggregate-init) handles sparse index rejection with no new code."""
    if len(blob) != circ.max_blocks * IDX_ENC_SIZE:
        raise DecodeError("bad sparse index blob length")
    raw = np.frombuffer(blob, dtype=">u4")
    indices = [-1 if int(v) == IDX_PADDING else int(v) for v in raw]
    reason = validate_block_indices(indices, circ.n_logical_blocks, circ.max_blocks)
    if reason is not None:
        raise DecodeError(f"invalid sparse block indices: {reason}")
    return tuple(indices)


def decode_index_columns(rows: list[bytes | None], circ: "SparseSumVec"):
    """Vectorized fast path of `decode_block_indices` over a batch of
    raw PUBLIC SHARE rows: -> ([n, max_blocks] int32 block indices
    (padding -1), ok mask). A row failing any predicate gets False and
    all-padding indices, landing the rejection on exactly that lane.
    Bit-equivalent to the reference decoder per row (pinned by the
    reject-divergence fuzz in tests/test_sparse_vdaf.py)."""
    n = len(rows)
    mb = circ.max_blocks
    blob_len = mb * IDX_ENC_SIZE
    lanes = np.zeros((n, mb), dtype=np.int64)
    ok = np.zeros(n, dtype=bool)
    for i, row in enumerate(rows):
        if row is None or len(row) < blob_len:
            continue
        lanes[i] = np.frombuffer(row[:blob_len], dtype=">u4").astype(np.int64)
        ok[i] = True
    pad = lanes == IDX_PADDING
    lanes = np.where(pad, np.int64(-1), lanes)
    in_range = pad | ((lanes >= 0) & (lanes < circ.n_logical_blocks))
    ok &= in_range.all(axis=1)
    if mb > 1:
        # strictly increasing over the non-padding prefix, and padding
        # only ever followed by padding
        both = ~pad[:, 1:] & ~pad[:, :-1]
        ok &= (~both | (lanes[:, 1:] > lanes[:, :-1])).all(axis=1)
        ok &= (~pad[:, :-1] | pad[:, 1:]).all(axis=1)
    lanes[~ok] = -1
    return lanes.astype(np.int32), ok


def flat_scatter_indices(block_idx: np.ndarray, circ: "SparseSumVec") -> np.ndarray:
    """[n, max_blocks] block indices -> [n, compact_len] int32 flat
    logical positions for the engine scatter kernel. Padding/rejected
    lanes map to the out-of-bounds sentinel `logical_length` (POSITIVE
    on purpose: a negative index would wrap under jnp scatter indexing
    instead of dropping)."""
    bs = circ.block_size
    L = circ.logical_length
    bi = np.asarray(block_idx, dtype=np.int64)
    flat = bi[:, :, None] * bs + np.arange(bs, dtype=np.int64)[None, None, :]
    flat = np.where(bi[:, :, None] < 0, np.int64(L), flat)
    return flat.reshape(bi.shape[0], -1).astype(np.int32)


# ---------------------------------------------------------------------------
# columnar field-vector codecs (numpy, whole-batch)
# ---------------------------------------------------------------------------


def field_rows_u8(jf, value) -> np.ndarray:
    """Device field value [batch, n] -> one uint8 matrix [batch, n*enc]
    of the per-row little-endian encodings (the whole-batch form behind
    encode_field_rows; the columnar framing passes splice it directly)."""
    if hasattr(value, "to_numpy"):  # engine_cache.DeviceRows
        value = value.to_numpy()
    limbs = [np.asarray(x, dtype=np.uint64) for x in value]
    if len(limbs) == 1:
        lanes = limbs[0]
    else:
        lanes = np.stack(limbs, axis=-1).reshape(limbs[0].shape[0], -1)
    le = np.ascontiguousarray(lanes.astype("<u8"))
    return le.view(np.uint8).reshape(le.shape[0], -1)


def encode_field_rows(jf, value) -> list[bytes]:
    """Device field value [batch, n] -> per-row little-endian encodings."""
    u8 = field_rows_u8(jf, value)
    return [row.tobytes() for row in u8]


def lanes_in_range(lanes: np.ndarray, modulus: int, limbs: int) -> np.ndarray:
    """Element-wise `value < modulus` over little-endian u64 lane arrays
    shaped [..., n*limbs]. Single home for the two-limb lexicographic
    compare so upload validation and driver staging can't diverge."""
    if limbs == 1:
        return lanes < np.uint64(modulus)
    r = lanes.reshape(lanes.shape[:-1] + (-1, 2))
    lo, hi = r[..., 0], r[..., 1]
    p_lo = np.uint64(modulus & 0xFFFFFFFFFFFFFFFF)
    p_hi = np.uint64(modulus >> 64)
    return (hi < p_hi) | ((hi == p_hi) & (lo < p_lo))


def decode_field_rows(jf, rows: list[bytes], n: int):
    """Per-row encodings -> host numpy limb tuple [batch, n] (validated).

    Returns (limb_arrays, ok_mask): rows failing length or range checks
    get a False mask lane and zeroed content (ragged-batch design,
    SURVEY.md section 7).
    """
    batch = len(rows)
    enc_size = 8 * jf.LIMBS
    lanes = np.zeros((batch, n * jf.LIMBS), dtype=np.uint64)
    ok = np.zeros(batch, dtype=bool)
    for i, row in enumerate(rows):
        if row is None or len(row) != n * enc_size:
            continue
        lanes[i] = np.frombuffer(row, dtype="<u8")
        ok[i] = True
    ok &= lanes_in_range(lanes, jf.MODULUS, jf.LIMBS).all(axis=-1)
    if jf.LIMBS == 1:
        limbs = (lanes,)
    else:
        r = lanes.reshape(batch, n, 2)
        limbs = (np.ascontiguousarray(r[:, :, 0]), np.ascontiguousarray(r[:, :, 1]))
    # zero out bad rows so device math stays in range
    for l in limbs:
        l[~ok] = 0
    return limbs, ok


def seeds_to_lanes(rows: list[bytes | None]) -> tuple[np.ndarray, np.ndarray]:
    """16-byte seed rows -> ([batch, 2] u64 lanes, ok mask)."""
    batch = len(rows)
    lanes = np.zeros((batch, 2), dtype=np.uint64)
    ok = np.zeros(batch, dtype=bool)
    for i, row in enumerate(rows):
        if row is not None and len(row) == SEED_SIZE:
            lanes[i] = np.frombuffer(row, dtype="<u8")
            ok[i] = True
    return lanes, ok


def lanes_to_seed_rows(lanes) -> list[bytes]:
    return [row.tobytes() for row in np.asarray(lanes, dtype="<u8")]


# ---------------------------------------------------------------------------
# columnar ping-pong framing (leader hot path)
# ---------------------------------------------------------------------------


class PingPongFrameColumn:
    """A whole batch of uniform-stride ping-pong frames in ONE buffer.

    The leader's init path frames every report's prep share with the
    same tag and the same length prefix (all prep shares of a batch are
    the same size), so the frames can be built in a single vectorized
    pass instead of one Encoder round per report. `row(i)` slices
    report i's frame out of the shared buffer — bit-identical to
    `encode_pingpong(tag, ..., share)` for that row (pinned by the
    codec-equivalence fuzz in tests/test_wire_columnar.py)."""

    __slots__ = ("buf", "stride", "n")

    def __init__(self, buf: bytes, stride: int, n: int):
        self.buf = buf
        self.stride = stride
        self.n = n

    def row(self, i: int) -> bytes:
        s = i * self.stride
        return self.buf[s : s + self.stride]

    def rows(self) -> list[bytes]:
        return [self.row(i) for i in range(self.n)]


def encode_pingpong_share_column(jf, ver_value, part_value) -> PingPongFrameColumn:
    """Batched `encode_pingpong(PP_INITIALIZE, None,
    encode_prep_share_raw(ver_row, part_row))`: one numpy pass building
    every report's framed prep share.

    ver_value: device/host field value [batch, verifier_len] (limb
    tuple or DeviceRows); part_value: [batch, 2] u64 joint-rand part
    lanes, or None for circuits without joint randomness."""
    ver_u8 = field_rows_u8(jf, ver_value)
    n = ver_u8.shape[0]
    cols = [ver_u8]
    share_len = ver_u8.shape[1]
    if part_value is not None:
        part_u8 = (
            np.ascontiguousarray(np.asarray(part_value, dtype="<u8"))
            .view(np.uint8)
            .reshape(n, -1)
        )
        cols.append(part_u8)
        share_len += part_u8.shape[1]
    # frame header: u8 tag || u32 big-endian share length — constant
    # across the batch, broadcast into the leading 5 columns
    hdr = np.empty((n, 5), dtype=np.uint8)
    hdr[:] = np.frombuffer(
        bytes([PP_INITIALIZE]) + share_len.to_bytes(4, "big"), dtype=np.uint8
    )
    mat = np.concatenate([hdr] + cols, axis=1)
    return PingPongFrameColumn(mat.tobytes(), 5 + share_len, n)


def pingpong_finish_frame_matches(frame: bytes, want_msg: bytes) -> bool | None:
    """Fast verify of a helper's 1-round answer against the expected
    prep message: True = frame is `finish(want_msg)`, False = a finish
    frame carrying a DIFFERENT message of the right length (VDAF prep
    error), None = not a well-formed finish-of-that-length frame at all
    (invalid message). `frame` must be exactly one self-delimiting
    ping-pong message (the response decoder guarantees this), so the
    check reduces to two bytes compares instead of a Decoder pass."""
    hdr = bytes([PP_FINISH]) + len(want_msg).to_bytes(4, "big")
    if len(frame) != len(hdr) + len(want_msg) or frame[: len(hdr)] != hdr:
        return None
    return frame[len(hdr) :] == want_msg


# ---------------------------------------------------------------------------
# scalar wire codecs (client side / message framing)
# ---------------------------------------------------------------------------


def encode_pingpong(tag: int, prep_msg: bytes | None, prep_share: bytes | None) -> bytes:
    enc = Encoder()
    enc.u8(tag)
    if tag == PP_INITIALIZE:
        enc.opaque_u32(prep_share)
    elif tag == PP_CONTINUE:
        enc.opaque_u32(prep_msg)
        enc.opaque_u32(prep_share)
    elif tag == PP_FINISH:
        enc.opaque_u32(prep_msg)
    else:
        raise ValueError(f"bad ping-pong tag {tag}")
    return enc.bytes()


def decode_pingpong(raw: bytes) -> tuple[int, bytes | None, bytes | None]:
    """-> (tag, prep_msg, prep_share); raises DecodeError."""
    dec = Decoder(raw)
    tag = dec.u8()
    if tag == PP_INITIALIZE:
        out = (tag, None, dec.opaque_u32())
    elif tag == PP_CONTINUE:
        out = (tag, dec.opaque_u32(), dec.opaque_u32())
    elif tag == PP_FINISH:
        out = (tag, dec.opaque_u32(), None)
    else:
        raise DecodeError(f"bad ping-pong tag {tag}")
    dec.finish()
    return out


class Prio3Wire:
    """Per-circuit sizes + scalar encoders (client path uses these)."""

    def __init__(self, circ: Circuit):
        self.circ = circ
        self.enc_size = circ.FIELD.ENCODED_SIZE
        self.uses_jr = circ.joint_rand_len > 0
        # sparse circuits prefix the public share with the PUBLIC block
        # indices (PREAMBLE trade-off: the sparsity pattern is
        # aggregator-visible; values stay secret-shared)
        self.sparse = isinstance(circ, SparseSumVec)
        self.idx_len = circ.max_blocks * IDX_ENC_SIZE if self.sparse else 0

    # sizes
    @property
    def leader_share_len(self) -> int:
        n = (self.circ.input_len + self.circ.proof_len) * self.enc_size
        return n + (SEED_SIZE if self.uses_jr else 0)

    @property
    def helper_share_len(self) -> int:
        return SEED_SIZE + (SEED_SIZE if self.uses_jr else 0)

    @property
    def public_share_len(self) -> int:
        return self.idx_len + (2 * SEED_SIZE if self.uses_jr else 0)

    @property
    def prep_share_len(self) -> int:
        return self.circ.verifier_len * self.enc_size + (SEED_SIZE if self.uses_jr else 0)

    @property
    def prep_msg_len(self) -> int:
        return SEED_SIZE if self.uses_jr else 0

    # scalar encoders (ints)
    def encode_leader_share(self, meas: list[int], proof: list[int], blind: bytes | None) -> bytes:
        F = self.circ.FIELD
        out = F.encode_vec(meas) + F.encode_vec(proof)
        if self.uses_jr:
            out += blind
        return out

    def encode_leader_share_raw(self, encoded_meas_proof: bytes, blind: bytes | None) -> bytes:
        """Column path: meas||proof row already encoded (encode_field_rows)."""
        return encoded_meas_proof + (blind if self.uses_jr else b"")

    def validate_leader_share(self, raw: bytes) -> None:
        """Length + field-range validation without scalar decoding.

        The upload handler only needs to know the share is well-formed
        (the stored payload is re-staged columnar by the driver); the
        full scalar decode of a 16k-element share costs ~100ms/report
        in Python and was the measured upload bottleneck. numpy checks
        the same conditions in microseconds."""
        if len(raw) != self.leader_share_len:
            raise DecodeError("bad leader share length")
        n = self.circ.input_len + self.circ.proof_len
        lanes = np.frombuffer(raw[: n * self.enc_size], dtype="<u8")
        limbs = self.enc_size // 8
        if not bool(lanes_in_range(lanes, self.circ.FIELD.MODULUS, limbs).all()):
            raise DecodeError("leader share element out of field range")

    def decode_leader_share(self, raw: bytes) -> tuple[list[int], list[int], bytes | None]:
        F = self.circ.FIELD
        n = self.circ.input_len * self.enc_size
        p = self.circ.proof_len * self.enc_size
        if len(raw) != self.leader_share_len:
            raise DecodeError("bad leader share length")
        meas = F.decode_vec(raw[:n])
        proof = F.decode_vec(raw[n : n + p])
        blind = raw[n + p :] if self.uses_jr else None
        return meas, proof, blind

    def encode_helper_share(self, seed: bytes, blind: bytes | None) -> bytes:
        return seed + (blind if self.uses_jr else b"")

    def decode_helper_share(self, raw: bytes) -> tuple[bytes, bytes | None]:
        if len(raw) != self.helper_share_len:
            raise DecodeError("bad helper share length")
        return raw[:SEED_SIZE], (raw[SEED_SIZE:] if self.uses_jr else None)

    def encode_public_share(self, parts: list[bytes]) -> bytes:
        if self.sparse:
            indices = getattr(parts, "indices", None)
            if indices is None:
                raise ValueError(
                    "sparse public share needs block indices: pass the "
                    "SparsePublicShare from Prio3Sparse.shard"
                )
            blob = encode_block_indices(indices)
            return blob + (b"".join(parts) if self.uses_jr else b"")
        return b"".join(parts) if self.uses_jr else b""

    def decode_public_share(self, raw: bytes) -> list[bytes]:
        if len(raw) != self.public_share_len:
            raise DecodeError("bad public share length")
        if self.sparse:
            indices = decode_block_indices(raw[: self.idx_len], self.circ)
            rest = raw[self.idx_len :]
            parts = [rest[:SEED_SIZE], rest[SEED_SIZE:]] if self.uses_jr else []
            return SparsePublicShare(parts, indices)
        if not self.uses_jr:
            return []
        return [raw[:SEED_SIZE], raw[SEED_SIZE:]]

    def encode_prep_share_raw(self, verifier_bytes: bytes, part: bytes | None) -> bytes:
        """Column path: verifier row already encoded (encode_field_rows)."""
        return verifier_bytes + (part if self.uses_jr else b"")

    def encode_prep_share(self, verifier: list[int], part: bytes | None) -> bytes:
        out = self.circ.FIELD.encode_vec(verifier)
        if self.uses_jr:
            out += part
        return out

    def decode_prep_share(self, raw: bytes) -> tuple[list[int], bytes | None]:
        if len(raw) != self.prep_share_len:
            raise DecodeError("bad prep share length")
        n = self.circ.verifier_len * self.enc_size
        verifier = self.circ.FIELD.decode_vec(raw[:n])
        return verifier, (raw[n:] if self.uses_jr else None)


def split_prep_share_columns(wire: Prio3Wire, jf, rows: list[bytes | None]):
    """Batch of encoded prep shares -> (verifier limbs, part lanes, ok).

    Used by the helper to stage the leader's prep shares
    (PrepareInit.message payloads) into device arrays.
    """
    vlen = wire.circ.verifier_len
    vbytes = vlen * wire.enc_size
    ver_rows: list[bytes | None] = []
    part_rows: list[bytes | None] = []
    for row in rows:
        if row is None or len(row) != wire.prep_share_len:
            ver_rows.append(None)
            part_rows.append(None)
            continue
        ver_rows.append(row[:vbytes])
        part_rows.append(row[vbytes:] if wire.uses_jr else b"\x00" * SEED_SIZE)
    limbs, ok = decode_field_rows(jf, ver_rows, vlen)
    if wire.uses_jr:
        part_lanes, ok2 = seeds_to_lanes(part_rows)
        ok = ok & ok2
    else:
        part_lanes = np.zeros((len(rows), 2), dtype=np.uint64)
    return limbs, part_lanes, ok
