"""Poplar1: heavy-hitters VDAF over an incremental DPF.

Capability parity with the reference's declared
`Poplar1<XofShake128, 16>` (aggregator/src/aggregator.rs:1096,
core/src/task.rs Poplar1 variant). In the reference it is constructed
but unreachable end-to-end because nontrivial aggregation parameters
are unsupported in the DAP flow (README.md:9-11;
`VdafHasAggregationParameter` marker, aggregator_core/src/lib.rs:44).
Here the VDAF itself is fully implemented and tested host-side —
shard / prepare (with the sketch check) / aggregate / unshard over
arbitrary prefix queries — and the DAP aggregator applies the same
nontrivial-agg-param gate as the reference.

Design (draft-irtf-cfrg-vdaf Poplar1, re-derived):

- **IDPF**: an incremental distributed point function over a bit
  string alpha of length `bits`. Two key shares; evaluated at any
  prefix p, the two parties' outputs sum to (beta_level if p is a
  prefix of alpha else 0). Each tree level's value is a vector
  (1, alpha_extra) in a level field: inner levels use Field64,
  the leaf level Field128 (the draft's field split).
- **Sketch**: the draft's full quadratic sketch with client-supplied
  correlated randomness. Per level the client provides additive shares
  of random (a, b) and of c = a^2 + b (leader explicit, helper derived
  from a seed). With verify-randomness r_p per queried prefix (derived
  by the aggregators from the shared verify key + report nonce, so the
  client cannot predict it), the aggregators reveal the masked sums
  A = a + SUM r_p y_p and B = b + SUM r_p^2 y_p, then exchange shares
  of sigma = A^2 - B - 2*A*a + c  (= Z^2 - W for Z = SUM r_p y_p,
  W = SUM r_p^2 y_p) and accept iff sigma == 0. This holds exactly when
  y is all-zero (pruned path) or one-hot with value 1; a forged vector
  like (2, -1, 0, ...) — which passes a bare sum check — makes
  sigma = 2(r_0 - r_1)^2 != 0 w.h.p. (tested in test_poplar1.py).
- **Aggregation parameter**: (level, list of prefixes). The collector
  walks levels, keeping heavy prefixes — the classic Poplar
  heavy-hitters loop (tested in test_poplar1.py).

XOF: the project-wide SHAKE128 XOF (vdaf/xof.py) with Poplar1's
algorithm id for domain separation.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..fields.field import Field64, Field128
from .reference import VdafError
from .xof import SEED_SIZE, dst, prng_expand
from .xof import XofShake128

ALGO_ID = 0x00001000  # matches the reference's declared codepoint

USAGE_CONVERT = 5
USAGE_EXTEND = 6
USAGE_VERIFY_RAND = 7
USAGE_CORR_RAND = 8
# Domain separation for the convert VALUE vector lives in the usage id
# (not a binder): every XOF prefix stays lane-aligned, which is what
# lets the batched device walk (poplar1_jax) share the single-block
# counter-mode Keccak machinery.
USAGE_CONVERT_VALUE = 9


def _xof_vec(field, seed: bytes, usage: int, binder: bytes, length: int):
    return prng_expand(field, seed, dst(ALGO_ID, usage), binder, length)


def _extend(seed: bytes) -> tuple[bytes, int, bytes, int]:
    """One IDPF tree step: seed -> (seed_L, bit_L, seed_R, bit_R)."""
    out = XofShake128(seed, dst(ALGO_ID, USAGE_EXTEND)).next(2 * SEED_SIZE + 2)
    return (
        out[:SEED_SIZE],
        out[2 * SEED_SIZE] & 1,
        out[SEED_SIZE : 2 * SEED_SIZE],
        out[2 * SEED_SIZE + 1] & 1,
    )


def _convert(field, seed: bytes, length: int) -> tuple[bytes, list[int]]:
    """Seed -> (next seed, value vector) in the level's field."""
    nxt = XofShake128.derive_seed(seed, dst(ALGO_ID, USAGE_CONVERT), b"")
    return nxt, _xof_vec(field, seed, USAGE_CONVERT_VALUE, b"", length)


@dataclass
class IdpfKey:
    """One party's IDPF key: root seed + per-level correction words +
    the sketch's correlated randomness (leader: explicit per-level
    (a, b, c) shares; helper: a 16-byte seed they derive from)."""

    root_seed: bytes
    # per level: (seed_cw, bit_cw_L, bit_cw_R, value_cw)
    correction_words: list
    # leader (party 0): list of per-level (a_share, b_share, c_share);
    # helper (party 1): 16-byte corr seed. None only for legacy tests.
    corr: object = None


def corr_from_seed(bits: int, corr_seed: bytes, level: int):
    """The helper's per-level (a, b, c) share, derived from its seed."""
    F = Field128 if level == bits - 1 else Field64
    vec = _xof_vec(F, corr_seed, USAGE_CORR_RAND, level.to_bytes(2, "big"), 3)
    return tuple(vec)


def verify_rand(bits: int, verify_key: bytes, nonce: bytes, param: "Poplar1AggParam"):
    """Per-prefix sketch randomness r_p, shared by both aggregators and
    unpredictable to the client: XOF(verify_key, nonce || level ||
    H(prefixes))."""
    import hashlib

    F = Field128 if param.level == bits - 1 else Field64
    binder = (
        nonce
        + param.level.to_bytes(2, "big")
        + hashlib.sha256(b"".join(p.to_bytes(16, "big") for p in param.prefixes)).digest()
    )
    return _xof_vec(F, verify_key, USAGE_VERIFY_RAND, binder, len(param.prefixes))


class Idpf:
    """2-party incremental DPF (the draft's IDPF with 2-element values:
    [count, weighted payload]); inner levels over Field64, leaf level
    over Field128."""

    VALUE_LEN = 2

    def __init__(self, bits: int):
        assert 1 <= bits <= 128
        self.bits = bits

    def field_at(self, level: int):
        return Field128 if level == self.bits - 1 else Field64

    def gen(self, alpha: int, beta_inner: list[int] | None = None, beta_leaf: int | None = None):
        """-> (public [shared correction words], key0, key1).

        Values programmed per level: [1, beta] where beta defaults to 1.
        """
        assert 0 <= alpha < (1 << self.bits)
        seed = [secrets.token_bytes(SEED_SIZE), secrets.token_bytes(SEED_SIZE)]
        ctrl = [0, 1]
        root = (seed[0], seed[1])
        cws = []
        for level in range(self.bits):
            F = self.field_at(level)
            bit = (alpha >> (self.bits - 1 - level)) & 1
            s0 = _extend(seed[0])
            s1 = _extend(seed[1])
            # (seed_L, t_L, seed_R, t_R) per party
            keep, lose = (2, 0) if bit else (0, 2)  # index into tuples
            seed_cw = bytes(a ^ b for a, b in zip(s0[lose], s1[lose]))
            t_cw_l = s0[1] ^ s1[1] ^ bit ^ 1
            t_cw_r = s0[3] ^ s1[3] ^ bit
            new_seed = []
            new_ctrl = []
            for p, s in ((0, s0), (1, s1)):
                ks, kt = s[keep], s[keep + 1]
                if ctrl[p]:
                    ks = bytes(a ^ b for a, b in zip(ks, seed_cw))
                    kt ^= t_cw_l if bit == 0 else t_cw_r
                new_seed.append(ks)
                new_ctrl.append(kt)
            # value correction for this level
            conv = []
            next_seed = []
            for p in (0, 1):
                ns, vec = _convert(F, new_seed[p], self.VALUE_LEN)
                conv.append(vec)
                next_seed.append(ns)
            beta = 1
            if level == self.bits - 1 and beta_leaf is not None:
                beta = beta_leaf
            elif beta_inner is not None and level < self.bits - 1:
                beta = beta_inner[level]
            want = [1, beta]
            # W_cw = (-1)^{t1} * (want - conv0 + conv1): the on-path party
            # holding ctrl=1 adds W_cw, party 1 negates its whole share
            sign = F.MODULUS - 1 if new_ctrl[1] else 1
            value_cw = [
                F.mul(sign, F.add(F.sub(w, conv[0][i]), conv[1][i]))
                for i, w in enumerate(want)
            ]
            cws.append((seed_cw, t_cw_l, t_cw_r, value_cw))
            seed = next_seed
            ctrl = new_ctrl
        return cws, IdpfKey(root[0], cws), IdpfKey(root[1], cws)

    def eval_prefixes(self, party: int, key: IdpfKey, level: int, prefixes: list[int]):
        """Evaluate this party's share at each prefix of bit-length
        level+1; returns [len(prefixes)][VALUE_LEN] field shares."""
        F = self.field_at(level)
        out = []
        for p in prefixes:
            share = self._eval_one(party, key, level, p)
            out.append(share)
        return out

    def _eval_one(self, party: int, key: IdpfKey, level: int, prefix: int):
        seed = key.root_seed
        ctrl = party  # party 1 starts with control bit 1
        value = None
        for lvl in range(level + 1):
            F = self.field_at(lvl)
            bit = (prefix >> (level - lvl)) & 1
            seed_cw, t_cw_l, t_cw_r, value_cw = key.correction_words[lvl]
            sl, tl, sr, tr = _extend(seed)
            if ctrl:
                sl = bytes(a ^ b for a, b in zip(sl, seed_cw))
                sr = bytes(a ^ b for a, b in zip(sr, seed_cw))
                tl ^= t_cw_l
                tr ^= t_cw_r
            seed, ctrl = (sr, tr) if bit else (sl, tl)
            seed, vec = _convert(F, seed, self.VALUE_LEN)
            if lvl == level:
                value = list(vec)
                if ctrl:
                    value = [F.add(v, cw) for v, cw in zip(value, value_cw)]
                if party == 1:
                    value = [F.neg(v) for v in value]
        return value


@dataclass
class Poplar1AggParam:
    level: int
    prefixes: tuple[int, ...]

    def encode(self) -> bytes:
        import struct

        out = struct.pack(">HI", self.level, len(self.prefixes))
        for p in self.prefixes:
            out += p.to_bytes(16, "big")
        return out

    @classmethod
    def decode(cls, raw: bytes) -> "Poplar1AggParam":
        import struct

        level, n = struct.unpack(">HI", raw[:6])
        prefixes = tuple(
            int.from_bytes(raw[6 + 16 * i : 22 + 16 * i], "big") for i in range(n)
        )
        return cls(level, prefixes)


@dataclass
class _PrepState:
    field: object
    y_shares: list  # per-prefix count share
    party: int
    a_share: int  # correlated-randomness shares for this level
    c_share: int
    sigma_share: int | None = None  # set after prepare_next


class Poplar1:
    """Host Poplar1: shard / prepare (quadratic sketch, 2 exchange
    rounds) / aggregate / unshard.

    Two aggregators (leader=0, helper=1). Round 1 reveals the masked
    sums (A, B); round 2 reveals sigma = Z^2 - W (module docstring),
    which is 0 iff the y vector is all-zero or one-hot with value 1.
    """

    NUM_SHARES = 2

    def __init__(self, bits: int):
        self.bits = bits
        self.idpf = Idpf(bits)

    # --- client ---
    def shard(self, measurement: int):
        """measurement: the alpha bit string as an int < 2^bits.

        Key 0 (leader) carries explicit per-level (a, b, c) correlated-
        randomness shares; key 1 (helper) derives its shares from a
        seed — constant wire size for the helper, like the draft."""
        cws, k0, k1 = self.idpf.gen(measurement)
        corr_seed = secrets.token_bytes(SEED_SIZE)
        leader_corr = []
        for level in range(self.bits):
            F = self.idpf.field_at(level)
            a = int.from_bytes(secrets.token_bytes(16), "big") % F.MODULUS
            b = int.from_bytes(secrets.token_bytes(16), "big") % F.MODULUS
            c = F.add(F.mul(a, a), b)  # c = a^2 + b
            a1, b1, c1 = corr_from_seed(self.bits, corr_seed, level)
            leader_corr.append((F.sub(a, a1), F.sub(b, b1), F.sub(c, c1)))
        k0.corr = leader_corr
        k1.corr = corr_seed
        return cws, (k0, k1)

    def _corr_at(self, party: int, key: IdpfKey, level: int):
        if party == 0:
            return key.corr[level]
        return corr_from_seed(self.bits, key.corr, level)

    # --- aggregator ---
    def prepare_init(
        self, party: int, key: IdpfKey, agg_param: Poplar1AggParam,
        verify_key: bytes = b"\x00" * SEED_SIZE, nonce: bytes = b"",
    ):
        """-> (state, round-1 message [A_share, B_share])."""
        F = self.idpf.field_at(agg_param.level)
        vals = self.idpf.eval_prefixes(party, key, agg_param.level, list(agg_param.prefixes))
        y = [v[0] for v in vals]
        r = verify_rand(self.bits, verify_key, nonce, agg_param)
        z = 0  # share of Z = SUM r_p y_p
        w = 0  # share of W = SUM r_p^2 y_p
        for rp, yp in zip(r, y):
            z = F.add(z, F.mul(rp, yp))
            w = F.add(w, F.mul(F.mul(rp, rp), yp))
        a_sh, b_sh, c_sh = self._corr_at(party, key, agg_param.level)
        state = _PrepState(F, y, party, a_sh, c_sh)
        return state, [F.add(z, a_sh), F.add(w, b_sh)]

    def prepare_next(self, state: _PrepState, round1_msgs: list[list[int]]):
        """Combine round-1 messages -> (state, round-2 msg [sigma_share])."""
        F = state.field
        A = 0
        B = 0
        for m in round1_msgs:
            A = F.add(A, m[0])
            B = F.add(B, m[1])
        sigma = F.sub(F.mul(2 % F.MODULUS, F.mul(A, state.a_share)), state.c_share)
        sigma = F.neg(sigma)  # -2*A*a_share + c_share
        if state.party == 0:
            sigma = F.add(sigma, F.sub(F.mul(A, A), B))
        state.sigma_share = sigma
        return state, [sigma]

    def prepare_finish(self, state: _PrepState, round2_msgs: list[list[int]]):
        F = state.field
        sigma = 0
        for m in round2_msgs:
            sigma = F.add(sigma, m[0])
        # sigma = Z^2 - W: zero iff y is all-zero (pruned path) or
        # one-hot with value 1
        if sigma != 0:
            raise VdafError("poplar1 sketch failed: y is not one-hot")
        return state.y_shares

    # --- aggregation ---
    def aggregate(self, agg_param: Poplar1AggParam, out_shares: list[list[int]]):
        F = self.idpf.field_at(agg_param.level)
        agg = [0] * len(agg_param.prefixes)
        for share in out_shares:
            agg = [F.add(a, b) for a, b in zip(agg, share)]
        return agg

    def unshard(self, agg_param: Poplar1AggParam, agg_shares: list[list[int]]):
        F = self.idpf.field_at(agg_param.level)
        agg = [0] * len(agg_param.prefixes)
        for share in agg_shares:
            agg = [F.add(a, b) for a, b in zip(agg, share)]
        return [int(x) for x in agg]


# ---------------------------------------------------------------------------
# DAP wire codecs (public share = correction words; input share = root
# seed). The reference declares Poplar1 but cannot drive it through DAP
# (nontrivial aggregation parameters unsupported, README.md:9-11);
# these codecs + the aggregator's agg-param plumbing make it reachable
# here.
# ---------------------------------------------------------------------------


def encode_public_share(bits: int, cws: list) -> bytes:
    """Correction words: per level seed_cw(16) || ctrl byte(t_l<<1|t_r)
    || value_cw elements (2, level field, fixed width)."""
    idpf = Idpf(bits)
    out = bytearray()
    for level, (seed_cw, t_l, t_r, value_cw) in enumerate(cws):
        F = idpf.field_at(level)
        out += seed_cw
        out.append((t_l << 1) | t_r)
        for v in value_cw:
            out += int(v).to_bytes(F.ENCODED_SIZE, "little")
    return bytes(out)


def decode_public_share(bits: int, raw: bytes) -> list:
    idpf = Idpf(bits)
    cws = []
    off = 0
    for level in range(bits):
        F = idpf.field_at(level)
        if off + SEED_SIZE + 1 + 2 * F.ENCODED_SIZE > len(raw):
            raise ValueError("poplar1 public share truncated")
        seed_cw = raw[off : off + SEED_SIZE]
        off += SEED_SIZE
        ctrl = raw[off]
        off += 1
        if ctrl > 3:
            raise ValueError("poplar1 public share bad control byte")
        value_cw = []
        for _ in range(Idpf.VALUE_LEN):
            v = int.from_bytes(raw[off : off + F.ENCODED_SIZE], "little")
            if v >= F.MODULUS:
                raise ValueError("poplar1 correction word out of range")
            value_cw.append(v)
            off += F.ENCODED_SIZE
        cws.append((seed_cw, (ctrl >> 1) & 1, ctrl & 1, value_cw))
    if off != len(raw):
        raise ValueError("poplar1 public share trailing bytes")
    return cws


def _leader_corr_size(bits: int) -> int:
    idpf = Idpf(bits)
    return sum(3 * idpf.field_at(level).ENCODED_SIZE for level in range(bits))


def encode_input_share(key: IdpfKey, party: int, bits: int) -> bytes:
    """Party 0: root_seed || per-level explicit (a, b, c) shares;
    party 1: root_seed || corr_seed."""
    if party == 1:
        return key.root_seed + key.corr
    idpf = Idpf(bits)
    out = bytearray(key.root_seed)
    for level, (a, b, c) in enumerate(key.corr):
        es = idpf.field_at(level).ENCODED_SIZE
        for v in (a, b, c):
            out += int(v).to_bytes(es, "little")
    return bytes(out)


def decode_input_share(bits: int, cws: list, raw: bytes, party: int) -> IdpfKey:
    if party == 1:
        if len(raw) != 2 * SEED_SIZE:
            raise ValueError("poplar1 helper input share must be root seed + corr seed")
        return IdpfKey(raw[:SEED_SIZE], cws, corr=raw[SEED_SIZE:])
    if len(raw) != SEED_SIZE + _leader_corr_size(bits):
        raise ValueError("poplar1 leader input share length mismatch")
    idpf = Idpf(bits)
    corr = []
    off = SEED_SIZE
    for level in range(bits):
        F = idpf.field_at(level)
        es = F.ENCODED_SIZE
        vals = []
        for _ in range(3):
            v = int.from_bytes(raw[off : off + es], "little")
            if v >= F.MODULUS:
                raise ValueError("poplar1 correlated randomness out of range")
            vals.append(v)
            off += es
        corr.append(tuple(vals))
    return IdpfKey(raw[:SEED_SIZE], cws, corr=corr)


def heavy_hitters(
    poplar: Poplar1, keys0, keys1, threshold: int, verify_key: bytes = b"\x00" * SEED_SIZE
) -> list[int]:
    """The classic Poplar loop: walk levels keeping prefixes whose count
    reaches the threshold; returns the heavy alpha values."""
    prefixes = [0, 1]
    for level in range(poplar.bits):
        agg_param = Poplar1AggParam(level, tuple(prefixes))
        out0, out1 = [], []
        for i, (k0, k1) in enumerate(zip(keys0, keys1)):
            nonce = i.to_bytes(16, "big")
            st0, m0 = poplar.prepare_init(0, k0, agg_param, verify_key, nonce)
            st1, m1 = poplar.prepare_init(1, k1, agg_param, verify_key, nonce)
            st0, s0 = poplar.prepare_next(st0, [m0, m1])
            st1, s1 = poplar.prepare_next(st1, [m0, m1])
            out0.append(poplar.prepare_finish(st0, [s0, s1]))
            out1.append(poplar.prepare_finish(st1, [s0, s1]))
        counts = poplar.unshard(
            agg_param,
            [poplar.aggregate(agg_param, out0), poplar.aggregate(agg_param, out1)],
        )
        survivors = [p for p, c in zip(prefixes, counts) if c >= threshold]
        if level == poplar.bits - 1:
            return survivors
        prefixes = [p << 1 for p in survivors] + [(p << 1) | 1 for p in survivors]
        prefixes.sort()
    return []
