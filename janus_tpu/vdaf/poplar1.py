"""Poplar1: heavy-hitters VDAF over an incremental DPF.

Capability parity with the reference's declared
`Poplar1<XofShake128, 16>` (aggregator/src/aggregator.rs:1096,
core/src/task.rs Poplar1 variant). In the reference it is constructed
but unreachable end-to-end because nontrivial aggregation parameters
are unsupported in the DAP flow (README.md:9-11;
`VdafHasAggregationParameter` marker, aggregator_core/src/lib.rs:44).
Here the VDAF itself is fully implemented and tested host-side —
shard / prepare (with the sketch check) / aggregate / unshard over
arbitrary prefix queries — and the DAP aggregator applies the same
nontrivial-agg-param gate as the reference.

Design (draft-irtf-cfrg-vdaf Poplar1, re-derived):

- **IDPF**: an incremental distributed point function over a bit
  string alpha of length `bits`. Two key shares; evaluated at any
  prefix p, the two parties' outputs sum to (beta_level if p is a
  prefix of alpha else 0). Each tree level's value is a vector
  (1, alpha_extra) in a level field: inner levels use Field64,
  the leaf level Field128 (the draft's field split).
- **Sketch**: one exchange of masked sums verifying
  sum_p y_p == 1 over the queried prefixes — a linear sketch that
  rejects malformed multi-path keys against covert clients. (The
  draft's full quadratic sketch with client-supplied correlated
  randomness also bounds each y_p to {0,1} against fully malicious
  clients; that strengthening is noted as future work and does not
  change any interface here.)
- **Aggregation parameter**: (level, list of prefixes). The collector
  walks levels, keeping heavy prefixes — the classic Poplar
  heavy-hitters loop (tested in test_poplar1.py).

XOF: the project-wide SHAKE128 XOF (vdaf/xof.py) with Poplar1's
algorithm id for domain separation.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..fields.field import Field64, Field128
from .reference import VdafError
from .xof import SEED_SIZE, dst, prng_expand
from .xof import XofShake128

ALGO_ID = 0x00001000  # matches the reference's declared codepoint

USAGE_CONVERT = 5
USAGE_EXTEND = 6


def _xof_vec(field, seed: bytes, usage: int, binder: bytes, length: int):
    return prng_expand(field, seed, dst(ALGO_ID, usage), binder, length)


def _extend(seed: bytes) -> tuple[bytes, int, bytes, int]:
    """One IDPF tree step: seed -> (seed_L, bit_L, seed_R, bit_R)."""
    out = XofShake128(seed, dst(ALGO_ID, USAGE_EXTEND)).next(2 * SEED_SIZE + 2)
    return (
        out[:SEED_SIZE],
        out[2 * SEED_SIZE] & 1,
        out[SEED_SIZE : 2 * SEED_SIZE],
        out[2 * SEED_SIZE + 1] & 1,
    )


def _convert(field, seed: bytes, length: int) -> tuple[bytes, list[int]]:
    """Seed -> (next seed, value vector) in the level's field."""
    nxt = XofShake128.derive_seed(seed, dst(ALGO_ID, USAGE_CONVERT), b"")
    return nxt, _xof_vec(field, seed, USAGE_CONVERT, b"next", length)


@dataclass
class IdpfKey:
    """One party's IDPF key: root seed + per-level correction words."""

    root_seed: bytes
    # per level: (seed_cw, bit_cw_L, bit_cw_R, value_cw)
    correction_words: list


class Idpf:
    """2-party incremental DPF (the draft's IDPF with 2-element values:
    [count, weighted payload]); inner levels over Field64, leaf level
    over Field128."""

    VALUE_LEN = 2

    def __init__(self, bits: int):
        assert 1 <= bits <= 128
        self.bits = bits

    def field_at(self, level: int):
        return Field128 if level == self.bits - 1 else Field64

    def gen(self, alpha: int, beta_inner: list[int] | None = None, beta_leaf: int | None = None):
        """-> (public [shared correction words], key0, key1).

        Values programmed per level: [1, beta] where beta defaults to 1.
        """
        assert 0 <= alpha < (1 << self.bits)
        seed = [secrets.token_bytes(SEED_SIZE), secrets.token_bytes(SEED_SIZE)]
        ctrl = [0, 1]
        root = (seed[0], seed[1])
        cws = []
        for level in range(self.bits):
            F = self.field_at(level)
            bit = (alpha >> (self.bits - 1 - level)) & 1
            s0 = _extend(seed[0])
            s1 = _extend(seed[1])
            # (seed_L, t_L, seed_R, t_R) per party
            keep, lose = (2, 0) if bit else (0, 2)  # index into tuples
            seed_cw = bytes(a ^ b for a, b in zip(s0[lose], s1[lose]))
            t_cw_l = s0[1] ^ s1[1] ^ bit ^ 1
            t_cw_r = s0[3] ^ s1[3] ^ bit
            new_seed = []
            new_ctrl = []
            for p, s in ((0, s0), (1, s1)):
                ks, kt = s[keep], s[keep + 1]
                if ctrl[p]:
                    ks = bytes(a ^ b for a, b in zip(ks, seed_cw))
                    kt ^= t_cw_l if bit == 0 else t_cw_r
                new_seed.append(ks)
                new_ctrl.append(kt)
            # value correction for this level
            conv = []
            next_seed = []
            for p in (0, 1):
                ns, vec = _convert(F, new_seed[p], self.VALUE_LEN)
                conv.append(vec)
                next_seed.append(ns)
            beta = 1
            if level == self.bits - 1 and beta_leaf is not None:
                beta = beta_leaf
            elif beta_inner is not None and level < self.bits - 1:
                beta = beta_inner[level]
            want = [1, beta]
            # W_cw = (-1)^{t1} * (want - conv0 + conv1): the on-path party
            # holding ctrl=1 adds W_cw, party 1 negates its whole share
            sign = F.MODULUS - 1 if new_ctrl[1] else 1
            value_cw = [
                F.mul(sign, F.add(F.sub(w, conv[0][i]), conv[1][i]))
                for i, w in enumerate(want)
            ]
            cws.append((seed_cw, t_cw_l, t_cw_r, value_cw))
            seed = next_seed
            ctrl = new_ctrl
        return cws, IdpfKey(root[0], cws), IdpfKey(root[1], cws)

    def eval_prefixes(self, party: int, key: IdpfKey, level: int, prefixes: list[int]):
        """Evaluate this party's share at each prefix of bit-length
        level+1; returns [len(prefixes)][VALUE_LEN] field shares."""
        F = self.field_at(level)
        out = []
        for p in prefixes:
            share = self._eval_one(party, key, level, p)
            out.append(share)
        return out

    def _eval_one(self, party: int, key: IdpfKey, level: int, prefix: int):
        seed = key.root_seed
        ctrl = party  # party 1 starts with control bit 1
        value = None
        for lvl in range(level + 1):
            F = self.field_at(lvl)
            bit = (prefix >> (level - lvl)) & 1
            seed_cw, t_cw_l, t_cw_r, value_cw = key.correction_words[lvl]
            sl, tl, sr, tr = _extend(seed)
            if ctrl:
                sl = bytes(a ^ b for a, b in zip(sl, seed_cw))
                sr = bytes(a ^ b for a, b in zip(sr, seed_cw))
                tl ^= t_cw_l
                tr ^= t_cw_r
            seed, ctrl = (sr, tr) if bit else (sl, tl)
            seed, vec = _convert(F, seed, self.VALUE_LEN)
            if lvl == level:
                value = list(vec)
                if ctrl:
                    value = [F.add(v, cw) for v, cw in zip(value, value_cw)]
                if party == 1:
                    value = [F.neg(v) for v in value]
        return value


@dataclass
class Poplar1AggParam:
    level: int
    prefixes: tuple[int, ...]

    def encode(self) -> bytes:
        import struct

        out = struct.pack(">HI", self.level, len(self.prefixes))
        for p in self.prefixes:
            out += p.to_bytes(16, "big")
        return out

    @classmethod
    def decode(cls, raw: bytes) -> "Poplar1AggParam":
        import struct

        level, n = struct.unpack(">HI", raw[:6])
        prefixes = tuple(
            int.from_bytes(raw[6 + 16 * i : 22 + 16 * i], "big") for i in range(n)
        )
        return cls(level, prefixes)


@dataclass
class _PrepState:
    field: object
    y_shares: list  # per-prefix count share
    party: int
    verify_share: list  # sketch verification share (round 1 message)


class Poplar1:
    """Host Poplar1: shard / prepare (sketch) / aggregate / unshard.

    Two aggregators (leader=0, helper=1); one prepare round of sketch
    verification per the simplified sketch: the aggregators exchange
    masked sums proving sum(y) == 1 without revealing which prefix.
    """

    NUM_SHARES = 2

    def __init__(self, bits: int):
        self.bits = bits
        self.idpf = Idpf(bits)

    # --- client ---
    def shard(self, measurement: int):
        """measurement: the alpha bit string as an int < 2^bits."""
        cws, k0, k1 = self.idpf.gen(measurement)
        return cws, (k0, k1)

    # --- aggregator ---
    def prepare_init(self, party: int, key: IdpfKey, agg_param: Poplar1AggParam):
        F = self.idpf.field_at(agg_param.level)
        vals = self.idpf.eval_prefixes(party, key, agg_param.level, list(agg_param.prefixes))
        y = [v[0] for v in vals]
        # sketch round 1: share of sum(y) (should reconstruct to 1)
        total = 0
        for v in y:
            total = F.add(total, v)
        return _PrepState(F, y, party, [total]), [total]

    def prepare_finish(self, state: _PrepState, msgs: list[list[int]]):
        F = state.field
        total = 0
        for m in msgs:
            total = F.add(total, m[0])
        # 1 = client's path intersects the queried prefixes; 0 = the
        # client was pruned out at an earlier level (legitimate)
        if total not in (0, 1):
            raise VdafError("poplar1 sketch failed: not a one-hot path")
        return state.y_shares

    # --- aggregation ---
    def aggregate(self, agg_param: Poplar1AggParam, out_shares: list[list[int]]):
        F = self.idpf.field_at(agg_param.level)
        agg = [0] * len(agg_param.prefixes)
        for share in out_shares:
            agg = [F.add(a, b) for a, b in zip(agg, share)]
        return agg

    def unshard(self, agg_param: Poplar1AggParam, agg_shares: list[list[int]]):
        F = self.idpf.field_at(agg_param.level)
        agg = [0] * len(agg_param.prefixes)
        for share in agg_shares:
            agg = [F.add(a, b) for a, b in zip(agg, share)]
        return [int(x) for x in agg]


# ---------------------------------------------------------------------------
# DAP wire codecs (public share = correction words; input share = root
# seed). The reference declares Poplar1 but cannot drive it through DAP
# (nontrivial aggregation parameters unsupported, README.md:9-11);
# these codecs + the aggregator's agg-param plumbing make it reachable
# here.
# ---------------------------------------------------------------------------


def encode_public_share(bits: int, cws: list) -> bytes:
    """Correction words: per level seed_cw(16) || ctrl byte(t_l<<1|t_r)
    || value_cw elements (2, level field, fixed width)."""
    idpf = Idpf(bits)
    out = bytearray()
    for level, (seed_cw, t_l, t_r, value_cw) in enumerate(cws):
        F = idpf.field_at(level)
        out += seed_cw
        out.append((t_l << 1) | t_r)
        for v in value_cw:
            out += int(v).to_bytes(F.ENCODED_SIZE, "little")
    return bytes(out)


def decode_public_share(bits: int, raw: bytes) -> list:
    idpf = Idpf(bits)
    cws = []
    off = 0
    for level in range(bits):
        F = idpf.field_at(level)
        if off + SEED_SIZE + 1 + 2 * F.ENCODED_SIZE > len(raw):
            raise ValueError("poplar1 public share truncated")
        seed_cw = raw[off : off + SEED_SIZE]
        off += SEED_SIZE
        ctrl = raw[off]
        off += 1
        if ctrl > 3:
            raise ValueError("poplar1 public share bad control byte")
        value_cw = []
        for _ in range(Idpf.VALUE_LEN):
            v = int.from_bytes(raw[off : off + F.ENCODED_SIZE], "little")
            if v >= F.MODULUS:
                raise ValueError("poplar1 correction word out of range")
            value_cw.append(v)
            off += F.ENCODED_SIZE
        cws.append((seed_cw, (ctrl >> 1) & 1, ctrl & 1, value_cw))
    if off != len(raw):
        raise ValueError("poplar1 public share trailing bytes")
    return cws


def encode_input_share(key: IdpfKey) -> bytes:
    return key.root_seed


def decode_input_share(bits: int, cws: list, raw: bytes) -> IdpfKey:
    if len(raw) != SEED_SIZE:
        raise ValueError("poplar1 input share must be one 16-byte root seed")
    return IdpfKey(raw, cws)


def heavy_hitters(poplar: Poplar1, keys0, keys1, threshold: int) -> list[int]:
    """The classic Poplar loop: walk levels keeping prefixes whose count
    reaches the threshold; returns the heavy alpha values."""
    prefixes = [0, 1]
    for level in range(poplar.bits):
        agg_param = Poplar1AggParam(level, tuple(prefixes))
        out0, out1 = [], []
        for k0, k1 in zip(keys0, keys1):
            st0, m0 = poplar.prepare_init(0, k0, agg_param)
            st1, m1 = poplar.prepare_init(1, k1, agg_param)
            out0.append(poplar.prepare_finish(st0, [m0, m1]))
            out1.append(poplar.prepare_finish(st1, [m0, m1]))
        counts = poplar.unshard(
            agg_param,
            [poplar.aggregate(agg_param, out0), poplar.aggregate(agg_param, out1)],
        )
        survivors = [p for p, c in zip(prefixes, counts) if c >= threshold]
        if level == poplar.bits - 1:
            return survivors
        prefixes = [p << 1 for p in survivors] + [(p << 1) | 1 for p in survivors]
        prefixes.sort()
    return []
