"""XOF (extendable output function) for VDAF: counter-mode SHAKE128.

Modeled on the XofShake128 construction of VDAF-07 (the XOF the
reference's `prio` 0.15 dependency implements; SURVEY.md section 2.2
"XOF (SHAKE128-family) share/joint-randomness expansion"), with two
TPU-motivated framing changes. The exact byte framing is internal to
this framework's two cooperating aggregators; both sides derive it from
here, and the device implementation (janus_tpu.vdaf.keccak_jax) is
byte-identical (differential-tested).

1. **Counter-mode output** instead of sequential sponge squeezing:

       block_i = SHAKE128(dst16 || seed || binder' || le64(i))[:168]
       stream  = block_0 || block_1 || ...

   Sequential squeezing chains one Keccak permutation per 168-byte
   block: expanding a SumVec-16k share (256 KB) is ~1.5k permutations
   that *must run one after another* — on TPU that is pure latency, a
   tiny [batch, 25]-lane op launched 36k rounds deep. In counter mode
   every block depends only on (seed, binder, i), so the whole stream
   of every report in a batch is one batched permutation: the same
   total permutation count (the prefix always fits one rate block, so
   absorb+squeeze is a single Keccak-f[1600] per block either way) at
   sequential depth 24 rounds instead of ~36,000.

2. **Tree-digested long binders.** The joint-randomness part binds the
   full encoded leader measurement share (VDAF-07 semantics), which for
   SumVec is 256 KB absorbed — again inherently sequential in a sponge.
   Binders longer than 112 bytes are replaced by a 16-byte Merkle
   digest with 112-byte leaves and arity-7 internal nodes; every node
   hash is a single-block SHAKE128 message, so each tree *level* is one
   batched permutation (depth ~log_7(n) instead of n). Node messages
   carry (magic, level, index, total length), making the tree shape a
   pure function of the data length — unambiguous padding, standard
   Merkle collision resistance. A 16-byte digest keeps the reference's
   security level: Prio3's joint-randomness parts and seeds are 16
   bytes already.

All binder layouts used by Prio3 are multiples of 8 bytes (agg ids are
carried as 8-byte little-endian words), so every field of every
message is u64-lane-aligned and the batched device Keccak packs
messages as uint64 lane arrays with no byte-straddling shifts.

Security analysis of every deviation here (claim, bound, what to
attack): SECURITY-NOTES.md #1 (counter mode), #2 (tree digest),
#5 (oversample-and-reduce).

Field-element sampling is **oversample-and-reduce** (the RFC 9380
hash-to-field construction, not the VDAF draft's rejection sampling):
element i consumes (LIMBS+1) 8-byte little-endian lanes — 128 random
bits for Field64, 192 for Field128 — interpreted as an integer and
reduced mod p. Statistical distance from uniform is <= 2^-64 per
element (p/2^sample_bits), cryptographically negligible and standard
practice. The TPU motivation: rejection sampling needs data-dependent
compaction, which lowers to row-wise gathers + sort-based scatters —
profiled at 78% of the whole two-party SumVec step on real hardware —
while reduction is pure elementwise limb math. Chunks may straddle
block boundaries; the stream is the plain concatenation of blocks.
"""

from __future__ import annotations

import hashlib

SEED_SIZE = 16

# Domain-separation usage tags (one byte each), following the Prio3
# usage enumeration. The exact byte values are internal to this
# framework's two cooperating aggregators; both sides derive them from
# here.
USAGE_SHARD_RAND = 1
USAGE_MEASUREMENT_SHARE = 2
USAGE_PROOF_SHARE = 3
USAGE_JOINT_RANDOMNESS = 4
USAGE_PROVE_RANDOMNESS = 5
USAGE_QUERY_RANDOMNESS = 6
USAGE_JOINT_RAND_SEED = 7
USAGE_JOINT_RAND_PART = 8

ALGO_CLASS_VDAF = 0
DST_SIZE = 16

RATE = 168  # SHAKE128 rate in bytes

# Binders longer than this are replaced by tree_digest(binder).
INLINE_BINDER_MAX = 112
# Tree hash geometry: 112-byte leaves, arity-7 internal nodes
# (7 x 16-byte digests = 112 bytes), every node message single-block.
TREE_CHUNK = 112
TREE_ARITY = 7
TREE_DIGEST_SIZE = 16
TREE_MAGIC = b"JanusTr1"


def dst(algo_id: int, usage: int, version: int = 7) -> bytes:
    """Domain-separation tag: class || version || algo id || usage,
    zero-padded to DST_SIZE so it occupies exactly two u64 lanes."""
    raw = (
        bytes([ALGO_CLASS_VDAF, version])
        + algo_id.to_bytes(4, "big")
        + usage.to_bytes(2, "big")
    )
    return raw.ljust(DST_SIZE, b"\x00")


def _le64(i: int) -> bytes:
    return i.to_bytes(8, "little")


def tree_digest(data: bytes) -> bytes:
    """16-byte Merkle digest of lane-aligned data (see module docstring).

    Leaf k's payload is PLANAR: u64 lane j*n+k for j in 0..13 (n = leaf
    count), a fixed bijection of the data rather than contiguous
    112-byte chunks. Rationale: on device every leaf lane column is
    then one contiguous slice — the contiguous-chunk layout forced a
    stride-14 gather over the whole binder (~30% of the digest wall
    time at the 25.6 MB len=100k leader binder, measured r5). Same
    collision resistance: the node encoding is unchanged and the
    leaf<->data mapping is a bijection.
    """
    assert len(data) % 8 == 0
    total = _le64(len(data))

    def node(level: int, index: int, payload: bytes) -> bytes:
        assert len(payload) == TREE_CHUNK
        msg = TREE_MAGIC + _le64(level) + _le64(index) + total + payload
        return hashlib.shake_128(msg).digest(TREE_DIGEST_SIZE)

    import numpy as _np

    lanes = _np.frombuffer(data, dtype="<u8")
    n = max(1, -(-lanes.size // (TREE_CHUNK // 8)))
    planes = _np.zeros((TREE_CHUNK // 8, n), dtype=_np.uint64)
    planes.reshape(-1)[: lanes.size] = lanes
    digs = [node(0, k, planes[:, k].tobytes()) for k in range(n)]
    level = 0
    while len(digs) > 1:
        level += 1
        pad = -len(digs) % TREE_ARITY
        digs.extend([b"\x00" * TREE_DIGEST_SIZE] * pad)
        digs = [
            node(level, g, b"".join(digs[g * TREE_ARITY : (g + 1) * TREE_ARITY]))
            for g in range(len(digs) // TREE_ARITY)
        ]
    return digs[0]


class XofCtr128:
    """Counter-mode SHAKE128 XOF (the host oracle for the device Keccak)."""

    SEED_SIZE = SEED_SIZE

    def __init__(self, seed: bytes, dst_: bytes, binder: bytes = b""):
        assert len(seed) == SEED_SIZE
        assert len(dst_) <= DST_SIZE
        if len(binder) > INLINE_BINDER_MAX:
            # The 16-byte digest's ~2^64 collision bound is only argued
            # safe for the joint-rand-part usage (SECURITY-NOTES.md #2);
            # any new long-binder usage must be analyzed, not inherited.
            # Explicit raise, not assert: a security boundary must
            # survive python -O.
            usage = int.from_bytes(dst_[6:8], "big")
            if usage != USAGE_JOINT_RAND_PART:
                raise ValueError(
                    f"tree-digest substitution restricted to joint-rand-part "
                    f"(SECURITY-NOTES.md #2); got usage {usage}"
                )
            binder = tree_digest(binder)
        self._prefix = dst_.ljust(DST_SIZE, b"\x00") + seed + binder
        assert len(self._prefix) + 8 <= RATE - 1  # always one absorb block
        self._block = 0
        self._buf = b""

    def next(self, n: int) -> bytes:
        while len(self._buf) < n:
            self._buf += hashlib.shake_128(
                self._prefix + _le64(self._block)
            ).digest(RATE)
            self._block += 1
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def next_vec(self, field, length: int) -> list[int]:
        """Sample `length` field elements by oversample-and-reduce:
        ENCODED_SIZE + 8 stream bytes per element, little-endian,
        mod p (bias <= 2^-64; see module docstring)."""
        size = field.ENCODED_SIZE + 8
        p = field.MODULUS
        return [
            int.from_bytes(self.next(size), "little") % p for _ in range(length)
        ]

    @classmethod
    def derive_seed(cls, seed: bytes, dst_: bytes, binder: bytes = b"") -> bytes:
        return cls(seed, dst_, binder).next(SEED_SIZE)


# The class named for what the stream is derived from; modules that
# predate the counter-mode rename import this alias.
XofShake128 = XofCtr128


DRAFT_VERSION = 7


def draft_dst(algo_id: int, usage: int) -> bytes:
    """VDAF-07-style 8-byte domain-separation tag:
    version || class || algo id (u32be) || usage (u16be)."""
    return (
        bytes([DRAFT_VERSION, ALGO_CLASS_VDAF])
        + algo_id.to_bytes(4, "big")
        + usage.to_bytes(2, "big")
    )


class XofSponge128:
    """Sequential-sponge SHAKE128 XOF with rejection sampling — the
    VDAF-07 XofShake128 construction (`xof_mode: draft`).

    Framing: absorb ``byte(len(dst)) || dst || seed || binder``, squeeze
    the output stream sequentially. Field elements are rejection-sampled
    from ENCODED_SIZE-byte little-endian chunks (resample on >= p), per
    the draft — none of the fast-mode deviations (SECURITY-NOTES.md
    #1/#2/#5) apply here.

    Conformance status: this follows the draft-irtf-cfrg-vdaf-07
    construction as implemented by the reference's prio 0.15 dependency
    (Cargo.lock:2939); byte-exactness against the published test
    vectors is NOT verified in this build environment (no network
    access — see tests/test_vdaf_vectors.py, which consumes the
    official JSON vector format when vectors are provided).
    """

    SEED_SIZE = SEED_SIZE

    def __init__(self, seed: bytes, dst_: bytes, binder: bytes = b""):
        assert len(seed) == SEED_SIZE
        self._absorbed = bytes([len(dst_)]) + dst_ + seed + binder
        self._off = 0
        self._squeezed = b""

    def next(self, n: int) -> bytes:
        # Sequential squeezing of one sponge == successive bytes of a
        # single arbitrary-length SHAKE128 output. hashlib can't extend
        # a digest incrementally, so re-digest with doubling lengths
        # (amortized O(total), not O(total^2)).
        end = self._off + n
        if end > len(self._squeezed):
            self._squeezed = hashlib.shake_128(self._absorbed).digest(
                max(end, 2 * len(self._squeezed), 256)
            )
        chunk = self._squeezed[self._off : end]
        self._off = end
        return chunk

    def next_vec(self, field, length: int) -> list[int]:
        size = field.ENCODED_SIZE
        p = field.MODULUS
        out: list[int] = []
        while len(out) < length:
            # bulk-read for the common all-accepted case
            want = length - len(out)
            buf = self.next(size * want)
            for i in range(want):
                x = int.from_bytes(buf[i * size : (i + 1) * size], "little")
                if x < p:
                    out.append(x)
        return out

    @classmethod
    def derive_seed(cls, seed: bytes, dst_: bytes, binder: bytes = b"") -> bytes:
        return cls(seed, dst_, binder).next(SEED_SIZE)


def prng_expand(field, seed: bytes, dst_: bytes, binder: bytes, length: int):
    """Expand a seed into a vector of field elements (host path).

    Uses the native C Keccak (janus_tpu.native, the analog of the
    reference keeping XOF expansion in native code) when available;
    byte-identical pure-Python fallback otherwise.
    """
    out = prng_expand_batch(field, dst_, [seed], [binder] if binder else None, length)
    if out is not None:
        return out[0]
    return XofCtr128(seed, dst_, binder).next_vec(field, length)


def prng_expand_batch(field, dst_: bytes, seeds, binders, length: int):
    """Expand many seeds at once on host threads -> list of int vectors.

    seeds: list of 16-byte seeds; binders: matching list of equal-length
    binders (or None for empty binders). Returns None when the native
    library is unavailable (callers fall back to the scalar path).
    """
    from .. import native

    if not native.available():
        return None
    limbs = field.ENCODED_SIZE // 8
    if limbs not in (1, 2) or field.ENCODED_SIZE != 8 * limbs:
        return None  # native path only supports whole-u64-lane encodings
    arr = native.expand_field_batch(
        dst_.ljust(DST_SIZE, b"\x00"), seeds, binders, length, limbs, field.MODULUS
    )
    if arr is None:
        return None
    if limbs == 1:
        return [row[:, 0].tolist() for row in arr]
    return [
        (row[:, 0].astype(object) + (row[:, 1].astype(object) << 64)).tolist()
        for row in arr
    ]
