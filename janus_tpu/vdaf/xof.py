"""XOF (extendable output function) for VDAF: SHAKE128-based.

Modeled on the XofShake128 construction of VDAF-07 (the VDAF draft the
reference's `prio` 0.15 dependency implements; SURVEY.md section 2.2
"XOF (SHAKE128-family) share/joint-randomness expansion"), with one
TPU-motivated framing change:

    stream = SHAKE128( dst16 || seed || binder )

where dst16 is the domain-separation tag zero-padded to 16 bytes, and
all binder layouts used by Prio3 are multiples of 8 bytes (agg ids are
carried as 8-byte little-endian words). Every field of every absorbed
message is therefore u64-lane-aligned, which lets the batched device
Keccak (janus_tpu.vdaf.keccak_jax) pack messages as [batch, 21] uint64
lane arrays with no byte-straddling shifts. Host and device produce
byte-identical streams.

Field-element sampling reads ENCODED_SIZE-byte little-endian chunks and
rejects values >= p (rejection probability ~2^-32 for both fields).

The device-side equivalent (janus_tpu.vdaf.keccak_jax) implements the
same stream semantics with a batched Keccak-f[1600] permutation so that
helper share expansion never leaves the TPU; this module is the host
oracle and the path used for small per-report derivations.
"""

from __future__ import annotations

import hashlib

SEED_SIZE = 16

# Domain-separation usage tags (one byte each), following the Prio3
# usage enumeration. The exact byte values are internal to this
# framework's two cooperating aggregators; both sides derive them from
# here.
USAGE_SHARD_RAND = 1
USAGE_MEASUREMENT_SHARE = 2
USAGE_PROOF_SHARE = 3
USAGE_JOINT_RANDOMNESS = 4
USAGE_PROVE_RANDOMNESS = 5
USAGE_QUERY_RANDOMNESS = 6
USAGE_JOINT_RAND_SEED = 7
USAGE_JOINT_RAND_PART = 8

ALGO_CLASS_VDAF = 0
DST_SIZE = 16


def dst(algo_id: int, usage: int, version: int = 7) -> bytes:
    """Domain-separation tag: class || version || algo id || usage,
    zero-padded to DST_SIZE so it occupies exactly two u64 lanes."""
    raw = (
        bytes([ALGO_CLASS_VDAF, version])
        + algo_id.to_bytes(4, "big")
        + usage.to_bytes(2, "big")
    )
    return raw.ljust(DST_SIZE, b"\x00")


class XofShake128:
    SEED_SIZE = SEED_SIZE

    def __init__(self, seed: bytes, dst_: bytes, binder: bytes = b""):
        assert len(seed) == SEED_SIZE
        assert len(dst_) <= DST_SIZE
        self._shake = hashlib.shake_128()
        self._shake.update(dst_.ljust(DST_SIZE, b"\x00") + seed + binder)
        self._buf = b""
        self._pos = 0

    def update(self, binder: bytes) -> None:
        assert self._pos == 0, "cannot absorb after squeezing"
        self._shake.update(binder)

    def next(self, n: int) -> bytes:
        need = self._pos + n
        if need > len(self._buf):
            # hashlib has no incremental squeeze; re-digest with headroom.
            self._buf = self._shake.digest(max(need, 2 * len(self._buf), 512))
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def next_vec(self, field, length: int) -> list[int]:
        """Sample `length` field elements by rejection sampling."""
        out: list[int] = []
        size = field.ENCODED_SIZE
        while len(out) < length:
            chunk = self.next(size)
            v = int.from_bytes(chunk, "little")
            if v < field.MODULUS:
                out.append(v)
        return out

    @classmethod
    def derive_seed(cls, seed: bytes, dst_: bytes, binder: bytes = b"") -> bytes:
        return cls(seed, dst_, binder).next(SEED_SIZE)


def prng_expand(field, seed: bytes, dst_: bytes, binder: bytes, length: int):
    """Expand a seed into a vector of field elements (host path).

    Uses the native C Keccak (janus_tpu.native, the analog of the
    reference keeping XOF expansion in native code) when available;
    byte-identical pure-Python fallback otherwise.
    """
    out = prng_expand_batch(field, dst_, [seed], [binder] if binder else None, length)
    if out is not None:
        return out[0]
    return XofShake128(seed, dst_, binder).next_vec(field, length)


def prng_expand_batch(field, dst_: bytes, seeds, binders, length: int):
    """Expand many seeds at once on host threads -> list of int vectors.

    seeds: list of 16-byte seeds; binders: matching list of equal-length
    binders (or None for empty binders). Returns None when the native
    library is unavailable (callers fall back to the scalar path).
    """
    from .. import native

    if not native.available():
        return None
    limbs = field.ENCODED_SIZE // 8
    if limbs not in (1, 2) or field.ENCODED_SIZE != 8 * limbs:
        return None  # native path only supports whole-u64-lane encodings
    arr = native.expand_field_batch(
        dst_.ljust(DST_SIZE, b"\x00"), seeds, binders, length, limbs, field.MODULUS
    )
    if arr is None:
        return None
    if limbs == 1:
        return [row[:, 0].tolist() for row in arr]
    return [
        (row[:, 0].astype(object) + (row[:, 1].astype(object) << 64)).tolist()
        for row in arr
    ]
