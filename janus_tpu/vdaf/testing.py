"""Report-batch generation utilities (tests, benchmarks, load drivers).

The analog of the reference's transcript generator
(core/src/test_util/mod.rs:50 run_vdaf) adapted to column batches:
produce every array the two-party device step consumes, via the
batched device shard (so generating 1M reports is itself a device op).
"""

from __future__ import annotations

import numpy as np

from .registry import VdafInstance, prio3_batched


def random_measurements(inst: VdafInstance, batch: int, rng: np.random.Generator):
    if inst.kind == "count":
        return rng.integers(0, 2, size=batch)
    if inst.kind == "sum":
        hi = min(inst.bits, 62)
        return rng.integers(0, 1 << hi, size=batch)
    if inst.kind == "sumvec":
        hi = min(inst.bits, 62)
        return rng.integers(0, 1 << hi, size=(batch, inst.length))
    if inst.kind == "histogram":
        return rng.integers(0, inst.length, size=batch)
    if inst.kind == "countvec":
        return rng.integers(0, 2, size=(batch, inst.length))
    if inst.kind == "fixedpoint":
        # signed raw values kept small enough that any vector's L2 norm < 1
        offset = 1 << (inst.bits - 1)
        hi = max(1, int(offset / (inst.length**0.5)) // 2)
        return rng.integers(-hi, hi, size=(batch, inst.length))
    raise ValueError(inst.kind)


def make_report_batch(inst: VdafInstance, measurements, seed: int = 0):
    """Shard a batch of measurements on device.

    Returns (step_args, measurements) where step_args is the positional
    tuple for parallel.api.two_party_step: (nonce_lanes, public_parts,
    leader_meas, leader_proof, blind0, helper_seed, blind1).
    """
    p3 = prio3_batched(inst)
    rng = np.random.default_rng(seed)
    batch = len(measurements)
    inp_np = p3.bc.encode_batch(measurements)
    inp = p3.jf.from_ints(inp_np.astype(object))
    nonce_lanes = rng.integers(0, 1 << 63, size=(batch, 2), dtype=np.uint64)
    n_seeds = 4 if p3.uses_joint_rand else 2
    rand_lanes = rng.integers(0, 1 << 63, size=(batch, n_seeds, 2), dtype=np.uint64)
    sh = p3.shard_jit(inp, nonce_lanes, rand_lanes)
    args = (
        nonce_lanes,
        sh["public_parts"],
        sh["leader_meas"],
        sh["leader_proof"],
        sh["blind0"],
        sh["helper_seed"],
        sh["blind1"],
    )
    return args, measurements
