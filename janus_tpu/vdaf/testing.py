"""Report-batch generation utilities (tests, benchmarks, load drivers).

The analog of the reference's transcript generator
(core/src/test_util/mod.rs:50 run_vdaf) adapted to column batches:
produce every array the two-party device step consumes, via the
batched device shard (so generating 1M reports is itself a device op).
"""

from __future__ import annotations

import numpy as np

from .registry import VdafInstance, prio3_batched


def random_measurements(inst: VdafInstance, batch: int, rng: np.random.Generator):
    if inst.kind == "count":
        return rng.integers(0, 2, size=batch)
    if inst.kind == "sum":
        hi = min(inst.bits, 62)
        return rng.integers(0, 1 << hi, size=batch)
    if inst.kind == "sumvec":
        hi = min(inst.bits, 62)
        return rng.integers(0, 1 << hi, size=(batch, inst.length))
    if inst.kind == "sparse_sumvec":
        # per-report list of (block_index, dense block) pairs, sorted by
        # index — the sparse measurement currency (vdaf.reference)
        hi = min(inst.bits, 62)
        n_blocks = inst.length // inst.block_size
        out = []
        for _ in range(batch):
            nb = int(rng.integers(1, inst.max_blocks + 1))
            idxs = sorted(rng.choice(n_blocks, size=nb, replace=False).tolist())
            out.append(
                [
                    (int(b), [int(v) for v in rng.integers(0, 1 << hi, size=inst.block_size)])
                    for b in idxs
                ]
            )
        return out
    if inst.kind == "histogram":
        return rng.integers(0, inst.length, size=batch)
    if inst.kind == "countvec":
        return rng.integers(0, 2, size=(batch, inst.length))
    if inst.kind == "fixedpoint":
        # signed raw values kept small enough that any vector's L2 norm < 1
        offset = 1 << (inst.bits - 1)
        hi = max(1, int(offset / (inst.length**0.5)) // 2)
        return rng.integers(-hi, hi, size=(batch, inst.length))
    raise ValueError(inst.kind)


def sparse_compact_batch(inst: VdafInstance, measurements):
    """Convert sparse pair-measurements to the device currency:
    ([batch, compact_len] uint64 compact value rows, [batch, max_blocks]
    int32 block indices, -1 padding). The value rows feed the batched
    engine exactly like dense SumVec rows; the indices ride the public
    share / scatter path."""
    from .registry import circuit_for

    circ = circuit_for(inst)
    vals, idxs = [], []
    for m in measurements:
        v, ix = circ.compact_values(m)
        vals.append(v)
        idxs.append(list(ix))
    return (
        np.asarray(vals, dtype=np.uint64),
        np.asarray(idxs, dtype=np.int32),
    )


def make_wire_reports(
    inst: VdafInstance,
    measurements,
    task_id,
    leader_hpke_config,
    helper_hpke_config,
    time,
    seed: int = 0,
):
    """Device-shard a batch and assemble full DAP Report messages.

    A batched client: sharding runs on device (one traced computation
    for the whole batch), then each report is HPKE-sealed and framed
    exactly as client.Client.prepare_report does per report
    (reference client/src/lib.rs:212-260). Used by load generators and
    the served-mode bench.
    """
    from ..core.hpke import HpkeApplicationInfo, Label, hpke_seal
    from ..messages import (
        InputShareAad,
        PlaintextInputShare,
        Report,
        ReportId,
        ReportMetadata,
        Role,
    )
    from .registry import circuit_for
    from .wire import Prio3Wire, encode_field_rows

    p3 = prio3_batched(inst)
    wire = Prio3Wire(circuit_for(inst))
    sparse = inst.kind == "sparse_sumvec"
    if sparse:
        from .reference import SparsePublicShare

        _, block_idx = sparse_compact_batch(inst, measurements)
    args, _ = make_report_batch(inst, measurements, seed=seed)
    nonce_lanes, public_parts, leader_meas, leader_proof, blind0, helper_seed, blind1 = args
    n = nonce_lanes.shape[0]
    meas_rows = encode_field_rows(p3.jf, leader_meas)
    proof_rows = encode_field_rows(p3.jf, leader_proof)
    seed_rows = [r.tobytes() for r in np.asarray(helper_seed, dtype="<u8")]
    if p3.uses_joint_rand:
        blind0_rows = [r.tobytes() for r in np.asarray(blind0, dtype="<u8")]
        blind1_rows = [r.tobytes() for r in np.asarray(blind1, dtype="<u8")]
        pp = np.asarray(public_parts, dtype="<u8")
        part_rows = [(pp[i, 0].tobytes(), pp[i, 1].tobytes()) for i in range(n)]
    reports = []
    for i in range(n):
        report_id = ReportId(nonce_lanes[i].astype("<u8").tobytes())
        metadata = ReportMetadata(report_id, time)
        if p3.uses_joint_rand:
            parts = list(part_rows[i])
            leader_payload = wire.encode_leader_share_raw(
                meas_rows[i] + proof_rows[i], blind0_rows[i]
            )
            helper_payload = wire.encode_helper_share(seed_rows[i], blind1_rows[i])
        else:
            parts = []
            leader_payload = meas_rows[i] + proof_rows[i]
            helper_payload = wire.encode_helper_share(seed_rows[i], None)
        if sparse:
            public_share = wire.encode_public_share(SparsePublicShare(parts, block_idx[i]))
        elif p3.uses_joint_rand:
            public_share = wire.encode_public_share(parts)
        else:
            public_share = b""
        aad = InputShareAad(task_id, metadata, public_share).to_bytes()
        leader_ct = hpke_seal(
            leader_hpke_config,
            HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER),
            PlaintextInputShare((), leader_payload).to_bytes(),
            aad,
        )
        helper_ct = hpke_seal(
            helper_hpke_config,
            HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.HELPER),
            PlaintextInputShare((), helper_payload).to_bytes(),
            aad,
        )
        reports.append(Report(metadata, public_share, leader_ct, helper_ct))
    return reports


def make_report_batch(inst: VdafInstance, measurements, seed: int = 0, shard_chunk: int = 0):
    """Shard a batch of measurements on device.

    Returns (step_args, measurements) where step_args is the positional
    tuple for parallel.api.two_party_step: (nonce_lanes, public_parts,
    leader_meas, leader_proof, blind0, helper_seed, blind1).

    shard_chunk > 0 shards in sub-batches of that size and concatenates
    on host: the FLP *prove* graph peaks at [chunk, arity, n2] per
    sub-batch, so long-vector configs (SumVec len=100k) can stage a
    batch far larger than the prove path could hold at once. The
    prepare step's own memory is unaffected (query needs no wire-poly
    coefficient arrays).
    """
    p3 = prio3_batched(inst)
    rng = np.random.default_rng(seed)
    batch = len(measurements)
    nonce_lanes = rng.integers(0, 1 << 63, size=(batch, 2), dtype=np.uint64)
    n_seeds = 4 if p3.uses_joint_rand else 2
    rand_lanes = rng.integers(0, 1 << 63, size=(batch, n_seeds, 2), dtype=np.uint64)

    def shard_slice(lo: int, hi: int):
        if inst.kind == "sparse_sumvec":
            # the device engine runs the COMPACT encoding: convert pair
            # measurements to compact value rows (the engine never sees
            # the logical length; indices ride the public share)
            vals, _ = sparse_compact_batch(inst, measurements[lo:hi])
            inp_np = p3.bc.encode_batch(vals)
        else:
            inp_np = p3.bc.encode_batch(measurements[lo:hi])
        inp = p3.jf.from_ints(inp_np.astype(object))
        return p3.shard_jit(inp, nonce_lanes[lo:hi], rand_lanes[lo:hi])

    if not shard_chunk or shard_chunk >= batch:
        sh = shard_slice(0, batch)
    else:
        parts = []
        for lo in range(0, batch, shard_chunk):
            s = shard_slice(lo, min(lo + shard_chunk, batch))
            # pull to host so device frees the sub-batch before the next
            parts.append(
                {
                    k: (
                        None
                        if v is None
                        else tuple(np.asarray(x) for x in v)
                        if isinstance(v, tuple)
                        else np.asarray(v)
                    )
                    for k, v in s.items()
                }
            )
        sh = {}
        for k in parts[0]:
            if parts[0][k] is None:
                sh[k] = None
            elif isinstance(parts[0][k], tuple):
                sh[k] = tuple(
                    np.concatenate([p[k][i] for p in parts])
                    for i in range(len(parts[0][k]))
                )
            else:
                sh[k] = np.concatenate([p[k] for p in parts])
    args = (
        nonce_lanes,
        sh["public_parts"],
        sh["leader_meas"],
        sh["leader_proof"],
        sh["blind0"],
        sh["helper_seed"],
        sh["blind1"],
    )
    return args, measurements
