"""VDAF engine: XOF, FLP proof system, Prio3, ping-pong topology.

This package owns the math the reference outsources to the external
`prio` crate (SURVEY.md section 2.2): batched shard / prepare_init /
prepare_next / aggregate / unshard over `[batch, ...]` arrays.

Two implementations live side by side:
  reference.py  -- host, Python ints, exact and slow; the oracle, and
                   the path used by clients/tools for single reports.
  engine.py     -- batched JAX (device) implementation of the hot path,
                   differential-tested against reference.py.
"""
