"""VDAF instance registry + dispatch.

Equivalent of the reference's `VdafInstance` enum and `vdaf_dispatch!`
macro (core/src/task.rs:24-650): a serializable description of a VDAF
configuration that resolves to concrete host/device implementations.
A table lookup replaces the Rust macro (SURVEY.md section 7 step 3).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

from .prio3_jax import Prio3Batched
from .reference import (
    Circuit,
    Count,
    FixedPointVec,
    Histogram,
    Prio3,
    Prio3Sparse,
    SparseSumVec,
    Sum,
    SumVec,
    optimal_chunk_length,
)

VERIFY_KEY_LENGTH = 16  # reference core/src/task.rs:15


@dataclass(frozen=True)
class VdafInstance:
    """One VDAF configuration; hashable so dispatch results are cached."""

    kind: str  # "count" | "sum" | "sumvec" | "sparse_sumvec" | "histogram" | ...
    bits: int = 0
    length: int = 0
    chunk_length: int = 0  # 0 -> sqrt heuristic (core/src/task.rs:84-86)
    # block-sparse geometry (kind == "sparse_sumvec" only): the logical
    # vector is `length`-dim, a report carries up to `max_blocks` dense
    # blocks of `block_size` values. Serialized by to_dict whenever
    # nonzero — these fields are part of every shape-manifest / AOT /
    # prewarm key derived from the instance, so a sparse geometry can
    # never collide with a dense one at the same compact width.
    block_size: int = 0
    max_blocks: int = 0
    # XOF framing mode: "fast" = TPU counter-mode framing (default;
    # SECURITY-NOTES.md), "draft" = VDAF-07 sequential-sponge framing
    # (host-only, for spec conformance / cross-implementation pairing).
    # Part of the instance identity: both aggregators of a task must
    # agree or every report fails verification — the aggregation-job
    # framing check makes that mismatch fail loudly.
    xof_mode: str = "fast"

    # --- constructors mirroring the reference enum variants ---
    @classmethod
    def count(cls) -> "VdafInstance":
        return cls("count")

    @classmethod
    def sum(cls, bits: int) -> "VdafInstance":
        return cls("sum", bits=bits)

    @classmethod
    def sum_vec(cls, length: int, bits: int, chunk_length: int = 0) -> "VdafInstance":
        return cls("sumvec", bits=bits, length=length, chunk_length=chunk_length)

    @classmethod
    def sparse_sumvec(
        cls,
        bits: int,
        length: int,
        block_size: int,
        max_blocks: int,
        chunk_length: int = 0,
    ) -> "VdafInstance":
        """Block-sparse vector sum (ISSUE 17): a logical `length`-dim
        vector carried as up to `max_blocks` (block_index, dense
        `block_size`-value block) pairs. The FLP runs at the compact
        length `max_blocks * block_size`; aggregation scatters into a
        dense logical accumulator by the PUBLIC block indices."""
        return cls(
            "sparse_sumvec",
            bits=bits,
            length=length,
            chunk_length=chunk_length,
            block_size=block_size,
            max_blocks=max_blocks,
        )

    @classmethod
    def histogram(cls, length: int, chunk_length: int = 0) -> "VdafInstance":
        return cls("histogram", length=length, chunk_length=chunk_length)

    @classmethod
    def count_vec(cls, length: int, chunk_length: int = 0) -> "VdafInstance":
        """Vector of counts (the reference's Prio3CountVec: SumVec with
        bits=1, core/src/task.rs:28-33)."""
        return cls("countvec", bits=1, length=length, chunk_length=chunk_length)

    @classmethod
    def fixed_point_vec(cls, length: int, bits: int = 16, chunk_length: int = 0) -> "VdafInstance":
        """Fixed-point vector sum with bounded L2 norm (the reference's
        Prio3FixedPoint{16,32,64}BitBoundedL2VecSum, core/src/task.rs:44-49)."""
        return cls("fixedpoint", bits=bits, length=length, chunk_length=chunk_length)

    @classmethod
    def poplar1(cls, bits: int) -> "VdafInstance":
        """Heavy-hitters VDAF (the reference's Poplar1 variant,
        core/src/task.rs) — fully reachable through DAP here, with
        nontrivial aggregation parameters (level, prefixes): the
        collection flow creates param-scoped aggregation jobs and the
        two-round sketch exchange rides the continue machinery
        (aggregator.poplar1_ops; tests/test_poplar1_dap.py). The
        reference declares this variant but punts on the DAP plumbing
        (README.md:9-11, VdafHasAggregationParameter,
        aggregator_core/src/lib.rs:44)."""
        return cls("poplar1", bits=bits)

    # --- test-only fakes (the reference's VdafInstance::Fake* variants,
    # core/src/task.rs:50-58, backed by dummy_vdaf with injectable
    # failures, core/src/test_util/dummy_vdaf.rs:17-66). They run the
    # Count circuit but force per-report prepare failures at the
    # aggregator dispatch sites, exercising error paths without crypto.
    @classmethod
    def fake(cls) -> "VdafInstance":
        return cls("fake")

    @classmethod
    def fake_fails_prep_init(cls) -> "VdafInstance":
        return cls("fake_fails_prep_init")

    @classmethod
    def fake_fails_prep_step(cls) -> "VdafInstance":
        return cls("fake_fails_prep_step")

    @classmethod
    def fake_two_round(cls) -> "VdafInstance":
        """Two-round fake VDAF: exercises the DAP continue machinery
        (helper WaitingHelper state, ord-matched AggregationJobContinueReq,
        step/replay validation — reference
        aggregation_job_continue.rs:30-300) the same way the reference
        tests it with dummy_vdaf. Runs the Count circuit for its shares;
        round 2 is a prep-message echo."""
        return cls("fake_two_round")

    @property
    def rounds(self) -> int:
        """DAP prepare rounds: 1 for all Prio3; 2 for Poplar1 (sketch
        exchange then verify) and the two-round fake."""
        return 2 if self.kind in ("fake_two_round", "poplar1") else 1

    @property
    def has_aggregation_parameter(self) -> bool:
        """Nontrivial aggregation parameters (Poplar1's (level,
        prefixes)): reports aggregate once PER parameter, and
        aggregation jobs are created by the collection flow instead of
        the upload-batch creator. The reference marks this with
        VdafHasAggregationParameter (aggregator_core/src/lib.rs:44) but
        punts on the DAP plumbing (README.md:9-11); here it is
        implemented."""
        return self.kind == "poplar1"

    @property
    def fails_prep_init(self) -> bool:
        return self.kind == "fake_fails_prep_init"

    @property
    def fails_prep_step(self) -> bool:
        return self.kind == "fake_fails_prep_step"

    def fails_at(self, stage: str) -> bool:
        """Single seam for the fake-failure dispatch sites: stage is
        "init" (prepare initialization) or "step" (continue/finish)."""
        assert stage in ("init", "step")
        return self.fails_prep_init if stage == "init" else self.fails_prep_step

    def to_dict(self) -> dict:
        d = {"kind": self.kind}
        for k in ("bits", "length", "chunk_length", "block_size", "max_blocks"):
            if getattr(self, k):
                d[k] = getattr(self, k)
        if self.xof_mode != "fast":
            d["xof_mode"] = self.xof_mode
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "VdafInstance":
        return cls(
            d["kind"],
            bits=d.get("bits", 0),
            length=d.get("length", 0),
            chunk_length=d.get("chunk_length", 0),
            xof_mode=d.get("xof_mode", "fast"),
            block_size=d.get("block_size", 0),
            max_blocks=d.get("max_blocks", 0),
        )


@lru_cache(maxsize=None)
def circuit_for(inst: VdafInstance) -> Circuit:
    assert inst.xof_mode in ("fast", "draft"), inst.xof_mode
    ch = inst.chunk_length or None
    if inst.kind == "count":
        return Count()
    if inst.kind == "sum":
        return Sum(bits=inst.bits)
    if inst.kind == "sumvec":
        return SumVec(length=inst.length, bits=inst.bits, chunk_length=ch)
    if inst.kind == "sparse_sumvec":
        return SparseSumVec(
            length=inst.length,
            block_size=inst.block_size,
            max_blocks=inst.max_blocks,
            bits=inst.bits,
            chunk_length=ch,
        )
    if inst.kind == "histogram":
        return Histogram(length=inst.length, chunk_length=ch)
    if inst.kind == "countvec":
        return SumVec(length=inst.length, bits=1, chunk_length=ch)
    if inst.kind == "fixedpoint":
        return FixedPointVec(length=inst.length, bits=inst.bits, chunk_length=ch)
    if inst.kind in ("fake", "fake_fails_prep_init", "fake_fails_prep_step", "fake_two_round"):
        return Count()
    if inst.kind == "poplar1":
        raise ValueError(
            "Poplar1 has no FLP circuit: the aggregator dispatches it to "
            "aggregator.poplar1_ops (IDPF + sketch over per-parameter "
            "prefixes), not the Prio3 engine"
        )
    raise ValueError(f"unknown VDAF kind {inst.kind!r}")


@lru_cache(maxsize=None)
def prio3_host(inst: VdafInstance) -> Prio3:
    """Host (scalar) implementation: clients, tools, oracles."""
    if inst.kind == "sparse_sumvec":
        return Prio3Sparse(circuit_for(inst), mode=inst.xof_mode)
    return Prio3(circuit_for(inst), mode=inst.xof_mode)


@lru_cache(maxsize=None)
def prio3_batched(inst: VdafInstance) -> Prio3Batched:
    """Device (batched) implementation: the aggregator hot path.

    Cached so repeated dispatch returns the identical instance and jit
    caches keyed on it never recompile. Draft-framing (VDAF-07)
    instances run the device draft engine when their streams are short
    enough for the sequential sponge (Count, Sum, small vectors —
    vdaf.draft_jax); longer draft tasks raise and fall back to the host
    engine (aggregator.engine_cache dispatches)."""
    if inst.xof_mode != "fast":
        from .draft_jax import Prio3BatchedDraft

        circ = circuit_for(inst)
        if not Prio3BatchedDraft.supports_circuit(circ):
            raise ValueError(
                "draft-mode streams too long for the device sponge or too "
                "large for the device memory budget (vdaf.feasibility); "
                "this task runs the host engine"
            )
        return Prio3BatchedDraft(circ)
    return Prio3Batched(circuit_for(inst))


__all__ = [
    "VERIFY_KEY_LENGTH",
    "VdafInstance",
    "circuit_for",
    "prio3_host",
    "prio3_batched",
    "optimal_chunk_length",
]
