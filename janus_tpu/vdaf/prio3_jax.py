"""Batched device Prio3: shard / prepare / aggregate over report batches.

The reference's hot path runs one report at a time through the `prio`
crate's Prio3 (leader: aggregation_job_driver.rs:363,580; helper:
aggregator.rs:1777-1797). Here every step is a single traced JAX
computation over [batch]-leading arrays:

  - seeds/nonces/XOF-derived values are [batch, 2] uint64 lane arrays
    (16-byte strings in little-endian u64 lanes),
  - field vectors are limb-tuple values (janus_tpu.fields.jfield),
  - XOF expansion runs on device via the batched Keccak
    (janus_tpu.vdaf.keccak_jax) with the same lane-aligned stream
    framing as the host XofCtr128 — host and device are
    byte-identical, so a host-sharded report verifies on device and
    vice versa (differential-tested).

Validity is a boolean lane mask throughout; invalid reports never
break the batch (SURVEY.md section 7 "Ragged/failure-laden batches").
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..fields.jfield import fmap
from .engine import (
    BatchedCircuit,
    batched_circuit,
    flp_decide_batched,
    flp_prove_batched,
    flp_query_batched,
    flp_query_streamed,
    sliced_meas_source,
    stream_plan,
)
from .keccak_jax import (
    ctr_stream_lanes,
    expand_field_vec,
    tree_digest_lanes,
)
from .reference import AGG1, Circuit
from .xof import (
    DST_SIZE,
    SEED_SIZE,
    USAGE_JOINT_RAND_PART,
    USAGE_JOINT_RAND_SEED,
    USAGE_JOINT_RANDOMNESS,
    USAGE_MEASUREMENT_SHARE,
    USAGE_PROOF_SHARE,
    USAGE_PROVE_RANDOMNESS,
    USAGE_QUERY_RANDOMNESS,
    dst,
)

AGG0 = (0).to_bytes(8, "little")
SEED_LANES = SEED_SIZE // 8  # 2
DST_LANES = DST_SIZE // 8  # 2


def bytes_to_lane_batch(rows: list[bytes]) -> np.ndarray:
    """[batch] of 8k-byte strings -> [batch, k] u64 lanes."""
    return np.stack([np.frombuffer(r, dtype="<u8") for r in rows]).astype(np.uint64)


def lanes_to_bytes(lanes) -> list[bytes]:
    a = np.asarray(lanes, dtype="<u8")
    return [row.tobytes() for row in a]


def field_value_to_enc_lanes(jf, v):
    """Field vector [batch, n] -> little-endian encoded lanes [batch, n*LIMBS].

    Matches Field.encode_vec byte-for-byte: each element is ENCODED_SIZE
    little-endian bytes, i.e. its limbs lo..hi in lane order.
    """
    if jf.LIMBS == 1:
        return v[0]
    return jnp.stack(v, axis=-1).reshape(v[0].shape[0], -1)


class Prio3Batched:
    """Batched device Prio3 for one validity circuit.

    Instances are hashable-by-identity and meant to be constructed once
    per task (cache them; every method is pure and jit-safe).
    """

    NUM_SHARES = 2
    # Streamed-query controls. _can_stream: the FLP query runs via
    # engine.flp_query_streamed at large input_len (the query math is
    # XOF-framing independent, so the draft engine streams too).
    # _stream_expand_offsets: the helper share expansion supports
    # random-access counter offsets, so the share never materializes
    # (true only for this class's counter-mode framing; the draft's
    # sequential sponge materializes the share once and slices it).
    _can_stream = True
    _stream_expand_offsets = True

    def __init__(self, circuit: Circuit):
        self.circ = circuit
        self.bc: BatchedCircuit = batched_circuit(circuit)
        self.jf = self.bc.jf
        self._shard_jit = None

    @property
    def shard_jit(self):
        """jit-compiled shard (client/load-generator batches); eager
        per-op dispatch of the 16k-element circuits is minutes of
        overhead that the traced version doesn't pay."""
        if self._shard_jit is None:
            import jax

            self._shard_jit = jax.jit(self.shard)
        return self._shard_jit

    # --- XOF plumbing (device) ---
    def _dst(self, usage: int) -> bytes:
        return dst(self.circ.algo_id, usage)

    def _prefix_parts(self, usage: int, seed_lanes, binder_parts, binder_len: int, batch: int):
        """Counter-mode prefix (dst||seed||binder') as lane segments.

        Binders longer than INLINE_BINDER_MAX are replaced by their tree
        digest, matching xof.XofCtr128 exactly.
        """
        from .xof import INLINE_BINDER_MAX, TREE_DIGEST_SIZE

        if binder_len > INLINE_BINDER_MAX:
            # Restricted to joint-rand-part: SECURITY-NOTES.md #2.
            # Explicit raise so the boundary survives python -O.
            if usage != USAGE_JOINT_RAND_PART:
                raise ValueError(
                    f"tree-digest substitution restricted to joint-rand-part "
                    f"(SECURITY-NOTES.md #2); got usage {usage}"
                )
            digest = tree_digest_lanes(binder_parts, binder_len, batch)
            binder_parts = [(0, digest)]
            binder_len = TREE_DIGEST_SIZE
        parts = [(0, self._dst(usage))]
        if isinstance(seed_lanes, (bytes, bytearray)):
            parts.append((DST_LANES, bytes(seed_lanes)))
        else:
            parts.append((DST_LANES, seed_lanes))
        off = DST_LANES + SEED_LANES
        for rel_off, content in binder_parts:
            parts.append((off + rel_off, content))
        return parts, DST_SIZE + SEED_SIZE + binder_len

    def _expand_vec(self, usage: int, seed_lanes, binder_parts, binder_len: int, length: int):
        """Field vector [batch, length] from per-report seeds + binder."""
        batch = seed_lanes.shape[0]
        parts, prefix_len = self._prefix_parts(
            usage, seed_lanes, binder_parts, binder_len, batch
        )
        return expand_field_vec(self.jf, parts, prefix_len, batch, length)

    def _derive_seed(self, usage: int, seed_lanes, binder_parts, binder_len: int):
        """[batch, 2] output seed lanes."""
        batch = seed_lanes.shape[0] if hasattr(seed_lanes, "shape") else binder_parts[0][1].shape[0]
        parts, prefix_len = self._prefix_parts(
            usage, seed_lanes, binder_parts, binder_len, batch
        )
        out = ctr_stream_lanes(parts, prefix_len, batch, 1)
        return out[:, 0, :SEED_LANES]

    def _expand_share(self, seed_lanes, usage: int, length: int):
        """Expand helper measurement/proof share: binder = AGG1."""
        return self._expand_vec(usage, seed_lanes, [(0, AGG1)], 8, length)

    def _expand_share_source(self, seed_lanes, usage: int, plan):
        """meas_source for flp_query_streamed: expands the helper share a
        group at a time via the counter-mode block offset (the expanded
        share never fully materializes). plan.group is block-aligned
        (7 Field128 elements per counter block, engine.stream_plan)."""
        from .keccak_jax import _assemble_segments, expand_field_vec

        batch = seed_lanes.shape[0]
        parts, prefix_len = self._prefix_parts(usage, seed_lanes, [(0, AGG1)], 8, batch)
        # assemble the (loop-invariant) prefix once, outside the scan
        prefix = _assemble_segments(parts, prefix_len // 8, batch)
        blocks_per_step = plan.group // 7

        def src(step):
            return expand_field_vec(
                self.jf,
                [(0, prefix)],
                prefix_len,
                batch,
                plan.group,
                block_offset=step * blocks_per_step,
            )

        return src

    def _part_binder(self, agg_id: int, meas, helper_seed):
        """The share binder for joint-rand part derivation (as lanes):
        the leader binds its full encoded measurement share; the helper
        binds its 16-byte seed (the fast-mode shortcut,
        SECURITY-NOTES.md #3). Draft mode overrides to bind the full
        expanded share for both, per the spec."""
        if agg_id == 0:
            return field_value_to_enc_lanes(self.jf, meas)
        return helper_seed

    def _joint_rand_part(self, agg_id: int, blind_lanes, nonce_lanes, share_binder_lanes):
        """derive_seed(blind, ..., agg_id8 + nonce + share_binder)."""
        agg = AGG0 if agg_id == 0 else AGG1
        n_binder_lanes = share_binder_lanes.shape[-1]
        return self._derive_seed(
            USAGE_JOINT_RAND_PART,
            blind_lanes,
            [(0, agg), (1, nonce_lanes), (1 + SEED_LANES, share_binder_lanes)],
            8 + SEED_SIZE + 8 * n_binder_lanes,
        )

    def _joint_rand_seed(self, part0_lanes, part1_lanes):
        return self._derive_seed(
            USAGE_JOINT_RAND_SEED,
            b"\x00" * SEED_SIZE,
            [(0, part0_lanes), (SEED_LANES, part1_lanes)],
            2 * SEED_SIZE,
        )

    def _joint_rand(self, jr_seed_lanes):
        return self._expand_vec(
            USAGE_JOINT_RANDOMNESS, jr_seed_lanes, [], 0, self.circ.joint_rand_len
        )

    def _query_rand(self, verify_key, nonce_lanes):
        """verify_key: 16 bytes (one task — baked into the trace) OR a
        [batch, 2] u64 lane array (cross-TASK coalesced dispatches: each
        lane carries its own task's key through the XOF, exactly like
        the per-lane nonce segment)."""
        batch = nonce_lanes.shape[0]
        if isinstance(verify_key, (bytes, bytearray)):
            assert len(verify_key) == SEED_SIZE
        parts = [
            (0, self._dst(USAGE_QUERY_RANDOMNESS)),
            (DST_LANES, verify_key),
            (DST_LANES + SEED_LANES, nonce_lanes),
        ]
        prefix_len = DST_SIZE + SEED_SIZE + SEED_SIZE
        return expand_field_vec(
            self.jf, parts, prefix_len, batch, self.circ.query_rand_len
        )

    @property
    def uses_joint_rand(self) -> bool:
        return self.circ.joint_rand_len > 0

    # ------------------------------------------------------------------
    # shard (client / load-generator side, batched on device)
    # ------------------------------------------------------------------
    def shard(self, inp, nonce_lanes, rand_lanes):
        """Shard a batch of encoded measurements.

        inp: field value [batch, input_len] (from bc.encode_batch);
        nonce_lanes: [batch, 2]; rand_lanes: [batch, n_seeds, 2] with
        n_seeds = 2 (+2 with joint rand): prove, helper(, blind0, blind1).

        Returns dict with public_parts [batch, 2, 2] (or None),
        leader_meas, leader_proof (field values), and passthrough
        helper_seed/blind lanes.
        """
        circ = self.circ
        jf = self.jf
        prove_seed = rand_lanes[:, 0]
        helper_seed = rand_lanes[:, 1]
        helper_meas = self._expand_share(helper_seed, USAGE_MEASUREMENT_SHARE, circ.input_len)
        leader_meas = jf.sub(inp, helper_meas)

        public_parts = None
        joint_rand = ()
        blind0 = blind1 = None
        if self.uses_joint_rand:
            blind0 = rand_lanes[:, 2]
            blind1 = rand_lanes[:, 3]
            part0 = self._joint_rand_part(
                0, blind0, nonce_lanes, self._part_binder(0, leader_meas, None)
            )
            part1 = self._joint_rand_part(
                1, blind1, nonce_lanes, self._part_binder(1, helper_meas, helper_seed)
            )
            jr_seed = self._joint_rand_seed(part0, part1)
            joint_rand = self._joint_rand(jr_seed)
            public_parts = jnp.stack([part0, part1], axis=1)

        prove_rand = self._expand_vec(
            USAGE_PROVE_RANDOMNESS, prove_seed, [], 0, circ.prove_rand_len
        )
        proof = flp_prove_batched(self.bc, inp, prove_rand, joint_rand)
        helper_proof = self._expand_share(helper_seed, USAGE_PROOF_SHARE, circ.proof_len)
        leader_proof = jf.sub(proof, helper_proof)
        return {
            "public_parts": public_parts,
            "leader_meas": leader_meas,
            "leader_proof": leader_proof,
            "helper_seed": helper_seed,
            "blind0": blind0,
            "blind1": blind1,
        }

    # ------------------------------------------------------------------
    # prepare (aggregator side)
    # ------------------------------------------------------------------
    def prepare_init_leader(self, verify_key: bytes, nonce_lanes, public_parts, meas, proof, blind0):
        """Leader prepare-init over a batch.

        Returns (out_share, corrected_seed_lanes|None, verifier, own_part|None).
        """
        return self._prepare_init(
            verify_key, 0, nonce_lanes, public_parts, meas, proof, blind0, None
        )

    def prepare_init_helper(self, verify_key: bytes, nonce_lanes, public_parts, helper_seed, blind1):
        circ = self.circ
        plan = stream_plan(self.bc) if self._can_stream else None
        if plan is not None:
            proof = self._expand_share(helper_seed, USAGE_PROOF_SHARE, circ.proof_len)
            if self._stream_expand_offsets:
                # fully streamed: the expanded measurement share never
                # materializes (the fast-mode joint-rand binder is the
                # seed, so nothing else needs the whole share)
                src = self._expand_share_source(helper_seed, USAGE_MEASUREMENT_SHARE, plan)
                meas = None
            else:
                # draft framing: the sponge expansion is sequential (no
                # random access) and the joint-rand binder needs the
                # whole share — materialize once, stream the query over
                # slices (kills the O(input_len) wire intermediates)
                meas = self._expand_share(helper_seed, USAGE_MEASUREMENT_SHARE, circ.input_len)
                src = sliced_meas_source(self.bc, plan, meas)
            return self._prepare_init_streamed(
                verify_key, 1, nonce_lanes, public_parts, src, proof, blind1, helper_seed,
                plan, meas=meas,
            )
        meas = self._expand_share(helper_seed, USAGE_MEASUREMENT_SHARE, circ.input_len)
        proof = self._expand_share(helper_seed, USAGE_PROOF_SHARE, circ.proof_len)
        return self._prepare_init(
            verify_key, 1, nonce_lanes, public_parts, meas, proof, blind1, helper_seed
        )

    def _prepare_init(self, verify_key, agg_id, nonce_lanes, public_parts, meas, proof, blind, helper_seed):
        circ = self.circ
        jf = self.jf
        plan = stream_plan(self.bc) if self._can_stream and agg_id == 0 else None
        if plan is not None:
            # leader streamed: meas exists (staged input), but the query's
            # O(input_len) wire intermediates are replaced by group folds
            src = sliced_meas_source(self.bc, plan, meas)
            return self._prepare_init_streamed(
                verify_key, agg_id, nonce_lanes, public_parts, src, proof, blind, helper_seed,
                plan, meas=meas,
            )
        corrected_seed = None
        own_part = None
        joint_rand = ()
        if self.uses_joint_rand:
            binder = self._part_binder(agg_id, meas, helper_seed)
            own_part = self._joint_rand_part(agg_id, blind, nonce_lanes, binder)
            other = public_parts[:, 1 - agg_id]
            parts = (own_part, other) if agg_id == 0 else (other, own_part)
            corrected_seed = self._joint_rand_seed(*parts)
            joint_rand = self._joint_rand(corrected_seed)
        query_rand = self._query_rand(verify_key, nonce_lanes)
        verifier = flp_query_batched(
            self.bc, meas, proof, query_rand, joint_rand, self.NUM_SHARES
        )
        out_share = self.bc.truncate(meas)
        return out_share, corrected_seed, verifier, own_part

    def _prepare_init_streamed(
        self, verify_key, agg_id, nonce_lanes, public_parts, meas_source, proof, blind,
        helper_seed, plan, meas=None,
    ):
        """Streamed prepare-init: query + truncate via flp_query_streamed.

        Field-element identical to _prepare_init (differential-tested in
        tests/test_stream_query.py); the joint-rand derivation is
        unchanged (leader binder = staged meas, helper binder = seed)."""
        corrected_seed = None
        own_part = None
        joint_rand = ()
        if self.uses_joint_rand:
            binder = self._part_binder(agg_id, meas, helper_seed)
            own_part = self._joint_rand_part(agg_id, blind, nonce_lanes, binder)
            other = public_parts[:, 1 - agg_id]
            parts = (own_part, other) if agg_id == 0 else (other, own_part)
            corrected_seed = self._joint_rand_seed(*parts)
            joint_rand = self._joint_rand(corrected_seed)
        query_rand = self._query_rand(verify_key, nonce_lanes)
        verifier, out_share = flp_query_streamed(
            self.bc, plan, meas_source, proof, query_rand, joint_rand, self.NUM_SHARES
        )
        return out_share, corrected_seed, verifier, own_part

    def prep_shares_to_prep(self, verifier0, verifier1, part0=None, part1=None):
        """Combine both verifier shares: (accept_mask [batch], prep_msg_lanes|None)."""
        jf = self.jf
        verifier = jf.add(verifier0, verifier1)
        mask = flp_decide_batched(self.bc, verifier)
        prep_msg = None
        if self.uses_joint_rand:
            prep_msg = self._joint_rand_seed(part0, part1)
        return mask, prep_msg

    def prepare_finish(self, corrected_seed, prep_msg, mask):
        """Final joint-randomness equality check, folded into the mask."""
        if self.uses_joint_rand:
            eq = jnp.all(prep_msg == corrected_seed, axis=-1)
            mask = mask & eq
        return mask

    # ------------------------------------------------------------------
    # aggregate / unshard
    # ------------------------------------------------------------------
    def aggregate(self, out_shares, mask):
        """Masked sum over the batch axis -> aggregate share [output_len].

        Invalid lanes contribute zero (the static-shape equivalent of the
        reference skipping failed reports at accumulate time,
        aggregator/src/aggregator/accumulator.rs:76-122).
        """
        from ..fields.jfield import fsum

        jf = self.jf
        masked = fmap(lambda x: jnp.where(mask[:, None], x, jnp.zeros_like(x)), out_shares)
        return fsum(jf, masked, axis=0)

    def aggregate_buckets(self, out_shares, bucket_idx, k: int):
        """Per-bucket masked sums -> [k, output_len] field value.

        bucket_idx: [batch] int32 assigning each lane to a batch bucket
        (0..k-1); rejected lanes carry -1 and contribute nowhere. One
        traced computation replaces k separate masked aggregates (k mask
        uploads + k fetches) — the delta kernel of the device-resident
        accumulator path. Field-element identical to calling
        `aggregate(out_shares, bucket_idx == j)` per j (same adds in the
        same lane order).
        """
        jf = self.jf
        # deliberately k unrolled masked reduces, not one one-hot/segment
        # pass: XLA schedules them sequentially so peak HBM stays at ONE
        # bucket's working set (a [n, k, output_len] one-hot intermediate
        # is O(k) memory — fatal at north-star output lengths), and
        # segment_sum's plain integer adds would overflow the field
        # limbs without jf.add's interleaved modular reduction
        parts = [self.aggregate(out_shares, bucket_idx == j) for j in range(k)]
        return tuple(
            jnp.stack([p[i] for p in parts], axis=0) for i in range(jf.LIMBS)
        )

    def merge_agg_shares(self, a, b):
        return self.jf.add(a, b)

    def scatter_rows(self, acc, values, flat_idx):
        """Scatter-add each report's compact lanes into a dense logical
        accumulator — the sparse-sumvec aggregation kernel (ISSUE 17).

        acc: [L] logical accumulator (field limb tuple, L = logical
        length); values: [b, cm] compact out-share rows; flat_idx:
        [b, cm] int32 flat logical positions with DROPPED lanes (padding
        blocks, rejected reports, other buckets) set to the
        out-of-bounds sentinel L. The sentinel is POSITIVE on purpose:
        a negative index would wrap under jnp gather/scatter semantics
        and silently corrupt lane L-1.

        A lax.scan over reports keeps peak memory at one report's
        gather (a one-hot matmul would materialize [b, cm, L]); each
        step is gather -> modular add -> unique-index scatter. Within a
        report the valid flat indices are unique by construction (block
        indices are validated strictly increasing), so the
        gather/set pair is an exact modular scatter-ADD; cross-report
        duplicates are handled by the scan's sequencing. Dropped lanes
        read clamped garbage and then DROP the write (mode="drop"), so
        they contribute nothing. Field-element identical to
        reference.Prio3Sparse.aggregate_sparse over the same rows.
        """
        jf = self.jf

        def step(carry, xs):
            ix = xs[-1]
            v = tuple(xs[:-1])
            cur = tuple(x[ix] for x in carry)
            s = jf.add(cur, v)
            new = tuple(
                c.at[ix].set(sv, mode="drop") for c, sv in zip(carry, s)
            )
            return new, None

        acc, _ = jax.lax.scan(step, acc, (*values, flat_idx))
        return acc
