"""Host reference implementation of Prio3 (FLP + XOF + secret sharing).

This is the exact-semantics oracle for the batched TPU engine
(janus_tpu.vdaf.engine), and the implementation used by clients/tools
for single reports. It owns the capability the reference outsources to
the external `prio` crate (SURVEY.md section 2.2): Prio3 Count / Sum /
SumVec / Histogram over Field64/Field128 with the FLP proof system of
BBCG+19 as specified by the VDAF drafts.

Structure of the FLP ("fully linear proof"):
  * A validity circuit C evaluates arithmetic over the input, calling
    nonlinear *gadgets* (degree-2 polynomials here) some number of times.
  * prove(): the prover interpolates per-wire polynomials through the
    gadget-call inputs (plus a random wire seed at alpha^0) and includes
    each gadget's composed output polynomial in the proof.
  * query(): each verifier evaluates C on its additive share, reading
    gadget outputs from the proof polynomial (linear), and emits a
    verifier share: [circuit output, wire evals at random t, proof
    poly eval at t] per gadget.
  * decide(): on the combined verifier message, the circuit output must
    be 0 and each gadget identity G(wires(t)) == proofpoly(t) must hold.

Divergence note (documented, performance-motivated): the joint-rand
part binder for *seed-expanded* helper shares hashes the 16-byte seed
rather than the expanded share encoding; the seed uniquely determines
the share, so binding is preserved while keeping hashing O(1) per
report. The reference's hot loop pays the full hash on CPU
(aggregator/src/aggregator.rs:1633-1797 does all of this per report).
Full analysis: SECURITY-NOTES.md #3 (seed binder), #4 (fixed
4-candidate eval point).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..fields.field import Field, Field64, Field128
from .xof import (
    SEED_SIZE,
    USAGE_JOINT_RAND_PART,
    USAGE_JOINT_RAND_SEED,
    USAGE_JOINT_RANDOMNESS,
    USAGE_MEASUREMENT_SHARE,
    USAGE_PROOF_SHARE,
    USAGE_PROVE_RANDOMNESS,
    USAGE_QUERY_RANDOMNESS,
    XofShake128,
    XofSponge128,
    draft_dst,
    dst,
)

VERIFY_KEY_SIZE = SEED_SIZE
AGG1 = (1).to_bytes(8, "little")  # helper aggregator id, lane-aligned
EVAL_POINT_CANDIDATES = 4  # fixed draw per gadget; first t with t^m != 1 wins


def next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


# ---------------------------------------------------------------------------
# Host NTT (small sizes; constants feed the device NTT too)
# ---------------------------------------------------------------------------


def ntt(field: type[Field], coeffs: list[int], n: int) -> list[int]:
    """Evaluate a polynomial (len <= n coeffs) at the n-th roots w^0..w^{n-1}."""
    a = list(coeffs) + [0] * (n - len(coeffs))
    _ntt_inplace(field, a, field.root_of_unity(n))
    return a


def intt(field: type[Field], evals: list[int]) -> list[int]:
    """Inverse: values at w^0..w^{n-1} -> coefficients."""
    n = len(evals)
    a = list(evals)
    _ntt_inplace(field, a, field.inv(field.root_of_unity(n)))
    n_inv = field.inv(n)
    return [field.mul(x, n_inv) for x in a]


def _ntt_inplace(field: type[Field], a: list[int], root: int) -> None:
    n = len(a)
    assert n & (n - 1) == 0
    p = field.MODULUS
    # bit-reversal permutation
    j = 0
    for i in range(1, n):
        bit = n >> 1
        while j & bit:
            j ^= bit
            bit >>= 1
        j |= bit
        if i < j:
            a[i], a[j] = a[j], a[i]
    length = 2
    while length <= n:
        w_len = pow(root, n // length, p)
        for start in range(0, n, length):
            w = 1
            for k in range(length // 2):
                u = a[start + k]
                v = a[start + k + length // 2] * w % p
                a[start + k] = (u + v) % p
                a[start + k + length // 2] = (u - v) % p
                w = w * w_len % p
        length <<= 1


def poly_eval(field: type[Field], coeffs: list[int], x: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % field.MODULUS
    return acc


# ---------------------------------------------------------------------------
# Gadgets
# ---------------------------------------------------------------------------


class Gadget:
    arity: int
    degree: int

    def eval(self, field: type[Field], inputs: list[int]) -> int:
        raise NotImplementedError


class Mul(Gadget):
    arity = 2
    degree = 2

    def eval(self, field, inputs):
        return field.mul(inputs[0], inputs[1])


class PolyEval(Gadget):
    """p(x) for a fixed polynomial p; arity 1."""

    arity = 1

    def __init__(self, coeffs: list[int]):
        self.coeffs = coeffs
        self.degree = len(coeffs) - 1

    def eval(self, field, inputs):
        return poly_eval(field, self.coeffs, inputs[0])


class ParallelSum(Gadget):
    """sum_{c} inner(inputs[c*k : (c+1)*k]) for an inner gadget of arity k."""

    def __init__(self, inner: Gadget, count: int):
        self.inner = inner
        self.count = count
        self.arity = inner.arity * count
        self.degree = inner.degree

    def eval(self, field, inputs):
        k = self.inner.arity
        acc = 0
        for c in range(self.count):
            acc = field.add(acc, self.inner.eval(field, inputs[c * k : (c + 1) * k]))
        return acc


# ---------------------------------------------------------------------------
# Validity circuits
# ---------------------------------------------------------------------------


@dataclass
class GadgetUse:
    gadget: Gadget
    calls: int

    @property
    def wire_poly_len(self) -> int:  # m
        return next_pow2(1 + self.calls)

    @property
    def gadget_poly_len(self) -> int:  # degree*(m-1) + 1 coefficients
        return self.gadget.degree * (self.wire_poly_len - 1) + 1


class Circuit:
    """A validity circuit. Subclasses define encode/truncate/decode and the
    gadget-call schedule. Constraint (relied on by query()): gadget inputs
    are affine in (input, joint_rand-scaled input terms) and never depend
    on other gadget outputs; the final output is affine in gadget outputs.
    """

    FIELD: type[Field]
    input_len: int
    joint_rand_len: int
    output_len: int
    gadget_uses: list[GadgetUse]
    # measurement type tag for registries
    algo_id: int

    @property
    def prove_rand_len(self) -> int:
        return sum(g.gadget.arity for g in self.gadget_uses)

    @property
    def query_rand_len(self) -> int:
        return EVAL_POINT_CANDIDATES * len(self.gadget_uses)

    @property
    def proof_len(self) -> int:
        return sum(g.gadget.arity + g.gadget_poly_len for g in self.gadget_uses)

    @property
    def verifier_len(self) -> int:
        return 1 + sum(g.gadget.arity + 1 for g in self.gadget_uses)

    # --- measurement plumbing ---
    def encode(self, measurement) -> list[int]:
        raise NotImplementedError

    def truncate(self, input_: list[int]) -> list[int]:
        raise NotImplementedError

    def decode(self, output: list[int], num_measurements: int):
        raise NotImplementedError

    # --- circuit schedule ---
    def gadget_inputs(self, inp: list[int], joint_rand: list[int], shares_inv: int):
        """Per gadget-use: list over calls of input lists (arity long)."""
        raise NotImplementedError

    def finish(
        self,
        inp: list[int],
        joint_rand: list[int],
        gadget_outputs: list[list[int]],
        shares_inv: int,
    ) -> int:
        """Affine combination producing the single circuit output."""
        raise NotImplementedError


class Count(Circuit):
    """measurement in {0,1}; check x*x - x == 0. Field64, one Mul call."""

    FIELD = Field64
    input_len = 1
    joint_rand_len = 0
    output_len = 1
    algo_id = 0x00000000

    def __init__(self):
        self.gadget_uses = [GadgetUse(Mul(), 1)]

    def encode(self, measurement):
        assert measurement in (0, 1)
        return [measurement]

    def truncate(self, input_):
        return list(input_)

    def decode(self, output, num_measurements):
        return output[0]

    def gadget_inputs(self, inp, joint_rand, shares_inv):
        return [[[inp[0], inp[0]]]]

    def finish(self, inp, joint_rand, gadget_outputs, shares_inv):
        return self.FIELD.sub(gadget_outputs[0][0], inp[0])


class Sum(Circuit):
    """measurement in [0, 2^bits); input = bit decomposition.

    Bit check via PolyEval(x^2 - x) per bit, combined with powers of one
    joint-rand element.
    """

    FIELD = Field128
    joint_rand_len = 1
    output_len = 1
    algo_id = 0x00000001

    def __init__(self, bits: int):
        self.bits = bits
        self.input_len = bits
        p = self.FIELD.MODULUS
        self.gadget_uses = [GadgetUse(PolyEval([0, p - 1, 1]), bits)]  # x^2 - x

    def encode(self, measurement):
        assert 0 <= measurement < (1 << self.bits)
        return [(measurement >> j) & 1 for j in range(self.bits)]

    def truncate(self, input_):
        F = self.FIELD
        acc = 0
        for j, b in enumerate(input_):
            acc = F.add(acc, F.mul(pow(2, j, F.MODULUS), b))
        return [acc]

    def decode(self, output, num_measurements):
        return output[0]

    def gadget_inputs(self, inp, joint_rand, shares_inv):
        return [[[x] for x in inp]]

    def finish(self, inp, joint_rand, gadget_outputs, shares_inv):
        F = self.FIELD
        r = joint_rand[0]
        acc = 0
        rp = r
        for out in gadget_outputs[0]:
            acc = F.add(acc, F.mul(rp, out))
            rp = F.mul(rp, r)
        return acc


class SumVec(Circuit):
    """Vector of `length` values, each in [0, 2^bits).

    Input is length*bits bit entries. Bit check: sum_i s_i * x_i * (x_i-1)
    == 0 with s_i = r^{i+1}, evaluated chunk-wise through a
    ParallelSum(Mul, chunk_length) gadget (the structural analog of the
    reference's sqrt-chunked ParallelSum gadget, core/src/task.rs:84-86).
    """

    FIELD = Field128
    joint_rand_len = 1
    algo_id = 0x00000002

    def __init__(self, length: int, bits: int, chunk_length: int | None = None):
        self.length = length
        self.bits = bits
        self.input_len = length * bits
        self.output_len = length
        self.chunk_length = chunk_length or optimal_chunk_length(self.input_len)
        calls = (self.input_len + self.chunk_length - 1) // self.chunk_length
        self.gadget_uses = [GadgetUse(ParallelSum(Mul(), self.chunk_length), calls)]

    def encode(self, measurement):
        assert len(measurement) == self.length
        out = []
        for v in measurement:
            assert 0 <= v < (1 << self.bits)
            out.extend((v >> j) & 1 for j in range(self.bits))
        return out

    def truncate(self, input_):
        F = self.FIELD
        out = []
        for i in range(self.length):
            acc = 0
            for j in range(self.bits):
                acc = F.add(
                    acc, F.mul(pow(2, j, F.MODULUS), input_[i * self.bits + j])
                )
            out.append(acc)
        return out

    def decode(self, output, num_measurements):
        return list(output)

    def gadget_inputs(self, inp, joint_rand, shares_inv):
        F = self.FIELD
        r = joint_rand[0]
        n = self.input_len
        ch = self.chunk_length
        calls = self.gadget_uses[0].calls
        rp = r
        out = []
        for k in range(calls):
            call_inputs = []
            for c in range(ch):
                i = k * ch + c
                if i < n:
                    call_inputs += [F.mul(rp, inp[i]), F.sub(inp[i], neg_share_const(F, shares_inv))]
                    rp = F.mul(rp, r)
                else:
                    call_inputs += [0, 0]
            out.append(call_inputs)
        return [out]

    def finish(self, inp, joint_rand, gadget_outputs, shares_inv):
        F = self.FIELD
        acc = 0
        for out in gadget_outputs[0]:
            acc = F.add(acc, out)
        return acc


def neg_share_const(field: type[Field], shares_inv: int) -> int:
    """The share of the public constant 1 held by each aggregator."""
    return shares_inv


def validate_block_indices(indices, n_logical_blocks: int, max_blocks: int) -> str | None:
    """The sparse block-index predicate (PREAMBLE-style compact
    encoding, PAPERS.md arXiv:2503.11897): indices are PUBLIC — both
    aggregators validate the same deterministic predicate on the same
    bytes, which is exactly as binding as proving it in the FLP would
    be (there is nothing secret to prove about public data). Rules:

      * exactly `max_blocks` entries;
      * each entry is either −1 (a padding lane) or in
        [0, n_logical_blocks);
      * the non-padding prefix is STRICTLY increasing (no duplicates,
        no descending runs — a duplicate index would let one report
        scatter twice into the same logical block);
      * once a padding lane appears, every later lane must also be
        padding (the compact layout is front-packed).

    Returns None when valid, else a short reason string.
    """
    indices = list(indices)
    if len(indices) != max_blocks:
        return f"expected {max_blocks} block indices, got {len(indices)}"
    prev = -1
    padding = False
    for t, ix in enumerate(indices):
        if ix == -1:
            padding = True
            continue
        if padding:
            return f"block index at lane {t} follows a padding lane"
        if not 0 <= ix < n_logical_blocks:
            return f"block index {ix} at lane {t} out of range [0, {n_logical_blocks})"
        if ix <= prev:
            return (
                f"block index {ix} at lane {t} not strictly increasing "
                f"(previous {prev})"
            )
        prev = ix
    return None


class SparsePublicShare(list):
    """Public share of a sparse report: the joint-randomness parts (the
    list payload, so every parts-only consumer — `list(public_share)`,
    unpacking, np.stack of the elements — keeps working) PLUS the
    public block indices. Carried intact from wire decode to the
    accumulate stage; the indices never enter the FLP."""

    __slots__ = ("indices",)

    def __init__(self, parts, indices):
        super().__init__(parts)
        self.indices = tuple(int(i) for i in indices)


class SparseSumVec(SumVec):
    """Block-sparse vector sum: a logical `length`-dim vector carried
    as up to `max_blocks` (block_index, dense `block_size`-value block)
    pairs (the PREAMBLE compact encoding, PAPERS.md arXiv:2503.11897).

    The FLP runs ENTIRELY at the compact length `max_blocks *
    block_size` — it is a plain SumVec bit-range check over the packed
    block values, so proof size and prepare cost scale with nonzeros,
    never the logical dimension. Block indices are PUBLIC (the
    documented PREAMBLE trade-off: the sparsity PATTERN leaks to the
    aggregators while every value stays secret-shared) and are
    validated by `validate_block_indices` at wire decode and
    prepare-init on both aggregators. Padding lanes carry index −1 and
    all-zero values; the zero values pass the bit check and scatter
    nothing.

    Aggregation is the part that differs: an output share is compact,
    and aggregating means SCATTERING each report's blocks into a dense
    logical accumulator by block index (`agg_output_len` =
    `length`) — the engine's scatter-merge kernel on device,
    `Prio3Sparse.aggregate_sparse` on the host."""

    algo_id = 0x000000F2  # outside the draft-registry range: janus_tpu extension

    def __init__(
        self,
        length: int,
        block_size: int,
        max_blocks: int,
        bits: int,
        chunk_length: int | None = None,
    ):
        if length <= 0 or block_size <= 0 or max_blocks <= 0:
            raise ValueError("sparse_sumvec geometry must be positive")
        if length % block_size:
            raise ValueError(
                f"logical length {length} must be a multiple of block_size {block_size}"
            )
        self.logical_length = length
        self.block_size = block_size
        self.max_blocks = max_blocks
        self.n_logical_blocks = length // block_size
        if max_blocks > self.n_logical_blocks:
            raise ValueError(
                f"max_blocks {max_blocks} exceeds the {self.n_logical_blocks} "
                "logical blocks"
            )
        super().__init__(length=max_blocks * block_size, bits=bits, chunk_length=chunk_length)

    # dense aggregate/unshard length: the logical dimension, not the
    # compact FLP width (Prio3.aggregate/unshard read this; the base
    # Circuit default is output_len)
    @property
    def agg_output_len(self) -> int:
        return self.logical_length

    def encode(self, measurement):
        """measurement: iterable of (block_index, block_values) pairs,
        block indices strictly increasing. Returns the COMPACT bit
        encoding (front-packed blocks, zero padding)."""
        values, indices = self.compact_values(measurement)
        del indices
        return super().encode(values)

    def compact_values(self, measurement):
        """(compact value row of `max_blocks * block_size` ints,
        front-packed indices of `max_blocks` ints with −1 padding)."""
        pairs = sorted((int(ix), list(vals)) for ix, vals in measurement)
        if len(pairs) > self.max_blocks:
            raise ValueError(f"more than {self.max_blocks} blocks")
        indices = [ix for ix, _ in pairs] + [-1] * (self.max_blocks - len(pairs))
        reason = validate_block_indices(indices, self.n_logical_blocks, self.max_blocks)
        if reason is not None:
            raise ValueError(reason)
        values = []
        for ix, vals in pairs:
            if len(vals) != self.block_size:
                raise ValueError(
                    f"block {ix} has {len(vals)} values, expected {self.block_size}"
                )
            values.extend(int(v) for v in vals)
        values.extend([0] * (self.block_size * (self.max_blocks - len(pairs))))
        return values, indices

    def encode_indices(self, measurement):
        """Front-packed public block indices (−1 padding) for the wire
        public share."""
        _, indices = self.compact_values(measurement)
        return indices

    def expand(self, indices, compact_row):
        """Scatter one compact row (length `max_blocks * block_size`)
        to the logical vector by its public indices — the host oracle
        for the device scatter kernel."""
        out = [0] * self.logical_length
        F = self.FIELD
        for t, ix in enumerate(indices):
            if ix == -1:
                continue
            base = ix * self.block_size
            seg = compact_row[t * self.block_size : (t + 1) * self.block_size]
            for o, v in enumerate(seg):
                out[base + o] = F.add(out[base + o], v)
        return out


class Histogram(Circuit):
    """One-hot vector of `length` buckets.

    Two checks combined with joint randomness: every entry is a bit
    (ParallelSum chunked as in SumVec, randomized by powers of jr[0]),
    and the entries sum to one (weighted by jr[1]).
    """

    FIELD = Field128
    joint_rand_len = 2
    algo_id = 0x00000003

    def __init__(self, length: int, chunk_length: int | None = None):
        self.length = length
        self.input_len = length
        self.output_len = length
        self.chunk_length = chunk_length or optimal_chunk_length(length)
        calls = (length + self.chunk_length - 1) // self.chunk_length
        self.gadget_uses = [GadgetUse(ParallelSum(Mul(), self.chunk_length), calls)]

    def encode(self, measurement):
        assert 0 <= measurement < self.length
        return [1 if i == measurement else 0 for i in range(self.length)]

    def truncate(self, input_):
        return list(input_)

    def decode(self, output, num_measurements):
        return list(output)

    def gadget_inputs(self, inp, joint_rand, shares_inv):
        F = self.FIELD
        r = joint_rand[0]
        ch = self.chunk_length
        calls = self.gadget_uses[0].calls
        rp = r
        out = []
        for k in range(calls):
            call_inputs = []
            for c in range(ch):
                i = k * ch + c
                if i < self.length:
                    call_inputs += [
                        F.mul(rp, inp[i]),
                        F.sub(inp[i], neg_share_const(F, shares_inv)),
                    ]
                    rp = F.mul(rp, r)
                else:
                    call_inputs += [0, 0]
            out.append(call_inputs)
        return [out]

    def finish(self, inp, joint_rand, gadget_outputs, shares_inv):
        F = self.FIELD
        bit_check = 0
        for out in gadget_outputs[0]:
            bit_check = F.add(bit_check, out)
        sum_check = F.sub(sum(inp) % F.MODULUS, shares_inv)  # sum - 1 (shared)
        return F.add(bit_check, F.mul(joint_rand[1], sum_check))


class FixedPointVec(Circuit):
    """Fixed-point vector with bounded L2 norm (capability parity with the
    reference's `Prio3FixedPointBoundedL2VecSum{16,32,64}` variants,
    core/src/task.rs:44-49 / prio's `fpvec_bounded_l2` feature,
    aggregator/Cargo.toml:17).

    Each of `length` entries is a signed fixed-point value v in
    [-2^(bits-1), 2^(bits-1)) representing v / 2^(bits-1) in [-1, 1).
    The client submits:

      - per entry, `bits` bits of the offset-binary value u = v + 2^(bits-1),
      - `norm_bits = 2*bits - 2` bits claiming N = sum_i v_i^2,

    and the circuit proves (a) every submitted value is a bit, and
    (b) the claimed norm equals the recomputed norm — which, with the
    claimed norm range-limited to [0, 2^(2b-2)) by its bit width,
    bounds the real L2 norm strictly below 1.

    Both checks ride ONE ParallelSum(Mul, chunk) gadget use: the first
    `calls_bits` calls carry joint-rand-weighted bit checks over all
    input positions, the remaining `calls_sq` calls carry (y_e, y_e)
    squares where y_e is the (affine) offset-corrected entry value.
    finish() = bit_check + jr[1] * (recomputed_norm - claimed_norm),
    affine in gadget outputs as query() requires.

    Soundness needs the integer norm to not wrap mod p:
    length * 4^(bits-1) < p. For bits=16/32 that allows huge vectors;
    for bits=64 it limits length <= 3 (the same Field128 ceiling that
    applies to the reference's 64-bit variant).
    """

    FIELD = Field128
    joint_rand_len = 2
    algo_id = 0x00FF0001  # private codepoint; not in the VDAF registry

    def __init__(self, length: int, bits: int, chunk_length: int | None = None):
        if bits not in (16, 32, 64):
            raise ValueError("fixed-point bits must be 16, 32 or 64")
        if length < 1:
            raise ValueError("length must be >= 1")
        if length * (1 << (2 * bits - 2)) >= self.FIELD.MODULUS:
            raise ValueError(
                f"length {length} too large for {bits}-bit entries: "
                "integer norm would overflow Field128"
            )
        self.length = length
        self.bits = bits
        self.norm_bits = 2 * bits - 2
        self.n_bits = length * bits + self.norm_bits  # bit-checked positions
        self.input_len = self.n_bits
        self.output_len = length
        self.offset = 1 << (bits - 1)
        self.chunk_length = chunk_length or optimal_chunk_length(self.n_bits)
        ch = self.chunk_length
        self.calls_bits = (self.n_bits + ch - 1) // ch
        self.calls_sq = (length + ch - 1) // ch
        self.gadget_uses = [
            GadgetUse(ParallelSum(Mul(), ch), self.calls_bits + self.calls_sq)
        ]

    # measurement: list of `length` signed ints v in [-2^(b-1), 2^(b-1))
    def encode(self, measurement):
        assert len(measurement) == self.length
        out = []
        norm = 0
        for v in measurement:
            v = int(v)
            assert -self.offset <= v < self.offset, "entry out of [-1, 1)"
            u = v + self.offset
            out.extend((u >> j) & 1 for j in range(self.bits))
            norm += v * v
        assert norm < (1 << self.norm_bits), "L2 norm must be < 1"
        out.extend((norm >> j) & 1 for j in range(self.norm_bits))
        return out

    def _entry_value(self, inp, e: int, shares_inv: int) -> int:
        """Share of v_e = sum_j 2^j u_bits - offset (offset split by share)."""
        F = self.FIELD
        acc = 0
        for j in range(self.bits):
            acc = F.add(acc, F.mul(pow(2, j, F.MODULUS), inp[e * self.bits + j]))
        return F.sub(acc, F.mul(self.offset, shares_inv))

    def truncate(self, input_):
        # Output the offset-binary u_e; decode() removes count*offset.
        F = self.FIELD
        out = []
        for e in range(self.length):
            acc = 0
            for j in range(self.bits):
                acc = F.add(
                    acc, F.mul(pow(2, j, F.MODULUS), input_[e * self.bits + j])
                )
            out.append(acc)
        return out

    def decode(self, output, num_measurements):
        F = self.FIELD
        half = F.MODULUS // 2
        res = []
        for u in output:
            t = F.sub(u, F.mul(self.offset, num_measurements))
            signed = t - F.MODULUS if t > half else t
            res.append(signed / self.offset)
        return res

    def gadget_inputs(self, inp, joint_rand, shares_inv):
        F = self.FIELD
        r = joint_rand[0]
        ch = self.chunk_length
        out = []
        rp = r
        for k in range(self.calls_bits):
            call_inputs = []
            for c in range(ch):
                i = k * ch + c
                if i < self.n_bits:
                    call_inputs += [
                        F.mul(rp, inp[i]),
                        F.sub(inp[i], neg_share_const(F, shares_inv)),
                    ]
                    rp = F.mul(rp, r)
                else:
                    call_inputs += [0, 0]
            out.append(call_inputs)
        for k in range(self.calls_sq):
            call_inputs = []
            for c in range(ch):
                e = k * ch + c
                if e < self.length:
                    y = self._entry_value(inp, e, shares_inv)
                    call_inputs += [y, y]
                else:
                    call_inputs += [0, 0]
            out.append(call_inputs)
        return [out]

    def finish(self, inp, joint_rand, gadget_outputs, shares_inv):
        F = self.FIELD
        outs = gadget_outputs[0]
        bit_check = 0
        for o in outs[: self.calls_bits]:
            bit_check = F.add(bit_check, o)
        norm = 0
        for o in outs[self.calls_bits :]:
            norm = F.add(norm, o)
        claimed = 0
        base = self.length * self.bits
        for j in range(self.norm_bits):
            claimed = F.add(claimed, F.mul(pow(2, j, F.MODULUS), inp[base + j]))
        return F.add(bit_check, F.mul(joint_rand[1], F.sub(norm, claimed)))


def fp_encode_floats(values, bits: int) -> list[int]:
    """Floats in [-1, 1) -> raw fixed-point ints (scale 2^(bits-1))."""
    scale = 1 << (bits - 1)
    out = []
    for x in values:
        v = int(round(float(x) * scale))
        v = max(-scale, min(scale - 1, v))
        out.append(v)
    return out


def optimal_chunk_length(measurement_length: int) -> int:
    """sqrt-ish chunk size balancing gadget arity vs calls (the same
    heuristic the reference applies, core/src/task.rs:84-86)."""
    return max(1, int(measurement_length**0.5))


# ---------------------------------------------------------------------------
# FLP generic prove / query / decide
# ---------------------------------------------------------------------------


def flp_prove(circ: Circuit, inp: list[int], prove_rand: list[int], joint_rand: list[int]) -> list[int]:
    F = circ.FIELD
    all_gadget_inputs = circ.gadget_inputs(inp, joint_rand, 1)
    proof: list[int] = []
    pr = iter(prove_rand)
    for use, calls_inputs in zip(circ.gadget_uses, all_gadget_inputs):
        g = use.gadget
        m = use.wire_poly_len
        seeds = [next(pr) for _ in range(g.arity)]
        wire_polys = []
        for j in range(g.arity):
            evals = [seeds[j]] + [ci[j] for ci in calls_inputs]
            evals += [0] * (m - len(evals))
            wire_polys.append(intt(F, _to_domain_order(F, evals, m)))
        n2 = next_pow2(g.degree * (m - 1) + 1)
        wire_evals = [ntt(F, wp, n2) for wp in wire_polys]
        gadget_evals = [
            g.eval(F, [wire_evals[j][i] for j in range(g.arity)]) for i in range(n2)
        ]
        gpoly = intt(F, gadget_evals)
        keep = use.gadget_poly_len
        assert all(c == 0 for c in gpoly[keep:]), "gadget poly degree overflow"
        proof += seeds + gpoly[:keep]
    assert len(proof) == circ.proof_len
    return proof


def _to_domain_order(field: type[Field], evals: list[int], m: int) -> list[int]:
    """Wire values are indexed seed@alpha^0, call k@alpha^{k+1}; the NTT
    domain is exactly that order, so this is the identity (kept for
    clarity/symmetry with the device engine)."""
    assert len(evals) == m
    return evals


def flp_query(
    circ: Circuit,
    inp_share: list[int],
    proof_share: list[int],
    query_rand: list[int],
    joint_rand: list[int],
    num_shares: int,
) -> list[int]:
    F = circ.FIELD
    shares_inv = F.inv(num_shares)
    all_gadget_inputs = circ.gadget_inputs(inp_share, joint_rand, shares_inv)
    qr = iter(query_rand)
    pf_pos = 0
    verifier: list[int] = []
    gadget_outputs = []
    per_gadget_tail: list[int] = []
    for use, calls_inputs in zip(circ.gadget_uses, all_gadget_inputs):
        g = use.gadget
        m = use.wire_poly_len
        seeds = proof_share[pf_pos : pf_pos + g.arity]
        pf_pos += g.arity
        gcoeffs = proof_share[pf_pos : pf_pos + use.gadget_poly_len]
        pf_pos += use.gadget_poly_len
        alpha = F.root_of_unity(m)
        t = _pick_eval_point([next(qr) for _ in range(EVAL_POINT_CANDIDATES)], F, m)
        # gadget outputs at call points alpha^{k+1}
        outs = [poly_eval(F, gcoeffs, pow(alpha, k + 1, F.MODULUS)) for k in range(use.calls)]
        gadget_outputs.append(outs)
        # wire polys (shares) and their evals at t
        for j in range(g.arity):
            evals = [seeds[j]] + [ci[j] for ci in calls_inputs]
            evals += [0] * (m - len(evals))
            wp = intt(F, evals)
            per_gadget_tail.append(poly_eval(F, wp, t))
        per_gadget_tail.append(poly_eval(F, gcoeffs, t))
    v = circ.finish(inp_share, joint_rand, gadget_outputs, shares_inv)
    verifier = [v] + per_gadget_tail
    assert len(verifier) == circ.verifier_len
    return verifier


def _pick_eval_point(candidates: list[int], field: type[Field], m: int) -> int:
    for t in candidates:
        if pow(t, m, field.MODULUS) != 1:
            return t
    raise ValueError("no valid FLP evaluation point in candidate draw")


def flp_decide(circ: Circuit, verifier: list[int]) -> bool:
    F = circ.FIELD
    if verifier[0] % F.MODULUS != 0:
        return False
    idx = 1
    for use in circ.gadget_uses:
        g = use.gadget
        wires = verifier[idx : idx + g.arity]
        y = verifier[idx + g.arity]
        idx += g.arity + 1
        if g.eval(F, wires) != y % F.MODULUS:
            return False
    return True


# ---------------------------------------------------------------------------
# Prio3 VDAF (multi-share; DAP uses exactly 2: leader=0, helper=1)
# ---------------------------------------------------------------------------


@dataclass
class LeaderShare:
    measurement_share: list[int]
    proof_share: list[int]
    joint_rand_blind: bytes | None


@dataclass
class HelperShare:
    seed: bytes
    joint_rand_blind: bytes | None


@dataclass
class PrepState:
    out_share: list[int]
    corrected_joint_rand_seed: bytes | None


@dataclass
class PrepShare:
    verifier_share: list[int]
    joint_rand_part: bytes | None


class Prio3:
    """Host Prio3 for one circuit.

    mode selects the XOF framing (per-task `xof_mode`):
      - "fast": counter-mode XofCtr128 with the TPU framing
        (SECURITY-NOTES.md #1-#5) — the intra-deployment default.
      - "draft": sequential-sponge XofSponge128 + rejection sampling +
        8-byte draft DSTs + single-byte aggregator ids + full-share
        joint-rand binders, following the VDAF-07 construction the
        reference's prio dependency implements (conformance caveat in
        XofSponge128's docstring). Host-only: prio3_batched refuses
        draft-mode instances.
    """

    NUM_SHARES = 2
    ROUNDS = 1

    def __init__(self, circuit: Circuit, mode: str = "fast"):
        assert mode in ("fast", "draft")
        self.circuit = circuit
        self.mode = mode
        self.xof = XofShake128 if mode == "fast" else XofSponge128

    # --- domain separation ---
    def _dst(self, usage: int) -> bytes:
        if self.mode == "draft":
            return draft_dst(self.circuit.algo_id, usage)
        return dst(self.circuit.algo_id, usage)

    def _agg_id_bytes(self, agg_id: int) -> bytes:
        # fast mode keeps ids lane-aligned (8-byte LE); draft uses the
        # draft's single byte
        if self.mode == "draft":
            return bytes([agg_id])
        return agg_id.to_bytes(8, "little")

    @property
    def uses_joint_rand(self) -> bool:
        return self.circuit.joint_rand_len > 0

    @property
    def rand_size(self) -> int:
        n = 2  # prove seed + helper seed
        if self.uses_joint_rand:
            n += self.NUM_SHARES  # blinds
        return n * SEED_SIZE

    # --- sharding (client side) ---
    def shard(self, measurement, nonce: bytes, rand: bytes | None = None):
        circ = self.circuit
        F = circ.FIELD
        if rand is None:
            rand = secrets.token_bytes(self.rand_size)
        assert len(rand) == self.rand_size
        seeds = [rand[i : i + SEED_SIZE] for i in range(0, len(rand), SEED_SIZE)]
        prove_seed, helper_seed = seeds[0], seeds[1]
        blinds = seeds[2:] if self.uses_joint_rand else [None, None]

        inp = circ.encode(measurement)
        agg1 = self._agg_id_bytes(1)
        helper_meas = self._expand(helper_seed, USAGE_MEASUREMENT_SHARE, agg1, circ.input_len)
        leader_meas = [F.sub(x, h) for x, h in zip(inp, helper_meas)]

        joint_rand: list[int] = []
        parts: list[bytes] = []
        if self.uses_joint_rand:
            # fast mode binds the helper's 16-byte seed (SECURITY-NOTES.md
            # #3); draft mode binds the full expanded share per the spec
            helper_binder = (
                helper_seed if self.mode == "fast" else self._encode_vec(helper_meas)
            )
            parts = [
                self._joint_rand_part(0, blinds[0], nonce, self._encode_vec(leader_meas)),
                self._joint_rand_part(1, blinds[1], nonce, helper_binder),
            ]
            jr_seed = self._joint_rand_seed(parts)
            joint_rand = self._next_vec(jr_seed, USAGE_JOINT_RANDOMNESS, b"", circ.joint_rand_len)

        prove_rand = self._next_vec(prove_seed, USAGE_PROVE_RANDOMNESS, b"", circ.prove_rand_len)
        proof = flp_prove(circ, inp, prove_rand, joint_rand)
        helper_proof = self._expand(helper_seed, USAGE_PROOF_SHARE, agg1, circ.proof_len)
        leader_proof = [F.sub(x, h) for x, h in zip(proof, helper_proof)]

        public_share = parts if self.uses_joint_rand else []
        shares = [
            LeaderShare(leader_meas, leader_proof, blinds[0]),
            HelperShare(helper_seed, blinds[1]),
        ]
        return public_share, shares

    # --- preparation (aggregator side) ---
    def prepare_init(
        self,
        verify_key: bytes,
        agg_id: int,
        nonce: bytes,
        public_share: list[bytes],
        input_share,
    ) -> tuple[PrepState, PrepShare]:
        circ = self.circuit
        F = circ.FIELD
        if isinstance(input_share, HelperShare):
            agg1 = self._agg_id_bytes(1)
            meas = self._expand(input_share.seed, USAGE_MEASUREMENT_SHARE, agg1, circ.input_len)
            proof = self._expand(input_share.seed, USAGE_PROOF_SHARE, agg1, circ.proof_len)
            blind = input_share.joint_rand_blind
            # seed binder is the fast-mode shortcut (SECURITY-NOTES.md #3)
            part_binder = (
                input_share.seed if self.mode == "fast" else self._encode_vec(meas)
            )
        else:
            meas = input_share.measurement_share
            proof = input_share.proof_share
            blind = input_share.joint_rand_blind
            part_binder = self._encode_vec(meas)

        joint_rand: list[int] = []
        corrected_seed = None
        own_part = None
        if self.uses_joint_rand:
            own_part = self._joint_rand_part(agg_id, blind, nonce, part_binder)
            parts = list(public_share)
            parts[agg_id] = own_part
            corrected_seed = self._joint_rand_seed(parts)
            joint_rand = self._next_vec(
                corrected_seed, USAGE_JOINT_RANDOMNESS, b"", circ.joint_rand_len
            )

        query_rand = self._next_vec(
            verify_key, USAGE_QUERY_RANDOMNESS, nonce, circ.query_rand_len
        )
        verifier_share = flp_query(circ, meas, proof, query_rand, joint_rand, self.NUM_SHARES)
        state = PrepState(circ.truncate(meas), corrected_seed)
        return state, PrepShare(verifier_share, own_part)

    def prepare_shares_to_prep(self, prep_shares: list[PrepShare]) -> bytes | None:
        """Combine prep shares; returns the prep message. Raises on invalid."""
        circ = self.circuit
        F = circ.FIELD
        verifier = [0] * circ.verifier_len
        for ps in prep_shares:
            verifier = [F.add(a, b) for a, b in zip(verifier, ps.verifier_share)]
        if not flp_decide(circ, verifier):
            raise VdafError("FLP check failed: report invalid")
        if self.uses_joint_rand:
            return self._joint_rand_seed([ps.joint_rand_part for ps in prep_shares])
        return None

    def prepare_next(self, state: PrepState, prep_msg: bytes | None) -> list[int]:
        """Final transition: returns the output share. Raises on invalid."""
        if self.uses_joint_rand and prep_msg != state.corrected_joint_rand_seed:
            raise VdafError("joint randomness check failed: report invalid")
        return state.out_share

    # --- aggregation / unsharding ---
    # aggregate/unshard run at the circuit's DENSE aggregate length:
    # output_len for every dense kind, the logical length for sparse
    # circuits (SparseSumVec.agg_output_len) whose aggregate shares are
    # scattered to the logical dimension before they reach here
    @property
    def agg_output_len(self) -> int:
        return getattr(self.circuit, "agg_output_len", self.circuit.output_len)

    def aggregate(self, out_shares: list[list[int]]) -> list[int]:
        F = self.circuit.FIELD
        agg = [0] * self.agg_output_len
        for s in out_shares:
            agg = [F.add(a, b) for a, b in zip(agg, s)]
        return agg

    def unshard(self, agg_shares: list[list[int]], num_measurements: int):
        F = self.circuit.FIELD
        agg = [0] * self.agg_output_len
        for s in agg_shares:
            agg = [F.add(a, b) for a, b in zip(agg, s)]
        return self.circuit.decode(agg, num_measurements)

    # --- internals ---
    def _next_vec(self, seed: bytes, usage: int, binder: bytes, length: int) -> list[int]:
        F = self.circuit.FIELD
        if self.mode == "fast":
            return prng_next_vec(F, seed, self._dst(usage), binder, length)
        return XofSponge128(seed, self._dst(usage), binder).next_vec(F, length)

    def _expand(self, seed: bytes, usage: int, binder: bytes, length: int) -> list[int]:
        return self._next_vec(seed, usage, binder, length)

    def _joint_rand_part(self, agg_id: int, blind: bytes, nonce: bytes, share_binder: bytes) -> bytes:
        return self.xof.derive_seed(
            blind,
            self._dst(USAGE_JOINT_RAND_PART),
            self._agg_id_bytes(agg_id) + nonce + share_binder,
        )

    def _joint_rand_seed(self, parts: list[bytes]) -> bytes:
        return self.xof.derive_seed(
            b"\x00" * SEED_SIZE, self._dst(USAGE_JOINT_RAND_SEED), b"".join(parts)
        )

    def _encode_vec(self, vec: list[int]) -> bytes:
        return self.circuit.FIELD.encode_vec(vec)


class Prio3Sparse(Prio3):
    """Host Prio3 over a SparseSumVec circuit. The FLP legs are the
    plain compact-length Prio3; what changes is the PUBLIC SHARE (it
    carries the block indices alongside the joint-randomness parts)
    and aggregation (compact out shares scatter to the logical
    dimension by those indices)."""

    def shard(self, measurement, nonce: bytes, rand: bytes | None = None):
        indices = self.circuit.encode_indices(measurement)
        parts, shares = super().shard(measurement, nonce, rand)
        return SparsePublicShare(parts, indices), shares

    def prepare_init(self, verify_key, agg_id, nonce, public_share, input_share):
        # wire decode already validated client-originated indices; a
        # direct caller (tests, fuzz) still gets the same predicate
        if isinstance(public_share, SparsePublicShare):
            reason = validate_block_indices(
                public_share.indices,
                self.circuit.n_logical_blocks,
                self.circuit.max_blocks,
            )
            if reason is not None:
                raise VdafError(f"invalid sparse block indices: {reason}")
        return super().prepare_init(
            verify_key, agg_id, nonce, list(public_share), input_share
        )

    def aggregate_sparse(self, pairs) -> list[int]:
        """Aggregate [(indices, compact_out_share)] pairs into one
        LOGICAL-length aggregate share (the host oracle for the device
        scatter-merge kernel)."""
        circ = self.circuit
        F = circ.FIELD
        agg = [0] * circ.logical_length
        for indices, out_share in pairs:
            row = circ.expand(indices, out_share)
            agg = [F.add(a, b) for a, b in zip(agg, row)]
        return agg

    def aggregate(self, out_shares):
        raise VdafError(
            "sparse aggregation needs the public block indices: use "
            "aggregate_sparse([(indices, out_share), ...])"
        )


class VdafError(Exception):
    pass


def prng_next_vec(field, seed, dst_, binder, length):
    from .xof import prng_expand

    return prng_expand(field, seed, dst_, binder, length)
