"""Batched device Poplar1 prepare: IDPF eval + quadratic sketch on TPU.

The host implementation (vdaf.poplar1) walks the IDPF tree per report,
per prefix, per level — a sequential sponge-free but scalar loop, like
the reference's CPU Poplar1 (`Poplar1<XofShake128,16>`,
aggregator/src/aggregator.rs:1096). The walk is level-synchronous:
every (report, prefix) pair performs the same `extend`/`convert` XOF
step at each level, and every XOF call here is a SINGLE-BLOCK
counter-mode SHAKE128 — exactly the shape the project's batched Keccak
machinery (vdaf.keccak_jax.ctr_stream_lanes, which dispatches to the
Pallas kernel on chip) was built for. So the device path flattens
[reports x prefixes] into one batch axis and runs the level loop as
`level+1` batched permutations; the per-prefix L/R selection is an
elementwise `where` on the prefix bit, correction words broadcast per
report, and the sketch (z = sum r_p y_p, w = sum r_p^2 y_p) is a field
dot product over the prefix axis via fields.jfield.

Bit-identical to the host walk (differential-tested in
tests/test_poplar1_jax.py): same XofCtr128 framing (DST || seed ||
binder || counter), same oversample-and-reduce sampling
(keccak_jax.sample_field_vec == XofCtr128.next_vec), same correction
and negation order as Idpf._eval_one.

VERDICT r4 item 4: this was the one VDAF with no TPU design at all.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.jfield import JF64, JF128, fdot, fmap, fwhere
from .keccak_jax import ctr_stream_lanes, sample_field_vec
from .poplar1 import ALGO_ID, USAGE_CONVERT, USAGE_CONVERT_VALUE, USAGE_EXTEND
from .xof import DST_SIZE, SEED_SIZE, dst

U64 = jnp.uint64

_DST_EXTEND = dst(ALGO_ID, USAGE_EXTEND)
_DST_CONVERT = dst(ALGO_ID, USAGE_CONVERT)
_DST_CONVERT_VALUE = dst(ALGO_ID, USAGE_CONVERT_VALUE)
_PREFIX_LEN = DST_SIZE + SEED_SIZE  # dst || seed


def _jf_at(bits: int, level: int):
    return JF128 if level == bits - 1 else JF64


def _extend_lanes(seed_lanes):
    """Batched Idpf `_extend`: [N,2] seeds -> (sl [N,2], tl [N], sr, tr)."""
    stream = ctr_stream_lanes(
        [(0, _DST_EXTEND), (2, seed_lanes)], _PREFIX_LEN, seed_lanes.shape[0], 1
    ).reshape(seed_lanes.shape[0], -1)
    sl = stream[:, 0:2]
    sr = stream[:, 2:4]
    tl = stream[:, 4] & U64(1)
    tr = (stream[:, 4] >> U64(8)) & U64(1)
    return sl, tl, sr, tr


def _convert_lanes(jf, seed_lanes, sample: bool):
    """Batched Idpf `_convert`: -> (next seed [N,2], y value or None)."""
    n = seed_lanes.shape[0]
    nxt = ctr_stream_lanes(
        [(0, _DST_CONVERT), (2, seed_lanes)], _PREFIX_LEN, n, 1
    ).reshape(n, -1)[:, 0:2]
    y = None
    if sample:
        stream = ctr_stream_lanes(
            [(0, _DST_CONVERT_VALUE), (2, seed_lanes)], _PREFIX_LEN, n, 1
        )
        y = fmap(lambda v: v[:, 0], sample_field_vec(jf, stream, 1))
    return nxt, y


@lru_cache(maxsize=128)
def _eval_fn(bits: int, level: int, P: int, party: int):
    """jitted [n, P]-batched IDPF eval + sketch for one (level, P)."""
    jf = _jf_at(bits, level)

    def fn(root, cw_seed, cw_tl, cw_tr, vcw0, prefixes, r, a_sh, b_sh):
        # root [n,2]; cw_seed [n, L, 2]; cw_tl/tr [n, L]; vcw0 field [n];
        # prefixes [P]; r field [n, P]; a_sh/b_sh field [n]
        n = root.shape[0]
        N = n * P
        seeds = jnp.broadcast_to(root[:, None, :], (n, P, 2)).reshape(N, 2)
        ctrl = jnp.full((N,), np.uint64(party), dtype=U64)
        for lvl in range(level + 1):
            sl, tl, sr, tr = _extend_lanes(seeds)
            cw_s = jnp.broadcast_to(
                cw_seed[:, lvl, None, :], (n, P, 2)
            ).reshape(N, 2)
            ctl = jnp.broadcast_to(cw_tl[:, lvl, None], (n, P)).reshape(N)
            ctr_ = jnp.broadcast_to(cw_tr[:, lvl, None], (n, P)).reshape(N)
            mask = (U64(0) - ctrl)[:, None]
            sl = sl ^ (cw_s & mask)
            sr = sr ^ (cw_s & mask)
            tl = tl ^ (ctl & ctrl)
            tr = tr ^ (ctr_ & ctrl)
            bit = (prefixes >> U64(level - lvl)) & U64(1)  # [P]
            bitN = jnp.broadcast_to(bit[None, :], (n, P)).reshape(N)
            sel = bitN[:, None].astype(bool)
            seeds = jnp.where(sel, sr, sl)
            ctrl = jnp.where(bitN.astype(bool), tr, tl)
            seeds, y = _convert_lanes(jf, seeds, sample=(lvl == level))
        # value correction on the on-path control bit, then party sign
        vcw = fmap(lambda v: jnp.broadcast_to(v[:, None], (n, P)).reshape(N), vcw0)
        y = fwhere(ctrl.astype(bool), jf.add(y, vcw), y)
        if party == 1:
            y = jf.neg(y)
        y = fmap(lambda v: v.reshape(n, P), y)
        # sketch shares: A = a + sum r_p y_p, B = b + sum r_p^2 y_p
        z = fdot(jf, r, y, axis=-1)
        w = fdot(jf, jf.mul(r, r), y, axis=-1)
        A = jf.add(z, a_sh)
        B = jf.add(w, b_sh)
        return y, A, B

    return jax.jit(fn)


def _seed_to_lanes(seed: bytes) -> np.ndarray:
    return np.frombuffer(seed, dtype="<u8").astype(np.uint64)


def _field_from_ints(jf, arr) -> tuple:
    a = np.asarray(arr, dtype=object)
    lo = (a & ((1 << 64) - 1)).astype(np.uint64)
    if jf.LIMBS == 1:
        return (jnp.asarray(lo),)
    hi = (a >> 64).astype(np.uint64)
    return (jnp.asarray(lo), jnp.asarray(hi))


def prepare_init_batched(bits: int, party: int, keys, param, verify_key: bytes, nonces):
    """Device twin of `Poplar1.prepare_init` over a report batch.

    keys: list of IdpfKey (with .corr populated); nonces: list of
    bytes. Returns (y_ints [n][P], A [n], B [n], a_shares [n],
    c_shares [n]) as host ints — identical values to the host walk.
    """
    from .poplar1 import corr_from_seed, verify_rand

    assert bits <= 64, "device path holds prefixes in u64 lanes"
    n = len(keys)
    level = param.level
    P = len(param.prefixes)
    # Bucket both batch axes: _eval_fn compiles per (level, P_pad,
    # batch shape), and the heavy-hitters loop varies both n and P
    # every level — exact shapes would mean a fresh XLA compile per
    # aggregation job (engine_cache buckets for the same reason).
    # Padding is with zero keys / prefix 0 / r=0; padded rows and
    # prefixes are sliced off (r=0 keeps them out of the sketch sums).
    n_pad = 8
    while n_pad < n:
        n_pad *= 2
    P_pad = 1
    while P_pad < P:
        P_pad *= 2
    jf = _jf_at(bits, level)
    F = JF128.HOST if jf is JF128 else JF64.HOST

    root = np.zeros((n_pad, 2), dtype=np.uint64)
    L = level + 1
    cw_seed = np.zeros((n_pad, L, 2), dtype=np.uint64)
    cw_tl = np.zeros((n_pad, L), dtype=np.uint64)
    cw_tr = np.zeros((n_pad, L), dtype=np.uint64)
    vcw0 = []
    corr = []
    for i, k in enumerate(keys):
        root[i] = _seed_to_lanes(k.root_seed)
        for lvl in range(L):
            seed_cw, t_l, t_r, value_cw = k.correction_words[lvl]
            cw_seed[i, lvl] = _seed_to_lanes(seed_cw)
            cw_tl[i, lvl] = t_l
            cw_tr[i, lvl] = t_r
            if lvl == level:
                vcw0.append(int(value_cw[0]))
        corr.append(
            k.corr[level] if party == 0 else corr_from_seed(bits, k.corr, level)
        )
    vcw0 += [0] * (n_pad - n)
    a_sh = [c[0] for c in corr]
    b_sh = [c[1] for c in corr]
    c_sh = [c[2] for c in corr]
    pad_elems = [0] * (n_pad - n)

    r_rows = [
        list(verify_rand(bits, verify_key, nonce, param)) + [0] * (P_pad - P)
        for nonce in nonces
    ] + [[0] * P_pad] * (n_pad - n)
    # [n][P] host ints (host-derived: must match the host walk exactly)

    prefixes = list(param.prefixes) + [0] * (P_pad - P)
    fn = _eval_fn(bits, level, P_pad, party)
    y, A, B = fn(
        jnp.asarray(root),
        jnp.asarray(cw_seed),
        jnp.asarray(cw_tl),
        jnp.asarray(cw_tr),
        _field_from_ints(jf, vcw0),
        jnp.asarray(np.asarray(prefixes, dtype=np.uint64)),
        _field_from_ints(jf, r_rows),
        _field_from_ints(jf, a_sh + pad_elems),
        _field_from_ints(jf, b_sh + pad_elems),
    )
    y_ints = jf.to_ints(y)
    A_ints = jf.to_ints(A)
    B_ints = jf.to_ints(B)
    return (
        [[int(v) for v in row[:P]] for row in y_ints[:n]],
        [int(x) for x in A_ints[:n]],
        [int(x) for x in B_ints[:n]],
        a_sh,
        c_sh,
    )
