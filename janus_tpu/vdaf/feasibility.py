"""HBM feasibility model for device prepare dispatches.

The round-5 measurements (BASELINE.md "Draft mode", ISSUE r5) showed the
device path at north-star lengths was capped not by compute but by HBM
capacity: batch 128 at SumVec len=100k wants 20.68 GB of a 15.75 GB v5e
budget, and the only knob — batch size — was picked blind (power-of-two
bucketing in aggregator.engine_cache) with a hard `XlaRuntimeError`
when the guess was wrong. This module is the shared answer:

- `device_memory_budget()` reads the accelerator's own accounting
  (`jax.local_devices()[0].memory_stats()`), falling back to the
  `JANUS_HBM_BUDGET` env override (bytes). On hosts with no budget
  accounting (CPU backend) it returns None — callers treat that as
  "unbounded" and keep legacy behavior.
- `prepare_row_bytes()` estimates resident bytes per report row of a
  two-party prepare from the circuit geometry (input/proof/output/
  verifier lengths, limb width) plus the tiled working set (the
  streamed query's per-step tensors scale with the TILE, not
  input_len — vdaf.engine.stream_plan).
- `feasible_rows()` / `feasible_bucket()` turn that into the largest
  safe batch (power-of-two for the jit bucket cache).

The model is deliberately a first-order estimate with headroom, not a
buffer-assignment oracle: it picks the *starting* bucket; the runtime
halve-on-OOM retry in `aggregator.engine_cache.EngineCache` is the
backstop when the estimate is optimistic.
"""

from __future__ import annotations

import os

# Fraction of the reported budget the model is allowed to plan into.
# XLA needs slack for fusion temporaries, the compiler's own scratch,
# and donation gaps; 0.85 matches the measured len=100k fit (batch 256
# modeled at ~11.3 GB inside 15.75 GB).
DEFAULT_HEADROOM = 0.85

# Copies of a tile-sized tensor live at once inside one scan step of the
# streamed query (masked share, wire pair a/b or the MM fold operands,
# the XOF candidate stream, and XLA double-buffering of the carry).
TILE_WORKING_COPIES = 6

# Whole-share working copies for the untiled (short-circuit) query path:
# calls-inputs tensor, its r-power product, and the interleaved pairs.
UNTILED_WORKING_COPIES = 4


def device_memory_budget(device=None) -> int | None:
    """Usable accelerator memory in bytes, or None when unknown.

    `JANUS_HBM_BUDGET` (bytes) overrides — the tunnel backend reports no
    memory_stats, and tests pin the budget to exercise the model.
    """
    env = os.environ.get("JANUS_HBM_BUDGET")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    try:
        import jax

        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return stats.get("bytes_limit") or stats.get("bytes_reservable_limit")


def _elem_bytes(circ) -> int:
    # one field element = LIMBS u64 lanes = ENCODED_SIZE bytes resident
    return circ.FIELD.ENCODED_SIZE


def prepare_row_bytes(circ, tile_elems: int | None = None, draft: bool = False) -> int:
    """Modeled resident bytes per report row of a two-party prepare.

    tile_elems: the streamed query's tile (group) size in input
    elements, or None when the whole-share path runs (short circuits).
    draft: the VDAF-07 framing materializes the full helper share (the
    sequential sponge has no random-access counter) plus its rejection
    candidate stream, so it pays O(input_len) regardless of tiling.
    """
    per = _elem_bytes(circ)
    n = circ.input_len
    # staged leader measurement share is device-resident for the whole
    # step; both proof shares, both verifier shares, both out shares.
    resident = n * per
    resident += 2 * circ.proof_len * per
    resident += 2 * circ.verifier_len * per
    resident += 2 * circ.output_len * per
    if tile_elems is not None and tile_elems < n:
        resident += TILE_WORKING_COPIES * tile_elems * per
    else:
        resident += UNTILED_WORKING_COPIES * n * per
    if draft:
        # materialized helper share + the ~1.5x candidate stream the
        # rejection sampler reads it from (24 raw bytes per F128 lane
        # pair amortizes to ~1.5 resident copies)
        resident += int(2.5 * n * per)
    return resident


def feasible_rows(
    circ,
    budget_bytes: int | None,
    tile_elems: int | None = None,
    draft: bool = False,
    headroom: float = DEFAULT_HEADROOM,
) -> int | None:
    """Largest report-row count the budget supports, or None (unbounded)
    when the budget is unknown. Always at least 1: a budget too small
    for one row still returns 1 and lets the runtime OOM retry make the
    final call (host fallback)."""
    if budget_bytes is None:
        return None
    row = prepare_row_bytes(circ, tile_elems=tile_elems, draft=draft)
    return max(1, int(budget_bytes * headroom) // max(1, row))


def feasible_bucket(
    circ,
    budget_bytes: int | None,
    tile_elems: int | None = None,
    draft: bool = False,
    headroom: float = DEFAULT_HEADROOM,
) -> int | None:
    """Largest power-of-two batch bucket within the budget (None =
    unbounded). This is the adaptive replacement for the blind
    `bucket_size(n)` growth in aggregator.engine_cache."""
    rows = feasible_rows(circ, budget_bytes, tile_elems=tile_elems, draft=draft, headroom=headroom)
    if rows is None:
        return None
    b = 1
    while b * 2 <= rows:
        b *= 2
    return b


def describe(circ, tile_elems: int | None = None, draft: bool = False, budget_bytes=None) -> dict:
    """One JSON-able snapshot of the model for a circuit — used by
    `bench.py --dry-run` and surfaced in the bench JSON so every run
    records the bucket the model chose and why."""
    if budget_bytes is None:
        budget_bytes = device_memory_budget()
    row = prepare_row_bytes(circ, tile_elems=tile_elems, draft=draft)
    return {
        "input_len": circ.input_len,
        "proof_len": circ.proof_len,
        "verifier_len": circ.verifier_len,
        "output_len": circ.output_len,
        "elem_bytes": _elem_bytes(circ),
        "tile_elems": tile_elems,
        "row_bytes": row,
        "budget_bytes": budget_bytes,
        "headroom": DEFAULT_HEADROOM,
        "feasible_rows": feasible_rows(circ, budget_bytes, tile_elems=tile_elems, draft=draft),
        "feasible_bucket": feasible_bucket(circ, budget_bytes, tile_elems=tile_elems, draft=draft),
    }
