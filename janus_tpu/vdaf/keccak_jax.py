"""Batched Keccak-f[1600] / SHAKE128 in JAX for on-device XOF expansion.

The VDAF hot path needs, per report, hundreds of KB of XOF output to
expand helper measurement/proof shares from 16-byte seeds (the
reference does this on CPU inside `prio`'s Xof, one report at a time,
invoked from aggregator/src/aggregator.rs:1775-1797). Keccak is pure
64-bit bitwise logic, which vectorizes perfectly: the state is 25 u64
lanes per message, and every round is elementwise XOR/rotate/and-not.
On TPU the u64 ops lower to u32 pairs on the VPU.

The XOF stream framing is **counter mode** (janus_tpu.vdaf.xof, which
is the host oracle — see its docstring for the design): every 168-byte
output block is an independent single-block SHAKE128 message
(dst||seed||binder'||le64(i)), so one `keccak_f1600` call over
[batch, n_blocks]-shaped lanes produces the *entire* stream of every
report in a batch — sequential depth 24 rounds regardless of stream
length. Long binders are bound via an arity-7 Merkle digest whose
levels are each one batched permutation (`tree_digest_lanes`). All
messages are u64-lane-aligned by construction; host and device produce
byte-identical streams — tested in tests/test_keccak.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

U64 = jnp.uint64

RATE_BYTES = 168  # SHAKE128
RATE_LANES = RATE_BYTES // 8  # 21

_RC = np.array(
    [
        0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
        0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
        0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
        0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
        0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
        0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
        0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
        0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
    ],
    dtype=np.uint64,
)

# rotation offsets indexed [x][y]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]


def _rotl(x, r: int):
    if r == 0:
        return x
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _keccak_round(a, rc):
    """One Keccak round; a: tuple of 25 u64 arrays, rc: scalar constant."""
    # theta
    c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
    d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
    a = [a[i] ^ d[i % 5] for i in range(25)]
    # rho + pi: B[y, 2x+3y] = rot(A[x, y])
    b = [None] * 25
    for x in range(5):
        for y in range(5):
            b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], _ROT[x][y])
    # chi
    a = [
        b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y])
        for y in range(5)
        for x in range(5)
    ]
    # iota
    a[0] = a[0] ^ rc
    return tuple(a)


# Round count for every permutation in this module (the kernel and the
# scan path). 24 always in production; tests monkeypatch it to run the
# full kernel plumbing at a reduced count in interpret mode
# (tests/test_keccak_pallas.py) — patching here covers every dispatch
# site, including the single-block kernel below.
KECCAK_ROUNDS = 24


def keccak_f1600(state, rounds: int | None = None):
    """One permutation. state: 25 u64 arrays (lane (x,y) at index x + 5*y).

    On TPU this dispatches to the Pallas kernel (janus_tpu.ops.
    keccak_pallas): all 24 rounds stay in VMEM on native u32 halves,
    one HBM read+write per element. Elsewhere the rounds run under
    lax.scan so the round body is traced and compiled once — an
    unrolled permutation inflates the XLA graph by ~2k ops per call
    site, which multiplies out to minutes of compile time across the
    expansion pipeline. `rounds < 24` exists for the reduced-round CI
    differentials only (tests/test_keccak_pallas.py).
    """
    from ..ops import keccak_pallas

    if rounds is None:
        rounds = KECCAK_ROUNDS
    state = tuple(jnp.asarray(x, dtype=U64) for x in state)
    n = int(np.prod(state[0].shape)) if state[0].shape else 1
    if keccak_pallas.enabled(n):
        return keccak_pallas.keccak_f1600_pallas(state, rounds)

    def body(a, rc):
        return _keccak_round(a, rc), None

    out, _ = jax.lax.scan(body, state, jnp.asarray(_RC[:rounds]))
    return out


def _absorb_block(state, block_lanes):
    """XOR one rate block ([batch, 21]) into the state and permute."""
    state = list(state)
    for lane in range(RATE_LANES):
        state[lane] = state[lane] ^ block_lanes[:, lane]
    return keccak_f1600(state)


_UNROLL_BLOCKS = 4  # small messages stay unrolled; long ones lax.scan


def shake128_squeeze_lanes(msg_lanes, out_blocks: int):
    """SHAKE128 over pre-padded messages; returns raw squeezed lanes.

    msg_lanes: [batch, n_blocks, 21] u64 — the message already padded to
    whole rate blocks (use pad_message_lanes). Returns
    [batch, out_blocks, 21] u64 of output stream lanes.

    Absorb/squeeze are lax.scan loops over blocks (the permutation is
    inherently sequential per report), so the traced graph stays O(1)
    in stream length — a SumVec-100k share expansion is ~1.5k blocks
    and must not unroll.
    """
    batch = msg_lanes.shape[0]
    n_blocks = msg_lanes.shape[1]
    state = tuple(jnp.zeros((batch,), dtype=U64) for _ in range(25))
    if n_blocks <= _UNROLL_BLOCKS:
        for blk in range(n_blocks):
            state = _absorb_block(state, msg_lanes[:, blk])
    else:
        state = _absorb_scan(state, msg_lanes)
    if out_blocks <= _UNROLL_BLOCKS:
        outs = []
        for blk in range(out_blocks):
            if blk > 0:
                state = keccak_f1600(state)
            outs.append(jnp.stack(state[:RATE_LANES], axis=-1))
        return jnp.stack(outs, axis=1)
    return _squeeze_scan(state, out_blocks)


# Sponge chains past this many blocks run as NESTED scans (an outer
# scan of _SCAN_CHUNK-length inner scans): a single flat lax.scan goes
# wildly superlinear past ~32k trip counts on the TPU runtime
# (measured: 1.9 s at 32k blocks vs 209 s at 152k — BASELINE.md "Draft
# mode"), which round 4 mistook for an inherent cost and capped the
# draft device gate on. The chunking is value-neutral: the same
# sequential permutation chain, same output blocks.
_SCAN_CHUNK = 4096


def _absorb_scan(state, msg_lanes):
    n_blocks = msg_lanes.shape[1]

    def absorb(st, blk):
        return _absorb_block(st, blk), None

    n_full = (n_blocks // _SCAN_CHUNK) if n_blocks > 2 * _SCAN_CHUNK else 0
    if n_full:
        head = jnp.moveaxis(
            msg_lanes[:, : n_full * _SCAN_CHUNK].reshape(
                msg_lanes.shape[0], n_full, _SCAN_CHUNK, RATE_LANES
            ),
            0,
            2,
        )  # [n_full, chunk, batch, 21]

        def outer(st, chunk_blocks):
            st2, _ = jax.lax.scan(absorb, st, chunk_blocks)
            return st2, None

        state, _ = jax.lax.scan(outer, state, head)
        msg_lanes = msg_lanes[:, n_full * _SCAN_CHUNK :]
    if msg_lanes.shape[1]:
        xs = jnp.moveaxis(msg_lanes, 1, 0)
        state, _ = jax.lax.scan(absorb, state, xs)
    return state


def _squeeze_scan(state, out_blocks: int):
    def squeeze(st, _):
        ys = jnp.stack(st[:RATE_LANES], axis=-1)
        return keccak_f1600(st), ys

    if out_blocks <= 2 * _SCAN_CHUNK:
        _, ys = jax.lax.scan(squeeze, state, None, length=out_blocks)
        return jnp.moveaxis(ys, 0, 1)
    # full chunks via the nested scan + a flat remainder scan (mirrors
    # _absorb_scan; rounding the squeeze up would waste up to a whole
    # chunk of permutations over the batch)
    n_full = out_blocks // _SCAN_CHUNK
    rem = out_blocks - n_full * _SCAN_CHUNK

    def outer(st, _):
        st2, ys = jax.lax.scan(squeeze, st, None, length=_SCAN_CHUNK)
        return st2, ys

    state, yss = jax.lax.scan(outer, state, None, length=n_full)
    ys = yss.reshape(n_full * _SCAN_CHUNK, yss.shape[2], RATE_LANES)
    if rem:
        state, tail = jax.lax.scan(squeeze, state, None, length=rem)
        ys = jnp.concatenate([ys, tail], axis=0)
    return jnp.moveaxis(ys, 0, 1)


def pad_message_lanes(parts, msg_len_bytes: int, batch: int):
    """Assemble a padded SHAKE128 message as [batch, n_blocks, 21] lanes.

    parts: list of (lane_offset, lanes) where lanes is a [batch, k] u64
    array (dynamic content) or a host bytes object of length 8*k (static
    content), in ascending offset order (gaps are zero-filled).
    msg_len_bytes must be a multiple of 8 (guaranteed by the
    lane-aligned stream framing in janus_tpu.vdaf.xof). Assembly is
    whole-array concatenation so the traced graph stays O(#parts), not
    O(message length).
    """
    assert msg_len_bytes % 8 == 0
    msg_lanes_n = msg_len_bytes // 8
    n_blocks = msg_lanes_n // RATE_LANES + 1  # always room for padding
    total = n_blocks * RATE_LANES
    lanes = _assemble_segments(parts, msg_lanes_n, batch)
    # SHAKE padding: 0x1F at msg end, 0x80 at the last byte of the last
    # block (may share a lane).
    tail = np.zeros(total - msg_lanes_n, dtype=np.uint64)
    tail[0] ^= np.uint64(0x1F)
    tail[-1] ^= np.uint64(0x80) << np.uint64(56)
    lanes = jnp.concatenate(
        [lanes, jnp.broadcast_to(jnp.asarray(tail), (batch, tail.size))], axis=1
    )
    return lanes.reshape(batch, n_blocks, RATE_LANES)


def bytes_to_lanes(data: bytes) -> np.ndarray:
    assert len(data) % 8 == 0
    return np.frombuffer(data, dtype="<u8").astype(np.uint64)


def _assemble_segments(parts, total_lanes: int, batch: int):
    """Concatenate (lane_offset, lanes|bytes) parts into [batch, total_lanes].

    Gaps are zero-filled; host bytes are broadcast across the batch.
    """
    segs = []
    pos = 0
    for off, content in sorted(parts, key=lambda p: p[0]):
        assert off >= pos, "overlapping message parts"
        if off > pos:
            segs.append(jnp.zeros((batch, off - pos), dtype=U64))
            pos = off
        if isinstance(content, (bytes, bytearray)):
            assert len(content) % 8 == 0
            lanes = np.frombuffer(bytes(content), dtype="<u8").astype(np.uint64)
            segs.append(jnp.broadcast_to(jnp.asarray(lanes), (batch, lanes.size)))
            pos += lanes.size
        else:
            segs.append(content.astype(U64))
            pos += content.shape[-1]
    assert pos <= total_lanes
    if pos < total_lanes:
        segs.append(jnp.zeros((batch, total_lanes - pos), dtype=U64))
    return jnp.concatenate(segs, axis=1)


# ---------------------------------------------------------------------------
# Counter-mode stream + tree digest (the janus_tpu.vdaf.xof framing)
# ---------------------------------------------------------------------------

PAD_START = np.uint64(0x1F)
PAD_END = np.uint64(0x80) << np.uint64(56)


def _single_block_keccak(lane_cols, out_lanes: int = 25):
    """Permute single-block messages given as a list of 21 lane arrays.

    lane_cols: 21 arrays of identical shape [...] (the rate lanes of the
    already-padded message). Returns (at least) the first `out_lanes`
    output lanes; callers that only need a digest or a rate block pass
    a smaller out_lanes so the Pallas path can skip moving the rest
    (keccak_single_block_pallas: 42 u32 rows in, 2*out_lanes out,
    instead of the general kernel's 50/50).
    """
    from ..ops import keccak_pallas

    shape = lane_cols[0].shape
    n = int(np.prod(shape)) if shape else 1
    if out_lanes < 25 and keccak_pallas.enabled(n):
        return keccak_pallas.keccak_single_block_pallas(
            lane_cols, out_lanes, rounds=KECCAK_ROUNDS
        )
    zeros = jnp.zeros_like(lane_cols[0])
    state = tuple(lane_cols) + (zeros,) * 4
    return keccak_f1600(state)


def ctr_stream_lanes(prefix_parts, prefix_len_bytes: int, batch: int, out_blocks: int, ctr_offset=0):
    """Counter-mode SHAKE128 stream: [batch, out_blocks, 21] u64 lanes.

    prefix_parts: (lane_offset, content) segments of the prefix
    dst16 || seed || binder' (binder' already inline-size). Every output
    block is the independent single-block message prefix || le64(i), so
    the whole stream is ONE batched permutation — this is the load-bearing
    TPU restructuring over sequential sponge squeezing.

    ctr_offset (python int or traced scalar) starts the counter at block
    `ctr_offset` instead of 0 — the streamed-expansion path (engine.py
    flp_query_streamed) generates the stream a slice at a time.
    """
    assert prefix_len_bytes % 8 == 0
    p = prefix_len_bytes // 8
    assert p + 1 <= RATE_LANES - 1, "prefix + counter must fit one rate block"
    prefix = _assemble_segments(prefix_parts, p, batch)  # [batch, p]
    shape = (batch, out_blocks)
    cols = []
    for lane in range(RATE_LANES):
        if lane < p:
            cols.append(jnp.broadcast_to(prefix[:, lane : lane + 1], shape))
        elif lane == p:
            ctr = jnp.arange(out_blocks, dtype=U64)[None, :] + jnp.asarray(ctr_offset, U64)
            cols.append(jnp.broadcast_to(ctr, shape))
        else:
            v = np.uint64(0)
            if lane == p + 1:
                v |= PAD_START
            if lane == RATE_LANES - 1:
                v |= PAD_END
            cols.append(jnp.broadcast_to(jnp.asarray(v), shape))
    state = _single_block_keccak(cols, out_lanes=RATE_LANES)
    return jnp.stack(state[:RATE_LANES], axis=-1)  # [batch, out_blocks, 21]


TREE_MAGIC_LANE = np.frombuffer(b"JanusTr1", dtype="<u8")[0]
TREE_CHUNK_LANES = 14  # 112 bytes
TREE_ARITY = 7
TREE_DIGEST_LANES = 2


def _tree_level_planar(planes, level: int, total_lanes_bytes: int):
    """Hash one tree level from plane-major input: planes
    [batch, 14, n] -> digests [batch, n, 2]. Node k's payload lane j is
    planes[:, j, k] — a contiguous row slice."""
    batch, _, n = planes.shape
    shape = (batch, n)
    idx = jnp.broadcast_to(jnp.arange(n, dtype=U64)[None, :], shape)
    consts = {
        0: np.uint64(TREE_MAGIC_LANE),
        1: np.uint64(level),
        3: np.uint64(total_lanes_bytes),
        18: PAD_START,
        20: PAD_END,
    }
    cols = []
    for lane in range(RATE_LANES):
        if lane == 2:
            cols.append(idx)
        elif 4 <= lane < 4 + TREE_CHUNK_LANES:
            cols.append(planes[:, lane - 4, :])
        else:
            cols.append(
                jnp.broadcast_to(jnp.asarray(consts.get(lane, np.uint64(0))), shape)
            )
    state = _single_block_keccak(cols, out_lanes=TREE_DIGEST_LANES)
    return jnp.stack(state[:TREE_DIGEST_LANES], axis=-1)


def _tree_level(chunks, level: int, total_lanes_bytes: int):
    """Hash one tree level: chunks [batch, n, 14] -> digests [batch, n, 2]."""
    batch, n, _ = chunks.shape
    shape = (batch, n)
    idx = jnp.broadcast_to(jnp.arange(n, dtype=U64)[None, :], shape)
    consts = {
        0: np.uint64(TREE_MAGIC_LANE),
        1: np.uint64(level),
        3: np.uint64(total_lanes_bytes),
        18: PAD_START,  # message = 4 + 14 lanes; 0x1f right after
        20: PAD_END,
    }
    cols = []
    for lane in range(RATE_LANES):
        if lane == 2:
            cols.append(idx)
        elif 4 <= lane < 4 + TREE_CHUNK_LANES:
            cols.append(chunks[:, :, lane - 4])
        else:
            cols.append(
                jnp.broadcast_to(jnp.asarray(consts.get(lane, np.uint64(0))), shape)
            )
    state = _single_block_keccak(cols, out_lanes=TREE_DIGEST_LANES)
    return jnp.stack(state[:TREE_DIGEST_LANES], axis=-1)  # [batch, n, 2]


def tree_digest_lanes(data_parts, data_len_bytes: int, batch: int):
    """Arity-7 Merkle digest of lane-aligned data: [batch, 2] u64.

    Byte-identical to janus_tpu.vdaf.xof.tree_digest. Each level is one
    batched permutation over all of that level's nodes. Level 0 uses
    the PLANAR leaf mapping (lane j of leaf k = data lane j*n+k, see
    tree_digest): each leaf lane column is one contiguous slice of the
    binder instead of a stride-14 gather over all of it.
    """
    assert data_len_bytes % 8 == 0
    lanes_n = data_len_bytes // 8
    data = _assemble_segments(data_parts, lanes_n, batch)  # [batch, L]
    n = max(1, -(-lanes_n // TREE_CHUNK_LANES))
    pad = n * TREE_CHUNK_LANES - lanes_n
    if pad:
        data = jnp.pad(data, ((0, 0), (0, pad)))
    planes = data.reshape(batch, TREE_CHUNK_LANES, n)
    digs = _tree_level_planar(planes, 0, data_len_bytes)  # [batch, n, 2]
    level = 0
    while n > 1:
        level += 1
        groups = -(-n // TREE_ARITY)
        gpad = groups * TREE_ARITY - n
        if gpad:
            digs = jnp.pad(digs, ((0, 0), (0, gpad), (0, 0)))
        chunks = digs.reshape(batch, groups, TREE_CHUNK_LANES)
        digs = _tree_level(chunks, level, data_len_bytes)
        n = groups
    return digs[:, 0, :]  # [batch, 2]


# ---------------------------------------------------------------------------
# Field-element sampling (oversample-and-reduce; janus_tpu.vdaf.xof)
# ---------------------------------------------------------------------------


def sample_count_blocks(jf, length: int) -> int:
    """Number of SHAKE output blocks needed to sample `length` elements."""
    lanes_needed = length * (jf.LIMBS + 1)
    return (lanes_needed + RATE_LANES - 1) // RATE_LANES


def sample_field_vec(jf, stream_lanes, length: int):
    """Sample `length` field elements by reducing (LIMBS+1)-lane
    little-endian chunks mod p (bias <= 2^-64 per element; see
    janus_tpu.vdaf.xof). Pure elementwise limb math — rejection
    sampling's data-dependent compaction lowered to row-wise gathers
    and sort-based scatters that were 78% of the two-party SumVec step
    on chip. stream_lanes: [batch, out_blocks, 21] u64; returns a field
    value of shape [batch, length].
    """
    from ..fields.jfield import _f64_reduce_wide, _f128_reduce256

    batch = stream_lanes.shape[0]
    g = jf.LIMBS + 1
    flat = stream_lanes.reshape(batch, -1)
    assert flat.shape[1] >= length * g
    lanes = tuple(flat[:, i : length * g : g] for i in range(g))
    if jf.LIMBS == 1:
        return (_f64_reduce_wide(lanes[0], lanes[1]),)
    zero = jnp.zeros_like(lanes[0])
    return _f128_reduce256(lanes[0], lanes[1], lanes[2], zero)


def expand_field_vec(jf, prefix_parts, prefix_len_bytes: int, batch: int, length: int, block_offset=0):
    """XOF-expand per-report prefixes straight to field vectors on device.

    prefix_parts lay out dst16 || seed || binder' (counter-mode framing,
    janus_tpu.vdaf.xof); the binder must already be inline-size.

    Long Field128 expansions dispatch to the fused Pallas kernel
    (janus_tpu.ops.expand_pallas): permutation + mod-p sampling in
    VMEM, so the raw stream (24 bytes/element) never reaches HBM.

    block_offset (python int or traced scalar) starts the counter at
    that stream block; the caller is responsible for block-aligning the
    element range (Field128: 7 elements per block).
    """
    from ..ops import expand_pallas

    assert prefix_len_bytes % 8 == 0  # lane-aligned framing (xof.py)
    blocks = sample_count_blocks(jf, length)
    if expand_pallas.enabled(jf, blocks):
        prefix = _assemble_segments(prefix_parts, prefix_len_bytes // 8, batch)
        return expand_pallas.expand_f128(prefix, blocks, length, block_offset=block_offset)
    out = ctr_stream_lanes(prefix_parts, prefix_len_bytes, batch, blocks, ctr_offset=block_offset)
    return sample_field_vec(jf, out, length)
