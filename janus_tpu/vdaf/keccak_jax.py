"""Batched Keccak-f[1600] / SHAKE128 in JAX for on-device XOF expansion.

The VDAF hot path needs, per report, hundreds of KB of XOF output to
expand helper measurement/proof shares from 16-byte seeds (the
reference does this on CPU inside `prio`'s Xof, one report at a time,
invoked from aggregator/src/aggregator.rs:1775-1797). Keccak is pure
64-bit bitwise logic, which vectorizes perfectly across a report batch:
the state is 25 u64 lanes per report, and every round is elementwise
XOR/rotate/and-not over [batch, 25]-shaped lanes. On TPU the u64 ops
lower to u32 pairs on the VPU; throughput scales with batch size.

Stream framing matches janus_tpu.vdaf.xof exactly (all absorbed
messages are u64-lane-aligned by construction), so host and device
produce byte-identical streams — tested in tests/test_keccak.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

U64 = jnp.uint64

RATE_BYTES = 168  # SHAKE128
RATE_LANES = RATE_BYTES // 8  # 21

_RC = np.array(
    [
        0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
        0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
        0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
        0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
        0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
        0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
        0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
        0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
    ],
    dtype=np.uint64,
)

# rotation offsets indexed [x][y]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]


def _rotl(x, r: int):
    if r == 0:
        return x
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def keccak_f1600(state: list):
    """One permutation. state: 25 u64 arrays (lane (x,y) at index x + 5*y)."""
    a = list(state)
    for rnd in range(24):
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        a = [a[i] ^ d[i % 5] for i in range(25)]
        # rho + pi: B[y, 2x+3y] = rot(A[x, y])
        b = [None] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], _ROT[x][y])
        # chi
        a = [
            b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y])
            for y in range(5)
            for x in range(5)
        ]
        # iota
        a[0] = a[0] ^ _RC[rnd]
    return a


def shake128_squeeze_lanes(msg_lanes, out_blocks: int):
    """SHAKE128 over pre-padded messages; returns raw squeezed lanes.

    msg_lanes: [batch, n_blocks, 21] u64 — the message already padded to
    whole rate blocks (use pad_message_lanes). Returns
    [batch, out_blocks, 21] u64 of output stream lanes.
    """
    batch = msg_lanes.shape[0]
    n_blocks = msg_lanes.shape[1]
    state = [jnp.zeros((batch,), dtype=U64) for _ in range(25)]
    for blk in range(n_blocks):
        for lane in range(RATE_LANES):
            state[lane] = state[lane] ^ msg_lanes[:, blk, lane]
        state = keccak_f1600(state)
    outs = []
    for blk in range(out_blocks):
        if blk > 0:
            state = keccak_f1600(state)
        outs.append(jnp.stack(state[:RATE_LANES], axis=-1))
    return jnp.stack(outs, axis=1)


def pad_message_lanes(parts, msg_len_bytes: int, batch: int):
    """Assemble a padded SHAKE128 message as [batch, n_blocks, 21] lanes.

    parts: list of (lane_offset, lanes) where lanes is a [batch, k] u64
    array (dynamic content) or a host bytes object of length 8*k (static
    content). msg_len_bytes must be a multiple of 8 (guaranteed by the
    lane-aligned stream framing in janus_tpu.vdaf.xof).
    """
    assert msg_len_bytes % 8 == 0
    msg_lanes_n = msg_len_bytes // 8
    n_blocks = msg_lanes_n // RATE_LANES + 1  # always room for padding
    total = n_blocks * RATE_LANES
    cols = [jnp.zeros((batch,), dtype=U64)] * total
    for off, content in parts:
        if isinstance(content, (bytes, bytearray)):
            assert len(content) % 8 == 0
            for i in range(len(content) // 8):
                v = int.from_bytes(content[8 * i : 8 * i + 8], "little")
                cols[off + i] = jnp.full((batch,), np.uint64(v), dtype=U64)
        else:
            for i in range(content.shape[-1]):
                cols[off + i] = content[:, i].astype(U64)
    # SHAKE padding: 0x1F at msg end, 0x80 at last byte of the block
    pad_lane = msg_lanes_n
    cols[pad_lane] = cols[pad_lane] ^ np.uint64(0x1F)
    cols[total - 1] = cols[total - 1] ^ np.uint64(0x80 << 56)
    lanes = jnp.stack(cols, axis=-1)
    return lanes.reshape(batch, n_blocks, RATE_LANES)


def bytes_to_lanes(data: bytes) -> np.ndarray:
    assert len(data) % 8 == 0
    return np.frombuffer(data, dtype="<u8").astype(np.uint64)


# ---------------------------------------------------------------------------
# Field-element sampling (rejection with static-shape compaction)
# ---------------------------------------------------------------------------

SAMPLE_SLACK = 8  # extra candidates; P[>=8 rejections] ~ (n choose 8) * 2^-256


def sample_count_blocks(jf, length: int) -> int:
    """Number of SHAKE output blocks needed to sample `length` elements."""
    cand = length + SAMPLE_SLACK
    lanes_needed = cand * jf.LIMBS
    return (lanes_needed + RATE_LANES - 1) // RATE_LANES


def sample_field_vec(jf, stream_lanes, length: int):
    """Rejection-sample `length` field elements from squeezed lanes.

    stream_lanes: [batch, out_blocks, 21] u64. Emulates the host
    semantics exactly: consume LIMBS-lane little-endian chunks in order,
    skipping values >= p; take the first `length` accepted.
    Returns a field value of shape [batch, length].
    """
    batch = stream_lanes.shape[0]
    flat = stream_lanes.reshape(batch, -1)
    cand = min(length + SAMPLE_SLACK, flat.shape[1] // jf.LIMBS)
    limbs = tuple(flat[:, i : cand * jf.LIMBS : jf.LIMBS] for i in range(jf.LIMBS))
    # accept mask: value < p
    if jf.LIMBS == 1:
        p0 = np.uint64(jf.MODULUS)
        accept = limbs[0] < p0
    else:
        lo, hi = limbs
        p_lo = np.uint64(jf.MODULUS & 0xFFFFFFFFFFFFFFFF)
        p_hi = np.uint64(jf.MODULUS >> 64)
        accept = (hi < p_hi) | ((hi == p_hi) & (lo < p_lo))
    # output slot each accepted candidate lands at (strictly increasing)
    idx = jnp.cumsum(accept.astype(jnp.int32), axis=1) - 1
    slot = jnp.where(accept, idx, cand)  # rejected -> out of bounds, dropped
    # scatter candidate index i into out_idx[b, slot[b, i]]
    bidx = jnp.broadcast_to(jnp.arange(batch, dtype=jnp.int32)[:, None], slot.shape)
    cidx = jnp.broadcast_to(jnp.arange(cand, dtype=jnp.int32)[None, :], slot.shape)
    out_idx = jnp.zeros((batch, length), dtype=jnp.int32)
    out_idx = out_idx.at[bidx, slot].max(cidx, mode="drop")
    gathered = tuple(jnp.take_along_axis(limb, out_idx, axis=1) for limb in limbs)
    return gathered


def expand_field_vec(jf, msg_parts, msg_len_bytes: int, batch: int, length: int):
    """XOF-expand per-report messages straight to field vectors on device."""
    lanes = pad_message_lanes(msg_parts, msg_len_bytes, batch)
    out = shake128_squeeze_lanes(lanes, sample_count_blocks(jf, length))
    return sample_field_vec(jf, out, length)


@partial(jax.jit, static_argnums=(0, 2, 3))
def _jit_expand(jf, lanes, out_blocks, length):
    out = shake128_squeeze_lanes(lanes, out_blocks)
    return sample_field_vec(jf, out, length)
