"""Batched DEVICE Prio3 for `xof_mode: draft` — the VDAF-07 framing.

Draft mode exists for cross-implementation pairing: it follows the
draft-irtf-cfrg-vdaf-07 XofShake128 construction the reference's
`prio` 0.15 dependency implements (sequential sponge, 8-byte DSTs,
single-byte aggregator ids, full-share joint-rand binders, rejection
sampling — none of the fast-mode deviations in SECURITY-NOTES.md).
Round 2 ran draft tasks through a scalar host loop at ~1 report/s
(VERDICT r2 Weak #3); this module runs the same construction batched
on device for short-stream circuits (Count, Sum, small
Histogram/SumVec), reusing the batched Keccak-f[1600].

The two device obstacles the fast framing was designed around are
handled head-on here, because short streams make them affordable:

- **Byte-misaligned framing.** The draft absorb layout
  ``byte(len(dst)) || dst8 || seed16 || binder`` puts the binder at
  byte 25 — not u64-lane-aligned. `_assemble_bytes` packs arbitrary
  byte-offset segments into rate blocks with u64 shift/or lane math
  (one shift pair per segment, O(#segments) ops).
- **Rejection sampling without gathers.** The draft samples field
  elements by rejecting candidates >= p, a data-dependent compaction.
  The select is O(window x length) over shifted slices (element e is
  filled by candidate e+j iff exactly j rejects precede it), which is
  elementwise + one prefix sum — no gathers, any vector length. The
  candidate cushion makes window exhaustion cryptographically
  unreachable (P < 2^-80; an exhausted lane would surface as FLP
  rejection of that report, never silent acceptance).

At north-star lengths the FLP query runs streamed over the materialized
share (engine.flp_query_streamed via the sliced source), so the
O(input_len) wire intermediates never exist; the sponge chain itself is
the remaining sequential cost.

Differentially tested byte-for-byte against the host draft oracle
(`reference.Prio3(mode="draft")`) in tests/test_draft_jax.py.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .keccak_jax import RATE_LANES, shake128_squeeze_lanes
from .prio3_jax import Prio3Batched, field_value_to_enc_lanes
from .xof import (
    SEED_SIZE,
    USAGE_JOINT_RAND_PART,
    USAGE_JOINT_RAND_SEED,
    USAGE_JOINT_RANDOMNESS,
    USAGE_MEASUREMENT_SHARE,
    USAGE_PROOF_SHARE,
    USAGE_QUERY_RANDOMNESS,
    draft_dst,
)

U64 = jnp.uint64
RATE = 8 * RATE_LANES  # 168
DRAFT_DST_SIZE = 8
PREFIX_BYTES = 1 + DRAFT_DST_SIZE + SEED_SIZE  # byte(len dst) || dst || seed


def _shift_lanes(lanes, s: int):
    """Prepend s (0..7) zero bytes to a little-endian u64 lane string
    [batch, k] -> [batch, k+1] (tail lane carries the spill)."""
    lanes = lanes.astype(U64)
    if s == 0:
        return jnp.concatenate([lanes, jnp.zeros_like(lanes[:, :1])], axis=1)
    sh = U64(8 * s)
    inv = U64(64 - 8 * s)
    lo = lanes << sh
    carry = lanes >> inv
    lo = jnp.concatenate([lo, jnp.zeros_like(lanes[:, :1])], axis=1)
    carry = jnp.concatenate([jnp.zeros_like(lanes[:, :1]), carry], axis=1)
    return lo | carry


def _assemble_bytes(segments, msg_len_bytes: int, batch: int):
    """Byte-offset segments -> padded SHAKE128 message blocks.

    segments: list of (byte_offset, content) with content either host
    bytes (any length; broadcast) or a [batch, k] u64 lane array
    (byte length 8k). Segments must occupy disjoint bytes. Returns
    [batch, n_blocks, RATE_LANES] u64 ready for the sponge.
    """
    n_blocks = msg_len_bytes // RATE + 1
    total_lanes = n_blocks * RATE_LANES
    out = jnp.zeros((batch, total_lanes), dtype=U64)
    # SHAKE padding: 0x1F after the message, 0x80 at the last rate byte
    # (bit-disjoint even when they share a byte)
    segments = list(segments) + [
        (msg_len_bytes, b"\x1f"),
        (total_lanes * 8 - 1, b"\x80"),
    ]
    for off, content in segments:
        base, s = divmod(off, 8)
        if isinstance(content, (bytes, bytearray)):
            raw = b"\x00" * s + bytes(content)
            raw = raw.ljust(-(-len(raw) // 8) * 8, b"\x00")
            lanes = np.frombuffer(raw, dtype="<u8").astype(np.uint64)
            seg = jnp.broadcast_to(jnp.asarray(lanes), (batch, lanes.size))
        else:
            seg = _shift_lanes(content, s)
        width = seg.shape[1]
        assert base + width <= total_lanes + 1, (off, width, total_lanes)
        seg = seg[:, : total_lanes - base]  # drop an all-zero spill tail
        out = out | jnp.pad(seg, ((0, 0), (base, total_lanes - base - seg.shape[1])))
    return out.reshape(batch, n_blocks, RATE_LANES)


def _sponge_stream(segments, msg_len_bytes: int, batch: int, out_blocks: int):
    """Draft sponge: absorb the assembled message, squeeze sequentially.
    Returns [batch, out_blocks * RATE_LANES] u64 stream lanes."""
    msg = _assemble_bytes(segments, msg_len_bytes, batch)
    out = shake128_squeeze_lanes(msg, out_blocks)
    return out.reshape(batch, -1)


# Max rejected candidates absorbed per expansion before the output
# tail degrades to zero (and the report FLP-rejects, explicitly).
# P(> 8 rejects) even for Field64 at 10M candidates is ~(10M * 2^-32)^9
# / 9! ~ 2^-80; Field128's per-candidate reject prob is 2^-68.
_REJECT_WINDOW = 8


def _candidate_count(jf, length: int) -> int:
    """Candidates sampled per vector: the window plus a little slack so
    every shifted slice below stays in range."""
    return length + 2 * _REJECT_WINDOW


def _reject_sample(jf, stream_lanes, length: int):
    """Order-exact draft rejection sampling from contiguous
    ENCODED_SIZE-byte candidates. Returns a field value [batch, length];
    if (improbably) more than _REJECT_WINDOW candidates are rejected,
    the missing tail is zero — downstream FLP verification rejects such
    a report, so exhaustion can never yield silent acceptance.

    Compaction without gathers, O(window * length) instead of the dense
    O(length^2) rank-select: element e is filled by candidate e+j
    (j <= window) exactly when candidate e+j is accepted and exactly j
    rejects precede it — rank(e+j) = (e+j) - rejects_before(e+j) = e.
    Elementwise masks over shifted slices; works at any vector length
    (the dense select capped device draft mode at short streams)."""
    C = _candidate_count(jf, length)
    L = jf.LIMBS
    cand = tuple(stream_lanes[:, i : C * L : L] for i in range(L))  # [batch, C] limbs
    if L == 1:
        accept = cand[0] < U64(jf.MODULUS)
    else:
        p_lo = U64(jf.MODULUS & 0xFFFFFFFFFFFFFFFF)
        p_hi = U64(jf.MODULUS >> 64)
        accept = (cand[1] < p_hi) | ((cand[1] == p_hi) & (cand[0] < p_lo))
    # rejects strictly before each candidate (exclusive prefix sum)
    rej = (~accept).astype(jnp.int32)
    rejects_before = jnp.cumsum(rej, axis=1) - rej
    out = tuple(jnp.zeros((stream_lanes.shape[0], length), dtype=U64) for _ in range(L))
    for j in range(_REJECT_WINDOW + 1):
        sel = accept[:, j : j + length] & (rejects_before[:, j : j + length] == j)
        out = tuple(
            o | jnp.where(sel, c[:, j : j + length], U64(0))
            for o, c in zip(out, cand)
        )
    return out


def _stream_blocks_for(jf, length: int) -> int:
    lanes = _candidate_count(jf, length) * jf.LIMBS
    return -(-lanes // RATE_LANES)


class Prio3BatchedDraft(Prio3Batched):
    """Device Prio3 with the VDAF-07 draft XOF framing.

    Shares the entire FLP/field pipeline with the fast engine; only the
    XOF plumbing (framing, sampling, binder choices) differs.
    `supports_circuit` bounds the sponge stream length
    (MAX_STREAM_BLOCKS below): since r5 the cap covers the north-star
    SumVec len=100k — nested scans made long chains linear — with the
    device winning from batch >=128-equivalent amortization; truly
    huge streams still fall back to the scalar host loop.
    """

    # Draft framing: sponge streams have no random-access counter and
    # the joint-rand binder is the full expanded share — so the helper
    # share materializes once and the streamed query slices it
    # (prio3_jax.prepare_init_helper's sliced branch). The query
    # streaming itself applies unchanged (the FLP math is
    # framing-independent; differential-tested in test_draft_jax.py).
    _can_stream = True
    _stream_expand_offsets = False

    # max sponge blocks per expansion (absorb or squeeze side). The
    # chain is sequential per report (~24 rounds/block of pure latency)
    # but fully batched across reports, and the scan-based sponge keeps
    # the traced graph O(1) in stream length. History: round 4 capped
    # this at 32,768 on a measured "superlinear knee" (1.9 s @ 32k vs
    # 209 s @ 152k blocks); round 5 showed that knee was a FLAT-scan
    # runtime pathology, not inherent — with nested scans
    # (keccak_jax._SCAN_CHUNK) the chain is linear: 91 us/block at
    # 152,382 blocks (13.9 s/chain @ batch 8, 8.9 s @ batch 256 —
    # near-flat in batch, so amortization works). The cap now covers
    # the north-star SumVec len=100k (152,382 blocks) with margin.
    # Honest bound (measured 2026-08-01): a FULL draft len=100k
    # prepare is ~5-6 sequential chains, 49.5 s/step at batch 64
    # (1.29 r/s ~= the 1.3 r/s host loop; device wins from batch >=128
    # and tops out ~2.5-5 r/s at the HBM-bound batch ~256) — the
    # draft's sequential sponge remains why spec-framing cannot reach
    # the fast framing's 100 r/s at this length on any single
    # accelerator (BASELINE.md "Draft mode").
    MAX_STREAM_BLOCKS = 160_000

    # Smallest batch at which the device draft engine beats the scalar
    # host loop (measured r5: host parity at 64, device wins from ~128;
    # 8 keeps smaller accelerators eligible while rejecting configs
    # whose materialized share cannot amortize at all).
    MIN_DEVICE_ROWS = 8

    @classmethod
    def supports_circuit(cls, circ, budget_bytes=None) -> bool:
        import math

        jf_limbs = circ.FIELD.ENCODED_SIZE // 8
        longest = max(
            circ.input_len, circ.proof_len, circ.prove_rand_len, circ.query_rand_len,
            circ.joint_rand_len,
        )
        blocks = math.ceil(
            (longest + 2 * _REJECT_WINDOW) * jf_limbs / RATE_LANES
        )
        # absorb side: the longest binder is the encoded measurement
        # share (joint-rand part)
        absorb_blocks = (PREFIX_BYTES + 1 + SEED_SIZE + circ.input_len * circ.FIELD.ENCODED_SIZE) // RATE + 1
        if max(blocks, absorb_blocks) > cls.MAX_STREAM_BLOCKS:
            return False
        # HBM feasibility bound (ISSUE r6): the draft sponge has no
        # random-access counter, so the helper share MATERIALIZES at
        # O(input_len) per row regardless of query tiling — a stream
        # length under MAX_STREAM_BLOCKS can still be un-runnable on a
        # small-HBM part. Gate on the model: if fewer than
        # MIN_DEVICE_ROWS rows fit the budget, the scalar host loop is
        # both safer and (below the amortization knee) faster. Unknown
        # budget (CPU backend, tunnel without memory_stats) keeps the
        # legacy blocks-only behavior.
        from . import engine
        from .feasibility import device_memory_budget, feasible_rows

        if budget_bytes is None:
            budget_bytes = device_memory_budget()
        tile = (
            min(engine.STREAM_TILE_ELEMS, circ.input_len)
            if circ.input_len >= engine.STREAM_MIN_INPUT_LEN
            else None
        )
        rows = feasible_rows(circ, budget_bytes, tile_elems=tile, draft=True)
        return rows is None or rows >= cls.MIN_DEVICE_ROWS

    # --- draft XOF plumbing ---
    def _draft_dst(self, usage: int) -> bytes:
        return draft_dst(self.circ.algo_id, usage)

    def _prefix_segments(self, usage: int, seed):
        """byte(8) || dst8 at offset 0 (static), seed16 at offset 9."""
        head = bytes([DRAFT_DST_SIZE]) + self._draft_dst(usage)
        if isinstance(seed, (bytes, bytearray)):
            return [(0, head + bytes(seed))]
        return [(0, head), (9, seed)]

    def _expand_vec_draft(self, usage: int, seed, binder_segs, binder_len: int, length: int, batch: int):
        segs = self._prefix_segments(usage, seed) + [
            (PREFIX_BYTES + off, content) for off, content in binder_segs
        ]
        stream = _sponge_stream(
            segs, PREFIX_BYTES + binder_len, batch, _stream_blocks_for(self.jf, length)
        )
        return _reject_sample(self.jf, stream, length)

    def _derive_seed_draft(self, usage: int, seed, binder_segs, binder_len: int, batch: int):
        segs = self._prefix_segments(usage, seed) + [
            (PREFIX_BYTES + off, content) for off, content in binder_segs
        ]
        stream = _sponge_stream(segs, PREFIX_BYTES + binder_len, batch, 1)
        return stream[:, : SEED_SIZE // 8]

    # --- overrides of the fast-framing plumbing ---
    def _expand_share(self, seed_lanes, usage: int, length: int):
        batch = seed_lanes.shape[0]
        return self._expand_vec_draft(usage, seed_lanes, [(0, b"\x01")], 1, length, batch)

    def _expand_vec(self, usage: int, seed_lanes, binder_parts, binder_len: int, length: int):
        # only ever called with an empty binder from the shared pipeline
        # (prove/joint randomness); share expansion goes via _expand_share
        assert not binder_parts and binder_len == 0, "draft binders use byte segments"
        batch = seed_lanes.shape[0]
        return self._expand_vec_draft(usage, seed_lanes, [], 0, length, batch)

    def _part_binder(self, agg_id: int, meas, helper_seed):
        # draft binds the full encoded share for BOTH aggregators
        return field_value_to_enc_lanes(self.jf, meas)

    def _joint_rand_part(self, agg_id: int, blind_lanes, nonce_lanes, share_binder_lanes):
        batch = blind_lanes.shape[0]
        binder_len = 1 + SEED_SIZE + 8 * share_binder_lanes.shape[-1]
        segs = [
            (0, bytes([agg_id])),
            (1, nonce_lanes),
            (1 + SEED_SIZE, share_binder_lanes),
        ]
        return self._derive_seed_draft(
            USAGE_JOINT_RAND_PART, blind_lanes, segs, binder_len, batch
        )

    def _joint_rand_seed(self, part0_lanes, part1_lanes):
        batch = part0_lanes.shape[0]
        segs = [(0, part0_lanes), (SEED_SIZE, part1_lanes)]
        return self._derive_seed_draft(
            USAGE_JOINT_RAND_SEED, b"\x00" * SEED_SIZE, segs, 2 * SEED_SIZE, batch
        )

    def _joint_rand(self, jr_seed_lanes):
        return self._expand_vec(
            USAGE_JOINT_RANDOMNESS, jr_seed_lanes, [], 0, self.circ.joint_rand_len
        )

    def _query_rand(self, verify_key: bytes, nonce_lanes):
        batch = nonce_lanes.shape[0]
        return self._expand_vec_draft(
            USAGE_QUERY_RANDOMNESS,
            verify_key,
            [(0, nonce_lanes)],
            SEED_SIZE,
            self.circ.query_rand_len,
            batch,
        )
