"""First-class fault injection: a registry of named failpoints.

A DAP deployment's steady state includes helper outages, slow WANs and
mid-commit crashes; this module lets tests, the chaos harness
(scripts/chaos_run.py) and operators provoke those failures
deterministically at the exact seams where they happen in production
(docs/ROBUSTNESS.md has the full fault matrix).

Configuration — the `JANUS_FAILPOINTS` environment variable or the
`failpoints:` key of the common YAML config section (env wins):

    JANUS_FAILPOINTS='datastore.commit=error:0.3;helper.request=delay:2.0,count=5;engine.dispatch=oom:1'

Grammar (';'-separated entries):

    <name>=<action>[:<arg>][,prob=<P>][,count=<N>][,after=<K>]

Actions:

    error[:P]    raise at the site with probability P (default 1.0).
                 The site chooses the exception type so the injected
                 failure is indistinguishable from the real one (a
                 retryable transport error at the HTTP client, a
                 retryable conflict in run_tx, ...).
    delay[:S]    sleep S seconds (default 1.0), then continue — a slow
                 WAN / slow response body.
    timeout[:S]  sleep S seconds (default 1.0), then raise the site's
                 timeout error — a hung peer that eventually trips the
                 socket timeout.
    crash[:P]    os._exit(CRASH_EXIT_CODE) with probability P — the
                 moral equivalent of SIGKILL at this exact line; no
                 finally blocks, no flushes, no transaction rollback.
    oom[:P]      raise a RESOURCE_EXHAUSTED-shaped error so the engine
                 OOM-recovery path (halved-bucket retry, host fallback)
                 takes over.
    hang[:S]     park the calling thread — a wedged device dispatch /
                 tunnel stall that never returns. S seconds when given;
                 default (0) parks FOREVER, released only by the
                 process stopper (release_hangs(), wired to SIGTERM) or
                 by re-configuring/disarming the registry. A timed park
                 or a reconfigure-release RESUMES the site (the device
                 finally answered); a STOPPER release raises
                 FailpointError instead — a thread woken mid-teardown
                 must not re-enter real device work while the
                 interpreter finalizes. Pair with the dispatch watchdog
                 (docs/ROBUSTNESS.md "Device hangs & deadlines") to
                 prove hung work is abandoned, not waited out.

Modifiers: `prob=P` overrides the firing probability regardless of
action arg; `count=N` is a firing budget — after N firings the
failpoint goes inert (failures that storm and then clear); `after=K`
skips the first K hits of the site before arming — "let two jobs land,
wedge the third" schedules (the resident-accumulator chaos proof
quarantines mid-stream this way) without racing a sleep against the
job loop.

Scoped names: sites that serve many logical operations fire both their
base name and a scoped variant — run_tx fires `datastore.commit` and
`datastore.commit.<tx_name>` — so a schedule can target one transaction
("crash the leader's aggregation write, nothing else").

Cost when disabled: `hit()` is a single module-flag check (measured in
the bench --dry-run `failpoint_overhead` record); the registry compiles
to a no-op on every production hot path unless explicitly armed.
"""

from __future__ import annotations

import contextlib
import logging
import os
import random
import threading
import time

log = logging.getLogger(__name__)

# Distinctive exit status for the crash action so harnesses can tell an
# injected crash from a real one.
CRASH_EXIT_CODE = 77

_ACTIONS = ("error", "delay", "timeout", "crash", "oom", "hang")


class FailpointError(Exception):
    """Deliberately injected failure (the default when a site does not
    supply a more realistic exception type)."""


class FailpointSpecError(ValueError):
    """A JANUS_FAILPOINTS / YAML failpoint spec did not parse."""


class _Failpoint:
    __slots__ = ("name", "action", "arg", "prob", "count", "after", "fired", "hits")

    def __init__(
        self,
        name: str,
        action: str,
        arg: float,
        prob: float,
        count: int | None,
        after: int = 0,
    ):
        self.name = name
        self.action = action
        self.arg = arg
        self.prob = prob
        self.count = count  # None = unlimited
        self.after = after  # skip the first N hits before arming
        self.fired = 0
        self.hits = 0

    def snapshot(self) -> dict:
        return {
            "action": self.action,
            "arg": self.arg,
            "prob": self.prob,
            "count": self.count,
            "after": self.after,
            "hits": self.hits,
            "fired": self.fired,
        }


# ENABLED is THE hot-path flag: hit() returns after one check when no
# failpoint is armed. Everything else is guarded by _lock.
ENABLED = False
_lock = threading.Lock()
_registry: dict[str, _Failpoint] = {}
# deterministic under JANUS_FAILPOINTS_SEED (chaos schedules that want
# reproducible probabilistic faults), process-random otherwise
_rng = random.Random(
    int(os.environ["JANUS_FAILPOINTS_SEED"])
    if os.environ.get("JANUS_FAILPOINTS_SEED")
    else None
)
# Threads parked by the hang action wait on this event. It is set (and
# replaced with a fresh one) on every reconfigure/disarm, and by
# release_hangs() — which the binaries' SIGTERM handler calls — so a
# parked "wedged device" releases on shutdown or schedule change
# instead of pinning teardown.
_hang_release = threading.Event()


def release_hangs() -> None:
    """Unpark every thread currently held by a hang failpoint (the
    process stopper hook: a modeled device wedge must not outlive the
    process's intent to exit). Unlike a reconfigure — where the site
    RESUMES, modeling a device that finally answered — a stopper
    release makes the site RAISE FailpointError: a thread woken during
    teardown must not re-enter real (native device) work while the
    interpreter finalizes underneath it."""
    global _hang_release
    with _lock:
        old = _hang_release
        _hang_release = threading.Event()
    old._janus_hang_raise = True  # waiters captured THIS event
    old.set()


def _parse_one(name: str, body: str) -> _Failpoint:
    parts = [p.strip() for p in body.split(",") if p.strip()]
    if not parts:
        raise FailpointSpecError(f"failpoint {name!r}: empty action")
    action, _, raw_arg = parts[0].partition(":")
    action = action.strip()
    if action not in _ACTIONS:
        raise FailpointSpecError(
            f"failpoint {name!r}: unknown action {action!r} (expected one of {_ACTIONS})"
        )
    try:
        # hang's arg is seconds with 0 = forever, so its default is 0
        arg = float(raw_arg) if raw_arg else (0.0 if action == "hang" else 1.0)
    except ValueError:
        raise FailpointSpecError(f"failpoint {name!r}: bad action arg {raw_arg!r}") from None
    # for error/crash/oom the positional arg IS the probability; for
    # delay/timeout/hang it is seconds and prob defaults to always
    prob = arg if action in ("error", "crash", "oom") else 1.0
    count = None
    after = 0
    for mod in parts[1:]:
        key, _, val = mod.partition("=")
        key = key.strip()
        try:
            if key == "prob":
                prob = float(val)
            elif key == "count":
                count = int(val)
            elif key == "after":
                after = int(val)
            else:
                raise FailpointSpecError(
                    f"failpoint {name!r}: unknown modifier {key!r} "
                    "(expected prob=/count=/after=)"
                )
        except ValueError:
            raise FailpointSpecError(f"failpoint {name!r}: bad modifier {mod!r}") from None
    if not 0.0 <= prob <= 1.0:
        raise FailpointSpecError(f"failpoint {name!r}: prob {prob} outside [0, 1]")
    if count is not None and count < 0:
        raise FailpointSpecError(f"failpoint {name!r}: negative count")
    if after < 0:
        raise FailpointSpecError(f"failpoint {name!r}: negative after")
    return _Failpoint(name, action, arg, prob, count, after)


def parse_spec(spec) -> dict[str, _Failpoint]:
    """Parse a spec string (`name=action:arg,mod=...;name2=...`) or a
    mapping ({name: "action:arg,mod=..."}, the YAML form) into
    failpoints. Raises FailpointSpecError on malformed input — a chaos
    schedule with a typo must fail loudly, not silently inject nothing.
    """
    entries: list[tuple[str, str]] = []
    if isinstance(spec, dict):
        entries = [(str(k).strip(), str(v)) for k, v in spec.items()]
    else:
        for chunk in str(spec).split(";"):
            chunk = chunk.strip()
            if not chunk:
                continue
            name, sep, body = chunk.partition("=")
            if not sep:
                raise FailpointSpecError(f"failpoint entry {chunk!r}: expected name=action")
            entries.append((name.strip(), body))
    out: dict[str, _Failpoint] = {}
    for name, body in entries:
        if not name:
            raise FailpointSpecError(f"failpoint entry with empty name: {body!r}")
        out[name] = _parse_one(name, body)
    return out


def configure(spec) -> None:
    """Replace the active failpoint set. `spec` is a spec string, a
    mapping, or None/''/{} to disarm everything."""
    global ENABLED, _hang_release
    parsed = parse_spec(spec) if spec else {}
    with _lock:
        _registry.clear()
        _registry.update(parsed)
        ENABLED = bool(_registry)
        # re-arming or disarming releases threads parked by the OLD
        # schedule's hang entries (the modeled wedge "recovers")
        old_release, _hang_release = _hang_release, threading.Event()
    old_release.set()
    if parsed:
        log.warning(
            "failpoints ARMED: %s",
            "; ".join(f"{n}={fp.action}:{fp.arg}" for n, fp in parsed.items()),
        )


def configure_from_env(default=None, environ=os.environ) -> None:
    """Arm from JANUS_FAILPOINTS, falling back to `default` (the YAML
    `failpoints:` value) when the env var is absent. An empty env var
    explicitly disarms (overriding the YAML)."""
    raw = environ.get("JANUS_FAILPOINTS")
    configure(raw if raw is not None else default)


def clear() -> None:
    configure(None)


# Boot-warmup suppression (ISSUE 17): engine warmup dispatches are
# infrastructure, not the serving path a chaos schedule drills. When a
# binary boots with failpoints armed AND warmup_engines_at_boot, the
# warmup's dispatches would otherwise consume `after=K` anchors and
# `count=` budgets, shifting where a scheduled fault lands — the
# suppression window keeps every site inert (hits not even counted) so
# schedules stay anchored to SERVING dispatch counts. Process-global
# on purpose: warm dispatches run on watchdog/lane worker threads, not
# the caller's, and boot warmup completes before serving starts.
_suppressed = 0


@contextlib.contextmanager
def suppressed():
    """Context manager: every failpoint site is a no-op inside."""
    global _suppressed
    with _lock:
        _suppressed += 1
    try:
        yield
    finally:
        with _lock:
            _suppressed -= 1


def status() -> dict:
    """Snapshot for /statusz: active failpoints with remaining budgets."""
    with _lock:
        if not _registry:
            return {"enabled": False}
        return {
            "enabled": True,
            "failpoints": {name: fp.snapshot() for name, fp in _registry.items()},
        }


def _lookup_and_arm(name: str) -> _Failpoint | None:
    """One armed firing of `name`, or None. Budget/probability are
    evaluated under the lock so concurrent sites cannot overspend a
    count= budget."""
    with _lock:
        fp = _registry.get(name)
        if fp is None:
            return None
        fp.hits += 1
        if fp.hits <= fp.after:
            return None  # not armed yet (after=K skips the first K hits)
        if fp.count is not None and fp.fired >= fp.count:
            return None
        if fp.prob < 1.0 and _rng.random() >= fp.prob:
            return None
        fp.fired += 1
    from . import metrics

    metrics.failpoints_fired_total.add(name=name, action=fp.action)
    return fp


def _act(fp: _Failpoint, error_factory=None, timeout_factory=None) -> None:
    if fp.action == "delay":
        log.warning("failpoint %s: delaying %.3fs", fp.name, fp.arg)
        time.sleep(fp.arg)
        return
    if fp.action == "timeout":
        log.warning("failpoint %s: timing out after %.3fs", fp.name, fp.arg)
        time.sleep(fp.arg)
        exc = (
            timeout_factory()
            if timeout_factory is not None
            else TimeoutError(f"injected timeout (failpoint {fp.name})")
        )
        raise exc
    if fp.action == "crash":
        # the point is to model SIGKILL mid-line: no cleanup, no
        # rollback, no flush — only the log line (stderr) escapes
        log.error("failpoint %s: crashing (os._exit %d)", fp.name, CRASH_EXIT_CODE)
        os._exit(CRASH_EXIT_CODE)
    if fp.action == "oom":
        raise RuntimeError(f"RESOURCE_EXHAUSTED: injected failpoint {fp.name}")
    if fp.action == "hang":
        with _lock:
            release = _hang_release
        log.warning(
            "failpoint %s: hanging %s",
            fp.name,
            f"{fp.arg:.3f}s" if fp.arg > 0 else "forever (until released)",
        )
        release.wait(fp.arg if fp.arg > 0 else None)
        if getattr(release, "_janus_hang_raise", False):
            # stopper release (process exiting): abort the site instead
            # of resuming the modeled device work mid-teardown
            raise FailpointError(f"hang released by process stop (failpoint {fp.name})")
        return
    # action == "error"
    log.warning("failpoint %s: injecting error", fp.name)
    exc = (
        error_factory()
        if error_factory is not None
        else FailpointError(f"injected failure (failpoint {fp.name})")
    )
    raise exc


def hit(name: str, error_factory=None, timeout_factory=None) -> None:
    """The instrumented-site entry point. A no-op (one module-flag
    check) unless failpoints are armed; otherwise evaluates `name`'s
    probability/budget and performs its action. `error_factory` /
    `timeout_factory` let the site raise its own realistic exception
    types for the error/timeout actions."""
    if not ENABLED or _suppressed:
        return
    fp = _lookup_and_arm(name)
    if fp is not None:
        _act(fp, error_factory, timeout_factory)


def hit_scoped(base: str, scope: str, error_factory=None, timeout_factory=None) -> None:
    """Fire `base` and `base.scope` (e.g. `datastore.commit` and
    `datastore.commit.step_agg_job_write`) so schedules can target
    either every operation through a seam or one specific one."""
    if not ENABLED or _suppressed:
        return
    fp = _lookup_and_arm(base)
    if fp is not None:
        _act(fp, error_factory, timeout_factory)
    fp = _lookup_and_arm(base + "." + scope)
    if fp is not None:
        _act(fp, error_factory, timeout_factory)
