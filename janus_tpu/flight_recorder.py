"""Telemetry flight recorder: in-process metric history + trend/leak
detection (docs/OBSERVABILITY.md "Flight recorder and trend alerts").

Every other observability surface here is point-in-time: /metrics,
/statusz and /alertz can say how the process is doing NOW, but nothing
records how a number has MOVED over the last hours — so "zero-slope
resource curves under sustained load" (ROADMAP endurance gates) had no
judge. This module is that judge:

  1. **Recorder** (`FlightRecorder`): a low-cadence daemon thread
     (profiler-style; YAML `flight:` stanza on CommonConfig, installed
     by janus_main by default) snapshots a configured set of series —
     process RSS from /proc, HBM resident bytes, datastore table row
     counts and on-disk artifact sizes (both fed by the health
     sampler's gauges), upload-journal bytes, GC deleted-row counters —
     into a bounded on-disk ring of JSONL segments with downsampling
     tiers (raw interval → 1m → 10m rollups, fixed byte budget,
     torn-tail-tolerant reads like the upload journal). Raw snapshots
     also carry cumulative histogram bucket counts for the configured
     latency families, so p99 can be re-derived over any sub-window.

  2. **Trend analyzer**: per tracked series, a robust (Theil–Sen)
     linear-regression slope over the in-memory window with a leak
     verdict — the projected growth over the window must clear BOTH the
     residual noise band (median absolute deviation) and a relative
     floor, so flat-but-noisy series and microscopic drift both stay
     quiet. Latency families get a window-vs-window p99 comparison
     (first half vs second half of the window, from bucket deltas).
     Exported as `janus_flight_slope{series}` /
     `janus_flight_leak_active{series}` / `janus_flight_p99_ratio
     {family}` and wired into the SLO engine as the `trend` signal
     kind (slo.py), so a sustained leak pages through the existing
     burn-rate ladder and /alertz.

  3. **Serving**: `GET /debug/flight` (window queries, JSON) on every
     health listener, a `flight` /statusz section (ring occupancy,
     series tracked, last-snapshot age, live leak verdicts), and the
     chaos soak scenario (scripts/chaos_run.py --scenario soak) that
     gates on the recorder's verdicts.

The recorder measures its own cost and exports it
(`janus_flight_overhead_ratio`) — like the profiler, the <1% overhead
claim is a metric, not a promise. A failpoint (`flight.synthetic_leak`)
grows a synthetic tracked series while armed, so the leak detector can
be proven live end-to-end (the injected-leak negative test).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field

from .statusz import register_status_provider, unregister_status_provider

log = logging.getLogger(__name__)

# bytes the synthetic-leak failpoint adds per armed snapshot: large
# against every noise band, so the negative test flips the verdict in
# a handful of intervals
SYNTHETIC_LEAK_STEP = 1 << 20


def _read_rss_bytes() -> float | None:
    """Resident set size from /proc/self/statm (field 2, pages); None
    off Linux (the series is simply absent rather than fake)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Tracked series
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SeriesSpec:
    """One tracked series. source="metric" sums the named registry
    family over the label matchers; source="rss" reads /proc. `leak`
    marks the series as leak-gated: the analyzer issues a slope/leak
    verdict for it (cumulative counters are recorded for history but
    not leak-gated — their slope is their job)."""

    name: str
    source: str = "metric"  # metric | rss
    metric: str = ""
    labels: tuple = ()  # compiled matchers (metrics.compile_matchers)
    leak: bool = True

    @classmethod
    def from_dict(cls, d: dict) -> "SeriesSpec":
        from .metrics import compile_matchers

        source = str(d.get("source", "metric"))
        if source not in ("metric", "rss"):
            raise ValueError(f"unknown flight series source {source!r}")
        return cls(
            name=str(d["name"]),
            source=source,
            metric=str(d.get("metric", "")),
            labels=compile_matchers(d.get("labels")),
            leak=bool(d.get("leak", True)),
        )

    def read(self) -> float | None:
        if self.source == "rss":
            return _read_rss_bytes()
        from .metrics import REGISTRY

        m = REGISTRY.get(self.metric)
        if m is None or not hasattr(m, "sum_matching"):
            return None
        v, n = m.sum_matching(self.labels)
        return v if n else None


def BUILTIN_SERIES() -> list[SeriesSpec]:
    """The shipped tracked set — exactly the slow-leak risks the
    endurance gates name (RSS, HBM resident bytes, datastore rows,
    on-disk artifacts) plus the GC counters for history. YAML
    `flight.series` entries override these by name."""
    from .metrics import compile_matchers

    return [
        SeriesSpec(name="rss_bytes", source="rss", leak=True),
        SeriesSpec(
            name="engine_resident_bytes",
            metric="janus_engine_resident_bytes",
            leak=True,
        ),
        SeriesSpec(
            name="datastore_rows", metric="janus_datastore_table_rows", leak=True
        ),
        SeriesSpec(
            name="upload_journal_bytes", metric="janus_upload_journal_bytes", leak=True
        ),
        SeriesSpec(
            name="shape_manifest_bytes",
            metric="janus_artifact_bytes",
            labels=compile_matchers({"artifact": "shape_manifest"}),
            leak=True,
        ),
        SeriesSpec(
            name="aot_cache_bytes",
            metric="janus_artifact_bytes",
            labels=compile_matchers({"artifact": "aot_cache"}),
            leak=True,
        ),
        # cumulative: recorded into the ring for history/debug-bundle
        # evidence, never leak-gated (a healthy GC's counter RISES)
        SeriesSpec(
            name="gc_deleted_rows",
            metric="janus_gc_deleted_rows_total",
            leak=False,
        ),
    ]


@dataclass
class FlightRecorderConfig:
    """YAML `flight:` stanza on CommonConfig (enabled by default in
    every binary via janus_main). `dir: null` keeps the recorder
    memory-only (trend verdicts still work; nothing persists)."""

    enabled: bool = True
    interval_s: float = 10.0
    dir: str | None = None
    max_total_bytes: int = 16 << 20
    max_segment_bytes: int = 256 << 10
    # trend window the in-memory deque retains and verdicts judge over
    window_s: float = 3600.0
    # downsampling tiers written into the ring beside the raw records
    rollup_secs: tuple = (60.0, 600.0)
    # run the trend analysis every Nth snapshot pass (the Theil–Sen
    # pass costs more than a snapshot; the verdicts don't need to move
    # faster than a few intervals)
    analyze_every: int = 3
    # verdict knobs: at least min_points snapshots; projected growth
    # over the window must exceed BOTH noise_mult * residual MAD and
    # min_growth_ratio * max(|median level|, 1.0)
    min_points: int = 8
    noise_mult: float = 4.0
    min_growth_ratio: float = 0.05
    # window-vs-window p99: late/early ratio above this is unstable
    p99_max_ratio: float = 2.0
    # both halves of the window must have seen at least this many
    # observations for a p99 verdict — a handful of samples makes the
    # window-vs-window ratio pure noise
    p99_min_samples: int = 16
    latency_families: tuple = ("janus_http_request_duration_seconds",)
    series: tuple = ()  # raw dicts, merged over BUILTIN_SERIES by name

    @classmethod
    def from_dict(cls, d: dict | None) -> "FlightRecorderConfig":
        d = d or {}
        return cls(
            enabled=bool(d.get("enabled", True)),
            interval_s=float(d.get("interval_secs", 10.0)),
            dir=d.get("dir"),
            max_total_bytes=int(d.get("max_total_bytes", 16 << 20)),
            max_segment_bytes=int(d.get("max_segment_bytes", 256 << 10)),
            window_s=float(d.get("window_secs", 3600.0)),
            rollup_secs=tuple(
                float(x) for x in d.get("rollup_secs", (60.0, 600.0))
            ),
            analyze_every=max(1, int(d.get("analyze_every", 3))),
            min_points=int(d.get("min_points", 8)),
            noise_mult=float(d.get("noise_mult", 4.0)),
            min_growth_ratio=float(d.get("min_growth_ratio", 0.05)),
            p99_max_ratio=float(d.get("p99_max_ratio", 2.0)),
            p99_min_samples=int(d.get("p99_min_samples", 16)),
            latency_families=tuple(
                d.get("latency_families", ("janus_http_request_duration_seconds",))
            ),
            series=tuple(d.get("series", ())),
        )

    def build_series(self) -> list[SeriesSpec]:
        specs = {s.name: s for s in BUILTIN_SERIES()}
        for raw in self.series:
            spec = SeriesSpec.from_dict(raw)
            specs[spec.name] = spec
        return list(specs.values())


# ---------------------------------------------------------------------------
# Robust trend estimation
# ---------------------------------------------------------------------------


def theil_sen(points: list[tuple[float, float]]) -> tuple[float, float, float]:
    """(slope, intercept, residual MAD) of the Theil–Sen estimator over
    (t, v) points: slope = median of pairwise slopes, intercept =
    median(v - slope*t), noise = median absolute residual. Robust to a
    minority of outliers (a GC pause, one burst) the way least squares
    is not. Points are decimated evenly to <= 60 before the O(n^2)
    pairwise pass, so a 1h window at 1s cadence stays cheap."""
    n = len(points)
    if n < 2:
        return 0.0, points[0][1] if points else 0.0, 0.0
    if n > 60:
        step = n / 60.0
        points = [points[int(i * step)] for i in range(60)]
        n = len(points)
    slopes = []
    for i in range(n - 1):
        t0, v0 = points[i]
        for j in range(i + 1, n):
            t1, v1 = points[j]
            if t1 != t0:
                slopes.append((v1 - v0) / (t1 - t0))
    if not slopes:
        return 0.0, points[0][1], 0.0
    slopes.sort()
    slope = slopes[len(slopes) // 2]
    residuals = sorted(v - slope * t for t, v in points)
    intercept = residuals[len(residuals) // 2]
    abs_res = sorted(abs(v - (slope * t + intercept)) for t, v in points)
    mad = abs_res[len(abs_res) // 2]
    return slope, intercept, mad


def _p99_from_bucket_delta(
    bounds: tuple, early: list[float], late: list[float]
) -> float | None:
    """p99 upper-bound estimate from cumulative-bucket deltas
    (late - early, both cumulative counts per bound + the +Inf total
    appended last). None when the delta window saw no observations."""
    deltas = [b - a for a, b in zip(early, late)]
    total = deltas[-1]
    if total <= 0:
        return None
    target = 0.99 * total
    cum = 0.0
    for bound, d in zip(bounds, deltas):
        cum += d
        if cum >= target:
            return float(bound)
    return float("inf")


# ---------------------------------------------------------------------------
# The on-disk ring
# ---------------------------------------------------------------------------


class _Ring:
    """Bounded directory of JSONL segments (flight-<seq>.jsonl).
    Appends go to the active segment (flushed, not fsynced — history is
    best-effort evidence, not durability-critical); rotation at
    max_segment_bytes; the oldest whole segments are deleted to hold
    the byte budget. Reads are torn-tail-tolerant like the upload
    journal: an unparseable line (a crash mid-append) is skipped and
    counted, never fatal."""

    def __init__(self, path: str, max_segment_bytes: int, max_total_bytes: int):
        self.path = os.path.expanduser(path)
        self.max_segment_bytes = max(4096, int(max_segment_bytes))
        self.max_total_bytes = max(self.max_segment_bytes, int(max_total_bytes))
        os.makedirs(self.path, exist_ok=True)
        self._fh = None
        self._active = None
        self._active_bytes = 0
        self.dropped_segments = 0
        self.torn_lines = 0
        seqs = self._segment_seqs()
        self._seq = (seqs[-1] + 1) if seqs else 0

    def _segment_seqs(self) -> list[int]:
        out = []
        try:
            for name in os.listdir(self.path):
                if name.startswith("flight-") and name.endswith(".jsonl"):
                    try:
                        out.append(int(name[len("flight-") : -len(".jsonl")]))
                    except ValueError:
                        continue
        except OSError:
            pass
        return sorted(out)

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.path, f"flight-{seq:08d}.jsonl")

    def append(self, record: dict) -> None:
        line = json.dumps(record, separators=(",", ":")) + "\n"
        data = line.encode()
        if self._fh is None or self._active_bytes + len(data) > self.max_segment_bytes:
            self._rotate()
        self._fh.write(data)
        self._fh.flush()
        self._active_bytes += len(data)

    def _rotate(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        self._active = self._segment_path(self._seq)
        self._fh = open(self._active, "ab")
        self._active_bytes = 0
        self._seq += 1
        self._enforce_budget()

    def _enforce_budget(self) -> None:
        seqs = self._segment_seqs()
        sizes = {}
        for s in seqs:
            try:
                sizes[s] = os.path.getsize(self._segment_path(s))
            except OSError:
                sizes[s] = 0
        total = sum(sizes.values())
        for s in seqs:
            if total <= self.max_total_bytes or self._segment_path(s) == self._active:
                break
            try:
                os.unlink(self._segment_path(s))
                total -= sizes[s]
                self.dropped_segments += 1
            except OSError:
                break

    def state(self) -> dict:
        seqs = self._segment_seqs()
        total = 0
        for s in seqs:
            try:
                total += os.path.getsize(self._segment_path(s))
            except OSError:
                pass
        return {
            "dir": self.path,
            "segments": len(seqs),
            "bytes": total,
            "dropped_segments": self.dropped_segments,
            "torn_lines_skipped": self.torn_lines,
        }

    def read(self, since_unix: float | None = None, tier: str | None = None) -> list[dict]:
        """Records at or after `since_unix` (all when None), oldest
        first; `tier` filters ("raw"/"60"/"600")."""
        out: list[dict] = []
        for s in self._segment_seqs():
            try:
                with open(self._segment_path(s), "rb") as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            # torn tail (crash mid-append) or corruption:
                            # skip the line, keep the valid prefix
                            self.torn_lines += 1
                            continue
                        if since_unix is not None and rec.get("t", 0) < since_unix:
                            continue
                        if tier is not None and rec.get("tier") != tier:
                            continue
                        out.append(rec)
            except OSError:
                continue
        return out

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


class _RollupTier:
    """One downsampling tier: accumulates raw snapshots per
    floor(t/period) bucket and emits a mean/min/max/n record when the
    bucket completes."""

    __slots__ = ("period", "bucket", "stats")

    def __init__(self, period: float):
        self.period = float(period)
        self.bucket: int | None = None
        self.stats: dict[str, list] = {}  # name -> [sum, min, max, n]

    def feed(self, t: float, values: dict) -> dict | None:
        bucket = int(t // self.period)
        emitted = None
        if self.bucket is not None and bucket != self.bucket and self.stats:
            emitted = {
                "t": self.bucket * self.period,
                "tier": f"{self.period:g}",
                "v": {
                    name: {
                        "mean": s[0] / s[3],
                        "min": s[1],
                        "max": s[2],
                        "n": s[3],
                    }
                    for name, s in self.stats.items()
                },
            }
            self.stats = {}
        self.bucket = bucket
        for name, v in values.items():
            s = self.stats.get(name)
            if s is None:
                self.stats[name] = [v, v, v, 1]
            else:
                s[0] += v
                s[1] = min(s[1], v)
                s[2] = max(s[2], v)
                s[3] += 1
        return emitted


# ---------------------------------------------------------------------------
# The recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """See the module docstring. One instance per process, installed by
    `install_flight_recorder` (janus_main); tests construct their own
    and drive `snapshot_once()` / `analyze()` directly."""

    def __init__(self, cfg: FlightRecorderConfig | None = None, time_fn=time.time):
        self.cfg = cfg or FlightRecorderConfig()
        self._time = time_fn
        self.series = self.cfg.build_series()
        self._lock = threading.Lock()
        # in-memory window: (t, {name: value}) + histogram cumulatives
        self._window: list[tuple[float, dict]] = []
        self._hist_window: list[tuple[float, dict]] = []
        self._ring: _Ring | None = None
        if self.cfg.dir:
            try:
                self._ring = _Ring(
                    self.cfg.dir, self.cfg.max_segment_bytes, self.cfg.max_total_bytes
                )
            except OSError:
                log.exception("flight ring unavailable at %s; memory-only", self.cfg.dir)
        self._tiers = [_RollupTier(p) for p in self.cfg.rollup_secs]
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._started_unix: float | None = None
        self._last_snapshot_unix: float | None = None
        self._snapshots = 0
        self._busy_s = 0.0
        self._synthetic_bytes = 0
        self._last_analysis: dict = {}

    # -- lifecycle -----------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "FlightRecorder":
        if self.running:
            return self
        self._stop.clear()
        self._started_unix = self._time()
        self._thread = threading.Thread(
            target=self._loop, name="flight-recorder", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        # first pass immediately: a scrape right after boot must not
        # wait an interval for the janus_flight_* families to populate
        passes = 0
        while True:
            try:
                self.snapshot_once()
                passes += 1
                if passes % max(1, self.cfg.analyze_every) == 0:
                    self.analyze()
            except Exception:
                log.exception("flight recorder pass failed")
            if self._stop.wait(self.cfg.interval_s):
                return

    def stop(self, timeout_s: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout_s)
        self._thread = None
        if self._ring is not None:
            self._ring.close()

    # -- snapshotting --------------------------------------------------
    def _read_hist_cumulatives(self) -> dict:
        """{family: {"bounds": [...], "cum": [...]}}: cumulative bucket
        counts summed across label sets, +Inf total appended — enough
        to re-derive any sub-window's latency distribution by delta."""
        from . import metrics
        from .metrics import REGISTRY

        out = {}
        for family in self.cfg.latency_families:
            m = REGISTRY.get(family)
            if not isinstance(m, metrics.Histogram):
                continue
            with m._lock:
                per_bucket = [0.0] * len(m.buckets)
                total = 0.0
                for key, counts in m._counts.items():
                    for i, c in enumerate(counts):
                        per_bucket[i] += c
                    total += m._totals[key]
            cum = []
            running = 0.0
            for c in per_bucket:
                running += c
                cum.append(running)
            cum.append(total)
            out[family] = {"bounds": list(m.buckets), "cum": cum}
        return out

    def snapshot_once(self) -> dict:
        """One snapshot pass (the unit tests and the /debug handlers
        drive it directly): read every tracked series, append to the
        in-memory window and the on-disk ring, feed the rollup tiers,
        export the bookkeeping gauges. Returns the raw record."""
        from . import failpoints, metrics

        t0 = time.perf_counter()
        now = self._time()
        # the injected-leak failpoint: while armed (error action), every
        # snapshot grows a synthetic leak-gated series — the negative
        # test that proves the detector is live, not decorative
        try:
            failpoints.hit("flight.synthetic_leak")
        except Exception:
            self._synthetic_bytes += SYNTHETIC_LEAK_STEP
        values: dict[str, float] = {}
        for spec in self.series:
            try:
                v = spec.read()
            except Exception:
                log.exception("flight series %s read failed", spec.name)
                v = None
            if v is not None:
                values[spec.name] = float(v)
        if self._synthetic_bytes:
            values["synthetic_leak_bytes"] = float(self._synthetic_bytes)
        hists = self._read_hist_cumulatives()
        record = {"t": now, "tier": "raw", "v": values}
        with self._lock:
            self._window.append((now, values))
            self._hist_window.append((now, hists))
            cutoff = now - self.cfg.window_s * 1.25
            while self._window and self._window[0][0] < cutoff:
                self._window.pop(0)
            while self._hist_window and self._hist_window[0][0] < cutoff:
                self._hist_window.pop(0)
            self._snapshots += 1
            self._last_snapshot_unix = now
            if self._started_unix is None:
                self._started_unix = now
            if self._ring is not None:
                try:
                    self._ring.append(record)
                    for tier in self._tiers:
                        rollup = tier.feed(now, values)
                        if rollup is not None:
                            self._ring.append(rollup)
                except OSError:
                    log.exception("flight ring append failed")
            busy = time.perf_counter() - t0
            self._busy_s += busy
            overhead = self._overhead_ratio_locked(time.time())
            ring_state = self._ring.state() if self._ring is not None else None
        metrics.flight_snapshots_total.add()
        metrics.flight_overhead_ratio.set(overhead)
        if ring_state is not None:
            metrics.flight_ring_bytes.set(float(ring_state["bytes"]))
            metrics.flight_ring_segments.set(float(ring_state["segments"]))
        return record

    def _overhead_ratio_locked(self, now: float) -> float:
        span = now - self._started_unix if self._started_unix is not None else 0.0
        if span <= 0:
            return 0.0
        return self._busy_s / span

    # -- analysis ------------------------------------------------------
    def analyze(self, window_s: float | None = None) -> dict:
        """The trend verdicts over the trailing window: per leak-gated
        series a Theil–Sen slope (units/second) and a leak verdict, per
        latency family a first-half-vs-second-half p99 comparison.
        Exports janus_flight_slope / janus_flight_leak_active /
        janus_flight_p99_ratio as a side effect."""
        from . import metrics

        t0 = time.perf_counter()
        window_s = float(window_s or self.cfg.window_s)
        now = self._time()
        cutoff = now - window_s
        with self._lock:
            window = [(t, v) for t, v in self._window if t >= cutoff]
            hist_window = [(t, h) for t, h in self._hist_window if t >= cutoff]
        leak_gated = {s.name for s in self.series if s.leak}
        leak_gated.add("synthetic_leak_bytes")
        names = sorted({n for _, vals in window for n in vals})
        series_out = {}
        for name in names:
            points = [(t, vals[name]) for t, vals in window if name in vals]
            doc: dict = {"points": len(points), "leak_gated": name in leak_gated}
            if len(points) < max(2, self.cfg.min_points):
                doc["verdict"] = "insufficient_data"
                series_out[name] = doc
                continue
            t_base = points[0][0]
            rel = [(t - t_base, v) for t, v in points]
            slope, intercept, mad = theil_sen(rel)
            span = rel[-1][0]
            level = sorted(v for _, v in points)[len(points) // 2]
            growth = slope * window_s  # projected growth over the window
            noise_floor = self.cfg.noise_mult * mad
            rel_floor = self.cfg.min_growth_ratio * max(abs(level), 1.0)
            leak = (
                name in leak_gated
                and slope > 0
                and growth > noise_floor
                and growth > rel_floor
            )
            doc.update(
                {
                    "slope_per_s": slope,
                    "projected_window_growth": growth,
                    "noise_mad": mad,
                    "median_level": level,
                    "covered_s": span,
                    "verdict": "leak" if leak else "flat",
                }
            )
            series_out[name] = doc
            if name in leak_gated:
                metrics.flight_slope.set(slope, series=name)
                metrics.flight_leak_active.set(1.0 if leak else 0.0, series=name)
        latency_out = {}
        if len(hist_window) >= 3:
            mid = hist_window[len(hist_window) // 2]
            first, last = hist_window[0], hist_window[-1]
            for family in self.cfg.latency_families:
                h0 = first[1].get(family)
                hm = mid[1].get(family)
                h1 = last[1].get(family)
                if not (h0 and hm and h1):
                    continue
                bounds = tuple(h1["bounds"])
                early = _p99_from_bucket_delta(bounds, h0["cum"], hm["cum"])
                late = _p99_from_bucket_delta(bounds, hm["cum"], h1["cum"])
                n_early = hm["cum"][-1] - h0["cum"][-1]
                n_late = h1["cum"][-1] - hm["cum"][-1]
                doc = {
                    "p99_early_s": early,
                    "p99_late_s": late,
                    "early_n": n_early,
                    "late_n": n_late,
                    "early_window": [first[0], mid[0]],
                    "late_window": [mid[0], last[0]],
                }
                if (
                    early is None
                    or late is None
                    or min(n_early, n_late) < self.cfg.p99_min_samples
                ):
                    doc["verdict"] = "insufficient_data"
                elif early <= 0:
                    doc["verdict"] = "stable" if late <= 0 else "degraded"
                else:
                    ratio = late / early
                    doc["p99_ratio"] = ratio
                    doc["verdict"] = (
                        "stable" if ratio <= self.cfg.p99_max_ratio else "degraded"
                    )
                    metrics.flight_p99_ratio.set(ratio, family=family)
                latency_out[family] = doc
        analysis = {
            "window_s": window_s,
            "generated_unix": now,
            "series": series_out,
            "latency": latency_out,
            "leaking": sorted(
                n for n, d in series_out.items() if d.get("verdict") == "leak"
            ),
        }
        with self._lock:
            self._busy_s += time.perf_counter() - t0
            self._last_analysis = analysis
        return analysis

    # -- serving -------------------------------------------------------
    def document(self, window_s: float | None = None, max_points: int = 500) -> dict:
        """The GET /debug/flight payload: recent in-window snapshots
        (evenly decimated to max_points), the live trend analysis and
        the ring state. Pure read + one analysis pass."""
        window_s = float(window_s or self.cfg.window_s)
        analysis = self.analyze(window_s)
        now = self._time()
        cutoff = now - window_s
        with self._lock:
            snaps = [
                {"t": t, "v": vals} for t, vals in self._window if t >= cutoff
            ]
            ring_state = self._ring.state() if self._ring is not None else None
            overhead = self._overhead_ratio_locked(time.time())
            last = self._last_snapshot_unix
        if len(snaps) > max_points:
            step = len(snaps) / float(max_points)
            snaps = [snaps[int(i * step)] for i in range(max_points)]
        return {
            "enabled": True,
            "running": self.running,
            "interval_s": self.cfg.interval_s,
            "window_s": window_s,
            "series_tracked": [s.name for s in self.series],
            "snapshots_total": self._snapshots,
            "last_snapshot_unix": last,
            "overhead_ratio": round(overhead, 6),
            "ring": ring_state,
            "snapshots": snaps,
            "analysis": analysis,
        }

    def status(self) -> dict:
        """The compact /statusz `flight` section (scrape_check treats a
        stale last-snapshot age as a deploy regression)."""
        now = self._time()
        with self._lock:
            ring_state = self._ring.state() if self._ring is not None else None
            last = self._last_snapshot_unix
            overhead = self._overhead_ratio_locked(time.time())
            analysis = self._last_analysis
        leaks = {
            n: d.get("slope_per_s")
            for n, d in (analysis.get("series") or {}).items()
            if d.get("verdict") == "leak"
        }
        return {
            "enabled": self.cfg.enabled,
            "running": self.running,
            "interval_s": self.cfg.interval_s,
            "series_tracked": [s.name for s in self.series],
            "snapshots": self._snapshots,
            "last_snapshot_unix": last,
            "last_snapshot_age_s": (
                round(now - last, 3) if last is not None else None
            ),
            "overhead_ratio": round(overhead, 6),
            "ring": ring_state,
            "leaks_active": leaks,
            "latency_verdicts": {
                f: d.get("verdict")
                for f, d in (analysis.get("latency") or {}).items()
            },
        }


# ---------------------------------------------------------------------------
# Process-wide instance (the health listener's /debug/flight reads it)
# ---------------------------------------------------------------------------

_recorder: FlightRecorder | None = None
_recorder_lock = threading.Lock()


def install_flight_recorder(
    cfg: FlightRecorderConfig | None = None, start: bool = True
) -> FlightRecorder:
    """Install (replacing any previous) the process-wide recorder and
    register its /statusz `flight` section. janus_main calls this with
    the YAML stanza; a disabled config still installs (statusz and
    /debug/flight answer well-formed disabled documents)."""
    global _recorder
    cfg = cfg or FlightRecorderConfig()
    recorder = FlightRecorder(cfg)
    recorder._status_provider = recorder.status
    with _recorder_lock:
        prev, _recorder = _recorder, recorder
    if prev is not None:
        prev.stop()
    register_status_provider("flight", recorder._status_provider)
    if start and cfg.enabled:
        recorder.start()
    return recorder


def uninstall_flight_recorder() -> None:
    global _recorder
    with _recorder_lock:
        recorder, _recorder = _recorder, None
    if recorder is not None:
        recorder.stop()
        unregister_status_provider(
            "flight", getattr(recorder, "_status_provider", None)
        )


def get_flight_recorder() -> FlightRecorder | None:
    return _recorder


def flight_document(window_s: float | None = None, max_points: int = 500) -> dict:
    """The GET /debug/flight payload for this process (a process
    without an installed recorder answers a well-formed disabled
    document, like /alertz)."""
    recorder = _recorder
    if recorder is None:
        return {"enabled": False, "series_tracked": [], "snapshots": [], "analysis": {}}
    return recorder.document(window_s=window_s, max_points=max_points)
