"""DAP client: shard a measurement, HPKE-seal input shares, upload.

Equivalent of reference client/src/lib.rs:58-300 (`ClientParameters`,
HPKE-config fetch, `prepare_report`, `upload`). Sharding uses the host
Prio3 (single report); batched load generation uses the device shard
in janus_tpu.vdaf.testing instead.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from .core.hpke import HpkeApplicationInfo, Label, hpke_seal
from .core.retries import Backoff, retry_http_request
from .core.time_util import Clock, RealClock
from .messages import (
    Duration,
    HpkeConfig,
    HpkeConfigList,
    InputShareAad,
    PlaintextInputShare,
    Report,
    ReportId,
    ReportMetadata,
    Role,
    TaskId,
)
from .vdaf.registry import VdafInstance, circuit_for, prio3_host
from .vdaf.wire import Prio3Wire


@dataclass
class ClientParameters:
    """reference client/src/lib.rs:58."""

    task_id: TaskId
    leader_aggregator_endpoint: str
    helper_aggregator_endpoint: str
    time_precision: Duration

    def hpke_config_uri(self, role: Role) -> str:
        base = (
            self.leader_aggregator_endpoint
            if role == Role.LEADER
            else self.helper_aggregator_endpoint
        )
        return base.rstrip("/") + f"/hpke_config?task_id={b64url(self.task_id.data)}"

    def upload_uri(self) -> str:
        return self.leader_aggregator_endpoint.rstrip("/") + f"/tasks/{b64url(self.task_id.data)}/reports"


def b64url(raw: bytes) -> str:
    import base64

    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


class Client:
    """reference client/src/lib.rs:182."""

    def __init__(
        self,
        parameters: ClientParameters,
        vdaf: VdafInstance,
        leader_hpke_config: HpkeConfig,
        helper_hpke_config: HpkeConfig,
        clock: Clock | None = None,
        http=None,
    ):
        self.params = parameters
        self.vdaf = vdaf
        if vdaf.kind == "poplar1":
            from .vdaf.poplar1 import Poplar1

            self.prio3 = None
            self.wire = None
            self.poplar = Poplar1(vdaf.bits)
        else:
            self.prio3 = prio3_host(vdaf)
            self.wire = Prio3Wire(circuit_for(vdaf))
            self.poplar = None
        self.leader_hpke_config = leader_hpke_config
        self.helper_hpke_config = helper_hpke_config
        self.clock = clock or RealClock()
        self.http = http

    @classmethod
    def with_fetched_configs(cls, parameters: ClientParameters, vdaf: VdafInstance, http, clock=None):
        """Fetch both aggregators' HPKE config lists (reference :135)."""
        configs = []
        for role in (Role.LEADER, Role.HELPER):
            status, body = retry_http_request(
                lambda role=role: http.get(parameters.hpke_config_uri(role))
                + (getattr(http, "last_response_headers", {}),)
            )
            if status != 200:
                raise RuntimeError(f"hpke_config fetch failed: HTTP {status}")
            cfg_list = HpkeConfigList.from_bytes(body)
            if not cfg_list.configs:
                raise RuntimeError("aggregator advertised no HPKE configs")
            configs.append(cfg_list.configs[0])
        return cls(parameters, vdaf, configs[0], configs[1], clock=clock, http=http)

    def prepare_report(self, measurement, when=None) -> Report:
        """Shard + seal (reference client/src/lib.rs:212-260)."""
        report_id = ReportId(secrets.token_bytes(16))
        time = (when or self.clock.now()).to_batch_interval_start(self.params.time_precision)
        metadata = ReportMetadata(report_id, time)

        if self.poplar is not None:
            from .vdaf.poplar1 import encode_input_share, encode_public_share

            cws, (k0, k1) = self.poplar.shard(measurement)
            public_share = encode_public_share(self.poplar.bits, cws)
            leader_raw = encode_input_share(k0, 0, self.poplar.bits)
            helper_raw = encode_input_share(k1, 1, self.poplar.bits)
        else:
            public_share_parts, (leader_share, helper_share) = self.prio3.shard(
                measurement, report_id.data
            )
            public_share = self.wire.encode_public_share(public_share_parts)
            leader_raw = self.wire.encode_leader_share(
                leader_share.measurement_share,
                leader_share.proof_share,
                leader_share.joint_rand_blind,
            )
            helper_raw = self.wire.encode_helper_share(
                helper_share.seed, helper_share.joint_rand_blind
            )
        aad = InputShareAad(self.params.task_id, metadata, public_share).to_bytes()

        leader_payload = PlaintextInputShare((), leader_raw).to_bytes()
        helper_payload = PlaintextInputShare((), helper_raw).to_bytes()

        leader_ct = hpke_seal(
            self.leader_hpke_config,
            HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER),
            leader_payload,
            aad,
        )
        helper_ct = hpke_seal(
            self.helper_hpke_config,
            HpkeApplicationInfo(Label.INPUT_SHARE, Role.CLIENT, Role.HELPER),
            helper_payload,
            aad,
        )
        return Report(metadata, public_share, leader_ct, helper_ct)

    def upload(self, measurement, when=None) -> None:
        """PUT the report to the leader with retries (reference :270).
        The 3-tuple return hands response headers to the retry loop so
        a shedding leader's `429 + Retry-After` paces this client."""
        report = self.prepare_report(measurement, when=when)

        def attempt():
            status, body = self.http.put(
                self.params.upload_uri(),
                report.to_bytes(),
                {"Content-Type": Report.MEDIA_TYPE},
            )
            return status, body, getattr(self.http, "last_response_headers", {})

        status, body = retry_http_request(attempt, Backoff())
        if status not in (200, 201):
            raise RuntimeError(f"upload failed: HTTP {status}: {body[:200]!r}")
