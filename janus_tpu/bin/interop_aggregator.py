"""Interop-API aggregator binary: full DAP aggregator + in-process job
runners behind the interop test API (reference
interop_binaries/src/bin/janus_interop_aggregator.rs:121-160)."""

from __future__ import annotations

import argparse
import os
import secrets
import sys
import tempfile
import time

from ..core.time_util import RealClock
from ..datastore.store import Crypter, open_datastore
from ..interop import InteropAggregator
from ..trace import install_trace_subscriber


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="DAP interop test aggregator")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument(
        "--database", default="", help="datastore path (default: fresh temp file)"
    )
    parser.add_argument(
        "--datastore-keys",
        default=os.environ.get("DATASTORE_KEYS", ""),
        help="comma-separated base64url AES-128 keys; required with --database",
    )
    args = parser.parse_args(argv)
    install_trace_subscriber()

    if args.datastore_keys:
        from ..binary_utils import parse_datastore_keys

        keys = parse_datastore_keys(args.datastore_keys)
    elif args.database:
        raise SystemExit(
            "--datastore-keys (or DATASTORE_KEYS) is required with a persistent "
            "--database: a random per-process key cannot decrypt existing rows"
        )
    else:
        keys = [secrets.token_bytes(16)]  # ephemeral DB, ephemeral key
    db = args.database or os.path.join(tempfile.mkdtemp(prefix="interop_"), "ds.sqlite")
    ds = open_datastore(db, Crypter(keys), RealClock())
    agg = InteropAggregator(ds)
    srv = agg.server(host="0.0.0.0", port=args.port).start()
    agg.start_job_runners()
    print(f"interop aggregator listening on {srv.url} (db {db})", flush=True)
    try:
        while True:
            time.sleep(5)
    except KeyboardInterrupt:
        agg.stop()
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
