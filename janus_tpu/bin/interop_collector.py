"""Interop-API collector binary (reference
interop_binaries/src/bin/janus_interop_collector.rs)."""

from __future__ import annotations

import argparse
import sys
import time

from ..interop import InteropCollector
from ..trace import install_trace_subscriber


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="DAP interop test collector")
    parser.add_argument("--port", type=int, default=8080)
    args = parser.parse_args(argv)
    install_trace_subscriber()
    srv = InteropCollector().server(host="0.0.0.0", port=args.port).start()
    print(f"interop collector listening on {srv.url}", flush=True)
    try:
        while True:
            time.sleep(5)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
