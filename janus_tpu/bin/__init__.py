"""The five process entry points (reference aggregator/src/bin/):
`python -m janus_tpu.bin.aggregator` etc."""
