"""Aggregation job driver process (the leader's hot path).

Equivalent of reference aggregator/src/bin/aggregation_job_driver.rs:
instantiates the generic JobDriver loop with the AggregationJobDriver's
acquirer/stepper callbacks.
"""

from __future__ import annotations

import logging

from ..aggregator.aggregation_job_driver import (
    AggregationJobDriver,
    AggregationJobDriverConfig,
)
from ..aggregator.job_driver import JobDriver
from ..binary_utils import janus_main
from ..config import JobDriverBinaryConfig

log = logging.getLogger(__name__)


def run(cfg: JobDriverBinaryConfig, ds, stopper):
    from ..aggregator.health_sampler import HealthSampler, artifact_paths_from_config
    from ..aggregator.peer_health import default_tracker
    from ..aggregator.step_pipeline import StepPipeline
    from ..core.circuit_breaker import default_breakers

    # peer-outage parking + background half-open probing, sharing the
    # process-wide breaker registry with the driver below
    tracker = default_tracker(
        default_breakers(cfg.outbound_circuit_breaker), cfg.peer_health
    )
    tracker.start()
    driver = AggregationJobDriver(
        ds,
        # per-attempt timeout / body budget / size cap from the
        # `helper_http:` stanza (the overall budget stays the lease
        # deadline, recomputed per request)
        cfg.helper_http.build(),
        AggregationJobDriverConfig(
            maximum_attempts_before_failure=cfg.job_driver.maximum_attempts_before_failure,
            circuit_breaker=cfg.outbound_circuit_breaker,
            resident=cfg.resident_accumulators,
        ),
        # in-flight helper retries observe SIGTERM and step back instead
        # of spending the remaining lease on a dead peer
        stopper=stopper,
        peer_health=tracker if cfg.peer_health.enabled else None,
    )
    # a step failing during shutdown releases its lease immediately
    # (reacquirable by the surviving peer, attempts preserved)
    releaser = lambda acquired: driver.step_back(acquired, "shutdown_drain", 0.0)  # noqa: E731
    # stage-pipelined stepper (aggregator/step_pipeline.py): prefetch,
    # serialized device lane, detached HTTP/commit stages. Disable with
    # `step_pipeline: {enabled: false}` to fall back to serial steps.
    pipeline = None
    if cfg.step_pipeline.enabled:
        pipeline = StepPipeline(
            driver, cfg.step_pipeline, stopper=stopper, releaser=releaser
        )
    jd = JobDriver(
        cfg.job_driver,
        # fleet sharding + replica provenance on every claim
        # (docs/ARCHITECTURE.md "Running a fleet")
        driver.acquirer(cfg.job_driver.worker_lease_duration_s, fleet=cfg.common.fleet),
        driver.stepper,
        stopper,
        releaser=releaser,
        pipeline=pipeline,
    )
    # conservation-ledger evaluation rides the sampler (ledger.py)
    from ..ledger import install_ledger

    ledger_ev = install_ledger(ds, cfg.common.ledger)
    sampler = None
    if cfg.common.health_sampler_interval_s > 0:
        sampler = HealthSampler(
            ds,
            cfg.common.health_sampler_interval_s,
            artifact_paths=artifact_paths_from_config(cfg.common),
            ledger=ledger_ev,
        ).start()
    # resident mode: background flusher bounds the unflushed window for
    # idle drivers and flushes a quarantined engine's state so the
    # interim host path sees complete batch rows
    flusher = None
    if cfg.resident_accumulators.enabled:
        from ..aggregator.aggregation_job_driver import ResidentFlusher

        flusher = ResidentFlusher(
            driver, cfg.resident_accumulators.flush_interval_s
        ).start()
    try:
        jd.run()
    finally:
        tracker.stop()
        if sampler is not None:
            sampler.stop()
        if flusher is not None:
            flusher.stop()
        if pipeline is not None:
            # jd.run() drained the in-flight chains; this only retires
            # the idle stage workers
            pipeline.close()
        if cfg.resident_accumulators.enabled:
            # drain contract: in-flight chains are done (jd.run()
            # returned), so every committed delta is merged — flush the
            # resident state through the write-tx path before exit
            driver.flush_resident_state(reason="drain")
    log.info("aggregation job driver shut down")


def main(argv=None):
    return janus_main("DAP aggregation job driver", JobDriverBinaryConfig, run, argv)


if __name__ == "__main__":
    main()
