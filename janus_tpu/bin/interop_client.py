"""Interop-API client binary (reference
interop_binaries/src/bin/janus_interop_client.rs)."""

from __future__ import annotations

import argparse
import sys
import time

from ..interop import InteropClient
from ..trace import install_trace_subscriber


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="DAP interop test client")
    parser.add_argument("--port", type=int, default=8080)
    args = parser.parse_args(argv)
    install_trace_subscriber()
    srv = InteropClient().server(host="0.0.0.0", port=args.port).start()
    print(f"interop client listening on {srv.url}", flush=True)
    try:
        while True:
            time.sleep(5)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
