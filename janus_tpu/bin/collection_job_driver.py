"""Collection job driver process.

Equivalent of reference aggregator/src/bin/collection_job_driver.rs:
drives leader collection jobs (compute aggregate share, fetch the
helper's encrypted share, finish the job).
"""

from __future__ import annotations

import logging

from ..aggregator.collection_job_driver import (
    CollectionJobDriver,
    CollectionJobDriverConfig,
)
from ..aggregator.job_driver import JobDriver
from ..binary_utils import janus_main
from ..config import JobDriverBinaryConfig

log = logging.getLogger(__name__)


def run(cfg: JobDriverBinaryConfig, ds, stopper):
    from ..aggregator.health_sampler import HealthSampler, artifact_paths_from_config
    from ..aggregator.peer_health import default_tracker
    from ..core.circuit_breaker import default_breakers

    # peer-outage parking + background half-open probing, sharing the
    # process-wide breaker registry with the driver below
    tracker = default_tracker(
        default_breakers(cfg.outbound_circuit_breaker), cfg.peer_health
    )
    tracker.start()
    driver = CollectionJobDriver(
        ds,
        # per-attempt timeout / body budget / size cap from the
        # `helper_http:` stanza (overall budget = the lease deadline)
        cfg.helper_http.build(),
        CollectionJobDriverConfig(
            maximum_attempts_before_failure=cfg.job_driver.maximum_attempts_before_failure,
            circuit_breaker=cfg.outbound_circuit_breaker,
        ),
        stopper=stopper,
        peer_health=tracker if cfg.peer_health.enabled else None,
    )
    jd = JobDriver(
        cfg.job_driver,
        # fleet sharding + replica provenance on every claim
        # (docs/ARCHITECTURE.md "Running a fleet")
        driver.acquirer(cfg.job_driver.worker_lease_duration_s, fleet=cfg.common.fleet),
        driver.stepper,
        stopper,
        releaser=lambda acquired: driver.step_back(acquired, "shutdown_drain", 0.0),
    )
    # conservation-ledger evaluation rides the sampler, and the
    # installed evaluator also powers this driver's cross-aggregator
    # reconciliation after each finished collection (ledger.py)
    from ..ledger import install_ledger

    ledger_ev = install_ledger(ds, cfg.common.ledger)
    sampler = None
    if cfg.common.health_sampler_interval_s > 0:
        sampler = HealthSampler(
            ds,
            cfg.common.health_sampler_interval_s,
            artifact_paths=artifact_paths_from_config(cfg.common),
            ledger=ledger_ev,
        ).start()
    try:
        jd.run()
    finally:
        tracker.stop()
        if sampler is not None:
            sampler.stop()
    log.info("collection job driver shut down")


def main(argv=None):
    return janus_main("DAP collection job driver", JobDriverBinaryConfig, run, argv)


if __name__ == "__main__":
    main()
