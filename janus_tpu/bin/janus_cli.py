"""Operational CLI.

Equivalent of reference aggregator/src/bin/janus_cli.rs:54-78:
`provision-tasks` loads a YAML list of task documents into the
datastore; `create-datastore-key` emits a fresh AES-128 key. (The
reference's kubernetes-secret integration is deployment glue and is
out of scope; keys travel via flags/env here.)
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import secrets
import sys

import yaml

from ..binary_utils import parse_datastore_keys
from ..core.time_util import RealClock
from ..datastore.store import Crypter, open_datastore
from ..task import Task
from ..trace import install_trace_subscriber


def cmd_create_datastore_key(args) -> int:
    print(base64.urlsafe_b64encode(secrets.token_bytes(16)).decode().rstrip("="))
    return 0


def _open_datastore(args) -> Datastore:
    raw = args.datastore_keys or os.environ.get("DATASTORE_KEYS", "")
    keys = parse_datastore_keys(raw)
    return open_datastore(args.database, Crypter(keys), RealClock())


def cmd_provision_tasks(args) -> int:
    with open(args.tasks_file) as f:
        docs = yaml.safe_load(f)
    if not isinstance(docs, list):
        raise SystemExit("tasks file must be a YAML list of task documents")
    tasks = [Task.from_dict(d) for d in docs]
    if not args.dry_run:  # dry-run parses/validates only, touching no DB
        if not args.database:
            raise SystemExit("--database is required unless --dry-run")
        ds = _open_datastore(args)
        try:

            def tx_fn(tx):
                for task in tasks:
                    tx.put_task(task)

            ds.run_tx(tx_fn, "provision_tasks")
            if args.precompile:
                _precompile(args, ds)
        finally:
            ds.close()
    out = [
        {"task_id": base64.urlsafe_b64encode(t.task_id.data).decode().rstrip("=")}
        for t in tasks
    ]
    json.dump(out, sys.stdout, indent=2)
    print()
    return 0


def _precompile(args, ds) -> None:
    """AOT-compile the provisioned tasks' engine steps into the shared
    persistent compilation cache (VERDICT r4 item 10): a fresh
    deployment's first job then loads executables from disk in seconds
    instead of stalling minutes on the first jit per (task, bucket).
    The cache dir must match the binaries' CommonConfig
    compilation_cache_dir (default ~/.cache/janus_tpu_xla)."""
    import time

    from ..binary_utils import enable_compile_cache, warmup_engines

    cache_dir = os.path.expanduser(args.compilation_cache_dir)
    enable_compile_cache(cache_dir)
    buckets = [int(b) for b in str(args.precompile).split(",") if b]
    for b in sorted(buckets):
        t0 = time.time()
        warmup_engines(ds, batch=b)
        print(
            f"precompiled bucket {b} -> {cache_dir} ({time.time() - t0:.1f}s)",
            file=sys.stderr,
        )


def cmd_list_tasks(args) -> int:
    ds = _open_datastore(args)
    try:
        tasks = ds.run_tx(lambda tx: tx.get_tasks(), "list_tasks")
        for t in tasks:
            tid = base64.urlsafe_b64encode(t.task_id.data).decode().rstrip("=")
            print(f"{tid} role={t.role.name.lower()} vdaf={t.vdaf.kind}")
    finally:
        ds.close()
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="janus_cli", description="Janus-TPU ops CLI")
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("create-datastore-key", help="generate a datastore AES-128 key")

    def add_ds_args(p):
        p.add_argument("--database", required=True, help="datastore path")
        p.add_argument(
            "--datastore-keys", default="", help="comma-separated base64url keys (or DATASTORE_KEYS env)"
        )

    pt = sub.add_parser("provision-tasks", help="load tasks from a YAML file")
    pt.add_argument("tasks_file", help="YAML list of task documents")
    pt.add_argument("--dry-run", action="store_true", help="parse and validate only")
    pt.add_argument("--database", default="", help="datastore path (unused with --dry-run)")
    pt.add_argument(
        "--datastore-keys", default="", help="comma-separated base64url keys (or DATASTORE_KEYS env)"
    )
    pt.add_argument(
        "--precompile",
        default="",
        metavar="BUCKETS",
        help="AOT-compile the tasks' engine steps for these comma-"
        "separated batch buckets (e.g. 32,512) into the persistent "
        "compilation cache, so a fresh deployment's first job skips "
        "the minutes-long jit",
    )
    pt.add_argument(
        "--compilation-cache-dir",
        default="~/.cache/janus_tpu_xla",
        help="must match the aggregator binaries' "
        "compilation_cache_dir (CommonConfig default)",
    )

    lt = sub.add_parser("list-tasks", help="list provisioned tasks")
    add_ds_args(lt)
    return parser


def main(argv=None) -> int:
    install_trace_subscriber()
    args = build_parser().parse_args(argv)
    if args.command == "create-datastore-key":
        return cmd_create_datastore_key(args)
    if args.command == "provision-tasks":
        return cmd_provision_tasks(args)
    if args.command == "list-tasks":
        return cmd_list_tasks(args)
    raise SystemExit(2)


if __name__ == "__main__":
    sys.exit(main())
