"""DAP aggregator HTTP server.

Equivalent of reference aggregator/src/bin/aggregator.rs:29-110: the
DAP router on `listen_address`, an optional aggregator-api listener on
a second address, and an optional in-process GC loop.
"""

from __future__ import annotations

import logging
import threading

from ..aggregator import Aggregator
from ..aggregator.garbage_collector import GarbageCollector
from ..aggregator.health_sampler import HealthSampler, artifact_paths_from_config
from ..aggregator.http_handlers import DapHttpApp, DapServer
from ..binary_utils import _split_hostport, janus_main
from ..config import AggregatorConfig
from ..core.time_util import RealClock

log = logging.getLogger(__name__)


def run(cfg: AggregatorConfig, ds, stopper):
    clock = RealClock()
    aggregator = Aggregator(ds, clock, cfg.protocol_config())
    host, port = _split_hostport(cfg.listen_address)
    server = DapServer(
        DapHttpApp(aggregator),
        host=host,
        port=port,
        max_handler_threads=cfg.max_handler_threads,
    ).start()
    log.info(
        "DAP server listening on %s (handler threads <= %d, ingest queue depth %d)",
        server.url,
        cfg.max_handler_threads,
        cfg.ingest_queue_depth,
    )

    api_server = None
    if cfg.aggregator_api_listen_address:
        from ..aggregator_api import AggregatorApi, AggregatorApiServer

        api_host, api_port = _split_hostport(cfg.aggregator_api_listen_address)
        api = AggregatorApi(ds, auth_tokens=cfg.aggregator_api_auth_tokens)
        api_server = AggregatorApiServer(api, host=api_host, port=api_port).start()
        log.info("aggregator API listening on %s", api_server.url)

    gc = GarbageCollector(ds, clock) if cfg.garbage_collection_interval_s else None

    # report-flow conservation ledger (janus_tpu/ledger.py): balance
    # evaluation rides the health sampler; /debug/ledger + the `ledger`
    # statusz section read the installed evaluator ambiently
    from ..ledger import install_ledger

    ledger_ev = install_ledger(ds, cfg.common.ledger)

    sampler = None
    if cfg.common.health_sampler_interval_s > 0:
        sampler = HealthSampler(
            ds,
            cfg.common.health_sampler_interval_s,
            artifact_paths=artifact_paths_from_config(cfg.common, cfg),
            gc=gc,
            ledger=ledger_ev,
        ).start()

    gc_thread = None
    if gc is not None:

        def gc_loop():
            while not stopper.stopped:
                try:
                    gc.run_once()
                except Exception:
                    log.exception("garbage collection pass failed")
                stopper.wait(cfg.garbage_collection_interval_s)

        gc_thread = threading.Thread(target=gc_loop, name="gc-loop", daemon=True)
        gc_thread.start()

    try:
        while not stopper.stopped:
            stopper.wait(1.0)
    finally:
        server.stop()  # also drains the ingest pipeline (DapHttpApp.close)
        if sampler is not None:
            sampler.stop()
        if api_server is not None:
            api_server.stop()
        # flush any uploads still buffered in the group-commit writer
        # and stop the journal replayer, so a graceful shutdown never
        # drops admitted reports (journaled ones survive on disk and
        # replay on the next boot)
        aggregator.close()
    log.info("aggregator shut down")


def main(argv=None):
    return janus_main("DAP aggregator server", AggregatorConfig, run, argv)


if __name__ == "__main__":
    main()
