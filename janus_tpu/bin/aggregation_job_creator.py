"""Aggregation job creator process.

Equivalent of reference aggregator/src/bin/aggregation_job_creator.rs:
periodically packs unaggregated reports into aggregation jobs
(aggregation_job_creator.rs:87 run / :154 update_tasks).
"""

from __future__ import annotations

import logging

from ..aggregator.aggregation_job_creator import AggregationJobCreator
from ..binary_utils import janus_main
from ..config import JobCreatorConfig

log = logging.getLogger(__name__)


def run(cfg: JobCreatorConfig, ds, stopper):
    # fleet task-shard preference (docs/ARCHITECTURE.md "Running a
    # fleet"): sweep own-shard tasks every pass, steal a foreign
    # shard's task only once its backlog ages past steal_after_secs
    creator = AggregationJobCreator(ds, cfg.creator_config(), fleet=cfg.common.fleet)
    while not stopper.stopped:
        try:
            n = creator.run_once()
            if n:
                log.info("created %d aggregation jobs", n)
        except Exception:
            log.exception("aggregation job creation pass failed")
        stopper.wait(cfg.aggregation_job_creation_interval_s)
    log.info("aggregation job creator shut down")


def main(argv=None):
    return janus_main("DAP aggregation job creator", JobCreatorConfig, run, argv)


if __name__ == "__main__":
    main()
