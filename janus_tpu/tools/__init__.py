"""Operator CLIs (reference tools/src/bin/): collect, dap_decode,
hpke_keygen, gen_alert_rules (Prometheus rules from the in-process SLO
definitions), debug_bundle (incident snapshot of a health listener),
report_trace ("where did report X go" — one report joined across the
upload journal, every datastore table, and the conservation ledger).
Invoke as `python -m janus_tpu.tools.<name>`."""
