"""Operator CLIs (reference tools/src/bin/): collect, dap_decode,
hpke_keygen. Invoke as `python -m janus_tpu.tools.<name>`."""
