"""Operator CLIs (reference tools/src/bin/): collect, dap_decode,
hpke_keygen, gen_alert_rules (Prometheus rules from the in-process SLO
definitions), debug_bundle (incident snapshot of a health listener).
Invoke as `python -m janus_tpu.tools.<name>`."""
