"""Render the in-process SLO definitions as a Prometheus rule file.

    python -m janus_tpu.tools.gen_alert_rules [--check]

Deployments that DO run an external Prometheus get the same alerts the
in-process engine (janus_tpu/slo.py) evaluates — generated from the
same `SloDefinition` objects, so the checked-in rule file
(docs/alerts/janus-alerts.yaml) can never drift from the code the way
the old prose alert sketches did. A tier-1 test asserts the checked-in
file matches this generator's output byte-for-byte; regenerate with:

    python -m janus_tpu.tools.gen_alert_rules > docs/alerts/janus-alerts.yaml

Translation notes (best effort, semantics documented in
docs/OBSERVABILITY.md):
  - counter-ratio and latency SLOs become multi-window multi-burn-rate
    expressions (SRE Workbook ch. 5): both the long and the short
    window must exceed `burn_rate x budget`.
  - condition SLOs (datastore-up, device health) become direct
    threshold alerts with `for:` set to the rung's short window —
    PromQL has no cheap equivalent of the engine's bad-tick ratio, and
    a threshold alert is what an operator wants from these anyway.
  - trend SLOs (resource leaks) alert while the flight recorder's
    janus_flight_leak_active verdict gauge is nonzero for the rung's
    short window — the slope/noise analysis already ran in-process.
  - conservation SLOs (report-flow ledger) alert while any
    janus_ledger_breach_active series is nonzero for the rung's short
    window — the ledger evaluator already debounced the imbalance
    through its grace window, so the gauge is a settled verdict.
"""

from __future__ import annotations

import argparse
import sys

from ..slo import (
    BUILTIN_SLOS,
    ConditionSignal,
    ConservationSignal,
    LatencySignal,
    RatioSignal,
    SloDefinition,
    TrendSignal,
    format_window,
)

HEADER = """\
# GENERATED FILE — DO NOT EDIT.
#
# Prometheus alerting rules generated from janus_tpu's in-process SLO
# definitions (janus_tpu/slo.py BUILTIN_SLOS) by
#   python -m janus_tpu.tools.gen_alert_rules
# A tier-1 test (tests/test_tools.py) pins this file to the
# generator's output; regenerate instead of editing.
"""


def _matchers_promql(compiled: tuple) -> str:
    parts = []
    for name, kind, want in compiled:
        if kind == "eq":
            parts.append(f'{name}="{want}"')
        elif kind == "re":
            parts.append(f'{name}=~"{want.pattern}"')
        else:  # "in"
            parts.append(f'{name}=~"{"|".join(sorted(want))}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def _ratio_rate(selectors, window: str) -> str:
    terms = [
        f"sum(rate({s.metric}{_matchers_promql(s.labels)}[{window}]))"
        for s in selectors
    ]
    return " + ".join(terms) if len(terms) > 1 else terms[0]


def _ratio_err_expr(sig: RatioSignal, window: str) -> str:
    bad = _ratio_rate(sig.bad, window)
    total = _ratio_rate(tuple(sig.good) + tuple(sig.bad), window)
    return f"(({bad}) / (({total}) > 0))"


def _latency_err_expr(sig: LatencySignal, window: str) -> str:
    le = f"{sig.effective_threshold_s():g}"
    base = _matchers_promql(sig.labels)
    # splice le into the bucket selector
    if base:
        bucket_sel = base[:-1] + f',le="{le}"}}'
    else:
        bucket_sel = f'{{le="{le}"}}'
    good = f"sum(rate({sig.metric}_bucket{bucket_sel}[{window}]))"
    total = f"sum(rate({sig.metric}_count{base}[{window}]))"
    return f"(1 - (({good}) / (({total}) > 0)))"


def _condition_expr(sig: ConditionSignal, short_window: str) -> str:
    parts = []
    for cond in sig.conditions:
        sel = f"{cond.selector.metric}{_matchers_promql(cond.selector.labels)}"
        if cond.mode == "delta":
            parts.append(f"(increase({sel}[{short_window}]) {cond.op} {cond.value:g})")
        else:
            parts.append(f"(sum({sel}) {cond.op} {cond.value:g})")
    return " or ".join(parts)


def _alert_name(slo_name: str, severity: str) -> str:
    camel = "".join(p.capitalize() for p in slo_name.split("_"))
    return f"Janus{camel}{severity.capitalize()}"


def rules_for(defs: list[SloDefinition]) -> dict:
    rules = []
    for d in defs:
        budget = d.budget
        for w in d.windows:
            long_w, short_w = format_window(w.long_s), format_window(w.short_s)
            threshold = f"({w.burn_rate:g} * {budget:g})"
            if isinstance(d.signal, RatioSignal):
                expr = (
                    f"{_ratio_err_expr(d.signal, long_w)} > {threshold}\n"
                    f"and\n"
                    f"{_ratio_err_expr(d.signal, short_w)} > {threshold}"
                )
                for_ = None
            elif isinstance(d.signal, LatencySignal):
                expr = (
                    f"{_latency_err_expr(d.signal, long_w)} > {threshold}\n"
                    f"and\n"
                    f"{_latency_err_expr(d.signal, short_w)} > {threshold}"
                )
                for_ = None
            elif isinstance(d.signal, ConditionSignal):
                expr = _condition_expr(d.signal, short_w)
                for_ = short_w
            elif isinstance(d.signal, ConservationSignal):
                # the ledger already held the residual through its
                # grace window before raising the breach gauge, so a
                # threshold alert on the debounced verdict is faithful
                sel = f"{d.signal.metric}{_matchers_promql(d.signal.labels)}"
                expr = f"(sum({sel}) > 0)"
                for_ = short_w
            elif isinstance(d.signal, TrendSignal):
                # like conditions: the leak-verdict gauge is already a
                # debounced boolean, so a threshold alert held for the
                # rung's short window is the faithful translation
                sel = f"{d.signal.metric}{_matchers_promql(d.signal.labels)}"
                expr = f"(sum({sel}) > 0)"
                for_ = short_w
            else:  # pragma: no cover - new signal kinds must be added here
                raise TypeError(f"no PromQL translation for {type(d.signal).__name__}")
            rule = {
                "alert": _alert_name(d.name, w.severity),
                "expr": expr,
            }
            if for_ is not None:
                rule["for"] = for_
            rule["labels"] = {"severity": w.severity, "slo": d.name}
            rule["annotations"] = {
                "summary": f"{d.name}: burn rate over {w.burn_rate:g}x "
                f"(objective {d.objective:g})",
                "description": d.description
                or f"SLO {d.name} is burning its error budget at more than "
                f"{w.burn_rate:g}x over both the {long_w} and {short_w} windows.",
                "runbook": "GET /alertz on the affected binary for burn rates, "
                "budget and evidence; scripts/debug_bundle.py for a snapshot.",
            }
            rules.append(rule)
    return {"groups": [{"name": "janus-slo-burn-rates", "rules": rules}]}


def generate_rules_text(defs: list[SloDefinition] | None = None) -> str:
    import yaml

    doc = rules_for(BUILTIN_SLOS() if defs is None else defs)
    return HEADER + yaml.safe_dump(doc, sort_keys=False, default_flow_style=False)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--check",
        metavar="PATH",
        help="exit non-zero unless PATH matches the generated output "
        "(the CI sync check)",
    )
    args = ap.parse_args(argv)
    text = generate_rules_text()
    if args.check:
        with open(args.check) as f:
            on_disk = f.read()
        if on_disk != text:
            print(
                f"{args.check} is out of date: regenerate with "
                "python -m janus_tpu.tools.gen_alert_rules > " + args.check,
                file=sys.stderr,
            )
            return 1
        print(f"{args.check} is in sync")
        return 0
    sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
