"""Full-featured collector CLI.

Equivalent of reference tools/src/bin/collect.rs:59-553: every VDAF
type and both query types, DAP-auth or bearer tokens, HPKE key
material via flags. Prints the report count, interval and aggregate
result.

Examples:
  python -m janus_tpu.tools.collect \
    --task-id <b64> --leader https://leader.example.com/ \
    --vdaf count \
    --authorization-bearer-token tok \
    --hpke-config <b64> --hpke-private-key <b64> \
    --batch-interval-start 1700000000 --batch-interval-duration 3600

  ... --vdaf sumvec --bits 16 --length 100 --current-batch
"""

from __future__ import annotations

import argparse
import base64
import sys

from ..collector import Collector, CollectorParameters
from ..core.auth import AuthenticationToken
from ..core.hpke import HpkeKeypair
from ..core.http_client import HttpClient
from ..messages import (
    BatchId,
    Duration,
    FixedSizeQuery,
    HpkeConfig,
    Interval,
    Query,
    TaskId,
    Time,
)
from ..vdaf.registry import VdafInstance


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="DAP collector (reference tools/collect)")
    p.add_argument("--task-id", required=True, help="base64url task id")
    p.add_argument("--leader", required=True, help="leader endpoint URL")

    auth = p.add_mutually_exclusive_group(required=True)
    auth.add_argument("--authorization-bearer-token", help="collector bearer token")
    auth.add_argument("--dap-auth-token", help="collector DAP-Auth-Token")

    p.add_argument("--hpke-config", required=True, help="base64url collector HpkeConfig")
    p.add_argument("--hpke-private-key", required=True, help="base64url collector private key")

    p.add_argument(
        "--vdaf",
        required=True,
        choices=["count", "countvec", "sum", "sumvec", "histogram", "fixedpoint16vec", "fixedpoint32vec", "fixedpoint64vec"],
    )
    p.add_argument("--bits", type=int, help="bit width (sum, sumvec)")
    p.add_argument("--length", type=int, help="vector length / bucket count")

    q = p.add_mutually_exclusive_group(required=True)
    q.add_argument("--batch-interval-start", type=int, help="time-interval query start (s)")
    q.add_argument("--current-batch", action="store_true", help="fixed-size: current batch")
    q.add_argument("--batch-id", help="fixed-size: base64url batch id")
    p.add_argument("--batch-interval-duration", type=int, help="time-interval query duration (s)")
    p.add_argument(
        "--timeout",
        type=float,
        default=600.0,
        help="seconds to poll before giving up (first aggregation can be slow)",
    )
    return p


def vdaf_from_args(args) -> VdafInstance:
    if args.vdaf == "count":
        return VdafInstance.count()
    if args.vdaf == "countvec":
        if args.length is None:
            raise SystemExit("--length is required for countvec")
        return VdafInstance.count_vec(length=args.length)
    if args.vdaf == "sum":
        if args.bits is None:
            raise SystemExit("--bits is required for sum")
        return VdafInstance.sum(bits=args.bits)
    if args.vdaf == "sumvec":
        if args.bits is None or args.length is None:
            raise SystemExit("--bits and --length are required for sumvec")
        return VdafInstance.sum_vec(length=args.length, bits=args.bits)
    if args.vdaf == "histogram":
        if args.length is None:
            raise SystemExit("--length is required for histogram")
        return VdafInstance.histogram(length=args.length)
    if args.vdaf.startswith("fixedpoint"):
        if args.length is None:
            raise SystemExit("--length is required for fixed-point vectors")
        bits = int(args.vdaf.removeprefix("fixedpoint").removesuffix("vec"))
        return VdafInstance.fixed_point_vec(length=args.length, bits=bits)
    raise SystemExit(f"unknown vdaf {args.vdaf}")


def query_from_args(args) -> Query:
    if args.batch_interval_start is not None:
        if args.batch_interval_duration is None:
            raise SystemExit("--batch-interval-duration is required with --batch-interval-start")
        return Query.time_interval(
            Interval(Time(args.batch_interval_start), Duration(args.batch_interval_duration))
        )
    if args.current_batch:
        return Query.fixed_size(FixedSizeQuery(FixedSizeQuery.CURRENT_BATCH))
    return Query.fixed_size(
        FixedSizeQuery(FixedSizeQuery.BY_BATCH_ID, BatchId(_unb64(args.batch_id)))
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    vdaf = vdaf_from_args(args)  # validate VDAF/query args before key material
    query = query_from_args(args)
    if args.authorization_bearer_token:
        token = AuthenticationToken.bearer(args.authorization_bearer_token)
    else:
        token = AuthenticationToken.dap_auth(args.dap_auth_token)
    try:
        keypair = HpkeKeypair(
            HpkeConfig.from_bytes(_unb64(args.hpke_config)), _unb64(args.hpke_private_key)
        )
        task_id = TaskId(_unb64(args.task_id))
    except Exception as e:
        raise SystemExit(f"bad key material or task id: {e}")
    params = CollectorParameters(task_id, args.leader, token, keypair)
    collector = Collector(params, vdaf, HttpClient())
    result = collector.collect(query, timeout_s=args.timeout)
    if result.partial_batch_selector is not None:
        bid = base64.urlsafe_b64encode(result.partial_batch_selector.batch_id.data)
        print(f"Batch: {bid.decode().rstrip('=')}")
    print(f"Number of reports: {result.report_count}")
    print(f"Interval: [{result.interval.start.seconds}, +{result.interval.duration.seconds}s)")
    print(f"Aggregation result: {result.aggregate_result}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
