""""Where did report X go" — trace one report through the pipeline.

    python -m janus_tpu.tools.report_trace \\
        --db /var/janus/ds.sqlite --task-id <b64url> --report-id <b64url> \\
        [--journal-dir /var/janus/journal] [--datastore-keys k1,k2] [--json]

The conservation ledger (janus_tpu/ledger.py; GET /debug/ledger) says
HOW MANY reports are unaccounted for per task; this answers WHICH
stage one specific report reached, by joining three sources in one
pass:

- the upload spill journal (admitted-but-not-yet-replayed reports
  survive a datastore outage on disk — a report can be "accepted"
  while absent from every table),
- the datastore (client_reports / report_aggregations + their jobs /
  batch_aggregations covering the report's timestamp), via the same
  single-snapshot query the ledger uses,
- the task's ledger books (counters, in-flight, imbalance) for the
  verdict's context: a report that is nowhere AND books that don't
  balance is a loss; a report that is nowhere with balanced books and
  a nonzero `expired` counter was garbage-collected.

Read-only against the datastore; journal segments are read directly
(never recovered/rotated) so tracing never mutates a live journal.
"""

from __future__ import annotations

import argparse
import base64
import glob
import json
import os
import sys

from ..datastore.store import Crypter, open_datastore
from ..core.time_util import RealClock
from ..messages import PrepareError, ReportId, TaskId


def _b64u(s: str, size: int, what: str) -> bytes:
    s = s.strip()
    try:
        raw = base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))
    except Exception:
        raise SystemExit(f"{what}: not valid base64url: {s!r}")
    if len(raw) != size:
        raise SystemExit(f"{what}: want {size} bytes, got {len(raw)}")
    return raw


def _scan_journal(journal_dir: str, crypter, task_id: TaskId, report_id: ReportId) -> dict:
    """Look for the report among spilled-but-unreplayed journal frames.
    Reads segment files directly — never constructs an UploadJournal,
    which would recover/rotate a live journal out from under its
    owner."""
    from ..ingest import journal as _j

    found = []
    segments = sorted(
        glob.glob(os.path.join(journal_dir, f"{_j._SEGMENT_PREFIX}*{_j._SEGMENT_SUFFIX}"))
    )
    undecodable = 0
    for path in segments:
        payloads, _reason = _j._read_frames(path)
        for payload in payloads:
            try:
                row = _j._decode_row(crypter, payload)
            except Exception:
                # wrong --datastore-keys (or none): frames are encrypted
                # at rest; count, keep scanning — CRC already validated
                undecodable += 1
                continue
            if row.task_id == task_id and row.report_id == report_id:
                found.append(
                    {
                        "segment": os.path.basename(path),
                        "client_time": row.client_time.seconds,
                    }
                )
    return {
        "dir": journal_dir,
        "segments_scanned": len(segments),
        "undecodable_frames": undecodable,
        "found": found,
    }


def _verdict(trace: dict, journal: dict | None, books: dict | None) -> str:
    ras = trace["report_aggregations"]
    terminal = [ra for ra in ras if ra["state"] in ("finished", "failed")]
    if terminal:
        ra = terminal[-1]
        if ra["state"] == "finished":
            collected = [b for b in trace["batch_aggregations"] if b["state"] == "collected"]
            if collected:
                return (
                    f"AGGREGATED and COLLECTED: finished in job {ra['job_id'][:16]}…; "
                    f"{len(collected)} covering batch shard(s) already collected"
                )
            return (
                f"AGGREGATED, awaiting collection: finished in job {ra['job_id'][:16]}…; "
                "its batch shards are not collected yet"
            )
        err = ra["prepare_error"]
        name = PrepareError(err).name.lower() if err is not None else "unknown"
        return f"REJECTED ({name}) in job {ra['job_id'][:16]}… — terminal, counted in the ledger's rejected:{name} lane"
    live = [ra for ra in ras if ra["job_state"] == "in_progress"]
    if live:
        ra = live[-1]
        return (
            f"IN FLIGHT: state {ra['state']!r} in job {ra['job_id'][:16]}… "
            f"(job step {ra['job_step']}, {ra['job_attempts']} attempt(s))"
        )
    if trace["client_report"] is not None:
        if ras:
            # claimed by jobs that are all abandoned/gone: back in the
            # unclaimed pool (mark_reports_unaggregated) or wedged
            return (
                "CLAIMED but every claiming job is no longer in progress — "
                "either re-queued for a fresh job or wedged (ledger imbalance will say which)"
            )
        if trace["client_report"]["aggregation_started"]:
            return "CLAIMED (aggregation_started) but no report_aggregations row — claim tx landed, job creation did not (in the creator's grace window)"
        return "ADMITTED, awaiting aggregation (unclaimed in client_reports)"
    if journal and journal["found"]:
        return (
            "SPILLED: accepted into the upload journal, not yet replayed into the "
            "datastore (outage spill; the replayer will admit it)"
        )
    hints = []
    if books:
        if (books.get("imbalance") or {}).get("ingest"):
            hints.append(
                f"task ingest imbalance is {books['imbalance']['ingest']} — consistent with a LOST report"
            )
        if books.get("expired"):
            hints.append(f"task has {books['expired']} expired report(s) — may have been GC'd")
    return "NOT FOUND in journal or datastore" + (": " + "; ".join(hints) if hints else " (expired/GC'd, never admitted, or lost)")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="trace one report through the pipeline")
    parser.add_argument("--db", required=True, help="database URL (postgres://…) or SQLite path")
    parser.add_argument("--task-id", required=True, help="base64url task id")
    parser.add_argument("--report-id", required=True, help="base64url report id")
    parser.add_argument("--journal-dir", help="upload journal directory to scan for spilled frames")
    parser.add_argument(
        "--datastore-keys",
        help="comma-separated base64url AES keys (only needed to decode journal frames)",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)

    task_id = TaskId(_b64u(args.task_id, 32, "--task-id"))
    report_id = ReportId(_b64u(args.report_id, 16, "--report-id"))
    keys = [
        base64.urlsafe_b64decode(k.strip() + "=" * (-len(k.strip()) % 4))
        for k in (args.datastore_keys or "").split(",")
        if k.strip()
    ]
    crypter = Crypter(keys) if keys else Crypter()

    ds = open_datastore(args.db, crypter, RealClock())
    # one snapshot: the per-report drill-down and the task's books from
    # the same transaction the ledger itself reads
    def read(tx):
        return (
            tx.ledger_report_trace(task_id, report_id),
            tx.get_task_counters(task_id),
            tx.ledger_inflight_by_task().get(task_id.data, {}),
        )

    trace, counters, inflight = ds.run_tx(read, "report_trace")

    from .. import ledger as _ledger

    rejected = {
        k[len(_ledger.REJECTED_PREFIX):]: v
        for k, v in counters.items()
        if k.startswith(_ledger.REJECTED_PREFIX)
    }
    rejected_param = {
        k[len(_ledger.REJECTED_PARAM_PREFIX):]: v
        for k, v in counters.items()
        if k.startswith(_ledger.REJECTED_PARAM_PREFIX)
    }
    books = {
        "admitted": counters.get(_ledger.ADMITTED, 0),
        "aggregated": counters.get(_ledger.AGGREGATED, 0),
        "collected": counters.get(_ledger.COLLECTED, 0),
        "expired": counters.get(_ledger.EXPIRED, 0),
        "lost": counters.get(_ledger.LOST, 0),
        "rejected": rejected,
        "param": {
            "admitted": counters.get(_ledger.ADMITTED_PARAM, 0),
            "aggregated": counters.get(_ledger.AGGREGATED_PARAM, 0),
            "rejected": rejected_param,
            "expired": counters.get(_ledger.EXPIRED_PARAM, 0),
        },
        "in_flight": inflight,
        # the same three balance equations the evaluator exports
        # (janus_tpu/ledger.py): param fanout keeps its own lane, and
        # collect drains both lanes' mass through batch_aggregations
        "imbalance": {
            "ingest": counters.get(_ledger.ADMITTED, 0)
            - counters.get(_ledger.AGGREGATED, 0)
            - sum(rejected.values())
            - counters.get(_ledger.EXPIRED, 0)
            - inflight.get("pending_reports", 0)
            - inflight.get("pending_aggregation", 0),
            "param": counters.get(_ledger.ADMITTED_PARAM, 0)
            - counters.get(_ledger.AGGREGATED_PARAM, 0)
            - sum(rejected_param.values())
            - counters.get(_ledger.EXPIRED_PARAM, 0)
            - inflight.get("pending_aggregation_param", 0),
            "collect": counters.get(_ledger.AGGREGATED, 0)
            + counters.get(_ledger.AGGREGATED_PARAM, 0)
            - counters.get(_ledger.COLLECTED, 0)
            - inflight.get("awaiting_collection", 0),
        },
    }

    journal = None
    if args.journal_dir:
        journal = _scan_journal(args.journal_dir, crypter, task_id, report_id)

    verdict = _verdict(trace, journal, books)
    doc = {
        "task_id": args.task_id,
        "report_id": args.report_id,
        "verdict": verdict,
        "trace": trace,
        "ledger": books,
        "journal": journal,
    }
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    print(f"report {args.report_id} of task {args.task_id}")
    print(f"  verdict: {verdict}")
    cr = trace["client_report"]
    if cr is not None:
        print(
            f"  client_reports: present, client_time {cr['client_time']}, "
            f"aggregation_started {cr['aggregation_started']}"
        )
    else:
        print("  client_reports: absent")
    for ra in trace["report_aggregations"]:
        err = ra["prepare_error"]
        errs = f", prepare_error {PrepareError(err).name.lower()}" if err is not None else ""
        print(
            f"  report_aggregation: job {ra['job_id'][:16]}… ord {ra['ord']} "
            f"state {ra['state']}{errs} (job: {ra['job_state']}, step {ra['job_step']})"
        )
    for ba in trace["batch_aggregations"]:
        print(
            f"  batch shard {ba['batch_identifier'][:16]}… ord {ba['ord']}: "
            f"state {ba['state']}, {ba['report_count']} report(s)"
        )
    if journal is not None:
        where = ", ".join(f["segment"] for f in journal["found"]) or "not found"
        extra = (
            f" ({journal['undecodable_frames']} undecodable frame(s) — wrong --datastore-keys?)"
            if journal["undecodable_frames"]
            else ""
        )
        print(f"  journal: {journal['segments_scanned']} segment(s) scanned, {where}{extra}")
    print(
        f"  ledger books: admitted {books['admitted']}, aggregated {books['aggregated']}, "
        f"rejected {sum(rejected.values())}, expired {books['expired']}, "
        f"collected {books['collected']}, imbalance {books['imbalance']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
