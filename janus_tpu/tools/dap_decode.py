"""Decode any DAP wire message from a file (or stdin) and pretty-print it.

Equivalent of reference tools/src/bin/dap_decode.rs: `--media-type`
selects the message type; the input is the raw TLS-syntax bytes.
"""

from __future__ import annotations

import argparse
import sys

from .. import messages as m

# media type -> message class (reference dap_decode.rs match arms)
MEDIA_TYPES = {
    "hpke-config-list": m.HpkeConfigList,
    "report": m.Report,
    "aggregation-job-init-req": m.AggregationJobInitializeReq,
    "aggregation-job-continue-req": m.AggregationJobContinueReq,
    "aggregation-job-resp": m.AggregationJobResp,
    "aggregate-share-req": m.AggregateShareReq,
    "aggregate-share": m.AggregateShare,
    "collect-req": m.CollectionReq,
    "collection": m.Collection,
}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="decode a DAP message")
    parser.add_argument("message_file", help="path to message bytes, or - for stdin")
    parser.add_argument(
        "--media-type",
        "-t",
        required=True,
        choices=sorted(MEDIA_TYPES),
        help="DAP media type of the message",
    )
    args = parser.parse_args(argv)

    if args.message_file == "-":
        data = sys.stdin.buffer.read()
    else:
        with open(args.message_file, "rb") as f:
            data = f.read()

    cls = MEDIA_TYPES[args.media_type]
    msg = cls.from_bytes(data)
    print(msg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
