"""One-command incident debug bundle.

    python scripts/debug_bundle.py --url http://127.0.0.1:9001 \\
        [--url http://127.0.0.1:9002 ...] [--config-file cfg.yaml] \\
        [--journal-dir /var/janus/journal] \\
        [--shape-manifest ~/.cache/janus_tpu_xla/shape_manifest.jsonl] \\
        [--out bundle.tar.gz]

Snapshots every introspection endpoint of one or several binaries'
health listeners — /metrics (both exposition modes), /statusz,
/debug/vars, /debug/traces, /debug/profile (collapsed + JSON),
/debug/boot, /debug/flight, /debug/ledger, /alertz, /readyz,
/healthz — plus the
resolved YAML config (secrets redacted) and the upload-journal
directory state, into a timestamped tar.gz with a MANIFEST.json
inventorying every capture (source, HTTP status, bytes, sha256). One
invocation takes ANY number of --url targets, and each target's
MANIFEST entry records the fleet replica id read off its /statusz —
so one incident bundle covers a whole replica fleet and stays
attributable per process. This
is the artifact an operator attaches to an incident: the flight
recorder, the SLO engine's burn rates and the metric families of the
moment, collected before the evidence scrolls out of the rings.

Non-200 answers (a degraded /readyz) are captured, never fatal; an
unreachable endpoint is recorded in the manifest with its error so a
half-dead process still yields a bundle.
"""

from __future__ import annotations

import argparse
import hashlib
import io
import json
import os
import re
import sys
import tarfile
import time

# endpoint name -> path; the names become file names inside the bundle
ENDPOINTS = (
    ("healthz", "/healthz"),
    ("readyz", "/readyz"),
    ("metrics", "/metrics"),
    ("metrics_openmetrics", "/metrics?openmetrics=1"),
    ("statusz", "/statusz"),
    ("debug_vars", "/debug/vars"),
    ("debug_traces", "/debug/traces?limit=10000"),
    ("alertz", "/alertz"),
    # continuous profiler (ISSUE 13): both renderings — the collapsed
    # folded stacks feed flamegraph.pl directly from the bundle — plus
    # the boot-phase timeline
    ("debug_profile", "/debug/profile"),
    ("debug_profile_json", "/debug/profile?format=json"),
    ("debug_boot", "/debug/boot"),
    # telemetry flight recorder (ISSUE 18): the recent window + the
    # slope/leak report — the long-horizon evidence a point-in-time
    # snapshot can't reconstruct
    ("debug_flight", "/debug/flight"),
    # report-flow conservation ledger: the per-task balance document —
    # whether the books closed at capture time, and where the
    # imbalance sits if they didn't
    ("debug_ledger", "/debug/ledger"),
)

_SECRET_KEY_RE = re.compile(r"(token|secret|password|key)s?$", re.IGNORECASE)
REDACTED = "**REDACTED**"


def redact_config(doc):
    """Recursively mask values whose key smells like a secret
    (token/secret/password/key). Keys are kept so the shape of the
    config survives; values never leave the host."""
    if isinstance(doc, dict):
        out = {}
        for k, v in doc.items():
            if _SECRET_KEY_RE.search(str(k)) and isinstance(v, (str, bytes, list, tuple)):
                out[k] = REDACTED
            else:
                out[k] = redact_config(v)
        return out
    if isinstance(doc, (list, tuple)):
        return [redact_config(v) for v in doc]
    return doc


def _fetch(url: str, timeout: float) -> tuple[int, bytes]:
    """(status, body) tolerating non-2xx (a degraded /readyz is 503 —
    still evidence, not an error)."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _target_name(url: str) -> str:
    """Filesystem-safe directory name for one listener URL."""
    return re.sub(r"[^A-Za-z0-9.]+", "_", url.split("://", 1)[-1]).strip("_")


def journal_dir_state(path: str) -> dict:
    """Non-content inventory of the upload-journal directory: segment
    names/sizes/mtimes (the rows themselves are encrypted at rest and
    stay on the host)."""
    entries = []
    total = 0
    try:
        names = sorted(os.listdir(path))
    except OSError as e:
        return {"path": path, "error": f"{type(e).__name__}: {e}"}
    for name in names:
        full = os.path.join(path, name)
        try:
            st = os.stat(full)
        except OSError:
            continue
        entries.append({"name": name, "bytes": st.st_size, "mtime": st.st_mtime})
        total += st.st_size
    return {
        "path": path,
        "segments": entries,
        "segment_count": len(entries),
        "total_bytes": total,
        "corrupt_segments": [
            e["name"] for e in entries if e["name"].endswith(".corrupt")
        ],
    }


def shape_manifest_state(path: str, aot_dir: str | None = None) -> dict:
    """Non-content inventory of the shape manifest + the AOT blob dir
    (names/sizes only; entry counts come from a tolerant parse — a
    corrupt manifest is evidence, not an error). `aot_dir` defaults to
    the manifest's sibling `aot/` (the standard layout under the
    compile cache dir); pass it explicitly for a relocated manifest."""
    out: dict = {"path": path}
    try:
        st = os.stat(path)
        out["bytes"] = st.st_size
        out["mtime"] = st.st_mtime
    except OSError as e:
        out["error"] = f"{type(e).__name__}: {e}"
        return out
    try:
        # READ-ONLY parse: a diagnostic tool must never compact/rewrite
        # the live manifest — the corrupt lines ARE the evidence
        from janus_tpu.aggregator.shape_manifest import inspect_file

        entries, stats = inspect_file(path)
        out["entries"] = len(entries)
        out["load"] = stats
    except Exception as e:  # stdlib-only parse, but stay non-fatal
        out["parse_error"] = f"{type(e).__name__}: {e}"
    aot_dir = aot_dir or os.path.join(os.path.dirname(path), "aot")
    blobs = []
    try:
        for name in sorted(os.listdir(aot_dir)):
            full = os.path.join(aot_dir, name)
            try:
                blobs.append({"name": name, "bytes": os.stat(full).st_size})
            except OSError:
                continue
        out["aot"] = {
            "dir": aot_dir,
            "blobs": blobs,
            "blob_count": len(blobs),
            "total_bytes": sum(b["bytes"] for b in blobs),
        }
    except OSError as e:
        out["aot"] = {"dir": aot_dir, "error": f"{type(e).__name__}: {e}"}
    return out


def flight_dir_state(path: str) -> dict:
    """Non-content inventory of the flight-recorder segment ring:
    segment names/sizes/mtimes plus per-segment record/torn-line counts
    from a READ-ONLY tolerant parse (`inspect_file` discipline — never
    compact or rewrite what you are capturing as evidence; the torn
    tail IS the evidence)."""
    entries = []
    total = 0
    try:
        names = sorted(os.listdir(path))
    except OSError as e:
        return {"path": path, "error": f"{type(e).__name__}: {e}"}
    for name in names:
        if not (name.startswith("flight-") and name.endswith(".jsonl")):
            continue
        full = os.path.join(path, name)
        try:
            st = os.stat(full)
        except OSError:
            continue
        records = 0
        torn = 0
        tiers: dict[str, int] = {}
        try:
            with open(full, "rb") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        torn += 1
                        continue
                    records += 1
                    tier = str(rec.get("tier", "?"))
                    tiers[tier] = tiers.get(tier, 0) + 1
        except OSError:
            pass
        entries.append(
            {
                "name": name,
                "bytes": st.st_size,
                "mtime": st.st_mtime,
                "records": records,
                "torn_lines": torn,
                "tiers": tiers,
            }
        )
        total += st.st_size
    return {
        "path": path,
        "segments": entries,
        "segment_count": len(entries),
        "total_bytes": total,
        "torn_lines": sum(e["torn_lines"] for e in entries),
    }


def collect_bundle(
    urls: list[str],
    out_path: str | None = None,
    config_file: str | None = None,
    journal_dir: str | None = None,
    shape_manifest: str | None = None,
    aot_dir: str | None = None,
    flight_dir: str | None = None,
    timeout: float = 10.0,
    now: float | None = None,
) -> dict:
    """Build the bundle; returns the manifest (its `bundle_path` is the
    written tar.gz)."""
    now = time.time() if now is None else now
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(now))
    bundle_name = f"janus-debug-{stamp}"
    out_path = out_path or f"{bundle_name}.tar.gz"

    files: list[tuple[str, bytes]] = []  # (path inside bundle, content)
    manifest: dict = {
        "created_unix": now,
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now)),
        "tool": "janus_tpu.tools.debug_bundle",
        "targets": {},
        "files": [],
    }

    def add_file(rel: str, content: bytes, source: str, status=None, error=None):
        entry = {
            "path": rel,
            "source": source,
            "bytes": len(content),
            "sha256": hashlib.sha256(content).hexdigest(),
        }
        if status is not None:
            entry["status"] = status
        if error is not None:
            entry["error"] = error
        manifest["files"].append(entry)
        files.append((rel, content))

    for url in urls:
        base = url.rstrip("/")
        target = _target_name(base)
        captured = {}
        replica_id = None
        peer_health = None
        for name, path in ENDPOINTS:
            source = base + path
            ext = (
                ".txt"
                if name in ("healthz", "metrics", "metrics_openmetrics", "debug_profile")
                else ".json"
            )
            rel = f"{bundle_name}/{target}/{name}{ext}"
            try:
                status, body = _fetch(source, timeout)
            except Exception as e:
                err = f"{type(e).__name__}: {e}"
                add_file(rel, err.encode(), source, error=err)
                captured[name] = {"error": err}
                continue
            add_file(rel, body, source, status=status)
            captured[name] = {"status": status, "bytes": len(body)}
            if name == "statusz" and status == 200:
                # fleet replica identity per capture (ISSUE 15): one
                # incident bundle covers the whole fleet, so every
                # target records WHICH replica it was
                try:
                    snap = json.loads(body)
                    replica_id = snap.get("fleet", {}).get("replica_id")
                    # peer-outage state per capture (ISSUE 19): a
                    # "helper down?" incident bundle answers at the top
                    # of the manifest, not three files deep
                    ph = snap.get("peer_health")
                    if isinstance(ph, dict):
                        peer_health = {
                            "parked": ph.get("parked"),
                            "parked_peers": sorted(
                                p
                                for p, ent in (ph.get("peers") or {}).items()
                                if (ent or {}).get("state") not in ("closed", None)
                            ),
                        }
                except Exception:
                    replica_id = None
        manifest["targets"][target] = {
            "url": base,
            "replica_id": replica_id,
            "endpoints": captured,
        }
        if peer_health is not None:
            manifest["targets"][target]["peer_health"] = peer_health

    if config_file:
        try:
            import yaml

            with open(config_file) as f:
                raw = yaml.safe_load(f) or {}
            redacted = yaml.safe_dump(redact_config(raw), sort_keys=False)
            add_file(
                f"{bundle_name}/resolved-config.yaml",
                redacted.encode(),
                f"config:{config_file}",
            )
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            add_file(
                f"{bundle_name}/resolved-config.yaml",
                err.encode(),
                f"config:{config_file}",
                error=err,
            )

    if journal_dir:
        state = journal_dir_state(journal_dir)
        add_file(
            f"{bundle_name}/upload-journal.json",
            json.dumps(state, indent=2).encode(),
            f"journal:{journal_dir}",
        )

    if shape_manifest:
        state = shape_manifest_state(shape_manifest, aot_dir=aot_dir)
        add_file(
            f"{bundle_name}/shape-manifest.json",
            json.dumps(state, indent=2, default=str).encode(),
            f"shape_manifest:{shape_manifest}",
        )

    if flight_dir:
        state = flight_dir_state(flight_dir)
        add_file(
            f"{bundle_name}/flight-ring.json",
            json.dumps(state, indent=2).encode(),
            f"flight:{flight_dir}",
        )

    manifest["bundle_path"] = os.path.abspath(out_path)
    manifest_bytes = json.dumps(manifest, indent=2, default=str).encode()

    with tarfile.open(out_path, "w:gz") as tar:

        def add(rel: str, content: bytes) -> None:
            info = tarfile.TarInfo(rel)
            info.size = len(content)
            info.mtime = int(now)
            tar.addfile(info, io.BytesIO(content))

        add(f"{bundle_name}/MANIFEST.json", manifest_bytes)
        for rel, content in files:
            add(rel, content)
    return manifest


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--url",
        action="append",
        required=True,
        help="health listener base URL (repeatable: leader + helper + drivers)",
    )
    ap.add_argument("--out", help="output tar.gz path (default: timestamped in cwd)")
    ap.add_argument(
        "--config-file",
        help="YAML config to include, secrets redacted (token/secret/password/key)",
    )
    ap.add_argument(
        "--journal-dir",
        help="upload-journal directory to inventory (names/sizes only)",
    )
    ap.add_argument(
        "--shape-manifest",
        help="shape manifest file to inventory (entry counts + AOT blob "
        "names/sizes, no contents)",
    )
    ap.add_argument(
        "--aot-dir",
        help="AOT executable-blob dir to inventory (default: the "
        "manifest's sibling aot/ — the standard layout under the "
        "compile cache dir)",
    )
    ap.add_argument(
        "--flight-dir",
        help="flight-recorder segment-ring dir to inventory (segment "
        "names/sizes + record/torn-line counts, read-only)",
    )
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    manifest = collect_bundle(
        args.url,
        out_path=args.out,
        config_file=args.config_file,
        journal_dir=args.journal_dir,
        shape_manifest=args.shape_manifest,
        aot_dir=args.aot_dir,
        flight_dir=args.flight_dir,
        timeout=args.timeout,
    )
    errors = [f for f in manifest["files"] if f.get("error")]
    print(f"debug_bundle: wrote {manifest['bundle_path']} "
          f"({len(manifest['files'])} files, {len(errors)} capture errors)")
    for f in errors:
        print(f"debug_bundle:   {f['source']}: {f['error']}", file=sys.stderr)
    # a bundle with SOME captures is still a success — incident tooling
    # must degrade, not abort; only a bundle with zero successful
    # captures exits non-zero
    ok = any("error" not in f for f in manifest["files"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
