"""Generate a DAP HPKE keypair.

Equivalent of reference tools/src/bin/hpke_keygen.rs: emits the
base64url HpkeConfig (shareable with clients/peers) and the base64url
private key (kept secret).
"""

from __future__ import annotations

import argparse
import base64
import sys

from ..core.hpke import generate_hpke_config_and_private_key


def b64(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="generate a DAP HPKE keypair")
    parser.add_argument("id", type=int, nargs="?", default=0, help="HPKE config id (0-255)")
    args = parser.parse_args(argv)
    if not 0 <= args.id < 256:
        raise SystemExit("config id must be in [0, 255]")
    kp = generate_hpke_config_and_private_key(config_id=args.id)
    print(f"hpke_config: {b64(kp.config.to_bytes())}")
    print(f"private_key: {b64(kp.private_key)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
