"""Prometheus text-exposition parser, validator, and naming lint.

One minimal parser shared by three consumers so they can never
disagree about what "scrape-valid" means:

  - tests/test_metrics_exposition.py (every registered metric must
    render parseable output),
  - bench.py's --dry-run observability smoke (the served scrape must
    be valid end-to-end over HTTP),
  - scripts/scrape_check.py (deploy smoke check against a live
    aggregator).

Covers the subset of the format janus_tpu.metrics emits: # HELP /
# TYPE comments, samples with escaped label values, histogram
_bucket/_sum/_count families, and — in OpenMetrics mode
(openmetrics=True, the `?openmetrics=1` exposition) — histogram-bucket
exemplars (`... # {trace_id="..."} value ts`) plus the `# EOF`
terminator. Not a general-purpose OpenMetrics parser.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)

# Counters predating the *_total convention (reference-mirroring names,
# aggregator.rs:114-245). New counters MUST end in _total; these are
# the explicit grandfather list the naming lint accepts.
GRANDFATHERED_COUNTERS = frozenset(
    {
        "janus_upload_decrypt_failures",
        "janus_upload_replayed_reports",
        "janus_upload_decode_failures",
        "janus_aggregate_step_failures",
        "janus_job_cancellations",
        "janus_engine_oom_retries",
        "janus_engine_host_fallbacks",
        "janus_http_requests",
    }
)

# Histograms whose unit is a COUNT, not a duration (their name carries
# the unit implicitly). Everything else ending up as a histogram must
# be a duration and end _seconds.
SIZE_HISTOGRAMS = frozenset(
    {
        "janus_hpke_batch_size",
    }
)


class ExpositionError(ValueError):
    pass


@dataclass
class Family:
    name: str
    type: str = "untyped"
    help: str = ""
    # [(sample_name, labels dict, value)]
    samples: list = field(default_factory=list)
    # OpenMetrics mode: [(sample_name, labels dict, exemplar dict)]
    # where exemplar = {"labels": {...}, "value": float, "ts": float|None}
    exemplars: list = field(default_factory=list)


def _parse_labels(raw: str, errors: list[str], where: str) -> dict:
    """Parse `k="v",k2="v2"` honoring the exposition escapes
    (\\\\, \\", \\n) inside label values."""
    labels: dict[str, str] = {}
    i, n = 0, len(raw)
    while i < n:
        m = re.match(r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"', raw[i:])
        if not m:
            errors.append(f"{where}: malformed label segment at {raw[i:]!r}")
            return labels
        key = m.group(1)
        i += m.end()
        out = []
        closed = False
        while i < n:
            c = raw[i]
            if c == "\\":
                if i + 1 >= n:
                    errors.append(f"{where}: dangling backslash in label value")
                    return labels
                esc = raw[i + 1]
                out.append({"n": "\n", "\\": "\\", '"': '"'}.get(esc, "\\" + esc))
                i += 2
            elif c == '"':
                closed = True
                i += 1
                break
            elif c == "\n":
                # a REAL newline inside a label value is the corruption
                # the escaping exists to prevent
                errors.append(f"{where}: unescaped newline in label value")
                return labels
            else:
                out.append(c)
                i += 1
        if not closed:
            errors.append(f"{where}: unterminated label value for {key}")
            return labels
        labels[key] = "".join(out)
        i += re.match(r"\s*,?", raw[i:]).end()
    return labels


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    return float(raw)


def _split_unquoted_hash(line: str) -> tuple[str, str | None]:
    """Split a sample line at the first '#' that sits OUTSIDE a quoted
    label value (the OpenMetrics exemplar marker). Returns
    (base, exemplar_clause or None); a '#' inside a label value —
    hostile task ids are legal — never splits."""
    in_q = False
    i, n = 0, len(line)
    while i < n:
        c = line[i]
        if c == "\\" and in_q:
            i += 2
            continue
        if c == '"':
            in_q = not in_q
        elif c == "#" and not in_q:
            return line[:i].rstrip(), line[i + 1 :].strip()
        i += 1
    return line, None


# OpenMetrics spec: the combined rune length of an exemplar's label
# names and values must not exceed 128
_EXEMPLAR_MAX_RUNES = 128


def _parse_exemplar(clause: str, errors: list[str], where: str) -> dict | None:
    """Parse `{labels} value [ts]` (the clause after the unquoted '#').
    Appends errors and returns None when malformed."""
    if not clause.startswith("{"):
        errors.append(f"{where}: malformed exemplar clause {clause!r}")
        return None
    # find the matching close brace outside quoted values
    in_q = False
    end = -1
    i = 1
    while i < len(clause):
        c = clause[i]
        if c == "\\" and in_q:
            i += 2
            continue
        if c == '"':
            in_q = not in_q
        elif c == "}" and not in_q:
            end = i
            break
        i += 1
    if end < 0:
        errors.append(f"{where}: unterminated exemplar label set")
        return None
    label_errors: list[str] = []
    labels = (
        _parse_labels(clause[1:end], label_errors, where) if end > 1 else {}
    )
    if label_errors:
        errors.extend(label_errors)
        return None
    runes = sum(len(k) + len(v) for k, v in labels.items())
    if runes > _EXEMPLAR_MAX_RUNES:
        errors.append(
            f"{where}: exemplar label set exceeds {_EXEMPLAR_MAX_RUNES} runes"
        )
        return None
    rest = clause[end + 1 :].split()
    if not rest or len(rest) > 2:
        errors.append(f"{where}: exemplar needs `value [timestamp]`, got {clause!r}")
        return None
    try:
        value = _parse_value(rest[0])
    except ValueError:
        errors.append(f"{where}: unparseable exemplar value {rest[0]!r}")
        return None
    ts = None
    if len(rest) == 2:
        try:
            ts = float(rest[1])
        except ValueError:
            errors.append(f"{where}: unparseable exemplar timestamp {rest[1]!r}")
            return None
    return {"labels": labels, "value": value, "ts": ts}


def parse_exposition(
    text: str, openmetrics: bool = False
) -> tuple[dict[str, Family], list[str]]:
    """-> ({family name: Family}, [error strings]). Sample names like
    foo_bucket/_sum/_count attach to their histogram family `foo`.
    With openmetrics=True, histogram-bucket/counter exemplars are
    parsed into Family.exemplars (malformed ones are errors) and a
    `# EOF` terminator line is accepted; in the default mode any
    exemplar clause is a parse error — the stock scrape must stay
    bit-compatible with the 0.0.4 text format."""
    families: dict[str, Family] = {}
    errors: list[str] = []
    saw_eof = False

    def family_for(sample_name: str) -> Family | None:
        if sample_name in families:
            return families[sample_name]
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix):
                base = sample_name[: -len(suffix)]
                fam = families.get(base)
                if fam is not None and fam.type == "histogram":
                    return fam
        return None

    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.rstrip("\r")
        if not line.strip():
            continue
        where = f"line {lineno}"
        if saw_eof:
            errors.append(f"{where}: content after # EOF")
            break
        if openmetrics and line.strip() == "# EOF":
            saw_eof = True
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP ") :].split(" ", 1)
            name = parts[0]
            if not _NAME_RE.match(name):
                errors.append(f"{where}: bad metric name {name!r}")
                continue
            families.setdefault(name, Family(name)).help = (
                parts[1] if len(parts) > 1 else ""
            )
        elif line.startswith("# TYPE "):
            parts = line[len("# TYPE ") :].split(" ", 1)
            if len(parts) != 2 or parts[1] not in (
                "counter",
                "gauge",
                "histogram",
                "summary",
                "untyped",
            ):
                errors.append(f"{where}: bad TYPE line {line!r}")
                continue
            families.setdefault(parts[0], Family(parts[0])).type = parts[1]
        elif line.startswith("#"):
            continue  # other comments are legal
        else:
            exemplar = None
            if openmetrics:
                base, clause = _split_unquoted_hash(line)
                if clause is not None:
                    exemplar = _parse_exemplar(clause, errors, where)
                    if exemplar is None:
                        continue
                    line = base
            m = _SAMPLE_RE.match(line)
            if not m:
                errors.append(f"{where}: unparseable sample {line!r}")
                continue
            name = m.group("name")
            labels = (
                _parse_labels(m.group("labels"), errors, where)
                if m.group("labels") is not None
                else {}
            )
            try:
                value = _parse_value(m.group("value"))
            except ValueError:
                errors.append(f"{where}: unparseable value {m.group('value')!r}")
                continue
            fam = family_for(name)
            if fam is None:
                errors.append(f"{where}: sample {name!r} has no # TYPE family")
                continue
            if exemplar is not None:
                # OpenMetrics allows exemplars on histogram buckets and
                # counters only — and a bucket exemplar must sit within
                # its bucket's bound
                if name.endswith("_bucket") and fam.type == "histogram":
                    le = labels.get("le")
                    try:
                        bound = _parse_value(le) if le is not None else math.inf
                    except ValueError:
                        bound = math.inf
                    if exemplar["value"] > bound:
                        errors.append(
                            f"{where}: exemplar value {exemplar['value']:g} above "
                            f"bucket bound le={le}"
                        )
                        continue
                elif fam.type != "counter":
                    errors.append(
                        f"{where}: exemplar on a {fam.type} sample {name!r} "
                        "(only histogram buckets and counters may carry one)"
                    )
                    continue
                fam.exemplars.append((name, labels, exemplar))
            fam.samples.append((name, labels, value))
    if openmetrics and not saw_eof:
        errors.append("missing # EOF terminator (OpenMetrics mode)")
    return families, errors


def _histogram_errors(fam: Family) -> list[str]:
    """Bucket monotonicity + _sum/_count consistency per label set."""
    errors: list[str] = []
    by_key: dict[tuple, dict] = {}
    for name, labels, value in fam.samples:
        key_labels = {k: v for k, v in labels.items() if k != "le"}
        key = tuple(sorted(key_labels.items()))
        ent = by_key.setdefault(key, {"buckets": [], "sum": None, "count": None})
        if name.endswith("_bucket"):
            if "le" not in labels:
                errors.append(f"{fam.name}: _bucket sample without le label")
                continue
            ent["buckets"].append((_parse_value(labels["le"]), value))
        elif name.endswith("_sum"):
            ent["sum"] = value
        elif name.endswith("_count"):
            ent["count"] = value
    for key, ent in by_key.items():
        lbl = dict(key)
        buckets = sorted(ent["buckets"])
        if not buckets:
            errors.append(f"{fam.name}{lbl}: histogram label set without buckets")
            continue
        if buckets[-1][0] != math.inf:
            errors.append(f"{fam.name}{lbl}: missing +Inf bucket")
        prev = -math.inf
        for le, v in buckets:
            if v < prev:
                errors.append(f"{fam.name}{lbl}: bucket counts not monotone at le={le}")
            prev = v
        if ent["count"] is None or ent["sum"] is None:
            errors.append(f"{fam.name}{lbl}: missing _sum/_count")
            continue
        if buckets[-1][0] == math.inf and buckets[-1][1] != ent["count"]:
            errors.append(
                f"{fam.name}{lbl}: +Inf bucket {buckets[-1][1]} != _count {ent['count']}"
            )
        if ent["count"] == 0 and ent["sum"] not in (0, 0.0):
            errors.append(f"{fam.name}{lbl}: zero count with nonzero sum")
    return errors


def validate_exposition(text: str, openmetrics: bool = False) -> list[str]:
    """Full scrape validation: parse errors + per-family semantic checks.
    Empty list = scrape-valid. openmetrics=True validates the exemplar
    exposition mode (exemplar syntax + # EOF terminator)."""
    families, errors = parse_exposition(text, openmetrics=openmetrics)
    for fam in families.values():
        if fam.type == "histogram":
            errors.extend(_histogram_errors(fam))
        elif fam.type == "counter":
            for _, _, value in fam.samples:
                if value < 0:
                    errors.append(f"{fam.name}: negative counter value {value}")
    return errors


def lint_metric_names(
    names_by_type: dict[str, str], grandfathered: frozenset = GRANDFATHERED_COUNTERS
) -> list[str]:
    """Naming-convention lint over {family name: type}: every metric is
    janus_-prefixed; counters end _total unless explicitly
    grandfathered; duration histograms end _seconds."""
    errors = []
    for name, typ in sorted(names_by_type.items()):
        if not name.startswith("janus_"):
            errors.append(f"{name}: metric names must start with janus_")
        if typ == "counter" and not name.endswith("_total") and name not in grandfathered:
            errors.append(f"{name}: counters must end _total (or be grandfathered)")
        if (
            typ == "histogram"
            and not name.endswith("_seconds")
            and name not in SIZE_HISTOGRAMS
        ):
            errors.append(f"{name}: duration histograms must end _seconds")
    return errors


def registry_names_by_type(registry) -> dict[str, str]:
    """{name: type} for a janus_tpu.metrics.MetricsRegistry (the lint's
    input when checking the live registry rather than a scrape)."""
    from . import metrics as m

    out = {}
    for metric in registry.metrics_list():
        if isinstance(metric, m.Counter):
            out[metric.name] = "counter"
        elif isinstance(metric, m.Gauge):
            out[metric.name] = "gauge"
        elif isinstance(metric, m.Histogram):
            out[metric.name] = "histogram"
    return out
