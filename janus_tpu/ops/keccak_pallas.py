"""Keccak-f[1600] as a Pallas TPU kernel: 24 rounds in VMEM, u32-native.

The XLA path (janus_tpu.vdaf.keccak_jax.keccak_f1600) runs the rounds
as a lax.scan: every round reads and writes the whole 25-lane state
from HBM — ~24 x 2 x state-size of traffic — and each u64 bit-op
lowers to a u32 pair anyway. This kernel keeps the state of a row tile
resident in VMEM for all 24 rounds and works on the u32 halves
directly: one HBM read + one write per element total. Profiled on the
SumVec two-party step the scan-based permutations were ~50% of device
time.

Layout: callers hold the state as 25 u64 arrays of identical shape S
(one array per Keccak lane, batch shape S). Here that becomes one
[50, R, 128] u32 array — row 2k = lane k's low half, row 2k+1 = high
half, with prod(S) flattened and zero-padded to R*128 columns — tiled
over a grid on R. Zero columns permute to garbage and are sliced away.

Enabled on single-device TPU processes by default (JANUS_PALLAS=0
disables; =1 forces interpret mode on CPU for differential tests;
multi-device TPU is always off — see _mode — and JANUS_PALLAS=1 does
NOT override that); everything else falls back to the scan path. The flag and backend are
read once at the first XOF call and cached (jitted graphs embed the
dispatch decision, so mid-process toggles could not take effect
anyway); tests that need a different mode patch `_mode` directly.
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

# Round constants / rotation offsets shared with the scan path — one
# authoritative copy (keccak_jax imports this module only lazily inside
# keccak_f1600, so there is no import cycle).
from ..vdaf.keccak_jax import _RC as _RC_U64, _ROT

_RC = [int(x) for x in _RC_U64]

_TILE_ROWS = 8  # u32 min tile is (8, 128)


def _xor2(a, b):
    return (a[0] ^ b[0], a[1] ^ b[1])


def _rot64(a, r: int):
    """Rotate-left a u64 held as (lo32, hi32) by r."""
    lo, hi = a
    r %= 64
    if r == 0:
        return a
    if r >= 32:
        lo, hi = hi, lo
        r -= 32
        if r == 0:
            return (lo, hi)
    s = np.uint32(r)
    t = np.uint32(32 - r)
    return ((lo << s) | (hi >> t), (hi << s) | (lo >> t))


def permute_pairs(a, rounds: int = 24):
    """Keccak-f[1600] rounds on a 25-list of (lo32, hi32) pairs.

    Shared between the plain-permutation kernel below and the fused
    expansion kernel (janus_tpu.ops.expand_pallas). `rounds < 24` is a
    test-only reduction (same round function, first `rounds` round
    constants) so the full kernel framing runs in interpret mode in
    default CI without the 24-round unrolled-body compile cost; both
    sides of every differential use the same count."""
    for rnd in range(rounds):
        # theta
        c = [
            _xor2(_xor2(_xor2(a[i], a[i + 5]), _xor2(a[i + 10], a[i + 15])), a[i + 20])
            for i in range(5)
        ]
        d = [_xor2(c[(i - 1) % 5], _rot64(c[(i + 1) % 5], 1)) for i in range(5)]
        a = [_xor2(a[i], d[i % 5]) for i in range(25)]
        # rho + pi
        b = [None] * 25
        for xx in range(5):
            for yy in range(5):
                b[yy + 5 * ((2 * xx + 3 * yy) % 5)] = _rot64(a[xx + 5 * yy], _ROT[xx][yy])
        # chi
        a = [
            _xor2(
                b[xx + 5 * yy],
                (
                    (~b[(xx + 1) % 5 + 5 * yy][0]) & b[(xx + 2) % 5 + 5 * yy][0],
                    (~b[(xx + 1) % 5 + 5 * yy][1]) & b[(xx + 2) % 5 + 5 * yy][1],
                ),
            )
            for yy in range(5)
            for xx in range(5)
        ]
        # iota
        rc = _RC[rnd]
        a[0] = (
            a[0][0] ^ np.uint32(rc & 0xFFFFFFFF),
            a[0][1] ^ np.uint32(rc >> 32),
        )
    return a


def _kernel_for(rounds: int):
    def _kernel(x_ref, o_ref):
        x = x_ref[:]  # [50, TR, 128] u32
        a = permute_pairs([(x[2 * i], x[2 * i + 1]) for i in range(25)], rounds)
        o_ref[:] = jnp.stack([h for pair in a for h in pair], axis=0)

    return _kernel


@lru_cache(maxsize=1)
def _mode() -> str:
    """'tpu' (real kernel), 'interpret' (forced on non-TPU), or 'off'.

    Multi-device TPU processes run with kernels off: engine_cache binds
    jitted steps to a dp mesh there, and pallas_call has no SPMD
    partitioning rule — sharding it needs shard_map plumbing around
    every call site (future work; single-chip is where the benchmarks
    run today)."""
    flag = os.environ.get("JANUS_PALLAS")
    if flag == "0":
        return "off"
    if jax.default_backend() == "tpu":
        return "tpu" if len(jax.devices()) == 1 else "off"
    return "interpret" if flag == "1" else "off"


# Below this many state columns the relayout into [50, R, 128] u32
# costs more than the kernel saves (measured: Count at batch 8192 ran
# ~10% slower through the kernel; SumVec's 1.2M-column states gain 41%).
MIN_COLUMNS = 32768


def enabled(n_columns: int | None = None) -> bool:
    if _mode() == "off":
        return False
    return n_columns is None or n_columns >= MIN_COLUMNS


@lru_cache(maxsize=None)
def _call(rows: int, interpret: bool, rounds: int = 24):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (rows // _TILE_ROWS,)
    # all three block indices derived from the grid index so the index
    # map is monomorphic i32 (literal 0s lower to i64 constants, which
    # this Mosaic build refuses to mix in func.return)
    spec = pl.BlockSpec(
        (50, _TILE_ROWS, 128), lambda i: (i * 0, i, i * 0), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        _kernel_for(rounds),
        out_shape=jax.ShapeDtypeStruct((50, rows, 128), jnp.uint32),
        grid=grid,
        in_specs=[spec],
        out_specs=spec,
        interpret=interpret,
    )


def keccak_f1600_pallas(state, rounds: int = 24):
    """Permute 25 u64 arrays of identical shape; returns the same tuple
    structure. Caller guarantees enabled() is True."""
    shape = state[0].shape
    n = int(np.prod(shape)) if shape else 1
    cols = -(-n // (_TILE_ROWS * 128)) * (_TILE_ROWS * 128)
    rows = cols // 128
    flat = [jnp.ravel(x) for x in state]
    halves = []
    for x in flat:
        halves.append(x.astype(jnp.uint32))          # low 32 bits
        halves.append((x >> np.uint64(32)).astype(jnp.uint32))
    stacked = jnp.stack(halves, axis=0)  # [50, n]
    if cols != n:
        stacked = jnp.pad(stacked, ((0, 0), (0, cols - n)))
    out = _call(rows, _mode() != "tpu", rounds)(stacked.reshape(50, rows, 128))
    out = out.reshape(50, cols)[:, :n]
    res = []
    for i in range(25):
        lo = out[2 * i].astype(jnp.uint64)
        hi = out[2 * i + 1].astype(jnp.uint64)
        res.append((lo | (hi << np.uint64(32))).reshape(shape))
    return tuple(res)


# ---------------------------------------------------------------------------
# Single-block variant: rate lanes in, first `out_lanes` lanes out.
# ---------------------------------------------------------------------------


def _kernel_single(out_lanes: int, rounds: int):
    def _kernel(x_ref, o_ref):
        x = x_ref[:]  # [42, TR, 128] u32 — 21 rate lanes as lo/hi pairs
        zeros = jnp.zeros_like(x[0])
        a = [(x[2 * i], x[2 * i + 1]) for i in range(21)] + [(zeros, zeros)] * 4
        a = permute_pairs(a, rounds)
        o_ref[:] = jnp.stack(
            [h for i in range(out_lanes) for h in a[i]], axis=0
        )

    return _kernel


@lru_cache(maxsize=None)
def _call_single(rows: int, interpret: bool, out_lanes: int, rounds: int = 24):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (rows // _TILE_ROWS,)
    in_spec = pl.BlockSpec(
        (42, _TILE_ROWS, 128), lambda i: (i * 0, i, i * 0), memory_space=pltpu.VMEM
    )
    out_spec = pl.BlockSpec(
        (2 * out_lanes, _TILE_ROWS, 128),
        lambda i: (i * 0, i, i * 0),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        _kernel_single(out_lanes, rounds),
        out_shape=jax.ShapeDtypeStruct((2 * out_lanes, rows, 128), jnp.uint32),
        grid=grid,
        in_specs=[in_spec],
        out_specs=out_spec,
        interpret=interpret,
    )


def keccak_single_block_pallas(lane_cols, out_lanes: int, rounds: int = 24):
    """Permute single-block messages given as 21 rate-lane u64 arrays of
    identical shape; return the first `out_lanes` output lanes (same
    tuple-of-arrays structure). vs keccak_f1600_pallas this moves 42
    u32 rows in and 2*out_lanes out instead of 50/50 — the tree-digest
    levels (out_lanes=2) were paying ~3x their necessary HBM traffic
    through the general kernel, the dominant cost of the leader
    joint-rand binder at SumVec len=100k (profiled r5)."""
    shape = lane_cols[0].shape
    n = int(np.prod(shape)) if shape else 1
    cols_pad = -(-n // (_TILE_ROWS * 128)) * (_TILE_ROWS * 128)
    rows = cols_pad // 128
    halves = []
    for x in lane_cols:
        flat = jnp.ravel(x)
        halves.append(flat.astype(jnp.uint32))
        halves.append((flat >> np.uint64(32)).astype(jnp.uint32))
    stacked = jnp.stack(halves, axis=0)  # [42, n]
    if cols_pad != n:
        stacked = jnp.pad(stacked, ((0, 0), (0, cols_pad - n)))
    out = _call_single(rows, _mode() != "tpu", out_lanes, rounds)(
        stacked.reshape(42, rows, 128)
    )
    out = out.reshape(2 * out_lanes, cols_pad)[:, :n]
    res = []
    for i in range(out_lanes):
        lo = out[2 * i].astype(jnp.uint64)
        hi = out[2 * i + 1].astype(jnp.uint64)
        res.append((lo | (hi << np.uint64(32))).reshape(shape))
    return tuple(res)
