"""Exact field contractions on the MXU via 7-bit limb decomposition.

The FLP query's hot loop is a contraction over gadget calls:
wire_t[j] = sum_call w[call] * X[call, j] in Field64/Field128 — per
report a [W x calls] @ [calls x chunk] product. The reference computes
the equivalent per report on CPU inside `prio`
(aggregator/src/aggregator/aggregation_job_driver.rs:329-402); round-4
ran it on the VPU as u64-emulated limb multiplies, which the roofline
pinned at ~14% of envelope (BASELINE.md) — the admitted instruction-mix
headroom. This module moves those multiplies to the MXU, the unit with
~40x the integer throughput, by decomposing field elements into 7-bit
limbs and contracting with int8 x int8 -> int32 `dot_general`s:

  a = sum_l1 A_l1 2^(7 l1),  b = sum_l2 B_l2 2^(7 l2)   (A,B < 2^7)
  sum_call a b = sum_{l1,l2} 2^(7(l1+l2)) sum_call A_l1 B_l2
                              ^^^^^^^^^^^ one i32 matmul per (l1,l2)

Every step is exact: products < 2^14, i32 column sums safe for
calls <= 2^17, the diagonal-group recombination runs in u64 with full
carries, and the final value reduces mod p by the same sparse-moduli
folds as janus_tpu.fields.jfield. The result is the bit-identical
field element the sequential path produces (fuzzed in
tests/test_limbmm.py; the engine differential tests pin the query).

Field64 uses 10 limbs (70 bits), Field128 uses 19 (133 bits); the
(l1, l2) grid rides as extra rows/columns of one batched matmul:
[batch, W*19, calls] @ [batch, calls, 19*C].

`JANUS_LIMBMM_DTYPE=f32` switches the matmul operand dtype for
backends without an int8 MXU path; f32 accumulation is exact while
products * calls < 2^24, so the contraction is segmented at 1024
calls (int8/i32 allows 2^17 before segmenting).
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..fields.jfield import (
    _f64_reduce_wide,
    _f128_fold,
    _f128_reduce256,
    add_limbs,
)

_NLIMB = {1: 10, 2: 19}  # 7-bit limbs per element, by u64 limb count
_MASK7 = np.uint64(0x7F)

# int8 path: column sums bounded by calls * 127^2 < 2^31 -> 2^17 calls.
# f32 path: exact while bounded by 2^24 -> 1024 calls.
_SEG = {"int8": 1 << 17, "f32": 1 << 10}


def _dtype() -> str:
    d = os.environ.get("JANUS_LIMBMM_DTYPE", "int8")
    assert d in ("int8", "f32"), d
    return d


def decompose7(jf, v):
    """Field value (limb tuple, any shape S) -> u8-in-int8 array
    [*S, nlimbs] of 7-bit limbs, little-endian."""
    nl = _NLIMB[jf.LIMBS]
    dt = jnp.int8 if _dtype() == "int8" else jnp.float32
    pieces = []
    for j in range(nl):
        bit = 7 * j
        w, off = divmod(bit, 64)
        if w >= jf.LIMBS:
            pieces.append(jnp.zeros_like(v[0], dtype=dt))
            continue
        piece = v[w] >> np.uint64(off)
        if off > 57 and w + 1 < jf.LIMBS:
            piece = piece | (v[w + 1] << np.uint64(64 - off))
        pieces.append((piece & _MASK7).astype(dt))
    return jnp.stack(pieces, axis=-1)


def _reduce_limbs(jf, limbs):
    """u64 limb list (value < 2^292 for F128 / 2^166 for F64) -> field."""
    if jf.LIMBS == 1:
        l0, l1, l2 = limbs
        m = _f64_reduce_wide(l1, l2)
        return (_f64_reduce_wide(l0, m),)
    # F128: 5 limbs < 2^292. One fold (H = limbs[2:5] < 2^164) lands
    # under 7H*2^66 + L < 2^234 < 2^256, then the 256-bit reduction.
    r = _f128_fold(list(limbs), 3)[:4]
    return _f128_reduce256(*r)


def fold_contract(jf, w, X):
    """Exact field contraction: out[b, i, c] = sum_p w[b, i, p] * X[b, p, c].

    w: field value [batch, W, calls] (weight rows; W small).
    X: field value [batch, calls, C].
    Returns a reduced field value [batch, W, C], bit-identical to
    fsum(jf, jf.mul(w[..., None], X[:, None]), axis=2).
    """
    nl = _NLIMB[jf.LIMBS]
    dt = _dtype()
    b, W, calls = w[0].shape
    _, _, C = X[0].shape
    dl = decompose7(jf, w)  # [b, W, calls, nl]
    dr = decompose7(jf, X)  # [b, calls, C, nl]
    dl = jnp.transpose(dl, (0, 1, 3, 2)).reshape(b, W * nl, calls)
    dr = jnp.transpose(dr, (0, 1, 3, 2)).reshape(b, calls, nl * C)

    seg = _SEG[dt]
    acc = None  # u64 [b, W, nl, nl, C]
    for s0 in range(0, calls, seg):
        s1 = min(calls, s0 + seg)
        out = lax.dot_general(
            dl[:, :, s0:s1],
            dr[:, s0:s1, :],
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32 if dt == "int8" else jnp.float32,
        )
        part = (
            out.astype(jnp.uint64)
            if dt == "int8"
            # f32 accumulation is exact under the segment bound; values
            # are non-negative integers < 2^24
            else out.astype(jnp.int32).astype(jnp.uint64)
        ).reshape(b, W, nl, nl, C)
        acc = part if acc is None else acc + part  # < calls*127^2*segs: no wrap

    # diagonal groups: value = sum_s 2^(7s) colsum[s], s = l1 + l2 —
    # one scatter-add (an nl^2 python loop traced ~361 adds; trace time
    # is first-job latency, binary_utils warmup docstring)
    n_s = 2 * nl - 1
    s_idx = jnp.asarray(
        np.add.outer(np.arange(nl), np.arange(nl)).reshape(-1), dtype=jnp.int32
    )
    grouped = (
        jnp.zeros((b, W, n_s, C), dtype=jnp.uint64)
        .at[:, :, s_idx, :]
        .add(acc.reshape(b, W, nl * nl, C))
    )
    colsum = [grouped[:, :, s, :] for s in range(n_s)]

    # assemble u64 limbs with carries: each colsum (< 2^40: <= nl
    # segment-partials of < 2^31/2^24 each) contributes at bit offset
    # 7s, straddling at most two limbs
    n_limbs = 5 if jf.LIMBS == 2 else 3
    limbs = [jnp.zeros_like(colsum[0]) for _ in range(n_limbs)]
    for s in range(n_s):
        wd, off = divmod(7 * s, 64)
        lo = colsum[s] << np.uint64(off)
        add = [jnp.zeros_like(lo) for _ in range(n_limbs)]
        add[wd] = lo
        if off > 24 and wd + 1 < n_limbs:  # 2^40 << off crosses the limb
            add[wd + 1] = colsum[s] >> np.uint64(64 - off)
        limbs, _ = add_limbs(limbs, add)
        # total value < 2^292 (F128) / 2^166 (F64): top limb never wraps
    return _reduce_limbs(jf, limbs)
