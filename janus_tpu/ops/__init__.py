"""Device-side numerical kernels (NTT, polynomial ops) for the VDAF engine."""

from .ntt import intt_batched, ntt_batched, poly_eval_powers, powers

__all__ = ["ntt_batched", "intt_batched", "poly_eval_powers", "powers"]
