"""Batched radix-2 NTT over limb-tuple field values.

The FLP proof system evaluates/interpolates wire and gadget polynomials
on power-of-two root-of-unity domains (reference: the external `prio`
crate's FFT, consumed per-report from
aggregator/src/aggregator/aggregation_job_driver.rs:363; SURVEY.md
section 2.2). Here a transform processes an arbitrary leading batch
shape at once: values are tuples of u64 limb arrays shaped
``[..., n]`` and every butterfly is an elementwise field op over the
whole batch, so XLA tiles it onto the VPU lanes with no per-report
loop.

Structure per stage (classic iterative Cooley-Tukey, decimation in
time, after a static bit-reversal gather):

    a.reshape(..., n // L, L) ->  u = a[..., :L/2],  v = a[..., L/2:]
    a' = concat(u + w*v, u - w*v)

with the twiddle vector ``w`` a host-precomputed constant, broadcast
across the batch. log2(n) stages total; all shapes static, everything
fuses under jit.

Twiddle/permutation plans are cached per (field, n, direction). The
domain order matches the host oracle in janus_tpu.vdaf.reference (ntt /
intt on Python ints), which the differential tests compare against.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.jfield import anti_recompute_barrier, fconst, fmap


def _bitrev_perm(n: int) -> np.ndarray:
    bits = (n - 1).bit_length()
    perm = np.zeros(n, dtype=np.int32)
    for i in range(n):
        r = 0
        for b in range(bits):
            r |= ((i >> b) & 1) << (bits - 1 - b)
        perm[i] = r
    return perm


def _int_to_limbs(value: int, limbs: int) -> tuple:
    return tuple(
        np.uint64((value >> (64 * i)) & 0xFFFFFFFFFFFFFFFF) for i in range(limbs)
    )


@lru_cache(maxsize=None)
def _plan(jf, n: int, inverse: bool):
    """Host-precomputed (perm, per-stage twiddles, n_inv) for one size."""
    F = jf.HOST
    root = F.root_of_unity(n)
    if inverse:
        root = F.inv(root)
    perm = _bitrev_perm(n)
    stages = []
    length = 2
    while length <= n:
        w_len = F.pow(root, n // length)
        tw = np.empty((jf.LIMBS, length // 2), dtype=np.uint64)
        w = 1
        for k in range(length // 2):
            for li, limb in enumerate(_int_to_limbs(w, jf.LIMBS)):
                tw[li, k] = limb
            w = F.mul(w, w_len)
        stages.append(tuple(tw[li] for li in range(jf.LIMBS)))
        length <<= 1
    n_inv = F.inv(n) if inverse else None
    return perm, stages, n_inv


def _transform(jf, v, n: int, inverse: bool):
    perm, stages, n_inv = _plan(jf, n, inverse)
    batch_shape = v[0].shape[:-1]
    a = fmap(lambda x: jnp.take(x, jnp.asarray(perm), axis=-1), v)
    length = 2
    for tw in stages:
        half = length // 2
        a = fmap(lambda x: x.reshape(batch_shape + (n // length, length)), a)
        u = fmap(lambda x: x[..., :half], a)
        w = tuple(jnp.asarray(t) for t in tw)  # [half], broadcasts over batch
        wv = jf.mul(fmap(lambda x: x[..., half:], a), w)
        a = fmap(
            lambda p, q: jnp.concatenate([p, q], axis=-1), jf.add(u, wv), jf.sub(u, wv)
        )
        a = fmap(lambda x: x.reshape(batch_shape + (n,)), a)
        # Materialize each butterfly stage. Without the barrier XLA's
        # fusion duplicates the producer chain into every consumer of the
        # concat, recomputing earlier stages exponentially (measured 2.5x
        # end-to-end on the SumVec query graph); each stage's output is
        # reused by both halves of the next stage, so it must be CSE'd,
        # not inlined.
        a = anti_recompute_barrier(a)
        length <<= 1
    if inverse:
        a = jf.mul(a, fconst(jf, n_inv))
    return a


def ntt_batched(jf, coeffs, n: int):
    """Evaluate polynomials at the n-th roots w^0..w^{n-1}.

    coeffs: field value [..., k] with k <= n; zero-padded to n.
    """
    k = coeffs[0].shape[-1]
    assert k <= n and n & (n - 1) == 0
    if k < n:
        pad = [(0, 0)] * (coeffs[0].ndim - 1) + [(0, n - k)]
        coeffs = fmap(lambda x: jnp.pad(x, pad), coeffs)
    return _transform(jf, coeffs, n, inverse=False)


def intt_batched(jf, evals):
    """Inverse: values at w^0..w^{n-1} -> coefficients. Last axis = n."""
    n = evals[0].shape[-1]
    assert n & (n - 1) == 0
    return _transform(jf, evals, n, inverse=True)


def lagrange_eval_weights(jf, t_powers, m: int):
    """L_k(t) for the m-point root-of-unity domain {α^0..α^{m-1}}:
    the weights such that a polynomial interpolated from domain values
    v_k evaluates at t as Σ_k v_k·L_k(t).

    Closed form: L_k(t) = α^k·(t^m−1)/(m·(t−α^k)) — and since
    (t^m−1)/(t−α^k) = Σ_i α^{k(m-1-i)} t^i, this collapses to
    L_k(t) = (1/m)·Σ_i α^{-ki}·t^i, i.e. **the inverse NTT of t's
    power vector** [t^0..t^{m-1}]. One batched log-depth transform, no
    per-element field inversions (an explicit 1/(t−α^k) formulation
    compiled pathologically on XLA CPU). Identical field elements to
    the host oracle's intt-then-Horner (differential-tested).

    t_powers: field value [..., >=m] of powers of t. Returns [..., m].
    """
    pw_m = fmap(lambda x: x[..., :m], t_powers)
    return intt_batched(jf, pw_m)


def powers(jf, x, n: int):
    """[x^0, x^1, ..., x^{n-1}] along a new trailing axis, log-depth.

    x: field value of shape [...]; returns [..., n].
    """
    assert n >= 1
    one = fconst(jf, 1, x[0].shape)
    acc = fmap(lambda a, b: jnp.stack([a, b], axis=-1), one, x)  # [..., 2]
    cur = 2
    while cur < n:
        # acc holds x^0..x^{cur-1}; extend with acc * x^cur
        last = fmap(lambda a: a[..., -1], acc)
        xc = jf.mul(last, x)  # x^cur
        ext = jf.mul(acc, fmap(lambda a: a[..., None], xc))
        acc = fmap(lambda a, b: jnp.concatenate([a, b], axis=-1), acc, ext)
        # same anti-recomputation barrier as the NTT stages: each
        # doubling feeds the next, and XLA otherwise inlines the chain
        # into every consumer
        acc = anti_recompute_barrier(acc)
        cur *= 2
    if cur != n:
        acc = fmap(lambda a: a[..., :n], acc)
    return acc


def poly_eval_powers(jf, coeffs, pw):
    """Evaluate polynomials given precomputed point powers.

    coeffs: [..., k]; pw: [..., m] powers of the evaluation point with
    m >= k. Returns [...]: sum_i coeffs[i] * x^i.
    """
    from ..fields.jfield import fsum

    k = coeffs[0].shape[-1]
    pwk = fmap(lambda a: a[..., :k], pw)
    return fsum(jf, jf.mul(coeffs, pwk), axis=-1)
