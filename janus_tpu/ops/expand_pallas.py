"""Fused XOF-expansion Pallas kernel: Keccak + mod-p sampling in VMEM.

The unfused device path (janus_tpu.vdaf.keccak_jax.expand_field_vec)
materializes the counter-mode SHAKE128 stream in HBM — 168 bytes per
block in, 168+ out of the permutation kernel, re-read by the sampler —
~24 raw stream bytes per Field128 element that exist only to be reduced
mod p and thrown away. At the north-star SumVec len=100k that stream is
38.4 MB per report and is what capped the single-chip batch at 8
(BASELINE.md "Roofline": the limiter is HBM *capacity*).

This kernel fuses the whole expansion: each grid cell covers 8 reports
x 128 counter blocks; the single-block counter-mode Keccak state is
built in VMEM from a per-report prefix row (dst||seed||binder', <=160
bytes, broadcast along lanes) plus a lane-index counter, permuted for
all 24 rounds (janus_tpu.ops.keccak_pallas.permute_pairs), and each
168-byte rate block is reduced to 7 Field128 elements in-kernel. Only
the 112 bytes/block of element words ever reach HBM; the raw stream
never exists.

The mod-p reduction mirrors janus_tpu.fields.jfield._f128_reduce256 on
32-bit words (TPU VPU native): p = 2^128 - 7*2^66 + 1, so folding
H*2^128 ≡ H*(7*2^66 - 1) is shift/add/borrow only — no multiplies.
The sampled value here is 192 bits (three u64 stream lanes per element,
oversample-and-reduce, xof.py), so two folds + a top-bit correction +
one conditional subtract reach canonical form:

  X < 2^192:  fold H=X>>128 (< 2^64)  -> X1 < 2^133
              fold H=X1>>128 (< 2^6)  -> X2 < 2^128 + 2^75  (carry c4)
  c4 set:     X2 - p = (X2 - 2^128) + 7*2^66 - 1  (< 2^76)
  finally:    one conditional subtract of p.

Field64 (21 lanes/block, 2 lanes/element) straddles block boundaries
and its expansions are tiny (Count/Sum); it stays on the unfused path.

Gating and interpret-mode plumbing follow keccak_pallas (JANUS_PALLAS
env, cached at first use).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.field import Field128
from . import keccak_pallas
from .keccak_pallas import permute_pairs


def _mode() -> str:
    # via the module so tests patching keccak_pallas._mode take effect
    return keccak_pallas._mode()

U32 = jnp.uint32
U64 = jnp.uint64

_TILE_REPORTS = 8
_TILE_BLOCKS = 128

_P = Field128.MODULUS
_P_WORDS = tuple(np.uint32((_P >> (32 * k)) & 0xFFFFFFFF) for k in range(4))
# 7*2^66 - 1 = 27*2^64 + (2^64 - 1), the p-complement added when the
# top (2^128) bit is folded away.
_E_WORDS = (
    np.uint32(0xFFFFFFFF),
    np.uint32(0xFFFFFFFF),
    np.uint32(0x0000001B),
    np.uint32(0),
)

# Minimum counter blocks per report to dispatch here: the tile quantum
# is 128 blocks, so short expansions (query/joint randomness) would pay
# mostly-padding tiles; they stay on the unfused path.
MIN_BLOCKS = 64


def enabled(jf, out_blocks: int) -> bool:
    if jf.LIMBS != 2 or _mode() == "off":
        return False
    if out_blocks < MIN_BLOCKS:
        return False
    # bound padded-tile waste: below one full tile the pad can dominate
    padded = -(-out_blocks // _TILE_BLOCKS) * _TILE_BLOCKS
    return padded <= 2 * out_blocks


def _addc(x, y, c):
    """x + y + c on u32 words; c in {0,1}. Returns (sum, carry)."""
    s = x + y
    c1 = (s < x).astype(U32)
    s2 = s + c
    c2 = (s2 < s).astype(U32)
    return s2, c1 | c2


def _subb(x, y, b):
    """x - y - b on u32 words; b in {0,1}. Returns (diff, borrow)."""
    d = x - y
    b1 = (x < y).astype(U32)
    d2 = d - b
    b2 = (d < b).astype(U32)
    return d2, b1 | b2


def _reduce_f128_words(w, zero):
    """Reduce a 192-bit little-endian 6-word value mod p -> 4 words."""
    h_lo, h_hi = w[4], w[5]
    # h7 = 7*H = (H << 3) - H, 3 words
    s0 = h_lo << np.uint32(3)
    s1 = (h_hi << np.uint32(3)) | (h_lo >> np.uint32(29))
    s2 = h_hi >> np.uint32(29)
    t0, b = _subb(s0, h_lo, zero)
    t1, b = _subb(s1, h_hi, b)
    t2 = s2 - b  # exact: 7H >= 0 fits 3 words
    # g = h7 << 2  (7H*2^66 = g*2^64), 3 words (7H < 2^67)
    g0 = t0 << np.uint32(2)
    g1 = (t1 << np.uint32(2)) | (t0 >> np.uint32(30))
    g2 = (t2 << np.uint32(2)) | (t1 >> np.uint32(30))
    # X1 = L + g*2^64 - H, 5 words
    x0, x1 = w[0], w[1]
    x2, c = _addc(w[2], g0, zero)
    x3, c = _addc(w[3], g1, c)
    x4 = g2 + c
    x0, b = _subb(x0, h_lo, zero)
    x1, b = _subb(x1, h_hi, b)
    x2, b = _subb(x2, zero, b)
    x3, b = _subb(x3, zero, b)
    x4 = x4 - b  # X1 >= 0 guarantees no wrap (see module docstring)
    # fold2: H2 = x4 < 2^6; D = 7*H2*2^66 - H2 as 3 words
    h2 = x4
    c2w = ((h2 << np.uint32(3)) - h2) << np.uint32(2)  # 28*H2, fits a word
    nz = (h2 > zero).astype(U32)
    d0 = zero - h2
    d1 = zero - nz
    d2 = c2w - nz  # c2w >= 28 when nz, no borrow
    y0, c = _addc(x0, d0, zero)
    y1, c = _addc(x1, d1, c)
    y2, c = _addc(x2, d2, c)
    y3, c4 = _addc(x3, zero, c)
    # top-bit correction: if c4, value = 2^128 + Y; Y + (7*2^66 - 1) < 2^76
    z0, c = _addc(y0, jnp.full_like(zero, _E_WORDS[0]), zero)
    z1, c = _addc(y1, jnp.full_like(zero, _E_WORDS[1]), c)
    z2, c = _addc(y2, jnp.full_like(zero, _E_WORDS[2]), c)
    z3 = y3 + c
    top = c4 != zero
    y0 = jnp.where(top, z0, y0)
    y1 = jnp.where(top, z1, y1)
    y2 = jnp.where(top, z2, y2)
    y3 = jnp.where(top, z3, y3)
    # final conditional subtract of p
    s0, b = _subb(y0, jnp.full_like(zero, _P_WORDS[0]), zero)
    s1, b = _subb(y1, jnp.full_like(zero, _P_WORDS[1]), b)
    s2_, b = _subb(y2, jnp.full_like(zero, _P_WORDS[2]), b)
    s3, b = _subb(y3, jnp.full_like(zero, _P_WORDS[3]), b)
    ge = b == zero
    return (
        jnp.where(ge, s0, y0),
        jnp.where(ge, s1, y1),
        jnp.where(ge, s2_, y2),
        jnp.where(ge, s3, y3),
    )


def _expand_kernel(p_lanes: int, tile_blocks: int = _TILE_BLOCKS, rounds: int = 24):
    """Kernel factory: prefix occupies lanes [0, p_lanes), counter at
    lane p_lanes, SHAKE padding at p_lanes+1 and lane 20 (the
    ctr_stream_lanes single-block framing, keccak_jax.py). off_ref is a
    [1] SMEM scalar: the stream-block counter offset (0 for whole-share
    expansion; step*blocks_per_step for the streamed query path)."""

    def kern(off_ref, pref_ref, o_ref):
        shape = (_TILE_REPORTS, tile_blocks)
        zero = jnp.zeros(shape, U32)
        lane_i = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        ctr_lo = (lane_i + pl_program_id(1) * tile_blocks + off_ref[0]).astype(U32)
        a = []
        for lane in range(25):
            if lane < p_lanes:
                lo = jnp.broadcast_to(pref_ref[:, 2 * lane : 2 * lane + 1], shape)
                hi = jnp.broadcast_to(pref_ref[:, 2 * lane + 1 : 2 * lane + 2], shape)
                a.append((lo, hi))
            elif lane == p_lanes:
                a.append((ctr_lo, zero))
            else:
                lo = zero
                hi = zero
                if lane == p_lanes + 1:
                    lo = jnp.full(shape, np.uint32(0x1F))
                if lane == 20:  # RATE_LANES - 1: 0x80 in the last byte
                    hi = jnp.full(shape, np.uint32(0x80000000))
                a.append((lo, hi))
        a = permute_pairs(a, rounds)
        for t in range(7):
            w = (
                a[3 * t][0],
                a[3 * t][1],
                a[3 * t + 1][0],
                a[3 * t + 1][1],
                a[3 * t + 2][0],
                a[3 * t + 2][1],
            )
            words = _reduce_f128_words(w, zero)
            for k in range(4):
                o_ref[:, 0, 4 * t + k, :] = words[k]

    return kern


def pl_program_id(axis: int):
    from jax.experimental import pallas as pl

    return pl.program_id(axis)


@lru_cache(maxsize=None)
def _call(p_lanes: int, b8: int, nb: int, tile_blocks: int, interpret: bool, rounds: int = 24):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (b8 // _TILE_REPORTS, nb)
    # index maps derived from grid indices only (monomorphic i32 — see
    # keccak_pallas._call for the Mosaic constraint this dodges)
    # explicit monomorphic index map (literal 0s lower to i64 constants,
    # which this Mosaic build refuses to mix in func.return — see
    # keccak_pallas._call)
    off_spec = pl.BlockSpec((1,), lambda b, j: (j * 0,), memory_space=pltpu.SMEM)
    in_spec = pl.BlockSpec(
        (_TILE_REPORTS, 128), lambda b, j: (b, j * 0), memory_space=pltpu.VMEM
    )
    # block tail dims must be divisible by (8, 128) or equal the array
    # dims — hence (..., nb, 28, tile) with a full (28, tile) tail block
    out_spec = pl.BlockSpec(
        (_TILE_REPORTS, 1, 28, tile_blocks),
        lambda b, j: (b, j, j * 0, j * 0),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        _expand_kernel(p_lanes, tile_blocks, rounds),
        out_shape=jax.ShapeDtypeStruct((b8, nb, 28, tile_blocks), jnp.uint32),
        grid=grid,
        in_specs=[off_spec, in_spec],
        out_specs=out_spec,
        interpret=interpret,
    )


def expand_f128(prefix_lanes, out_blocks: int, length: int, block_offset=0, rounds: int = 24):
    """Expand per-report counter-mode prefixes straight to Field128
    limb arrays, fused on device.

    prefix_lanes: [batch, p] u64 (dst||seed||binder', lane-aligned);
    returns a (lo, hi) limb tuple of shape [batch, length] — the same
    value keccak_jax.sample_field_vec produces from the unfused stream
    (differential-tested in tests/test_expand_pallas.py). block_offset
    (python int or traced scalar) starts the stream counter at that
    block.
    """
    prefix_lanes = jnp.asarray(prefix_lanes, U64)
    batch, p = prefix_lanes.shape
    assert p + 1 <= 20, "prefix + counter must fit one rate block"
    assert 7 * out_blocks >= length
    b8 = -(-batch // _TILE_REPORTS) * _TILE_REPORTS
    nb = -(-out_blocks // _TILE_BLOCKS)
    lo32 = prefix_lanes.astype(U32)
    hi32 = (prefix_lanes >> np.uint64(32)).astype(U32)
    inter = jnp.stack([lo32, hi32], axis=-1).reshape(batch, 2 * p)
    inter = jnp.pad(inter, ((0, b8 - batch), (0, 128 - 2 * p)))
    off = jnp.asarray(block_offset, jnp.int32).reshape(1)
    out = _call(p, b8, nb, _TILE_BLOCKS, _mode() != "tpu", rounds)(off, inter)
    # out[b, nbi, t*4+k, lane] = word k of element t of block
    # nbi*128+lane; element index is block*7 + t
    o = out.reshape(b8, nb, 7, 4, _TILE_BLOCKS)
    o = jnp.transpose(o, (0, 1, 4, 2, 3)).reshape(b8, nb * _TILE_BLOCKS * 7, 4)
    lo = o[:batch, :length, 0].astype(U64) | (o[:batch, :length, 1].astype(U64) << np.uint64(32))
    hi = o[:batch, :length, 2].astype(U64) | (o[:batch, :length, 3].astype(U64) << np.uint64(32))
    return (lo, hi)
