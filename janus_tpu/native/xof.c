/* Host-side native XOF: Keccak-f[1600] / SHAKE128 batch expansion.
 *
 * The reference keeps XOF share expansion in native code (the prio
 * crate's XofTurboShake128, consumed from e.g.
 * aggregator/src/aggregator/aggregation_job_driver.rs:363); this is the
 * TPU build's equivalent for the *host* side of the pipeline: clients,
 * tools, and the staging path that feeds device buffers. The device
 * side has its own batched Keccak (janus_tpu/vdaf/keccak_jax.py).
 *
 * Stream framing matches janus_tpu.vdaf.xof.XofCtr128 byte-for-byte
 * (counter mode; see that module's docstring for the rationale):
 *     block_i = SHAKE128(dst16 || seed16 || binder' || le64(i))[:168]
 *     stream  = block_0 || block_1 || ...
 * where binder' is the binder itself when <= 112 bytes, else its
 * arity-7 Merkle tree digest (112-byte leaves, single-block node
 * messages). Field sampling is oversample-and-reduce (RFC 9380
 * hash-to-field style, matching xof.py): ENCODED_SIZE+8 little-endian
 * stream bytes per element, reduced mod p (bias <= 2^-64).
 *
 * Exposed as a plain C ABI for ctypes (no pybind11 in this image).
 * All entry points are thread-safe; the batch expander shards the seed
 * axis over pthreads.
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>
#include <stdlib.h>
#include <pthread.h>

#define RATE 168 /* SHAKE128 rate in bytes */

static const uint64_t KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static inline uint64_t rotl64(uint64_t x, int s) {
  return (x << s) | (x >> (64 - s));
}

static void keccakf(uint64_t st[25]) {
  uint64_t bc[5], t;
  for (int round = 0; round < 24; round++) {
    /* theta */
    for (int i = 0; i < 5; i++)
      bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
    for (int i = 0; i < 5; i++) {
      t = bc[(i + 4) % 5] ^ rotl64(bc[(i + 1) % 5], 1);
      for (int j = 0; j < 25; j += 5) st[j + i] ^= t;
    }
    /* rho + pi */
    static const int rho[24] = {1,  3,  6,  10, 15, 21, 28, 36, 45, 55, 2,  14,
                                27, 41, 56, 8,  25, 43, 62, 18, 39, 61, 20, 44};
    static const int pi[24] = {10, 7,  11, 17, 18, 3,  5,  16, 8,  21, 24, 4,
                               15, 23, 19, 13, 12, 2,  20, 14, 22, 9,  6,  1};
    t = st[1];
    for (int i = 0; i < 24; i++) {
      uint64_t tmp = st[pi[i]];
      st[pi[i]] = rotl64(t, rho[i]);
      t = tmp;
    }
    /* chi */
    for (int j = 0; j < 25; j += 5) {
      for (int i = 0; i < 5; i++) bc[i] = st[j + i];
      for (int i = 0; i < 5; i++)
        st[j + i] = bc[i] ^ ((~bc[(i + 1) % 5]) & bc[(i + 2) % 5]);
    }
    /* iota */
    st[0] ^= KECCAK_RC[round];
  }
}

typedef struct {
  uint64_t st[25];
  size_t pos; /* squeeze position within current rate block */
} shake_ctx;

/* One-shot absorb (message fully known up front) + pad. */
static void shake128_absorb(shake_ctx *ctx, const uint8_t *in, size_t inlen) {
  memset(ctx->st, 0, sizeof(ctx->st));
  uint8_t *stb = (uint8_t *)ctx->st; /* little-endian hosts only */
  while (inlen >= RATE) {
    for (size_t i = 0; i < RATE; i++) stb[i] ^= in[i];
    keccakf(ctx->st);
    in += RATE;
    inlen -= RATE;
  }
  for (size_t i = 0; i < inlen; i++) stb[i] ^= in[i];
  stb[inlen] ^= 0x1f;
  stb[RATE - 1] ^= 0x80;
  keccakf(ctx->st);
  ctx->pos = 0;
}

static void shake128_squeeze(shake_ctx *ctx, uint8_t *out, size_t n) {
  const uint8_t *stb = (const uint8_t *)ctx->st;
  while (n > 0) {
    if (ctx->pos == RATE) {
      keccakf(ctx->st);
      ctx->pos = 0;
    }
    size_t take = RATE - ctx->pos;
    if (take > n) take = n;
    memcpy(out, stb + ctx->pos, take);
    out += take;
    ctx->pos += take;
    n -= take;
  }
}

void janus_shake128(const uint8_t *in, size_t inlen, uint8_t *out,
                    size_t outlen) {
  shake_ctx ctx;
  shake128_absorb(&ctx, in, inlen);
  shake128_squeeze(&ctx, out, outlen);
}

/* --- counter-mode stream (janus_tpu.vdaf.xof.XofCtr128 framing) --- */

#define INLINE_BINDER_MAX 112
#define TREE_CHUNK 112
#define TREE_ARITY 7
#define TREE_DIGEST 16
#define CTR_PREFIX_MAX (16 + 16 + INLINE_BINDER_MAX)

static const uint8_t TREE_MAGIC[8] = {'J', 'a', 'n', 'u', 's', 'T', 'r', '1'};

static void store_le64(uint8_t *p, uint64_t v) {
  for (int i = 0; i < 8; i++) p[i] = (uint8_t)(v >> (8 * i));
}

/* Single-block node hash: SHAKE128(magic||level||index||total||chunk)[:16]. */
static void tree_node(uint64_t level, uint64_t index, const uint8_t total[8],
                      const uint8_t chunk[TREE_CHUNK], uint8_t out[TREE_DIGEST]) {
  uint8_t msg[8 + 8 + 8 + 8 + TREE_CHUNK];
  memcpy(msg, TREE_MAGIC, 8);
  store_le64(msg + 8, level);
  store_le64(msg + 16, index);
  memcpy(msg + 24, total, 8);
  memcpy(msg + 32, chunk, TREE_CHUNK);
  shake_ctx ctx;
  shake128_absorb(&ctx, msg, sizeof(msg));
  shake128_squeeze(&ctx, out, TREE_DIGEST);
}

/* Arity-7 Merkle digest of lane-aligned data (> INLINE_BINDER_MAX bytes). */
static int tree_digest(const uint8_t *data, size_t len, uint8_t out[TREE_DIGEST]) {
  uint8_t total[8];
  store_le64(total, (uint64_t)len);
  size_t n = (len + TREE_CHUNK - 1) / TREE_CHUNK;
  uint8_t *digs = (uint8_t *)malloc(n * TREE_DIGEST);
  if (!digs) return -1;
  for (size_t k = 0; k < n; k++) {
    uint8_t chunk[TREE_CHUNK];
    size_t off = k * TREE_CHUNK;
    size_t take = len - off < TREE_CHUNK ? len - off : TREE_CHUNK;
    memcpy(chunk, data + off, take);
    if (take < TREE_CHUNK) memset(chunk + take, 0, TREE_CHUNK - take);
    tree_node(0, (uint64_t)k, total, chunk, digs + k * TREE_DIGEST);
  }
  uint64_t level = 0;
  while (n > 1) {
    level++;
    size_t groups = (n + TREE_ARITY - 1) / TREE_ARITY;
    for (size_t g = 0; g < groups; g++) {
      uint8_t chunk[TREE_CHUNK];
      memset(chunk, 0, TREE_CHUNK);
      size_t have = n - g * TREE_ARITY;
      if (have > TREE_ARITY) have = TREE_ARITY;
      memcpy(chunk, digs + g * TREE_ARITY * TREE_DIGEST, have * TREE_DIGEST);
      tree_node(level, (uint64_t)g, total, chunk, digs + g * TREE_DIGEST);
    }
    n = groups;
  }
  memcpy(out, digs, TREE_DIGEST);
  free(digs);
  return 0;
}

typedef struct {
  uint8_t prefix[CTR_PREFIX_MAX + 8]; /* dst||seed||binder' (+ room for ctr) */
  size_t prefix_len;
  uint64_t block;
  uint8_t buf[RATE];
  size_t pos;
} ctr_stream;

/* prefix = dst16 || seed16 || binder' (tree-digesting long binders). */
static int ctr_init(ctr_stream *s, const uint8_t *dst16, const uint8_t *seed16,
                    const uint8_t *binder, size_t binder_len) {
  memcpy(s->prefix, dst16, 16);
  memcpy(s->prefix + 16, seed16, 16);
  if (binder_len > INLINE_BINDER_MAX) {
    if (tree_digest(binder, binder_len, s->prefix + 32) != 0) return -1;
    s->prefix_len = 32 + TREE_DIGEST;
  } else {
    if (binder_len) memcpy(s->prefix + 32, binder, binder_len);
    s->prefix_len = 32 + binder_len;
  }
  s->block = 0;
  s->pos = RATE; /* force refill */
  return 0;
}

static void ctr_read(ctr_stream *s, uint8_t *out, size_t n) {
  while (n > 0) {
    if (s->pos == RATE) {
      store_le64(s->prefix + s->prefix_len, s->block++);
      shake_ctx ctx;
      shake128_absorb(&ctx, s->prefix, s->prefix_len + 8);
      shake128_squeeze(&ctx, s->buf, RATE);
      s->pos = 0;
    }
    size_t take = RATE - s->pos;
    if (take > n) take = n;
    memcpy(out, s->buf + s->pos, take);
    out += take;
    s->pos += take;
    n -= take;
  }
}

typedef unsigned __int128 u128;

/* a + b mod p for a, b < p (p any 128-bit modulus with 2^128 mod p = c). */
static inline u128 add_mod_u128(u128 a, u128 b, u128 p, u128 c) {
  u128 s = a + b;
  if (s < a) {
    /* wrapped past 2^128: 2^128 = p + c, so s = a+b-2^128+c = a+b-p,
     * which is already < p for a, b < p */
    return s + c;
  }
  if (s >= p) s -= p; /* non-wrap branch: s < 2p needs one subtract */
  return s;
}

/* (h*2^128 + L) mod p for the Field128 modulus (2^128 === 7*2^66 - 1). */
static u128 reduce192_f128(uint64_t h, u128 L, u128 p) {
  const u128 c = ((u128)7 << 66) - 1; /* 2^128 mod p; c = 27*2^64 + (2^64-1) */
  const uint64_t c1 = 27, c0 = ~(uint64_t)0;
  /* h*c = h*c1*2^64 + h*c0; fold the *2^64 term's overflow through c. */
  u128 hc1 = (u128)h * c1;             /* < 2^69 */
  u128 hc0 = (u128)h * c0;             /* < 2^128 */
  uint64_t d1 = (uint64_t)(hc1 >> 64); /* < 32 */
  u128 d0_64 = (u128)(uint64_t)hc1 << 64;
  u128 r = L % p;
  r = add_mod_u128(r, hc0 % p, p, c);
  r = add_mod_u128(r, ((u128)d1 * c) % p, p, c);
  r = add_mod_u128(r, d0_64 % p, p, c);
  return r;
}

/* Sample `length` field elements from one seed's stream by
 * oversample-and-reduce: (limbs+1) little-endian u64 lanes per element,
 * value mod p (janus_tpu.vdaf.xof semantics, bias <= 2^-64).
 * limbs = 1 (Field64) or 2 (Field128);
 * out: length*limbs u64 (element-major: e0.lo, e0.hi, e1.lo, ...). */
static int expand_one(const uint8_t *dst16, const uint8_t *seed16,
                      const uint8_t *binder, size_t binder_len, size_t length,
                      int limbs, uint64_t mod_lo, uint64_t mod_hi,
                      uint64_t *out) {
  ctr_stream s;
  if (ctr_init(&s, dst16, seed16, binder, binder_len) != 0) return -1;

  uint8_t chunk[24];
  for (size_t got = 0; got < length; got++) {
    ctr_read(&s, chunk, (size_t)(8 * (limbs + 1)));
    uint64_t l0, l1, l2 = 0;
    memcpy(&l0, chunk, 8);
    memcpy(&l1, chunk + 8, 8);
    if (limbs == 2) memcpy(&l2, chunk + 16, 8);
    if (limbs == 1) {
      u128 v = ((u128)l1 << 64) | l0;
      out[got] = (uint64_t)(v % mod_lo);
    } else {
      u128 p = ((u128)mod_hi << 64) | mod_lo;
      u128 r = reduce192_f128(l2, ((u128)l1 << 64) | l0, p);
      out[got * 2] = (uint64_t)r;
      out[got * 2 + 1] = (uint64_t)(r >> 64);
    }
  }
  return 0;
}

typedef struct {
  const uint8_t *dst16;
  const uint8_t *seeds;   /* n * 16 bytes */
  const uint8_t *binders; /* n * binder_len bytes (may be NULL) */
  size_t binder_len;
  size_t length;
  int limbs;
  uint64_t mod_lo, mod_hi;
  uint64_t *out; /* n * length * limbs */
  size_t begin, end;
  int rc; /* sticky failure flag for this stripe */
} expand_job;

static void *expand_worker(void *arg) {
  expand_job *job = (expand_job *)arg;
  for (size_t i = job->begin; i < job->end; i++) {
    if (expand_one(job->dst16, job->seeds + 16 * i,
                   job->binders ? job->binders + job->binder_len * i : NULL,
                   job->binders ? job->binder_len : 0, job->length, job->limbs,
                   job->mod_lo, job->mod_hi,
                   job->out + i * job->length * job->limbs) != 0)
      job->rc = -1;
  }
  return NULL;
}

/* Expand n seeds -> [n, length, limbs] u64. binders: per-seed fixed-size
 * binder block (NULL for empty binders). Returns 0 on success. */
int janus_expand_field_batch(const uint8_t *dst16, const uint8_t *seeds,
                             size_t n, const uint8_t *binders,
                             size_t binder_len, size_t length, int limbs,
                             uint64_t mod_lo, uint64_t mod_hi, uint64_t *out,
                             int n_threads) {
  if (limbs != 1 && limbs != 2) return -1;
  if (n_threads < 1) n_threads = 1;
  if ((size_t)n_threads > n) n_threads = (int)(n ? n : 1);
  if (n == 0) return 0;

  if (n_threads == 1) {
    expand_job job = {dst16, seeds, binders, binder_len, length,
                      limbs, mod_lo, mod_hi, out, 0, n, 0};
    expand_worker(&job);
    return job.rc;
  }
  pthread_t *tids = (pthread_t *)malloc(sizeof(pthread_t) * n_threads);
  expand_job *jobs = (expand_job *)malloc(sizeof(expand_job) * n_threads);
  size_t per = (n + n_threads - 1) / n_threads;
  int spawned = 0;
  for (int t = 0; t < n_threads; t++) {
    size_t b = per * t, e = b + per;
    if (b >= n) break;
    if (e > n) e = n;
    jobs[t] = (expand_job){dst16, seeds, binders, binder_len, length,
                           limbs, mod_lo, mod_hi, out, b, e, 0};
    if (pthread_create(&tids[t], NULL, expand_worker, &jobs[t]) != 0) {
      /* fall back to running this stripe inline */
      expand_worker(&jobs[t]);
      tids[t] = 0;
      continue;
    }
    spawned++;
    (void)spawned;
  }
  int rc = 0;
  for (int t = 0; t < n_threads; t++) {
    size_t b = per * t;
    if (b >= n) break;
    if (tids[t]) pthread_join(tids[t], NULL);
    if (jobs[t].rc != 0) rc = -1;
  }
  free(tids);
  free(jobs);
  return rc;
}

/* Batch derive_seed: out[i] = first 16 stream bytes for (seed_i, binder_i)
 * under the counter-mode framing. binders: per-seed fixed-size block
 * (NULL for empty). */
int janus_derive_seed_batch(const uint8_t *dst16, const uint8_t *seeds,
                            size_t n, const uint8_t *binders, size_t binder_len,
                            uint8_t *out) {
  for (size_t i = 0; i < n; i++) {
    ctr_stream s;
    if (ctr_init(&s, dst16, seeds + 16 * i,
                 binders ? binders + binder_len * i : NULL,
                 binders ? binder_len : 0) != 0)
      return -1;
    ctr_read(&s, out + 16 * i, 16);
  }
  return 0;
}
