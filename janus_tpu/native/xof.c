/* Host-side native XOF: Keccak-f[1600] / SHAKE128 batch expansion.
 *
 * The reference keeps XOF share expansion in native code (the prio
 * crate's XofTurboShake128, consumed from e.g.
 * aggregator/src/aggregator/aggregation_job_driver.rs:363); this is the
 * TPU build's equivalent for the *host* side of the pipeline: clients,
 * tools, and the staging path that feeds device buffers. The device
 * side has its own batched Keccak (janus_tpu/vdaf/keccak_jax.py).
 *
 * Stream framing matches janus_tpu.vdaf.xof.XofShake128 byte-for-byte:
 *     stream = SHAKE128(dst16 || seed16 || binder)
 * and field sampling is rejection sampling of ENCODED_SIZE-byte
 * little-endian chunks (< modulus).
 *
 * Exposed as a plain C ABI for ctypes (no pybind11 in this image).
 * All entry points are thread-safe; the batch expander shards the seed
 * axis over pthreads.
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>
#include <stdlib.h>
#include <pthread.h>

#define RATE 168 /* SHAKE128 rate in bytes */

static const uint64_t KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static inline uint64_t rotl64(uint64_t x, int s) {
  return (x << s) | (x >> (64 - s));
}

static void keccakf(uint64_t st[25]) {
  uint64_t bc[5], t;
  for (int round = 0; round < 24; round++) {
    /* theta */
    for (int i = 0; i < 5; i++)
      bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
    for (int i = 0; i < 5; i++) {
      t = bc[(i + 4) % 5] ^ rotl64(bc[(i + 1) % 5], 1);
      for (int j = 0; j < 25; j += 5) st[j + i] ^= t;
    }
    /* rho + pi */
    static const int rho[24] = {1,  3,  6,  10, 15, 21, 28, 36, 45, 55, 2,  14,
                                27, 41, 56, 8,  25, 43, 62, 18, 39, 61, 20, 44};
    static const int pi[24] = {10, 7,  11, 17, 18, 3,  5,  16, 8,  21, 24, 4,
                               15, 23, 19, 13, 12, 2,  20, 14, 22, 9,  6,  1};
    t = st[1];
    for (int i = 0; i < 24; i++) {
      uint64_t tmp = st[pi[i]];
      st[pi[i]] = rotl64(t, rho[i]);
      t = tmp;
    }
    /* chi */
    for (int j = 0; j < 25; j += 5) {
      for (int i = 0; i < 5; i++) bc[i] = st[j + i];
      for (int i = 0; i < 5; i++)
        st[j + i] = bc[i] ^ ((~bc[(i + 1) % 5]) & bc[(i + 2) % 5]);
    }
    /* iota */
    st[0] ^= KECCAK_RC[round];
  }
}

typedef struct {
  uint64_t st[25];
  size_t pos; /* squeeze position within current rate block */
} shake_ctx;

/* One-shot absorb (message fully known up front) + pad. */
static void shake128_absorb(shake_ctx *ctx, const uint8_t *in, size_t inlen) {
  memset(ctx->st, 0, sizeof(ctx->st));
  uint8_t *stb = (uint8_t *)ctx->st; /* little-endian hosts only */
  while (inlen >= RATE) {
    for (size_t i = 0; i < RATE; i++) stb[i] ^= in[i];
    keccakf(ctx->st);
    in += RATE;
    inlen -= RATE;
  }
  for (size_t i = 0; i < inlen; i++) stb[i] ^= in[i];
  stb[inlen] ^= 0x1f;
  stb[RATE - 1] ^= 0x80;
  keccakf(ctx->st);
  ctx->pos = 0;
}

static void shake128_squeeze(shake_ctx *ctx, uint8_t *out, size_t n) {
  const uint8_t *stb = (const uint8_t *)ctx->st;
  while (n > 0) {
    if (ctx->pos == RATE) {
      keccakf(ctx->st);
      ctx->pos = 0;
    }
    size_t take = RATE - ctx->pos;
    if (take > n) take = n;
    memcpy(out, stb + ctx->pos, take);
    out += take;
    ctx->pos += take;
    n -= take;
  }
}

void janus_shake128(const uint8_t *in, size_t inlen, uint8_t *out,
                    size_t outlen) {
  shake_ctx ctx;
  shake128_absorb(&ctx, in, inlen);
  shake128_squeeze(&ctx, out, outlen);
}

/* Rejection-sample `length` field elements from one seed's stream.
 * limbs = 1 (Field64) or 2 (Field128); element = limbs little-endian u64.
 * out: length*limbs u64 (element-major: e0.lo, e0.hi, e1.lo, ...). */
static void expand_one(const uint8_t *dst16, const uint8_t *seed16,
                       const uint8_t *binder, size_t binder_len, size_t length,
                       int limbs, uint64_t mod_lo, uint64_t mod_hi,
                       uint64_t *out) {
  uint8_t msg_stack[512];
  uint8_t *msg = msg_stack;
  size_t msg_len = 32 + binder_len;
  if (msg_len > sizeof(msg_stack)) msg = (uint8_t *)malloc(msg_len);
  memcpy(msg, dst16, 16);
  memcpy(msg + 16, seed16, 16);
  if (binder_len) memcpy(msg + 32, binder, binder_len);
  shake_ctx ctx;
  shake128_absorb(&ctx, msg, msg_len);
  if (msg != msg_stack) free(msg);

  size_t got = 0;
  uint8_t chunk[16];
  while (got < length) {
    shake128_squeeze(&ctx, chunk, (size_t)(8 * limbs));
    uint64_t lo, hi = 0;
    memcpy(&lo, chunk, 8);
    if (limbs == 2) memcpy(&hi, chunk + 8, 8);
    int ok;
    if (limbs == 1)
      ok = lo < mod_lo;
    else
      ok = (hi < mod_hi) || (hi == mod_hi && lo < mod_lo);
    if (ok) {
      out[got * limbs] = lo;
      if (limbs == 2) out[got * limbs + 1] = hi;
      got++;
    }
  }
}

typedef struct {
  const uint8_t *dst16;
  const uint8_t *seeds;   /* n * 16 bytes */
  const uint8_t *binders; /* n * binder_len bytes (may be NULL) */
  size_t binder_len;
  size_t length;
  int limbs;
  uint64_t mod_lo, mod_hi;
  uint64_t *out; /* n * length * limbs */
  size_t begin, end;
} expand_job;

static void *expand_worker(void *arg) {
  expand_job *job = (expand_job *)arg;
  for (size_t i = job->begin; i < job->end; i++) {
    expand_one(job->dst16, job->seeds + 16 * i,
               job->binders ? job->binders + job->binder_len * i : NULL,
               job->binders ? job->binder_len : 0, job->length, job->limbs,
               job->mod_lo, job->mod_hi,
               job->out + i * job->length * job->limbs);
  }
  return NULL;
}

/* Expand n seeds -> [n, length, limbs] u64. binders: per-seed fixed-size
 * binder block (NULL for empty binders). Returns 0 on success. */
int janus_expand_field_batch(const uint8_t *dst16, const uint8_t *seeds,
                             size_t n, const uint8_t *binders,
                             size_t binder_len, size_t length, int limbs,
                             uint64_t mod_lo, uint64_t mod_hi, uint64_t *out,
                             int n_threads) {
  if (limbs != 1 && limbs != 2) return -1;
  if (n_threads < 1) n_threads = 1;
  if ((size_t)n_threads > n) n_threads = (int)(n ? n : 1);
  if (n == 0) return 0;

  if (n_threads == 1) {
    expand_job job = {dst16, seeds, binders, binder_len, length,
                      limbs, mod_lo, mod_hi, out, 0, n};
    expand_worker(&job);
    return 0;
  }
  pthread_t *tids = (pthread_t *)malloc(sizeof(pthread_t) * n_threads);
  expand_job *jobs = (expand_job *)malloc(sizeof(expand_job) * n_threads);
  size_t per = (n + n_threads - 1) / n_threads;
  int spawned = 0;
  for (int t = 0; t < n_threads; t++) {
    size_t b = per * t, e = b + per;
    if (b >= n) break;
    if (e > n) e = n;
    jobs[t] = (expand_job){dst16, seeds, binders, binder_len, length,
                           limbs, mod_lo, mod_hi, out, b, e};
    if (pthread_create(&tids[t], NULL, expand_worker, &jobs[t]) != 0) {
      /* fall back to running this stripe inline */
      expand_worker(&jobs[t]);
      tids[t] = 0;
      continue;
    }
    spawned++;
    (void)spawned;
  }
  for (int t = 0; t < n_threads; t++) {
    size_t b = per * t;
    if (b >= n) break;
    if (tids[t]) pthread_join(tids[t], NULL);
  }
  free(tids);
  free(jobs);
  return 0;
}

/* Batch derive_seed: out[i] = SHAKE128(dst16 || seed_i || binder_i)[:16].
 * binders: per-seed fixed-size block (NULL for empty). */
int janus_derive_seed_batch(const uint8_t *dst16, const uint8_t *seeds,
                            size_t n, const uint8_t *binders, size_t binder_len,
                            uint8_t *out) {
  for (size_t i = 0; i < n; i++) {
    uint8_t msg_stack[512];
    uint8_t *msg = msg_stack;
    size_t msg_len = 32 + binder_len;
    if (msg_len > sizeof(msg_stack)) msg = (uint8_t *)malloc(msg_len);
    memcpy(msg, dst16, 16);
    memcpy(msg + 16, seeds + 16 * i, 16);
    if (binder_len) memcpy(msg + 32, binders + binder_len * i, binder_len);
    shake_ctx ctx;
    shake128_absorb(&ctx, msg, msg_len);
    if (msg != msg_stack) free(msg);
    shake128_squeeze(&ctx, out + 16 * i, 16);
  }
  return 0;
}
